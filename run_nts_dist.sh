#!/bin/bash
# Launch parity with the reference's run_nts_dist.sh:
#   scp cfg to every host in ./hostfile, then "mpiexec -hostfile hostfile -np N".
#
# Usage: ./run_nts_dist.sh <procs> <file.cfg> [hostfile]
#
# With a hostfile (one host per line): copies the cfg to each host's matching
# directory and launches one process per line over ssh, wiring the
# jax.distributed world exactly the way mpiexec wires MPI_COMM_WORLD —
# process 0's host is the coordinator, NTS_NUM_PROCESSES/NTS_PROCESS_ID are
# the rank variables (parallel/mesh.maybe_initialize_distributed).
#
# Without a hostfile: all <procs> processes spawn on localhost — the
# reference's multi-slot-on-one-host debugging rig ("strongly recommand use
# one slot, except for debugging", reference README), and the rig
# tests/test_multihost.py exercises in CI.
set -e
procs=${1:?usage: ./run_nts_dist.sh <procs> <file.cfg> [hostfile]}
cfg=${2:?usage: ./run_nts_dist.sh <procs> <file.cfg> [hostfile]}
hostfile=${3:-}
port=${NTS_PORT:-$((12000 + RANDOM % 20000))}
cur_dir=$(cd "$(dirname "$0")" && pwd)

if [ -n "${hostfile}" ]; then
  # blank/whitespace lines would miscount the world and ssh to "user@"
  mapfile -t hosts < <(sed 's/[[:space:]]*$//' "${hostfile}" | grep -v '^$')
  if [ "${#hosts[@]}" -lt "${procs}" ]; then
    echo "run_nts_dist.sh: ${procs} processes requested but hostfile has" \
      "only ${#hosts[@]} usable hosts — every rank would block forever in" \
      "jax.distributed.initialize waiting for the missing ones" >&2
    exit 2
  fi
  coord="${hosts[0]}:${port}"
  pids=()
  for ((i = 0; i < procs; i++)); do
    host="${hosts[$i]}"
    # a rank whose cfg never arrived must fail HERE, loudly — not crash the
    # whole world later on a missing file or train on a stale copy
    scp -q "${cfg}" "${USER}@${host}:${cur_dir}/" || {
      echo "run_nts_dist.sh: scp of ${cfg} to ${host} failed" >&2
      exit 3
    }
    ssh "${USER}@${host}" \
      "cd ${cur_dir} && NTS_COORDINATOR=${coord} NTS_NUM_PROCESSES=${procs} \
       NTS_PROCESS_ID=${i} NTS_PARTITIONS_OVERRIDE=${procs} \
       python -m neutronstarlite_tpu.run $(basename "${cfg}")" &
    pids+=($!)
  done
else
  # localhost: N processes, one JAX world over the loopback coordinator.
  # Forcing the CPU platform: N processes cannot share the one local
  # accelerator, and this mode exists for debugging the distributed wiring.
  coord="127.0.0.1:${port}"
  pids=()
  for ((i = 0; i < procs; i++)); do
    JAX_PLATFORMS=cpu NTS_COORDINATOR="${coord}" NTS_NUM_PROCESSES="${procs}" \
      NTS_PROCESS_ID="${i}" NTS_PARTITIONS_OVERRIDE="${procs}" \
      python -m neutronstarlite_tpu.run "${cfg}" &
    pids+=($!)
  done
fi

rc=0
for pid in "${pids[@]}"; do
  wait "${pid}" || rc=$?
done
exit "${rc}"
