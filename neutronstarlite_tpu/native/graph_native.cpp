// Native preprocessing runtime: CSC/CSR graph build + fan-out sampling.
//
// The TPU framework's counterpart of the reference's native preprocessing
// core: Graph::load_directed's adjacency construction (core/graph.hpp:1285-
// 1827), PartitionedGraph::PartitionToChunks' CSC+CSR+weight build
// (core/PartitionedGraph.hpp:324-420), and Sampler::reservoir_sample
// (core/ntsSampler.hpp:113-172). Device compute stays in XLA; this library
// accelerates the host-side, O(|E|) preprocessing that feeds HBM.
//
// Design: counting-sort adjacency build, OpenMP-parallel with per-thread
// histograms and atomic cursor placement (the lock-free write-cursor idea of
// the reference's emit_buffer path, network.cpp:511, applied to preprocessing
// instead of messaging). C ABI for ctypes; the Python side owns all memory
// (NumPy buffers), so there is no allocator coupling.

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>

#ifdef _OPENMP
#include <omp.h>
#endif

extern "C" {

// Degree counting: out_degree[src[e]]++, in_degree[dst[e]]++.
void nts_count_degrees(const uint32_t* src, const uint32_t* dst, int64_t e_num,
                       int32_t v_num, int32_t* out_degree, int32_t* in_degree) {
  std::memset(out_degree, 0, sizeof(int32_t) * v_num);
  std::memset(in_degree, 0, sizeof(int32_t) * v_num);
#pragma omp parallel for schedule(static)
  for (int64_t e = 0; e < e_num; ++e) {
    __atomic_fetch_add(&out_degree[src[e]], 1, __ATOMIC_RELAXED);
    __atomic_fetch_add(&in_degree[dst[e]], 1, __ATOMIC_RELAXED);
  }
}

// Dual CSC/CSR build with per-edge weights, counting-sort placement.
// weight_mode: 0 = gcn_norm (1/sqrt(max(d_out(src),1)*max(d_in(dst),1)),
// ntsBaseOp.hpp:194), 1 = ones.
// column_offset/row_offset are [v_num+1] and must already hold the exclusive
// prefix sums of in_degree/out_degree (caller computes them — cheap).
void nts_build_adjacency(const uint32_t* src, const uint32_t* dst,
                         int64_t e_num, int32_t v_num, int weight_mode,
                         const int32_t* out_degree, const int32_t* in_degree,
                         const int64_t* column_offset, int32_t* csc_src,
                         int32_t* csc_dst, float* csc_w,
                         const int64_t* row_offset, int32_t* csr_src,
                         int32_t* csr_dst, float* csr_w) {
  std::atomic<int64_t>* csc_cursor = new std::atomic<int64_t>[v_num];
  std::atomic<int64_t>* csr_cursor = new std::atomic<int64_t>[v_num];
#pragma omp parallel for schedule(static)
  for (int32_t v = 0; v < v_num; ++v) {
    csc_cursor[v].store(column_offset[v], std::memory_order_relaxed);
    csr_cursor[v].store(row_offset[v], std::memory_order_relaxed);
  }
#pragma omp parallel for schedule(static)
  for (int64_t e = 0; e < e_num; ++e) {
    const uint32_t s = src[e], d = dst[e];
    float w = 1.0f;
    if (weight_mode == 0) {
      const float ds = (float)(out_degree[s] > 0 ? out_degree[s] : 1);
      const float dd = (float)(in_degree[d] > 0 ? in_degree[d] : 1);
      w = 1.0f / std::sqrt(ds * dd);
    }
    const int64_t pc = csc_cursor[d].fetch_add(1, std::memory_order_relaxed);
    csc_src[pc] = (int32_t)s;
    csc_dst[pc] = (int32_t)d;
    csc_w[pc] = w;
    const int64_t pr = csr_cursor[s].fetch_add(1, std::memory_order_relaxed);
    csr_src[pr] = (int32_t)s;
    csr_dst[pr] = (int32_t)d;
    csr_w[pr] = w;
  }
  delete[] csc_cursor;
  delete[] csr_cursor;
}

// xorshift64* PRNG — deterministic per (seed, dst) stream.
static inline uint64_t xorshift64(uint64_t* s) {
  uint64_t x = *s;
  x ^= x << 13;
  x ^= x >> 7;
  x ^= x << 17;
  *s = x;
  return x * 0x2545F4914F6CDD1DULL;
}

// Fan-out neighbor sampling over a CSC adjacency: for each of n_dst
// destinations, uniformly choose min(deg, fanout) distinct in-neighbors
// (reservoir algorithm — the reference's ntsSampler.hpp:138-158 loop).
// Outputs are preallocated [n_dst * fanout]; returns edges written per dst
// in out_counts. out_src holds global source ids, out_dst_idx the dst's
// index in the input list.
void nts_sample_hop(const int64_t* column_offset, const int32_t* row_indices,
                    const int64_t* dsts, int64_t n_dst, int32_t fanout,
                    uint64_t seed, int32_t* out_src, int32_t* out_dst_idx,
                    int32_t* out_counts) {
#pragma omp parallel for schedule(dynamic, 64)
  for (int64_t i = 0; i < n_dst; ++i) {
    const int64_t v = dsts[i];
    const int64_t lo = column_offset[v], hi = column_offset[v + 1];
    const int64_t deg = hi - lo;
    int32_t* dst_out = out_src + i * fanout;
    int64_t rs = seed * 0x9E3779B97F4A7C15ULL + (uint64_t)v + 1;
    int64_t k = 0;
    if (deg <= fanout) {
      for (int64_t j = lo; j < hi; ++j) dst_out[k++] = row_indices[j];
    } else if (deg > (int64_t)fanout * 8 && fanout <= 256) {
      // Floyd's distinct sampling: O(fanout) uniform positions. The
      // reservoir below is O(deg) per destination — on a power-law graph
      // a 2^21-degree hub drawn as a dst costs a 2M-edge scan every batch
      // (measured 70 of 94 ms/batch at full Reddit scale); Floyd never
      // touches the adjacency beyond the sampled slots.
      int64_t pos[256];
      for (int64_t j = deg - fanout; j < deg; ++j) {
        int64_t t = (int64_t)(xorshift64((uint64_t*)&rs) % (uint64_t)(j + 1));
        int found = 0;
        for (int64_t m = 0; m < k; ++m)
          if (pos[m] == t) { found = 1; break; }
        pos[k++] = found ? j : t;
      }
      for (int64_t m = 0; m < k; ++m)
        dst_out[m] = row_indices[lo + pos[m]];
    } else {
      // reservoir: fill first `fanout`, then replace with prob fanout/j
      for (int64_t j = 0; j < fanout; ++j) dst_out[j] = row_indices[lo + j];
      k = fanout;
      for (int64_t j = fanout; j < deg; ++j) {
        const uint64_t r = xorshift64((uint64_t*)&rs) % (uint64_t)(j + 1);
        if ((int64_t)r < fanout) dst_out[r] = row_indices[lo + j];
      }
    }
    out_counts[i] = (int32_t)k;
    for (int64_t j = 0; j < k; ++j) out_dst_idx[i * fanout + j] = (int32_t)i;
  }
}

// Sorted dedup + remap of a batch's sampled source ids (the hot part of
// sampCSC::postprocessing, coocsc.hpp:62-89 — std::map there). Two hash
// passes around one m-element sort beat numpy's full n log n sort+search:
// (1) open-addressing insert of all n ids -> unique set, (2) sort the m
// uniques (sorted ids keep the device feature-gather local), (3) re-insert
// sorted ids, (4) look up each id's local index. Returns m. uniq must have
// capacity >= n; local capacity n.
static inline int64_t nts_hash_slot(int64_t key, int64_t mask) {
  uint64_t h = (uint64_t)key * 0x9E3779B97F4A7C15ULL;
  return (int64_t)((h ^ (h >> 29)) & (uint64_t)mask);
}

int64_t nts_dedup_remap(const int64_t* ids, int64_t n, int64_t* uniq,
                        int32_t* local) {
  if (n == 0) return 0;
  int64_t cap = 1;
  while (cap < n * 2) cap <<= 1;
  const int64_t mask = cap - 1;
  int64_t* keys = new int64_t[cap];
  int32_t* vals = new int32_t[cap];
  for (int64_t i = 0; i < cap; ++i) keys[i] = -1;
  int64_t m = 0;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t k = ids[i];
    int64_t s = nts_hash_slot(k, mask);
    while (keys[s] != -1 && keys[s] != k) s = (s + 1) & mask;
    if (keys[s] == -1) {
      keys[s] = k;
      uniq[m++] = k;
    }
  }
  // insertion sort is fine for tiny m; std::sort otherwise
  std::sort(uniq, uniq + m);
  for (int64_t i = 0; i < cap; ++i) keys[i] = -1;
  for (int64_t j = 0; j < m; ++j) {
    int64_t s = nts_hash_slot(uniq[j], mask);
    while (keys[s] != -1) s = (s + 1) & mask;
    keys[s] = uniq[j];
    vals[s] = (int32_t)j;
  }
  for (int64_t i = 0; i < n; ++i) {
    const int64_t k = ids[i];
    int64_t s = nts_hash_slot(k, mask);
    while (keys[s] != k) s = (s + 1) & mask;
    local[i] = vals[s];
  }
  delete[] keys;
  delete[] vals;
  return m;
}

// Stable counting sort of edges by source tile. Input edges are already
// dst-grouped (CSC order), so the output permutation is (tile, dst)-sorted —
// the order the blocked ELL layout needs (ops/blocked_ell.py) without the
// O(E log E) comparison sort. Single pass each for histogram and placement.
void nts_sort_by_tile(const int32_t* tile, int64_t e_num, int32_t n_tiles,
                      int64_t* order) {
  int64_t* cursor = new int64_t[n_tiles + 1]();
  for (int64_t e = 0; e < e_num; ++e) ++cursor[tile[e] + 1];
  for (int32_t t = 0; t < n_tiles; ++t) cursor[t + 1] += cursor[t];
  for (int64_t e = 0; e < e_num; ++e) order[cursor[tile[e]]++] = e;
  delete[] cursor;
}

// Fill one stacked blocked-ELL level: row r's run of `row_len[r]` sorted
// edges is copied into nbr/wgt[row_tile[r], row_slot[r], :] and its dst
// recorded. Caller zero-inits nbr/wgt and v_num-fills dstr (padding rows).
void nts_fill_blocked_level(const int64_t* row_start, const int64_t* row_len,
                            const int32_t* row_tile, const int32_t* row_dst,
                            const int64_t* row_slot, int64_t n_rows,
                            int64_t n_l, int32_t K,
                            const int32_t* src_sorted, const float* w_sorted,
                            int32_t* nbr, float* wgt, int32_t* dstr) {
#pragma omp parallel for schedule(static)
  for (int64_t r = 0; r < n_rows; ++r) {
    const int64_t base = (int64_t)row_tile[r] * n_l + row_slot[r];
    int32_t* nb = nbr + base * K;
    float* wg = wgt + base * K;
    const int64_t lo = row_start[r];
    const int64_t len = row_len[r];
    for (int64_t j = 0; j < len; ++j) {
      nb[j] = src_sorted[lo + j];
      wg[j] = w_sorted[lo + j];
    }
    dstr[base] = row_dst[r];
  }
}

// Fill the block-sparse packed tables (ops/bsp_ell.py): run u (one
// destination's in-edge run within one source-tile group, already sorted)
// spans rows row_of_first[u] .. +ceil(len/K); edge j of the run lands in
// block row_block[row], lane row_slot[row], slot j%K. Caller zero-inits
// nbr/wgt and zero-inits ldst. One OpenMP pass over runs replaces the
// three O(E) fancy-index scatters of the NumPy build (its measured
// bottleneck at full scale).
void nts_fill_bsp(const int64_t* run_start, const int64_t* run_len,
                  const int64_t* row_of_first, const int32_t* run_ldst,
                  int64_t n_runs, const int64_t* row_block,
                  const int64_t* row_slot, const int32_t* src_local,
                  const float* w_sorted, int32_t K, int32_t R,
                  int32_t* nbr, float* wgt, int32_t* ldst) {
#pragma omp parallel for schedule(static)
  for (int64_t u = 0; u < n_runs; ++u) {
    const int64_t lo = run_start[u];
    const int64_t len = run_len[u];
    const int64_t row0 = row_of_first[u];
    const int32_t d = run_ldst[u];
    for (int64_t j = 0; j < len; ++j) {
      const int64_t row = row0 + j / K;
      const int64_t b = row_block[row];
      const int64_t s = row_slot[row];
      const int64_t at = (b * K + (j % K)) * R + s;
      nbr[at] = src_local[lo + j];
      wgt[at] = w_sorted[lo + j];
      if (j % K == 0) ldst[b * R + s] = d;
    }
  }
}

int nts_native_version(void) { return 6; }

}  // extern "C"
