"""ctypes bindings for the native preprocessing runtime (libnts_native.so).

Builds the shared library on first use if the toolchain is available
(one g++ invocation, cached beside this file); everything degrades to the
NumPy implementations when the library can't be built (NTS_NO_NATIVE=1
forces the fallback).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

from neutronstarlite_tpu.utils.logging import get_logger

log = get_logger("native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libnts_native.so")
_lib = None
_tried = False


def _build() -> bool:
    src = os.path.join(_DIR, "graph_native.cpp")
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O3", "-march=native", "-fPIC", "-shared", "-fopenmp", "-std=c++17",
        "-o", _SO, src,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
        return True
    except Exception as e:  # toolchain missing / compile error -> fallback
        log.warning("native build failed (%s); using NumPy fallback", e)
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if os.environ.get("NTS_NO_NATIVE", "0") == "1":
        return None
    # rebuild when missing or staler than its source (-march=native output
    # is machine-specific, so the .so is never shipped, only built here)
    src = os.path.join(_DIR, "graph_native.cpp")
    stale = not os.path.exists(_SO) or (
        os.path.exists(src) and os.path.getmtime(src) > os.path.getmtime(_SO)
    )
    if stale and not _build():
        return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError as e:
        log.warning("failed to load %s: %s", _SO, e)
        return None

    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
    f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")

    lib.nts_count_degrees.argtypes = [
        u32p, u32p, ctypes.c_int64, ctypes.c_int32, i32p, i32p,
    ]
    lib.nts_build_adjacency.argtypes = [
        u32p, u32p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int,
        i32p, i32p, i64p, i32p, i32p, f32p, i64p, i32p, i32p, f32p,
    ]
    lib.nts_sample_hop.argtypes = [
        i64p, i32p, i64p, ctypes.c_int64, ctypes.c_int32, ctypes.c_uint64,
        i32p, i32p, i32p,
    ]
    lib.nts_sort_by_tile.argtypes = [
        i32p, ctypes.c_int64, ctypes.c_int32, i64p,
    ]
    lib.nts_fill_blocked_level.argtypes = [
        i64p, i64p, i32p, i32p, i64p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int32, i32p, f32p, i32p, f32p, i32p,
    ]
    lib.nts_fill_bsp.argtypes = [
        i64p, i64p, i64p, i32p, ctypes.c_int64, i64p, i64p, i32p, f32p,
        ctypes.c_int32, ctypes.c_int32, i32p, f32p, i32p,
    ]
    lib.nts_dedup_remap.argtypes = [
        i64p, ctypes.c_int64, i64p, i32p,
    ]
    lib.nts_dedup_remap.restype = ctypes.c_int64
    lib.nts_native_version.restype = ctypes.c_int
    _lib = lib
    log.info("native runtime loaded (v%d)", lib.nts_native_version())
    return _lib


def available() -> bool:
    return get_lib() is not None


def build_adjacency(
    src: np.ndarray, dst: np.ndarray, v_num: int, weight_mode: int
) -> Tuple[np.ndarray, ...]:
    """Counting-sort CSC+CSR build. Returns (column_offset, csc_src, csc_dst,
    csc_w, row_offset, csr_src, csr_dst, csr_w, out_degree, in_degree).
    Edge order within a vertex's group is unspecified (grouped, dst-/src-
    sorted across groups) — sufficient for the segment ops' sorted promise."""
    lib = get_lib()
    assert lib is not None
    e_num = src.shape[0]
    src = np.ascontiguousarray(src, dtype=np.uint32)
    dst = np.ascontiguousarray(dst, dtype=np.uint32)
    out_degree = np.empty(v_num, np.int32)
    in_degree = np.empty(v_num, np.int32)
    lib.nts_count_degrees(src, dst, e_num, v_num, out_degree, in_degree)
    column_offset = np.zeros(v_num + 1, np.int64)
    np.cumsum(in_degree, out=column_offset[1:])
    row_offset = np.zeros(v_num + 1, np.int64)
    np.cumsum(out_degree, out=row_offset[1:])
    csc_src = np.empty(e_num, np.int32)
    csc_dst = np.empty(e_num, np.int32)
    csc_w = np.empty(e_num, np.float32)
    csr_src = np.empty(e_num, np.int32)
    csr_dst = np.empty(e_num, np.int32)
    csr_w = np.empty(e_num, np.float32)
    lib.nts_build_adjacency(
        src, dst, e_num, v_num, weight_mode, out_degree, in_degree,
        column_offset, csc_src, csc_dst, csc_w,
        row_offset, csr_src, csr_dst, csr_w,
    )
    return (
        column_offset, csc_src, csc_dst, csc_w,
        row_offset, csr_src, csr_dst, csr_w, out_degree, in_degree,
    )


def sort_by_tile(tile_of_edge: np.ndarray, n_tiles: int) -> np.ndarray:
    """Stable counting-sort permutation by source tile (O(E) vs argsort's
    O(E log E)); with dst-grouped input edges the result is (tile, dst)-
    sorted — the blocked ELL build's edge order."""
    lib = get_lib()
    assert lib is not None
    tile = np.ascontiguousarray(tile_of_edge, np.int32)
    order = np.empty(len(tile), np.int64)
    lib.nts_sort_by_tile(tile, len(tile), n_tiles, order)
    return order


def fill_blocked_level(
    row_start: np.ndarray, row_len: np.ndarray, row_tile: np.ndarray,
    row_dst: np.ndarray, row_slot: np.ndarray, n_l: int, K: int,
    src_sorted: np.ndarray, w_sorted: np.ndarray,
    nbr: np.ndarray, wgt: np.ndarray, dstr: np.ndarray,
) -> None:
    """Fill one stacked [T, n_l, K] blocked-ELL level in place (nbr/wgt
    zero-initialized, dstr v_num-filled by the caller)."""
    lib = get_lib()
    assert lib is not None
    lib.nts_fill_blocked_level(
        np.ascontiguousarray(row_start, np.int64),
        np.ascontiguousarray(row_len, np.int64),
        np.ascontiguousarray(row_tile, np.int32),
        np.ascontiguousarray(row_dst, np.int32),
        np.ascontiguousarray(row_slot, np.int64),
        len(row_start), n_l, K,
        np.ascontiguousarray(src_sorted, np.int32),
        np.ascontiguousarray(w_sorted, np.float32),
        nbr, wgt, dstr,
    )


def fill_bsp(
    run_start: np.ndarray, run_len: np.ndarray, row_of_first: np.ndarray,
    run_ldst: np.ndarray, row_block: np.ndarray, row_slot: np.ndarray,
    src_local: np.ndarray, w_sorted: np.ndarray, K: int, R: int,
    nbr: np.ndarray, wgt: np.ndarray, ldst: np.ndarray,
) -> None:
    """Fill the [B, K, R] block-sparse tables in place (ops/bsp_ell.py);
    nbr/wgt/ldst zero-initialized by the caller."""
    lib = get_lib()
    assert lib is not None
    lib.nts_fill_bsp(
        np.ascontiguousarray(run_start, np.int64),
        np.ascontiguousarray(run_len, np.int64),
        np.ascontiguousarray(row_of_first, np.int64),
        np.ascontiguousarray(run_ldst, np.int32),
        len(run_start),
        np.ascontiguousarray(row_block, np.int64),
        np.ascontiguousarray(row_slot, np.int64),
        np.ascontiguousarray(src_local, np.int32),
        np.ascontiguousarray(w_sorted, np.float32),
        K, R, nbr, wgt, ldst,
    )


def sample_hop(
    column_offset: np.ndarray,
    row_indices: np.ndarray,
    dsts: np.ndarray,
    fanout: int,
    seed: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """Fan-out sampling (reservoir or Floyd per degree); returns (src, dst_idx)."""
    lib = get_lib()
    assert lib is not None
    n = len(dsts)
    out_src = np.empty(n * fanout, np.int32)
    out_dst_idx = np.empty(n * fanout, np.int32)
    out_counts = np.empty(n, np.int32)
    lib.nts_sample_hop(
        np.ascontiguousarray(column_offset, np.int64),
        np.ascontiguousarray(row_indices, np.int32),
        np.ascontiguousarray(dsts, np.int64),
        n, fanout, seed, out_src, out_dst_idx, out_counts,
    )
    # compact: keep the first counts[i] entries of each dst's slot
    keep = (np.arange(n * fanout) % fanout) < np.repeat(out_counts, fanout)
    return out_src[keep].astype(np.int64), out_dst_idx[keep].astype(np.int64)


def dedup_remap(ids: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Sorted unique ids + each input's index into them — semantically
    ``uniq = np.unique(ids); local = np.searchsorted(uniq, ids)`` via two
    O(n) hash passes around an m-element sort (sampCSC::postprocessing's
    dedup, coocsc.hpp:62-89). Ids must be NONNEGATIVE (vertex ids): the C
    hash table uses -1 as its empty-slot sentinel."""
    lib = get_lib()
    assert lib is not None
    ids = np.ascontiguousarray(ids, np.int64)
    if len(ids) and ids.min() < 0:
        raise ValueError("dedup_remap requires nonnegative ids (vertex ids)")
    n = len(ids)
    uniq = np.empty(n, np.int64)
    local = np.empty(n, np.int32)
    m = lib.nts_dedup_remap(
        np.ascontiguousarray(ids, np.int64), n, uniq, local
    )
    return uniq[:m], local.astype(np.int64)
