"""Multi-writer, sequence-numbered, append-only GraphDelta log.

PR 14's delta ingestion is single-writer and unlogged: whoever holds the
servers applies a :class:`~neutronstarlite_tpu.serve.delta.GraphDelta`
and the history is gone. A streaming fleet needs the opposite — many
writers producing deltas concurrently, one total order every replica
agrees on, and a durable record a late-joining replica can replay. This
module is that record.

Merge semantics (the determinism contract)
------------------------------------------

Writers stage deltas into per-writer :class:`WriterSession`\\ s; nothing
is ordered at stage time. :meth:`DeltaLog.commit` is the ordering point:
every staged delta across all sessions is collected and sorted by the
CANONICAL key ``(writer_id, writer_seq)`` — NOT arrival order — then
assigned consecutive global sequence numbers and applied, one by one, to
the log's head graph. Because the key depends only on who wrote what
(not on thread scheduling), two arbitrarily interleaved stage orders of
the same sessions commit to the SAME total order, the same per-seq
graphs, and therefore the same digest sequence — the multi-writer
extension of the PR 14 bitwise oracle, pinned by
tests/test_stream_log.py.

Every committed entry records the canonical ``graph_digest``
(graph/digest.py) of the head graph AT that sequence point. Any replica
that has applied the log through seq N holds a graph bitwise-identical
to a fresh ``build_graph`` over the post-delta edge list at seq N; the
digest is the proof carried in-band, and consumers verify it on apply
(stream/ingest.py).

Commit is atomic at the batch level: every staged delta is validated and
applied to a SCRATCH head first (an invalid delta — e.g. removing an
edge that does not exist under the canonical order — aborts the whole
commit with nothing written and nothing staged lost), and only then do
the entries reach disk.

On-disk format (docs/STREAMING.md)
----------------------------------

A log directory holds::

    meta.json                    # schema, base digest, base v_num
    tail.jsonl                   # the live append file, one entry/line
    seg-00000001-00000042.jsonl  # sealed segments (seq lo..hi), immutable

Entries append to ``tail.jsonl`` (fsync'd per commit). :meth:`seal`
compacts the tail into an immutable segment published via the tmp +
``os.replace`` idiom — a reader never observes a half-written segment.
A writer killed MID tail append (the ``writer_crash`` chaos kind fires
at the ``delta_commit`` fault point planted between the two halves of
the entry's line) leaves a torn final line; recovery drops it LOUDLY and
keeps the committed prefix — tests kill a real subprocess to pin this.
A crash between segment publication and tail truncation can leave the
same seq in both files; readers dedup by seq, first occurrence wins.

Feature rows for appended vertices ride in the entry as nested float
lists: float32 -> Python float -> JSON -> float32 is exact (float64 is
a superset of float32 and JSON round-trips float64), so the digest /
bitwise guarantees survive serialization.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from neutronstarlite_tpu.graph.digest import graph_digest
from neutronstarlite_tpu.graph.storage import CSCGraph, build_graph
from neutronstarlite_tpu.resilience.faults import fault_point
from neutronstarlite_tpu.serve.delta import GraphDelta
from neutronstarlite_tpu.utils.logging import get_logger

log = get_logger("stream")

SCHEMA_VERSION = 1
META_NAME = "meta.json"
TAIL_NAME = "tail.jsonl"
SEG_PREFIX = "seg-"


@dataclasses.dataclass
class LogEntry:
    """One committed delta at its sequence point."""

    seq: int  # global total-order position (1-based)
    writer: str  # the committing WriterSession's id
    writer_seq: int  # position within that writer's session
    digest: str  # canonical head-graph digest AFTER applying this delta
    delta: GraphDelta

    def to_json(self) -> str:
        d = self.delta
        obj = {
            "seq": self.seq,
            "writer": self.writer,
            "writer_seq": self.writer_seq,
            "digest": self.digest,
            "add": [[int(s), int(t)]
                    for s, t in zip(d.add_src, d.add_dst)],
            "remove": [[int(s), int(t)]
                       for s, t in zip(d.remove_src, d.remove_dst)],
            "add_vertices": int(d.add_vertices),
        }
        if d.add_features is not None:
            rows = np.asarray(d.add_features)
            obj["add_features"] = [[float(x) for x in row] for row in rows]
            obj["feature_dtype"] = str(rows.dtype)
        return json.dumps(obj, separators=(",", ":"))

    @classmethod
    def from_json(cls, line: str) -> "LogEntry":
        obj = json.loads(line)
        feats = None
        if obj.get("add_features") is not None:
            feats = np.asarray(
                obj["add_features"],
                dtype=np.dtype(obj.get("feature_dtype", "float32")),
            )
        delta = GraphDelta.edges(
            add=[tuple(e) for e in obj.get("add", [])],
            remove=[tuple(e) for e in obj.get("remove", [])],
            add_vertices=int(obj.get("add_vertices", 0)),
            add_features=feats,
        )
        return cls(
            seq=int(obj["seq"]), writer=str(obj["writer"]),
            writer_seq=int(obj["writer_seq"]), digest=str(obj["digest"]),
            delta=delta,
        )


class WriterSession:
    """One writer's staging buffer; deltas carry (writer_id, writer_seq)
    — the canonical merge key — from the moment they are staged."""

    def __init__(self, log_: "DeltaLog", writer_id: str):
        self._log = log_
        self.writer_id = writer_id
        self.staged: List[Tuple[int, GraphDelta]] = []
        self._next_writer_seq = 1

    def stage(self, delta: GraphDelta) -> int:
        """Buffer a delta; returns its writer_seq. Thread-safe with other
        sessions (the log lock serializes), ordering-irrelevant with them
        (commit orders canonically, not by arrival)."""
        if delta.empty:
            raise ValueError("refusing to stage an empty GraphDelta")
        with self._log._lock:
            wseq = self._next_writer_seq
            self._next_writer_seq += 1
            self.staged.append((wseq, delta))
        return wseq


def _parse_lines(path: str, *, source: str) -> Tuple[List[LogEntry], int]:
    """Parse a jsonl file into entries; a torn final line (no trailing
    newline, or JSON that does not parse) is dropped LOUDLY with
    everything after it. Returns (entries, dropped_line_count)."""
    if not os.path.exists(path):
        return [], 0
    with open(path, "rb") as fh:
        raw = fh.read()
    entries: List[LogEntry] = []
    lines = raw.split(b"\n")
    # a file ending in "\n" splits into [..., b""]; anything else means
    # the final line never finished (the torn tail)
    complete, leftover = lines[:-1], lines[-1]
    dropped = 1 if leftover else 0
    for i, line in enumerate(complete):
        if not line.strip():
            continue
        try:
            entries.append(LogEntry.from_json(line.decode("utf-8")))
        except (ValueError, KeyError, UnicodeDecodeError):
            dropped += len(complete) - i
            break
    if dropped:
        log.warning(
            "stream log %s: dropped %d torn/unparseable trailing line(s) "
            "— a writer died mid-commit; the committed prefix is intact",
            source, dropped,
        )
    return entries, dropped


def _segments(root: str) -> List[str]:
    names = [n for n in os.listdir(root)
             if n.startswith(SEG_PREFIX) and n.endswith(".jsonl")]
    return [os.path.join(root, n) for n in sorted(names)]


def read_log_entries(root: str, after_seq: int = 0) -> List[LogEntry]:
    """Read committed entries with seq > ``after_seq`` from a log
    directory (sealed segments first, then the live tail), deduped by
    seq. The lightweight consumer path: tailing replicas and the
    fine-tune worker poll this without holding a graph."""
    entries: List[LogEntry] = []
    seen: Dict[int, bool] = {}
    for path in _segments(root) + [os.path.join(root, TAIL_NAME)]:
        parsed, _ = _parse_lines(path, source=path)
        for e in parsed:
            if e.seq in seen:
                continue
            seen[e.seq] = True
            if e.seq > after_seq:
                entries.append(e)
    entries.sort(key=lambda e: e.seq)
    return entries


class DeltaLog:
    """The ordered, durable, multi-writer GraphDelta log.

    ``DeltaLog(root, graph)`` opens-or-creates the log at ``root`` over
    the base ``graph`` (whose digest must match a pre-existing log's
    recorded base). Existing entries are replayed over the base to
    rebuild the head graph, verifying the recorded digest chain — an
    entry whose recomputed digest disagrees with its recorded one fails
    the open (corruption must not propagate silently).
    """

    def __init__(self, root: str, graph: CSCGraph, *, verify: bool = True):
        self.root = root
        self._lock = threading.RLock()
        self._sessions: Dict[str, WriterSession] = {}
        os.makedirs(root, exist_ok=True)
        base_digest = graph_digest(graph)
        meta_path = os.path.join(root, META_NAME)
        if os.path.exists(meta_path):
            with open(meta_path) as fh:
                meta = json.load(fh)
            if meta.get("base_digest") != base_digest:
                raise ValueError(
                    f"stream log {root} was recorded over base digest "
                    f"{meta.get('base_digest', '?')[:12]}..., but the "
                    f"supplied graph digests {base_digest[:12]}... — "
                    "wrong base graph"
                )
        else:
            meta = {
                "schema": SCHEMA_VERSION,
                "base_digest": base_digest,
                "base_v_num": int(graph.v_num),
            }
            tmp = meta_path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(meta, fh)
            os.replace(tmp, meta_path)
        self.base_digest = base_digest
        self.head_graph = graph
        self.head_digest = base_digest
        self.head_seq = 0
        self.head_features: Optional[np.ndarray] = None
        self.recovered_dropped = 0
        self._recover(verify=verify)

    # ---- open/recovery ---------------------------------------------------

    def _recover(self, verify: bool) -> None:
        entries = read_log_entries(self.root, after_seq=0)
        # count what recovery threw away (the torn-tail telemetry)
        _, dropped = _parse_lines(
            os.path.join(self.root, TAIL_NAME), source="tail"
        )
        self.recovered_dropped = dropped
        for e in entries:
            if e.seq != self.head_seq + 1:
                raise ValueError(
                    f"stream log {self.root}: sequence gap — entry seq "
                    f"{e.seq} follows head {self.head_seq}"
                )
            g2 = _apply_delta(self.head_graph, e.delta)
            if verify:
                d = graph_digest(g2)
                if d != e.digest:
                    raise ValueError(
                        f"stream log {self.root}: digest chain broken at "
                        f"seq {e.seq}: recorded {e.digest[:12]}..., "
                        f"recomputed {d[:12]}..."
                    )
            self.head_graph = g2
            self.head_digest = e.digest
            self.head_seq = e.seq
        if entries:
            log.info(
                "stream log %s: replayed %d entries to seq %d (digest %s)",
                self.root, len(entries), self.head_seq,
                self.head_digest[:12],
            )
        if dropped:
            # rewrite the tail without the torn line(s): the damage is
            # acknowledged once, not re-warned on every future open
            tail_entries, _ = _parse_lines(
                os.path.join(self.root, TAIL_NAME), source="tail"
            )
            self._rewrite_tail(tail_entries)

    def _rewrite_tail(self, entries: List[LogEntry]) -> None:
        tail = os.path.join(self.root, TAIL_NAME)
        tmp = tail + ".tmp"
        with open(tmp, "w") as fh:
            for e in entries:
                fh.write(e.to_json() + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, tail)

    # ---- writing ---------------------------------------------------------

    def writer(self, writer_id: str) -> WriterSession:
        """The (one) staging session for ``writer_id``."""
        with self._lock:
            sess = self._sessions.get(writer_id)
            if sess is None:
                sess = WriterSession(self, writer_id)
                self._sessions[writer_id] = sess
            return sess

    def commit(self) -> List[LogEntry]:
        """The ordering point: collect every staged delta across all
        sessions, order canonically by (writer_id, writer_seq), assign
        consecutive global seqs, apply to the head, record digests, and
        append durably. Atomic: an invalid delta aborts the whole batch
        with nothing written and nothing staged lost."""
        with self._lock:
            pending: List[Tuple[str, int, GraphDelta]] = []
            for wid in sorted(self._sessions):
                for wseq, d in self._sessions[wid].staged:
                    pending.append((wid, wseq, d))
            if not pending:
                return []
            pending.sort(key=lambda t: (t[0], t[1]))

            # validate + apply on a scratch head first (atomicity): only
            # a fully-valid batch reaches disk or the real head
            scratch = self.head_graph
            entries: List[LogEntry] = []
            seq = self.head_seq
            for wid, wseq, d in pending:
                seq += 1
                scratch = _apply_delta(scratch, d)
                entries.append(LogEntry(
                    seq=seq, writer=wid, writer_seq=wseq,
                    digest=graph_digest(scratch), delta=d,
                ))

            tail = os.path.join(self.root, TAIL_NAME)
            with open(tail, "ab") as fh:
                for e in entries:
                    line = (e.to_json() + "\n").encode("utf-8")
                    half = len(line) // 2
                    fh.write(line[:half])
                    fh.flush()
                    os.fsync(fh.fileno())
                    # the torn-tail chaos plant: writer_crash@seq=k dies
                    # HERE, with half of seq k's line durably on disk —
                    # recovery must drop exactly that half-line
                    fault_point("delta_commit", seq=e.seq)
                    fh.write(line[half:])
                fh.flush()
                os.fsync(fh.fileno())

            for sess in self._sessions.values():
                sess.staged.clear()
            self.head_graph = scratch
            self.head_digest = entries[-1].digest
            self.head_seq = entries[-1].seq
            log.info(
                "stream log commit: %d entries, head seq %d (digest %s)",
                len(entries), self.head_seq, self.head_digest[:12],
            )
            return entries

    def seal(self) -> Optional[str]:
        """Compact the live tail into an immutable segment file,
        published atomically (tmp + ``os.replace``); returns the segment
        path, or None when the tail is empty. A crash between segment
        publication and tail truncation duplicates entries across the
        two files — readers dedup by seq."""
        with self._lock:
            tail = os.path.join(self.root, TAIL_NAME)
            entries, _ = _parse_lines(tail, source="tail")
            if not entries:
                return None
            lo, hi = entries[0].seq, entries[-1].seq
            seg = os.path.join(self.root, f"{SEG_PREFIX}{lo:08d}-{hi:08d}.jsonl")
            tmp = seg + ".tmp"
            with open(tmp, "w") as fh:
                for e in entries:
                    fh.write(e.to_json() + "\n")
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, seg)
            self._rewrite_tail([])
            log.info("stream log sealed segment %s (seq %d..%d)",
                     os.path.basename(seg), lo, hi)
            return seg

    # ---- reading ---------------------------------------------------------

    def entries(self, after_seq: int = 0) -> List[LogEntry]:
        """Committed entries with seq > after_seq (replay-from-seq for a
        late-joining replica)."""
        return read_log_entries(self.root, after_seq=after_seq)

    def digest_sequence(self) -> List[str]:
        """The per-seq digest chain [digest@1, ..., digest@head] — the
        determinism oracle's comparison object."""
        return [e.digest for e in self.entries()]

    def iter_graphs(self, base: CSCGraph) -> Iterator[Tuple[int, CSCGraph]]:
        """Replay from ``base``, yielding (seq, graph-at-seq) — the
        fresh-build side of the bitwise oracle."""
        g = base
        for e in self.entries():
            g = _apply_delta(g, e.delta)
            yield e.seq, g


def _apply_delta(graph: CSCGraph, delta: GraphDelta) -> CSCGraph:
    """Apply one delta to a host graph via the deterministic NumPy build
    path — validation (missing removals raise) and edge-list editing
    shared with serve/delta.plan_delta, minus the dirty-set work the log
    does not need."""
    from neutronstarlite_tpu.serve import delta as delta_mod

    old_src = graph.row_indices.astype(np.int64)
    old_dst = graph.dst_of_edge.astype(np.int64)
    new_v = graph.v_num + int(delta.add_vertices)
    for name, arr in (("add_src", delta.add_src), ("add_dst", delta.add_dst),
                      ("remove_src", delta.remove_src),
                      ("remove_dst", delta.remove_dst)):
        if len(arr) and (int(arr.min()) < 0 or int(arr.max()) >= new_v):
            raise ValueError(
                f"graph delta {name} references a vertex outside "
                f"0..{new_v - 1}"
            )
    mask = np.ones(len(old_src), dtype=bool)
    if len(delta.remove_src):
        keys = delta_mod._edge_keys(old_src, old_dst)
        rm = np.unique(
            delta_mod._edge_keys(delta.remove_src, delta.remove_dst)
        )
        present = np.isin(rm, keys)
        if not present.all():
            missing = rm[~present][:5]
            pairs = [(int(k >> 32), int(k & 0xFFFFFFFF)) for k in missing]
            raise ValueError(
                f"graph delta removes edge(s) that do not exist: {pairs}"
            )
        mask = ~np.isin(keys, rm)
    src = np.concatenate([old_src[mask], delta.add_src])
    dst = np.concatenate([old_dst[mask], delta.add_dst])
    return build_graph(
        src.astype(np.uint32), dst.astype(np.uint32), new_v,
        weight="gcn_norm", use_native=False,
    )
