"""Recompile-free high-rate delta ingestion for a serving fleet.

Two costs dominate PR 14's delta path at streaming rates, and this
module removes both:

**Vertex-capacity margin.** A vertex append changes the feature slab's
shape, the AOT bucket executables' feature aval no longer matches, and
the whole ladder recompiles — tail-latency death at any real append
rate. :func:`reserve_feature_margin` pre-sizes the slab to
``[V + margin, f]`` (``NTS_STREAM_VERTEX_MARGIN``) BEFORE warmup, so
the ladder compiles against the padded aval once; appends within the
margin patch feature rows into reserved slack (serve/delta.py's
in-margin branch) and the ladder never notices — ``compile_counts``
stays pinned (test-asserted). Slack rows are zero and unreachable:
sampling only ever returns ids below the live ``v_num``. Overflowing
the margin degrades LOUDLY to the PR 14 full-invalidation path. The
device neighbor table gets the same treatment
(``DeviceUniformSampler.reserve_capacity``).

**Bitset approximate dirty closure.** The exact out-edge closure walks
real adjacency per delta — eager work proportional to reach, on the
ingest critical path. ``NTS_STREAM_DIRTY=bitset`` swaps in
:class:`BitsetDirtyTracker`: vertices hash into B buckets
(``NTS_STREAM_DIRTY_BUCKETS``, default 1024), a ``[B, B]`` boolean
bucket-adjacency matrix summarizes the edge set, and the closure runs
at bucket granularity — O(hops · B²) bitwise work independent of graph
size. Soundness: ``v -> w`` implies ``bucket(v) -> bucket(w)``, so the
bucket closure REACHES every bucket the exact closure touches and the
expanded vertex set is a SUPERSET of exact (pinned by
tests/test_stream_ingest.py) — extra invalidations cost recompute;
a missed one would serve stale logits, which is why only the
conservative direction is ever approximate. Added edges set bits
incrementally; removals leave stale bits (still a superset — monotone).
The false-positive rate is measured against the exact closure on an
audit cadence (``NTS_STREAM_DIRTY_AUDIT``, default every 16th commit)
and reported as the ``stream.dirty_fp_rate`` gauge.

:class:`StreamIngestor` ties the legs together: it consumes
:class:`~neutronstarlite_tpu.stream.log.DeltaLog` entries in order,
applies each through serve/delta.py (margin-aware, with the configured
dirty closure), VERIFIES the entry's recorded digest against the
applied graph (a diverged replica fails loudly instead of serving a
graph nobody committed), accumulates the dirty region for the
fine-tune worker, and emits one typed ``delta_commit`` record per
entry.
"""

from __future__ import annotations

import os
import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from neutronstarlite_tpu.graph.storage import CSCGraph
from neutronstarlite_tpu.serve import delta as delta_mod
from neutronstarlite_tpu.stream.log import LogEntry, read_log_entries
from neutronstarlite_tpu.utils.logging import get_logger

log = get_logger("stream")

DEFAULT_MARGIN = 0
DEFAULT_BUCKETS = 1024
DEFAULT_AUDIT_EVERY = 16


def margin_from_env() -> int:
    raw = os.environ.get("NTS_STREAM_VERTEX_MARGIN", "")
    if raw:
        try:
            return max(int(raw), 0)
        except ValueError:
            log.warning(
                "NTS_STREAM_VERTEX_MARGIN=%r is not an int; margin disabled",
                raw,
            )
    return DEFAULT_MARGIN


def dirty_mode_from_env() -> str:
    mode = os.environ.get("NTS_STREAM_DIRTY", "exact").strip() or "exact"
    if mode not in ("exact", "bitset"):
        raise ValueError(
            f"NTS_STREAM_DIRTY={mode!r}: known modes are 'exact' and "
            "'bitset'"
        )
    return mode


def reserve_feature_margin(engines: Sequence, margin: int) -> int:
    """Pre-size the engines' shared feature slab (and any device
    neighbor table) with ``margin`` slack rows. MUST run before
    ``warmup()``: the AOT ladder compiles against the feature aval it
    sees, and only a slab that is already padded gives appends room to
    patch without changing it. Engines cloned from one template share
    the slab — it is padded once and re-pointed everywhere. Returns the
    new physical row capacity."""
    import jax.numpy as jnp

    if margin <= 0:
        return int(engines[0].feature.shape[0])
    base = engines[0]
    feat = base.feature
    pad = jnp.zeros((int(margin), int(feat.shape[1])), dtype=feat.dtype)
    padded = jnp.concatenate([feat, pad], axis=0)
    toolkits = {}
    for eng in engines:
        eng.feature = padded
        # lets apply_to_engines tell "armed margin, fully consumed"
        # apart from "never armed" once the slack runs out (the loud
        # overflow warning hangs off this)
        eng.margin_armed = True
        toolkits[id(eng.toolkit)] = eng.toolkit
        hop = getattr(eng.sampler, "hop_sampler", None)
        if hop is not None and hop.margin < margin:
            hop.reserve_capacity(margin)
    for tk in toolkits.values():
        # the fine-tune worker's train step reads toolkit.feature; the
        # padded slab keeps its aval constant across future appends too
        tk.feature = padded
    log.info(
        "stream ingest: reserved a %d-row vertex-capacity margin "
        "(feature slab %s -> %s); the AOT ladder compiled after this "
        "point survives every in-margin append",
        margin, tuple(feat.shape), tuple(padded.shape),
    )
    return int(padded.shape[0])


class BitsetDirtyTracker:
    """Bucket-granular approximate out-closure (superset of exact)."""

    def __init__(self, graph: CSCGraph, buckets: int = DEFAULT_BUCKETS):
        self.B = max(int(buckets), 1)
        self.adj = np.zeros((self.B, self.B), dtype=bool)
        self._ingest_edges(
            graph.row_indices.astype(np.int64),
            graph.dst_of_edge.astype(np.int64),
        )
        self.fp_rate = 0.0

    def _bucket(self, v: np.ndarray) -> np.ndarray:
        return np.asarray(v, dtype=np.int64) % self.B

    def _ingest_edges(self, src: np.ndarray, dst: np.ndarray) -> None:
        if len(src):
            self.adj[self._bucket(src), self._bucket(dst)] = True

    def observe_delta(self, delta) -> None:
        """Fold a delta's ADDED edges into the bucket adjacency.
        Removed edges leave their bits set — stale bits only ever widen
        the closure (the superset stays sound, monotonically)."""
        self._ingest_edges(delta.add_src, delta.add_dst)

    def rebuild(self, graph: CSCGraph) -> None:
        """Drop accumulated stale bits by re-summarizing the live edge
        set (call on whatever cadence the measured fp rate motivates)."""
        self.adj[:] = False
        self._ingest_edges(
            graph.row_indices.astype(np.int64),
            graph.dst_of_edge.astype(np.int64),
        )

    def closure(self, old_graph: CSCGraph, new_graph: CSCGraph,
                changed_src: np.ndarray, changed_dst: np.ndarray,
                hops: int) -> np.ndarray:
        """The ``dirty_closure`` hook for serve/delta.plan_delta: the
        exact seed rule lifted to buckets, closed over the bucket
        adjacency, then expanded back to every vertex in a dirty
        bucket."""
        mask = np.zeros(self.B, dtype=bool)
        mask[self._bucket(changed_dst)] = True
        src_mask = np.zeros(self.B, dtype=bool)
        src_mask[self._bucket(changed_src)] = True
        # seed rule: changed destinations + out-neighbors of changed
        # sources — one bucket hop from the changed-source buckets
        mask |= self.adj[src_mask].any(axis=0)
        frontier = mask.copy()
        for _ in range(max(int(hops) - 1, 0)):
            nxt = self.adj[frontier].any(axis=0)
            fresh = nxt & ~mask
            if not fresh.any():
                break
            mask |= fresh
            frontier = fresh
        verts = np.arange(new_graph.v_num, dtype=np.int64)
        return verts[mask[verts % self.B]]


class StreamIngestor:
    """Ordered, digest-verified log consumption into a serving fleet.

    One ingestor per process; hand it the engines (and any servers whose
    caches must follow) plus the log root. :meth:`arm` reserves the
    capacity margin (before warmup); :meth:`consume` applies every
    committed entry past the current position; :meth:`take_dirty` hands
    the accumulated dirty region to the fine-tune worker and resets it.
    """

    def __init__(
        self,
        engines: Sequence,
        servers: Sequence = (),
        *,
        log_root: Optional[str] = None,
        margin: Optional[int] = None,
        dirty_mode: Optional[str] = None,
        buckets: Optional[int] = None,
        audit_every: Optional[int] = None,
        metrics=None,
    ):
        if not engines:
            raise ValueError("StreamIngestor needs at least one engine")
        self.engines = list(engines)
        self.servers = list(servers)
        self.log_root = log_root
        self.margin = margin_from_env() if margin is None else int(margin)
        self.dirty_mode = (dirty_mode_from_env() if dirty_mode is None
                           else str(dirty_mode))
        if self.dirty_mode not in ("exact", "bitset"):
            raise ValueError(
                f"dirty_mode {self.dirty_mode!r}: known modes are 'exact' "
                "and 'bitset'"
            )
        self.metrics = metrics if metrics is not None \
            else self.engines[0].metrics
        self.applied_seq = 0
        self._lock = threading.Lock()
        self._dirty: np.ndarray = np.empty(0, np.int64)
        self._dirty_from_seq = 1
        self.tracker: Optional[BitsetDirtyTracker] = None
        if self.dirty_mode == "bitset":
            nb = int(buckets) if buckets is not None else int(
                os.environ.get("NTS_STREAM_DIRTY_BUCKETS", DEFAULT_BUCKETS)
            )
            self.tracker = BitsetDirtyTracker(
                self.engines[0].sampler.graph, buckets=nb
            )
        self.audit_every = int(audit_every) if audit_every is not None \
            else int(os.environ.get("NTS_STREAM_DIRTY_AUDIT",
                                    DEFAULT_AUDIT_EVERY))
        self._applied_count = 0

    @property
    def head_seq(self) -> int:
        """Last sequence point applied to the engines."""
        return self.applied_seq

    def arm(self) -> None:
        """Reserve the vertex-capacity margin (call BEFORE warmup)."""
        if self.margin > 0:
            reserve_feature_margin(self.engines, self.margin)

    # ---- application -----------------------------------------------------

    def _dirty_closure_hook(self):
        if self.tracker is None:
            return None
        return self.tracker.closure

    def apply(self, entry: LogEntry) -> "delta_mod.DeltaPlan":
        """Apply one committed entry in order; verifies the recorded
        digest against the post-apply graph and accumulates the dirty
        region."""
        with self._lock:
            if entry.seq != self.applied_seq + 1:
                raise ValueError(
                    f"stream ingest: entry seq {entry.seq} does not follow "
                    f"applied head {self.applied_seq} — replay the log from "
                    f"seq {self.applied_seq} instead"
                )
            t0 = time.perf_counter()
            if self.tracker is not None:
                self.tracker.observe_delta(entry.delta)
            hook = self._dirty_closure_hook()
            base = self.engines[0]
            plan = delta_mod.plan_delta(
                base.sampler.graph, entry.delta, hops=len(base.fanouts),
                dirty_closure=hook,
            )
            if plan.digest != entry.digest:
                raise ValueError(
                    f"stream ingest: applying seq {entry.seq} produced "
                    f"digest {plan.digest[:12]}..., but the log recorded "
                    f"{entry.digest[:12]}... — this replica diverged from "
                    "the committed history"
                )
            fp_rate = None
            if self.tracker is not None and self.audit_every > 0 \
                    and (self._applied_count % self.audit_every) == 0:
                exact = delta_mod.plan_delta(
                    base.sampler.graph, entry.delta, hops=len(base.fanouts),
                )
                n_exact, n_approx = len(exact.dirty), len(plan.dirty)
                missing = np.setdiff1d(exact.dirty, plan.dirty)
                if len(missing):
                    raise AssertionError(
                        f"bitset dirty closure missed {len(missing)} "
                        "exact-dirty vertices — the superset invariant is "
                        "broken"
                    )
                fp_rate = (n_approx - n_exact) / max(n_approx, 1)
                self.tracker.fp_rate = fp_rate
                if self.metrics is not None:
                    self.metrics.gauge_set("stream.dirty_fp_rate",
                                           round(fp_rate, 6))
            if self.servers:
                delta_mod.apply_to_servers(
                    self.servers, entry.delta,
                    extra_engines=self.engines, plan=plan,
                )
            else:
                delta_mod.apply_to_engines(self.engines, entry.delta,
                                           plan=plan)
            self.applied_seq = entry.seq
            self._applied_count += 1
            self._dirty = np.union1d(self._dirty, plan.dirty)
            seconds = time.perf_counter() - t0
            if self.metrics is not None:
                self.metrics.counter_add("stream.entries_applied")
                self.metrics.gauge_set("stream.head_seq", entry.seq)
                fields = dict(
                    seq=entry.seq, writer=entry.writer,
                    writer_seq=entry.writer_seq,
                    added_edges=plan.added_edges,
                    removed_edges=plan.removed_edges,
                    added_vertices=plan.added_vertices,
                    graph_digest=plan.digest,
                    dirty=int(len(plan.dirty)),
                    dirty_mode=self.dirty_mode,
                    seconds=float(seconds),
                )
                if fp_rate is not None:
                    fields["fp_rate"] = float(round(fp_rate, 6))
                self.metrics.event("delta_commit", **fields)
            return plan

    def consume(self, log_root: Optional[str] = None) -> List[LogEntry]:
        """Apply every committed entry past the current position from
        the log directory; returns the entries applied."""
        root = log_root or self.log_root
        if root is None:
            raise ValueError("StreamIngestor has no log_root to consume")
        entries = read_log_entries(root, after_seq=self.applied_seq)
        for e in entries:
            self.apply(e)
        return entries

    # ---- the fine-tune worker's feed -------------------------------------

    def take_dirty(self) -> Tuple[np.ndarray, int, int]:
        """Hand over (dirty vertices, first seq, last seq) accumulated
        since the previous take, and reset the accumulator."""
        with self._lock:
            dirty = self._dirty
            lo, hi = self._dirty_from_seq, self.applied_seq
            self._dirty = np.empty(0, np.int64)
            self._dirty_from_seq = self.applied_seq + 1
            return dirty, lo, hi
