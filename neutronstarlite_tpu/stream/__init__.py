"""Streaming graph learning: the live half of the serving story.

Three legs (docs/STREAMING.md):

- :mod:`neutronstarlite_tpu.stream.log` — the multi-writer, sequence-
  numbered, append-only GraphDelta log with deterministic merge
  semantics and a canonical graph digest at every sequence point.
- :mod:`neutronstarlite_tpu.stream.ingest` — recompile-free ingestion:
  a pre-sized vertex-capacity margin so appends patch into reserved
  slack instead of invalidating the AOT bucket ladder, plus the bitset
  approximate dirty-closure for high delta rates.
- :mod:`neutronstarlite_tpu.stream.finetune` — the continuous
  fine-tune worker draining the accumulated dirty region between serve
  flushes and publishing checkpoints into the canary-gated rollout.
"""

from neutronstarlite_tpu.stream.log import (  # noqa: F401
    DeltaLog,
    LogEntry,
    WriterSession,
    read_log_entries,
)
