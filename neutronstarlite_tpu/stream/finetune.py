"""Continuous fine-tune worker: the model follows the streaming graph.

The closed loop this worker completes (ROADMAP's top open item): deltas
commit into the stream log, the ingestor applies them to the serving
fleet and accumulates the DIRTY region (vertices whose aggregation
inputs changed), and between serve flushes this worker drains that
region — a few epochs of the sampled trainer's own jitted step over
seeds biased toward dirty vertices — then checkpoints through the
existing digest-verified path and publishes the checkpoint into
``CrossHostFleet.rollout()``, where the PR 17 canary gate decides
promotion. The graph changes under load, the model follows, and the
fleet never stops answering.

Isolation contract: training mutates ``toolkit.params`` — but every
serving engine holds its OWN reference to the params tree it restored
(serve/engine.py), so in-flight serving NEVER sees half-trained
weights. New weights reach traffic only through the published
checkpoint and the canary-gated rollout — exactly the promotion
discipline the rollout machinery exists to enforce.

Zero-recompile discipline: rounds train with the toolkit's existing
jitted ``_train_batch`` over a :class:`~...sample.sampler.Sampler`
built with the SAME batch_size/fanouts (identical static node_caps),
and the feature operand is the margin-padded slab shared with serving
(stream/ingest.py) — so after the first round, every subsequent round
replays the same executable regardless of how many vertices streamed
in.

Knobs: ``epochs_per_drain`` (how hard each drain trains),
``dirty_frac`` (seed bias toward the dirty region,
:func:`~...sample.sampler.dirty_biased_seeds`), ``staleness_tol`` /
``NTS_STALENESS_TOL`` (how many sequence points the served model may
lag the graph head before the lag is flagged — the drift_audit
staleness leg reads the ``stream.head_seq``/``stream.model_seq``
gauges this worker maintains).

Supervision: each round plants the ``finetune_round`` fault point
(``exc@point=finetune_round`` kills one round); the worker retries a
failed round up to ``max_retries`` times (typed ``recovery`` records),
then gives the round up LOUDLY — a fine-tune death degrades freshness,
never serving.

Every completed round emits one typed ``finetune_round`` record: the
drained seq range, dirty size, epochs/batches/loss, the checkpoint
step, and the rollout verdict when a publish hook is wired.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

from neutronstarlite_tpu.resilience import events
from neutronstarlite_tpu.resilience.faults import fault_point
from neutronstarlite_tpu.sample.sampler import Sampler, dirty_biased_seeds
from neutronstarlite_tpu.utils.logging import get_logger

log = get_logger("stream")

DEFAULT_STALENESS_TOL = 8


def staleness_tol_from_env() -> int:
    raw = os.environ.get("NTS_STALENESS_TOL", "")
    if raw:
        try:
            return max(int(raw), 0)
        except ValueError:
            log.warning(
                "NTS_STALENESS_TOL=%r is not an int; using %d", raw,
                DEFAULT_STALENESS_TOL,
            )
    return DEFAULT_STALENESS_TOL


class FineTuneWorker:
    """Background trainer draining the ingestor's dirty region.

    ``source`` is anything with ``take_dirty() -> (dirty, lo, hi)`` and
    a ``head_seq`` attribute — in practice the
    :class:`~neutronstarlite_tpu.stream.ingest.StreamIngestor`.
    ``publish`` is called with the checkpoint dir after each round's
    save and should return the rollout record's fields
    (``CrossHostFleet.rollout`` does exactly that); None skips
    publication.
    """

    def __init__(
        self,
        toolkit: Any,
        source: Any,
        ckpt_dir: str,
        *,
        publish: Optional[Callable[[str], Dict[str, Any]]] = None,
        epochs_per_drain: int = 1,
        dirty_frac: float = 0.7,
        seeds_per_round: Optional[int] = None,
        staleness_tol: Optional[int] = None,
        max_retries: int = 2,
        interval_s: float = 0.2,
        seed: int = 0,
        metrics=None,
    ):
        self.toolkit = toolkit
        self.source = source
        self.ckpt_dir = ckpt_dir
        self.publish = publish
        self.epochs_per_drain = max(int(epochs_per_drain), 1)
        self.dirty_frac = float(dirty_frac)
        self.seeds_per_round = seeds_per_round
        self.staleness_tol = (staleness_tol_from_env()
                              if staleness_tol is None else int(staleness_tol))
        self.max_retries = max(int(max_retries), 0)
        self.interval_s = float(interval_s)
        self.seed = int(seed)
        self.metrics = metrics if metrics is not None else toolkit.metrics
        self.rounds = 0  # completed rounds
        self.model_seq = 0  # last sequence point the published model saw
        self._rng = np.random.default_rng(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        from neutronstarlite_tpu.utils.checkpoint import latest_npz_step

        latest = latest_npz_step(ckpt_dir) if os.path.isdir(ckpt_dir) else None
        self._next_step = (latest + 1) if latest is not None else 0

    # ---- one round -------------------------------------------------------

    def staleness(self) -> int:
        """How many sequence points the served model lags the applied
        graph head (the quantity NTS_STALENESS_TOL bounds)."""
        return max(int(self.source.head_seq) - int(self.model_seq), 0)

    def drain_once(self) -> Optional[Dict[str, Any]]:
        """Synchronous single drain: take the accumulated dirty region,
        fine-tune over it, checkpoint, publish. Returns the round
        summary, or None when nothing had changed. A failed round is
        retried up to ``max_retries`` times, then given up loudly."""
        dirty, lo, hi = self.source.take_dirty()
        if hi < lo:
            return None
        rnd = self.rounds
        attempt = 0
        while True:
            try:
                summary = self._round(rnd, dirty, lo, hi)
                break
            except Exception as exc:  # supervised: retry, then give up
                attempt += 1
                if attempt > self.max_retries:
                    events.emit_recovery(
                        "giveup", point="finetune_round", attempt=attempt,
                        epoch=rnd,
                    )
                    log.error(
                        "fine-tune round %d failed %d time(s), giving it "
                        "up: %s — the model stays at seq %d (stale by %d)",
                        rnd, attempt, exc, self.model_seq,
                        hi - self.model_seq,
                    )
                    return None
                events.emit_recovery(
                    "restart", point="finetune_round", attempt=attempt,
                    epoch=rnd,
                )
                log.warning(
                    "fine-tune round %d died (%s); supervised retry "
                    "%d/%d", rnd, exc, attempt, self.max_retries,
                )
        self.rounds += 1
        self.model_seq = hi
        if self.metrics is not None:
            self.metrics.gauge_set("stream.model_seq", self.model_seq)
        lag = self.staleness()
        if lag > self.staleness_tol:
            log.warning(
                "fine-tune worker is %d sequence points behind the graph "
                "head (NTS_STALENESS_TOL=%d) — drains are not keeping up "
                "with the delta rate", lag, self.staleness_tol,
            )
        return summary

    def _round(self, rnd: int, dirty: np.ndarray, lo: int,
               hi: int) -> Dict[str, Any]:
        import jax

        from neutronstarlite_tpu.models.gcn_sample import _batch_arrays

        t0 = time.perf_counter()
        # the worker-death chaos plant (exc@point=finetune_round)
        fault_point("finetune_round", epoch=rnd)
        tk = self.toolkit
        train_nids = np.where(tk.datum.mask == 0)[0]
        n = self.seeds_per_round
        if n is None:
            n = min(len(train_nids), max(tk.cfg.batch_size * 4, 1))
        seeds = dirty_biased_seeds(
            train_nids, dirty, int(n), self.dirty_frac, self._rng,
        )
        if len(seeds) == 0:
            raise RuntimeError("fine-tune round has no trainable seeds")
        # same batch_size/fanouts as training -> identical static
        # node_caps -> _train_batch replays its compiled executable
        sampler = Sampler(
            tk.host_graph, seeds, tk.cfg.batch_size, tk.fanouts,
            seed=self.seed + 7919 * rnd + 1,
        )
        key = jax.random.PRNGKey(self.seed + 104729 + rnd)
        loss = None
        batches = 0
        for ep in range(self.epochs_per_drain):
            for bi, b in enumerate(sampler.sample_epoch(shuffle=True)):
                nodes, hops, seed_mask, seeds_arr = _batch_arrays(b)
                bkey = jax.random.fold_in(key, ep * 100003 + bi)
                tk.params, tk.opt_state, loss = tk._train_batch(
                    tk.params, tk.opt_state, tk.feature, tk.label,
                    nodes, hops, seed_mask, seeds_arr, bkey,
                )
                batches += 1
        jax.block_until_ready(loss)
        loss_f = float(loss) if loss is not None else float("nan")

        step = self._next_step
        tk.save(self.ckpt_dir, step)  # the digest-verified publish path
        self._next_step += 1

        verdict = None
        rollout: Dict[str, Any] = {}
        if self.publish is not None:
            rollout = self.publish(self.ckpt_dir) or {}
            verdict = rollout.get("verdict")
        seconds = time.perf_counter() - t0
        if self.metrics is not None:
            self.metrics.counter_add("stream.finetune_rounds")
            self.metrics.observe("stream.finetune_round", seconds)
            fields = dict(
                round=rnd, seq_lo=int(lo), seq_hi=int(hi),
                dirty=int(len(dirty)), epochs=self.epochs_per_drain,
                batches=int(batches), loss=loss_f, ckpt_step=int(step),
                verdict=verdict, seconds=float(seconds),
            )
            self.metrics.event("finetune_round", **fields)
        log.info(
            "fine-tune round %d: drained seq %d..%d (%d dirty), %d "
            "batches, loss %.4f, ckpt step %d%s (%.2fs)",
            rnd, lo, hi, len(dirty), batches, loss_f, step,
            f", rollout {verdict}" if verdict else "", seconds,
        )
        return dict(
            round=rnd, seq_lo=lo, seq_hi=hi, dirty=int(len(dirty)),
            batches=batches, loss=loss_f, ckpt_step=step,
            verdict=verdict, rollout=rollout, seconds=seconds,
        )

    # ---- background operation --------------------------------------------

    def start(self) -> None:
        """Run drains on a daemon thread until :meth:`stop`."""
        if self._thread is not None:
            raise RuntimeError("fine-tune worker already started")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="finetune-worker", daemon=True,
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.drain_once()
            except Exception:
                # drain_once already retries; anything that escapes is a
                # supervisor bug — keep the worker alive, serving wins
                log.exception("fine-tune drain escaped its supervisor")
            self._stop.wait(self.interval_s)

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
