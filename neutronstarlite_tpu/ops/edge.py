"""Edge-level operators: V->E scatter, E->V aggregate, per-dst edge softmax.

TPU counterparts of the reference's edge-op family used by the GAT/GIN chains:

- ``scatter_src_to_edge`` / ``scatter_dst_to_edge`` / ``scatter_src_dst_to_edge``
  mirror SingleCPUSrcScatterOp / DistScatterSrc / DistScatterDst /
  SingleCPUSrcDstScatterOp (core/ntsSingleCPUGraphOp.hpp:34/:94,
  core/ntsDistCPUGraphOp.hpp:127/:186). V->E gather; the autodiff transpose is
  the scatter-add the reference hand-writes as the backward.
- ``aggregate_edge_to_dst`` mirrors SingleCPUDstAggregateOp /
  DistAggregateDst (E->V sum; backward broadcasts the gradient to edges).
- ``aggregate_edge_to_dst_weighted`` is the two-input op
  DistAggregateDstFuseWeight (core/ntsDistCPUGraphOp.hpp:499): out[dst] +=
  w_e * x[src]; gradients flow to BOTH the edge weights (dot product, :581,
  the reference returns it via get_additional_grad) and the features — jax
  autodiff produces exactly that pair from the einsum form.
- ``edge_softmax`` mirrors SingleEdgeSoftMax / DistEdgeSoftMax /
  edge_softmax_forward_block (core/ntsSingleCPUGraphOp.hpp:343,
  cuda/ntsCUDADistKernel.cuh:100): per-destination softmax over incident-edge
  scores, with the softmax-Jacobian backward s*(g - sum_dst(s*g)) hand-paired
  via custom_vjp (reference backward at ntsSingleCPUGraphOp.hpp:397).

All edge tensors are in CSC (dst-sorted) order and padded; ``edge_mask``
zeroes padding so softmax normalization and scatters ignore it.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from neutronstarlite_tpu.ops.device_graph import DeviceGraph
from neutronstarlite_tpu.ops.segment import (
    segment_max_sorted,
    segment_min_sorted,
    segment_sum_sorted,
    zero_cotangent,
)


def scatter_src_to_edge(graph: DeviceGraph, x: jax.Array) -> jax.Array:
    """[V, f] -> [Ep, f]: edge e gets x[src(e)] (zero on padding)."""
    return x[graph.csc_src] * graph.edge_mask[:, None].astype(x.dtype)


def scatter_dst_to_edge(graph: DeviceGraph, x: jax.Array) -> jax.Array:
    """[V, f] -> [Ep, f]: edge e gets x[dst(e)] (zero on padding)."""
    return x[graph.csc_dst] * graph.edge_mask[:, None].astype(x.dtype)


def scatter_src_dst_to_edge(graph: DeviceGraph, x: jax.Array) -> jax.Array:
    """[V, f] -> [Ep, 2f]: edge e gets [x[src(e)] || x[dst(e)]] — the 2f-wide
    layout of SingleCPUSrcDstScatterOp."""
    return jnp.concatenate(
        [scatter_src_to_edge(graph, x), scatter_dst_to_edge(graph, x)], axis=1
    )


def aggregate_edge_to_dst(graph: DeviceGraph, edge_vals: jax.Array) -> jax.Array:
    """[Ep, f] -> [V, f]: out[v] = sum of edge_vals over in-edges of v."""
    masked = edge_vals * graph.edge_mask[:, None].astype(edge_vals.dtype)
    return segment_sum_sorted(masked, graph.csc_dst, graph.v_num)


def aggregate_edge_to_dst_weighted(
    graph: DeviceGraph, edge_weight: jax.Array, x: jax.Array
) -> jax.Array:
    """Two-input op: out[v] = sum over in-edges e of edge_weight[e] * x[src(e)].

    ``edge_weight`` is [Ep] or [Ep, 1]. Differentiable in both inputs
    (DistAggregateDstFuseWeight semantics incl. its get_additional_grad path).
    """
    if edge_weight.ndim == 1:
        edge_weight = edge_weight[:, None]
    vals = x[graph.csc_src] * edge_weight * graph.edge_mask[:, None].astype(x.dtype)
    return segment_sum_sorted(vals, graph.csc_dst, graph.v_num)


def _edge_extreme_impl(v_num, is_min, dst, mask, ev):
    """Per-dst elementwise extreme over edge values + winning-edge record.

    The shared core of SingleCPUDstAggregateOpMin/Max
    (core/ntsSingleCPUGraphOp.hpp:206/:274) and DistAggregateDstMin/Max
    (core/ntsDistCPUGraphOp.hpp:306/:374): ``record`` holds the first edge
    attaining the extreme per (vertex, feature), the backward routes the
    gradient to exactly that edge (the reference's nts_assign routing).
    """
    el = dst.shape[0]
    fill = jnp.inf if is_min else -jnp.inf
    masked = jnp.where(mask[:, None] > 0, ev, fill)
    seg = (
        segment_min_sorted(masked, dst, v_num)
        if is_min
        else segment_max_sorted(masked, dst, v_num)
    )
    eidx = jnp.arange(el, dtype=jnp.int32)[:, None]
    hit = (masked == seg[dst]) & (mask[:, None] > 0)
    record = segment_min_sorted(jnp.where(hit, eidx, el), dst, v_num)
    out = jnp.where(jnp.isfinite(seg), seg, 0.0).astype(ev.dtype)
    return out, record


@partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _edge_extreme(v_num, is_min, dst, mask, ev):
    out, _ = _edge_extreme_impl(v_num, is_min, dst, mask, ev)
    return out


def _edge_extreme_fwd(v_num, is_min, dst, mask, ev):
    out, record = _edge_extreme_impl(v_num, is_min, dst, mask, ev)
    return out, (dst, mask, record)


def _edge_extreme_bwd(v_num, is_min, res, g):
    dst, mask, record = res
    el = dst.shape[0]
    valid = record < el
    safe = jnp.minimum(record, el - 1)  # [v_num, f] winning edge per element
    cols = jnp.broadcast_to(
        jnp.arange(g.shape[1], dtype=jnp.int32)[None, :], safe.shape
    )
    grad_ev = (
        jnp.zeros((el, g.shape[1]), dtype=g.dtype)
        .at[safe, cols]
        .add(jnp.where(valid, g, 0.0))
    )
    return (zero_cotangent(dst), zero_cotangent(mask), grad_ev)


_edge_extreme.defvjp(_edge_extreme_fwd, _edge_extreme_bwd)


def aggregate_edge_to_dst_max(graph: DeviceGraph, edge_vals: jax.Array) -> jax.Array:
    """[Ep, f] -> [V, f]: per-dst elementwise max; gradient routed to the
    winning edge (SingleCPUDstAggregateOpMax, core/ntsSingleCPUGraphOp.hpp:274)."""
    return _edge_extreme(graph.v_num, False, graph.csc_dst, graph.edge_mask, edge_vals)


def aggregate_edge_to_dst_min(graph: DeviceGraph, edge_vals: jax.Array) -> jax.Array:
    """[Ep, f] -> [V, f]: per-dst elementwise min (SingleCPUDstAggregateOpMin,
    core/ntsSingleCPUGraphOp.hpp:206)."""
    return _edge_extreme(graph.v_num, True, graph.csc_dst, graph.edge_mask, edge_vals)


def _edge_softmax_impl(v_num, csc_dst, mask, score):
    # PINNED CONVENTION (regression-tested, tests/test_fused_edge.py):
    # a destination whose incident edges are ALL padding (or that has no
    # in-edges at all) must produce EXACT ZEROS, never NaN — the empty
    # softmax normalizes over nothing, so its weights are defined as 0
    # and the downstream weighted aggregate yields zero rows. The fused
    # online softmax (ops/fused_edge.fused_finalize) reproduces exactly
    # this: l == 0 -> out = 0. The padded -inf scores zero out in the
    # exp, and the empty-segment denominator is guarded below.
    neg = jnp.asarray(-jnp.inf, dtype=score.dtype)
    masked = jnp.where(mask[:, None] > 0, score, neg)
    m = segment_max_sorted(masked, csc_dst, v_num)
    m = jnp.where(jnp.isfinite(m), m, 0.0)  # vertices with no in-edges
    e = jnp.exp(masked - m[csc_dst])
    e = jnp.where(mask[:, None] > 0, e, 0.0)
    denom = segment_sum_sorted(e, csc_dst, v_num)
    # empty segments (padding vertices with no in-edges) sum to 0; 1e-38 is
    # subnormal in f32 and XLA flushes it to zero, so guard with where
    denom = jnp.where(denom > 0, denom, jnp.ones_like(denom))
    return e / denom[csc_dst]


@partial(jax.custom_vjp, nondiff_argnums=(0,))
def _edge_softmax(v_num, csc_dst, mask, score):
    return _edge_softmax_impl(v_num, csc_dst, mask, score)


def _edge_softmax_fwd(v_num, csc_dst, mask, score):
    s = _edge_softmax_impl(v_num, csc_dst, mask, score)
    return s, (csc_dst, mask, s)


def _edge_softmax_bwd(v_num, res, g):
    csc_dst, mask, s = res
    # softmax Jacobian per destination segment: ds = s * (g - sum_seg(s*g))
    sg = s * g
    tot = segment_sum_sorted(sg, csc_dst, v_num)
    grad = s * (g - tot[csc_dst])
    grad = grad * mask[:, None].astype(grad.dtype)
    return (zero_cotangent(csc_dst), zero_cotangent(mask), grad)


_edge_softmax.defvjp(_edge_softmax_fwd, _edge_softmax_bwd)


def edge_softmax(graph: DeviceGraph, score: jax.Array) -> jax.Array:
    """[Ep, h] -> [Ep, h]: per-destination softmax over incident-edge scores
    (h = attention heads). Numerically stabilized by per-segment max."""
    squeeze = score.ndim == 1
    if squeeze:
        score = score[:, None]
    out = _edge_softmax(graph.v_num, graph.csc_dst, graph.edge_mask, score)
    return out[:, 0] if squeeze else out
