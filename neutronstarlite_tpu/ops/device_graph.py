"""HBM-resident graph representation — the device twin of CSCGraph.

Reference: CSC_segment_pinned's GPU twins (``*_gpu`` arrays,
core/GraphSegment.cpp:78-115 pinned alloc, :178-212 CopyGraphToDevice).
On TPU there is no pinned/device distinction — the arrays live in HBM and the
structure is a JAX pytree so it can flow through jit/shard_map unchanged.

Edge arrays are padded to a multiple of the edge-chunk size so the chunked
aggregation loop (ops/aggregate.py) sees static shapes. Padded edges carry
weight 0 and mask 0 and point at vertex 0, so weighted sums ignore them;
masked ops (edge softmax, min/max) use ``edge_mask``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from neutronstarlite_tpu.graph.storage import CSCGraph

# Default edge-chunk length for the blocked aggregation loop. 256Ki edges
# keeps the per-chunk gathered activation block (chunk x feature) well under
# 1 GB for feature widths up to ~1k while amortizing scan overhead.
DEFAULT_EDGE_CHUNK = 1 << 18


def _pad_to(arr: np.ndarray, n: int, fill) -> np.ndarray:
    if arr.shape[0] == n:
        return arr
    out = np.full((n,) + arr.shape[1:], fill, dtype=arr.dtype)
    out[: arr.shape[0]] = arr
    return out


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class DeviceGraph:
    """Dual CSC/CSR edge arrays on device.

    CSC view (dst-sorted, forward aggregation):
      ``csc_src``  [Ep] source vertex of each edge
      ``csc_dst``  [Ep] destination (non-decreasing)
      ``csc_weight`` [Ep] forward edge weight (0 on padding)
    CSR view (src-sorted, backward/gradient push):
      ``csr_src``  [Ep] source (non-decreasing)
      ``csr_dst``  [Ep] destination
      ``csr_weight`` [Ep]
    ``edge_mask`` [Ep] 1.0 on real edges, 0.0 on padding (CSC order).
    ``in_degree`` / ``out_degree`` [V] float32 (zero-clamped available via ops).
    """

    csc_src: jax.Array
    csc_dst: jax.Array
    csc_weight: jax.Array
    csr_src: jax.Array
    csr_dst: jax.Array
    csr_weight: jax.Array
    edge_mask: jax.Array
    in_degree: jax.Array
    out_degree: jax.Array
    v_num: int = dataclasses.field(metadata=dict(static=True))
    e_num: int = dataclasses.field(metadata=dict(static=True))
    edge_chunk: int = dataclasses.field(metadata=dict(static=True))

    @property
    def e_pad(self) -> int:
        return self.csc_src.shape[0]

    @property
    def num_chunks(self) -> int:
        return self.e_pad // self.edge_chunk

    @staticmethod
    def from_host(
        g: CSCGraph,
        edge_chunk: Optional[int] = None,
        dtype=jnp.float32,
    ) -> "DeviceGraph":
        """Ship a host CSCGraph to device, padding edge arrays to a chunk
        multiple (CopyGraphToDevice analog)."""
        if edge_chunk is None:
            edge_chunk = min(DEFAULT_EDGE_CHUNK, max(128, int(g.e_num)))
        e_pad = ((g.e_num + edge_chunk - 1) // edge_chunk) * edge_chunk
        e_pad = max(e_pad, edge_chunk)

        mask = np.zeros(e_pad, dtype=np.float32)
        mask[: g.e_num] = 1.0

        return DeviceGraph(
            csc_src=jnp.asarray(_pad_to(g.row_indices, e_pad, 0)),
            csc_dst=jnp.asarray(_pad_to(g.dst_of_edge, e_pad, 0)),
            csc_weight=jnp.asarray(
                _pad_to(g.edge_weight_forward, e_pad, 0.0), dtype=dtype
            ),
            csr_src=jnp.asarray(_pad_to(g.src_of_edge, e_pad, 0)),
            csr_dst=jnp.asarray(_pad_to(g.column_indices, e_pad, 0)),
            csr_weight=jnp.asarray(
                _pad_to(g.edge_weight_backward, e_pad, 0.0), dtype=dtype
            ),
            edge_mask=jnp.asarray(mask),
            in_degree=jnp.asarray(g.in_degree, dtype=jnp.float32),
            out_degree=jnp.asarray(g.out_degree, dtype=jnp.float32),
            v_num=int(g.v_num),
            e_num=int(g.e_num),
            edge_chunk=int(edge_chunk),
        )
