"""Per-destination neighborhood views of edge/vertex tensors.

Reference counterpart: ``ntsEdgeTensor`` / ``ntsVertexTensor``
(core/NtsEdgeTensor.hpp:23-183) — ``getNbrTensor(v)`` returns the slice of an
edge tensor holding v's incident edges, the utility the reference uses to run
per-vertex NN over a vertex's incident-edge block.

TPU re-design: ragged per-vertex slices are hostile to XLA (dynamic shapes),
so the view is materialized as a *padded dense neighborhood table*
``[V, K, f]`` via one gather — K is the (optionally capped) max in-degree and
``mask`` zeroes the padding. Per-vertex NN over incident edges then becomes a
single batched op over axis 1, which is exactly how a TPU wants to see it
(static shapes, MXU-batchable).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from neutronstarlite_tpu.graph.storage import CSCGraph


@dataclasses.dataclass(frozen=True)
class NbrTable:
    """[V, K] edge-slot table into the CSC-ordered edge arrays + mask."""

    edge_slot: jax.Array  # [V, K] int32 indices into [Ep]-shaped edge tensors
    mask: jax.Array  # [V, K] float32, 1 on real incident edges
    cap: int

    @staticmethod
    def build(g: CSCGraph, cap: Optional[int] = None) -> "NbrTable":
        """K = max in-degree unless ``cap`` truncates heavy vertices (the
        fan-out-style bound; reference slices are exact because libtorch
        tolerates ragged views — here capping is the static-shape price)."""
        deg = g.in_degree.astype(np.int64)
        K = int(deg.max()) if cap is None else min(int(deg.max()), cap)
        K = max(K, 1)
        off = g.column_offset.astype(np.int64)
        v = g.v_num
        slot = np.zeros((v, K), dtype=np.int32)
        mask = np.zeros((v, K), dtype=np.float32)
        k = np.arange(K)
        take = np.minimum(deg, K)  # [V]
        valid = k[None, :] < take[:, None]  # [V, K]
        slot[valid] = (off[:v, None] + k[None, :])[valid]
        mask[valid] = 1.0
        return NbrTable(
            edge_slot=jnp.asarray(slot), mask=jnp.asarray(mask), cap=K
        )

    def edge_view(self, edge_tensor: jax.Array) -> jax.Array:
        """[Ep, f] edge tensor -> [V, K, f] per-dst incident-edge blocks
        (getNbrTensor for every vertex at once)."""
        m = self.mask
        vals = edge_tensor[self.edge_slot]
        return vals * m[..., None].astype(vals.dtype)

    def vertex_view(self, graph, x: jax.Array) -> jax.Array:
        """[V, f] vertex tensor -> [V, K, f] neighbor-feature blocks:
        block[v, k] = x[src of v's k-th in-edge]."""
        src = graph.csc_src[self.edge_slot]  # [V, K]
        vals = x[src]
        return vals * self.mask[..., None].astype(vals.dtype)

    def reduce_sum(self, blocks: jax.Array) -> jax.Array:
        """[V, K, f] -> [V, f] sum over the neighborhood axis. Blocks from
        edge_view/vertex_view are already padding-masked; NN-transformed
        blocks whose padding rows became nonzero (e.g. a bias add) should be
        re-masked by the caller via ``blocks * mask[..., None]`` first."""
        return blocks.sum(axis=1)
