"""Segment-op helpers shared by the aggregation/edge operators."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def zero_cotangent(x):
    """Zero cotangent for a primal of any dtype (float0 for integer arrays) —
    used by custom_vjp backwards whose extra operands (graph indices/weights)
    carry no gradient."""
    if jnp.issubdtype(jnp.result_type(x), jnp.floating) or jnp.issubdtype(
        jnp.result_type(x), jnp.complexfloating
    ):
        return jnp.zeros_like(x)
    return np.zeros(jnp.shape(x), dtype=jax.dtypes.float0)


def segment_sum_sorted(data, segment_ids, num_segments):
    """segment_sum with the sorted-indices promise (CSC/CSR order gives it)."""
    return jax.ops.segment_sum(
        data, segment_ids, num_segments=num_segments, indices_are_sorted=True
    )


def segment_max_sorted(data, segment_ids, num_segments):
    return jax.ops.segment_max(
        data, segment_ids, num_segments=num_segments, indices_are_sorted=True
    )


def segment_min_sorted(data, segment_ids, num_segments):
    return jax.ops.segment_min(
        data, segment_ids, num_segments=num_segments, indices_are_sorted=True
    )
