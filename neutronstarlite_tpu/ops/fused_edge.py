"""Fused SDDMM + online softmax + SpMM: the blocked edge kernel for the
attention/edge-op families (GAT / GGCN).

The eager edge-op chain (models/gat.py, models/ggcn.py over ops/edge.py)
runs the paper's decoupled operator sequence literally: ``scatter_src_to_
edge`` materializes a padded [Ep, f] edge tensor in HBM, ``edge_softmax``
makes two more [Ep]-shaped passes (segment max + segment sum), and
``aggregate_edge_to_dst_weighted`` reads the edge space back — three HBM
round-trips of edge-width tensors per layer, the traffic class the GCN
family already avoids via the blocked kernels. FusedMM (PAPERS.md) shows
the SDDMM (edge-score) and SpMM (aggregate) phases fuse into one kernel
with no edge-tensor round-trip; this module is that fusion re-derived for
the streamed-block regime of ops/blocked_ell.py / ops/bsp_ell.py:

- the source space is cut into tiles of ``vt`` rows; per (src-tile,
  dst-run) block the tables hold tile-LOCAL source ids, so every gather
  indexes a [vt, .] resident slab (the ops/ell.py on-chip-gather premise;
  a Mosaic/Pallas lowering of the same schedule would build the scores as
  one-hot MXU matmuls against these tables — the bsp_ell one-hot regime —
  because Mosaic has no row gather, see ops/pallas_kernels.py. The XLA
  blocked form ships first: it compiles everywhere, pays no dt*f FLOPs
  per row for the scatter matmul, and fixes the same HBM envelope);
- the per-destination softmax is ONLINE (flash-attention style): a
  running (max m, normalizer l, weighted accumulator acc) per destination
  is carried across source tiles; each block rescales the carried state
  by exp(m_old - m_new) and folds its exp-scores in, so no [Ep]-shaped
  score/alpha tensor ever exists — the jaxpr of the fused forward has no
  Ep x f aval (pinned by tests/test_fused_edge.py);
- the backward is hand-paired (custom_vjp): the softmax Jacobian
  ``s * (g - sum_dst(s * g))`` is recomputed BLOCKWISE from the saved
  (m, l) statistics — three streamed passes (per-dst Jacobian sum T1 over
  the forward tables; dst-half score gradient over the forward tables;
  feature + src-half gradients over the TRANSPOSED tables, the CSR
  direction tiled by destination) — never an [Ep, f] intermediate.

Two score layouts serve both model families through ONE code path,
selected by the channel width C of the score halves:

- GAT  (C = 1): score(e) = leaky_relu(asrc[src] + adst[dst]), a scalar
  per edge; softmax per destination; out[d] = sum_e s_e * h[src].
- GGCN (C = f): per-CHANNEL scores/softmax (the gated-GCN chain), same
  expressions with elementwise [.., C] broadcasting.

``asrc``/``adst`` are the decomposed per-vertex score halves the models
already compute as MXU matmuls (a . [h_src||h_dst] = a_src.h_src +
a_dst.h_dst — the reference's own GAT_CPU_DIST_OPTM trick), so gradients
to the attention parameters flow through those matmuls from the
``grad_asrc``/``grad_adst`` this op returns.

Numeric policy matches ops/blocked_ell.py: f32 state (m, l, acc) and f32
products regardless of input dtype, one cast at the end. Empty
destinations (no real in-edges, incl. all-padding rows) produce EXACT
zeros — the ops/edge.edge_softmax convention, pinned by regression test.

Tables are BlockedEll pairs built with unit weights (the attention family
is weight_mode "ones"; the table weights serve as the validity mask) and
degree-binned levels by default (blocked_ell.resolve_levels). The
distributed ring form (parallel/dist_fused_edge.py) carries the SAME
(m, l, acc) state across ring hops — the aggregate_into-style f32 carry —
so the online softmax extends across partitions with no extra exchange.

Enable per-trainer with ``KERNEL:fused_edge`` (cfg); the eager edge chain
stays the parity oracle (tests/test_fused_edge.py sweeps forward and
backward, f32/bf16, GAT/GGCN, single-chip and ring sim).
"""

from __future__ import annotations

import dataclasses
import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from neutronstarlite_tpu.graph.storage import CSCGraph
from neutronstarlite_tpu.ops.blocked_ell import BlockedEll
from neutronstarlite_tpu.ops.ell import _chunk_budget_bytes
from neutronstarlite_tpu.utils.logging import get_logger

log = get_logger("fused_edge")

# masked-slot score sentinel (bf16-safe, same as ops/ell_gat.NEG_INF);
# exp(NEG_INF - finite) flushes to exactly 0 in f32, never NaN
NEG_INF = -1e30

DEFAULT_FUSED_VT = 4096  # source-tile rows (the resident-slab height)


def default_fused_vt(v_num: int, kernel_tile: int = 0) -> int:
    """KERNEL_TILE when set, else the default slab height capped by V —
    ONE definition shared by the trainers and the benches."""
    return int(kernel_tile) or min(int(v_num), DEFAULT_FUSED_VT)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class FusedEdgePair:
    """Forward (CSC, src-tiled) + transposed (CSR, dst-tiled) unit-weight
    blocked tables. ``fwd`` rows are per-(tile, dst) runs; ``bwd`` rows
    are per-(tile, src) runs — the backward's pass C streams the
    destination side as the resident slab (g, m, l, T1, adst live there)
    while feature/src-half gradients accumulate into the row space."""

    fwd: BlockedEll
    bwd: BlockedEll

    @staticmethod
    def from_host(
        g: CSCGraph, vt: int = 0, levels: str = ""
    ) -> "FusedEdgePair":
        vt = default_fused_vt(g.v_num, vt)
        levels = levels or os.environ.get("NTS_ELL_LEVELS", "") or "binned"
        ones = np.ones(g.e_num, np.float32)
        fwd = BlockedEll.build(
            g.v_num, g.column_offset, g.row_indices, ones, vt, levels=levels
        )
        bwd = BlockedEll.build(
            g.v_num, g.row_offset, g.column_indices, ones, vt, levels=levels
        )
        return FusedEdgePair(fwd=fwd, bwd=bwd)

    def slot_count(self) -> int:
        return sum(int(np.prod(n.shape)) for n in self.fwd.nbr) + sum(
            int(np.prod(n.shape)) for n in self.bwd.nbr
        )


# ---- streamed-pass scaffolding ---------------------------------------------


def _scan_tiles(fe: BlockedEll, per_tile, state, level_fn):
    """Stream the stacked level tables tile by tile, threading ``state``.

    ``per_tile``: tuple of [T, vt, .] arrays resident one tile at a time.
    ``level_fn(state, tile_slices, nbr, msk, dstr) -> state`` runs once
    per level. First tile peeled outside the scan (the blocked_ell
    varying-carry move, so the same body runs inside shard_map)."""
    tables = list(zip(fe.nbr, fe.wgt, fe.dst_row))
    if not tables:
        return state

    def body(state, xs):
        tile_slices, tabs = xs
        for nbr, msk, dstr in tabs:
            state = level_fn(state, tile_slices, nbr, msk, dstr)
        return state, None

    first = (
        tuple(a[0] for a in per_tile),
        [(n[0], w[0], d[0]) for n, w, d in tables],
    )
    state, _ = body(state, first)
    if fe.n_tiles > 1:
        rest = (
            tuple(a[1:] for a in per_tile),
            [(n[1:], w[1:], d[1:]) for n, w, d in tables],
        )
        state, _ = lax.scan(body, state, rest)
    return state


def _scan_row_chunks(state, nbr, msk, dstr, rows, fill, chunk_fn):
    """Byte-bound one level's rows (the [rows, K, max(f, C)] gather slab)
    with an inner scan; first chunk peeled (varying-carry)."""
    n_l, K = nbr.shape
    if n_l <= rows:
        return chunk_fn(state, nbr, msk, dstr)
    n_ch = -(-n_l // rows)
    pad = n_ch * rows - n_l
    nb = jnp.pad(nbr, ((0, pad), (0, 0))).reshape(n_ch, rows, K)
    mk = jnp.pad(msk, ((0, pad), (0, 0))).reshape(n_ch, rows, K)
    dr = jnp.pad(dstr, (0, pad), constant_values=fill).reshape(n_ch, rows)
    state = chunk_fn(state, nb[0], mk[0], dr[0])
    if n_ch > 1:

        def body(s, xs):
            return chunk_fn(s, *xs), None

        state, _ = lax.scan(body, state, (nb[1:], mk[1:], dr[1:]))
    return state


def _tile_stack(fe: BlockedEll, arr: jax.Array) -> jax.Array:
    """[S, .] -> [T, vt, .] resident-slab stacking (pad the tail tile)."""
    S = fe.src_num or fe.v_num
    pad = fe.n_tiles * fe.vt - S
    return jnp.pad(arr, ((0, pad), (0, 0))).reshape(
        fe.n_tiles, fe.vt, arr.shape[1]
    )


def _row_budget(K: int, f: int, C: int) -> int:
    return max(_chunk_budget_bytes() // (K * max(f, C) * 4), 1)


def _scatter_kw():
    return dict(indices_are_sorted=True, unique_indices=True, mode="drop")


# ---- forward: one streamed pass, online softmax ----------------------------


def fused_init_state(v_num: int, C: int, f: int):
    """(m, l, acc) — running per-destination max / normalizer / weighted
    accumulator. The distributed ring carries this tuple across hops."""
    return (
        jnp.full((v_num, C), NEG_INF, jnp.float32),
        jnp.zeros((v_num, C), jnp.float32),
        jnp.zeros((v_num, f), jnp.float32),
    )


def fused_forward_into(
    fe: BlockedEll, state, h, asrc, adst, slope: float
):
    """Fold one table set's contributions into the carried (m, l, acc).

    ``h`` [S, f] / ``asrc`` [S, C] live in the table's SOURCE space (one
    ring shard on the dist path); ``adst`` [V, C] in its destination
    space. Per block: scores from the resident slabs, block max, rescale
    the carried state by exp(m_old - m_new), fold exp-scores and weighted
    features in — the flash-attention update over graph runs."""
    V, f, C = fe.v_num, h.shape[1], asrc.shape[1]
    ht = _tile_stack(fe, h)
    at = _tile_stack(fe, asrc)
    ad = adst.astype(jnp.float32)

    def level_fn(state, tile, nbr, msk, dstr):
        x_tile, a_tile = tile
        rows = _row_budget(nbr.shape[1], f, C)

        def chunk_fn(state, nb, mk, dr):
            m, l, acc = state
            drc = jnp.minimum(dr, V - 1)  # clamp padding rows (dropped below)
            real = (mk != 0.0)[:, :, None]
            q = a_tile[nb].astype(jnp.float32) + ad[drc][:, None, :]
            z = jnp.where(
                real, jax.nn.leaky_relu(q, negative_slope=slope), NEG_INF
            )
            bm = z.max(axis=1)  # [n, C] block max per destination row
            m_old = m[drc]
            m_new = jnp.maximum(m_old, bm)
            p = jnp.where(real, jnp.exp(z - m_new[:, None, :]), 0.0)
            scale = jnp.exp(m_old - m_new)  # all-pad rows: exp(0) = 1
            xv = x_tile[nb].astype(jnp.float32)  # [n, K, f]
            row_acc = (xv * p).sum(axis=1)  # C==1 broadcasts over f
            l_new = l[drc] * scale + p.sum(axis=1)
            acc_new = acc[drc] * scale + row_acc
            kw = _scatter_kw()
            return (
                m.at[dr].set(m_new, **kw),
                l.at[dr].set(l_new, **kw),
                acc.at[dr].set(acc_new, **kw),
            )

        return _scan_row_chunks(state, nbr, msk, dstr, rows, V, chunk_fn)

    return _scan_tiles(fe, (ht, at), state, level_fn)


def fused_finalize(state, dtype):
    """acc / l with the empty-destination zero convention (the pinned
    ops/edge.edge_softmax behavior: no in-edges -> exact zeros)."""
    _, l, acc = state
    return jnp.where(l > 0, acc / jnp.where(l > 0, l, 1.0), 0.0).astype(dtype)


# ---- backward: three streamed passes ---------------------------------------


def _safe_l(l):
    return jnp.where(l > 0, l, 1.0)


def _recompute_s(a_tile, nb, ad_rows, m_rows, l_rows, real, slope):
    """Blockwise softmax recomputation from the saved (m, l) statistics:
    s = exp(leaky_relu(q) - m[dst]) / l[dst], zero on padding slots."""
    q = a_tile[nb].astype(jnp.float32) + ad_rows[:, None, :]
    z = jax.nn.leaky_relu(q, negative_slope=slope)
    s = jnp.where(
        real, jnp.exp(z - m_rows[:, None, :]) / l_rows[:, None, :], 0.0
    )
    return q, s


def _score_grad(s, gs, t1_b, q, real, slope):
    """Softmax Jacobian s*(gs - T1[dst]) through the leaky_relu: the
    reference backward s*(g - sum_dst(s*g)) recomputed blockwise.
    ``t1_b`` is already broadcastable to ``gs`` ([n, 1, C] from a row
    gather in pass B, [n, K, C] from a slot gather in pass C)."""
    dq = jnp.where(q >= 0, 1.0, slope)
    return jnp.where(real, s * (gs - t1_b) * dq, 0.0)


def fused_bwd_t1_into(fe: BlockedEll, t1, h, asrc, adst, m, l, g, slope):
    """Pass A (forward tables): T1[d] = sum over in-edges of s * gs where
    gs is the per-edge score cotangent <g[d], h[src]> (summed over f for
    C==1, per-channel for C==f) — the per-destination Jacobian sum the
    blockwise softmax backward needs complete before pass B/C."""
    V, f, C = fe.v_num, h.shape[1], asrc.shape[1]
    ht = _tile_stack(fe, h)
    at = _tile_stack(fe, asrc)
    ad, gf = adst.astype(jnp.float32), g.astype(jnp.float32)
    ls = _safe_l(l)

    def level_fn(t1, tile, nbr, msk, dstr):
        x_tile, a_tile = tile
        rows = _row_budget(nbr.shape[1], f, C)

        def chunk_fn(t1, nb, mk, dr):
            drc = jnp.minimum(dr, V - 1)
            real = (mk != 0.0)[:, :, None]
            _, s = _recompute_s(
                a_tile, nb, ad[drc], m[drc], ls[drc], real, slope
            )
            xv = x_tile[nb].astype(jnp.float32)
            gs = gf[drc][:, None, :] * xv
            if C == 1:
                gs = gs.sum(axis=2, keepdims=True)
            return t1.at[dr].add((s * gs).sum(axis=1), **_scatter_kw())

        return _scan_row_chunks(t1, nbr, msk, dstr, rows, V, chunk_fn)

    return _scan_tiles(fe, (ht, at), t1, level_fn)


def fused_bwd_gadst_into(
    fe: BlockedEll, gad, h, asrc, adst, m, l, t1, g, slope
):
    """Pass B (forward tables, T1 complete): per-destination score-half
    gradient grad_adst[d] = sum over in-edges of gz (rows are unique
    destinations per tile, so the scatter stays sorted+unique)."""
    V, f, C = fe.v_num, h.shape[1], asrc.shape[1]
    ht = _tile_stack(fe, h)
    at = _tile_stack(fe, asrc)
    ad, gf = adst.astype(jnp.float32), g.astype(jnp.float32)
    ls = _safe_l(l)

    def level_fn(gad, tile, nbr, msk, dstr):
        x_tile, a_tile = tile
        rows = _row_budget(nbr.shape[1], f, C)

        def chunk_fn(gad, nb, mk, dr):
            drc = jnp.minimum(dr, V - 1)
            real = (mk != 0.0)[:, :, None]
            q, s = _recompute_s(
                a_tile, nb, ad[drc], m[drc], ls[drc], real, slope
            )
            xv = x_tile[nb].astype(jnp.float32)
            gs = gf[drc][:, None, :] * xv
            if C == 1:
                gs = gs.sum(axis=2, keepdims=True)
            gz = _score_grad(s, gs, t1[drc][:, None, :], q, real, slope)
            return gad.at[dr].add(gz.sum(axis=1), **_scatter_kw())

        return _scan_row_chunks(gad, nbr, msk, dstr, rows, V, chunk_fn)

    return _scan_tiles(fe, (ht, at), gad, level_fn)


def fused_bwd_src_into(
    feT: BlockedEll, state, h, asrc, adst, m, l, t1, g, slope
):
    """Pass C (TRANSPOSED tables, tiled by destination): stream the
    destination side as the resident slab (adst, m, l, T1, g) and
    accumulate the source-space gradients — grad_h[src] += s * g[dst]
    (the value path) and grad_asrc[src] += gz (the score path). Rows are
    unique SOURCES per tile, so both scatters stay sorted+unique. On the
    dist path the resident slab is the reverse-ring payload and
    (grad_h, grad_asrc) stay device-local."""
    S = feT.v_num  # the transposed table's row space = source vertices
    f, C = h.shape[1], asrc.shape[1]
    adt = _tile_stack(feT, adst.astype(jnp.float32))
    mt = _tile_stack(feT, m)
    lt = _tile_stack(feT, _safe_l(l))
    t1t = _tile_stack(feT, t1)
    gt = _tile_stack(feT, g.astype(jnp.float32))
    hf, af = h.astype(jnp.float32), asrc.astype(jnp.float32)

    def level_fn(state, tile, nbr, msk, dstr):
        ad_t, m_t, l_t, t1_t, g_t = tile
        rows = _row_budget(nbr.shape[1], f, C)

        def chunk_fn(state, nb, mk, dr):
            gh, gas = state
            drc = jnp.minimum(dr, S - 1)  # rows are SOURCE vertices here
            real = (mk != 0.0)[:, :, None]
            q = af[drc][:, None, :] + ad_t[nb].astype(jnp.float32)
            z = jax.nn.leaky_relu(q, negative_slope=slope)
            s = jnp.where(real, jnp.exp(z - m_t[nb]) / l_t[nb], 0.0)
            gv = g_t[nb]  # [n, K, f] resident-gathered cotangent rows
            gh_row = (s * gv).sum(axis=1)  # value-path feature gradient
            gs = gv * hf[drc][:, None, :]
            if C == 1:
                gs = gs.sum(axis=2, keepdims=True)
            gz = _score_grad(s, gs, t1_t[nb], q, real, slope)
            kw = _scatter_kw()
            return (
                gh.at[dr].add(gh_row, **kw),
                gas.at[dr].add(gz.sum(axis=1), **kw),
            )

        return _scan_row_chunks(state, nbr, msk, dstr, rows, S, chunk_fn)

    return _scan_tiles(feT, (adt, mt, lt, t1t, gt), state, level_fn)


# ---- the custom_vjp-paired single-chip op ----------------------------------


def _fused_forward(fe: BlockedEll, h, asrc, adst, slope):
    state = fused_init_state(fe.v_num, asrc.shape[1], h.shape[1])
    m, l, acc = fused_forward_into(fe, state, h, asrc, adst, slope)
    return fused_finalize((m, l, acc), h.dtype), (m, l)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _fused_apply(slope, pair, h, asrc, adst):
    out, _ = _fused_forward(pair.fwd, h, asrc, adst, slope)
    return out


def _fused_apply_fwd(slope, pair, h, asrc, adst):
    out, (m, l) = _fused_forward(pair.fwd, h, asrc, adst, slope)
    return out, (pair, h, asrc, adst, m, l)


def _fused_apply_bwd(slope, res, g):
    from neutronstarlite_tpu.ops.segment import zero_cotangent

    pair, h, asrc, adst, m, l = res
    f, C = h.shape[1], asrc.shape[1]
    V = pair.fwd.v_num  # destination space
    S = pair.bwd.v_num  # source space (== V on the square single-chip form)
    t1 = fused_bwd_t1_into(
        pair.fwd, jnp.zeros((V, C), jnp.float32), h, asrc, adst, m, l, g,
        slope,
    )
    gad = fused_bwd_gadst_into(
        pair.fwd, jnp.zeros((V, C), jnp.float32), h, asrc, adst, m, l, t1,
        g, slope,
    )
    gh, gas = fused_bwd_src_into(
        pair.bwd,
        (jnp.zeros((S, f), jnp.float32), jnp.zeros((S, C), jnp.float32)),
        h, asrc, adst, m, l, t1, g, slope,
    )
    return (
        jax.tree.map(zero_cotangent, pair),
        gh.astype(h.dtype),
        gas.astype(asrc.dtype),
        gad.astype(adst.dtype),
    )


_fused_apply.defvjp(_fused_apply_fwd, _fused_apply_bwd)


def fused_edge_attention_aggregate(
    pair: FusedEdgePair,
    h: jax.Array,
    asrc: jax.Array,
    adst: jax.Array,
    slope: float,
) -> jax.Array:
    """The whole score -> per-dst softmax -> weighted-aggregate chain,
    [V, f] -> [V, f], no [Ep, .] tensors. ``asrc``/``adst`` [V, C] are the
    decomposed score halves (C=1: GAT scalar attention; C=f: GGCN
    per-channel gates); gradients flow to all three inputs."""
    return _fused_apply(float(slope), pair, h, asrc, adst)
