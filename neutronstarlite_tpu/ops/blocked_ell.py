"""Blocked (source-tiled) ELL aggregation: the beyond-VMEM hot path.

The plain ELL layout (ops/ell.py) wins on TPU because XLA serves its random
row gathers from on-chip memory — measured at multi-TB/s when the gathered
table fits VMEM (docs/PERF.md section 1). Past that size every gathered row
is an HBM transaction and the op costs O(E * f) HBM bytes per application
(e.g. Reddit's standard-order first layer: [233k, 602] bf16 = 280 MB table,
~69 GB of gather traffic per epoch).

This module tiles the SOURCE dimension instead: vertices are cut into T
contiguous tiles of ``vt`` rows; each tile owns the sub-adjacency of edges
whose source lies in the tile, with tile-LOCAL source ids, so every gather
indexes only a [vt, f] slice sized to the on-chip budget. HBM traffic
becomes O(E * 8 B) table reads + O(rows * f) partial-sum scatter instead of
O(E * f) scattered row reads, and the access pattern is streaming. This is
the TPU analog of the reference's shared-memory tiling in its optimized
CUDA aggregation kernel (cuda/ntsCUDAFuseKernel.cuh:154-208, block-local
accumulation) — re-derived for a memory system where the win comes from
keeping the GATHER SOURCE on-chip rather than the accumulator.

Layout (round-2 redesign): the first version gave each tile its own
EllBuckets with tile-specific level structure, unrolled in Python — at
Reddit scale the resulting program had hundreds of heterogeneous fusion
regions and took 44 MINUTES to compile (docs/PERF.md section 3c). The
production layout is UNIFORM across tiles: global power-of-two degree
levels, each level one stacked [T, N_l, K_l] table padded to the max
per-tile row count, and aggregation is ONE ``lax.scan`` over tiles — the
compiled program is a single tile body, independent of T. Two structural
bonuses fall out:

- no supernode bucket: a destination's per-tile in-degree is bounded by
  ``vt``, so K_l <= next_pow2(vt) — the power-law hub that forces the
  plain layout's K ~ 2^21 level (and its K-chunked scan) cannot occur;
- rows exist only where a (tile, dst) pair has edges: the per-tile scatter
  touches len(rows) destinations, not V, and padding rows carry
  ``dst = v_num`` and are dropped by the scatter (mode="drop").

Forward/backward pairing follows ops/ell.py exactly: the backward is the
same blocked op over the transposed (CSR) adjacency, tiled by the original
destination side, wrapped in one ``custom_vjp``. Numeric policy matches
ops.ell.ell_tables_aggregate: f32 products, f32 accumulation (both the
per-row K-reduction and the cross-tile scatter accumulator), one cast at
the end. Byte budget: the [rows, K, f] gather intermediate is bounded by
the same NTS_ELL_CHUNK_MIB budget, chunking level rows with an inner scan.

Distributed use (round 3): the layout is rectangular — ``src_num`` may
exceed ``v_num`` — so a device can aggregate its vp destination rows
from the [P*vp] all_gathered source space (parallel/dist_blocked.py
stacks per-device tables; KERNEL_TILE:vt on the dist trainers). Both
scans peel their first iteration so the accumulator carry is varying
under shard_map (the ops/aggregate._scatter_accumulate move).

Enable per-trainer with ``OPTIM_KERNEL:1`` + ``KERNEL_TILE:<vt>`` (cfg), or
pass a ``BlockedEllPair`` anywhere a graph/EllPair is accepted by
ops.aggregate.gather_dst_from_src.
"""

from __future__ import annotations

import dataclasses
import os
from typing import List

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from neutronstarlite_tpu.graph.storage import CSCGraph
from neutronstarlite_tpu.ops.ell import DEFAULT_SLOT_CHUNK, _chunk_budget_bytes
from neutronstarlite_tpu.utils.logging import get_logger

log = get_logger("blocked_ell")

_MIN_K = 4


def resolve_levels(levels: str = "") -> str:
    """Level-construction mode for the stacked tables: ``pow2`` (the
    original ladder — K = next power of two of each (tile, dst) run) or
    ``binned`` (Accel-GCN-style degree binning: K values are the observed
    run-length quantiles rounded up to ``_MIN_K`` multiples, so a skewed
    graph's rows don't pad to the pow2 ceiling — a 130-edge run lands in a
    132-slot row, not 256). ``""`` resolves NTS_ELL_LEVELS then ``pow2``
    (the fused edge path defaults to ``binned`` at its call site)."""
    lv = levels or os.environ.get("NTS_ELL_LEVELS", "") or "pow2"
    if lv not in ("pow2", "binned"):
        raise ValueError(
            f"ELL level mode must be pow2 or binned, got {lv!r} "
            "(NTS_ELL_LEVELS or the build's levels= argument)"
        )
    return lv


def _binned_row_k(
    row_len: np.ndarray, row_tile: np.ndarray, n_tiles: int
) -> np.ndarray:
    """Per-row level capacity, degree-binned (Accel-GCN's bucketing idea
    re-derived for the stacked-tile layout). Start from the pow2 ladder's
    degree bins, then fit each bin's capacity to the DATA:

    - a bin's K shrinks from the pow2 ceiling to its observed max run
      rounded up to a ``_MIN_K`` multiple (a skewed graph whose hub bin
      holds runs of <= 130 pads rows to 132 slots, not 256);
    - a bin splits at its row-count median when the split saves >= 25%
      of the bin's slots PRICED ON THE STACKED ALLOCATION — a level
      costs n_tiles * max-rows-in-any-one-tile * K, so a candidate split
      whose halves concentrate in different tiles (each new level paying
      its own per-tile max) prices high and is rejected.

    Every row's capacity is <= its pow2 ceiling, shrinking and merging
    only reduce a level's stacked cost, and splits fire only when the
    stacked cost drops — so the total padded slots are never worse than
    pow2 BY CONSTRUCTION (regression-tested, including the adversarial
    tile-skew case), while the level count grows at most 2x."""
    lens = np.maximum(row_len.astype(np.int64), 1)
    tiles = row_tile.astype(np.int64)
    pow2 = np.maximum(
        2 ** np.ceil(np.log2(lens)).astype(np.int64), _MIN_K
    )
    up = lambda v: max(int(-(-int(v) // _MIN_K) * _MIN_K), _MIN_K)

    def tile_rows(mask):
        """max rows any one tile contributes — the n_l a level of these
        rows allocates (times n_tiles * K, constant across candidates)."""
        return (
            int(np.bincount(tiles[mask], minlength=n_tiles).max())
            if mask.any()
            else 0
        )

    out = np.empty_like(lens)
    for K in np.unique(pow2):
        sel = pow2 == K
        lb = lens[sel]
        mx = up(lb.max())
        med = up(np.median(lb))
        if med < mx:
            low = sel & (lens <= med)
            cost_split = tile_rows(low) * med + tile_rows(sel & ~low) * mx
            if cost_split <= 0.75 * tile_rows(sel) * mx:
                out[sel] = np.where(lb <= med, med, mx)
                continue
        out[sel] = mx
    return out


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BlockedEll:
    """One direction's source-tiled stacked tables.

    Per level l: ``nbr[l]`` [T, N_l, K_l] tile-local neighbor ids,
    ``wgt[l]`` [T, N_l, K_l] weights (0 on padding slots), ``dst_row[l]``
    [T, N_l] global destination of each row (``v_num`` on padding rows —
    dropped by the scatter). Rows are sorted by destination within each
    (tile, level) and unique there (a dst's whole in-tile run lives in
    exactly one level), so the scatter carries sorted+unique flags.
    """

    nbr: List[jax.Array]
    wgt: List[jax.Array]
    dst_row: List[jax.Array]
    vt: int = dataclasses.field(metadata=dict(static=True))
    v_num: int = dataclasses.field(metadata=dict(static=True))
    n_tiles: int = dataclasses.field(metadata=dict(static=True))
    # source-space row count when it differs from the destination space —
    # the distributed path aggregates a device's vp destination rows from
    # the [P*vp] all_gathered source space (parallel/dist_blocked.py)
    src_num: int = dataclasses.field(default=0, metadata=dict(static=True))

    @staticmethod
    def build(
        v_num: int,
        offsets: np.ndarray,  # [V+1] per-dst adjacency offsets
        adj: np.ndarray,  # [E] source ids, grouped by dst
        weights: np.ndarray,  # [E]
        vt: int,
        slot_chunk: int = DEFAULT_SLOT_CHUNK,  # kept for API compat; byte
        # budget (NTS_ELL_CHUNK_MIB) governs chunking at trace time
        src_num: int | None = None,  # source rows (default: square, = v_num)
        log_stats: bool = True,  # the ring builder runs P*P tiny builds and
        # logs ONE consolidated line itself (parallel/dist_ring_blocked.py)
        levels: str = "",  # "" -> NTS_ELL_LEVELS / pow2; "binned" = degree-
        # binned K values from the run-length distribution (resolve_levels)
    ) -> "BlockedEll":
        from neutronstarlite_tpu import native as native_rt

        levels = resolve_levels(levels)
        src_num = v_num if src_num is None else int(src_num)
        n_tiles = -(-src_num // vt)
        # int32 fast path: with T*V < 2^31 the (tile, dst) key fits int32,
        # halving the memory traffic of every pass AND letting numpy's
        # stable sort use its integer radix path — measured ~2x on the
        # full-scale 114.6M-edge build (1-core rig)
        idx_t = (
            np.int32
            if max(n_tiles * v_num, src_num) < 2**31
            else np.int64
        )
        deg = np.diff(offsets).astype(np.int64)
        dst_of_edge = np.repeat(np.arange(v_num, dtype=idx_t), deg)
        adj = np.asarray(adj, dtype=idx_t)
        weights = np.asarray(weights)
        if len(adj) == 0:
            return BlockedEll(
                nbr=[], wgt=[], dst_row=[],
                vt=int(vt), v_num=int(v_num), n_tiles=int(n_tiles),
                src_num=src_num,
            )

        # sort edges by (source tile, dst): edges arrive row-grouped
        # (offsets order), so ONE stable pass by tile yields (tile, row)
        # order — O(E) native counting sort, or numpy's stable sort over
        # the combined key as the fallback
        tile_of_edge = adj // np.asarray(vt, idx_t)
        use_native = native_rt.available()
        if use_native:
            order = native_rt.sort_by_tile(
                tile_of_edge.astype(np.int32, copy=False), n_tiles
            )
        else:
            key = tile_of_edge * np.asarray(v_num, idx_t) + dst_of_edge
            order = np.argsort(key, kind="stable")
        tile_sorted = tile_of_edge[order]
        dst_sorted = dst_of_edge[order]
        # sorted: extract (tile, dst) runs with one linear pass
        change = (tile_sorted[1:] != tile_sorted[:-1]) | (
            dst_sorted[1:] != dst_sorted[:-1]
        )
        bounds = np.nonzero(np.concatenate([[True], change]))[0]
        row_start = bounds
        row_len = np.diff(np.concatenate([bounds, [len(order)]]))
        row_tile = tile_sorted[bounds].astype(np.int64)
        row_dst = dst_sorted[bounds].astype(np.int64)

        # uniform global levels. pow2: K in {4, 8, ..., next_pow2(max run)}
        # (bounded by next_pow2(vt) since an in-tile run can't exceed vt);
        # binned: K at run-length quantiles (_binned_row_k) — same stacked
        # layout and invariants, only the per-level capacities differ
        if levels == "binned":
            row_k = _binned_row_k(row_len, row_tile, n_tiles)
        else:
            row_k = np.maximum(
                2 ** np.ceil(np.log2(np.maximum(row_len, 1))).astype(np.int64),
                _MIN_K,
            )
        src_local = (adj - tile_of_edge * np.asarray(vt, idx_t))[order]
        w_sorted = weights[order]
        if use_native:
            src_local = src_local.astype(np.int32, copy=False)
            w_sorted = np.ascontiguousarray(w_sorted, np.float32)

        nbrs, wgts, dsts = [], [], []
        pad_slots = real_slots = 0
        # one stacked level per DISTINCT capacity (pow2 visits the same set
        # its ladder would; binned visits the quantile capacities)
        for K in sorted(int(k) for k in np.unique(row_k)):
            sel = np.nonzero(row_k == K)[0]
            if len(sel):
                t_sel = row_tile[sel]
                counts = np.bincount(t_sel, minlength=n_tiles)
                n_l = int(counts.max())
                nbr = np.zeros((n_tiles, n_l, K), dtype=np.int32)
                wgt = np.zeros((n_tiles, n_l, K), dtype=np.float32)
                dstr = np.full((n_tiles, n_l), v_num, dtype=np.int32)
                # slot of each row inside its tile = rank among the tile's
                # rows (sel is sorted by (tile, dst), so ranks preserve the
                # per-tile dst order -> sorted scatter indices)
                starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
                slot = np.arange(len(sel)) - starts[t_sel]
                d = row_len[sel]
                if use_native:
                    native_rt.fill_blocked_level(
                        row_start[sel], d, t_sel.astype(np.int32),
                        row_dst[sel].astype(np.int32), slot, n_l, K,
                        src_local, w_sorted, nbr, wgt, dstr,
                    )
                else:
                    lo = row_start[sel]
                    k = np.arange(K)
                    valid = k[None, :] < d[:, None]
                    flat_idx = (lo[:, None] + k[None, :])[valid]
                    ti = np.broadcast_to(t_sel[:, None], (len(sel), K))[valid]
                    si = np.broadcast_to(slot[:, None], (len(sel), K))[valid]
                    ki = np.broadcast_to(k, (len(sel), K))[valid]
                    nbr[ti, si, ki] = src_local[flat_idx]
                    wgt[ti, si, ki] = w_sorted[flat_idx]
                    dstr[t_sel, slot] = row_dst[sel]
                nbrs.append(nbr)
                wgts.append(wgt)
                dsts.append(dstr)
                pad_slots += n_tiles * n_l * K - int(d.sum())
                real_slots += int(d.sum())
        if real_slots and log_stats:
            log.info(
                "blocked ELL: %d tiles of %d, %d levels, padding waste %.2fx "
                "(%d real / %d padded slots)",
                n_tiles, vt, len(nbrs), (real_slots + pad_slots) / real_slots,
                real_slots, pad_slots,
            )
        return BlockedEll(
            nbr=[jnp.asarray(n) for n in nbrs],
            wgt=[jnp.asarray(w) for w in wgts],
            dst_row=[jnp.asarray(d) for d in dsts],
            vt=int(vt),
            v_num=int(v_num),
            n_tiles=int(n_tiles),
            src_num=src_num,
        )

    def aggregate(self, x: jax.Array) -> jax.Array:
        """out[v] = sum over in-edges of w * x[src]; [S, f] -> [V, f]
        (S = src_num; square S == V on the single-chip path).

        One lax.scan over tiles; the carry is the [V, f] f32 accumulator
        (a vertex whose in-neighbors span many tiles must not round T
        times in a narrow dtype). Per level the [rows, K, f] gather
        intermediate is byte-bounded by chunking rows with an inner scan.

        shard_map compatibility (the round-2 "varying-carry peel" note):
        both scans peel their FIRST iteration outside the loop — under
        shard_map a zeros-initialized carry is unvarying over the mesh
        axis while the body's output (which mixes in sharded tables) is
        varying, and lax.scan requires carry-in == carry-out varying
        types. One data-dependent update before each scan makes the carry
        varying without naming the mesh axis here (the same move as
        ops/aggregate._scatter_accumulate, so this op runs identically
        inside and outside shard_map)."""
        acc = jnp.zeros((self.v_num, x.shape[1]), jnp.float32)
        return self.aggregate_into(acc, x).astype(x.dtype)

    def aggregate_into(self, acc: jax.Array, x: jax.Array) -> jax.Array:
        """``aggregate`` over an EXISTING [V, f] f32 accumulator, returned
        un-cast — the ring-pipelined distributed path
        (parallel/dist_ring_blocked.py) adds one source partition's
        contribution per ring step into the same f32 carry, so the
        cross-step sum never rounds in a narrow dtype."""
        f = x.shape[1]
        src_num = self.src_num or self.v_num
        v_pad = self.n_tiles * self.vt - src_num
        xt = jnp.pad(x, ((0, v_pad), (0, 0))).reshape(self.n_tiles, self.vt, f)
        budget = _chunk_budget_bytes()

        def level_add(acc, x_tile, nbr, wgt, dstr):
            n_l, K = nbr.shape
            rows = max(budget // (K * f * 4), 1)

            def chunk_add(a, chunk):
                nb, wg, dr = chunk
                vals = x_tile[nb].astype(jnp.float32) * wg[:, :, None]
                return a.at[dr].add(
                    vals.sum(axis=1),
                    indices_are_sorted=True,
                    unique_indices=True,
                    mode="drop",  # padding rows carry dst = v_num
                ), None

            if n_l <= rows:
                acc, _ = chunk_add(acc, (nbr, wgt, dstr))
                return acc
            n_ch = -(-n_l // rows)
            pad = n_ch * rows - n_l
            nb = jnp.pad(nbr, ((0, pad), (0, 0))).reshape(n_ch, rows, K)
            wg = jnp.pad(wgt, ((0, pad), (0, 0))).reshape(n_ch, rows, K)
            dr = jnp.pad(
                dstr, (0, pad), constant_values=self.v_num
            ).reshape(n_ch, rows)
            # first chunk outside the scan (varying-carry peel, see above)
            acc, _ = chunk_add(acc, (nb[0], wg[0], dr[0]))
            if n_ch > 1:
                acc, _ = lax.scan(chunk_add, acc, (nb[1:], wg[1:], dr[1:]))
            return acc

        def body(acc, xs):
            x_tile, tables = xs
            for nbr, wgt, dstr in tables:
                acc = level_add(acc, x_tile, nbr, wgt, dstr)
            return acc, None

        tables = list(zip(self.nbr, self.wgt, self.dst_row))
        if not tables:
            return acc
        # first tile outside the scan (varying-carry peel, see above)
        acc, _ = body(acc, (xt[0], [(n[0], w[0], d[0]) for n, w, d in tables]))
        if self.n_tiles > 1:
            rest = [(n[1:], w[1:], d[1:]) for n, w, d in tables]
            acc, _ = lax.scan(body, acc, (xt[1:], rest))
        return acc


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BlockedEllPair:
    """Forward (CSC, tiled by src) + backward (CSR, tiled by dst) tables."""

    fwd: BlockedEll
    bwd: BlockedEll

    @staticmethod
    def from_host(
        g: CSCGraph, vt: int, slot_chunk: int = DEFAULT_SLOT_CHUNK
    ) -> "BlockedEllPair":
        fwd = BlockedEll.build(
            g.v_num, g.column_offset, g.row_indices, g.edge_weight_forward,
            vt, slot_chunk,
        )
        bwd = BlockedEll.build(
            g.v_num, g.row_offset, g.column_indices, g.edge_weight_backward,
            vt, slot_chunk,
        )
        return BlockedEllPair(fwd=fwd, bwd=bwd)


@jax.custom_vjp
def _blocked_aggregate(fwd: BlockedEll, bwd: BlockedEll, x: jax.Array):
    return fwd.aggregate(x)


def _blocked_aggregate_fwd(fwd, bwd, x):
    return fwd.aggregate(x), (fwd, bwd)


def _blocked_aggregate_bwd(res, g):
    from neutronstarlite_tpu.ops.segment import zero_cotangent

    fwd, bwd = res
    zero = jax.tree.map(zero_cotangent, (fwd, bwd))
    return (*zero, bwd.aggregate(g))


_blocked_aggregate.defvjp(_blocked_aggregate_fwd, _blocked_aggregate_bwd)


def blocked_gather_dst_from_src(pair: BlockedEllPair, x: jax.Array) -> jax.Array:
    """Source-tiled weighted aggregation (custom_vjp pairs the transpose)."""
    return _blocked_aggregate(pair.fwd, pair.bwd, x)


def blocked_gather_src_from_dst(pair: BlockedEllPair, y: jax.Array) -> jax.Array:
    """The CSR direction as a forward op."""
    return _blocked_aggregate(pair.bwd, pair.fwd, y)
