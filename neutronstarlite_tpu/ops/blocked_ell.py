"""Blocked (source-tiled) ELL aggregation: the beyond-VMEM hot path.

The plain ELL layout (ops/ell.py) wins on TPU because XLA serves its random
row gathers from on-chip memory — measured at multi-TB/s when the gathered
table fits VMEM (docs/PERF.md section 1). Past that size every gathered row
is an HBM transaction and the op costs O(E * f) HBM bytes per application
(e.g. Reddit's standard-order first layer: [233k, 602] bf16 = 280 MB table,
~69 GB of gather traffic per epoch).

This module tiles the SOURCE dimension instead: vertices are cut into T
contiguous tiles of ``vt`` rows; each tile owns the sub-adjacency of edges
whose source lies in the tile, stored as ELL bucket tables with tile-LOCAL
source ids. Aggregation sums per-tile aggregates:

    out = sum_t  ell_aggregate(tables_t, x[t*vt : t*vt + vt])

Every gather in the per-tile term indexes only the [vt, f] slice — sized to
the on-chip budget — so the random access stays in the fast regime at ANY
graph size. HBM traffic becomes O(E * 8 B) table reads + O(T * V * f)
partial-sum accumulation instead of O(E * f) scattered row reads: at Reddit
scale with f = 602 that is ~8x less traffic, and the access pattern is
streaming, not random. This is the TPU analog of the reference's
shared-memory tiling in its optimized CUDA aggregation kernel
(cuda/ntsCUDAFuseKernel.cuh:154-208, block-local accumulation) — re-derived
for a memory system where the win comes from keeping the GATHER SOURCE
on-chip rather than the accumulator.

Forward/backward pairing follows ops/ell.py exactly: the backward is the
same blocked op over the transposed (CSR) adjacency, tiled by the original
destination side, wrapped in one ``custom_vjp``. Numeric policy is shared
via ops.ell.ell_tables_aggregate (f32 products + accumulation).

Enable per-trainer with ``OPTIM_KERNEL:1`` + ``KERNEL_TILE:<vt>`` (cfg), or
pass a ``BlockedEllPair`` anywhere a graph/EllPair is accepted by
ops.aggregate.gather_dst_from_src.
"""

from __future__ import annotations

import dataclasses
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from neutronstarlite_tpu.graph.storage import CSCGraph
from neutronstarlite_tpu.ops.ell import (
    DEFAULT_SLOT_CHUNK,
    EllBuckets,
    ell_tables_aggregate,
)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BlockedEll:
    """One direction's source-tiled tables. ``tiles[t]`` holds EllBuckets
    whose neighbor ids are LOCAL to source tile t (rows are global dst)."""

    tiles: List[EllBuckets]
    vt: int = dataclasses.field(metadata=dict(static=True))
    v_num: int = dataclasses.field(metadata=dict(static=True))

    @staticmethod
    def build(
        v_num: int,
        offsets: np.ndarray,  # [V+1] per-dst adjacency offsets
        adj: np.ndarray,  # [E] source ids, grouped by dst
        weights: np.ndarray,  # [E]
        vt: int,
        slot_chunk: int = DEFAULT_SLOT_CHUNK,
    ) -> "BlockedEll":
        deg = np.diff(offsets)
        dst_of_edge = np.repeat(np.arange(v_num, dtype=np.int64), deg)
        adj = np.asarray(adj, dtype=np.int64)
        weights = np.asarray(weights)
        n_tiles = -(-v_num // vt)
        # one stable pass: order edges by source tile, keeping dst grouping
        tile_of_edge = adj // vt
        order = np.argsort(tile_of_edge, kind="stable")
        counts = np.bincount(tile_of_edge, minlength=n_tiles)
        starts = np.concatenate([[0], np.cumsum(counts)])
        tiles = []
        for t in range(n_tiles):
            lo, hi = starts[t], starts[t + 1]
            sel = order[lo:hi]
            sub_dst = dst_of_edge[sel]
            sub_src = adj[sel] - t * vt
            sub_w = weights[sel]
            sub_deg = np.bincount(sub_dst, minlength=v_num)
            sub_off = np.concatenate([[0], np.cumsum(sub_deg)])
            # regroup by dst (stable, so source order inside a dst persists)
            by_dst = np.argsort(sub_dst, kind="stable")
            tiles.append(
                EllBuckets.build(
                    v_num,
                    sub_off,
                    sub_src[by_dst].astype(np.int32),
                    sub_w[by_dst],
                    slot_chunk,
                )
            )
        return BlockedEll(tiles=tiles, vt=int(vt), v_num=int(v_num))

    def aggregate(self, x: jax.Array) -> jax.Array:
        """out[v] = sum over in-edges of w * x[src]; [V, f] -> [V, f].

        Per-tile partials AND the cross-tile sum stay f32 (a vertex whose
        in-neighbors span many tiles must not round T times in bf16); one
        cast back to x.dtype at the end."""
        out = None
        for t, b in enumerate(self.tiles):
            x_tile = x[t * self.vt : (t + 1) * self.vt]
            part = ell_tables_aggregate(
                x_tile, b.nbr, b.wgt, b.slot_chunk, out_dtype=jnp.float32
            )[b.inv_perm]
            out = part if out is None else out + part
        return out.astype(x.dtype)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BlockedEllPair:
    """Forward (CSC, tiled by src) + backward (CSR, tiled by dst) tables."""

    fwd: BlockedEll
    bwd: BlockedEll

    @staticmethod
    def from_host(
        g: CSCGraph, vt: int, slot_chunk: int = DEFAULT_SLOT_CHUNK
    ) -> "BlockedEllPair":
        fwd = BlockedEll.build(
            g.v_num, g.column_offset, g.row_indices, g.edge_weight_forward,
            vt, slot_chunk,
        )
        bwd = BlockedEll.build(
            g.v_num, g.row_offset, g.column_indices, g.edge_weight_backward,
            vt, slot_chunk,
        )
        return BlockedEllPair(fwd=fwd, bwd=bwd)


@jax.custom_vjp
def _blocked_aggregate(fwd: BlockedEll, bwd: BlockedEll, x: jax.Array):
    return fwd.aggregate(x)


def _blocked_aggregate_fwd(fwd, bwd, x):
    return fwd.aggregate(x), (fwd, bwd)


def _blocked_aggregate_bwd(res, g):
    from neutronstarlite_tpu.ops.segment import zero_cotangent

    fwd, bwd = res
    zero = jax.tree.map(zero_cotangent, (fwd, bwd))
    return (*zero, bwd.aggregate(g))


_blocked_aggregate.defvjp(_blocked_aggregate_fwd, _blocked_aggregate_bwd)


def blocked_gather_dst_from_src(pair: BlockedEllPair, x: jax.Array) -> jax.Array:
    """Source-tiled weighted aggregation (custom_vjp pairs the transpose)."""
    return _blocked_aggregate(pair.fwd, pair.bwd, x)


def blocked_gather_src_from_dst(pair: BlockedEllPair, y: jax.Array) -> jax.Array:
    """The CSR direction as a forward op."""
    return _blocked_aggregate(pair.bwd, pair.fwd, y)
