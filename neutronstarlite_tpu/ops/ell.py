"""ELL-bucketed neighbor aggregation: the gather-only TPU hot path.

The reference's optimized CUDA aggregation
(``aggregate_kernel_from_src_with_weight_optim_nts``,
cuda/ntsCUDAFuseKernel.cuh:154-208, enabled by the ``OPTIM_KERNEL`` cfg flag)
packs multiple destination vertices per thread block and accumulates in
shared memory — its win is turning scattered global-memory accumulation into
block-local accumulation. The TPU analog must go further: TPU has no fast
scatter at all (XLA lowers scatter-add to a serialized update stream), while
*gather* is vectorized and fast. So the production layout removes the
scatter entirely:

- Vertices are grouped into power-of-two in-degree buckets (K = 4, 8, 16, …,
  next_pow2(max_degree)); each bucket stores a padded dense neighbor table
  ``nbr [Nk, K]`` + ``wgt [Nk, K]`` (ELLPACK slices, degree-sorted so padding
  waste is < 2x).
- Aggregation for a bucket is ``out[r] = sum_k wgt[r,k] * x[nbr[r,k]]`` —
  one gather plus a dense masked reduction, both native TPU operations; row
  chunks bound the [rows, K, f] gather intermediate in VMEM-friendly sizes.
- Results are assembled with one inverse-permutation gather (vertices were
  regrouped by bucket).

The backward needs grad_x[u] = sum over out-edges (u -> v) of w * g[v]: the
same operation over the transposed adjacency, so ``EllPair`` precomputes
forward (in-edge) and backward (out-edge) bucket tables and pairs them in a
``custom_vjp`` — exactly the reference's CSC-forward/CSR-backward kernel
pairing (GatherByDstFromSrc / GatherBySrcFromDst, NtsScheduler.hpp:151/:257).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from neutronstarlite_tpu.graph.storage import CSCGraph

# legacy upper cap on slots (rows * K) per scan step. Chunk sizing is now
# governed by the BYTE budget below (min(slot_chunk, slot_budget) — at the
# default 32 MiB budget and f >= 8 the byte bound is always the tighter
# one); the slot cap survives only as a table-layout knob for tests that
# force specific chunk counts.
DEFAULT_SLOT_CHUNK = 1 << 21
_MIN_K = 4


def _next_pow2(x: int) -> int:
    return 1 << max(int(x) - 1, 0).bit_length()


# Byte budget for one [rows, K, f] gather intermediate. At the default
# 2^21-SLOT chunk the full-scale intermediates (512 MiB at f=128) were
# materialized to HBM by the compiler; bounding them to ~32 MiB keeps them
# VMEM-resident in the compiled v5e module (docs/PERF.md section 3a).
# Width-aware (slots alone don't bound bytes when f varies 41..602).
# Override for on-chip tuning: NTS_ELL_CHUNK_MIB.
DEFAULT_CHUNK_MIB = 32


def _chunk_budget_bytes() -> int:
    """Read NTS_ELL_CHUNK_MIB (clamped to >= 1 MiB; non-numeric falls back
    to the default). TRACE-TIME semantics: the value is baked into the
    traced program, so changing the env after a jit cache is warm has no
    effect — set it before the first compile."""
    import os

    raw = os.environ.get("NTS_ELL_CHUNK_MIB", "")
    try:
        mib = int(raw) if raw else DEFAULT_CHUNK_MIB
    except ValueError:
        mib = DEFAULT_CHUNK_MIB
    return max(mib, 1) << 20


def ell_tables_aggregate(x, nbrs, wgts, slot_chunk: int, out_dtype=None) -> jax.Array:
    """Shared per-level ELL reduction: concat over levels of
    ``sum_k wgt[r, k] * x[nbr[r, k]]`` (callers apply their own inv_perm).
    Single source of the numeric policy for EllBuckets.aggregate AND the
    distributed DistEll._local_aggregate — the K-reduction accumulates in
    f32 regardless of x.dtype (the fused multiply-reduce holds its
    accumulator in registers, so wide accumulation costs no HBM traffic):
    bf16 reads keep the bandwidth win while degree-500 sums keep ~f32
    accuracy, the same policy as the reference's CUDA kernel whose
    shared-memory accumulator is float (cuda/ntsCUDAFuseKernel.cuh:147-208).

    The [rows, K, f] gather intermediate is bounded in BYTES (width-aware,
    see DEFAULT_CHUNK_MIB) by chunking rows — and, for the few-row hub
    levels whose K alone exceeds the budget (a 2^21-degree supernode at
    f=602 is a 2.4 GiB slab), by scanning K column chunks with an f32
    running sum. Chunk boundaries never split a row's K-reduction across
    different precisions, so results are invariant to the chunking.

    ``out_dtype``: result dtype (default x.dtype). Callers that keep
    accumulating across calls (the blocked source-tiled layout) pass
    float32 so the cross-call sum stays wide too. A level with K == 0
    (the zero-degree bucket) yields zero rows without any gather."""
    f = x.shape[1]
    out_dtype = out_dtype or x.dtype
    budget = _chunk_budget_bytes()
    # the chunk intermediate lives in f32 whatever x.dtype is (the upcast
    # below) — size the slot budget for the f32 slab, not the input bytes
    slot_budget = max(budget // (f * max(x.dtype.itemsize, 4)), 1)

    def partial_f32(nbr, wgt):
        # products AND accumulation in f32 (register-resident in the fused
        # reduce, so no extra HBM traffic; bf16 only on the gather reads) —
        # the ONE copy of the numeric policy; keep in sync with
        # ops/pallas_kernels._ell_level_kernel, which mirrors it in-kernel
        vals = x[nbr].astype(jnp.float32) * wgt[:, :, None]
        return vals.sum(axis=1)

    def row_sum(nbr, wgt):
        return partial_f32(nbr, wgt).astype(out_dtype)

    def k_chunked_sum(nbr, wgt):
        # K exceeds the per-chunk slot budget (hub levels); scan K column
        # chunks with an f32 running sum (padding columns carry weight 0)
        Nk, K = nbr.shape
        kc = max(slot_budget // max(Nk, 1), 1)
        n_ch = -(-K // kc)
        pad = n_ch * kc - K
        nb = jnp.pad(nbr, ((0, 0), (0, pad))).reshape(Nk, n_ch, kc)
        wg = jnp.pad(wgt, ((0, 0), (0, pad))).reshape(Nk, n_ch, kc)
        nb_t = nb.transpose(1, 0, 2)
        wg_t = wg.transpose(1, 0, 2)

        # first chunk outside the scan: a zeros-initialized carry is
        # unvarying over the mesh axis under shard_map while the body's
        # output is varying, and lax.scan requires carry-in == carry-out
        # varying types (the round-1 ring bug class; same peel as
        # ops/aggregate._scatter_accumulate)
        acc = partial_f32(nb_t[0], wg_t[0])
        if n_ch > 1:

            def body(acc, chunk):
                n, w = chunk
                return acc + partial_f32(n, w), None

            acc, _ = lax.scan(body, acc, (nb_t[1:], wg_t[1:]))
        return acc.astype(out_dtype)

    outs = []
    for nbr, wgt in zip(nbrs, wgts):
        Nk, K = nbr.shape
        if K == 0:
            outs.append(jnp.zeros((Nk, f), out_dtype))
            continue
        if K > slot_budget:
            # rows-of-1 chunks would still breach the byte bound; chunk K
            outs.append(k_chunked_sum(nbr, wgt))
            continue
        rows = max(min(slot_chunk, slot_budget) // K, 1)
        if Nk <= rows:
            outs.append(row_sum(nbr, wgt))
            continue
        n_ch = -(-Nk // rows)
        pad = n_ch * rows - Nk
        nb = jnp.pad(nbr, ((0, pad), (0, 0))).reshape(n_ch, rows, K)
        wg = jnp.pad(wgt, ((0, pad), (0, 0))).reshape(n_ch, rows, K)

        def body(_, chunk):
            n, w = chunk
            return 0, row_sum(n, w)

        _, out = lax.scan(body, 0, (nb, wg))
        outs.append(out.reshape(n_ch * rows, f)[:Nk])
    return jnp.concatenate(outs, axis=0)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class EllBuckets:
    """One direction's degree-bucketed neighbor tables.

    ``nbr[i]`` [Nk, K_i] neighbor ids, ``wgt[i]`` [Nk, K_i] weights (0 on
    padding, padding neighbors point at vertex 0), ``inv_perm`` [V] maps
    global vertex id -> row in the bucket-ordered concatenation.
    """

    nbr: List[jax.Array]
    wgt: List[jax.Array]
    inv_perm: jax.Array
    v_num: int = dataclasses.field(metadata=dict(static=True))
    slot_chunk: int = dataclasses.field(metadata=dict(static=True))

    @staticmethod
    def build(
        v_num: int,
        offsets: np.ndarray,  # [V+1] per-vertex adjacency offsets
        adj: np.ndarray,  # [E] neighbor ids, grouped by vertex
        weights: np.ndarray,  # [E]
        slot_chunk: int = DEFAULT_SLOT_CHUNK,
    ) -> "EllBuckets":
        deg = np.diff(offsets).astype(np.int64)
        order = np.argsort(deg, kind="stable")
        sdeg = deg[order]
        nbrs, wgts, perm_parts = [], [], []
        i = 0
        # zero-degree rows get a K=0 bucket: no slots, no gather work —
        # essential for the blocked source-tiled layout where most rows
        # have no edge in a given tile (power-law sparsity)
        j0 = int(np.searchsorted(sdeg, 0, side="right"))
        if j0 > 0:
            ids = order[:j0]
            nbrs.append(np.zeros((j0, 0), dtype=np.int32))
            wgts.append(np.zeros((j0, 0), dtype=np.float32))
            perm_parts.append(ids)
            i = j0
        from neutronstarlite_tpu import native as native_rt

        use_native = native_rt.available()
        if use_native:
            adj32 = np.ascontiguousarray(adj, np.int32)
            w32 = np.ascontiguousarray(weights, np.float32)
        while i < v_num:
            K = max(_next_pow2(max(int(sdeg[i]), 1)), _MIN_K)
            j = int(np.searchsorted(sdeg, K, side="right"))
            j = max(j, i + 1)
            ids = order[i:j]
            Nk = len(ids)
            nbr = np.zeros((Nk, K), dtype=np.int32)
            wgt = np.zeros((Nk, K), dtype=np.float32)
            lo = offsets[ids]
            d = deg[ids]
            if use_native:
                # C fill of the ragged runs (nts_fill_blocked_level with a
                # single "tile"; the dst/slot channel is the row index) —
                # the same routine the blocked layout uses
                dstr = np.empty((1, Nk), np.int32)
                native_rt.fill_blocked_level(
                    lo, d, np.zeros(Nk, np.int32), ids.astype(np.int32),
                    np.arange(Nk, dtype=np.int64), Nk, K, adj32, w32,
                    nbr.reshape(1, Nk, K), wgt.reshape(1, Nk, K), dstr,
                )
            else:
                # vectorized fill: [Nk, K] table rows from ragged runs
                k = np.arange(K)
                valid = k[None, :] < d[:, None]
                flat_idx = (lo[:, None] + k[None, :])[valid]
                nbr[valid] = adj[flat_idx]
                wgt[valid] = weights[flat_idx]
            nbrs.append(nbr)
            wgts.append(wgt)
            perm_parts.append(ids)
            i = j
        perm = np.concatenate(perm_parts) if perm_parts else np.zeros(0, np.int64)
        inv = np.empty(v_num, dtype=np.int64)
        inv[perm] = np.arange(v_num)
        return EllBuckets(
            nbr=[jnp.asarray(n) for n in nbrs],
            wgt=[jnp.asarray(w) for w in wgts],
            inv_perm=jnp.asarray(inv, dtype=jnp.int32),
            v_num=v_num,
            slot_chunk=int(slot_chunk),
        )

    def aggregate(self, x: jax.Array) -> jax.Array:
        """out[v] = sum over v's table row of w * x[nbr]; [V, f] -> [V, f]."""
        return ell_tables_aggregate(x, self.nbr, self.wgt, self.slot_chunk)[
            self.inv_perm
        ]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class EllPair:
    """Forward (in-edge/CSC) + backward (out-edge/CSR) bucket tables."""

    fwd: EllBuckets
    bwd: EllBuckets

    @staticmethod
    def from_host(g: CSCGraph, slot_chunk: int = DEFAULT_SLOT_CHUNK) -> "EllPair":
        fwd = EllBuckets.build(
            g.v_num,
            g.column_offset,
            g.row_indices,
            g.edge_weight_forward,
            slot_chunk,
        )
        bwd = EllBuckets.build(
            g.v_num,
            g.row_offset,
            g.column_indices,
            g.edge_weight_backward,
            slot_chunk,
        )
        return EllPair(fwd=fwd, bwd=bwd)


@jax.custom_vjp
def _ell_aggregate(fwd: EllBuckets, bwd: EllBuckets, x: jax.Array) -> jax.Array:
    return fwd.aggregate(x)


def _ell_aggregate_fwd(fwd, bwd, x):
    return fwd.aggregate(x), (fwd, bwd)


def _ell_aggregate_bwd(res, g):
    from neutronstarlite_tpu.ops.segment import zero_cotangent

    fwd, bwd = res
    zero = jax.tree.map(zero_cotangent, (fwd, bwd))
    return (*zero, bwd.aggregate(g))


_ell_aggregate.defvjp(_ell_aggregate_fwd, _ell_aggregate_bwd)


def ell_gather_dst_from_src(pair: EllPair, x: jax.Array) -> jax.Array:
    """Gather-only weighted aggregation (custom_vjp pairs the transpose)."""
    return _ell_aggregate(pair.fwd, pair.bwd, x)


def ell_gather_src_from_dst(pair: EllPair, y: jax.Array) -> jax.Array:
    """The CSR direction as a forward op."""
    return _ell_aggregate(pair.bwd, pair.fwd, y)
