"""Pallas TPU kernels for the fused neighbor aggregation.

The reference's CUDA analog is aggregate_kernel_from_src_with_weight[_optim]
(cuda/ntsCUDAFuseKernel.cuh:147-293): one fused kernel doing gather ->
scale-by-edge-weight -> per-dst accumulate, shared-memory tiled. The TPU
counterpart here operates on the ELL layout (ops/ell.py) — the gather-only
formulation measured 2.5x faster than scatter on real v5e (docs/PERF.md
section 2) — and fuses gather + scale + K-reduction in VMEM:

- ``ell_aggregate_pallas``: grid over row tiles of one [Nk, K] bucket
  level; each step holds an [R, K] neighbor/weight tile and the full
  [V, f] feature table in VMEM, gathers rows with a vectorized VMEM
  gather (one ``x[idx]`` per K column — K is static per level), and
  writes the f32-accumulated row sums. No HBM round-trips for
  intermediates; no serial per-edge loop (the round-1 prototype's flaw).
- ``gather_dst_from_src_pallas``: applies the kernel per bucket level and
  assembles with the inverse permutation — a drop-in twin of
  ``ops.ell.ell_gather_dst_from_src``'s forward.

**STATUS (round 3, discovered via topology AOT compiles 2026-07-31):
interpret-mode / design-study only — this kernel cannot lower to Mosaic.**
The TPU's only vectorized gather (``tpu.dynamic_gather``, exposed through
``jnp.take_along_axis``) is an ELEMENTWISE shuffle whose input, index and
output shapes must all match (jax/_src/pallas/mosaic/lowering.py's
lax.gather rule); a row gather ``x[idx]`` from a resident [V, f] table —
the core of this kernel — has out rows != V and is rejected for every
(rows, K, V, f) shape tested. There is no VMEM-resident random-row-gather
primitive to build on, so the whole "table resident, gather on-chip"
regime is unimplementable in compiled Pallas on this stack; the full-scale
bench legs that tried compiled ~50-kernel epochs of this design never
returned (the remote compile service hangs rather than surfacing the
ValueError). The PRODUCTION fused aggregation is ops/bsp_ell.py — the
(dst-tile, src-tile) streamed block-sparse kernel whose per-block combine
is a one-hot MXU matmul, i.e. the one fused design that needs NO gather
at all; ``PALLAS:1`` routes there (models/fullbatch.py). This module
remains as the interpret-mode twin (semantics tests, CPU CI) and the
written record of the regime analysis: feature-column chunking, level
merging and the VMEM budget math below are correct FOR THE DESIGN and
would apply directly should Mosaic grow a row-gather primitive.
"""

from __future__ import annotations

import dataclasses
import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from neutronstarlite_tpu.ops.ell import (
    EllBuckets,
    EllPair,
    _next_pow2,
    ell_tables_aggregate,
)

try:  # pallas TPU backend may be absent on pure-CPU builds
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    _HAS_PLTPU = False

DEFAULT_ROW_TILE = 512
_K_CHUNK = 8  # static inner unroll; K beyond this iterates a fori_loop
# bucket levels wider than this stay on the XLA path (row-vectorized kernel
# degrades to a serial K loop on few-row hub levels; Reddit-scale power-law
# graphs carry a K ~ 2^21 supernode bucket)
MAX_PALLAS_K = 1024
# the kernel holds the gathered [V, fc] table in VMEM; wider inputs are
# feature-column-chunked to fit (v5e VMEM = 128 MB, minus tile double
# buffers); only when the ROW count alone exceeds the budget does the call
# degrade to the XLA ELL path instead of failing Mosaic's VMEM allocation
MAX_TABLE_BYTES = 96 << 20
# levels with K below this are merged into one K=PALLAS_MIN_K level at
# PallasEllPair build time (round-3 hang postmortem): every (rows, K, f)
# triple is a distinct Mosaic compile, and at full Reddit scale ~22 bucket
# levels x f-chunks x fwd/bwd directions stacked ~50 kernel compiles into
# ONE jitted epoch program — aggregate compile time through the remote
# compile service blew the 1200 s measurement window with nothing
# persisted (the executable cache is whole-program). Low-K levels hold few
# SLOTS on power-law graphs (rows with deg <= 64 contribute << E slots),
# so padding them up is a few percent of slot traffic in exchange for
# ~halving the distinct-kernel count. 0 disables. Numerically exact:
# padding slots carry weight 0 into an f32 accumulation.
PALLAS_MIN_K = int(os.environ.get("NTS_PALLAS_MIN_K", "64"))


def _ell_level_kernel(nbr_ref, wgt_ref, x_ref, o_ref, *, k_cols: int):
    """One row tile of one bucket level: o[r] = sum_k w[r,k] * x[nbr[r,k]].

    nbr/wgt [R, K] and x [V, f] live in VMEM; the gather is a vectorized
    VMEM row gather per K column. K columns are walked by a fori_loop over
    _K_CHUNK-wide slices (static inner unroll) so high-degree bucket levels
    (K = next_pow2(max_degree), tens of thousands on power-law graphs) do
    not unroll into K separate ops. Products and accumulation are f32 in
    registers — the identical numeric policy to
    ops.ell.ell_tables_aggregate's row_sum."""
    x = x_ref[:]
    rows = nbr_ref.shape[0]
    f = x.shape[1]
    kc = min(_K_CHUNK, k_cols)
    n_blocks = k_cols // kc  # call site pads K to a _K_CHUNK multiple

    def block(b, acc):
        nb = nbr_ref[:, pl.ds(b * kc, kc)]
        wb = wgt_ref[:, pl.ds(b * kc, kc)]
        for j in range(kc):
            acc = acc + x[nb[:, j]].astype(jnp.float32) * wb[:, j][:, None]
        return acc

    acc = jax.lax.fori_loop(
        0, n_blocks, block, jnp.zeros((rows, f), jnp.float32)
    )
    o_ref[:] = acc.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("row_tile", "interpret"))
def ell_aggregate_pallas(
    nbr: jax.Array,  # [Nk, K] int32 neighbor ids (0 + weight 0 on padding)
    wgt: jax.Array,  # [Nk, K] f32 weights
    x: jax.Array,  # [V, f]
    row_tile: int = DEFAULT_ROW_TILE,
    interpret: bool = False,
) -> jax.Array:
    """[Nk, K] ELL tables + [V, f] features -> [Nk, f] row sums."""
    n_rows, k_cols = nbr.shape
    v_num, f = x.shape
    rt = min(row_tile, n_rows)
    pad = (-n_rows) % rt
    kpad = (-k_cols) % min(_K_CHUNK, k_cols) if k_cols else 0
    if pad or kpad:
        # padding slots carry weight 0 and index row 0: contribute nothing
        nbr = jnp.pad(nbr, ((0, pad), (0, kpad)))
        wgt = jnp.pad(wgt, ((0, pad), (0, kpad)))
    k_cols += kpad
    grid = ((n_rows + pad) // rt,)

    out = pl.pallas_call(
        functools.partial(_ell_level_kernel, k_cols=k_cols),
        out_shape=jax.ShapeDtypeStruct((n_rows + pad, f), x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((rt, k_cols), lambda i: (i, 0)),
            pl.BlockSpec((rt, k_cols), lambda i: (i, 0)),
            pl.BlockSpec((v_num, f), lambda i: (0, 0)),  # x resident
        ],
        out_specs=pl.BlockSpec((rt, f), lambda i: (i, 0)),
        interpret=interpret,
    )(nbr, wgt, x)
    return out[:n_rows]


def merge_level_tables(nbrs, wgts, min_k: int, row_axis: int = 0):
    """Merge every level with 0 < K <= min_k into ONE level padded to
    K=min_k: pad the (last) K axis, concatenate rows along ``row_axis``.
    Consecutive levels concatenate in their original order, so the
    concatenated output rows — and therefore any inv row map over them —
    are untouched; padding slots carry neighbor 0 with weight 0 and
    contribute nothing (the module-constant rationale explains why fewer
    levels matter: one Mosaic compile per (rows, K, f) triple). The K=0
    zero-degree level stays separate: merging it would buy slots for rows
    with no edges at all. Serves both the 2D EllBuckets tables
    (row_axis=0) and the stacked [P, Nk, K] dist tables (row_axis=1)."""
    if min_k <= 0:
        return list(nbrs), list(wgts)
    merged_nbr, merged_wgt = [], []
    group_n, group_w = [], []
    for nbr, wgt in zip(nbrs, wgts):
        k = nbr.shape[-1]
        if 0 < k <= min_k:
            pad = [(0, 0)] * nbr.ndim
            pad[-1] = (0, min_k - k)
            group_n.append(jnp.pad(nbr, pad))
            group_w.append(jnp.pad(wgt, pad))
            continue
        # levels arrive in increasing K, so the low-K group is a prefix
        # (after the optional K=0 level) — flush before any wider level
        if group_n:
            merged_nbr.append(jnp.concatenate(group_n, axis=row_axis))
            merged_wgt.append(jnp.concatenate(group_w, axis=row_axis))
            group_n, group_w = [], []
        merged_nbr.append(nbr)
        merged_wgt.append(wgt)
    if group_n:
        merged_nbr.append(jnp.concatenate(group_n, axis=row_axis))
        merged_wgt.append(jnp.concatenate(group_w, axis=row_axis))
    return merged_nbr, merged_wgt


def effective_min_k(total_slots: int, n_rows: int, min_k: int) -> int:
    """Cap the merge threshold at the graph's own degree scale: merging to
    K=64 on a mean-degree-5 graph (Cora) pads ~15x, while on mean-degree-
    492 Reddit the same merge costs a few percent. next-pow2 of the mean
    slot count per row keeps the compile-count win where slots are dense
    and bounds the padding where they are not (mean is computed over the
    already-padded tables, so it upper-bounds the real mean degree)."""
    if min_k <= 0 or n_rows <= 0:
        return min_k
    return min(min_k, _next_pow2(max(total_slots // n_rows, 1)))


def merge_low_k_levels(buckets: EllBuckets, min_k: int) -> EllBuckets:
    """EllBuckets wrapper of ``merge_level_tables`` (row_axis=0). ``min_k``
    is applied literally — degree-adaptive capping is the POLICY sites'
    job (PallasEllPair.from_pair, parallel/dist_ell.DistEllPair.build via
    ``effective_min_k``), not this mechanism's."""
    if min_k <= 0:
        return buckets
    merged_nbr, merged_wgt = merge_level_tables(
        buckets.nbr, buckets.wgt, min_k, row_axis=0
    )
    return EllBuckets(
        nbr=merged_nbr, wgt=merged_wgt, inv_perm=buckets.inv_perm,
        v_num=buckets.v_num, slot_chunk=buckets.slot_chunk,
    )


def gather_dst_from_src_pallas(
    ell_pair_or_buckets,
    x: jax.Array,
    row_tile: int = DEFAULT_ROW_TILE,
    interpret: bool = False,
) -> jax.Array:
    """Fused CSC aggregation out[v] = sum_{(u->v)} w_uv * x[u] over the ELL
    bucket layout (ops.ell.EllPair or EllBuckets). Forward only — for
    training use ``pallas_gather_dst_from_src`` (PallasEllPair), whose
    custom_vjp pairs this kernel with its transpose tables."""
    buckets: EllBuckets = (
        ell_pair_or_buckets.fwd
        if isinstance(ell_pair_or_buckets, EllPair)
        else ell_pair_or_buckets
    )
    return pallas_tables_aggregate(
        x, buckets.nbr, buckets.wgt, buckets.slot_chunk,
        row_tile=row_tile, interpret=interpret,
    )[buckets.inv_perm]


def pallas_tables_aggregate(
    x: jax.Array,
    nbrs,
    wgts,
    slot_chunk: int,
    row_tile: int = DEFAULT_ROW_TILE,
    interpret: bool = False,
) -> jax.Array:
    """Level-table twin of ``ops.ell.ell_tables_aggregate`` running the
    fused kernel per level (callers apply their own inv_perm) — the shared
    executor for the single-chip path above and the distributed per-shard
    path (parallel/dist_ell.py with kernel='pallas')."""
    v_num, f = x.shape
    if v_num * f * x.dtype.itemsize > MAX_TABLE_BYTES:
        # wider than the VMEM budget: chunk the FEATURE dim so each chunk's
        # [V, fc] table is resident — the tables are re-read per chunk but
        # every gather stays on-chip (module docstring, round-3 change)
        fc = (MAX_TABLE_BYTES // (v_num * x.dtype.itemsize)) // 128 * 128
        if fc == 0:
            # the ROW count alone exceeds the budget (V > ~375k rows in
            # bf16): single-chip beyond-VMEM graphs route to the XLA path
            # here; ops/bsp_ell.py is the Pallas kernel for that regime
            return ell_tables_aggregate(x, nbrs, wgts, slot_chunk)
        # pad f up to a chunk multiple first so EVERY chunk call shares one
        # [V, fc] shape — a ragged tail chunk (602 = 4*128 + 90) would be
        # its own Mosaic compile for every level (round-3 hang postmortem)
        fpad = (-f) % fc
        if fpad:
            x = jnp.pad(x, ((0, 0), (0, fpad)))
        return jnp.concatenate(
            [
                pallas_tables_aggregate(
                    x[:, lo: lo + fc], nbrs, wgts, slot_chunk,
                    row_tile=row_tile, interpret=interpret,
                )
                for lo in range(0, f + fpad, fc)
            ],
            axis=1,
        )[:, :f]
    outs = []
    for nbr, wgt in zip(nbrs, wgts):
        if nbr.shape[1] == 0:
            # zero-degree bucket: zero rows, no kernel launch
            outs.append(jnp.zeros((nbr.shape[0], x.shape[1]), x.dtype))
        elif nbr.shape[1] > MAX_PALLAS_K:
            # hub tail: the kernel vectorizes over rows and loops K, so a
            # [few rows, K ~ 2^21] level (a power-law supernode bucket)
            # would serialize; its XLA gather+reduce vectorizes over K
            outs.append(ell_tables_aggregate(x, [nbr], [wgt], slot_chunk))
        else:
            outs.append(
                ell_aggregate_pallas(
                    nbr, wgt, x, row_tile=row_tile, interpret=interpret
                )
            )
    return jnp.concatenate(outs, axis=0)


# ---- trainable Pallas backend (KERNEL selection: PALLAS:1) -----------------


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PallasEllPair:
    """EllPair twin whose aggregation runs the fused Pallas kernel.

    Same numeric policy and custom_vjp transpose pairing as ops.ell.EllPair;
    the tables differ in one build-time transform: levels with K <=
    PALLAS_MIN_K are merged into a single K=PALLAS_MIN_K level (fewer
    Mosaic compiles — see merge_low_k_levels; numerically exact). The
    per-level executor is the VMEM-resident vectorized gather kernel
    instead of XLA gather+reduce; hub levels wider than MAX_PALLAS_K still
    route to XLA, see gather_dst_from_src_pallas.
    Regime: the gathered [V, fc] table must fit the VMEM budget per
    feature-column chunk — any width works (wide layers are column-chunked,
    re-reading the tables per chunk), so both the EAGER order
    (GCN_CPU_EAGER.hpp:200-206 analog) and the full-scale STANDARD order
    (602-wide layer 1) run fused. The row count is the remaining bound
    (V <= ~375k rows bf16); past it the XLA path serves, or ops/bsp_ell.py.
    Off-TPU (tests, CPU CI) the kernel runs in interpret mode.
    """

    fwd: EllBuckets
    bwd: EllBuckets
    row_tile: int = dataclasses.field(
        default=DEFAULT_ROW_TILE, metadata=dict(static=True)
    )

    @staticmethod
    def from_host(g, row_tile: int = DEFAULT_ROW_TILE) -> "PallasEllPair":
        return PallasEllPair.from_pair(EllPair.from_host(g), row_tile)

    @staticmethod
    def from_pair(pair: EllPair, row_tile: int = DEFAULT_ROW_TILE) -> "PallasEllPair":
        def adaptive(buckets: EllBuckets) -> EllBuckets:
            slots = sum(int(n.shape[0] * n.shape[1]) for n in buckets.nbr)
            rows = sum(int(n.shape[0]) for n in buckets.nbr)
            return merge_low_k_levels(
                buckets, effective_min_k(slots, rows, PALLAS_MIN_K)
            )

        return PallasEllPair(
            fwd=adaptive(pair.fwd),
            bwd=adaptive(pair.bwd),
            row_tile=int(row_tile),
        )


def pallas_interpret_default() -> bool:
    """interpret everywhere the default backend can't lower Mosaic — keeps
    the CPU suite exercising the same code path the chip runs.
    NTS_PALLAS_FORCE_COMPILED=1 overrides for AOT lowering against a TPU
    TOPOLOGY from a CPU host (tools/aot_bench_path): tracing never executes
    the kernel, and the topology compiler consumes the Mosaic call."""
    if os.environ.get("NTS_PALLAS_FORCE_COMPILED", "0") == "1":
        return False
    return jax.default_backend() not in ("tpu",)


def _apply_buckets(buckets: EllBuckets, x: jax.Array, row_tile: int) -> jax.Array:
    return gather_dst_from_src_pallas(
        buckets, x, row_tile=row_tile, interpret=pallas_interpret_default()
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _pallas_pair_aggregate(row_tile, fwd, bwd, x):
    return _apply_buckets(fwd, x, row_tile)


def _pallas_pair_aggregate_fwd(row_tile, fwd, bwd, x):
    return _apply_buckets(fwd, x, row_tile), (fwd, bwd)


def _pallas_pair_aggregate_bwd(row_tile, res, g):
    from neutronstarlite_tpu.ops.segment import zero_cotangent

    fwd, bwd = res
    zero = jax.tree.map(zero_cotangent, (fwd, bwd))
    return (*zero, _apply_buckets(bwd, g, row_tile))


_pallas_pair_aggregate.defvjp(_pallas_pair_aggregate_fwd, _pallas_pair_aggregate_bwd)


def pallas_gather_dst_from_src(pair: PallasEllPair, x: jax.Array) -> jax.Array:
    """Fused-kernel weighted aggregation (custom_vjp pairs the transpose)."""
    return _pallas_pair_aggregate(pair.row_tile, pair.fwd, pair.bwd, x)


def pallas_gather_src_from_dst(pair: PallasEllPair, y: jax.Array) -> jax.Array:
    """The CSR direction as a forward op."""
    return _pallas_pair_aggregate(pair.row_tile, pair.bwd, pair.fwd, y)
