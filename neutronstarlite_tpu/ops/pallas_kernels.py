"""Pallas TPU kernels — experimental fused aggregation prototype.

The reference's CUDA analog is aggregate_kernel_from_src_with_weight[_optim]
(cuda/ntsCUDAFuseKernel.cuh:147-293): one fused kernel doing gather ->
scale-by-edge-weight -> per-dst accumulate over CSC chunks, shared-memory
tiled. This module provides the Pallas counterpart.

Performance notes (why this is a prototype, and what the production path is):

- The op is HBM-bandwidth-bound random access: out[dst] += w * x[src] over
  dst-sorted edges. XLA:TPU lowers ``.at[].add`` with ``indices_are_sorted``
  to its native sorted-scatter, and the gather x[src] to the hardware gather
  path; the chunked lax.scan in ops/aggregate.py already avoids any [E, f]
  HBM intermediate. A Pallas kernel must beat that by pipelining per-edge row
  DMAs against the accumulate — a serial-DMA schedule whose win must be
  measured on hardware, not assumed.
- This prototype therefore targets the VMEM-resident regime (x and the
  output tile fit on chip, V*f <= ~2M elements): the whole fused
  gather+scale+accumulate happens in one kernel with zero HBM round-trips
  for intermediates. The large-graph regime stays on the XLA path
  (ops/aggregate.py) until kernel profiling on real chips justifies a
  scalar-prefetch + double-buffered-DMA variant.
- Grid: one program per edge chunk; the output accumulates across grid steps
  (out block index_map is constant, so the block stays resident in VMEM).

Enable with gather_dst_from_src_pallas(...); tests run it in interpret mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend may be absent on pure-CPU builds
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    _HAS_PLTPU = False


def _agg_kernel(src_ref, dst_ref, w_ref, x_ref, out_ref, *, edge_chunk: int):
    """One grid step: accumulate this edge chunk into the full [V, f] output.

    x_ref/out_ref hold the full arrays in VMEM; src/dst/w hold this chunk.
    """
    c = pl.program_id(0)

    @pl.when(c == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    def body(e, _):
        s = src_ref[e]
        d = dst_ref[e]
        w = w_ref[e]
        out_ref[d, :] += w * x_ref[s, :]
        return _

    jax.lax.fori_loop(0, edge_chunk, body, 0)


@functools.partial(jax.jit, static_argnames=("v_num", "edge_chunk", "interpret"))
def gather_dst_from_src_pallas(
    csc_src: jax.Array,
    csc_dst: jax.Array,
    csc_weight: jax.Array,
    x: jax.Array,
    v_num: int,
    edge_chunk: int = 1024,
    interpret: bool = False,
) -> jax.Array:
    """Fused CSC aggregation: out[v] = sum_{(u->v)} w_uv * x[u].

    VMEM-resident prototype; see module docstring. Padding edges must carry
    weight 0 (they hit row 0 harmlessly).
    """
    e_pad = csc_src.shape[0]
    assert e_pad % edge_chunk == 0, "edge arrays must be chunk-padded"
    n_chunks = e_pad // edge_chunk
    f = x.shape[1]

    grid = (n_chunks,)
    in_specs = [
        pl.BlockSpec((edge_chunk,), lambda c: (c,)),
        pl.BlockSpec((edge_chunk,), lambda c: (c,)),
        pl.BlockSpec((edge_chunk,), lambda c: (c,)),
        pl.BlockSpec((v_num, f), lambda c: (0, 0)),  # full x resident
    ]
    out_specs = pl.BlockSpec((v_num, f), lambda c: (0, 0))  # accumulated

    return pl.pallas_call(
        functools.partial(_agg_kernel, edge_chunk=edge_chunk),
        out_shape=jax.ShapeDtypeStruct((v_num, f), x.dtype),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        interpret=interpret,
    )(csc_src, csc_dst, csc_weight, x)
