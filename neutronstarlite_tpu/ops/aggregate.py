"""Neighbor aggregation operators with hand-paired backward passes.

These are the TPU counterparts of the reference's fused aggregation kernels:

- ``gather_dst_from_src``: forward CSC aggregation out[dst] += w * x[src]
  (reference: GatherByDstFromSrc -> aggregate_kernel_from_src_with_weight,
  cuda/ntsCUDAFuseKernel.cuh:147; CPU nts_comp loop,
  core/ntsCPUFusedGraphOp.hpp:88-105). Its custom_vjp backward runs the CSR
  (src-sorted) aggregation of the output gradient — exactly the pairing the
  reference hand-writes (GatherBySrcFromDst, ntsCUDAFuseKernel.cuh:327;
  process_edges_backward engines).
- ``gather_src_from_dst``: the CSR direction exposed as a forward op.
- ``aggregate_dst_min`` / ``aggregate_dst_max``: elementwise min/max with
  arg-extreme routing in the backward, mirroring SingleCPUDstAggregateOpMin/Max
  (core/ntsSingleCPUGraphOp.hpp:206/:274) whose ``record`` array routes the
  gradient to the winning edge.

Implementation notes (TPU-first): the hot op never materializes the [E, f]
gathered-feature intermediate for large graphs — it scans fixed-size edge
chunks, each chunk doing gather -> scale -> scatter-add into the [V, f]
accumulator. Edge arrays are pre-sorted (CSC by dst, CSR by src) so the
scatter-add carries ``indices_are_sorted``; padding edges have weight 0 and
point at vertex 0, contributing nothing. This replaces the reference's
work-stealing/omp-chunk machinery (graph.hpp:2005-2041) with static chunking
decided at preprocessing time.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from neutronstarlite_tpu.ops.device_graph import DeviceGraph
from neutronstarlite_tpu.ops.segment import zero_cotangent


def _scatter_accumulate(
    src, dst, weight, x, v_num: int, edge_chunk: int, acc_dtype, acc=None
):
    """sum over edges of weight_e * x[src_e] into [v_num, f], chunked.

    ``src``/``dst``/``weight`` are [Ep] with Ep a multiple of edge_chunk and
    indices sorted by ``dst``. An existing accumulator may be passed (the
    distributed ring adds one partial per ring step into the same output).
    """
    e_pad = src.shape[0]
    f = x.shape[1]
    n_chunks = e_pad // edge_chunk
    if acc is None:
        acc = jnp.zeros((v_num, f), dtype=acc_dtype)

    def chunk_add(carry, s, d, w):
        vals = x[s] * w[:, None].astype(x.dtype)
        return carry.at[d].add(
            vals.astype(acc_dtype), indices_are_sorted=True, unique_indices=False
        )

    if n_chunks <= 1:
        return chunk_add(acc, src, dst, weight)

    # The first chunk is applied outside the scan: under shard_map the
    # zeros-initialized accumulator is unvarying over the mesh axis while the
    # scan body's output (which mixes in the sharded edge data) is varying,
    # and lax.scan requires carry-in == carry-out varying types. One
    # data-dependent update makes the carry varying without naming the mesh
    # axis here (this op runs both inside and outside shard_map).
    acc = chunk_add(acc, src[:edge_chunk], dst[:edge_chunk], weight[:edge_chunk])

    def body(carry, chunk):
        s, d, w = chunk
        return chunk_add(carry, s, d, w), None

    chunks = (
        src[edge_chunk:].reshape(n_chunks - 1, edge_chunk),
        dst[edge_chunk:].reshape(n_chunks - 1, edge_chunk),
        weight[edge_chunk:].reshape(n_chunks - 1, edge_chunk),
    )
    acc, _ = lax.scan(body, acc, chunks)
    return acc


@partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _aggregate(v_num, edge_chunk, fwd_src, fwd_dst, fwd_w, bwd_src, bwd_dst, bwd_w, x):
    return _scatter_accumulate(fwd_src, fwd_dst, fwd_w, x, v_num, edge_chunk, x.dtype)


def _aggregate_fwd(v_num, edge_chunk, fwd_src, fwd_dst, fwd_w, bwd_src, bwd_dst, bwd_w, x):
    out = _scatter_accumulate(fwd_src, fwd_dst, fwd_w, x, v_num, edge_chunk, x.dtype)
    return out, (fwd_src, fwd_dst, fwd_w, bwd_src, bwd_dst, bwd_w)


def _aggregate_bwd(v_num, edge_chunk, res, g):
    fwd_src, fwd_dst, fwd_w, bwd_src, bwd_dst, bwd_w = res
    # The paired backward: aggregate the output gradient along the reverse
    # (src-sorted) adjacency — grad_x[src] += w * g[dst].
    grad_x = _scatter_accumulate(bwd_dst, bwd_src, bwd_w, g, v_num, edge_chunk, g.dtype)
    return (
        zero_cotangent(fwd_src),
        zero_cotangent(fwd_dst),
        zero_cotangent(fwd_w),
        zero_cotangent(bwd_src),
        zero_cotangent(bwd_dst),
        zero_cotangent(bwd_w),
        grad_x,
    )


_aggregate.defvjp(_aggregate_fwd, _aggregate_bwd)


_LANE_WIDTH = 128
# the cliff only manifests at full scale (docs/PERF.md section 2a: 5%
# scale shows eager within 2% of standard; full scale shows 15x) — below
# this edge count the fence would only tax small runs with pad traffic
_LANE_PAD_MIN_EDGES = 1 << 20
_lane_pad_logged: set = set()


def _lane_pad_width(f: int, e_pad: int) -> int:
    """The eager/scatter full-scale cliff fence (docs/PERF.md section 2a:
    eager/scatter measured 15x slower than standard/scatter at full Reddit
    scale ONLY — the 41-wide scatter-add over 114.6M updates falls out of
    XLA's vectorized sorted-update regime below the 128-lane width).
    Fix: pad narrow features to the lane width before the scatter and
    slice after — 3x slot traffic at f=41 in exchange for the vectorized
    regime. ON by default for full-scale scatters (>= _LANE_PAD_MIN_EDGES
    padded edges); NTS_SCATTER_LANE_PAD=1 forces it at any size,
    NTS_SCATTER_LANE_PAD=0 disables it — with a one-line warning either
    way, so the 110-vs-7-second regression can't silently return."""
    import os

    from neutronstarlite_tpu.utils.logging import get_logger

    if f >= _LANE_WIDTH:
        return f
    mode = os.environ.get("NTS_SCATTER_LANE_PAD", "auto")
    log = get_logger("aggregate")
    if mode not in ("", "auto", "0", "1"):
        # historical semantics: any non-"1" value disabled the fence, so
        # an existing opt-out spelling (false/off/no) must keep opting
        # out when the default flips to auto — but say so, loudly
        if ("spelling", mode) not in _lane_pad_logged:
            _lane_pad_logged.add(("spelling", mode))
            log.warning(
                "NTS_SCATTER_LANE_PAD=%r is not a recognized value "
                "(use 0/1/auto); treating it as 0 (fence off) for "
                "backward compatibility", mode,
            )
        mode = "0"
    if mode == "0":
        if e_pad >= _LANE_PAD_MIN_EDGES and ("off", f) not in _lane_pad_logged:
            _lane_pad_logged.add(("off", f))
            log.warning(
                "narrow scatter width %d < lane width %d over %d edges "
                "with NTS_SCATTER_LANE_PAD=0 — this is the PERF.md "
                "section-2a 15x regime; expect a serialized scatter", f,
                _LANE_WIDTH, e_pad,
            )
        return f
    if mode != "1" and e_pad < _LANE_PAD_MIN_EDGES:
        return f
    if ("pad", f) not in _lane_pad_logged:
        _lane_pad_logged.add(("pad", f))
        log.warning(
            "scatter width %d below the %d-lane width over %d edges: "
            "routing through lane padding (%.1fx slot traffic; "
            "NTS_SCATTER_LANE_PAD=0 opts out)", f, _LANE_WIDTH, e_pad,
            _LANE_WIDTH / max(f, 1),
        )
    return _LANE_WIDTH


def gather_dst_from_src(graph, x: jax.Array) -> jax.Array:
    """out[v] = sum over in-edges (u -> v) of w_uv * x[u].  [V, f] -> [V, f].

    ``graph`` is a DeviceGraph (chunked sorted-scatter path), an
    ops.ell.EllPair (gather-only ELL path, the OPTIM_KERNEL cfg flag — the
    TPU analog of the reference's optimized aggregation kernel toggle,
    cuda/ntsCUDAFuseKernel.cuh:154), or an ops.blocked_ell.BlockedEllPair
    (source-tiled ELL for beyond-VMEM feature tables, OPTIM_KERNEL:1 +
    KERNEL_TILE:vt), an ops.pallas_kernels.PallasEllPair (fused Pallas
    kernel over the same ELL tables, OPTIM_KERNEL:1 + PALLAS:1), or an
    ops.bsp_ell.BspEllPair (streamed block-sparse Pallas kernel for
    V-beyond-VMEM graphs, OPTIM_KERNEL:1 + PALLAS:1 + KERNEL_TILE:vt)."""
    from neutronstarlite_tpu.ops.blocked_ell import (
        BlockedEllPair,
        blocked_gather_dst_from_src,
    )
    from neutronstarlite_tpu.ops.bsp_ell import BspEllPair, bsp_gather_dst_from_src
    from neutronstarlite_tpu.ops.ell import EllPair, ell_gather_dst_from_src
    from neutronstarlite_tpu.ops.pallas_kernels import (
        PallasEllPair,
        pallas_gather_dst_from_src,
    )

    if isinstance(graph, BspEllPair):
        return bsp_gather_dst_from_src(graph, x)
    if isinstance(graph, BlockedEllPair):
        return blocked_gather_dst_from_src(graph, x)
    if isinstance(graph, PallasEllPair):
        return pallas_gather_dst_from_src(graph, x)
    if isinstance(graph, EllPair):
        return ell_gather_dst_from_src(graph, x)
    f = x.shape[1]
    fp = _lane_pad_width(f, int(graph.csc_src.shape[0]))
    if fp != f:
        x = jnp.pad(x, ((0, 0), (0, fp - f)))
    out = _aggregate(
        graph.v_num,
        graph.edge_chunk,
        graph.csc_src,
        graph.csc_dst,
        graph.csc_weight,
        graph.csr_src,
        graph.csr_dst,
        graph.csr_weight,
        x,
    )
    return out[:, :f] if fp != f else out


def gather_src_from_dst(graph, y: jax.Array) -> jax.Array:
    """out[u] = sum over out-edges (u -> v) of w_uv * y[v] — the CSR direction
    (the reference's backward engine, exposed as a forward op)."""
    from neutronstarlite_tpu.ops.blocked_ell import (
        BlockedEllPair,
        blocked_gather_src_from_dst,
    )
    from neutronstarlite_tpu.ops.bsp_ell import BspEllPair, bsp_gather_src_from_dst
    from neutronstarlite_tpu.ops.ell import EllPair, ell_gather_src_from_dst
    from neutronstarlite_tpu.ops.pallas_kernels import (
        PallasEllPair,
        pallas_gather_src_from_dst,
    )

    if isinstance(graph, BspEllPair):
        return bsp_gather_src_from_dst(graph, y)
    if isinstance(graph, BlockedEllPair):
        return blocked_gather_src_from_dst(graph, y)
    if isinstance(graph, PallasEllPair):
        return pallas_gather_src_from_dst(graph, y)
    if isinstance(graph, EllPair):
        return ell_gather_src_from_dst(graph, y)
    # same narrow-width fence as the CSC direction (the scatter regime is
    # direction-agnostic)
    f = y.shape[1]
    fp = _lane_pad_width(f, int(graph.csr_dst.shape[0]))
    if fp != f:
        y = jnp.pad(y, ((0, 0), (0, fp - f)))
    out = _aggregate(
        graph.v_num,
        graph.edge_chunk,
        graph.csr_dst,
        graph.csr_src,
        graph.csr_weight,
        graph.csc_dst,
        graph.csc_src,
        graph.csc_weight,
        y,
    )
    return out[:, :f] if fp != f else out


def aggregate_dst_max(graph: DeviceGraph, x: jax.Array) -> jax.Array:
    """Elementwise max over in-neighbors; gradient routed to the winning
    edge's source (SingleCPUDstAggregateOpMax + its ``record`` routing,
    core/ntsSingleCPUGraphOp.hpp:274). Composition of the V->E gather with
    the shared masked-extreme core (ops/edge.py); the gather's autodiff
    transpose is the edge->source scatter-add. Not chunked: materializes
    [Ep, f] edge values — the edge-op model family path, not the
    Reddit-scale hot path."""
    from neutronstarlite_tpu.ops.edge import _edge_extreme

    return _edge_extreme(
        graph.v_num, False, graph.csc_dst, graph.edge_mask, x[graph.csc_src]
    )


def aggregate_dst_min(graph: DeviceGraph, x: jax.Array) -> jax.Array:
    """Elementwise min over in-neighbors (SingleCPUDstAggregateOpMin,
    core/ntsSingleCPUGraphOp.hpp:206)."""
    from neutronstarlite_tpu.ops.edge import _edge_extreme

    return _edge_extreme(
        graph.v_num, True, graph.csc_dst, graph.edge_mask, x[graph.csc_src]
    )
