"""Fused ELL-table GAT attention: scatter-free edge softmax + aggregation.

The edge-op GAT chain (models/gat.py; reference GAT_CPU.hpp:195-222)
materializes [E]-aligned score/alpha arrays and runs segment softmax +
segment sums over them. On TPU every one of those segment ops either
serializes (scatter) or pays sort machinery. This module re-expresses the
whole per-layer attention over the OPTIM_KERNEL degree-bucketed ELL tables
(ops/ell.py): a destination's in-edges occupy exactly ONE padded row
[K], so

- edge scores   e[r, k] = leaky_relu(al[nbr[r, k]] + ar[row_vertex[r]])
- edge softmax  alpha[r, k] = masked softmax over the row's K slots
- aggregation   out[r] = sum_k alpha[r, k] * h[nbr[r, k]]

are all DENSE [rows, K(, f)] operations — gathers and row reductions, no
scatter, no [E] tensors. This is the TPU analog of fusing SDDMM + softmax +
SpMM (FusedMM's unification) on top of the reference's own decomposed
attention (GAT_CPU_DIST_OPTM: a.[h_src||h_dst] = a_src.h_src + a_dst.h_dst).

The aggregation's h-gradient needs the transposed aggregation with the SAME
runtime alphas: ``GatEllPair`` precomputes, for every backward (CSR) table
slot, the flat index of its edge's forward slot (``bwd_alpha_idx``), so the
backward pass gathers alpha straight out of the forward tables — the
runtime-weight generalization of ops/ell.py's paired custom_vjp (reference
CSC-forward/CSR-backward pairing, cuda/ntsCUDAFuseKernel.cuh:147/:327).
"""

from __future__ import annotations

import dataclasses
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from jax import lax

from neutronstarlite_tpu.graph.storage import CSCGraph
from neutronstarlite_tpu.ops.ell import (
    DEFAULT_SLOT_CHUNK,
    EllBuckets,
    EllPair,
    _chunk_budget_bytes,
    ell_tables_aggregate,
)

NEG_INF = -1e30  # masked-slot score (bf16-safe sentinel, not actual inf)


def _flat_slot_layout(buckets: EllBuckets):
    """Host-side row layout of the concatenated per-level tables:
    (level_base, level_rows, level_K, row_vertex) where row_vertex[r] is the
    vertex whose in-edges occupy concatenated row r."""
    level_rows = [n.shape[0] for n in buckets.nbr]
    level_K = [n.shape[1] for n in buckets.nbr]
    bases, base = [], 0
    for rows, k in zip(level_rows, level_K):
        bases.append(base)
        base += rows * k
    inv = np.asarray(buckets.inv_perm)
    row_vertex = np.empty(buckets.v_num, dtype=np.int64)
    row_vertex[inv] = np.arange(buckets.v_num)
    return bases, level_rows, level_K, row_vertex


def _edge_flat_slots(offsets, adj_dst, buckets: EllBuckets):
    """For every edge of the direction's adjacency (CSC edge order for the
    forward tables): the flat index of its slot in the concatenated
    [rows, K] level tables. Relies on the build filling each row's slots in
    adjacency order (both the native and the NumPy fill copy runs
    adj[lo:lo+deg] left-to-right)."""
    bases, level_rows, level_K, _ = _flat_slot_layout(buckets)
    # concat-row index of each vertex + its level and intra-level row
    inv = np.asarray(buckets.inv_perm).astype(np.int64)
    row_starts = np.cumsum([0] + level_rows)
    level_of_row = np.searchsorted(row_starts, np.arange(row_starts[-1]), side="right") - 1
    e_num = len(adj_dst)
    k_within = np.arange(e_num) - offsets[adj_dst]
    rows = inv[adj_dst]
    lv = level_of_row[rows]
    local_row = rows - row_starts[lv]
    return (
        np.asarray(bases)[lv]
        + local_row * np.asarray(level_K)[lv]
        + k_within
    ).astype(np.int64)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class GatEllPair:
    """ELL tables + the extra maps GAT's data-dependent weights need.

    ``fwd_row_vertex[r]``: destination vertex of concatenated fwd row r
    (for gathering the per-dst attention half into row order).
    ``bwd_alpha_idx[l]`` [Nk_b, K_b]: flat fwd-slot index of each backward
    slot's edge (padding slots point at 0 and are masked by the bwd table's
    zero weight).
    """

    pair: EllPair
    fwd_row_vertex: jax.Array  # [V]
    bwd_alpha_idx: List[jax.Array]

    @staticmethod
    def from_host(g: CSCGraph, slot_chunk: int = DEFAULT_SLOT_CHUNK) -> "GatEllPair":
        return GatEllPair.from_pair(EllPair.from_host(g, slot_chunk), g)

    @staticmethod
    def from_pair(pair: EllPair, g: CSCGraph) -> "GatEllPair":
        """Add the attention slot maps to an already-built EllPair (the
        generic OPTIM_KERNEL build constructs the pair; this wraps it)."""
        _, level_rows_f, level_K_f, fwd_row_vertex = _flat_slot_layout(
            pair.fwd
        )
        total_f = sum(r * k for r, k in zip(level_rows_f, level_K_f))

        # fwd slot of every CSC edge
        fwd_slot_of_csc = _edge_flat_slots(
            g.column_offset, g.dst_of_edge.astype(np.int64), pair.fwd
        )
        # CSR edge -> CSC edge correspondence (multigraph-safe: stable sort
        # by (src, dst) pairs both edge orders the same way)
        csc_src = g.row_indices.astype(np.int64)
        csc_dst = g.dst_of_edge.astype(np.int64)
        csr_src = g.src_of_edge.astype(np.int64)
        csr_dst = g.column_indices.astype(np.int64)
        a = np.lexsort((csc_dst, csc_src))  # CSC edge ids in (src, dst) order
        b = np.lexsort((csr_dst, csr_src))  # CSR edge ids in (src, dst) order
        csc_of_csr = np.empty(g.e_num, dtype=np.int64)
        csc_of_csr[b] = a

        # bwd slot of every CSR edge, then invert into per-level tables
        bwd_slot_of_csr = _edge_flat_slots(
            g.row_offset, g.src_of_edge.astype(np.int64), pair.bwd
        )
        bases_b, level_rows_b, level_K_b, _ = _flat_slot_layout(pair.bwd)
        total_b = sum(r * k for r, k in zip(level_rows_b, level_K_b))
        # the on-device slot maps below are int32 (half the index bandwidth
        # of int64 on the gather-bound path); a padded slot space past 2^31
        # (graphs ~10x Reddit scale) would overflow them silently — refuse
        # loudly at build time instead
        if max(total_f, total_b) >= 2**31:
            raise ValueError(
                f"GatEllPair slot space exceeds int32: fwd {total_f} / "
                f"bwd {total_b} padded slots >= 2^31; shard the graph "
                "(PARTITIONS) so each shard's ELL table fits int32 indexing"
            )
        flat_idx = np.zeros(total_b, dtype=np.int64)  # padding -> fwd slot 0
        flat_idx[bwd_slot_of_csr] = fwd_slot_of_csc[csc_of_csr]
        bwd_alpha_idx = []
        for base, rows, k in zip(bases_b, level_rows_b, level_K_b):
            bwd_alpha_idx.append(
                jnp.asarray(
                    flat_idx[base: base + rows * k].reshape(rows, k),
                    dtype=jnp.int32,
                )
            )
        return GatEllPair(
            pair=pair,
            fwd_row_vertex=jnp.asarray(fwd_row_vertex, dtype=jnp.int32),
            bwd_alpha_idx=bwd_alpha_idx,
        )


@jax.custom_vjp
def _gather_al_levels(gep: GatEllPair, al: jax.Array):
    """Per-level ``al[nbr]`` with a scatter-free transpose: the cotangent of
    slot (r, k) belongs to vertex nbr[r, k], and summing a per-slot array
    into [V] is exactly a row reduction over the BACKWARD tables — each bwd
    row collects all of one vertex's forward slots via ``bwd_alpha_idx``.
    Autodiff of the plain gather would instead emit an E-sized scatter-add,
    the serialized lowering this module exists to avoid."""
    return [al[nbr] for nbr in gep.pair.fwd.nbr]


def _gal_fwd(gep, al):
    return _gather_al_levels(gep, al), gep


def _gal_bwd(gep, g_levels):
    from neutronstarlite_tpu.ops.segment import zero_cotangent

    dtype = g_levels[0].dtype if g_levels else jnp.float32
    bwd = gep.pair.bwd
    g_flat = jnp.concatenate([g.reshape(-1) for g in g_levels])
    parts = []
    for w, idx in zip(bwd.wgt, gep.bwd_alpha_idx):
        if idx.shape[1] == 0:
            parts.append(jnp.zeros((idx.shape[0],), dtype))
            continue
        parts.append(
            jnp.where(w != 0.0, g_flat[idx], 0.0).sum(axis=1).astype(dtype)
        )
    grad_al = jnp.concatenate(parts)[bwd.inv_perm]
    return (jax.tree.map(zero_cotangent, gep), grad_al)


_gather_al_levels.defvjp(_gal_fwd, _gal_bwd)


def gat_ell_alpha(gep: GatEllPair, al: jax.Array, ar: jax.Array, slope: float):
    """Per-level attention weights: masked softmax of
    leaky_relu(al[src] + ar[dst]) over each destination row's K slots.
    Dense differentiable ops; the src-half gather pairs a scatter-free
    transpose (``_gather_al_levels``), the dst-half gather's transpose is a
    V-sized width-1 permutation scatter (cheap) left to autodiff."""
    fwd = gep.pair.fwd
    row_starts = np.cumsum([0] + [n.shape[0] for n in fwd.nbr])
    al_levels = _gather_al_levels(gep, al)
    alphas = []
    for i, (nbr, wgt) in enumerate(zip(fwd.nbr, fwd.wgt)):
        if nbr.shape[1] == 0:
            alphas.append(jnp.zeros(nbr.shape, al.dtype))
            continue
        dst_v = jax.lax.dynamic_slice_in_dim(
            gep.fwd_row_vertex, int(row_starts[i]), nbr.shape[0]
        )
        e = jax.nn.leaky_relu(
            al_levels[i] + ar[dst_v][:, None], negative_slope=slope
        )
        real = wgt != 0.0
        e = jnp.where(real, e, NEG_INF)
        e = e - jax.lax.stop_gradient(e.max(axis=1, keepdims=True))
        ex = jnp.where(real, jnp.exp(e), 0.0)
        alphas.append(ex / jnp.maximum(ex.sum(axis=1, keepdims=True), 1e-20))
    return alphas


@jax.custom_vjp
def _runtime_weighted_aggregate(gep: GatEllPair, alphas, h):
    fwd = gep.pair.fwd
    return ell_tables_aggregate(h, fwd.nbr, alphas, fwd.slot_chunk)[
        fwd.inv_perm
    ]


def _rwa_fwd(gep, alphas, h):
    return _runtime_weighted_aggregate(gep, alphas, h), (gep, alphas, h)


def _rwa_bwd(res, g):
    from neutronstarlite_tpu.ops.segment import zero_cotangent

    gep, alphas, h = res
    fwd, bwd = gep.pair.fwd, gep.pair.bwd

    # grad_h: transposed aggregation with the SAME runtime alphas, gathered
    # into the backward tables by the precomputed slot map (padding slots
    # keep weight 0 via the bwd table's own zero weights)
    alpha_flat = jnp.concatenate([a.reshape(-1) for a in alphas])
    bwd_weights = [
        jnp.where(w != 0.0, alpha_flat[idx], 0.0)
        for w, idx in zip(bwd.wgt, gep.bwd_alpha_idx)
    ]
    grad_h = ell_tables_aggregate(g, bwd.nbr, bwd_weights, bwd.slot_chunk)[
        bwd.inv_perm
    ]

    # grad_alpha[r, k] = g[row_vertex[r]] . h[nbr[r, k]] — the [rows, K, f]
    # gather intermediate is bounded in bytes exactly like the forward
    # (ell_tables_aggregate's chunking; DEFAULT_CHUNK_MIB rationale)
    g_rows = g[gep.fwd_row_vertex]
    row_starts = np.cumsum([0] + [n.shape[0] for n in fwd.nbr])
    grad_alphas = []
    for i, (nbr, wgt) in enumerate(zip(fwd.nbr, fwd.wgt)):
        if nbr.shape[1] == 0:
            grad_alphas.append(jnp.zeros(nbr.shape, g.dtype))
            continue
        g_lv = jax.lax.dynamic_slice_in_dim(
            g_rows, int(row_starts[i]), nbr.shape[0]
        )
        grad_alphas.append(
            _grad_alpha_level(g_lv, h, nbr, wgt, fwd.slot_chunk)
        )

    return (jax.tree.map(zero_cotangent, gep), grad_alphas, grad_h)


def _grad_alpha_level(g_lv, h, nbr, wgt, slot_chunk: int):
    """[Nk, K] slot gradients with the gather slab byte-bounded by row (or,
    for hub levels whose K alone exceeds the budget, column) chunking —
    mirrors ell_tables_aggregate's chunk policy, f32 products."""
    f = h.shape[1]
    slot_budget = max(_chunk_budget_bytes() // (f * 4), 1)
    Nk, K = nbr.shape

    def dense(nb, wg, gl):
        ga = jnp.einsum(
            "rf,rkf->rk", gl.astype(jnp.float32), h[nb].astype(jnp.float32)
        )
        return jnp.where(wg != 0.0, ga, 0.0).astype(gl.dtype)

    if K > slot_budget:
        # hub level: chunk the K columns
        kc = max(slot_budget // max(Nk, 1), 1)
        n_ch = -(-K // kc)
        pad = n_ch * kc - K
        nb = jnp.pad(nbr, ((0, 0), (0, pad))).reshape(Nk, n_ch, kc)
        wg = jnp.pad(wgt, ((0, 0), (0, pad))).reshape(Nk, n_ch, kc)

        def kbody(_, chunk):
            n, w = chunk
            return 0, dense(n, w, g_lv)

        _, out = lax.scan(kbody, 0, (nb.transpose(1, 0, 2), wg.transpose(1, 0, 2)))
        return out.transpose(1, 0, 2).reshape(Nk, n_ch * kc)[:, :K]

    rows = max(min(slot_chunk, slot_budget) // K, 1)
    if Nk <= rows:
        return dense(nbr, wgt, g_lv)
    n_ch = -(-Nk // rows)
    pad = n_ch * rows - Nk
    nb = jnp.pad(nbr, ((0, pad), (0, 0))).reshape(n_ch, rows, K)
    wg = jnp.pad(wgt, ((0, pad), (0, 0))).reshape(n_ch, rows, K)
    gl = jnp.pad(g_lv, ((0, pad), (0, 0))).reshape(n_ch, rows, f)

    def body(_, chunk):
        n, w, g_c = chunk
        return 0, dense(n, w, g_c)

    _, out = lax.scan(body, 0, (nb, wg, gl))
    return out.reshape(n_ch * rows, K)[:Nk]


_runtime_weighted_aggregate.defvjp(_rwa_fwd, _rwa_bwd)


def gat_ell_attention_aggregate(
    gep: GatEllPair,
    h: jax.Array,
    al: jax.Array,
    ar: jax.Array,
    slope: float,
) -> jax.Array:
    """The whole GAT graph-op chain over ELL tables:
    scores -> per-dst softmax -> weighted aggregate, [V, f] -> [V, f]."""
    alphas = gat_ell_alpha(gep, al, ar, slope)
    return _runtime_weighted_aggregate(gep, alphas, h)
