from neutronstarlite_tpu.ops.device_graph import DeviceGraph
from neutronstarlite_tpu.ops.aggregate import (
    gather_dst_from_src,
    gather_src_from_dst,
    aggregate_dst_max,
    aggregate_dst_min,
)
from neutronstarlite_tpu.ops.edge import (
    scatter_src_to_edge,
    scatter_dst_to_edge,
    scatter_src_dst_to_edge,
    aggregate_edge_to_dst,
    aggregate_edge_to_dst_weighted,
    edge_softmax,
)

# aggregation-table layouts accepted by gather_dst_from_src (the graph
# argument picks the backend; see ops/aggregate.py)
from neutronstarlite_tpu.ops.blocked_ell import BlockedEllPair
from neutronstarlite_tpu.ops.ell import EllPair
from neutronstarlite_tpu.ops.pallas_kernels import PallasEllPair
from neutronstarlite_tpu.ops.ell_gat import GatEllPair, gat_ell_attention_aggregate

__all__ = [
    "DeviceGraph",
    "EllPair",
    "BlockedEllPair",
    "PallasEllPair",
    "GatEllPair",
    "gat_ell_attention_aggregate",
    "gather_dst_from_src",
    "gather_src_from_dst",
    "aggregate_dst_max",
    "aggregate_dst_min",
    "scatter_src_to_edge",
    "scatter_dst_to_edge",
    "scatter_src_dst_to_edge",
    "aggregate_edge_to_dst",
    "aggregate_edge_to_dst_weighted",
    "edge_softmax",
]
