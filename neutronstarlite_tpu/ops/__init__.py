from neutronstarlite_tpu.ops.device_graph import DeviceGraph
from neutronstarlite_tpu.ops.aggregate import (
    gather_dst_from_src,
    gather_src_from_dst,
    aggregate_dst_max,
    aggregate_dst_min,
)
from neutronstarlite_tpu.ops.edge import (
    scatter_src_to_edge,
    scatter_dst_to_edge,
    scatter_src_dst_to_edge,
    aggregate_edge_to_dst,
    aggregate_edge_to_dst_weighted,
    edge_softmax,
)

__all__ = [
    "DeviceGraph",
    "gather_dst_from_src",
    "gather_src_from_dst",
    "aggregate_dst_max",
    "aggregate_dst_min",
    "scatter_src_to_edge",
    "scatter_dst_to_edge",
    "scatter_src_dst_to_edge",
    "aggregate_edge_to_dst",
    "aggregate_edge_to_dst_weighted",
    "edge_softmax",
]
