"""Mini-batch aggregation over sampled subgraphs.

Reference: ``MiniBatchFuseOp`` (core/ntsMiniBatchGraphOp.hpp:61-129): weighted
gather over a batch-local sampCSC in the forward, weighted scatter-add in the
backward, plus the ``get_feature``/``get_label`` row gathers (:36-60). Here
the op is a segment-sum over the padded batch CSC and jax.grad supplies the
paired scatter; feature/label gathers are plain device indexing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def minibatch_gather(
    src_local: jax.Array,
    dst_local: jax.Array,
    weight: jax.Array,
    x: jax.Array,
    n_dst_cap: int,
) -> jax.Array:
    """out[dst] = sum over batch edges of w * x[src].  [Ncap_in, f] ->
    [n_dst_cap, f]. Padding edges have weight 0 and indices 0."""
    vals = x[src_local] * weight[:, None].astype(x.dtype)
    return jax.ops.segment_sum(vals, dst_local, num_segments=n_dst_cap)


def get_feature(feature: jax.Array, node_ids: jax.Array) -> jax.Array:
    """Gather input rows for the innermost sampled layer
    (ntsMiniBatchGraphOp.hpp:36)."""
    return feature[node_ids]


def get_label(label: jax.Array, seed_ids: jax.Array) -> jax.Array:
    return label[seed_ids]
