"""Block-sparse (dst-tile, src-tile) streamed Pallas aggregation.

The regime ladder for the fused neighbor aggregation on one chip:

1. [V, f] fits VMEM            -> ops/pallas_kernels.py (table resident)
2. [V, 128] fits VMEM          -> same kernel, feature-column chunked
3. V itself is beyond VMEM     -> THIS module (V ~ 10x Reddit and up)

Here neither the feature table nor a 128-wide column of it fits on-chip,
so the kernel streams BOTH sides: vertices are cut into destination tiles
of ``dt`` rows and source tiles of ``vt`` rows; edges are packed into
fixed-shape blocks, each block belonging to one (dst tile, src tile)
pair. The pallas grid walks blocks grouped per destination tile (each
tile's blocks CONSECUTIVE — the ordering invariant) with the [dt, f]
output tile living in VMEM across every consecutive block of its tile
(zeroed on first visit, spilled to HBM when the tile changes — the
revisiting-output accumulation pattern), while the [vt, f] source slab is
DMA-streamed per block via a scalar-prefetched block->tile map
(``pltpu.PrefetchScalarGridSpec``). HBM traffic per application:
O(E * 8 B) table reads + O(sum over dst tiles of present src tiles *
vt * f) slab streams + O(V * f) output writes — versus O(E * f) random
HBM gathers for the plain layout past VMEM.

Block layout: each block is ``R`` rows of ``K`` slots. A row is (a piece
of) one destination's in-edge run within one source tile: runs longer
than K split into several rows (legal because every row's partial sum is
accumulated). Rows store tile-LOCAL neighbor ids ``nbr`` [B, K, R] and
weights ``wgt`` [B, K, R] (the K-major layout keeps R on the 128-lane
axis), plus the row's tile-local destination ``ldst`` [B, R]. Padding
rows/slots carry weight 0 and index 0, contributing nothing.

The per-block combine is scatter-free BY CONSTRUCTION: row partial sums
``acc`` [R, f] land in the output tile through a one-hot MXU matmul —
``onehot(ldst) [dt, R] @ acc [R, f]`` — the TPU-idiomatic scatter (the
MXU is the only unit that reorders data at full bandwidth; per-row
dynamic stores would serialize). This is the cost that makes regime 2
preferable whenever the row count allows: the matmul spends
``dt * f * 2`` FLOPs per packed ROW (independent of K), so at Reddit
scale (~7M rows, dt=512, f=602) it would burn ~4.2 TFLOP per application
— slower than keeping a 128-wide column slab resident and gathering
from VMEM. Past ~375k-row slabs there is no resident option; the matmul
price buys streaming locality the plain layout cannot offer, and the
multi-chip path (parallel/dist_ell.py) re-enters regime 1/2 per shard by
cutting V by P. Reference analog: the shared-memory tiled CUDA
aggregation (cuda/ntsCUDAFuseKernel.cuh:154-208) — re-derived for a
memory system where the accumulator tile, not the source tile, is the
scarce on-chip resource.

Forward/backward pairing follows ops/ell.py: the backward is the same
kernel over the transposed (CSR) layout, one ``custom_vjp``. Numeric
policy: the one-hot W entries ROUND TO THE SLAB DTYPE (bf16 in
production) so the main dot runs at full MXU rate — a documented
divergence from the XLA ELL path's f32 edge weights, bounded by the
bf16 tolerance class (~5e-2 relative; on-chip check
tests/test_tpu.py::test_tpu_bsp_bf16_and_segmented) — with f32
accumulation in-block and across blocks and one cast at the end.
Off-TPU the kernel runs in interpret mode (tests).
"""

from __future__ import annotations

import dataclasses
import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

from neutronstarlite_tpu.graph.storage import CSCGraph
from neutronstarlite_tpu.utils.logging import get_logger

try:  # pallas TPU backend may be absent on pure-CPU builds
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    _HAS_PLTPU = False

log = get_logger("bsp_ell")

DEFAULT_DT = 512  # dst tile rows (the VMEM-resident accumulator height)
DEFAULT_VT = 4096  # src tile rows (the streamed slab height)
DEFAULT_K = 8  # slots per packed row
DEFAULT_R = 128  # rows per block (the 128-lane axis of the tables)
# Max blocks per pallas_call: the [B] int32 scalar-prefetch key must fit
# SMEM (~1 MB; round-3 AOT evidence: two ~600 KB maps RESOURCE_EXHAUSTED,
# one packed ~700 KB array compiled). 224k blocks = 896 KB of keys leaves
# headroom for Mosaic's own scalars. Past this the build SEGMENTS the
# grid at dst-tile boundaries (see BspEll.build) — the compiled program
# is then V-independent and there is no block-count ceiling at all.
DEFAULT_MAX_BLOCKS = 224 * 1024


def bsp_bseg_menu(cap_eff: int) -> "list[int]":
    """The EXACT b_seg menu a segmented build can emit under this cap:
    seven uniform quantum steps plus the cap itself (the quantum is
    floor(cap/8) rounded down to a multiple of 8, which need not divide
    the cap — the cap is its own 8th value). Shared with
    tools/aot_bsp_scale so the AOT proof enumerates precisely these."""
    quantum = max(8, (cap_eff // 8) - (cap_eff // 8) % 8)
    menu = [k * quantum for k in range(1, 8) if k * quantum < cap_eff]
    return menu + [cap_eff]


def bsp_tseg_menu(t_dst: int) -> "list[int]":
    """The EXACT t_seg menu a segmented build can emit for this t_dst:
    at most 16 quantum steps (quantum = ceil(hi/16) rounded up to a
    128-multiple) capped by hi = roundup128(t_dst + 1), the band bound
    (t_seg_cap <= t_dst always). Shared with tools/aot_bsp_scale so the
    AOT proof compiles precisely the (b_seg menu) x (this menu) lattice
    — an arbitrary roundup128(tiles) could land on any of ~t_dst/128
    values the tool never pre-lowered (ADVICE r4), re-exposing the
    full-scale Mosaic-compile hang the proof exists to retire. Snapping
    up wastes only per-call output-buffer rows (trailing tiles are
    never written or read): at most one quantum ~= 6% of the full
    output, and none of the compute grid, which is sized by b_seg."""
    hi = -(-(t_dst + 1) // 128) * 128
    quantum = max(128, -(-(hi // 16) // 128) * 128)
    menu = [k * quantum for k in range(1, 16) if k * quantum < hi]
    return menu + [hi]


def resolve_bsp_knobs(dt: int = 0, k_slots: int = 0) -> "tuple[int, int]":
    """Resolve the NTS_BSP_DT / NTS_BSP_K env tunables (0 = use env or
    default). Shared by the single-chip (BspEllPair.from_host) and dist
    (parallel/dist_bsp.DistBsp.build) builders so on-chip A/B knobs
    behave uniformly across paths."""
    dt = int(dt) or int(os.environ.get("NTS_BSP_DT", DEFAULT_DT))
    k_slots = int(k_slots) or int(os.environ.get("NTS_BSP_K", DEFAULT_K))
    return dt, k_slots


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BspEll:
    """One direction's packed block tables (see module docstring)."""

    nbr: jax.Array  # [S*b_seg, K, R] int32 tile-local neighbor ids
    wgt: jax.Array  # [S*b_seg, K, R] f32 (0 on padding)
    ldst: jax.Array  # [S*b_seg, R] int32 tile-local destination row
    # ONE packed per-block tile key: dst_tile_LOCAL * t_src + src_tile.
    # The key array is the kernel's scalar-prefetch operand and lives in
    # SMEM (1 MB): two separate [B] int32 maps overflowed it at full
    # Reddit scale (B ~ 141-175k -> 552-684 KB EACH, AOT
    # RESOURCE_EXHAUSTED, docs/perf_runs/round3/aot_eager_bsp2.json);
    # packed, one array fits to ~250k blocks. Past the budget the build
    # SEGMENTS the grid: blocks are cut at dst-tile boundaries into
    # n_seg uniform calls of b_seg blocks x t_seg dst tiles each, keys
    # are segment-LOCAL, and aggregate() runs one pallas_call per
    # segment (same shapes -> ONE compiled program reused n_seg times).
    # 10x-Reddit (~1.4M blocks) therefore compiles the same program as
    # full Reddit; only the Python-level segment count grows.
    blk_key: jax.Array  # [S*b_seg] int32 packed (local dst_tile, src_tile)
    v_num: int = dataclasses.field(metadata=dict(static=True))
    dt: int = dataclasses.field(metadata=dict(static=True))
    vt: int = dataclasses.field(metadata=dict(static=True))
    # RECTANGULAR form (the distributed per-shard case: dst rows are one
    # device's vp vertices, srcs index the full all_gathered [P*vp, f]
    # slab): src_num sizes the source tiling independently of v_num.
    # 0 = square (src space == dst space), the single-chip default.
    src_num: int = dataclasses.field(default=0, metadata=dict(static=True))
    # SMEM-budget segmentation (see blk_key): n_seg calls of b_seg blocks
    # each, call s covering the contiguous dst-tile range of seg_tiles[s]
    # tiles (t_seg = the per-call OUTPUT tile count >= max(seg_tiles);
    # trailing output tiles beyond a call's real range are never written
    # or read). Defaults describe the unsegmented form (b_seg/t_seg = 0
    # -> whole table / all tiles, one call).
    n_seg: int = dataclasses.field(default=1, metadata=dict(static=True))
    b_seg: int = dataclasses.field(default=0, metadata=dict(static=True))
    t_seg: int = dataclasses.field(default=0, metadata=dict(static=True))
    seg_tiles: tuple = dataclasses.field(
        default=(), metadata=dict(static=True)
    )

    @staticmethod
    def build(
        v_num: int,
        offsets: np.ndarray,  # [V+1] per-dst adjacency offsets
        adj: np.ndarray,  # [E] source ids, grouped by dst
        weights: np.ndarray,  # [E]
        dt: int = DEFAULT_DT,
        vt: int = DEFAULT_VT,
        k_slots: int = DEFAULT_K,
        r_rows: int = DEFAULT_R,
        src_num: int = 0,  # 0 = square; else rectangular (adj < src_num)
        max_blocks: int = 0,  # 0 -> NTS_BSP_MAX_BLOCKS / DEFAULT_MAX_BLOCKS
        keep_host: bool = False,  # True: leave tables as numpy (a caller
        # that re-lays them — DistBsp's segmented stack — avoids a
        # device round-trip at exactly the scale that segments)
    ) -> "BspEll":
        K, R = int(k_slots), int(r_rows)
        max_blocks = int(max_blocks) or int(
            os.environ.get("NTS_BSP_MAX_BLOCKS", DEFAULT_MAX_BLOCKS)
        )
        n_src = int(src_num) or int(v_num)
        t_dst = -(-v_num // dt)
        t_src = -(-n_src // vt)
        e_num = len(adj)
        deg = np.diff(offsets).astype(np.int64)
        dst_of_edge = np.repeat(np.arange(v_num, dtype=np.int64), deg)
        adj = np.asarray(adj, dtype=np.int64)
        weights = np.asarray(weights, dtype=np.float32)

        if e_num:
            # group edges by (dst tile, src tile); edges arrive dst-grouped,
            # so a stable sort by the pair key keeps dst ascending per group.
            # The key space is tiny (t_dst * t_src ~ 13k at full Reddit
            # scale), so the native O(E) counting sort applies directly —
            # measured neutral on wall time at full scale (the per-edge
            # fancy-index fills dominate the build, 274 s vs 276 s) but it
            # avoids argsort's O(E) int64 temp at peak
            from neutronstarlite_tpu import native as native_rt

            key = (dst_of_edge // dt) * t_src + adj // vt
            # key-space bound: the counting sort allocates an int64
            # histogram of t_dst * t_src entries — past ~16M keys (128 MB)
            # argsort is the better trade, long before the int32 limit
            if native_rt.available() and t_dst * t_src < 2**24:
                order = native_rt.sort_by_tile(
                    key.astype(np.int32, copy=False), t_dst * t_src
                )
            else:
                order = np.argsort(key, kind="stable")
            ks, ds = key[order], dst_of_edge[order]
            ss, ws = adj[order], weights[order]

            # (group, dst) runs -> packed rows of <= K slots each
            change = (ks[1:] != ks[:-1]) | (ds[1:] != ds[:-1])
            run_start = np.nonzero(np.concatenate([[True], change]))[0]
            run_len = np.diff(np.concatenate([run_start, [e_num]]))
            run_key, run_dst = ks[run_start], ds[run_start]
            rows_of_run = -(-run_len // K)
            n_rows = int(rows_of_run.sum())
            row_of_first = np.concatenate([[0], np.cumsum(rows_of_run)[:-1]])
            row_run = np.repeat(np.arange(len(run_start)), rows_of_run)
            row_key = run_key[row_run]
            row_dst = run_dst[row_run]

            # rows are key-sorted; rank within key -> (block, slot)
            key_change = np.nonzero(
                np.concatenate([[True], row_key[1:] != row_key[:-1]])
            )[0]
            first_row_of_key = np.repeat(
                key_change,
                np.diff(np.concatenate([key_change, [n_rows]])),
            )
            rank = np.arange(n_rows) - first_row_of_key
            # cumulative block count at each key group's start
            grp_rows = np.diff(np.concatenate([key_change, [n_rows]]))
            grp_blocks = -(-grp_rows // R)
            grp_block_start = np.concatenate([[0], np.cumsum(grp_blocks)[:-1]])
            blocks_before = np.repeat(grp_block_start, grp_rows)
            row_block = blocks_before + rank // R
            row_slot = rank % R
            n_data_blocks = int(grp_blocks.sum())
        else:
            n_rows = n_data_blocks = 0
            row_block = row_slot = row_dst = row_key = np.zeros(0, np.int64)

        # data blocks are created in key order, so bd is nondecreasing
        if n_data_blocks:
            blk_first = np.nonzero(
                np.concatenate([[True], row_block[1:] != row_block[:-1]])
            )[0]
            data_bd = (row_key[blk_first] // t_src).astype(np.int64)
            data_bs = (row_key[blk_first] % t_src).astype(np.int32)
        else:
            data_bd = np.zeros(0, np.int64)
            data_bs = np.zeros(0, np.int32)

        # fill the DATA blocks into dense temp tables (block ids are the
        # data block ids 0..n_data-1, exactly what row_block holds)
        nbr_d = np.zeros((n_data_blocks, K, R), dtype=np.int32)
        wgt_d = np.zeros((n_data_blocks, K, R), dtype=np.float32)
        ldst_d = np.zeros((n_data_blocks, R), dtype=np.int32)
        if e_num:
            src_local = (ss - (ss // vt) * vt).astype(np.int32)
            run_ldst = (run_dst - (run_dst // dt) * dt).astype(np.int32)
            if native_rt.available():
                # one OpenMP pass over runs (the three O(E) fancy-index
                # scatters below were the measured build bottleneck)
                native_rt.fill_bsp(
                    run_start, run_len, row_of_first, run_ldst,
                    row_block, row_slot, src_local,
                    np.ascontiguousarray(ws, np.float32), K, R,
                    nbr_d, wgt_d, ldst_d,
                )
            else:
                # per-edge placement: row-relative slot position
                run_of_edge = np.repeat(np.arange(len(run_start)), run_len)
                off = np.arange(e_num) - run_start[run_of_edge]
                e_row = row_of_first[run_of_edge] + off // K
                p = off % K
                b_e = row_block[e_row]
                s_e = row_slot[e_row]
                nbr_d[b_e, p, s_e] = src_local
                wgt_d[b_e, p, s_e] = ws
                ldst_d[row_block, row_slot] = run_ldst[row_run]

        # --- SMEM-budget segmentation (VERDICT r3 item 3) -----------------
        # Cut the grid into S contiguous dst-tile RANGES, each carrying at
        # most `max_blocks` blocks, so every pallas_call's [b_seg] key fits
        # SMEM. Ranges are packed greedily by BLOCK count (balanced: pad
        # blocks don't scale with cross-segment degree skew) under a
        # tile-count cap that bounds the per-call output buffer. When
        # segmented, b_seg is pinned to the budget and t_seg rounds up to
        # a 128-multiple so the compiled-program MENU is small and
        # provable by AOT (tools/aot_bsp_scale.py); per-block geometry —
        # the Mosaic lowering surface — is t_seg-invariant. A call's
        # output tiles beyond its real range are never written or read
        # (aggregate slices each call to its own range).
        # Within a segment: data blocks first (grouped per tile), then one
        # filler block per empty tile in range (every real tile must be
        # visited once so its output is zero-initialized — an unvisited
        # pallas output block would be uninitialized memory), then pad
        # blocks repeating the last real block's key (weight 0:
        # accumulate nothing, never re-zero). The kernel only needs each
        # tile's blocks CONSECUTIVE, which all three groups preserve.
        cap_eff = (max_blocks // 8) * 8
        blocks_per_tile = np.bincount(data_bd, minlength=t_dst).astype(np.int64)
        need = np.maximum(blocks_per_tile, 1)  # empty tiles need a filler
        if t_dst and int(need.max()) > cap_eff:
            raise ValueError(
                f"bsp ELL: a single dst tile needs {int(need.max())} blocks,"
                f" over the {max_blocks}-block SMEM key budget; raise dt/K/R"
                " or NTS_BSP_MAX_BLOCKS"
            )
        total_need = int(need.sum())
        s_est = max(1, -(-total_need // max(cap_eff, 1)))
        t_seg_cap = min(t_dst, 2 * (-(-t_dst // s_est))) if t_dst else 0
        # BALANCED packing: close a segment at ceil(total/S) blocks, not
        # at the cap — fill-to-cap left the LAST segment nearly empty and
        # the uniform b_seg then padded it with cap-sized dead work
        # (measured at full-scale vt=2048: 258k data blocks -> 458k padded
        # grid steps, 1.78x; balancing + the quantized b_seg below holds
        # that to ~1.1x). The cap stays the hard bound.
        target = min(cap_eff, -(-total_need // s_est))
        seg_of_tile = np.empty(t_dst, np.int64)
        first_tile = [0]
        acc_b = acc_t = seg = 0
        for tile in range(t_dst):  # t_dst ~ 4.5k at 10x Reddit: cheap
            nb = int(need[tile])
            if acc_t and (
                acc_t + 1 > t_seg_cap
                or acc_b + nb > target
            ):
                seg += 1
                first_tile.append(tile)
                acc_b = acc_t = 0
            seg_of_tile[tile] = seg
            acc_b += nb
            acc_t += 1
        # tail-merge the tile-granularity spill: closing at the balanced
        # target can strand a near-empty final segment (full-scale
        # vt=2048: a 0.4k-block 3rd segment that b_seg would pad with
        # 143k dead blocks); fold trailing segments back while the cap
        # and the tile bound both still hold
        seg_blocks = np.bincount(
            seg_of_tile, weights=need.astype(np.float64), minlength=seg + 1
        ).astype(np.int64)
        seg_tiles_n = np.bincount(seg_of_tile, minlength=seg + 1)
        while (
            len(first_tile) >= 2
            and seg_blocks[-1] + seg_blocks[-2] <= cap_eff
            and seg_tiles_n[-1] + seg_tiles_n[-2] <= t_seg_cap
        ):
            last = len(first_tile) - 1
            seg_of_tile[seg_of_tile == last] = last - 1
            seg_blocks[-2] += seg_blocks[-1]
            seg_tiles_n[-2] += seg_tiles_n[-1]
            seg_blocks = seg_blocks[:-1]
            seg_tiles_n = seg_tiles_n[:-1]
            first_tile.pop()
        S = len(first_tile)
        first_tile = np.asarray(first_tile, np.int64)
        tiles_in_seg = np.bincount(seg_of_tile, minlength=S)
        seg_of_data = seg_of_tile[data_bd] if n_data_blocks else data_bd
        counts_data = np.bincount(seg_of_data, minlength=S)
        empty_tiles = np.nonzero(blocks_per_tile == 0)[0]
        seg_of_fill = seg_of_tile[empty_tiles]
        counts_fill = np.bincount(seg_of_fill, minlength=S)
        used = counts_data + counts_fill
        if S == 1:
            t_seg = int(t_dst)
            b_seg = int(used.max()) if t_dst else 0
            b_seg += (-b_seg) % 8
        else:  # quantized: a small provable program menu (see above).
            # BOTH grid dims snap up to shared menus — b_seg to the
            # 8-value bsp_bseg_menu(cap), t_seg to the <=16-value
            # bsp_tseg_menu(t_dst) (trailing output tiles are never
            # written or read, so the snap costs only padded output
            # rows). tools/aot_bsp_scale compiles the exact
            # (b_seg menu) x (t_seg menu) lattice, so every program a
            # segmented build can emit is pre-lowered.
            tiles_max = int(tiles_in_seg.max())
            t_seg = next(v for v in bsp_tseg_menu(t_dst) if v >= tiles_max)
            u_max = int(used.max())
            b_seg = next(v for v in bsp_bseg_menu(cap_eff) if v >= u_max)
        assert b_seg <= max_blocks  # the construction's SMEM invariant

        B_total = S * b_seg
        nbr = np.zeros((B_total, K, R), dtype=np.int32)
        wgt = np.zeros((B_total, K, R), dtype=np.float32)
        ldst = np.zeros((B_total, R), dtype=np.int32)
        key = np.zeros(B_total, dtype=np.int32)
        if n_data_blocks:
            seg_first = np.concatenate([[0], np.cumsum(counts_data)[:-1]])
            pos = (
                seg_of_data * b_seg
                + np.arange(n_data_blocks)
                - seg_first[seg_of_data]
            )
            nbr[pos], wgt[pos], ldst[pos] = nbr_d, wgt_d, ldst_d
            key[pos] = (data_bd - first_tile[seg_of_data]) * t_src + data_bs
        if len(empty_tiles):
            fill_first = np.concatenate(
                [[0], np.cumsum(counts_fill)[:-1]]
            )
            key[
                seg_of_fill * b_seg
                + counts_data[seg_of_fill]
                + np.arange(len(empty_tiles))
                - fill_first[seg_of_fill]
            ] = (empty_tiles - first_tile[seg_of_fill]) * t_src
        if B_total:
            idx = np.arange(B_total)
            seg_idx = idx // b_seg
            pad_mask = (idx % b_seg) >= used[seg_idx]
            key[pad_mask] = key[
                (seg_idx * b_seg + used[seg_idx] - 1)[pad_mask]
            ]

        if e_num:
            waste = B_total * K * R / max(e_num, 1)
            log.info(
                "bsp ELL: %d blocks [%d slots x %d rows] in %d segment(s) "
                "of %d, %d dst x %d src tiles, %d packed rows, slot waste "
                "%.2fx",
                B_total, K, R, S, b_seg, t_dst, t_src, n_rows, waste,
            )
        conv = (lambda a: a) if keep_host else jnp.asarray
        return BspEll(
            nbr=conv(nbr),
            wgt=conv(wgt),
            ldst=conv(ldst),
            blk_key=conv(key),
            v_num=int(v_num),
            dt=int(dt),
            vt=int(vt),
            src_num=int(src_num),
            n_seg=int(S),
            b_seg=int(b_seg),
            t_seg=int(t_seg),
            seg_tiles=tuple(int(t) for t in tiles_in_seg),
        )

    def aggregate(self, x: jax.Array, interpret: bool = None) -> jax.Array:
        """out[v] = sum over in-edges of w * x[src]; [V, f] -> [V, f]."""
        if interpret is None:
            # shared policy incl. the NTS_PALLAS_FORCE_COMPILED override —
            # topology AOT compiles must lower real Mosaic, not the
            # interpret emulation (round-3 near-miss: an AOT "verification"
            # of this kernel silently compiled the emulation)
            from neutronstarlite_tpu.ops.pallas_kernels import (
                pallas_interpret_default,
            )

            interpret = pallas_interpret_default()
        f = x.shape[1]
        n_src = self.src_num or self.v_num
        t_dst = -(-self.v_num // self.dt)
        t_src = -(-n_src // self.vt)
        B = self.nbr.shape[0]
        if B == 0 or f == 0:
            return jnp.zeros((self.v_num, f), x.dtype)
        xp = jnp.pad(x, ((0, t_src * self.vt - n_src), (0, 0)))
        # one pallas_call per SMEM-budget segment: identical shapes, so
        # ONE compiled program serves all n_seg calls (the program is
        # V-independent; only this Python loop grows with scale). Each
        # call's output is sliced to its segment's REAL tile range —
        # trailing output tiles (t_seg is quantized) are never read.
        t_seg = self.t_seg or t_dst
        b_seg = self.b_seg or B
        seg_tiles = self.seg_tiles or (t_dst,)
        outs = []
        for s in range(self.n_seg):
            sl = slice(s * b_seg, (s + 1) * b_seg)
            outs.append(
                _bsp_call(
                    self.blk_key[sl], self.nbr[sl], self.wgt[sl],
                    self.ldst[sl], xp,
                    dt=self.dt, vt=self.vt, t_dst=t_seg, t_src=t_src,
                    interpret=interpret,
                )[: seg_tiles[s] * self.dt]
            )
        out = outs[0] if self.n_seg == 1 else jnp.concatenate(outs, axis=0)
        return out[: self.v_num].astype(x.dtype)


def _bsp_kernel(key_ref, nbr_ref, wgt_ref, ldst_ref, x_ref, o_ref, *, dt, vt, t_src):
    """One block, gather-free BY CONSTRUCTION (Mosaic's only gather is an
    elementwise same-shape shuffle — a row gather cannot lower, see
    ops/pallas_kernels.py): the block's <=K*R edges are folded into a
    weights-valued one-hot matrix W [R, vt] (W[r, src_local] = w), so
    gather+scale+K-reduce is ONE bf16 MXU matmul ``W @ slab``; the row
    partial sums then land in the dst tile through the one-hot(ldst)
    scatter matmul (f32, dt*R*f — an order smaller than the main dot).
    The dst tile is zeroed on its first visit and accumulated in f32
    across its consecutive blocks."""
    b = pl.program_id(0)
    prev_dst = key_ref[jnp.maximum(b - 1, 0)] // t_src

    @pl.when(jnp.logical_or(b == 0, key_ref[b] // t_src != prev_dst))
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    x = x_ref[:]  # [vt, f]
    K, R = nbr_ref.shape[1], nbr_ref.shape[2]
    # The one-hot W build is the ONLY Mosaic-expressible gather form —
    # both direct alternatives were tried against the topology compiler
    # and die inside Mosaic (2026-07-31):
    # (a) pad the K*R slot indices to the slab height and use the legal
    #     same-shape take_along_axis: "Gather indices and result have
    #     different bitwidths" (i32 idx vs bf16 data), and with an f32
    #     view: "Not implemented: Multiple source vregs along gather
    #     dimension" — tpu.dynamic_gather only shuffles WITHIN one
    #     8-sublane vreg, so any cross-slab row fetch is out.
    # (b) the resident-table row gather (ops/pallas_kernels.py) — same
    #     root cause.
    # Numeric policy: W entries round to the slab dtype (bf16 in
    # production) so the main dot runs at full MXU rate; accumulation is
    # f32 (preferred_element_type) in-block and across blocks. The build
    # costs O(K * R * vt) VPU compares per block — the lever that makes
    # SMALLER src tiles attractive (the plan's bsp_vt_* sweep).
    col = lax.broadcasted_iota(jnp.int32, (R, vt), 1)
    w = jnp.zeros((R, vt), jnp.float32)
    for k in range(K):  # K is a small static constant: full unroll
        nb = nbr_ref[0, k, :]
        wb = wgt_ref[0, k, :]
        # srcs within one packed row are distinct, so += never collides
        w = w + jnp.where(col == nb[:, None], wb[:, None], 0.0)
    acc = lax.dot_general(
        w.astype(x.dtype), x, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [R, f]
    # ldst rides in [8-row, R] VMEM blocks (Mosaic tiling needs sublane
    # multiples of 8); this block's row is a dynamic sublane select
    ld = ldst_ref[b % 8, :]  # [R]
    onehot = (
        lax.broadcasted_iota(jnp.int32, (dt, R), 0) == ld[None, :]
    ).astype(jnp.float32)
    o_ref[:] += lax.dot_general(
        onehot, acc, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@functools.partial(
    jax.jit, static_argnames=("dt", "vt", "t_dst", "t_src", "interpret")
)
def _bsp_call(blk_key, nbr, wgt, ldst, xp, *, dt, vt, t_dst, t_src, interpret):
    B, K, R = nbr.shape
    f = xp.shape[1]
    if not _HAS_PLTPU:  # pragma: no cover - exercised only on minimal builds
        raise RuntimeError("pallas TPU backend unavailable for bsp_ell")
    grid_spec = pltpu.PrefetchScalarGridSpec(
        # ONE packed (dst_tile, src_tile) key drives both index maps —
        # SMEM holds ~1 MB of scalars total (see BspEll.blk_key)
        num_scalar_prefetch=1,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, K, R), lambda b, key: (b, 0, 0)),
            pl.BlockSpec((1, K, R), lambda b, key: (b, 0, 0)),
            # ldst blocks are 8 sublanes tall (Mosaic tiling); the kernel
            # selects its row via b % 8. Build pads B to a multiple of 8.
            pl.BlockSpec((8, R), lambda b, key: (b // 8, 0)),
            pl.BlockSpec((vt, f), lambda b, key: (key[b] % t_src, 0)),
        ],
        out_specs=pl.BlockSpec((dt, f), lambda b, key: (key[b] // t_src, 0)),
    )
    return pl.pallas_call(
        functools.partial(_bsp_kernel, dt=dt, vt=vt, t_src=t_src),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t_dst * dt, f), jnp.float32),
        interpret=interpret,
    )(blk_key, nbr, wgt, ldst, xp)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BspEllPair:
    """Forward (CSC) + backward (CSR) block tables, custom_vjp-paired."""

    fwd: BspEll
    bwd: BspEll

    @staticmethod
    def from_host(
        g: CSCGraph,
        dt: int = 0,
        vt: int = DEFAULT_VT,
        k_slots: int = 0,
        r_rows: int = DEFAULT_R,
    ) -> "BspEllPair":
        # dt (dst-tile height: the scatter matmul's cost axis) and K
        # (slots/row: trades rows-per-edge against per-row padding) are
        # env-tunable so on-chip A/Bs need no code edits:
        # NTS_BSP_DT / NTS_BSP_K
        dt, k_slots = resolve_bsp_knobs(dt, k_slots)
        fwd = BspEll.build(
            g.v_num, g.column_offset, g.row_indices, g.edge_weight_forward,
            dt, vt, k_slots, r_rows,
        )
        bwd = BspEll.build(
            g.v_num, g.row_offset, g.column_indices, g.edge_weight_backward,
            dt, vt, k_slots, r_rows,
        )
        return BspEllPair(fwd=fwd, bwd=bwd)


@jax.custom_vjp
def _bsp_aggregate(fwd: BspEll, bwd: BspEll, x: jax.Array):
    return fwd.aggregate(x)


def _bsp_aggregate_fwd(fwd, bwd, x):
    return fwd.aggregate(x), (fwd, bwd)


def _bsp_aggregate_bwd(res, g):
    from neutronstarlite_tpu.ops.segment import zero_cotangent

    fwd, bwd = res
    zero = jax.tree.map(zero_cotangent, (fwd, bwd))
    return (*zero, bwd.aggregate(g))


_bsp_aggregate.defvjp(_bsp_aggregate_fwd, _bsp_aggregate_bwd)


def bsp_gather_dst_from_src(pair: BspEllPair, x: jax.Array) -> jax.Array:
    """Streamed block-sparse weighted aggregation (custom_vjp-paired)."""
    return _bsp_aggregate(pair.fwd, pair.bwd, x)


def bsp_gather_src_from_dst(pair: BspEllPair, y: jax.Array) -> jax.Array:
    """The CSR direction as a forward op."""
    return _bsp_aggregate(pair.bwd, pair.fwd, y)
