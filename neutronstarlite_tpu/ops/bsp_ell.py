"""Block-sparse (dst-tile, src-tile) streamed Pallas aggregation.

The regime ladder for the fused neighbor aggregation on one chip:

1. [V, f] fits VMEM            -> ops/pallas_kernels.py (table resident)
2. [V, 128] fits VMEM          -> same kernel, feature-column chunked
3. V itself is beyond VMEM     -> THIS module (V ~ 10x Reddit and up)

Here neither the feature table nor a 128-wide column of it fits on-chip,
so the kernel streams BOTH sides: vertices are cut into destination tiles
of ``dt`` rows and source tiles of ``vt`` rows; edges are packed into
fixed-shape blocks, each block belonging to one (dst tile, src tile)
pair. The pallas grid walks blocks sorted by destination tile with the
[dt, f] output tile living in VMEM across every consecutive block of its
tile (zeroed on first visit, spilled to HBM when the tile changes — the
revisiting-output accumulation pattern), while the [vt, f] source slab is
DMA-streamed per block via a scalar-prefetched block->tile map
(``pltpu.PrefetchScalarGridSpec``). HBM traffic per application:
O(E * 8 B) table reads + O(sum over dst tiles of present src tiles *
vt * f) slab streams + O(V * f) output writes — versus O(E * f) random
HBM gathers for the plain layout past VMEM.

Block layout: each block is ``R`` rows of ``K`` slots. A row is (a piece
of) one destination's in-edge run within one source tile: runs longer
than K split into several rows (legal because every row's partial sum is
accumulated). Rows store tile-LOCAL neighbor ids ``nbr`` [B, K, R] and
weights ``wgt`` [B, K, R] (the K-major layout keeps R on the 128-lane
axis), plus the row's tile-local destination ``ldst`` [B, R]. Padding
rows/slots carry weight 0 and index 0, contributing nothing.

The per-block combine is scatter-free BY CONSTRUCTION: row partial sums
``acc`` [R, f] land in the output tile through a one-hot MXU matmul —
``onehot(ldst) [dt, R] @ acc [R, f]`` — the TPU-idiomatic scatter (the
MXU is the only unit that reorders data at full bandwidth; per-row
dynamic stores would serialize). This is the cost that makes regime 2
preferable whenever the row count allows: the matmul spends
``dt * f * 2`` FLOPs per packed ROW (independent of K), so at Reddit
scale (~7M rows, dt=512, f=602) it would burn ~4.2 TFLOP per application
— slower than keeping a 128-wide column slab resident and gathering
from VMEM. Past ~375k-row slabs there is no resident option; the matmul
price buys streaming locality the plain layout cannot offer, and the
multi-chip path (parallel/dist_ell.py) re-enters regime 1/2 per shard by
cutting V by P. Reference analog: the shared-memory tiled CUDA
aggregation (cuda/ntsCUDAFuseKernel.cuh:154-208) — re-derived for a
memory system where the accumulator tile, not the source tile, is the
scarce on-chip resource.

Forward/backward pairing follows ops/ell.py: the backward is the same
kernel over the transposed (CSR) layout, one ``custom_vjp``. Numeric
policy: f32 row products, f32 accumulation (in-block and across blocks),
one cast at the end. Off-TPU the kernel runs in interpret mode (tests).
"""

from __future__ import annotations

import dataclasses
import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

from neutronstarlite_tpu.graph.storage import CSCGraph
from neutronstarlite_tpu.utils.logging import get_logger

try:  # pallas TPU backend may be absent on pure-CPU builds
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    _HAS_PLTPU = False

log = get_logger("bsp_ell")

DEFAULT_DT = 512  # dst tile rows (the VMEM-resident accumulator height)
DEFAULT_VT = 4096  # src tile rows (the streamed slab height)
DEFAULT_K = 8  # slots per packed row
DEFAULT_R = 128  # rows per block (the 128-lane axis of the tables)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BspEll:
    """One direction's packed block tables (see module docstring)."""

    nbr: jax.Array  # [B, K, R] int32 tile-local neighbor ids
    wgt: jax.Array  # [B, K, R] f32 (0 on padding)
    ldst: jax.Array  # [B, R] int32 tile-local destination row
    # ONE packed per-block tile key: dst_tile * t_src + src_tile. The key
    # array is the kernel's scalar-prefetch operand and lives in SMEM
    # (1 MB): two separate [B] int32 maps overflowed it at full Reddit
    # scale (B ~ 141-175k -> 552-684 KB EACH, AOT RESOURCE_EXHAUSTED,
    # docs/perf_runs/round3/aot_eager_bsp2.json); packed, one array fits
    # with room to ~250k blocks
    blk_key: jax.Array  # [B] int32 packed (dst_tile, src_tile)
    v_num: int = dataclasses.field(metadata=dict(static=True))
    dt: int = dataclasses.field(metadata=dict(static=True))
    vt: int = dataclasses.field(metadata=dict(static=True))
    # RECTANGULAR form (the distributed per-shard case: dst rows are one
    # device's vp vertices, srcs index the full all_gathered [P*vp, f]
    # slab): src_num sizes the source tiling independently of v_num.
    # 0 = square (src space == dst space), the single-chip default.
    src_num: int = dataclasses.field(default=0, metadata=dict(static=True))

    @staticmethod
    def build(
        v_num: int,
        offsets: np.ndarray,  # [V+1] per-dst adjacency offsets
        adj: np.ndarray,  # [E] source ids, grouped by dst
        weights: np.ndarray,  # [E]
        dt: int = DEFAULT_DT,
        vt: int = DEFAULT_VT,
        k_slots: int = DEFAULT_K,
        r_rows: int = DEFAULT_R,
        src_num: int = 0,  # 0 = square; else rectangular (adj < src_num)
    ) -> "BspEll":
        K, R = int(k_slots), int(r_rows)
        n_src = int(src_num) or int(v_num)
        t_dst = -(-v_num // dt)
        t_src = -(-n_src // vt)
        e_num = len(adj)
        deg = np.diff(offsets).astype(np.int64)
        dst_of_edge = np.repeat(np.arange(v_num, dtype=np.int64), deg)
        adj = np.asarray(adj, dtype=np.int64)
        weights = np.asarray(weights, dtype=np.float32)

        if e_num:
            # group edges by (dst tile, src tile); edges arrive dst-grouped,
            # so a stable sort by the pair key keeps dst ascending per group.
            # The key space is tiny (t_dst * t_src ~ 13k at full Reddit
            # scale), so the native O(E) counting sort applies directly —
            # measured neutral on wall time at full scale (the per-edge
            # fancy-index fills dominate the build, 274 s vs 276 s) but it
            # avoids argsort's O(E) int64 temp at peak
            from neutronstarlite_tpu import native as native_rt

            key = (dst_of_edge // dt) * t_src + adj // vt
            # key-space bound: the counting sort allocates an int64
            # histogram of t_dst * t_src entries — past ~16M keys (128 MB)
            # argsort is the better trade, long before the int32 limit
            if native_rt.available() and t_dst * t_src < 2**24:
                order = native_rt.sort_by_tile(
                    key.astype(np.int32, copy=False), t_dst * t_src
                )
            else:
                order = np.argsort(key, kind="stable")
            ks, ds = key[order], dst_of_edge[order]
            ss, ws = adj[order], weights[order]

            # (group, dst) runs -> packed rows of <= K slots each
            change = (ks[1:] != ks[:-1]) | (ds[1:] != ds[:-1])
            run_start = np.nonzero(np.concatenate([[True], change]))[0]
            run_len = np.diff(np.concatenate([run_start, [e_num]]))
            run_key, run_dst = ks[run_start], ds[run_start]
            rows_of_run = -(-run_len // K)
            n_rows = int(rows_of_run.sum())
            row_of_first = np.concatenate([[0], np.cumsum(rows_of_run)[:-1]])
            row_run = np.repeat(np.arange(len(run_start)), rows_of_run)
            row_key = run_key[row_run]
            row_dst = run_dst[row_run]

            # rows are key-sorted; rank within key -> (block, slot)
            key_change = np.nonzero(
                np.concatenate([[True], row_key[1:] != row_key[:-1]])
            )[0]
            first_row_of_key = np.repeat(
                key_change,
                np.diff(np.concatenate([key_change, [n_rows]])),
            )
            rank = np.arange(n_rows) - first_row_of_key
            # cumulative block count at each key group's start
            grp_rows = np.diff(np.concatenate([key_change, [n_rows]]))
            grp_blocks = -(-grp_rows // R)
            grp_block_start = np.concatenate([[0], np.cumsum(grp_blocks)[:-1]])
            blocks_before = np.repeat(grp_block_start, grp_rows)
            row_block = blocks_before + rank // R
            row_slot = rank % R
            n_data_blocks = int(grp_blocks.sum())
        else:
            n_rows = n_data_blocks = 0
            row_block = row_slot = row_dst = row_key = np.zeros(0, np.int64)

        # every dst tile needs >= 1 block so its output tile gets zeroed
        # (an unvisited pallas output block would be uninitialized memory)
        present = np.zeros(t_dst, dtype=bool)
        if n_data_blocks:
            blk_first = np.nonzero(
                np.concatenate([[True], row_block[1:] != row_block[:-1]])
            )[0]
            data_bd = (row_key[blk_first] // t_src).astype(np.int32)
            data_bs = (row_key[blk_first] % t_src).astype(np.int32)
            present[data_bd] = True
        else:
            data_bd = data_bs = np.zeros(0, np.int32)
        filler = np.nonzero(~present)[0].astype(np.int32)
        B = n_data_blocks + len(filler)

        nbr = np.zeros((B, K, R), dtype=np.int32)
        wgt = np.zeros((B, K, R), dtype=np.float32)
        ldst = np.zeros((B, R), dtype=np.int32)
        bd = np.concatenate([data_bd, filler])
        bs = np.concatenate([data_bs, np.zeros(len(filler), np.int32)])

        if e_num:
            src_local = (ss - (ss // vt) * vt).astype(np.int32)
            run_ldst = (run_dst - (run_dst // dt) * dt).astype(np.int32)
            if native_rt.available():
                # one OpenMP pass over runs (the three O(E) fancy-index
                # scatters below were the measured build bottleneck)
                native_rt.fill_bsp(
                    run_start, run_len, row_of_first, run_ldst,
                    row_block, row_slot, src_local,
                    np.ascontiguousarray(ws, np.float32), K, R,
                    nbr, wgt, ldst,
                )
            else:
                # per-edge placement: row-relative slot position
                run_of_edge = np.repeat(np.arange(len(run_start)), run_len)
                off = np.arange(e_num) - run_start[run_of_edge]
                e_row = row_of_first[run_of_edge] + off // K
                p = off % K
                b_e = row_block[e_row]
                s_e = row_slot[e_row]
                nbr[b_e, p, s_e] = src_local
                wgt[b_e, p, s_e] = ws
                ldst[row_block, row_slot] = run_ldst[row_run]
            waste = B * K * R / max(e_num, 1)
            log.info(
                "bsp ELL: %d blocks [%d slots x %d rows], %d dst x %d src "
                "tiles, %d packed rows, slot waste %.2fx",
                B, K, R, t_dst, t_src, n_rows, waste,
            )

        # blocks sorted by dst tile (stable: data blocks keep their src-tile
        # grouping) so output-tile revisits are consecutive
        order_b = np.argsort(bd, kind="stable")
        nbr, wgt, ldst = nbr[order_b], wgt[order_b], ldst[order_b]
        bd, bs = bd[order_b], bs[order_b]
        # pad B to a multiple of 8: the kernel reads ldst through 8-row
        # VMEM blocks. Pad blocks carry weight 0 and the LAST dst tile
        # (keeps bd nondecreasing, so the zero-init revisit logic holds)
        pad_b = (-B) % 8
        if pad_b:
            nbr = np.concatenate([nbr, np.zeros((pad_b, K, R), np.int32)])
            wgt = np.concatenate([wgt, np.zeros((pad_b, K, R), np.float32)])
            ldst = np.concatenate([ldst, np.zeros((pad_b, R), np.int32)])
            bd = np.concatenate(
                [bd, np.full(pad_b, bd[-1] if B else 0, np.int32)]
            )
            bs = np.concatenate([bs, np.zeros(pad_b, np.int32)])
        return BspEll(
            nbr=jnp.asarray(nbr),
            wgt=jnp.asarray(wgt),
            ldst=jnp.asarray(ldst),
            blk_key=jnp.asarray(
                bd.astype(np.int32) * np.int32(t_src) + bs.astype(np.int32)
            ),
            v_num=int(v_num),
            dt=int(dt),
            vt=int(vt),
            src_num=int(src_num),
        )

    def aggregate(self, x: jax.Array, interpret: bool = None) -> jax.Array:
        """out[v] = sum over in-edges of w * x[src]; [V, f] -> [V, f]."""
        if interpret is None:
            # shared policy incl. the NTS_PALLAS_FORCE_COMPILED override —
            # topology AOT compiles must lower real Mosaic, not the
            # interpret emulation (round-3 near-miss: an AOT "verification"
            # of this kernel silently compiled the emulation)
            from neutronstarlite_tpu.ops.pallas_kernels import (
                pallas_interpret_default,
            )

            interpret = pallas_interpret_default()
        f = x.shape[1]
        n_src = self.src_num or self.v_num
        t_dst = -(-self.v_num // self.dt)
        t_src = -(-n_src // self.vt)
        B = self.nbr.shape[0]
        if B == 0 or f == 0:
            return jnp.zeros((self.v_num, f), x.dtype)
        xp = jnp.pad(x, ((0, t_src * self.vt - n_src), (0, 0)))
        out = _bsp_call(
            self.blk_key, self.nbr, self.wgt, self.ldst, xp,
            dt=self.dt, vt=self.vt, t_dst=t_dst, t_src=t_src,
            interpret=interpret,
        )
        return out[: self.v_num].astype(x.dtype)


def _bsp_kernel(key_ref, nbr_ref, wgt_ref, ldst_ref, x_ref, o_ref, *, dt, vt, t_src):
    """One block, gather-free BY CONSTRUCTION (Mosaic's only gather is an
    elementwise same-shape shuffle — a row gather cannot lower, see
    ops/pallas_kernels.py): the block's <=K*R edges are folded into a
    weights-valued one-hot matrix W [R, vt] (W[r, src_local] = w), so
    gather+scale+K-reduce is ONE bf16 MXU matmul ``W @ slab``; the row
    partial sums then land in the dst tile through the one-hot(ldst)
    scatter matmul (f32, dt*R*f — an order smaller than the main dot).
    The dst tile is zeroed on its first visit and accumulated in f32
    across its consecutive blocks."""
    b = pl.program_id(0)
    prev_dst = key_ref[jnp.maximum(b - 1, 0)] // t_src

    @pl.when(jnp.logical_or(b == 0, key_ref[b] // t_src != prev_dst))
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    x = x_ref[:]  # [vt, f]
    K, R = nbr_ref.shape[1], nbr_ref.shape[2]
    # The one-hot W build is the ONLY Mosaic-expressible gather form —
    # both direct alternatives were tried against the topology compiler
    # and die inside Mosaic (2026-07-31):
    # (a) pad the K*R slot indices to the slab height and use the legal
    #     same-shape take_along_axis: "Gather indices and result have
    #     different bitwidths" (i32 idx vs bf16 data), and with an f32
    #     view: "Not implemented: Multiple source vregs along gather
    #     dimension" — tpu.dynamic_gather only shuffles WITHIN one
    #     8-sublane vreg, so any cross-slab row fetch is out.
    # (b) the resident-table row gather (ops/pallas_kernels.py) — same
    #     root cause.
    # Numeric policy: W entries round to the slab dtype (bf16 in
    # production) so the main dot runs at full MXU rate; accumulation is
    # f32 (preferred_element_type) in-block and across blocks. The build
    # costs O(K * R * vt) VPU compares per block — the lever that makes
    # SMALLER src tiles attractive (the plan's bsp_vt_* sweep).
    col = lax.broadcasted_iota(jnp.int32, (R, vt), 1)
    w = jnp.zeros((R, vt), jnp.float32)
    for k in range(K):  # K is a small static constant: full unroll
        nb = nbr_ref[0, k, :]
        wb = wgt_ref[0, k, :]
        # srcs within one packed row are distinct, so += never collides
        w = w + jnp.where(col == nb[:, None], wb[:, None], 0.0)
    acc = lax.dot_general(
        w.astype(x.dtype), x, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [R, f]
    # ldst rides in [8-row, R] VMEM blocks (Mosaic tiling needs sublane
    # multiples of 8); this block's row is a dynamic sublane select
    ld = ldst_ref[b % 8, :]  # [R]
    onehot = (
        lax.broadcasted_iota(jnp.int32, (dt, R), 0) == ld[None, :]
    ).astype(jnp.float32)
    o_ref[:] += lax.dot_general(
        onehot, acc, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@functools.partial(
    jax.jit, static_argnames=("dt", "vt", "t_dst", "t_src", "interpret")
)
def _bsp_call(blk_key, nbr, wgt, ldst, xp, *, dt, vt, t_dst, t_src, interpret):
    B, K, R = nbr.shape
    f = xp.shape[1]
    if not _HAS_PLTPU:  # pragma: no cover - exercised only on minimal builds
        raise RuntimeError("pallas TPU backend unavailable for bsp_ell")
    grid_spec = pltpu.PrefetchScalarGridSpec(
        # ONE packed (dst_tile, src_tile) key drives both index maps —
        # SMEM holds ~1 MB of scalars total (see BspEll.blk_key)
        num_scalar_prefetch=1,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, K, R), lambda b, key: (b, 0, 0)),
            pl.BlockSpec((1, K, R), lambda b, key: (b, 0, 0)),
            # ldst blocks are 8 sublanes tall (Mosaic tiling); the kernel
            # selects its row via b % 8. Build pads B to a multiple of 8.
            pl.BlockSpec((8, R), lambda b, key: (b // 8, 0)),
            pl.BlockSpec((vt, f), lambda b, key: (key[b] % t_src, 0)),
        ],
        out_specs=pl.BlockSpec((dt, f), lambda b, key: (key[b] // t_src, 0)),
    )
    return pl.pallas_call(
        functools.partial(_bsp_kernel, dt=dt, vt=vt, t_src=t_src),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t_dst * dt, f), jnp.float32),
        interpret=interpret,
    )(blk_key, nbr, wgt, ldst, xp)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BspEllPair:
    """Forward (CSC) + backward (CSR) block tables, custom_vjp-paired."""

    fwd: BspEll
    bwd: BspEll

    @staticmethod
    def from_host(
        g: CSCGraph,
        dt: int = 0,
        vt: int = DEFAULT_VT,
        k_slots: int = 0,
        r_rows: int = DEFAULT_R,
    ) -> "BspEllPair":
        # dt (dst-tile height: the scatter matmul's cost axis) and K
        # (slots/row: trades rows-per-edge against per-row padding) are
        # env-tunable so on-chip A/Bs need no code edits:
        # NTS_BSP_DT / NTS_BSP_K
        dt = dt or int(os.environ.get("NTS_BSP_DT", DEFAULT_DT))
        k_slots = k_slots or int(os.environ.get("NTS_BSP_K", DEFAULT_K))
        fwd = BspEll.build(
            g.v_num, g.column_offset, g.row_indices, g.edge_weight_forward,
            dt, vt, k_slots, r_rows,
        )
        bwd = BspEll.build(
            g.v_num, g.row_offset, g.column_indices, g.edge_weight_backward,
            dt, vt, k_slots, r_rows,
        )
        return BspEllPair(fwd=fwd, bwd=bwd)


@jax.custom_vjp
def _bsp_aggregate(fwd: BspEll, bwd: BspEll, x: jax.Array):
    return fwd.aggregate(x)


def _bsp_aggregate_fwd(fwd, bwd, x):
    return fwd.aggregate(x), (fwd, bwd)


def _bsp_aggregate_bwd(res, g):
    from neutronstarlite_tpu.ops.segment import zero_cotangent

    fwd, bwd = res
    zero = jax.tree.map(zero_cotangent, (fwd, bwd))
    return (*zero, bwd.aggregate(g))


_bsp_aggregate.defvjp(_bsp_aggregate_fwd, _bsp_aggregate_bwd)


def bsp_gather_dst_from_src(pair: BspEllPair, x: jax.Array) -> jax.Array:
    """Streamed block-sparse weighted aggregation (custom_vjp-paired)."""
    return _bsp_aggregate(pair.fwd, pair.bwd, x)


def bsp_gather_src_from_dst(pair: BspEllPair, y: jax.Array) -> jax.Array:
    """The CSR direction as a forward op."""
    return _bsp_aggregate(pair.bwd, pair.fwd, y)
