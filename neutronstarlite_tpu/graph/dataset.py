"""Feature / label / mask IO — the GNNDatum equivalent.

Reference: core/ntsDataloador.hpp:29-305. File formats (readFeature_Label_Mask,
:156-303): feature file lines are ``ID f0 f1 ... f_{d-1}``; label file lines
``ID label``; mask file lines ``ID train|val|eval|test`` with train=0,
val/eval=1, test=2. ``random_generate`` (:63) fills ones-features, random
labels, and mask = id % 3 when files are absent.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

import numpy as np

from neutronstarlite_tpu.utils.logging import get_logger

log = get_logger("dataset")

MASK_TRAIN = 0
MASK_VAL = 1
MASK_TEST = 2

_MASK_NAMES = {"train": MASK_TRAIN, "val": MASK_VAL, "eval": MASK_VAL, "test": MASK_TEST}


@dataclasses.dataclass
class GNNDatum:
    """Per-vertex features, labels, masks for the full graph (host NumPy)."""

    feature: np.ndarray  # [V, f0] float32
    label: np.ndarray  # [V] int32
    mask: np.ndarray  # [V] int32 in {0=train, 1=val, 2=test}

    @property
    def v_num(self) -> int:
        return self.feature.shape[0]

    @property
    def feature_size(self) -> int:
        return self.feature.shape[1]

    @staticmethod
    def random_generate(
        v_num: int, feature_size: int, label_num: int, seed: int = 0
    ) -> "GNNDatum":
        """Deterministic stand-in data (reference: random_generate, :63-76
        uses ones-features, rand labels, mask = i % 3)."""
        rng = np.random.default_rng(seed)
        feature = rng.standard_normal((v_num, feature_size), dtype=np.float32) * 0.1
        label = rng.integers(0, label_num, size=v_num, dtype=np.int32)
        mask = (np.arange(v_num) % 3).astype(np.int32)
        return GNNDatum(feature=feature, label=label, mask=mask)

    @staticmethod
    def read_feature_label_mask(
        feature_file: str,
        label_file: str,
        mask_file: str,
        v_num: int,
        feature_size: int,
        seed: int = 0,
    ) -> "GNNDatum":
        """Load the three text files; any missing file falls back to the
        random_generate fill for that field (the reference prints "open ...
        fail!" and returns; we degrade per-field instead so real labels can be
        paired with generated features when a dataset ships without features)."""
        rng = np.random.default_rng(seed)

        def fallback(kind: str, path: str):
            # loud, because a typo'd path otherwise trains on fake data and
            # the only symptom is a quietly wrong accuracy (the reference
            # prints "open ... fail!", GNNDatum::readF*, ntsDataloador.hpp)
            if path:
                log.warning(
                    "%s file %r missing — generating random %s", kind, path, kind
                )

        if feature_file and os.path.exists(feature_file):
            feature = _read_feature_table(feature_file, v_num, feature_size)
        else:
            fallback("feature", feature_file)
            feature = rng.standard_normal((v_num, feature_size), dtype=np.float32) * 0.1

        if label_file and os.path.exists(label_file):
            label = _read_id_value_table(label_file, v_num).astype(np.int32)
        else:
            fallback("label", label_file)
            label = rng.integers(0, 2, size=v_num, dtype=np.int32)

        if mask_file and os.path.exists(mask_file):
            mask = _read_mask_table(mask_file, v_num)
        else:
            fallback("mask", mask_file)
            mask = (np.arange(v_num) % 3).astype(np.int32)

        return GNNDatum(feature=feature, label=label, mask=mask)

    @staticmethod
    def read_feature_label_mask_ogb(
        feature_file: str,
        label_file: str,
        mask_dir: str,
        v_num: int,
        feature_size: int,
        seed: int = 0,
    ) -> "GNNDatum":
        """OGB-converted layout (readFeature_Label_Mask_OGB,
        core/ntsDataloador.hpp:223-303): the feature file is one
        comma-separated line of ``feature_size`` floats per vertex (row i =
        vertex i, no ID column), the label file one bare integer per
        vertex, and ``mask_dir`` a DIRECTORY holding train.csv / valid.csv
        / test.csv, each listing member vertex ids. Vertices in none of the
        three lists get mask 3 (excluded from every split — the
        reference's "unknown" value). Missing files degrade per-field with
        the same loud fallback as the standard reader."""
        rng = np.random.default_rng(seed)

        if feature_file and os.path.exists(feature_file):
            feature = np.loadtxt(
                feature_file, dtype=np.float32, delimiter=",", ndmin=2
            )
            if feature.shape != (v_num, feature_size):
                raise ValueError(
                    f"{feature_file}: expected {(v_num, feature_size)}, "
                    f"got {feature.shape}"
                )
        else:
            if feature_file:
                log.warning(
                    "feature file %r missing — generating random features",
                    feature_file,
                )
            feature = (
                rng.standard_normal((v_num, feature_size), dtype=np.float32) * 0.1
            )

        if label_file and os.path.exists(label_file):
            label = np.loadtxt(label_file, dtype=np.int64).reshape(-1)
            if label.shape[0] != v_num:
                raise ValueError(
                    f"{label_file}: expected {v_num} labels, got {label.shape[0]}"
                )
            label = label.astype(np.int32)
        else:
            if label_file:
                log.warning(
                    "label file %r missing — generating random labels", label_file
                )
            label = rng.integers(0, 2, size=v_num, dtype=np.int32)

        mask = np.full(v_num, 3, dtype=np.int32)  # 3 = in no split
        names = (("train.csv", MASK_TRAIN), ("valid.csv", MASK_VAL),
                 ("test.csv", MASK_TEST))
        if mask_dir and os.path.isdir(mask_dir):
            for name, val in names:
                p = os.path.join(mask_dir, name)
                if not os.path.exists(p):
                    log.warning("mask split %r missing — split left empty", p)
                    continue
                ids = np.loadtxt(p, dtype=np.int64, delimiter=",", ndmin=1)
                mask[ids.reshape(-1)] = val
        else:
            if mask_dir:
                log.warning(
                    "mask dir %r missing — falling back to mask = id %% 3",
                    mask_dir,
                )
            mask = (np.arange(v_num) % 3).astype(np.int32)

        return GNNDatum(feature=feature, label=label, mask=mask)

    def label_num(self) -> int:
        return int(self.label.max()) + 1

    def mask_tensor(self, which: int) -> np.ndarray:
        return (self.mask == which).astype(np.float32)


def _read_feature_table(path: str, v_num: int, feature_size: int) -> np.ndarray:
    if path.endswith(".npy"):
        # binary fast path for large feature tables (Reddit-scale text
        # tables are >1 GB; prep.py emits .npy for them)
        out = np.load(path).astype(np.float32, copy=False)
        if out.shape != (v_num, feature_size):
            raise ValueError(
                f"{path}: expected shape {(v_num, feature_size)}, got {out.shape}"
            )
        return out
    data = np.loadtxt(path, dtype=np.float32)
    if data.ndim == 1:
        data = data.reshape(1, -1)
    if data.shape[1] != feature_size + 1:
        raise ValueError(
            f"{path}: expected {feature_size + 1} columns (ID + features), got {data.shape[1]}"
        )
    out = np.zeros((v_num, feature_size), dtype=np.float32)
    ids = data[:, 0].astype(np.int64)
    out[ids] = data[:, 1:]
    return out


def _read_id_value_table(path: str, v_num: int) -> np.ndarray:
    data = np.loadtxt(path, dtype=np.int64)
    if data.ndim == 1:
        data = data.reshape(1, -1)
    out = np.zeros(v_num, dtype=np.int64)
    out[data[:, 0]] = data[:, 1]
    return out


def _read_mask_table(path: str, v_num: int) -> np.ndarray:
    out = np.full(v_num, MASK_TEST, dtype=np.int32)
    with open(path) as fh:
        for line in fh:
            parts = line.split()
            if len(parts) < 2:
                continue
            out[int(parts[0])] = _MASK_NAMES.get(parts[1].strip().lower(), MASK_TEST)
    return out
