"""Canonical graph content digest for the autotuner decision cache.

``graph_digest(g)`` hashes the graph's STRUCTURE — per-destination
canonicalized neighbor multisets — into one sha256 hex string, the
per-graph half of the tune-cache key (neutronstarlite_tpu/tune/cache.py).

Canonicalization matters: the native OpenMP adjacency builder orders tie
edges (same destination, concurrent writers) nondeterministically per
BUILD, so two byte-identical edge files can yield CSC arrays that differ
in within-segment edge order (the PR 7 deflake root cause,
tests/test_bench.py::_canonical_csc). A digest over the raw CSC arrays
would therefore change across builds of the SAME graph and turn every
cache lookup into a spurious miss. Sorting each destination segment by
source id (a stable lexsort over (dst, src)) makes the digest a function
of the neighbor MULTISET only — duplicate edges keep their multiplicity,
order wobble disappears, and the native and NumPy builders agree bitwise
(pinned by tests/test_graph.py::test_graph_digest_native_numpy_agree).

Edge weights are deliberately NOT hashed: the weight mode (gcn_norm /
ones) is a property of the algorithm family — itself a separate cache-key
field — and the float pipeline differs between the native (C) and NumPy
builders in ways that could break bitwise equality without changing the
graph.
"""

from __future__ import annotations

import hashlib

import numpy as np


def graph_digest(g) -> str:
    """sha256 hex digest of a CSCGraph's canonicalized structure.

    Hash input: (v_num, e_num, in-degree offsets, and the CSC source ids
    sorted within each destination segment) — all cast to fixed-width
    little-endian dtypes so builder-dependent array dtypes (int32 vs
    int64 offsets) cannot change the digest either.
    """
    dst = np.asarray(g.dst_of_edge, dtype=np.int64)
    src = np.asarray(g.row_indices, dtype=np.int64)
    # stable sort by (dst, src): dst_of_edge is already non-decreasing,
    # so this only canonicalizes the within-segment tie order
    perm = np.lexsort((src, dst))
    h = hashlib.sha256()
    h.update(np.array([g.v_num, g.e_num], dtype="<i8").tobytes())
    h.update(np.asarray(g.column_offset, dtype="<i8").tobytes())
    h.update(src[perm].astype("<i8").tobytes())
    return h.hexdigest()
