from neutronstarlite_tpu.graph.storage import (
    CSCGraph,
    build_graph,
    load_edges,
    load_edges_binary,
    load_edges_text,
    gcn_norm_weights,
    partition_offsets,
)
from neutronstarlite_tpu.graph.dataset import GNNDatum
from neutronstarlite_tpu.graph.digest import graph_digest
from neutronstarlite_tpu.graph.synthetic import synthetic_power_law_graph

__all__ = [
    "CSCGraph",
    "build_graph",
    "load_edges",
    "load_edges_binary",
    "load_edges_text",
    "gcn_norm_weights",
    "partition_offsets",
    "GNNDatum",
    "graph_digest",
    "synthetic_power_law_graph",
]
