"""Synthetic graph generators for tests and benchmarks.

The reference benchmarks on Reddit (V=232965, |E|~=114.6M binary edges,
gcn_reddit_full.cfg) but ships only the conversion scripts, not the data.
For benchmarking at the same scale we generate a power-law graph with matching
vertex/edge counts, plus a small community (planted-partition) graph whose
labels are recoverable by a GCN — the accuracy-convergence oracle the
reference gets from Cora (SURVEY.md section 4.7).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def synthetic_power_law_graph(
    v_num: int, e_num: int, seed: int = 0, self_loops: bool = True
) -> Tuple[np.ndarray, np.ndarray]:
    """Edge list with power-law-ish degree distribution (preferential-attachment
    flavored, vectorized): endpoints drawn from a Zipf-like distribution over
    vertices. Returns (src, dst) uint32 arrays, self-loops appended when asked
    (the reference trains on `.edge.self` files which include them)."""
    rng = np.random.default_rng(seed)
    n_rand = e_num - (v_num if self_loops else 0)
    if n_rand < 0:
        raise ValueError("e_num smaller than self-loop count")
    # Zipf-ish endpoint sampling via inverse-CDF on u^a mapping; a<1 skews mass
    # toward low vertex ids, giving hub vertices like real social graphs.
    a = 3.0
    src = (v_num * rng.random(n_rand) ** a).astype(np.uint32)
    dst = (v_num * rng.random(n_rand) ** a).astype(np.uint32)
    # random permutation of vertex ids decorrelates hubs from partition ranges
    perm = rng.permutation(v_num).astype(np.uint32)
    src, dst = perm[src], perm[dst]
    if self_loops:
        loops = np.arange(v_num, dtype=np.uint32)
        src = np.concatenate([src, loops])
        dst = np.concatenate([dst, loops])
    return src, dst


def planted_partition_graph(
    v_num: int,
    classes: int,
    avg_degree: float,
    p_in: float = 0.9,
    feature_size: int = 16,
    feature_noise: float = 1.0,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Community graph + noisy class-indicator features.

    Returns (src, dst, feature [V,f], label [V]). Within-class edges with
    probability mass p_in; features are a class embedding + Gaussian noise, so
    a 2-layer GCN reaches high accuracy quickly — the convergence oracle.
    """
    rng = np.random.default_rng(seed)
    label = rng.integers(0, classes, size=v_num, dtype=np.int32)
    e_num = int(v_num * avg_degree)
    src = rng.integers(0, v_num, size=e_num, dtype=np.uint32)
    same = rng.random(e_num) < p_in
    # choose dst: same-class vertex when `same` else uniform
    by_class = [np.where(label == c)[0] for c in range(classes)]
    dst = rng.integers(0, v_num, size=e_num, dtype=np.uint32)
    for c in range(classes):
        idx = np.where(same & (label[src] == c))[0]
        members = by_class[c]
        if len(members) and len(idx):
            dst[idx] = members[rng.integers(0, len(members), size=len(idx))]
    loops = np.arange(v_num, dtype=np.uint32)
    src = np.concatenate([src, loops])
    dst = np.concatenate([dst, loops])

    class_emb = rng.standard_normal((classes, feature_size)).astype(np.float32)
    feature = class_emb[label] + feature_noise * rng.standard_normal(
        (v_num, feature_size)
    ).astype(np.float32)
    return src, dst, feature, label
