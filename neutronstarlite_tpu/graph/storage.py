"""Host-side graph storage: CSC/CSR build, GCN normalization, partitioning.

This replaces the reference's Graph<EdgeData> loading/partitioning machinery
(core/graph.hpp:1127-1827 ``load_directed``, :4203 ``generate_backward_structure``)
and CSC_segment construction (core/GraphSegment.cpp:45-220,
core/PartitionedGraph.hpp:324-420 ``PartitionToChunks``) with vectorized NumPy
preprocessing. Where the reference builds per-socket NUMA copies and MPI-shuffles
edges to owner ranks, a TPU has a single HBM domain per chip, so preprocessing
happens once on the host and the resulting flat arrays are shipped to device
(optionally sharded over a mesh — see neutronstarlite_tpu.parallel.dist_graph).

Conventions:
- Edges are directed src -> dst; forward aggregation pulls from in-neighbors
  (CSC, edges sorted by dst), backward pushes gradients along out-edges
  (CSR, edges sorted by src) — mirroring the reference's forward CSC chunks
  (``incoming_adj_*``) and backward CSR (``incoming_adj_*_backward``,
  graph.hpp:127-153).
- Zero degrees are clamped to 1 for normalization, matching
  generate_backward_structure's clamp (graph.hpp:4396-4401).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Tuple

import numpy as np

# Reference: CHUNKSIZE (1<<20) edges per IO read (dep/gemini/constants.hpp:20).
# NumPy reads the whole file; kept only as the streaming chunk for huge files.
IO_CHUNK_EDGES = 1 << 24


def load_edges_binary(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """Read a Gemini binary edge list: pairs of little-endian uint32 (src, dst).

    Reference: chunked binary reads in ``load_directed`` (graph.hpp:1160-1181);
    8 bytes/edge per data/README.md.
    """
    size = os.path.getsize(path)
    if size % 8 != 0:
        raise ValueError(f"{path}: size {size} is not a multiple of 8 bytes/edge")
    raw = np.fromfile(path, dtype="<u4").reshape(-1, 2)
    return np.ascontiguousarray(raw[:, 0]), np.ascontiguousarray(raw[:, 1])


def load_edges_text(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """Read a whitespace text edge list: one ``src dst`` pair per line (the
    ``*.edge.txt`` files generate_nts_dataset.py emits). '#' comments and
    extra columns (per-edge weights) are ignored; negative ids are an error
    rather than a uint32 wraparound."""
    data = np.loadtxt(path, dtype=np.int64, usecols=(0, 1), comments="#", ndmin=2)
    if data.size and data.min() < 0:
        raise ValueError(f"{path}: negative vertex id {data.min()} in edge list")
    return (
        np.ascontiguousarray(data[:, 0].astype(np.uint32)),
        np.ascontiguousarray(data[:, 1].astype(np.uint32)),
    )


# text edge files may carry comments, float weight columns, sci notation
_TEXT_EDGE_BYTES = frozenset(b"0123456789 \t\r\n-+.eE#,")


def load_edges(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """Load an edge list, sniffing text vs Gemini-binary format.

    The reference ships both (.edge.txt and .edge.txt.bin); its loader is
    told by the caller, ours inspects the first bytes: an all-ASCII
    digits/whitespace/numeric-punctuation prefix means text. A text file a
    user feeds in with other content fails loudly in the text parser rather
    than being silently reinterpreted as binary uint32 pairs.
    """
    with open(path, "rb") as fh:
        head = fh.read(4096)
    # '#' comment lines may contain arbitrary text; drop them (and a
    # trailing partial line) before the byte-class check so a commented
    # text file isn't misrouted to the binary parser. An empty residue is
    # NOT treated as text (a binary file whose first byte happens to be
    # 0x23 with no newline in the head must stay binary) unless the whole
    # head itself decodes as comment-leading ASCII lines.
    lines = head.split(b"\n")
    if len(lines) > 1:
        lines = lines[:-1]
    data_lines = [ln for ln in lines if not ln.lstrip().startswith(b"#")]
    sniff = b"\n".join(data_lines)
    if sniff:
        is_text = all(b in _TEXT_EDGE_BYTES for b in sniff)
    else:
        # only comments in the head: text iff it is printable ASCII lines
        is_text = (
            len(lines) > 0
            and all(32 <= b < 127 or b in (9, 10, 13) for b in head)
            and all(ln.lstrip().startswith(b"#") for ln in lines)
        )
    if head and is_text:
        return load_edges_text(path)
    return load_edges_binary(path)


def load_undirected_from_directed(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """Load a directed edge list and symmetrize it: every stored edge
    (u, v) yields both (u, v) and (v, u).

    Reference: Graph::load_undirected_from_directed (core/graph.hpp:640),
    which bumps BOTH endpoint degrees per stored edge — so a stored self
    loop contributes twice there, and matching that exactly would double
    its weight. We keep one copy of each self loop (the aggregation
    semantics users actually want from "make it undirected") and document
    the deviation here. Format sniffing is shared with ``load_edges``.
    """
    src, dst = load_edges(path)
    rev = src != dst
    return (
        np.concatenate([src, dst[rev]]),
        np.concatenate([dst, src[rev]]),
    )


def gcn_norm_weights(
    src: np.ndarray, dst: np.ndarray, out_degree: np.ndarray, in_degree: np.ndarray
) -> np.ndarray:
    """Per-edge GCN weight 1/sqrt(d_out(src) * d_in(dst)).

    Reference: ``nts_norm_degree`` (core/ntsBaseOp.hpp:194-197) and the
    ``weight_compute`` callback passed to PartitionToChunks
    (PartitionedGraph.hpp:324).
    """
    d_out = np.maximum(out_degree[src], 1).astype(np.float64)
    d_in = np.maximum(in_degree[dst], 1).astype(np.float64)
    return (1.0 / np.sqrt(d_out * d_in)).astype(np.float32)


@dataclasses.dataclass
class CSCGraph:
    """Dual CSC/CSR adjacency with per-edge weights, host (NumPy) resident.

    CSC view (forward, dst-sorted):   column_offset [V+1], row_indices [E]
      (source of each edge), dst_of_edge [E], edge_weight_forward [E].
    CSR view (backward, src-sorted):  row_offset [V+1], column_indices [E]
      (destination of each edge), src_of_edge [E], edge_weight_backward [E].

    Reference: CSC_segment_pinned (core/GraphSegment.h:52-139) holds the same
    dual structure per (src-partition, dst-partition) chunk; here the
    single-chip graph is one flat chunk and the distributed build slices it.
    """

    v_num: int
    e_num: int
    # CSC (forward)
    column_offset: np.ndarray
    row_indices: np.ndarray
    dst_of_edge: np.ndarray
    edge_weight_forward: np.ndarray
    # CSR (backward)
    row_offset: np.ndarray
    column_indices: np.ndarray
    src_of_edge: np.ndarray
    edge_weight_backward: np.ndarray
    # degrees
    out_degree: np.ndarray
    in_degree: np.ndarray

    @property
    def avg_degree(self) -> float:
        return self.e_num / max(self.v_num, 1)


def build_graph(
    src: np.ndarray,
    dst: np.ndarray,
    v_num: int,
    weight: str = "gcn_norm",
    edge_weight: Optional[np.ndarray] = None,
    use_native: Optional[bool] = None,
) -> CSCGraph:
    """Build dual CSC/CSR from an edge list.

    ``weight``: "gcn_norm" (1/sqrt(dd), the GCN toolkits' choice), "ones"
    (GIN/GAT-style unweighted sum), or "custom" with ``edge_weight`` given.

    ``use_native``: route through the C++ counting-sort builder
    (neutronstarlite_tpu.native) when available — O(E) OpenMP build vs the
    NumPy argsort path; None = auto.
    """
    src = np.asarray(src, dtype=np.uint32)
    dst = np.asarray(dst, dtype=np.uint32)
    e_num = src.shape[0]
    if e_num and (int(src.max()) >= v_num or int(dst.max()) >= v_num):
        # guard before ids reach bincount / the native counting-sort builder
        raise ValueError(
            f"edge list references vertex {max(int(src.max()), int(dst.max()))} "
            f">= VERTICES {v_num}"
        )

    if use_native is not False and weight in ("gcn_norm", "ones"):
        from neutronstarlite_tpu import native

        if native.available():
            (
                column_offset, csc_src, csc_dst, csc_w,
                row_offset, csr_src, csr_dst, csr_w, out_degree, in_degree,
            ) = native.build_adjacency(
                src, dst, v_num, 0 if weight == "gcn_norm" else 1
            )
            return CSCGraph(
                v_num=v_num,
                e_num=e_num,
                column_offset=column_offset,
                row_indices=csc_src,
                dst_of_edge=csc_dst,
                edge_weight_forward=csc_w,
                row_offset=row_offset,
                column_indices=csr_dst,
                src_of_edge=csr_src,
                edge_weight_backward=csr_w,
                out_degree=out_degree,
                in_degree=in_degree,
            )

    out_degree = np.bincount(src, minlength=v_num).astype(np.int32)
    in_degree = np.bincount(dst, minlength=v_num).astype(np.int32)

    if weight == "gcn_norm":
        w = gcn_norm_weights(src, dst, out_degree, in_degree)
    elif weight == "ones":
        w = np.ones(e_num, dtype=np.float32)
    elif weight == "custom":
        if edge_weight is None:
            raise ValueError("custom weight requires edge_weight")
        w = np.asarray(edge_weight, dtype=np.float32)
    else:
        raise ValueError(f"unknown weight mode {weight}")

    # CSC: stable sort by dst so each vertex's in-edges are contiguous and
    # dst_of_edge is globally non-decreasing (segment-sum friendly).
    csc_perm = np.argsort(dst, kind="stable")
    csc_src = src[csc_perm]
    csc_dst = dst[csc_perm]
    column_offset = np.zeros(v_num + 1, dtype=np.int64)
    np.cumsum(in_degree, out=column_offset[1:])

    # CSR: stable sort by src.
    csr_perm = np.argsort(src, kind="stable")
    csr_src = src[csr_perm]
    csr_dst = dst[csr_perm]
    row_offset = np.zeros(v_num + 1, dtype=np.int64)
    np.cumsum(out_degree, out=row_offset[1:])

    return CSCGraph(
        v_num=v_num,
        e_num=e_num,
        column_offset=column_offset,
        row_indices=csc_src.astype(np.int32),
        dst_of_edge=csc_dst.astype(np.int32),
        edge_weight_forward=w[csc_perm],
        row_offset=row_offset,
        column_indices=csr_dst.astype(np.int32),
        src_of_edge=csr_src.astype(np.int32),
        edge_weight_backward=w[csr_perm],
        out_degree=out_degree,
        in_degree=in_degree,
    )


def partition_offsets(
    v_num: int,
    in_degree: np.ndarray,
    partitions: int,
    alpha: Optional[float] = None,
    page_size: int = 1,
) -> np.ndarray:
    """Locality-aware contiguous vertex partition boundaries.

    Balances ``edges + alpha * |V|`` per partition with
    ``alpha = 12 * (partitions + 1)`` by default and page-aligned boundaries —
    the reference's chunking scheme (graph.hpp:408, :1186-1211, PAGESIZE
    alignment :1203). Returns offsets of shape [partitions + 1].
    """
    if alpha is None:
        alpha = 12.0 * (partitions + 1)
    weights = in_degree.astype(np.float64) + alpha
    cum = np.concatenate([[0.0], np.cumsum(weights)])
    total = cum[-1]
    offsets = np.zeros(partitions + 1, dtype=np.int64)
    offsets[partitions] = v_num
    for p in range(1, partitions):
        target = total * p / partitions
        pos = int(np.searchsorted(cum, target))
        if page_size > 1:
            pos = (pos // page_size) * page_size
        pos = min(max(pos, offsets[p - 1]), v_num)
        offsets[p] = pos
    return offsets
