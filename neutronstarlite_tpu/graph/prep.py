"""Dataset preparation — the generate_nts_dataset.py equivalent.

Reference: data/generate_nts_dataset.py (252 LoC) downloads planetoid/Reddit
through DGL and writes the nts file formats (data/README.md): a binary edge
list (8 bytes/edge), ``<name>.featuretable`` (``ID f0 .. f_{d-1}`` lines),
``<name>.labeltable`` (``ID label``), ``<name>.mask`` (``ID train|val|test``).

This build runs with zero network egress, so:
- **cora** converts the files shipped with the reference checkout
  (/root/reference/data): binary edges + label/mask tables are real; the
  featuretable (not shipped) is generated deterministically.
- **citeseer / pubmed / reddit** are synthesized at the exact workload-matrix
  scale (VERTICES / LAYERS of the corresponding reference cfg) with
  planted-partition structure, so every config in configs/ is runnable and
  convergence remains a meaningful oracle. Reddit features are written as
  ``.npy`` (a >1 GB text table otherwise); pass --text-features to force text.

Usage: ``python -m neutronstarlite_tpu.graph.prep --dataset cora --out data``
"""

from __future__ import annotations

import argparse
import os

import numpy as np

# (v_num, feature_size, classes, default avg_degree, train/val counts)
DATASETS = {
    "cora": (2708, 1433, 7, None, (140, 500)),
    "citeseer": (3327, 3703, 6, 10, (120, 500)),
    "pubmed": (19717, 500, 3, 10, (60, 500)),
    "reddit": (232965, 602, 41, 50, (153431, 23831)),  # real split sizes
}

REFERENCE_DATA = "/root/reference/data"


def _write_edges_binary(path: str, src: np.ndarray, dst: np.ndarray) -> None:
    np.stack([src.astype("<u4"), dst.astype("<u4")], axis=1).tofile(path)


def _write_feature_table(path: str, feature: np.ndarray, text: bool) -> str:
    if not text:
        path = path + ".npy"
        np.save(path, feature.astype(np.float32))
        return path
    ids = np.arange(feature.shape[0])[:, None].astype(np.float32)
    np.savetxt(path, np.concatenate([ids, feature], axis=1), fmt="%.6g")
    return path


def _write_label_table(path: str, label: np.ndarray) -> None:
    ids = np.arange(len(label))
    np.savetxt(path, np.stack([ids, label], axis=1), fmt="%d")


def _write_mask(path: str, mask: np.ndarray) -> None:
    names = np.array(["train", "val", "test"])
    with open(path, "w") as fh:
        for i, m in enumerate(mask):
            fh.write(f"{i} {names[m]}\n")


def _split_mask(v_num: int, n_train: int, n_val: int, seed: int) -> np.ndarray:
    rng = np.random.default_rng(seed)
    order = rng.permutation(v_num)
    mask = np.full(v_num, 2, dtype=np.int32)
    mask[order[:n_train]] = 0
    mask[order[n_train : n_train + n_val]] = 1
    return mask


def prepare(
    dataset: str,
    out_dir: str,
    avg_degree: float | None = None,
    self_loop: bool = True,
    seed: int = 0,
    text_features: bool = False,
) -> dict:
    """Write the four nts files for ``dataset`` under ``out_dir/dataset/``.

    Returns {edge_file, feature_file, label_file, mask_file, v_num, e_num}.
    """
    if dataset not in DATASETS:
        raise KeyError(f"unknown dataset {dataset!r}; known: {sorted(DATASETS)}")
    v_num, f_dim, classes, default_deg, (n_train, n_val) = DATASETS[dataset]
    d = os.path.join(out_dir, dataset)
    os.makedirs(d, exist_ok=True)
    base = os.path.join(d, dataset)

    if dataset == "cora":
        from neutronstarlite_tpu.graph.storage import load_edges_binary

        name = "cora.2708.edge.self" if self_loop else "cora.2708.edge"
        src, dst = load_edges_binary(os.path.join(REFERENCE_DATA, name))
        label = np.zeros(v_num, dtype=np.int64)
        raw = np.loadtxt(os.path.join(REFERENCE_DATA, "cora.labeltable"), dtype=np.int64)
        label[raw[:, 0]] = raw[:, 1]
        from neutronstarlite_tpu.graph.dataset import _read_mask_table

        mask = _read_mask_table(os.path.join(REFERENCE_DATA, "cora.mask"), v_num)
        rng = np.random.default_rng(seed)
        # class-correlated features (featuretable is not shipped upstream)
        centers = rng.standard_normal((classes, f_dim)).astype(np.float32)
        feature = centers[label] * 0.5 + rng.standard_normal(
            (v_num, f_dim), dtype=np.float32
        )
    else:
        from neutronstarlite_tpu.graph.synthetic import planted_partition_graph

        deg = avg_degree if avg_degree is not None else default_deg
        src, dst, feature, label = planted_partition_graph(
            v_num, classes, avg_degree=deg, feature_size=f_dim, seed=seed
        )
        if self_loop:
            loops = np.arange(v_num, dtype=np.uint32)
            src = np.concatenate([src, loops])
            dst = np.concatenate([dst, loops])
        mask = _split_mask(v_num, n_train, n_val, seed)

    edge_file = f"{base}.edge.bin"
    _write_edges_binary(edge_file, src, dst)
    feature_file = _write_feature_table(f"{base}.featuretable", feature, text_features)
    label_file = f"{base}.labeltable"
    _write_label_table(label_file, label)
    mask_file = f"{base}.mask"
    _write_mask(mask_file, mask)
    return {
        "edge_file": edge_file,
        "feature_file": feature_file,
        "label_file": label_file,
        "mask_file": mask_file,
        "v_num": v_num,
        "e_num": len(src),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dataset", required=True, choices=sorted(DATASETS))
    ap.add_argument("--out", default="data")
    ap.add_argument("--avg-degree", type=float, default=None,
                    help="synthetic datasets: edges per vertex "
                    "(reddit real scale is ~492; default 50 keeps prep fast)")
    ap.add_argument("--no-self-loop", action="store_true")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--text-features", action="store_true",
                    help="write the featuretable as text even when large")
    a = ap.parse_args(argv)
    info = prepare(
        a.dataset,
        a.out,
        avg_degree=a.avg_degree,
        self_loop=not a.no_self_loop,
        seed=a.seed,
        text_features=a.text_features,
    )
    for k, v in info.items():
        print(f"{k}: {v}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
