"""nts-tpu: a TPU-native distributed GNN training framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of NeutronStar
(iDC-NEU/NeutronStarLite, SIGMOD'22): full-batch and mini-batch training of
GCN / GAT / GIN / CommNet on partitioned graphs, with master/mirror dependency
management, fused sparse aggregation operators with hand-paired backward
passes, edge-level operators, fan-out neighbor sampling, and data-parallel
model sync.

Where the reference is C++/MPI/OpenMP/libtorch/CUDA, this framework is
TPU-first:

- graph storage      : HBM-resident CSC/CSR device arrays, vertex-sharded
                       (reference: core/GraphSegment.h, core/PartitionedGraph.hpp)
- aggregation ops    : segment-sum / Pallas kernels with custom_vjp pairs
                       (reference: core/nts*GraphOp.hpp, cuda/ntsCUDAFuseKernel.cuh)
- distribution       : jax.sharding.Mesh + shard_map, ppermute ring exchange
                       over ICI in place of the MPI master/mirror ring
                       (reference: comm/network.cpp, core/graph.hpp engines)
- autodiff           : jax.grad end-to-end; custom_vjp where the reference
                       hand-pairs forward/backward (reference: core/ntsContext.hpp)
- models             : toolkit-style trainers driven by the same KEY:VALUE cfg
                       files (reference: toolkits/, GraphSegment.cpp:222)
"""

__version__ = "0.1.0"

from neutronstarlite_tpu.utils.config import InputInfo  # noqa: F401
