"""Trend-aware perf regression sentinel over the cross-run ledger.

The pairwise ``metrics_report --diff`` gate has a structural flaw on a
noisy rig: its baseline is ONE run, so the CI host's ~20% run-to-run
throughput swing eats the whole error budget — --tol had to be cranked
to 1.0 on the timing legs, which also waves real regressions through.
The sentinel replaces the single-run baseline with the TRAJECTORY:

  baseline  = median of the last K ledger rows matching the candidate's
              key (kind + graph digest + cfg fingerprint + backend —
              obs/ledger.row_key)
  tolerance = max(nsigma * 1.4826 * MAD, floor) of that same window,
              capped at --max-tol

MAD (median absolute deviation) is the robust spread estimate: the
rig's own observed noise sets the tolerance, so steady ±10% jitter does
NOT trip while a real 25% step-change still does (1.4826 * MAD estimates
sigma for a normal; nsigma=3 puts the gate at the noise's 3-sigma edge).
A single outlier in the history moves neither the median nor the MAD —
the property a mean/stdev baseline lacks.

Exit contract matches ``--diff`` so ci_tier1 adopts it per-gate:
0 = no regression (or not enough matching history to judge — gating on
a guess would be worse than not gating), 2 = regression beyond
tolerance, 1 = usage/unreadable ledger. ``--json`` emits one
machine-readable object in the --diff shape ({tol, metrics:{m:{a, b,
delta, regressed}}, regressed:[...]} plus baseline_n/tol per metric and
a warnings list).

Suite rows additionally get the margin check the ROADMAP kept as a
hand-written note: ``--suite-budget`` (defaulting to the row's own
recorded timeout) warns — or fails with ``--suite-fatal`` — when the
latest suite duration exceeds 80% of the timeout, and warns when
DOTS_PASSED dropped below the baseline median.

Usage:
  python -m neutronstarlite_tpu.tools.perf_sentinel check
      [--ledger DIR] [--kind run|suite|probe] [--k 8]
      [--min-baseline 2] [--nsigma 3.0] [--floor 0.08] [--max-tol 0.5]
      [--suite-budget S] [--suite-fatal] [--json]
  python -m neutronstarlite_tpu.tools.perf_sentinel record-suite
      --duration S --dots N --rc RC --timeout S [--ledger DIR]
  python -m neutronstarlite_tpu.tools.perf_sentinel list-keys
      [--ledger DIR] [--json]     (also: perf_sentinel --list-keys)

``list-keys`` enumerates the distinct (kind, graph digest, cfg,
backend) trajectories the ledger holds with row counts and last-seen
timestamps — the first stop when a check says "min-baseline not met"
(usually the key changed: new backend fingerprint, new cfg, new graph).
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
from typing import Any, Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from neutronstarlite_tpu.obs import ledger  # noqa: E402
from neutronstarlite_tpu.obs.ledger import as_number as _num  # noqa: E402
# the robust median+MAD tolerance math lives in obs/skew.py now — ONE
# definition shared with the live straggler detector (which applies the
# identical law to per-partition epoch times); re-exported here under the
# historical names so existing callers keep working
from neutronstarlite_tpu.obs.skew import (  # noqa: E402,F401
    baseline_stats,
    effective_tolerance,
)

# lower-is-better scalars gated per row kind; hist p99s join dynamically
GATED_METRICS = {
    "run": (
        "warm_median_epoch_s",
        "avg_epoch_s",
        "wire_bytes_fwd_per_epoch",
        "sample_stall_ms_per_epoch",
        # the fused sampler's structural zero (sample/fused.py): any
        # regression that reintroduces a per-batch host transfer grows
        # it off the 0-baseline trajectory
        "sample_h2d_bytes_per_epoch",
        "edge_hbm_bytes_per_epoch",
        "peak_hbm_bytes",
        # measured wire quantization error (obs/numerics): a dtype or
        # rounding regression grows it; the MAD window absorbs float
        # jitter. grad_global_norm is NOT here — it is not
        # lower-is-better; see the advisory two-sided leg in check()
        "wire_quant_rel_err",
    ),
    "suite": ("suite_duration_s",),
    "probe": ("seconds",),
    # serve rows (tools/serve_bench --> obs/ledger.serve_row): tail
    # latency + shed rate trend-gate exactly like epoch time — the key
    # embeds mode/replicas/CB so trajectories never mix load shapes.
    # router_overhead_p99_ms rides only on serve_bench --trace rows
    # (client latency minus the replica's summed stage time, from the
    # merged span streams) — absent on untraced rows, so it just skips
    "serve": ("p50_ms", "p95_ms", "p99_ms", "shed_rate",
              "router_overhead_p99_ms"),
    # fleet rows (obs/hub.fleet_row): the hub's merged cross-host view —
    # the fleet-wide latency tails ride in via hist_quantiles (below),
    # so the scalar tuple only carries the liveness-shaped metrics
    "fleet": ("targets_lost",),
}

SUITE_MARGIN_FRAC = 0.8  # the ROADMAP "watch the margin" note as a number


def _metric_values(row: Dict[str, Any], kind: str) -> Dict[str, float]:
    """The gated scalars one row carries (absent/null metrics skipped);
    hist quantiles flatten to ``hist_<name>_p99`` so serve/epoch tails
    ride the same gate."""
    out: Dict[str, float] = {}
    for m in GATED_METRICS.get(kind, ()):
        v = _num(row.get(m))
        if v is not None:
            out[m] = v
    for name, q in (row.get("hist_quantiles") or {}).items():
        v = _num((q or {}).get("p99"))
        if v is not None:
            out[f"hist_{name}_p99"] = v
    return out


def list_keys(rows: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """The distinct (kind, graph digest, cfg, backend) trajectories a
    ledger holds, with row counts and first/last-seen timestamps —
    the answer to "why does the sentinel say min-baseline not met"
    without hand-grepping JSONL."""
    groups: Dict[tuple, Dict[str, Any]] = {}
    for r in rows:
        key = ledger.row_key(r)
        g = groups.get(key)
        ts = _num(r.get("ts"))
        if g is None:
            g = groups[key] = {
                "kind": key[0], "graph_digest": key[1], "cfg": key[2],
                "backend": key[3], "rows": 0,
                "first_ts": ts, "last_ts": ts,
            }
        g["rows"] += 1
        if ts is not None:
            if g["first_ts"] is None or ts < g["first_ts"]:
                g["first_ts"] = ts
            if g["last_ts"] is None or ts > g["last_ts"]:
                g["last_ts"] = ts
    return sorted(
        groups.values(),
        key=lambda g: (str(g["kind"]), -(g["last_ts"] or 0.0)),
    )


def check(rows: List[Dict[str, Any]], kind: str, k: int, min_baseline: int,
          nsigma: float, floor: float, max_tol: float,
          suite_budget: Optional[float] = None) -> Dict[str, Any]:
    """Gate the latest row of ``kind`` against its matching history.

    Returns {candidate, baseline_n, tol, metrics, regressed, warnings};
    ``regressed`` empty when nothing tripped (or history was too thin —
    each skipped metric says so in warnings)."""
    of_kind = [r for r in rows if r.get("kind") == kind]
    out: Dict[str, Any] = {
        "kind": kind, "tol": floor, "metrics": {},
        "regressed": [], "warnings": [],
    }
    if not of_kind:
        out["warnings"].append(f"no {kind} rows in the ledger")
        return out
    cand = of_kind[-1]
    key = ledger.row_key(cand)
    history = [r for r in of_kind[:-1] if ledger.row_key(r) == key]
    if kind == "suite":
        # a failed/timed-out suite execution (nonzero rc) is not a valid
        # baseline: its duration saturates at the timeout and its
        # DOTS_PASSED is truncated, so including it would drag the
        # median toward exactly the degraded state the gate exists to
        # catch. The CANDIDATE is still judged whatever its rc.
        history = [r for r in history if not r.get("rc")]
    window = history[-k:]
    out["candidate"] = {
        "run_id": cand.get("run_id"), "ts": cand.get("ts"),
        "backend": cand.get("backend"), "cfg": cand.get("cfg"),
    }
    out["baseline_n"] = len(window)
    cand_metrics = _metric_values(cand, kind)
    for m, b_val in sorted(cand_metrics.items()):
        base_vals = [
            v for v in (_metric_values(r, kind).get(m) for r in window)
            if v is not None
        ]
        if len(base_vals) < min_baseline:
            out["warnings"].append(
                f"{m}: only {len(base_vals)} matching baseline row(s) "
                f"(< {min_baseline}); not gated"
            )
            continue
        stats = baseline_stats(base_vals)
        med = stats["median"]
        tol = effective_tolerance(med, stats["mad"], nsigma, floor, max_tol)
        if med > 0:
            delta = (b_val - med) / med
            regressed = b_val > med * (1.0 + tol)
        else:
            delta = 1.0 if b_val > 0 else 0.0
            regressed = b_val > tol  # zero baseline: tol is absolute
        out["metrics"][m] = {
            "a": med, "b": b_val, "delta": delta, "regressed": regressed,
            "tol": tol, "mad": stats["mad"], "baseline_n": stats["n"],
        }
        if regressed:
            out["regressed"].append(m)

    if kind == "run":
        # ADVISORY grad-norm trajectory leg (obs/numerics): the final
        # grad_global_norm checked TWO-SIDED against its own history —
        # a norm blowing up OR collapsing to ~0 is an optimization-
        # health drift, but neither direction is "better", so it warns
        # instead of gating (the ISSUE 15 sentinel contract)
        gn = _num(cand.get("grad_global_norm"))
        base_gn = [
            v for v in (_num(r.get("grad_global_norm")) for r in window)
            if v is not None
        ]
        if gn is not None and len(base_gn) >= min_baseline:
            stats = baseline_stats(base_gn)
            med = stats["median"]
            tol = effective_tolerance(med, stats["mad"], nsigma, floor,
                                      max_tol)
            if med > 0 and abs(gn - med) > med * tol:
                out["warnings"].append(
                    f"grad_global_norm: {gn:g} vs baseline median "
                    f"{med:g} ({(gn - med) / med * 100:+.1f}%, beyond "
                    f"±{tol:.0%}) — gradient-scale drift (advisory; "
                    "check the numerics block / tensor_stats records)"
                )
                out["grad_norm_drift"] = True

    if kind == "suite":
        budget = suite_budget if suite_budget is not None else _num(
            cand.get("timeout_s")
        )
        dur = _num(cand.get("suite_duration_s"))
        if budget and dur is not None and dur > SUITE_MARGIN_FRAC * budget:
            out["warnings"].append(
                f"suite_margin: suite ran {dur:.0f}s — over "
                f"{SUITE_MARGIN_FRAC:.0%} of the {budget:.0f}s timeout "
                f"({dur / budget:.0%}); the next noise swing can truncate "
                "a passing run (raise the timeout with ROADMAP.md or trim "
                "the suite)"
            )
            out["suite_margin_exceeded"] = True
        dots = _num(cand.get("dots_passed"))
        base_dots = [
            v for v in (_num(r.get("dots_passed")) for r in window)
            if v is not None
        ]
        if dots is not None and len(base_dots) >= min_baseline:
            med_dots = float(statistics.median(base_dots))
            if dots < med_dots:
                out["warnings"].append(
                    f"dots_passed: {dots:.0f} < baseline median "
                    f"{med_dots:.0f} — fewer tests passing than the "
                    "trajectory"
                )
    return out


def _render(result: Dict[str, Any]) -> str:
    lines = [
        f"perf sentinel: kind={result['kind']} "
        f"baseline_n={result.get('baseline_n', 0)}"
    ]
    header = ("metric", "baseline", "latest", "delta", "tol")
    table = [header]
    for m, d in sorted(result["metrics"].items()):
        table.append((
            m, f"{d['a']:g}", f"{d['b']:g}",
            f"{d['delta'] * 100:+.1f}%" + (
                " REGRESSED" if d["regressed"] else ""
            ),
            f"{d['tol'] * 100:.1f}%",
        ))
    widths = [max(len(r[i]) for r in table) for i in range(len(header))]
    lines.extend(
        "  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip()
        for row in table
    )
    for w in result["warnings"]:
        lines.append(f"  warning: {w}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="trend-aware perf regression sentinel over the "
        "NTS_LEDGER_DIR cross-run ledger (exit 2 on regression)"
    )
    sub = ap.add_subparsers(dest="cmd")

    chk = sub.add_parser("check", help="gate the latest ledger row "
                         "against its matching history")
    chk.add_argument("--ledger", default=None,
                     help="ledger directory (default NTS_LEDGER_DIR)")
    chk.add_argument("--kind", default="run",
                     choices=sorted(GATED_METRICS))
    chk.add_argument("--k", type=int, default=8,
                     help="baseline window: last K matching rows")
    chk.add_argument("--min-baseline", type=int, default=2,
                     help="fewest matching rows a metric needs before it "
                     "gates (thinner history = warn, exit 0)")
    chk.add_argument("--nsigma", type=float, default=3.0,
                     help="MAD multiplier (1.4826*MAD estimates sigma)")
    chk.add_argument("--floor", type=float, default=0.08,
                     help="relative tolerance floor (absolute threshold "
                     "against a zero baseline, the --diff convention)")
    chk.add_argument("--max-tol", type=float, default=0.5,
                     help="relative tolerance cap — a wild history must "
                     "not wave everything through")
    chk.add_argument("--suite-budget", type=float, default=None,
                     help="suite rows: the tier-1 timeout to check the "
                     "80%% margin against (default: the row's own "
                     "recorded timeout_s)")
    chk.add_argument("--suite-fatal", action="store_true",
                     help="escalate the suite-margin warning to exit 2")
    chk.add_argument("--json", action="store_true")

    rec = sub.add_parser("record-suite", help="append one kind=suite row "
                         "(ci_tier1.sh calls this after the pytest leg)")
    rec.add_argument("--ledger", default=None)
    rec.add_argument("--duration", type=float, required=True)
    rec.add_argument("--dots", type=int, required=True)
    rec.add_argument("--rc", type=int, required=True)
    rec.add_argument("--timeout", type=float, required=True)

    lk = sub.add_parser("list-keys", help="enumerate the distinct "
                        "(kind, graph digest, cfg, backend) trajectories "
                        "with row counts and last-seen timestamps")
    lk.add_argument("--ledger", default=None)
    lk.add_argument("--json", action="store_true")

    ap.add_argument("--list-keys", action="store_true",
                    dest="list_keys_flag",
                    help="shorthand for the list-keys subcommand "
                    "(ledger from NTS_LEDGER_DIR)")

    args = ap.parse_args(argv)
    if args.cmd is None and args.list_keys_flag:
        args.cmd = "list-keys"
        args.ledger = None
        args.json = False
    if args.cmd is None:
        ap.error("a subcommand is required (check | record-suite | "
                 "list-keys)")

    directory = args.ledger or ledger.ledger_dir()
    if not directory:
        print("perf_sentinel: no ledger directory (--ledger or "
              "NTS_LEDGER_DIR)", file=sys.stderr)
        return 1

    if args.cmd == "record-suite":
        path = ledger.append_row(
            ledger.suite_row(args.duration, args.dots, args.rc,
                             args.timeout),
            directory=directory,
        )
        if path is None:
            print("perf_sentinel: suite row append failed",
                  file=sys.stderr)
            return 1
        print(f"perf_sentinel: recorded suite row "
              f"({args.duration:.0f}s, {args.dots} dots) -> {path}",
              file=sys.stderr)
        return 0

    path = ledger.ledger_path(directory)
    if not path or not os.path.exists(path):
        # the documented contract: an unreadable/absent ledger is exit 1,
        # not a vacuous pass — a hard gate pointed at a typo'd path must
        # fail loudly, indistinguishable-from-clean is the worst outcome
        print(f"perf_sentinel: no ledger file at {path!r} (nothing was "
              "ever recorded here, or the path is wrong)", file=sys.stderr)
        return 1
    rows = ledger.read_rows(directory=directory)

    if args.cmd == "list-keys":
        keys = list_keys(rows)
        if args.json:
            print(json.dumps({"ledger": path, "keys": keys}))
            return 0
        print(f"perf_sentinel: {len(keys)} trajectory key(s) across "
              f"{len(rows)} row(s) in {path}")
        header = ("kind", "graph_digest", "cfg", "backend", "rows",
                  "last_seen")
        table = [header]
        for g in keys:
            last = g["last_ts"]
            last_s = (
                time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(last))
                if last is not None else "-"
            )
            table.append((
                str(g["kind"]), str(g["graph_digest"])[:16], str(g["cfg"]),
                str(g["backend"])[:24], str(g["rows"]), last_s,
            ))
        widths = [max(len(r[i]) for r in table) for i in range(len(header))]
        for row in table:
            print("  ".join(c.ljust(w) for c, w in zip(row, widths))
                  .rstrip())
        return 0

    result = check(
        rows, args.kind, args.k, args.min_baseline, args.nsigma,
        args.floor, args.max_tol, suite_budget=args.suite_budget,
    )
    result["tol"] = args.floor
    failed = bool(result["regressed"]) or (
        args.suite_fatal and result.get("suite_margin_exceeded")
    )
    if args.json:
        print(json.dumps(result))
    else:
        print(_render(result))
        if result["regressed"]:
            print(
                "REGRESSION beyond MAD tolerance: "
                + "; ".join(
                    f"{m}: {result['metrics'][m]['a']:g} -> "
                    f"{result['metrics'][m]['b']:g} "
                    f"({result['metrics'][m]['delta'] * 100:+.1f}% > "
                    f"{result['metrics'][m]['tol'] * 100:.1f}%)"
                    for m in result["regressed"]
                ),
                file=sys.stderr,
            )
    return 2 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
