"""AOT capacity proof for the segmented bsp kernel at 10x-Reddit scale.

VERDICT r3 item 3: the module's stated regime ("V ~ 10x Reddit and up",
ops/bsp_ell.py) needed ~1.4-1.75M blocks while the packed SMEM key
capped at ~250k. The fix is grid segmentation (BspEll.build): every
pallas_call carries at most NTS_BSP_MAX_BLOCKS blocks, covering one
contiguous dst-tile range, with segment-LOCAL keys — the compiled
program is independent of V; only the Python-level segment count grows.

Provability: when a build segments (n_seg > 1) it QUANTIZES the program
shape — b_seg snaps to the exact 8-value menu ``bsp_bseg_menu(cap)``
(seven quantum steps + the cap) and t_seg (the per-call output tile
count) snaps to the <=16-value menu ``bsp_tseg_menu(t_dst)`` — so every
segmented program at any scale comes from the finite
(b_seg menu) x (t_seg menu) lattice, which this tool compiles IN FULL
(~100 programs at ~1.7 s each; ADVICE r4 flagged the previous
3-candidate t_seg band for missing the values real builds emit). The
per-BLOCK geometry (the Mosaic lowering surface: [1,K,R] tables, the
[vt,f] slab, the [dt,f] output tile, the W one-hot build) is
t_seg-invariant; t_seg only sizes the output HBM buffer and the index
map range, which is why the whole lattice compiles in minutes with no
chip claimed. Green across the lattice means every segmented program
the builder can emit at that scale is pre-lowered into the persistent
compile cache — no first-run full-scale Mosaic compile on chip.

Reference analog: the beyond-shared-mem tiled CUDA aggregation
(cuda/ntsCUDAFuseKernel.cuh:163-207) whose shared-memory tile also had
to be proven at the target scale.

Usage: python -m neutronstarlite_tpu.tools.aot_bsp_scale
         [--scale 10.0] [--topology v5e:2x2] [--f 602]
Prints ONE JSON line: {ok, scale, b_seg, t_src, programs: [{t_seg,
compile_s, *_gib}], smem_key_kib | error}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

REDDIT_V = 232_965  # BASELINE.md north-star vertex count


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=10.0)
    ap.add_argument("--topology", default="v5e:2x2")
    ap.add_argument("--f", type=int, default=602)
    ap.add_argument(
        "--dist", type=int, default=0, metavar="P",
        help="compile the lattice at the DIST per-shard RECTANGULAR "
        "geometry instead of the single-chip square one: dst space is one "
        "shard's vp = roundup8(ceil(V/P)) rows, src space is the full "
        "all_gathered P*vp slab (parallel/dist_bsp.py) — VERDICT r4 item 6's "
        "'dist-bsp at 10x-Reddit AOT-green' without synthesizing a "
        "1.15B-edge graph (the kernel program depends only on geometry)",
    )
    args = ap.parse_args(argv)

    # contract: no accelerator claimed — CPU host, topology compiler only
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["NTS_PALLAS_FORCE_COMPILED"] = "1"
    from neutronstarlite_tpu.utils.platform import honor_platform_env

    honor_platform_env()

    import jax
    import numpy as np
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/nts_jit_cache"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    except Exception as e:  # pragma: no cover
        print(f"compile cache unavailable: {e}", file=sys.stderr, flush=True)

    from neutronstarlite_tpu.ops.bsp_ell import (
        DEFAULT_DT,
        DEFAULT_K,
        DEFAULT_MAX_BLOCKS,
        DEFAULT_R,
        DEFAULT_VT,
        _bsp_call,
        bsp_bseg_menu,
        bsp_tseg_menu,
    )

    v_num = int(REDDIT_V * args.scale)
    dt, vt, K, R = DEFAULT_DT, DEFAULT_VT, DEFAULT_K, DEFAULT_R
    cap = int(os.environ.get("NTS_BSP_MAX_BLOCKS", DEFAULT_MAX_BLOCKS))
    if args.dist > 0:
        # per-shard rectangular geometry (parallel/dist_bsp.py): dst rows
        # are one shard's padded vp, the src space is the all_gathered
        # [P*vp] slab. vp must be EXACT (r5 review: the degree-balanced
        # partition_offsets max span exceeds ceil(V/P) — a 2.4%-off vp
        # shifts t_dst/t_src and every compiled program shape), so it is
        # computed from the real generator's degree vector via the real
        # partitioner — the one-shot edge draw is minutes at 10x, cheap
        # next to a wrong cache seed.
        import numpy as _np

        from neutronstarlite_tpu.graph.storage import partition_offsets
        from neutronstarlite_tpu.graph.synthetic import (
            synthetic_power_law_graph,
        )
        from neutronstarlite_tpu.parallel.vertex_space import round_up

        P = args.dist
        e_num = max(int(114_615_892 * args.scale), 512)
        src_a, dst_a = synthetic_power_law_graph(v_num, e_num, seed=7)
        del src_a
        in_deg = _np.bincount(dst_a, minlength=v_num).astype(_np.int64)
        del dst_a
        offs = partition_offsets(v_num, in_deg, P)
        vp = round_up(max(int(_np.diff(offs).max()), 1), 8)
        t_dst = -(-vp // dt)
        t_src = -(-(P * vp) // vt)
    else:
        t_dst = -(-v_num // dt)
        t_src = -(-v_num // vt)
    cap_eff = (cap // 8) * 8
    bseg_menu = bsp_bseg_menu(cap_eff)
    # t_seg menu: the builder snaps every segmented t_seg UP to
    # bsp_tseg_menu(t_dst) (ADVICE r4: the old 3-candidate band missed
    # the roundup128(tiles) values real builds emit, e.g. ~640-768 at
    # 10x Reddit), so compiling the full menu here makes every
    # emittable program literally pre-lowered.
    # + exact t_dst, the call shape of the unsegmented fast path. Scope
    # (r5 review): this lattice covers every SEGMENTED program exactly
    # (segmented b_seg/t_seg are menu-snapped); an UNSEGMENTED program's
    # block count is roundup8(data blocks) — data-dependent, not
    # menu-aligned — so its exact (b, t_dst) pair is seeded by
    # tools/aot_bench_path (which builds the real tables for each bench
    # leg), not by this geometry-only tool. Unsegmented programs only
    # arise under the SMEM cap, where Mosaic compiles have never hung.
    cands = sorted(set(bsp_tseg_menu(t_dst)) | {t_dst})
    out = {
        "scale": args.scale, "v_num": v_num, "topology": args.topology,
        "dist_partitions": args.dist or None,
        "bseg_menu": bseg_menu, "t_src": t_src, "t_dst": t_dst,
        "f": args.f,
        "smem_key_kib_max": round(bseg_menu[-1] * 4 / 1024, 1),
        "programs": [],
    }
    try:
        topo = topologies.get_topology_desc(
            platform="tpu", topology_name=args.topology
        )
        mesh1 = Mesh(np.array(list(topo.devices)[:1]), ("one",))
        rep = NamedSharding(mesh1, PS())

        def sds(shape, dtype):
            return jax.ShapeDtypeStruct(shape, dtype, sharding=rep)

        import jax.numpy as jnp

        # slab dtype is part of the program: the bench's production slab
        # is bf16; the dist exchange's default (f-chunked standard order)
        # feeds f32 — dist mode compiles both
        slab_dtypes = (
            (jnp.bfloat16, jnp.float32) if args.dist else (jnp.bfloat16,)
        )

        def call_width(t_call: int) -> int:
            """The EXACT per-call slab width DistBsp._local_aggregate
            feeds at this geometry — THE SAME function the runtime calls
            (dist_bsp.bsp_call_width), so the tool cannot drift."""
            if not args.dist:
                return args.f
            from neutronstarlite_tpu.parallel.dist_bsp import bsp_call_width

            return bsp_call_width(t_call, dt, args.f)

        for b_seg in bseg_menu:
            for slab_dt in slab_dtypes:
                shapes = (
                    sds((b_seg,), jnp.int32),            # blk_key
                    sds((b_seg, K, R), jnp.int32),       # nbr
                    sds((b_seg, K, R), jnp.float32),     # wgt
                    sds((b_seg, R), jnp.int32),          # ldst
                )
                for t_seg in cands:
                    f_call = call_width(t_seg)
                    shapes = shapes[:4] + (
                        sds((t_src * vt, f_call), slab_dt),  # xp slab
                    )
                    t0 = time.time()
                    compiled = _bsp_call.lower(
                        *shapes, dt=dt, vt=vt, t_dst=t_seg, t_src=t_src,
                        interpret=False,
                    ).compile()
                    mem = compiled.memory_analysis()
                    out["programs"].append({
                        "b_seg": b_seg,
                        "t_seg": t_seg,
                        "f": f_call,
                        "slab": jnp.dtype(slab_dt).name,
                        "compile_s": round(time.time() - t0, 1),
                        "argument_gib": round(
                            mem.argument_size_in_bytes / 2**30, 3
                        ),
                        "temp_gib": round(mem.temp_size_in_bytes / 2**30, 3),
                        "output_gib": round(
                            mem.output_size_in_bytes / 2**30, 3
                        ),
                    })
        out["ok"] = True
    except Exception as e:  # noqa: BLE001 — report, don't trace-dump
        out.update(ok=False, error=f"{type(e).__name__}: {str(e)[:500]}")
    print(json.dumps(out))
    return 0 if out.get("ok") else 1


if __name__ == "__main__":
    raise SystemExit(main())
