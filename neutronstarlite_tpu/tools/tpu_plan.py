"""On-chip measurement plan: watch for TPU-tunnel recovery, then run it all.

Round-2 postmortem (docs/PERF.md §2a): the remote compile service crashed
mid-sweep and the tunnel stayed down for hours, losing the fastest config's
full-scale timing. The recovery watcher lived in /tmp and died with the
session. This tool is the same plan made durable: it lives in the repo,
probes the backend in a bounded subprocess, and the moment the tunnel is up
runs the full measurement sequence step by step — every step resumable, so
a mid-plan tunnel death costs only the step in flight.

Plan steps — ``--list`` is authoritative; in execution order:
  1. bench_full: north-star full-scale sweep + winner measurement (bench.py)
  2. micro_kernels: reproducible PERF §1 micro table (tools/micro_bench)
  2a. fullv_bsp: per-op timing of the bsp kernel at the full 233k-row
      table (the resident/fchunked triage legs were cut in round 4:
      proven un-lowerable, they would only burn window re-confirming it)
  3. tpu_tests: on-chip test module (tests/test_tpu.py, generous timeout)
  4. ell_chunk_{16,64,128}: NTS_ELL_CHUNK_MIB tuning on the eager/ELL path
  5. eager_pallas / standard_pallas / eager_bsp / bsp_vt_{2048,1024} /
     eager_blocked: the other full-scale kernel paths — pallas = the
     Mosaic bsp kernel at the default src tile, eager_bsp/bsp_vt_* sweep
     the src-tile height (W-build cost vs block count)
  6. eager_scatter_fence: lane-pad A/B for the PERF §2a scatter cliff
  7. aot_dist_blocked / aot_dist_bsp: full-scale 8-way dist capacity
     compiles (compiler-only)
  7a. aot_bsp_10x: segmented bsp kernel menu-band compile at 10x Reddit
      (compiler-only, tools/aot_bsp_scale)
  7b. ell_breakdown: NTS_DEBUGINFO per-phase breakdown of full-scale ELL
  8. bench_matrix: workload matrix over configs/ (tools/bench_matrix)
  9. sampled_bench: fan-out-sampled mini-batch at Reddit scale
  10. profile_trace: steady-state trace of standard/ELL (NTS_PROFILE_DIR)

Artifacts land in the --out dir (default docs/perf_runs/round4/):
per-step .log (stderr tail),
.json (the step's final JSON line, when it prints one), .ok marker
(resumability), and a `status` append-log with timestamps. The supervisor
itself NEVER initializes the accelerator — probes and steps are
subprocesses with hard timeouts, so a wedged PJRT init can only cost a
bounded wait (round-1 lesson, bench.py:20-34).

A step failing while the backend still answers the probe is a real
failure: it is retried up to --step-retries times, then recorded as
.failed and skipped so the plan always terminates. A step failing with
the backend down goes back to the waiting loop with the step still
pending.

The chip lease and the remote COMPILER fail independently; the
COMPILER_ONLY_STEPS (topology AOT capacity checks) run during chip-down
windows whenever a cheap topology-compile probe answers, judged against
the compiler probe for retry accounting.

Usage: python -m neutronstarlite_tpu.tools.tpu_plan [--out DIR]
         [--poll-s 120] [--max-wall-s 32400] [--probe-timeout-s 240]
         [--only step1,step2] [--list]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# ONE probe program for both tools: bench.py owns it (lease-release
# retries etc. land in one place); this tool differs only in env handling
from bench import _PROBE_SRC  # noqa: E402

# The chip lease and the remote COMPILER are separate services (2026-07-31:
# the compiler served a full day of topology AOT compiles while every chip
# init hung on a wedged lease). Steps in this set need only the compiler —
# when the chip probe fails, a cheap topology-compile probe decides whether
# these can run anyway instead of idling the window away.
COMPILER_ONLY_STEPS = {"aot_dist_blocked", "aot_dist_bsp", "aot_bsp_10x"}

_COMPILER_PROBE_SRC = r"""
import json, time
t0 = time.time()
import numpy as np
import jax
from jax.experimental import topologies
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS
topo = topologies.get_topology_desc(platform="tpu", topology_name="v5e:2x2")
mesh = Mesh(np.array(list(topo.devices)[:1]), ("x",))
sds = jax.ShapeDtypeStruct(
    (128, 128), jax.numpy.float32, sharding=NamedSharding(mesh, PS())
)
jax.jit(lambda a: a @ a).lower(sds).compile()
print(json.dumps({"ok": True, "compile_probe_s": round(time.time() - t0, 1)}))
"""


def _bench(*extra, epochs=3, warmup=1):
    return [
        sys.executable, os.path.join(REPO, "bench.py"), "--sweep", "off",
        "--epochs", str(epochs), "--warmup", str(warmup), *extra,
    ]


def build_steps(out_dir: str):
    """(name, cmd, timeout_s, env_overrides) in execution order."""
    matrix_epochs = os.environ.get("NTS_PLAN_MATRIX_EPOCHS", "3")
    return [
        # the north-star number FIRST: if the tunnel recovers late in a
        # round, the headline measurement must not queue behind anything
        (
            "bench_full",
            [sys.executable, os.path.join(REPO, "bench.py")],
            5100,
            {"NTS_BENCH_DEADLINE_S": "4800"},
        ),
        (
            # reproducible §1 micro table incl. the round-3 kernels
            "micro_kernels",
            [sys.executable, "-m", "neutronstarlite_tpu.tools.micro_bench"],
            1800,
            {},
        ),
        # round-3 hang triage, round-4 cut: the resident/fchunked pallas
        # ops are PROVEN un-lowerable (the Mosaic gather reckoning,
        # PERF.md §5) and the remote compile service hangs rather than
        # erroring on them — each step would burn its full 1200 s of a
        # chip window re-confirming a settled question. Only the bsp
        # triage (the real PALLAS:1 kernel, per-op at the full 233k-row
        # table) keeps its slot.
        (
            "fullv_bsp",
            [sys.executable, "-m",
             "neutronstarlite_tpu.tools.micro_bench",
             "--scale", "2.0", "--ops", "bsp_streamed"],
            1200,
            {},
        ),
        (
            "tpu_tests",
            [sys.executable, "-m", "pytest",
             os.path.join(REPO, "tests", "test_tpu.py"), "-q", "-rs"],
            2400,
            {"NTS_TPU_TEST_TIMEOUT_S": "1800"},
        ),
        *[
            (
                f"ell_chunk_{mib}",
                # eager order: at full scale aggregation runs at post-matmul
                # widths (128/41 not 602) — 4.7x less gather traffic on
                # layer 1, the expected production order for the chunk tune
                _bench("--order", "eager", "--path", "ell"),
                1800,
                # bench's internal watchdog must fire BEFORE the external
                # process-group kill: it dumps stacks and salvages the
                # final JSON line, both lost to a bare SIGKILL
                {"NTS_ELL_CHUNK_MIB": str(mib),
                 "NTS_BENCH_DEADLINE_S": "1500"},
            )
            for mib in (16, 64, 128)
        ],
        (
            "eager_pallas",
            _bench("--order", "eager", "--path", "pallas"),
            1800,
            {"NTS_BENCH_DEADLINE_S": "1500"},
        ),
        (
            # PALLAS:1 = the Mosaic bsp kernel at the default src tile;
            # the standard order prices its one-hot matmuls at f=602
            "standard_pallas",
            _bench("--order", "standard", "--path", "pallas"),
            1800,
            {"NTS_BENCH_DEADLINE_S": "1500"},
        ),
        (
            # the Mosaic bsp kernel at an explicit large src tile (8192)
            # vs the default-vt pallas legs and the small-vt sweep below
            "eager_bsp",
            _bench("--order", "eager", "--path", "bsp"),
            # measured: the full-scale packed-block host build is ~276 s
            # per direction (1-core, numpy) — budget both + compile + runs
            3600,
            {"NTS_BENCH_DEADLINE_S": "3300"},
        ),
        *[
            (
                # src-tile sensitivity of the Mosaic bsp kernel: the
                # in-kernel W build costs O(R * vt * K) VPU compares per
                # block while the block count grows sublinearly as vt
                # shrinks — the optimum is expected BELOW the streaming
                # defaults (8192/4096); eager_bsp + the pallas leg anchor
                # the high end
                f"bsp_vt_{vt}",
                _bench("--order", "eager", "--path", "bsp",
                       "--kernel-tile", str(vt)),
                3600,
                {"NTS_BENCH_DEADLINE_S": "3300"},
            )
            # vt=1024 dropped: 375.6k blocks overflow the 1 MB SMEM key
            # budget AND pad slots 3.36x (aotwarm_rpathbspkerneltile1024)
            for vt in (2048,)
        ],
        (
            "eager_blocked",
            # full-scale blocked host tables are ~2 min/direction on this
            # 1-core rig; the stacked layout's compile is seconds
            _bench("--order", "eager", "--path", "blocked"),
            3600,
            {"NTS_BENCH_DEADLINE_S": "3300"},
        ),
        (
            # round 3: A/B for the eager/scatter full-scale cliff fence
            # (docs/PERF.md 2a anomaly; ops/aggregate._lane_pad_width).
            # bench_full's sweep times plain eager/scatter; this step times
            # the lane-padded variant — together they decide the default
            "eager_scatter_fence",
            _bench("--order", "eager", "--path", "scatter", epochs=2),
            1800,
            {"NTS_SCATTER_LANE_PAD": "1", "NTS_BENCH_DEADLINE_S": "1500"},
        ),
        (
            # round 3: full-scale 8-way AOT capacity check of the
            # KERNEL_TILE dist path (VERDICT item 5's "full-scale
            # aot_check compile"); needs the remote TPU compiler, no chips
            "aot_dist_blocked",
            [sys.executable, "-m", "neutronstarlite_tpu.tools.aot_check",
             os.path.join(REPO, "configs", "gcn_reddit_full_dist_blocked.cfg"),
             "--topology", "v5e:2x4", "--synthetic-scale", "1.0"],
            3600,
            {},
        ),
        (
            # full-scale 8-way AOT capacity check of the PALLAS:1 dist
            # path (per-shard Mosaic bsp over the all_gathered slab)
            "aot_dist_bsp",
            [sys.executable, "-m", "neutronstarlite_tpu.tools.aot_check",
             os.path.join(REPO, "configs", "gcn_reddit_full_dist_bsp.cfg"),
             "--topology", "v5e:2x4", "--synthetic-scale", "1.0"],
            3600,
            {},
        ),
        (
            # round 4: the segmented bsp kernel's 10x-Reddit capacity
            # proof (VERDICT r3 item 3) — envelope program at the SMEM
            # cap against the topology compiler, no chip needed
            "aot_bsp_10x",
            [sys.executable, "-m", "neutronstarlite_tpu.tools.aot_bsp_scale",
             "--scale", "10.0"],
            1800,
            {},
        ),
        (
            # round 4: NTS_DEBUGINFO per-phase breakdown of the full-scale
            # production path (VERDICT r3 item 2's attribution input) —
            # separate from profile_trace so timer syncs can't pollute
            # the profiler's steady-state capture
            "ell_breakdown",
            _bench("--order", "standard", "--path", "ell"),
            1800,
            {"NTS_DEBUGINFO": "1", "NTS_BENCH_DEADLINE_S": "1500"},
        ),
        (
            "bench_matrix",
            [sys.executable, "-m", "neutronstarlite_tpu.tools.bench_matrix",
             "--configs", os.path.join(REPO, "configs"),
             "--epochs", matrix_epochs],
            3600,
            {},
        ),
        (
            "sampled_bench",
            # the OTHER headline training mode: fan-out-sampled mini-batch
            # at Reddit scale (shares bench.py's on-disk graph cache)
            [sys.executable, "-m", "neutronstarlite_tpu.tools.bench_sample"],
            1800,
            {},
        ),
        (
            "profile_trace",
            _bench("--order", "standard", "--path", "ell"),
            1800,
            {"NTS_PROFILE_DIR": os.path.join(out_dir, "profile"),
             "NTS_BENCH_DEADLINE_S": "1500"},
        ),
    ]


class Plan:
    def __init__(self, out_dir: str, probe_timeout_s: float, step_retries: int):
        self.out = out_dir
        self.probe_timeout_s = probe_timeout_s
        self.step_retries = step_retries
        os.makedirs(out_dir, exist_ok=True)

    def log(self, msg: str):
        line = f"[{time.strftime('%Y-%m-%d %H:%M:%S')}] {msg}"
        print(line, flush=True)
        with open(os.path.join(self.out, "status"), "a") as fh:
            fh.write(line + "\n")

    def probe(self) -> dict | None:
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)  # a CI cpu pin would make the probe
        # trivially "succeed" on CPU and defeat backend-down detection
        try:
            r = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                capture_output=True, text=True, timeout=self.probe_timeout_s,
                cwd=REPO, env=env,
            )
        except subprocess.TimeoutExpired:
            return None
        if r.returncode != 0 or not r.stdout.strip():
            return None
        try:
            return json.loads(r.stdout.strip().splitlines()[-1])
        except json.JSONDecodeError:
            return None

    def probe_compiler(self) -> bool:
        """Is the remote TPU COMPILER answering (chip not required)? The
        probe compiles a trivial program against a topology in a bounded
        subprocess on the CPU host platform."""
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"  # host side only; the compile goes to
        # the topology compiler, never to chips
        try:
            r = subprocess.run(
                [sys.executable, "-c", _COMPILER_PROBE_SRC],
                capture_output=True, text=True,
                timeout=self.probe_timeout_s, cwd=REPO, env=env,
            )
        except subprocess.TimeoutExpired:
            return False
        return r.returncode == 0 and '"ok": true' in r.stdout

    def _paths(self, name):
        return {
            ext: os.path.join(self.out, f"{name}.{ext}")
            for ext in ("ok", "failed", "log", "json", "tries")
        }

    def pending(self, steps):
        out = []
        for name, cmd, timeout_s, env_over in steps:
            p = self._paths(name)
            if not (os.path.exists(p["ok"]) or os.path.exists(p["failed"])):
                out.append((name, cmd, timeout_s, env_over))
        return out

    def run_step(self, name, cmd, timeout_s, env_over) -> bool:
        """Returns True when the step reached a terminal state (ok/failed);
        False when the backend died under it (leave pending, re-wait)."""
        p = self._paths(name)
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)  # accelerator, not the CI cpu pin
        env.update(env_over)
        self.log(f"step {name}: start (timeout {timeout_s}s) {' '.join(cmd)}")
        t0 = time.time()
        # child stdout/stderr go straight to files: on POSIX, TimeoutExpired
        # carries stdout=None with capture_output, which would lose exactly
        # the already-printed JSON line the salvage below exists to keep
        out_path = os.path.join(self.out, f"{name}.stdout")
        err_path = os.path.join(self.out, f"{name}.stderr")
        timed_out = False
        with open(out_path, "w") as out_fh, open(err_path, "w") as err_fh:
            # new session: on timeout, kill the WHOLE process group — a
            # bench step's measurement workers are grandchildren, and an
            # orphaned worker wedged in a compile would keep the single
            # accelerator's lease and fail every later probe
            proc = subprocess.Popen(
                cmd, stdout=out_fh, stderr=err_fh, env=env, cwd=REPO,
                start_new_session=True,
            )
            try:
                rc = proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                timed_out = True
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
                rc = proc.wait()
        wall = time.time() - t0
        with open(out_path) as fh:
            out_s = fh.read()
        with open(err_path) as fh:
            err_s = fh.read()
        if timed_out:
            err_s += f"\nSTEP TIMEOUT after {timeout_s}s (process group killed)"
        with open(p["log"], "w") as fh:
            fh.write(f"# {name} rc={rc} wall={wall:.0f}s\n# cmd: {' '.join(cmd)}\n")
            fh.write(f"# env: {json.dumps(env_over)}\n\n--- stdout ---\n")
            fh.write(out_s[-20000:])
            fh.write("\n--- stderr (tail) ---\n")
            fh.write(err_s[-20000:])
        os.unlink(out_path)
        os.unlink(err_path)
        # salvage the final JSON line even from a failed/timed-out step
        # (bench prints it before a final-eval hang can kill the process)
        for line in reversed(out_s.strip().splitlines() or [""]):
            line = line.strip()
            if line.startswith("{") and line.endswith("}"):
                try:
                    parsed = json.loads(line)
                    with open(p["json"], "w") as fh:
                        json.dump(parsed, fh, indent=1)
                    break
                except json.JSONDecodeError:
                    continue
        if rc == 0:
            with open(p["ok"], "w") as fh:
                fh.write(f"wall={wall:.0f}s\n")
            self.log(f"step {name}: OK in {wall:.0f}s")
            return True
        # rc != 0 — is this the step's fault or did the service die under
        # it? Compiler-only steps are judged against the COMPILER probe
        # (they run in chip-down windows where the chip probe always fails
        # — chip-probing them would retry forever without accounting)
        alive = (
            self.probe_compiler()
            if name in COMPILER_ONLY_STEPS
            else self.probe() is not None
        )
        if not alive:
            self.log(
                f"step {name}: rc={rc} after {wall:.0f}s with backend DOWN — "
                "left pending, back to waiting"
            )
            return False
        tries = 1
        if os.path.exists(p["tries"]):
            with open(p["tries"]) as fh:
                tries = int(fh.read().strip() or 0) + 1
        with open(p["tries"], "w") as fh:
            fh.write(str(tries))
        if tries > self.step_retries:
            with open(p["failed"], "w") as fh:
                fh.write(f"rc={rc} wall={wall:.0f}s tries={tries}\n")
            self.log(
                f"step {name}: FAILED permanently (rc={rc}, try {tries}) — "
                f"see {p['log']}"
            )
        else:
            self.log(f"step {name}: rc={rc} (try {tries}, backend up) — will retry")
        return True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--out", default=os.path.join(REPO, "docs", "perf_runs", "round4")
    )
    ap.add_argument("--poll-s", type=float, default=120.0)
    ap.add_argument("--max-wall-s", type=float, default=32400.0)
    ap.add_argument("--probe-timeout-s", type=float, default=240.0)
    ap.add_argument("--step-retries", type=int, default=2)
    ap.add_argument("--only", default="", help="comma-separated step subset")
    ap.add_argument("--list", action="store_true", help="print steps and exit")
    args = ap.parse_args(argv)

    plan = Plan(args.out, args.probe_timeout_s, args.step_retries)
    steps = build_steps(args.out)
    if args.only:
        keep = set(args.only.split(","))
        unknown = keep - {s[0] for s in steps}
        if unknown:
            print(f"unknown steps: {sorted(unknown)}", file=sys.stderr)
            return 2
        steps = [s for s in steps if s[0] in keep]
    if args.list:
        for name, cmd, timeout_s, env_over in steps:
            print(f"{name:15s} timeout={timeout_s:5d}s env={env_over}")
        return 0

    t0 = time.time()
    plan.log(f"plan start: {len(plan.pending(steps))}/{len(steps)} steps pending")
    backend_known_up = False  # skip re-probing right after a successful step
    while time.time() - t0 < args.max_wall_s:
        todo = plan.pending(steps)
        if not todo:
            plan.log("plan COMPLETE")
            return 0
        if not backend_known_up:
            info = plan.probe()
            if info is None:
                # chip down — but AOT-only steps can ride a compiler-only
                # window (the two services fail independently)
                comp_todo = [
                    s for s in todo if s[0] in COMPILER_ONLY_STEPS
                ]
                if comp_todo and plan.probe_compiler():
                    plan.log(
                        f"chip down but COMPILER answers: running "
                        f"{len(comp_todo)} AOT step(s)"
                    )
                    for s in comp_todo:
                        if not plan.run_step(*s):
                            # compiler died under the step: don't launch
                            # the next AOT step into a known-dead service
                            break
                    continue
                plan.log(
                    f"backend down ({len(todo)} steps pending); "
                    f"sleeping {args.poll_s:.0f}s"
                )
                time.sleep(args.poll_s)
                continue
            plan.log(
                f"backend up: {info.get('devices')} init {info.get('init_s')}s"
            )
        name, cmd, timeout_s, env_over = todo[0]
        if name in COMPILER_ONLY_STEPS and not plan.probe_compiler():
            # chip up, compiler down: an AOT step would fail with no retry
            # accounting (its failures are judged by the compiler probe) —
            # run a chip step instead, or sleep if none remain
            others = [s for s in todo if s[0] not in COMPILER_ONLY_STEPS]
            if not others:
                plan.log(
                    "only compiler-only steps pending and the compiler is "
                    f"down; sleeping {args.poll_s:.0f}s"
                )
                time.sleep(args.poll_s)
                continue
            name, cmd, timeout_s, env_over = others[0]
        # a terminal step outcome with rc==0 proves the backend is healthy
        # — but a compiler-only step's success proves only the COMPILER, so
        # the next (chip) step must re-probe; any failure path re-probes too
        backend_known_up = (
            plan.run_step(name, cmd, timeout_s, env_over)
            and os.path.exists(os.path.join(args.out, f"{name}.ok"))
            and name not in COMPILER_ONLY_STEPS
        )
    plan.log(f"max wall {args.max_wall_s:.0f}s reached; "
             f"{len(plan.pending(steps))} steps still pending")
    return 1


if __name__ == "__main__":
    sys.exit(main())
