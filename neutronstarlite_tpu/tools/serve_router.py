"""Run the cross-host serve router (serve/crosshost) as a process.

Two modes:

**Spawn (supervision) mode** — ``<cfg> <ckpt>`` given: fork
``--replicas`` serve children (each its own process, its own exporter
port carrying /predict + the scrape surfaces, compile-warm from the
shared tune cache), then route, supervise (miss-K ``target_loss`` ->
respawn from the recorded launch recipe, ``recovery action=restart``)
and — with ``--rollout CKPT`` — perform one rolling model rollout
(digest preflight -> canary gate under ``NTS_CANARY_TOL`` -> drain +
restart one replica at a time, rollback on abort).

**Targets (discovery) mode** — ``--targets host:port,...`` (or
``NTS_FLEET_TARGETS``): route and aggregate over already-running
replicas. No launch recipes, so replica death stays a ``target_loss``
(the fleet serves on the survivors) and rollout is refused.

Usage:
  python -m neutronstarlite_tpu.tools.serve_router <cfg> <ckpt>
      [--replicas N]     children to spawn (default 3)
      [--targets T,T]    discovery mode instead of spawning
      [--poll S]         router poll interval (default 0.5)
      [--miss-k K]       missed polls before loss/restart
                         (NTS_HUB_MISS_K, default 3)
      [--polls N]        report N status cycles then exit
                         (default: forever; ^C exits cleanly)
      [--ledger DIR]     append kind=fleet rows (default NTS_LEDGER_DIR)
      [--ledger-every N] one row per N polls
      [--rollout CKPT]   roll the fleet onto CKPT after --rollout-after
                         status cycles, then keep serving
      [--rollout-after N] (default 2)
      [--json]           one JSON status line per cycle

Exit 0 on a completed bounded run or clean ^C; 1 on setup errors; 3 when
a requested rollout did not promote (the fleet still exits cleanly on
its surviving checkpoint — a refused rollout is a verdict, not a crash).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from neutronstarlite_tpu.obs import ledger
from neutronstarlite_tpu.serve import crosshost


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="cross-host serve router: spawn/supervise replica "
        "processes, route over HTTP, rolling rollout with canary gate"
    )
    ap.add_argument("cfg", nargs="?", default="")
    ap.add_argument("ckpt", nargs="?", default="")
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--targets", default=None,
                    help="comma-separated replica addresses (discovery "
                    "mode; default spawn mode needs cfg+ckpt)")
    ap.add_argument("--poll", type=float, default=0.5)
    ap.add_argument("--miss-k", type=int, default=None)
    ap.add_argument("--polls", type=int, default=None,
                    help="status cycles to report before exiting")
    ap.add_argument("--ledger", default=None)
    ap.add_argument("--ledger-every", type=int, default=1)
    ap.add_argument("--rollout", default=None,
                    help="checkpoint dir to roll the fleet onto")
    ap.add_argument("--rollout-after", type=int, default=2)
    ap.add_argument("--spawn-dir", default=None,
                    help="port-file directory (spawn mode)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    common = dict(
        poll_s=args.poll, miss_k=args.miss_k,
        ledger_dir=args.ledger or ledger.ledger_dir(),
        ledger_every=args.ledger_every,
    )
    try:
        if args.targets is not None or (not args.cfg and
                                        crosshost.fleet_targets()):
            targets = ([t.strip() for t in args.targets.split(",")
                        if t.strip()] if args.targets else None)
            fleet = crosshost.CrossHostFleet.from_targets(targets, **common)
        elif args.cfg and args.ckpt:
            fleet = crosshost.CrossHostFleet.spawn(
                args.cfg, args.ckpt, args.replicas,
                spawn_dir=args.spawn_dir, **common,
            )
        else:
            print("serve_router: need <cfg> <ckpt> (spawn mode) or "
                  "--targets/NTS_FLEET_TARGETS (discovery mode)",
                  file=sys.stderr)
            return 1
    except (ValueError, RuntimeError, TimeoutError, OSError) as e:
        print(f"serve_router: {e}", file=sys.stderr)
        return 1

    for r in fleet.replicas:
        print(f"serve_router: replica {r.rid} -> {r.base_url}"
              + (f" (pid {r.proc.pid})" if r.proc is not None else ""),
              file=sys.stderr, flush=True)

    rollout_verdict = None
    n = 0
    try:
        while args.polls is None or n < args.polls:
            time.sleep(max(args.poll, 0.05))
            n += 1
            s = fleet.stats()
            if args.json:
                print(json.dumps({"cycle": n, **s}), flush=True)
            else:
                lat = s["latency_ms"]
                print(
                    f"serve_router: cycle {n}: "
                    f"{s['replicas'] - s['targets_lost']}/{s['replicas']} "
                    f"replica(s) ok, {s['requests']} served, "
                    f"{s['shed']} shed, {s['restarts']} restart(s), "
                    f"p99={lat.get('p99')}",
                    file=sys.stderr, flush=True,
                )
            if args.rollout and rollout_verdict is None and \
                    n >= args.rollout_after:
                rec = fleet.rollout(args.rollout)
                rollout_verdict = rec["verdict"]
                print(f"serve_router: rollout {rollout_verdict}: "
                      f"{json.dumps(rec)}", file=sys.stderr, flush=True)
    except KeyboardInterrupt:
        print("serve_router: interrupted; closing the fleet",
              file=sys.stderr)
    finally:
        stats = fleet.close()
        print(f"serve_router: closed: {json.dumps(stats)}",
              file=sys.stderr, flush=True)
    if args.rollout and rollout_verdict != "promoted":
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
