"""Merge per-rank span streams into one causal timeline.

The span records (obs/trace.py) land in per-rank JSONL files with
process-local monotonic clocks. This CLI reconstructs one coherent view:

1. **mono->wall recovery** per stream: a span record is written
   immediately after its span ends, so the envelope wall-clock ``ts``
   sits just past ``t0 + dur_s`` — ``median(ts - (t0 + dur_s))`` over a
   stream's spans is that process's monotonic->wall offset (robust to a
   few delayed writes; see docs/OBSERVABILITY.md for the caveats).
2. **epoch-marker rank alignment**: every rank ends epoch *e* at the same
   collective barrier, so per-epoch spans are cross-rank fence posts —
   each rank is shifted by the median difference of its epoch-end times
   against the reference (lowest) rank. Wall clocks that agree within the
   epoch time are left essentially untouched; skewed hosts snap into
   place.
3. **Chrome trace-event export** (``--chrome out.json``): complete ("X")
   events per span (pid = rank, tid = host thread), instant events for
   fault / recovery / shed / rank_loss / replan / tune_trial /
   tune_decision records — loadable in
   Perfetto or chrome://tracing. When the run also wrote a ``jax.profiler`` trace
   (``NTS_PROFILE_DIR``), the host spans were emitted as
   ``TraceAnnotation``s inside it too, so the device-op view carries the
   same names — open both in one Perfetto window to line host causality
   up with kernel truth.
4. **Derived metrics** printed as the timeline report (and rendered by
   tools/metrics_report as its "span timeline" block):
   - ring overlap efficiency — the NTS_OVERLAP_PROBE verdict (hop time
     hidden under blocked-kernel compute / total hop time);
   - serve critical path — per-request stage breakdown
     (queue -> cache_lookup -> sample -> execute -> reply), joined to the
     ``serve_request`` records by ``req_id``; the stage sum must match
     the recorded end-to-end latency (the tests pin the tolerance);
   - retry cost — per fault episode, time from the fault record to the
     first epoch completed after recovery, plus replayed-epoch counts;
   - elastic time-to-recover — per survivor replan, the time from the
     rank_loss detection record to the first post-replan epoch end.

5. **Cross-PROCESS fleet merge** (``--fleet``): the serve fabric's
   streams (router + N replica processes) share no epoch barriers, so
   step 2 cannot align them. They DO share distributed-trace clock
   pairs: every traced HTTP hop stamps the client's wall clock into the
   ``X-NTS-Send-Ts`` header and the server's at extraction, so each
   server-side handler span carries ``(send_ts, recv_ts)`` — two wall
   clocks taken one network hop apart — and its ``parent_id`` names the
   client-side span (in a DIFFERENT stream) whose envelope ``ts`` closes
   the exchange. NTP-style per pair, with t0=send_ts (client),
   t1=recv_ts (server), t2=server envelope ts (~reply write),
   t3=client envelope ts (~response received)::

       offset(server-client) = ((t1-t0) + (t2-t3)) / 2
       rtt                   = (t3-t0) - (t2-t1)

   The estimate's error is bounded by rtt/2 (the classic NTP bound: the
   true offset lies within ±rtt/2 of the estimate, reached only when the
   hop is fully asymmetric). Per connected stream the shift applied is
   the MEDIAN offset over its pairs, chained transitively (bounds add)
   when a stream only reaches the reference through another process.
   Streams with no pairs keep their own wall clock and a warning names
   why (the same warn-not-crash taxonomy as step 2). The fleet-merged
   Chrome export gives each PROCESS its own pid, and the per-request
   report joins spans by ``trace_id`` into client->router->replica->
   engine chains: complete-chain fraction, ``router_overhead_ms =
   client_latency - replica_stage_sum``, retry/re-route/suspect counts,
   and the prediction freshness lineage (``graph_seq``/``model_seq``).

Usage:
  python -m neutronstarlite_tpu.tools.trace_timeline <file-or-dir> [...]
      [--chrome OUT.json] [--json] [--fleet]
Exit 0 when at least one stream yielded a timeline; 1 otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
from typing import Any, Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from neutronstarlite_tpu.tools.metrics_report import (  # noqa: E402
    expand_paths,
    load_events,
)


def _median(vals: List[float]) -> Optional[float]:
    return statistics.median(vals) if vals else None


def _quantile(sorted_vals: List[float], q: float) -> Optional[float]:
    """Linear-interpolation quantile over an ALREADY-SORTED list."""
    if not sorted_vals:
        return None
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    return sorted_vals[lo] + (sorted_vals[hi] - sorted_vals[lo]) * (pos - lo)


def spans_of(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    return [e for e in events if e["event"] == "span"]


def stream_rank(events: List[Dict[str, Any]], path: str) -> int:
    """Rank of one stream: run_start.process_index, span.rank, or the
    ``-pN.jsonl`` filename convention; 0 when nothing says otherwise."""
    for e in events:
        if e["event"] == "run_start" and isinstance(
            e.get("process_index"), int
        ):
            return e["process_index"]
    for e in events:
        if e["event"] == "span" and isinstance(e.get("rank"), int):
            return e["rank"]
    stem = os.path.basename(path)
    if "-p" in stem:
        tail = stem.rsplit("-p", 1)[1].split(".", 1)[0]
        if tail.isdigit():
            return int(tail)
    return 0


def mono_wall_offset(events: List[Dict[str, Any]]) -> Optional[float]:
    """Monotonic->wall offset for one stream (docstring step 1)."""
    return _median([
        e["ts"] - (e["t0"] + e["dur_s"]) for e in spans_of(events)
    ])


class Stream:
    """One per-rank JSONL file with its clock corrections resolved."""

    def __init__(self, path: str, events: List[Dict[str, Any]]):
        self.path = path
        self.events = events
        self.rank = stream_rank(events, path)
        self.pid = self.rank  # Chrome pid; fleet mode re-keys per PROCESS
        self.offset = mono_wall_offset(events)  # mono -> wall (step 1)
        self.align = 0.0  # cross-rank/process shift (step 2 or 5)
        self.skew_bound: Optional[float] = None  # fleet_align's rtt/2 bound
        self.align_warning: Optional[str] = None  # set by align_streams
        self.run_id = next(
            (e["run_id"] for e in events if e.get("run_id")), "?"
        )

    def span_wall(self, span: Dict[str, Any]) -> Optional[float]:
        """Aligned wall-clock start of ``span`` (None without an offset —
        a stream with no spans has nothing to place on the timeline)."""
        if self.offset is None:
            return None
        return span["t0"] + self.offset + self.align

    def epoch_ends(self) -> Dict[int, float]:
        """{epoch: aligned wall end} from this stream's epoch spans."""
        out: Dict[int, float] = {}
        if self.offset is None:
            return out
        for s in spans_of(self.events):
            if s["name"] == "epoch" and isinstance(s.get("epoch"), int):
                out[s["epoch"]] = (
                    s["t0"] + s["dur_s"] + self.offset + self.align
                )
        return out


def align_streams(streams: List["Stream"]) -> None:
    """Epoch-marker alignment (docstring step 2), in place: the lowest
    rank with epoch spans anchors; every other stream shifts by the median
    epoch-end difference over shared epochs. Streams sharing no epochs
    (e.g. a serve-only stream next to a training stream) keep wall time.

    Failure mode is WARN, not crash: a span-bearing stream with no epoch
    markers (or none shared with the anchor) cannot be cross-rank
    corrected — it keeps its own wall clock (``align=0``), which may sit
    skewed against the other ranks by each host's clock error. The
    stream's ``align_warning`` names the reason and a stderr line
    surfaces it, so a skewed-looking timeline says WHY instead of
    silently interleaving misaligned ranks."""
    anchored = sorted(
        (s for s in streams if s.epoch_ends()), key=lambda s: s.rank
    )
    if not anchored:
        for s in streams:
            if any(True for _ in spans_of(s.events)):
                s.align_warning = (
                    "no stream carries epoch spans: cross-rank alignment "
                    "skipped (each stream keeps its own wall clock)"
                )
                print(f"{s.path}: {s.align_warning}", file=sys.stderr)
        return
    ref = anchored[0].epoch_ends()
    aligned_ids = {id(a) for a in anchored}
    for s in anchored[1:]:
        own = s.epoch_ends()
        deltas = [ref[e] - own[e] for e in ref.keys() & own.keys()]
        d = _median(deltas)
        if d is not None:
            s.align = d
        else:
            s.align_warning = (
                f"shares no epochs with the anchor (rank "
                f"{anchored[0].rank}): cross-rank alignment skipped for "
                "this stream (kept on its own wall clock)"
            )
            print(f"{s.path}: warning: {s.align_warning}", file=sys.stderr)
    for s in streams:
        if id(s) in aligned_ids or s.offset is None:
            continue
        # spans but no epoch markers at all (a serve/probe stream, or a
        # trainer that died before epoch 0 closed)
        s.align_warning = (
            "stream has spans but no epoch markers: cross-rank alignment "
            "skipped for this stream (kept on its own wall clock)"
        )
        print(f"{s.path}: warning: {s.align_warning}", file=sys.stderr)


def load_streams(paths: List[str], fleet: bool = False) -> List[Stream]:
    """Load + align. ``fleet=True`` switches step-2 epoch alignment for
    the step-5 clock-pair alignment (serve-fabric processes share no
    epoch barriers, so epoch alignment is meaningless across them)."""
    streams = []
    for p in paths:
        try:
            events = load_events(p)
        except OSError as e:
            print(f"{p}: {e}", file=sys.stderr)
            continue
        if events:
            streams.append(Stream(p, events))
    if fleet:
        fleet_align(streams)
    else:
        align_streams(streams)
    return streams


# ---------------------------------------------------------------------------
# Cross-process fleet merge (docstring step 5)
# ---------------------------------------------------------------------------


def clock_pairs(streams: List[Stream]) -> Dict[tuple, List[tuple]]:
    """Collect the distributed-trace clock pairs between streams.

    A pair comes from one traced HTTP hop: the SERVER-side span carries
    ``send_ts`` (client wall, from the X-NTS-Send-Ts header) and
    ``recv_ts`` (server wall at extraction) as attributes, and its
    ``parent_id`` names the CLIENT-side span — which must live in a
    DIFFERENT stream. (Replica-internal spans inherit the stamps via the
    handler's context but parent within their own stream, so the
    different-stream rule keeps them out of the clock estimate.)

    Returns ``{(client_idx, server_idx): [(offset_s, rtt_s), ...]}``
    with ``offset = server_wall - client_wall``.
    """
    # client-span index: (trace_id, span_id) -> stream idx + envelope ts
    client_idx: Dict[tuple, tuple] = {}
    for i, st in enumerate(streams):
        for s in spans_of(st.events):
            client_idx[(s.get("trace_id"), s["span_id"])] = (i, s["ts"])
    edges: Dict[tuple, List[tuple]] = {}
    for j, st in enumerate(streams):
        for s in spans_of(st.events):
            send_ts = s.get("send_ts")
            recv_ts = s.get("recv_ts")
            if send_ts is None or recv_ts is None or not s.get("parent_id"):
                continue
            hit = client_idx.get((s.get("trace_id"), s["parent_id"]))
            if hit is None or hit[0] == j:
                continue
            i, t3 = hit
            t0, t1, t2 = float(send_ts), float(recv_ts), float(s["ts"])
            offset = ((t1 - t0) + (t2 - t3)) / 2.0
            rtt = (t3 - t0) - (t2 - t1)
            edges.setdefault((i, j), []).append((offset, max(rtt, 0.0)))
    return edges


def fleet_align(streams: List[Stream]) -> Dict[str, Any]:
    """Clock-pair alignment across PROCESSES, in place.

    The reference is the stream with the most client-side hops (the
    router — it talks to everyone). Every stream reachable through clock
    pairs is shifted by the median pair offset onto the reference's wall
    clock, chaining transitively (BFS; error bounds add per hop, each
    hop's bound = min rtt/2 over its pairs — the NTP bound). Streams
    with spans but no pairs keep their own wall clock and get an
    ``align_warning`` (warn, not crash). Also re-keys ``Stream.pid`` per
    process so the Chrome export separates processes that share rank 0.
    """
    for i, st in enumerate(streams):
        st.pid = i
    info: Dict[str, Any] = {"reference": None, "streams": []}
    edges = clock_pairs(streams)
    delta: Dict[int, tuple] = {}
    if edges:
        # undirected adjacency with a signed median offset per edge
        adj: Dict[int, Dict[int, tuple]] = {}
        client_hops = [0] * len(streams)
        for (i, j), pairs in edges.items():
            client_hops[i] += len(pairs)
            med = statistics.median(p[0] for p in pairs)
            bound = min(p[1] for p in pairs) / 2.0
            # offset(j - i) = med; store both directions
            adj.setdefault(i, {})[j] = (med, bound, len(pairs))
            adj.setdefault(j, {})[i] = (-med, bound, len(pairs))
        ref = max(range(len(streams)), key=lambda k: client_hops[k])
        info["reference"] = streams[ref].path
        # BFS: delta[k] = wall(k) - wall(ref); mapping k onto the
        # reference timeline subtracts it (align = -delta)
        delta[ref] = (0.0, 0.0)
        frontier = [ref]
        while frontier:
            nxt = []
            for u in frontier:
                du, bu = delta[u]
                for v, (off, bound, _n) in adj.get(u, {}).items():
                    if v in delta:
                        continue
                    delta[v] = (du + off, bu + bound)
                    nxt.append(v)
            frontier = nxt
        for k, (d, b) in delta.items():
            st = streams[k]
            if k != ref:
                st.align = -d
            st.skew_bound = b
            info["streams"].append({
                "path": st.path, "pid": st.pid,
                "offset_vs_ref_s": d, "skew_bound_s": b,
            })
    for i, st in enumerate(streams):
        if i in delta:
            continue
        if spans_of(st.events):
            st.align_warning = (
                "no distributed-trace clock pairs reach this stream: "
                "fleet alignment skipped (kept on its own wall clock)"
            )
            print(f"{st.path}: warning: {st.align_warning}",
                  file=sys.stderr)
    return info


# ---------------------------------------------------------------------------
# Chrome trace export
# ---------------------------------------------------------------------------

_INSTANT_KINDS = ("fault", "recovery", "shed", "rank_loss", "replan",
                  "tune_trial", "tune_decision", "slo_status",
                  "backend_probe", "delta_commit", "finetune_round")
_ENVELOPE_OR_SPAN = (
    "event", "run_id", "schema", "ts", "seq", "name", "cat", "span_id",
    "trace_id", "parent_id", "t0", "dur_s", "rank", "thread",
)


def chrome_trace(streams: List[Stream]) -> Dict[str, Any]:
    """Chrome trace-event JSON (the ``traceEvents`` container form).

    pid = rank (or one pid per PROCESS after ``fleet_align`` — serve
    fabrics share rank 0 across processes), tid = one int per
    (pid, host thread); metadata records name both. Spans become
    complete ("X") events; fault/recovery/shed records become
    process-scoped instants ("i")."""
    events: List[Dict[str, Any]] = []
    starts: List[float] = []
    for st in streams:
        for s in spans_of(st.events):
            w = st.span_wall(s)
            if w is not None:
                starts.append(w)
        if st.offset is not None:
            for e in st.events:
                if e["event"] in _INSTANT_KINDS:
                    starts.append(e["ts"] + st.align)
    t0 = min(starts) if starts else 0.0

    tids: Dict[tuple, int] = {}
    for st in streams:
        events.append({
            "ph": "M", "name": "process_name", "pid": st.pid, "tid": 0,
            "ts": 0,
            "args": {"name": f"rank {st.rank} · {st.run_id}"},
        })
        for s in spans_of(st.events):
            w = st.span_wall(s)
            if w is None:
                continue
            key = (st.pid, s.get("thread") or "main")
            tid = tids.get(key)
            if tid is None:
                tid = tids[key] = len(tids) + 1
                events.append({
                    "ph": "M", "name": "thread_name", "pid": st.pid,
                    "tid": tid, "ts": 0, "args": {"name": key[1]},
                })
            args = {
                k: v for k, v in s.items()
                if k not in _ENVELOPE_OR_SPAN and v is not None
            }
            args["span_id"] = s["span_id"]
            if s.get("parent_id"):
                args["parent_id"] = s["parent_id"]
            events.append({
                "ph": "X",
                "name": s["name"],
                "cat": s.get("cat") or "host",
                "pid": st.pid,
                "tid": tid,
                "ts": (w - t0) * 1e6,
                "dur": s["dur_s"] * 1e6,
                "args": args,
            })
        if st.offset is None:
            continue
        for e in st.events:
            if e["event"] not in _INSTANT_KINDS:
                continue
            label = (
                e.get("kind") or e.get("action") or e.get("reason") or ""
            )
            if e["event"] in ("tune_trial", "tune_decision"):
                # the candidate tuple (and decision source), readable off
                # the marker name in Perfetto
                label = str(e.get("candidate") or "?")
                if e["event"] == "tune_decision":
                    label = f"{label} [{e.get('source')}]"
            if e["event"] == "replan":
                # the elastic degradation, readable off the marker name
                label = (
                    f"{e.get('from_partitions')}->{e.get('to_partitions')}"
                )
            if e["event"] == "slo_status":
                # the burn-rate verdict, readable off the marker name
                label = f"{e.get('metric')}={e.get('state')}"
            if e["event"] == "backend_probe":
                label = f"attempt{e.get('attempt')}:{e.get('outcome')}"
            events.append({
                "ph": "i",
                "name": f"{e['event']}:{label}",
                "cat": "marker",
                "pid": st.pid,
                "tid": 0,
                "ts": (e["ts"] + st.align - t0) * 1e6,
                "s": "p",
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def validate_chrome_trace(trace: Any) -> int:
    """Structural check of a Chrome trace-event JSON object; returns the
    event count, raises ValueError on the first violation. This is the
    schema the tests (and any CI consumer) pin."""
    def fail(msg):
        raise ValueError(f"chrome trace: {msg}")

    if not isinstance(trace, dict) or not isinstance(
        trace.get("traceEvents"), list
    ):
        fail("top level must be an object with a traceEvents array")
    for i, e in enumerate(trace["traceEvents"]):
        if not isinstance(e, dict):
            fail(f"traceEvents[{i}] is not an object")
        if e.get("ph") not in ("X", "i", "M"):
            fail(f"traceEvents[{i}].ph {e.get('ph')!r} not in X/i/M")
        if not isinstance(e.get("name"), str) or not e["name"]:
            fail(f"traceEvents[{i}].name must be a non-empty string")
        for key in ("pid", "tid"):
            if not isinstance(e.get(key), int):
                fail(f"traceEvents[{i}].{key} must be an int")
        if not isinstance(e.get("ts"), (int, float)):
            fail(f"traceEvents[{i}].ts must be a number")
        if e["ph"] == "X":
            if not isinstance(e.get("dur"), (int, float)) or e["dur"] < 0:
                fail(f"traceEvents[{i}].dur must be a number >= 0")
            if e["ts"] < 0:
                fail(f"traceEvents[{i}].ts must be >= 0")
    return len(trace["traceEvents"])


# ---------------------------------------------------------------------------
# Derived metrics
# ---------------------------------------------------------------------------


def ring_overlap_report(events: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The NTS_OVERLAP_PROBE verdict: prefers the run_summary gauges, falls
    back to the probe span's attributes (killed run)."""
    for e in reversed(events):
        if e["event"] == "run_summary":
            g = e.get("gauges") or {}
            if "ring.probe_overlap_s" in g:
                return {
                    "efficiency": g.get("ring.overlap_efficiency"),
                    "overlap_s": g.get("ring.probe_overlap_s"),
                    "compute_s": g.get("ring.probe_compute_s"),
                    "exchange_s": g.get("ring.probe_exchange_s"),
                    "simulated": bool(g.get("ring.probe_simulated")),
                }
    for e in reversed(events):
        if e["event"] == "span" and e["name"] == "ring_overlap_probe":
            return {
                "efficiency": e.get("efficiency"),
                "overlap_s": e.get("overlap_s"),
                "compute_s": e.get("compute_s"),
                "exchange_s": e.get("exchange_s"),
                "simulated": bool(e.get("simulated")),
            }
    return None


def sample_pipeline_report(events: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The async-sampling overlap verdict (sample/pipeline.py): total time
    the producer spent sampling + staging H2D vs the residual time the
    consumer actually waited on the queue. hidden_frac is the share of
    sampling time the pipeline moved off the critical path — 1.0 means the
    consumer never stalled, 0.0 means no overlap (the synchronous bound)."""
    per_run: Dict[Any, Dict[str, float]] = {}
    for s in spans_of(events):
        # cat=sample only: the trainer ALSO rolls the per-epoch stall up
        # into a "sample_wait" stage span (cat=stage) under each epoch —
        # summing both would double-count every wait
        if s.get("cat") != "sample":
            continue
        b = per_run.setdefault(
            s.get("run_id"), {"produce": 0.0, "wait": 0.0, "h2d": 0.0,
                              "n": 0}
        )
        if s["name"] == "sample_produce":
            b["produce"] += s["dur_s"]
            b["n"] += 1
        elif s["name"] == "sample_wait":
            b["wait"] += s["dur_s"]
        elif s["name"] == "h2d_copy":
            b["h2d"] += s["dur_s"]
    # aggregate ONLY runs that actually produced batches: a merged dir can
    # also hold a serve run whose executor emits sample_wait spans with no
    # matching sample_produce — blending those in would deflate the
    # training pipeline's verdict (the same cross-run rule the serve
    # critical path applies via its (run_id, id) join keys)
    rows = [b for b in per_run.values() if b["n"] > 0]
    if not rows:
        return None
    produce_s = sum(b["produce"] for b in rows)
    wait_s = sum(b["wait"] for b in rows)
    h2d_s = sum(b["h2d"] for b in rows)
    n = sum(b["n"] for b in rows)
    busy = produce_s + h2d_s
    return {
        "batches": n,
        "produce_s": produce_s,
        "h2d_s": h2d_s,
        "wait_s": wait_s,
        "hidden_frac": (busy - min(wait_s, busy)) / busy if busy > 0 else None,
    }


# h2d_copy and handoff exist only on the pipelined flush (serve/server.py
# two-stage path); sync flushes simply contribute 0.0 for them, keeping
# the stage-sum ≡ latency contract valid in BOTH modes
SERVE_STAGES = ("queue", "cache_lookup", "sample", "h2d_copy", "handoff",
                "execute", "reply")


def serve_critical_path(events: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Per-request stage breakdown from the serve lifecycle spans, joined
    to the ``serve_request`` records by ``req_id``. For each answered
    request: queue (its own span) + the four flush stages of the batch
    that served it (``flush_id`` join). The stage sum reproduces the
    recorded end-to-end latency — ``max_abs_mismatch_ms`` quantifies how
    tightly (tests pin it)."""
    spans = spans_of(events)
    # join keys carry run_id: req_id/flush_id counters restart at 0 in
    # every serving process, and a merged multi-run dir must not cross-join
    # run A's requests to run B's queue/stage spans
    queue_by_req = {
        (s.get("run_id"), s["req_id"]): s for s in spans
        if s["name"] == "queue" and s.get("req_id")
    }
    stages_by_flush: Dict[Any, Dict[str, float]] = {}
    for s in spans:
        if s["name"] in SERVE_STAGES[1:] and s.get("flush_id") is not None:
            stages_by_flush.setdefault(
                (s.get("run_id"), s["flush_id"]), {}
            )[s["name"]] = s["dur_s"] * 1000.0
    recs = [
        e for e in events
        if e["event"] == "serve_request" and e.get("status") != "shed"
        and e.get("req_id") and e.get("total_ms") is not None
    ]
    requests = []
    for r in recs:
        q = queue_by_req.get((r.get("run_id"), r["req_id"]))
        flush = stages_by_flush.get((r.get("run_id"), r.get("flush_id")))
        if q is None or not flush:
            continue
        stages = {"queue": q["dur_s"] * 1000.0}
        stages.update(
            {name: flush.get(name, 0.0) for name in SERVE_STAGES[1:]}
        )
        total = float(r["total_ms"])
        s_sum = sum(stages.values())
        requests.append({
            "req_id": r["req_id"],
            "flush_id": r.get("flush_id"),
            "status": r["status"],
            "total_ms": total,
            "stage_sum_ms": s_sum,
            "mismatch_ms": s_sum - total,
            "stages_ms": stages,
        })
    if not requests:
        return None
    p50 = {
        name: _median([r["stages_ms"][name] for r in requests])
        for name in SERVE_STAGES
    }
    return {
        "requests": requests,
        "n": len(requests),
        "stage_p50_ms": p50,
        "critical_stage": max(p50, key=lambda k: p50[k] or 0.0),
        "max_abs_mismatch_ms": max(
            abs(r["mismatch_ms"]) for r in requests
        ),
    }


def request_chains(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Join the fleet's spans into per-request distributed chains.

    The router stamps every request with trace_id ``run_id:req_id``, so
    one trace groups: the ``fleet_request`` root, its route/re-route/
    backoff/suspect/shed decisions, the ``predict_post`` client span
    (+ ``http_retry`` children), the replica's ``predict_handler`` and
    ``request``/``queue`` spans — and through the request span's
    ``(replica run_id, flush_id)`` the engine-side flush stage spans,
    which carry the replica's OWN trace_id (they serve a whole batch,
    not one request). A chain is COMPLETE when the client->router->
    replica->engine legs are all present:
    root + predict_post + predict_handler + request + an execute stage.

    ``router_overhead_ms = total_ms - replica_stage_sum_ms`` — what the
    fabric (routing, HTTP, queueing gaps between recorded stages) added
    on top of the replica's own stage time.
    """
    spans = spans_of(events)
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for s in spans:
        if s.get("trace_id"):
            by_trace.setdefault(s["trace_id"], []).append(s)
    stages_by_flush: Dict[Any, Dict[str, float]] = {}
    for s in spans:
        if s["name"] in SERVE_STAGES[1:] and s.get("flush_id") is not None:
            stages_by_flush.setdefault(
                (s.get("run_id"), s["flush_id"]), {}
            )[s["name"]] = s["dur_s"] * 1000.0
    chains: List[Dict[str, Any]] = []
    for tid, group in sorted(by_trace.items()):
        root = next(
            (s for s in group if s["name"] == "fleet_request"), None
        )
        if root is None:
            continue
        request = next((s for s in group if s["name"] == "request"), None)
        queue = next((s for s in group if s["name"] == "queue"), None)
        posts = [s for s in group if s["name"] == "predict_post"]
        handlers = [s for s in group if s["name"] == "predict_handler"]
        stage_ms: Dict[str, float] = {}
        if request is not None and request.get("flush_id") is not None:
            stage_ms.update(stages_by_flush.get(
                (request.get("run_id"), request["flush_id"])
            ) or {})
        if queue is not None:
            stage_ms["queue"] = queue["dur_s"] * 1000.0
        total_ms = root["dur_s"] * 1000.0
        complete = bool(
            posts and handlers and request is not None
            and "execute" in stage_ms
        )
        replica_sum = sum(stage_ms.values()) if stage_ms else None
        chains.append({
            "trace_id": tid,
            "req_id": root.get("req_id"),
            "status": root.get("status"),
            "complete": complete,
            "total_ms": total_ms,
            "replica_stage_sum_ms": replica_sum,
            "router_overhead_ms": (
                total_ms - replica_sum if complete else None
            ),
            "stages_ms": stage_ms,
            "n_posts": len(posts),
            "n_retries": sum(
                1 for s in group if s["name"] == "http_retry"
            ),
            "n_reroutes": sum(
                1 for s in group if s["name"] == "re_route"
            ),
            "n_suspects": sum(
                1 for s in group if s["name"] == "suspect"
            ),
            "n_sheds": sum(1 for s in group if s["name"] == "shed"),
            "graph_seq": request.get("graph_seq") if request else None,
            "model_seq": request.get("model_seq") if request else None,
            "replica_run_id": (
                request.get("run_id") if request else None
            ),
            "target": root.get("target"),
        })
    return chains


def request_tracing_report(
    events: List[Dict[str, Any]],
) -> Optional[Dict[str, Any]]:
    """The fleet-merged per-request verdict: complete-chain fraction
    (over requests answered ok), router-overhead quantiles over complete
    chains, fabric-event totals, and the freshness lineage summary
    (which graph/model versions answered)."""
    chains = request_chains(events)
    if not chains:
        return None
    ok = [c for c in chains if c["status"] == "ok"]
    complete = [c for c in ok if c["complete"]]
    overhead = sorted(
        c["router_overhead_ms"] for c in complete
        if c["router_overhead_ms"] is not None
    )
    return {
        "n_traces": len(chains),
        "n_ok": len(ok),
        "n_complete": len(complete),
        "complete_frac": (
            len(complete) / len(ok) if ok else 0.0
        ),
        "router_overhead_p50_ms": _quantile(overhead, 0.50),
        "router_overhead_p95_ms": _quantile(overhead, 0.95),
        "router_overhead_p99_ms": _quantile(overhead, 0.99),
        "retries": sum(c["n_retries"] for c in chains),
        "reroutes": sum(c["n_reroutes"] for c in chains),
        "suspects": sum(c["n_suspects"] for c in chains),
        "sheds": sum(c["n_sheds"] for c in chains),
        "graph_seqs": sorted({
            c["graph_seq"] for c in chains
            if c["graph_seq"] is not None
        }),
        "model_seqs": sorted({
            c["model_seq"] for c in chains
            if c["model_seq"] is not None
        }),
        "chains": chains,
    }


def request_tracing_block(events: List[Dict[str, Any]]) -> List[str]:
    """The "request tracing:" lines tools/metrics_report embeds (and the
    fleet CLI prints): complete-chain fraction, router-overhead
    quantiles, fabric-event totals, freshness lineage."""
    rep = request_tracing_report(events)
    if rep is None:
        return []

    def ms(v):
        return f"{v:.3f}" if v is not None else "n/a"

    lines = ["request tracing:"]
    lines.append(
        f"#traces={rep['n_traces']} ok={rep['n_ok']} "
        f"complete={rep['n_complete']} "
        f"(complete_chain_frac={rep['complete_frac']:.3f})"
    )
    lines.append(
        f"#router_overhead_ms=p50:{ms(rep['router_overhead_p50_ms'])} "
        f"p95:{ms(rep['router_overhead_p95_ms'])} "
        f"p99:{ms(rep['router_overhead_p99_ms'])}"
    )
    lines.append(
        f"#fabric_events=retries:{rep['retries']} "
        f"reroutes:{rep['reroutes']} suspects:{rep['suspects']} "
        f"sheds:{rep['sheds']}"
    )
    if rep["graph_seqs"] or rep["model_seqs"]:
        gs = rep["graph_seqs"]
        lines.append(
            "#lineage=graph_seq["
            + (f"{gs[0]}..{gs[-1]}" if len(gs) > 1
               else (str(gs[0]) if gs else "n/a"))
            + "] model_seq["
            + (",".join(str(m) for m in rep["model_seqs"])
               if rep["model_seqs"] else "n/a")
            + "]"
        )
    return lines


def retry_report(events: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Per fault episode: the recovery action taken and the time from the
    fault record to the first epoch completed afterwards (end-to-end
    retry cost, backoff + restore + replay included)."""
    faults = [e for e in events if e["event"] == "fault"]
    if not faults:
        return None
    recoveries = [e for e in events if e["event"] == "recovery"]
    epochs = [e for e in events if e["event"] == "epoch"]
    episodes = []
    for f in faults:
        # same-run pairing only: a merged multi-run dir must not heal one
        # run's fault with the first epoch another run happens to finish
        rid = f.get("run_id")
        action = next(
            (r for r in recoveries
             if r.get("run_id") == rid and r["ts"] >= f["ts"]), None
        )
        healed = next(
            (e for e in epochs
             if e.get("run_id") == rid and e["ts"] > f["ts"]), None
        )
        episodes.append({
            "kind": f.get("kind"),
            "epoch": f.get("epoch"),
            "attempt": f.get("attempt"),
            "action": action.get("action") if action else None,
            "recover_s": (healed["ts"] - f["ts"]) if healed else None,
        })
    replayed = 0
    for e in reversed(events):
        if e["event"] == "run_summary":
            replayed = int(
                (e.get("counters") or {}).get(
                    "resilience.replayed_epochs", 0
                )
            )
            break
    recovered = [p["recover_s"] for p in episodes if p["recover_s"]]
    return {
        "episodes": episodes,
        "n": len(episodes),
        "replayed_epochs": replayed,
        "mean_recover_s": (
            sum(recovered) / len(recovered) if recovered else None
        ),
    }


def elastic_report(events: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The elastic degraded-mode verdict: per ``replan`` episode, the
    time from the triggering ``rank_loss`` detection record to the first
    post-replan epoch end — end-to-end time-to-recover, plan rebuild +
    checkpoint restore + recompile included."""
    replans = [e for e in events if e["event"] == "replan"]
    if not replans:
        return None
    losses = [e for e in events if e["event"] == "rank_loss"]
    epochs = [e for e in events if e["event"] == "epoch"]
    episodes = []
    for r in replans:
        # same-run pairing only (the retry_report rule): a merged dir
        # must not heal one run's rank loss with another run's epochs
        rid = r.get("run_id")
        trigger = next(
            (x for x in reversed(losses)
             if x.get("run_id") == rid and x["ts"] <= r["ts"]), None
        )
        healed = next(
            (x for x in epochs
             if x.get("run_id") == rid and x["ts"] > r["ts"]), None
        )
        episodes.append({
            "from_partitions": r.get("from_partitions"),
            "to_partitions": r.get("to_partitions"),
            "lost": r.get("lost"),
            "recover_s": (
                healed["ts"] - trigger["ts"]
                if healed is not None and trigger is not None else None
            ),
        })
    recovered = [e["recover_s"] for e in episodes
                 if e["recover_s"] is not None]
    return {
        "episodes": episodes,
        "n": len(episodes),
        "mean_recover_s": (
            sum(recovered) / len(recovered) if recovered else None
        ),
    }


def span_inventory(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    by_name: Dict[str, Dict[str, float]] = {}
    for s in spans_of(events):
        b = by_name.setdefault(s["name"], {"count": 0, "total_s": 0.0})
        b["count"] += 1
        b["total_s"] += s["dur_s"]
    return by_name


def timeline_block(events: List[Dict[str, Any]]) -> List[str]:
    """The "span timeline" lines tools/metrics_report embeds under each
    run's #key=value block (one stream's events; empty without spans)."""
    inv = span_inventory(events)
    if not inv:
        return []
    lines = ["span timeline:"]
    lines.append(
        "#spans="
        + " ".join(
            f"{name}:{int(b['count'])}({b['total_s'] * 1000:.1f}ms)"
            for name, b in sorted(inv.items())
        )
    )
    ring = ring_overlap_report(events)
    if ring is not None and ring.get("overlap_s") is not None:
        eff = ring.get("efficiency")
        lines.append(
            f"#ring_overlap_efficiency="
            f"{f'{eff:.2f}' if eff is not None else 'n/a'} "
            f"(overlapped={ring['overlap_s'] * 1000:.3f}ms "
            f"compute_only={ring['compute_s'] * 1000:.3f}ms "
            f"exchange_only={ring['exchange_s'] * 1000:.3f}ms"
            f"{', sim rig' if ring.get('simulated') else ''})"
        )
    samp = sample_pipeline_report(events)
    if samp is not None:
        hidden = samp["hidden_frac"]
        lines.append(
            f"#sample_pipeline={samp['batches']} batch(es), "
            f"produce={samp['produce_s'] * 1000:.3f}ms "
            f"h2d={samp['h2d_s'] * 1000:.3f}ms "
            f"consumer_wait={samp['wait_s'] * 1000:.3f}ms "
            f"(hidden_frac="
            f"{f'{hidden:.2f}' if hidden is not None else 'n/a'})"
        )
    serve = serve_critical_path(events)
    if serve is not None:
        p50 = serve["stage_p50_ms"]
        lines.append(
            "#serve_critical_path_p50="
            + " ".join(
                f"{name}:{p50[name]:.3f}ms" for name in SERVE_STAGES
                if p50.get(name) is not None
            )
            + f" (critical={serve['critical_stage']}, n={serve['n']}, "
            f"max|stage_sum-latency|={serve['max_abs_mismatch_ms']:.3f}ms)"
        )
    ela = elastic_report(events)
    if ela is not None:
        last = ela["episodes"][-1]
        mean = ela["mean_recover_s"]
        lines.append(
            f"#elastic={ela['n']} replan(s), last P "
            f"{last['from_partitions']}->{last['to_partitions']} "
            f"(lost partition {last['lost']}), time_to_recover="
            f"{f'{mean:.2f}s' if mean is not None else 'n/a'}"
        )
    retry = retry_report(events)
    if retry is not None:
        mean = retry["mean_recover_s"]
        lines.append(
            f"#retry_cost={retry['n']} episode(s), "
            f"mean_time_to_recover="
            f"{f'{mean:.2f}s' if mean is not None else 'n/a'}, "
            f"replayed_epochs={retry['replayed_epochs']}"
        )
    return lines


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="merge per-rank obs span streams into one causal "
        "timeline: Chrome trace export + overlap/critical-path/retry "
        "derived metrics"
    )
    ap.add_argument("paths", nargs="+",
                    help="JSONL file(s) or NTS_METRICS_DIR directories")
    ap.add_argument("--chrome", metavar="OUT.json", default="",
                    help="write Chrome trace-event JSON here "
                    "(Perfetto / chrome://tracing)")
    ap.add_argument("--json", action="store_true",
                    help="emit the derived metrics as one JSON object")
    ap.add_argument("--fleet", action="store_true",
                    help="cross-PROCESS merge: align the router's and "
                    "each replica's streams via distributed-trace clock "
                    "pairs (instead of epoch markers), give each "
                    "process its own Chrome pid, and derive the "
                    "per-request chain report")
    args = ap.parse_args(argv)

    streams = load_streams(expand_paths(args.paths), fleet=args.fleet)
    streams = [s for s in streams if spans_of(s.events)]
    if not streams:
        print("no span records found in the given streams",
              file=sys.stderr)
        return 1

    merged: List[Dict[str, Any]] = []
    for s in streams:
        merged.extend(s.events)
    merged.sort(key=lambda e: e["ts"])

    out: Dict[str, Any] = {
        "streams": [
            {
                "path": s.path,
                "rank": s.rank,
                "pid": s.pid,
                "run_id": s.run_id,
                "spans": len(spans_of(s.events)),
                "mono_wall_offset_s": s.offset,
                "align_shift_s": s.align,
                "skew_bound_s": s.skew_bound,
                "align_warning": s.align_warning,
            }
            for s in streams
        ],
        "ring_overlap": ring_overlap_report(merged),
        "serve_critical_path": serve_critical_path(merged),
        "retries": retry_report(merged),
        "elastic": elastic_report(merged),
        "span_inventory": span_inventory(merged),
    }
    if args.fleet:
        out["request_tracing"] = request_tracing_report(merged)
    if args.chrome:
        trace = chrome_trace(streams)
        validate_chrome_trace(trace)
        with open(args.chrome, "w", encoding="utf-8") as fh:
            json.dump(trace, fh)
        out["chrome"] = {
            "path": args.chrome, "events": len(trace["traceEvents"]),
        }

    if args.json:
        print(json.dumps(out, default=str))
        return 0

    for s in out["streams"]:
        off = s["mono_wall_offset_s"]
        bound = s["skew_bound_s"]
        print(
            f"== stream rank {s['rank']} · {s['run_id']} — {s['path']}\n"
            f"   {s['spans']} spans, mono->wall offset "
            f"{off:.3f}s, align shift {s['align_shift_s'] * 1000:+.3f}ms"
            + (f", skew bound ±{bound * 1000:.3f}ms"
               if bound is not None else "")
        )
    for line in timeline_block(merged):
        print(line)
    if args.fleet:
        for line in request_tracing_block(merged):
            print(line)
    serve = out["serve_critical_path"]
    if serve is not None:
        worst = max(serve["requests"], key=lambda r: r["total_ms"])
        print(
            f"slowest request {worst['req_id']}: "
            f"{worst['total_ms']:.3f}ms total = "
            + " + ".join(
                f"{worst['stages_ms'][n]:.3f} {n}" for n in SERVE_STAGES
            )
        )
    if "chrome" in out:
        print(
            f"chrome trace: {out['chrome']['events']} events -> "
            f"{out['chrome']['path']} (open in Perfetto; with "
            f"NTS_PROFILE_DIR the same span names appear inside the "
            f"jax.profiler device trace)"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
