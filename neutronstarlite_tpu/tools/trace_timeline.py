"""Merge per-rank span streams into one causal timeline.

The span records (obs/trace.py) land in per-rank JSONL files with
process-local monotonic clocks. This CLI reconstructs one coherent view:

1. **mono->wall recovery** per stream: a span record is written
   immediately after its span ends, so the envelope wall-clock ``ts``
   sits just past ``t0 + dur_s`` — ``median(ts - (t0 + dur_s))`` over a
   stream's spans is that process's monotonic->wall offset (robust to a
   few delayed writes; see docs/OBSERVABILITY.md for the caveats).
2. **epoch-marker rank alignment**: every rank ends epoch *e* at the same
   collective barrier, so per-epoch spans are cross-rank fence posts —
   each rank is shifted by the median difference of its epoch-end times
   against the reference (lowest) rank. Wall clocks that agree within the
   epoch time are left essentially untouched; skewed hosts snap into
   place.
3. **Chrome trace-event export** (``--chrome out.json``): complete ("X")
   events per span (pid = rank, tid = host thread), instant events for
   fault / recovery / shed / rank_loss / replan / tune_trial /
   tune_decision records — loadable in
   Perfetto or chrome://tracing. When the run also wrote a ``jax.profiler`` trace
   (``NTS_PROFILE_DIR``), the host spans were emitted as
   ``TraceAnnotation``s inside it too, so the device-op view carries the
   same names — open both in one Perfetto window to line host causality
   up with kernel truth.
4. **Derived metrics** printed as the timeline report (and rendered by
   tools/metrics_report as its "span timeline" block):
   - ring overlap efficiency — the NTS_OVERLAP_PROBE verdict (hop time
     hidden under blocked-kernel compute / total hop time);
   - serve critical path — per-request stage breakdown
     (queue -> cache_lookup -> sample -> execute -> reply), joined to the
     ``serve_request`` records by ``req_id``; the stage sum must match
     the recorded end-to-end latency (the tests pin the tolerance);
   - retry cost — per fault episode, time from the fault record to the
     first epoch completed after recovery, plus replayed-epoch counts;
   - elastic time-to-recover — per survivor replan, the time from the
     rank_loss detection record to the first post-replan epoch end.

Usage:
  python -m neutronstarlite_tpu.tools.trace_timeline <file-or-dir> [...]
      [--chrome OUT.json] [--json]
Exit 0 when at least one stream yielded a timeline; 1 otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
from typing import Any, Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from neutronstarlite_tpu.tools.metrics_report import (  # noqa: E402
    expand_paths,
    load_events,
)


def _median(vals: List[float]) -> Optional[float]:
    return statistics.median(vals) if vals else None


def spans_of(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    return [e for e in events if e["event"] == "span"]


def stream_rank(events: List[Dict[str, Any]], path: str) -> int:
    """Rank of one stream: run_start.process_index, span.rank, or the
    ``-pN.jsonl`` filename convention; 0 when nothing says otherwise."""
    for e in events:
        if e["event"] == "run_start" and isinstance(
            e.get("process_index"), int
        ):
            return e["process_index"]
    for e in events:
        if e["event"] == "span" and isinstance(e.get("rank"), int):
            return e["rank"]
    stem = os.path.basename(path)
    if "-p" in stem:
        tail = stem.rsplit("-p", 1)[1].split(".", 1)[0]
        if tail.isdigit():
            return int(tail)
    return 0


def mono_wall_offset(events: List[Dict[str, Any]]) -> Optional[float]:
    """Monotonic->wall offset for one stream (docstring step 1)."""
    return _median([
        e["ts"] - (e["t0"] + e["dur_s"]) for e in spans_of(events)
    ])


class Stream:
    """One per-rank JSONL file with its clock corrections resolved."""

    def __init__(self, path: str, events: List[Dict[str, Any]]):
        self.path = path
        self.events = events
        self.rank = stream_rank(events, path)
        self.offset = mono_wall_offset(events)  # mono -> wall (step 1)
        self.align = 0.0  # cross-rank shift (step 2)
        self.align_warning: Optional[str] = None  # set by align_streams
        self.run_id = next(
            (e["run_id"] for e in events if e.get("run_id")), "?"
        )

    def span_wall(self, span: Dict[str, Any]) -> Optional[float]:
        """Aligned wall-clock start of ``span`` (None without an offset —
        a stream with no spans has nothing to place on the timeline)."""
        if self.offset is None:
            return None
        return span["t0"] + self.offset + self.align

    def epoch_ends(self) -> Dict[int, float]:
        """{epoch: aligned wall end} from this stream's epoch spans."""
        out: Dict[int, float] = {}
        if self.offset is None:
            return out
        for s in spans_of(self.events):
            if s["name"] == "epoch" and isinstance(s.get("epoch"), int):
                out[s["epoch"]] = (
                    s["t0"] + s["dur_s"] + self.offset + self.align
                )
        return out


def align_streams(streams: List["Stream"]) -> None:
    """Epoch-marker alignment (docstring step 2), in place: the lowest
    rank with epoch spans anchors; every other stream shifts by the median
    epoch-end difference over shared epochs. Streams sharing no epochs
    (e.g. a serve-only stream next to a training stream) keep wall time.

    Failure mode is WARN, not crash: a span-bearing stream with no epoch
    markers (or none shared with the anchor) cannot be cross-rank
    corrected — it keeps its own wall clock (``align=0``), which may sit
    skewed against the other ranks by each host's clock error. The
    stream's ``align_warning`` names the reason and a stderr line
    surfaces it, so a skewed-looking timeline says WHY instead of
    silently interleaving misaligned ranks."""
    anchored = sorted(
        (s for s in streams if s.epoch_ends()), key=lambda s: s.rank
    )
    if not anchored:
        for s in streams:
            if any(True for _ in spans_of(s.events)):
                s.align_warning = (
                    "no stream carries epoch spans: cross-rank alignment "
                    "skipped (each stream keeps its own wall clock)"
                )
                print(f"{s.path}: {s.align_warning}", file=sys.stderr)
        return
    ref = anchored[0].epoch_ends()
    aligned_ids = {id(a) for a in anchored}
    for s in anchored[1:]:
        own = s.epoch_ends()
        deltas = [ref[e] - own[e] for e in ref.keys() & own.keys()]
        d = _median(deltas)
        if d is not None:
            s.align = d
        else:
            s.align_warning = (
                f"shares no epochs with the anchor (rank "
                f"{anchored[0].rank}): cross-rank alignment skipped for "
                "this stream (kept on its own wall clock)"
            )
            print(f"{s.path}: warning: {s.align_warning}", file=sys.stderr)
    for s in streams:
        if id(s) in aligned_ids or s.offset is None:
            continue
        # spans but no epoch markers at all (a serve/probe stream, or a
        # trainer that died before epoch 0 closed)
        s.align_warning = (
            "stream has spans but no epoch markers: cross-rank alignment "
            "skipped for this stream (kept on its own wall clock)"
        )
        print(f"{s.path}: warning: {s.align_warning}", file=sys.stderr)


def load_streams(paths: List[str]) -> List[Stream]:
    streams = []
    for p in paths:
        try:
            events = load_events(p)
        except OSError as e:
            print(f"{p}: {e}", file=sys.stderr)
            continue
        if events:
            streams.append(Stream(p, events))
    align_streams(streams)
    return streams


# ---------------------------------------------------------------------------
# Chrome trace export
# ---------------------------------------------------------------------------

_INSTANT_KINDS = ("fault", "recovery", "shed", "rank_loss", "replan",
                  "tune_trial", "tune_decision", "slo_status",
                  "backend_probe", "delta_commit", "finetune_round")
_ENVELOPE_OR_SPAN = (
    "event", "run_id", "schema", "ts", "seq", "name", "cat", "span_id",
    "trace_id", "parent_id", "t0", "dur_s", "rank", "thread",
)


def chrome_trace(streams: List[Stream]) -> Dict[str, Any]:
    """Chrome trace-event JSON (the ``traceEvents`` container form).

    pid = rank, tid = one int per (rank, host thread); metadata records
    name both. Spans become complete ("X") events; fault/recovery/shed
    records become process-scoped instants ("i")."""
    events: List[Dict[str, Any]] = []
    starts: List[float] = []
    for st in streams:
        for s in spans_of(st.events):
            w = st.span_wall(s)
            if w is not None:
                starts.append(w)
        if st.offset is not None:
            for e in st.events:
                if e["event"] in _INSTANT_KINDS:
                    starts.append(e["ts"] + st.align)
    t0 = min(starts) if starts else 0.0

    tids: Dict[tuple, int] = {}
    for st in streams:
        events.append({
            "ph": "M", "name": "process_name", "pid": st.rank, "tid": 0,
            "ts": 0,
            "args": {"name": f"rank {st.rank} · {st.run_id}"},
        })
        for s in spans_of(st.events):
            w = st.span_wall(s)
            if w is None:
                continue
            key = (st.rank, s.get("thread") or "main")
            tid = tids.get(key)
            if tid is None:
                tid = tids[key] = len(tids) + 1
                events.append({
                    "ph": "M", "name": "thread_name", "pid": st.rank,
                    "tid": tid, "ts": 0, "args": {"name": key[1]},
                })
            args = {
                k: v for k, v in s.items()
                if k not in _ENVELOPE_OR_SPAN and v is not None
            }
            args["span_id"] = s["span_id"]
            if s.get("parent_id"):
                args["parent_id"] = s["parent_id"]
            events.append({
                "ph": "X",
                "name": s["name"],
                "cat": s.get("cat") or "host",
                "pid": st.rank,
                "tid": tid,
                "ts": (w - t0) * 1e6,
                "dur": s["dur_s"] * 1e6,
                "args": args,
            })
        if st.offset is None:
            continue
        for e in st.events:
            if e["event"] not in _INSTANT_KINDS:
                continue
            label = (
                e.get("kind") or e.get("action") or e.get("reason") or ""
            )
            if e["event"] in ("tune_trial", "tune_decision"):
                # the candidate tuple (and decision source), readable off
                # the marker name in Perfetto
                label = str(e.get("candidate") or "?")
                if e["event"] == "tune_decision":
                    label = f"{label} [{e.get('source')}]"
            if e["event"] == "replan":
                # the elastic degradation, readable off the marker name
                label = (
                    f"{e.get('from_partitions')}->{e.get('to_partitions')}"
                )
            if e["event"] == "slo_status":
                # the burn-rate verdict, readable off the marker name
                label = f"{e.get('metric')}={e.get('state')}"
            if e["event"] == "backend_probe":
                label = f"attempt{e.get('attempt')}:{e.get('outcome')}"
            events.append({
                "ph": "i",
                "name": f"{e['event']}:{label}",
                "cat": "marker",
                "pid": st.rank,
                "tid": 0,
                "ts": (e["ts"] + st.align - t0) * 1e6,
                "s": "p",
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def validate_chrome_trace(trace: Any) -> int:
    """Structural check of a Chrome trace-event JSON object; returns the
    event count, raises ValueError on the first violation. This is the
    schema the tests (and any CI consumer) pin."""
    def fail(msg):
        raise ValueError(f"chrome trace: {msg}")

    if not isinstance(trace, dict) or not isinstance(
        trace.get("traceEvents"), list
    ):
        fail("top level must be an object with a traceEvents array")
    for i, e in enumerate(trace["traceEvents"]):
        if not isinstance(e, dict):
            fail(f"traceEvents[{i}] is not an object")
        if e.get("ph") not in ("X", "i", "M"):
            fail(f"traceEvents[{i}].ph {e.get('ph')!r} not in X/i/M")
        if not isinstance(e.get("name"), str) or not e["name"]:
            fail(f"traceEvents[{i}].name must be a non-empty string")
        for key in ("pid", "tid"):
            if not isinstance(e.get(key), int):
                fail(f"traceEvents[{i}].{key} must be an int")
        if not isinstance(e.get("ts"), (int, float)):
            fail(f"traceEvents[{i}].ts must be a number")
        if e["ph"] == "X":
            if not isinstance(e.get("dur"), (int, float)) or e["dur"] < 0:
                fail(f"traceEvents[{i}].dur must be a number >= 0")
            if e["ts"] < 0:
                fail(f"traceEvents[{i}].ts must be >= 0")
    return len(trace["traceEvents"])


# ---------------------------------------------------------------------------
# Derived metrics
# ---------------------------------------------------------------------------


def ring_overlap_report(events: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The NTS_OVERLAP_PROBE verdict: prefers the run_summary gauges, falls
    back to the probe span's attributes (killed run)."""
    for e in reversed(events):
        if e["event"] == "run_summary":
            g = e.get("gauges") or {}
            if "ring.probe_overlap_s" in g:
                return {
                    "efficiency": g.get("ring.overlap_efficiency"),
                    "overlap_s": g.get("ring.probe_overlap_s"),
                    "compute_s": g.get("ring.probe_compute_s"),
                    "exchange_s": g.get("ring.probe_exchange_s"),
                    "simulated": bool(g.get("ring.probe_simulated")),
                }
    for e in reversed(events):
        if e["event"] == "span" and e["name"] == "ring_overlap_probe":
            return {
                "efficiency": e.get("efficiency"),
                "overlap_s": e.get("overlap_s"),
                "compute_s": e.get("compute_s"),
                "exchange_s": e.get("exchange_s"),
                "simulated": bool(e.get("simulated")),
            }
    return None


def sample_pipeline_report(events: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The async-sampling overlap verdict (sample/pipeline.py): total time
    the producer spent sampling + staging H2D vs the residual time the
    consumer actually waited on the queue. hidden_frac is the share of
    sampling time the pipeline moved off the critical path — 1.0 means the
    consumer never stalled, 0.0 means no overlap (the synchronous bound)."""
    per_run: Dict[Any, Dict[str, float]] = {}
    for s in spans_of(events):
        # cat=sample only: the trainer ALSO rolls the per-epoch stall up
        # into a "sample_wait" stage span (cat=stage) under each epoch —
        # summing both would double-count every wait
        if s.get("cat") != "sample":
            continue
        b = per_run.setdefault(
            s.get("run_id"), {"produce": 0.0, "wait": 0.0, "h2d": 0.0,
                              "n": 0}
        )
        if s["name"] == "sample_produce":
            b["produce"] += s["dur_s"]
            b["n"] += 1
        elif s["name"] == "sample_wait":
            b["wait"] += s["dur_s"]
        elif s["name"] == "h2d_copy":
            b["h2d"] += s["dur_s"]
    # aggregate ONLY runs that actually produced batches: a merged dir can
    # also hold a serve run whose executor emits sample_wait spans with no
    # matching sample_produce — blending those in would deflate the
    # training pipeline's verdict (the same cross-run rule the serve
    # critical path applies via its (run_id, id) join keys)
    rows = [b for b in per_run.values() if b["n"] > 0]
    if not rows:
        return None
    produce_s = sum(b["produce"] for b in rows)
    wait_s = sum(b["wait"] for b in rows)
    h2d_s = sum(b["h2d"] for b in rows)
    n = sum(b["n"] for b in rows)
    busy = produce_s + h2d_s
    return {
        "batches": n,
        "produce_s": produce_s,
        "h2d_s": h2d_s,
        "wait_s": wait_s,
        "hidden_frac": (busy - min(wait_s, busy)) / busy if busy > 0 else None,
    }


# h2d_copy and handoff exist only on the pipelined flush (serve/server.py
# two-stage path); sync flushes simply contribute 0.0 for them, keeping
# the stage-sum ≡ latency contract valid in BOTH modes
SERVE_STAGES = ("queue", "cache_lookup", "sample", "h2d_copy", "handoff",
                "execute", "reply")


def serve_critical_path(events: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Per-request stage breakdown from the serve lifecycle spans, joined
    to the ``serve_request`` records by ``req_id``. For each answered
    request: queue (its own span) + the four flush stages of the batch
    that served it (``flush_id`` join). The stage sum reproduces the
    recorded end-to-end latency — ``max_abs_mismatch_ms`` quantifies how
    tightly (tests pin it)."""
    spans = spans_of(events)
    # join keys carry run_id: req_id/flush_id counters restart at 0 in
    # every serving process, and a merged multi-run dir must not cross-join
    # run A's requests to run B's queue/stage spans
    queue_by_req = {
        (s.get("run_id"), s["req_id"]): s for s in spans
        if s["name"] == "queue" and s.get("req_id")
    }
    stages_by_flush: Dict[Any, Dict[str, float]] = {}
    for s in spans:
        if s["name"] in SERVE_STAGES[1:] and s.get("flush_id") is not None:
            stages_by_flush.setdefault(
                (s.get("run_id"), s["flush_id"]), {}
            )[s["name"]] = s["dur_s"] * 1000.0
    recs = [
        e for e in events
        if e["event"] == "serve_request" and e.get("status") != "shed"
        and e.get("req_id") and e.get("total_ms") is not None
    ]
    requests = []
    for r in recs:
        q = queue_by_req.get((r.get("run_id"), r["req_id"]))
        flush = stages_by_flush.get((r.get("run_id"), r.get("flush_id")))
        if q is None or not flush:
            continue
        stages = {"queue": q["dur_s"] * 1000.0}
        stages.update(
            {name: flush.get(name, 0.0) for name in SERVE_STAGES[1:]}
        )
        total = float(r["total_ms"])
        s_sum = sum(stages.values())
        requests.append({
            "req_id": r["req_id"],
            "flush_id": r.get("flush_id"),
            "status": r["status"],
            "total_ms": total,
            "stage_sum_ms": s_sum,
            "mismatch_ms": s_sum - total,
            "stages_ms": stages,
        })
    if not requests:
        return None
    p50 = {
        name: _median([r["stages_ms"][name] for r in requests])
        for name in SERVE_STAGES
    }
    return {
        "requests": requests,
        "n": len(requests),
        "stage_p50_ms": p50,
        "critical_stage": max(p50, key=lambda k: p50[k] or 0.0),
        "max_abs_mismatch_ms": max(
            abs(r["mismatch_ms"]) for r in requests
        ),
    }


def retry_report(events: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Per fault episode: the recovery action taken and the time from the
    fault record to the first epoch completed afterwards (end-to-end
    retry cost, backoff + restore + replay included)."""
    faults = [e for e in events if e["event"] == "fault"]
    if not faults:
        return None
    recoveries = [e for e in events if e["event"] == "recovery"]
    epochs = [e for e in events if e["event"] == "epoch"]
    episodes = []
    for f in faults:
        # same-run pairing only: a merged multi-run dir must not heal one
        # run's fault with the first epoch another run happens to finish
        rid = f.get("run_id")
        action = next(
            (r for r in recoveries
             if r.get("run_id") == rid and r["ts"] >= f["ts"]), None
        )
        healed = next(
            (e for e in epochs
             if e.get("run_id") == rid and e["ts"] > f["ts"]), None
        )
        episodes.append({
            "kind": f.get("kind"),
            "epoch": f.get("epoch"),
            "attempt": f.get("attempt"),
            "action": action.get("action") if action else None,
            "recover_s": (healed["ts"] - f["ts"]) if healed else None,
        })
    replayed = 0
    for e in reversed(events):
        if e["event"] == "run_summary":
            replayed = int(
                (e.get("counters") or {}).get(
                    "resilience.replayed_epochs", 0
                )
            )
            break
    recovered = [p["recover_s"] for p in episodes if p["recover_s"]]
    return {
        "episodes": episodes,
        "n": len(episodes),
        "replayed_epochs": replayed,
        "mean_recover_s": (
            sum(recovered) / len(recovered) if recovered else None
        ),
    }


def elastic_report(events: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The elastic degraded-mode verdict: per ``replan`` episode, the
    time from the triggering ``rank_loss`` detection record to the first
    post-replan epoch end — end-to-end time-to-recover, plan rebuild +
    checkpoint restore + recompile included."""
    replans = [e for e in events if e["event"] == "replan"]
    if not replans:
        return None
    losses = [e for e in events if e["event"] == "rank_loss"]
    epochs = [e for e in events if e["event"] == "epoch"]
    episodes = []
    for r in replans:
        # same-run pairing only (the retry_report rule): a merged dir
        # must not heal one run's rank loss with another run's epochs
        rid = r.get("run_id")
        trigger = next(
            (x for x in reversed(losses)
             if x.get("run_id") == rid and x["ts"] <= r["ts"]), None
        )
        healed = next(
            (x for x in epochs
             if x.get("run_id") == rid and x["ts"] > r["ts"]), None
        )
        episodes.append({
            "from_partitions": r.get("from_partitions"),
            "to_partitions": r.get("to_partitions"),
            "lost": r.get("lost"),
            "recover_s": (
                healed["ts"] - trigger["ts"]
                if healed is not None and trigger is not None else None
            ),
        })
    recovered = [e["recover_s"] for e in episodes
                 if e["recover_s"] is not None]
    return {
        "episodes": episodes,
        "n": len(episodes),
        "mean_recover_s": (
            sum(recovered) / len(recovered) if recovered else None
        ),
    }


def span_inventory(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    by_name: Dict[str, Dict[str, float]] = {}
    for s in spans_of(events):
        b = by_name.setdefault(s["name"], {"count": 0, "total_s": 0.0})
        b["count"] += 1
        b["total_s"] += s["dur_s"]
    return by_name


def timeline_block(events: List[Dict[str, Any]]) -> List[str]:
    """The "span timeline" lines tools/metrics_report embeds under each
    run's #key=value block (one stream's events; empty without spans)."""
    inv = span_inventory(events)
    if not inv:
        return []
    lines = ["span timeline:"]
    lines.append(
        "#spans="
        + " ".join(
            f"{name}:{int(b['count'])}({b['total_s'] * 1000:.1f}ms)"
            for name, b in sorted(inv.items())
        )
    )
    ring = ring_overlap_report(events)
    if ring is not None and ring.get("overlap_s") is not None:
        eff = ring.get("efficiency")
        lines.append(
            f"#ring_overlap_efficiency="
            f"{f'{eff:.2f}' if eff is not None else 'n/a'} "
            f"(overlapped={ring['overlap_s'] * 1000:.3f}ms "
            f"compute_only={ring['compute_s'] * 1000:.3f}ms "
            f"exchange_only={ring['exchange_s'] * 1000:.3f}ms"
            f"{', sim rig' if ring.get('simulated') else ''})"
        )
    samp = sample_pipeline_report(events)
    if samp is not None:
        hidden = samp["hidden_frac"]
        lines.append(
            f"#sample_pipeline={samp['batches']} batch(es), "
            f"produce={samp['produce_s'] * 1000:.3f}ms "
            f"h2d={samp['h2d_s'] * 1000:.3f}ms "
            f"consumer_wait={samp['wait_s'] * 1000:.3f}ms "
            f"(hidden_frac="
            f"{f'{hidden:.2f}' if hidden is not None else 'n/a'})"
        )
    serve = serve_critical_path(events)
    if serve is not None:
        p50 = serve["stage_p50_ms"]
        lines.append(
            "#serve_critical_path_p50="
            + " ".join(
                f"{name}:{p50[name]:.3f}ms" for name in SERVE_STAGES
                if p50.get(name) is not None
            )
            + f" (critical={serve['critical_stage']}, n={serve['n']}, "
            f"max|stage_sum-latency|={serve['max_abs_mismatch_ms']:.3f}ms)"
        )
    ela = elastic_report(events)
    if ela is not None:
        last = ela["episodes"][-1]
        mean = ela["mean_recover_s"]
        lines.append(
            f"#elastic={ela['n']} replan(s), last P "
            f"{last['from_partitions']}->{last['to_partitions']} "
            f"(lost partition {last['lost']}), time_to_recover="
            f"{f'{mean:.2f}s' if mean is not None else 'n/a'}"
        )
    retry = retry_report(events)
    if retry is not None:
        mean = retry["mean_recover_s"]
        lines.append(
            f"#retry_cost={retry['n']} episode(s), "
            f"mean_time_to_recover="
            f"{f'{mean:.2f}s' if mean is not None else 'n/a'}, "
            f"replayed_epochs={retry['replayed_epochs']}"
        )
    return lines


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="merge per-rank obs span streams into one causal "
        "timeline: Chrome trace export + overlap/critical-path/retry "
        "derived metrics"
    )
    ap.add_argument("paths", nargs="+",
                    help="JSONL file(s) or NTS_METRICS_DIR directories")
    ap.add_argument("--chrome", metavar="OUT.json", default="",
                    help="write Chrome trace-event JSON here "
                    "(Perfetto / chrome://tracing)")
    ap.add_argument("--json", action="store_true",
                    help="emit the derived metrics as one JSON object")
    args = ap.parse_args(argv)

    streams = load_streams(expand_paths(args.paths))
    streams = [s for s in streams if spans_of(s.events)]
    if not streams:
        print("no span records found in the given streams",
              file=sys.stderr)
        return 1

    merged: List[Dict[str, Any]] = []
    for s in streams:
        merged.extend(s.events)
    merged.sort(key=lambda e: e["ts"])

    out: Dict[str, Any] = {
        "streams": [
            {
                "path": s.path,
                "rank": s.rank,
                "run_id": s.run_id,
                "spans": len(spans_of(s.events)),
                "mono_wall_offset_s": s.offset,
                "align_shift_s": s.align,
            }
            for s in streams
        ],
        "ring_overlap": ring_overlap_report(merged),
        "serve_critical_path": serve_critical_path(merged),
        "retries": retry_report(merged),
        "elastic": elastic_report(merged),
        "span_inventory": span_inventory(merged),
    }
    if args.chrome:
        trace = chrome_trace(streams)
        validate_chrome_trace(trace)
        with open(args.chrome, "w", encoding="utf-8") as fh:
            json.dump(trace, fh)
        out["chrome"] = {
            "path": args.chrome, "events": len(trace["traceEvents"]),
        }

    if args.json:
        print(json.dumps(out, default=str))
        return 0

    for s in out["streams"]:
        off = s["mono_wall_offset_s"]
        print(
            f"== stream rank {s['rank']} · {s['run_id']} — {s['path']}\n"
            f"   {s['spans']} spans, mono->wall offset "
            f"{off:.3f}s, align shift {s['align_shift_s'] * 1000:+.3f}ms"
        )
    for line in timeline_block(merged):
        print(line)
    serve = out["serve_critical_path"]
    if serve is not None:
        worst = max(serve["requests"], key=lambda r: r["total_ms"])
        print(
            f"slowest request {worst['req_id']}: "
            f"{worst['total_ms']:.3f}ms total = "
            + " + ".join(
                f"{worst['stages_ms'][n]:.3f} {n}" for n in SERVE_STAGES
            )
        )
    if "chrome" in out:
        print(
            f"chrome trace: {out['chrome']['events']} events -> "
            f"{out['chrome']['path']} (open in Perfetto; with "
            f"NTS_PROFILE_DIR the same span names appear inside the "
            f"jax.profiler device trace)"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
