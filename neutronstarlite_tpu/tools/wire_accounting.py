"""Exact per-layer WIRE accounting for the dist exchanges (chip-free).

VERDICT r3 item 7: the comm-layer ranking and the DepCache threshold were
justified by CPU-mesh wall time, which ranks schedules noisily and says
nothing about real ICI. The decisions' actual currency is WIRE VOLUME —
an exact host-side count, no device needed — so this tool prints it and
checks the auto policies against it:

- per-device per-layer RECEIVED remote rows for each comm layer. The
  dense exchanges (ring ppermute rotation, ell/blocked all_gather) each
  deliver P-1 remote shard chunks of vp rows; the mirror all_to_all
  delivers P-1 compacted chunks of Mb rows (the reference's active-only
  message optimization, comm/network.cpp:505-518, as a layout property).
  Mb <= vp always (compaction never grows a chunk), so COMM_LAYER:auto's
  mirror-leaning tie-break is wire-sound; the tool verifies the choice
  equals the wire argmin on the actual graph.
- the DepCache split at a threshold ladder: mc cached (replicated hot
  rows, shipped only on refresh epochs) vs mf fetched per layer, with the
  per-layer amortized wire at refresh cadence R =
  (P-1) * (mf + mc / R) rows — and whether REP_THRESHOLD:auto's choice
  is the wire-minimizing threshold whose cache fits the HBM budget
  (core/NtsScheduler.hpp:556-637 analog).

Usage:
  python -m neutronstarlite_tpu.tools.wire_accounting
      [--scale 1.0 | --cora] [--partitions 8] [--feature 602]
      [--refresh 3] [--budget-mib 256]
Prints ONE JSON line; human-readable table to stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def exchange_rows_per_device(kind: str, P: int, vp: int, mb: int = 0) -> int:
    """Per-device per-layer RECEIVED remote feature rows for one exchange.

    The single formula bridged into the live ``obs`` wire counters (dist
    trainers) AND used by :func:`accounting` below, so the offline report
    and the run-time telemetry can never disagree. Dense exchanges (ring
    ppermute rotation, ell/blocked all_gather, AND the ring-pipelined
    ``ring_blocked`` path) deliver P-1 remote shard chunks of ``vp`` rows
    — ring_blocked ships the SAME total volume as all_gather, chunked
    over P-1 overlapped hops so at most one chunk is in flight (see
    :func:`peak_resident_rows`); the mirror all_to_all delivers P-1
    compacted chunks of ``mb`` rows (the reference's active-only message
    optimization, comm/network.cpp:505-518, as a layout property).
    """
    if P <= 1:
        return 0
    if kind in ("mirror", "mirror_uniform"):
        return (P - 1) * mb
    return (P - 1) * vp


def sample_batch_payload_bytes(node_caps, fanouts) -> int:
    """Bytes of ONE padded SampledBatch device payload — the sampled
    path's per-batch H2D cost the ``sample.h2d_bytes`` counter carries.

    The single formula in three places: the sync trainer loop prices it
    per step, the async producer MEASURES the staged payload
    (sample/pipeline.payload_nbytes — padded capacities are static, so
    measured == priced), and the tuner's sampled-family prior ranks
    modes by it (``SAMPLE_PIPELINE:fused`` ships 0 — the whole batch
    lives on-device). Layout (sample/sampler.py): per-level padded
    int64 node ids at ``node_caps[l]``; per hop ``ecap_h =
    node_caps[h+1] * fanouts[h]`` edges of (int64 src_local, int64
    dst_local, f32 weight); int64 seeds + f32 seed_mask at batch width.
    """
    caps = [int(c) for c in node_caps]
    fo = [int(f) for f in fanouts]
    if len(caps) != len(fo) + 1:
        raise ValueError(
            f"node_caps must be one longer than fanouts, got "
            f"{len(caps)} caps / {len(fo)} fanouts"
        )
    nodes = sum(caps) * 8
    hops = sum(caps[h + 1] * fo[h] * (8 + 8 + 4) for h in range(len(fo)))
    return nodes + hops + caps[-1] * (8 + 4)


def sample_h2d_bytes_per_epoch(n_seeds: int, node_caps, fanouts,
                               mode: str = "sync") -> int:
    """Per-epoch sampled-path H2D bytes for a SAMPLE_PIPELINE mode:
    batches/epoch x the payload formula above for the host-staged modes
    (sync/pipelined/device all ship the same padded payload — the
    pipeline changes WHEN, device mode changes WHERE the draw runs, not
    what crosses the wire), exactly 0 for fused."""
    if mode == "fused":
        return 0
    B = int(node_caps[-1])
    n_batches = -(-int(n_seeds) // max(B, 1))
    return n_batches * sample_batch_payload_bytes(node_caps, fanouts)


def peak_resident_rows(kind: str, P: int, vp: int, mb: int = 0) -> int:
    """Peak EXCHANGE-BUFFER rows live at once per device (the memory half
    of the comm-layer decision; the row count the obs gauge
    ``wire.peak_resident_rows`` carries). The all_gather family
    materializes every shard before compute starts (P*vp); the ring
    families are double-buffered — resident shard + the one in flight
    (2*vp, independent of P); the mirror all_to_all lands all P-1 remote
    compacted chunks plus the resident diagonal (P*mb)."""
    if P <= 1:
        return vp
    if kind in ("mirror", "mirror_uniform"):
        return P * mb
    if kind in ("ring", "ring_blocked"):
        return min(2, P) * vp
    return P * vp


def predict_mesh(g, pv: int, pf: int, widths, itemsize: int = 4,
                 out_widths=None) -> dict:
    """Exact per-device wire/memory prediction for the 2D (vertex x
    feature) mesh layout (parallel/partitioner.py) on one graph:

    - ``bytes_per_epoch``: the vertex RING exchange — (pv-1) hops per
      layer, each shipping a ``[vp, slab_width(w, pf)]`` feature slab.
      This is the quantity the live ``wire.bytes_fwd`` counter carries
      (same ``slab_width`` definition, so live == predicted whenever no
      skip suffix trims the rotation);
    - ``allreduce_bytes_per_epoch``: the feature-axis all-reduce XLA
      inserts where the blocked kernels contract (``agg @ W``): a ring
      all-reduce ships ~``2*(pf-1)/pf`` of each ``[vp, w_out]`` product
      per device per layer. Analytic only — GSPMD owns the collective,
      so no live counter mirrors it; the tune prior prices it so a
      degenerate ``(1, P)`` mesh cannot masquerade as wire-free;
    - ``peak_resident_feature_bytes``: the double-buffered exchange
      residency at slab width — ``min(2, pv) * vp * max(slab) *
      itemsize``, the O(vp*f/Pf) memory claim as a number (the
      ``wire.peak_resident_feature_bytes`` obs gauge).
    """
    from neutronstarlite_tpu.graph.storage import partition_offsets
    from neutronstarlite_tpu.parallel.partitioner import slab_width
    from neutronstarlite_tpu.parallel.vertex_space import round_up

    pv, pf = max(int(pv), 1), max(int(pf), 1)
    offsets = partition_offsets(g.v_num, g.in_degree, pv)
    vp = round_up(int(np.diff(offsets).max()), 8)  # DistGraph.build's rule
    widths = [int(w) for w in widths]
    outs = [int(w) for w in (out_widths if out_widths else widths)]
    slabs = [slab_width(w, pf) for w in widths]
    rows = (pv - 1) * vp
    peak_rows = min(2, pv) * vp
    return {
        "pv": pv, "pf": pf, "vp": int(vp),
        "slab_widths": slabs,
        "exchange_rows": int(rows),
        "bytes_per_epoch": int(rows * sum(slabs) * itemsize),
        "allreduce_bytes_per_epoch": int(
            sum(2 * (pf - 1) * vp * w // pf for w in outs) * itemsize
        ),
        "peak_resident_rows": int(peak_rows),
        "peak_resident_feature_bytes": int(
            peak_rows * (max(slabs) if slabs else 0) * itemsize
        ),
    }


def predict_all(g, P: int, f: int, widths=None, itemsize: int = 4,
                mesh=None) -> dict:
    """Machine-readable per-strategy prediction for one (graph, P, f):
    exchange rows, peak resident rows, and bytes per epoch — the
    autotuner's analytic prior (neutronstarlite_tpu/tune/runner.py) and
    the CLI ``--json`` payload in one function.

    ``widths``: the per-layer exchange widths (defaults to ``[f]`` — one
    exchange per epoch at feature width f); ``itemsize``: wire bytes per
    value (4 = f32, 2 = bf16 wire/compute). All strategies are priced by
    the SAME :func:`exchange_rows_per_device` /
    :func:`peak_resident_rows` formulas the live obs counters use, so the
    prior, the offline report, and the run-time telemetry can never
    disagree. ``mesh=(pv, pf)`` additionally prices the 2D
    (vertex x feature) layout as strategy ``ring2d`` via
    :func:`predict_mesh` (same single-definition slab math as the live
    ``mesh.*`` gauges).
    """
    from neutronstarlite_tpu.parallel.mirror import MirrorGraph, SplitMirror

    mb_uni, vp = MirrorGraph.estimate_mb(g, P)
    mb, _ = SplitMirror.estimate_mb_remote(g, P)
    widths = [int(w) for w in (widths if widths else [f])]
    mbs = {"mirror": mb, "mirror_uniform": mb_uni}
    strategies = {}
    for kind in ("ring", "ell", "blocked", "ring_blocked", "mirror",
                 "mirror_uniform"):
        m = mbs.get(kind, 0)
        rows = exchange_rows_per_device(kind, P, vp, m)
        peak = peak_resident_rows(kind, P, vp, m)
        strategies[kind] = {
            "exchange_rows": int(rows),
            "peak_resident_rows": int(peak),
            "bytes_per_epoch": int(rows * sum(widths) * itemsize),
            "peak_resident_bytes": int(peak * max(widths) * itemsize),
        }
    if mesh is not None:
        pv, pf = (int(x) for x in mesh)
        strategies["ring2d"] = predict_mesh(
            g, pv, pf, widths, itemsize=itemsize
        )
    return {
        "P": int(P), "f": int(f), "vp": int(vp), "mb": int(mb),
        "mb_uniform": int(mb_uni), "widths": widths,
        "itemsize": int(itemsize), "strategies": strategies,
    }


def accounting(g, P: int, f: int, refresh: int, budget_bytes: int,
               thresholds=None) -> dict:
    """All counts are per device per layer unless stated; bytes are f32
    rows (itemsize 4) at feature width f."""
    from neutronstarlite_tpu.parallel.feature_cache import CachedMirrorGraph
    from neutronstarlite_tpu.parallel.mirror import MirrorGraph, SplitMirror

    mb_uni, vp = MirrorGraph.estimate_mb(g, P)
    # the GCN fused path ships the SPLIT exchange since round 5: remote
    # need-sets only (self-loop graphs saturate the uniform mb at vp);
    # the uniform price is kept as a row for the GAT/DepCache chains that
    # still use the [P, P*Mb] layout
    mb, _ = SplitMirror.estimate_mb_remote(g, P)
    dense_rows = exchange_rows_per_device("ring", P, vp)
    mirror_rows = exchange_rows_per_device("mirror", P, vp, mb)
    mirror_uni_rows = exchange_rows_per_device("mirror", P, vp, mb_uni)
    layer_rows = {
        "ring": dense_rows, "ell": dense_rows, "blocked": dense_rows,
        "ring_blocked": dense_rows,
        "mirror": mirror_rows, "mirror_uniform": mirror_uni_rows,
    }
    out = {
        "P": P, "f": f, "vp": vp, "mb": mb, "mb_uniform": mb_uni,
        "layers": layer_rows,
        "bytes_per_layer": {k: v * f * 4 for k, v in layer_rows.items()},
        # wire volume is only half the decision: the ring ships the SAME
        # (P-1)*vp rows as all_gather but holds 2 shard buffers live
        # instead of P — the dist memory envelope argument. Each mirror
        # flavor is priced at ITS OWN slot count (the uniform layout's
        # mb_uni, not the split layout's compacted mb).
        "peak_resident_rows": {
            k: peak_resident_rows(
                k, P, vp,
                {"mirror": mb, "mirror_uniform": mb_uni}.get(k, 0),
            )
            for k in layer_rows
        },
    }
    out["peak_resident_bytes"] = {
        k: v * f * 4 for k, v in out["peak_resident_rows"].items()
    }

    # threshold ladder: degree percentiles of the mirror sources
    if thresholds is None:
        degs = g.out_degree[g.out_degree > 0]
        qs = [50, 75, 90, 99]
        thresholds = sorted(
            {int(np.percentile(degs, q)) for q in qs} | {1}
        )
    ladder = []
    for t in thresholds:
        cm = CachedMirrorGraph.build(g, P, replication_threshold=t)
        amortized = (P - 1) * (cm.mf + cm.mc / max(refresh, 1))
        ladder.append({
            "threshold": t, "mc": cm.mc, "mf": cm.mf,
            "hot_fraction": round(float(cm.cached_fraction), 4),
            "fetch_rows": (P - 1) * cm.mf,
            "amortized_rows": round(amortized, 1),
            "cached_bytes_device": P * cm.mc * f * 4,
        })
    out["depcache"] = ladder

    # --- auto decisions vs the wire argmin --------------------------------
    from neutronstarlite_tpu.models.gcn_dist import DistGCNTrainer
    from neutronstarlite_tpu.utils.config import InputInfo

    cfg = InputInfo()
    cfg.comm_layer = "auto"
    auto_choice = DistGCNTrainer.resolve_comm_layer(cfg, g, P)
    wire_argmin = min(out["layers"], key=out["layers"].get)
    out["comm_auto"] = {
        "choice": auto_choice,
        "wire_argmin": wire_argmin,
        # mirror and the dense layers tie when compaction saturates
        # (mb == vp); the auto tie-break prefers mirror (one all_to_all
        # vs P-1 dependent rounds) — wire-equivalent, so still sound
        "wire_optimal": out["layers"][auto_choice]
        == out["layers"][wire_argmin],
    }

    t_auto = CachedMirrorGraph.choose_replication_threshold(
        g, P, f, budget_bytes
    )
    cm_auto = CachedMirrorGraph.build(g, P, replication_threshold=t_auto)
    fits = P * cm_auto.mc * f * 4 <= budget_bytes
    # wire-minimality under the budget: no ladder threshold that FITS the
    # budget ships strictly less per-layer wire (smaller mf) than the
    # auto choice — compared by wire, not by threshold value (different
    # thresholds can induce the same hot/cold split)
    smaller_wire_fitting = [
        e for e in ladder
        if e["cached_bytes_device"] <= budget_bytes and e["mf"] < cm_auto.mf
    ]
    out["rep_auto"] = {
        "threshold": t_auto, "mc": cm_auto.mc, "mf": cm_auto.mf,
        "cached_bytes_device": P * cm_auto.mc * f * 4,
        "budget_bytes": budget_bytes,
        "fits": fits,
        "wire_minimal_under_budget": not smaller_wire_fitting,
    }
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--cora", action="store_true")
    ap.add_argument("--partitions", type=int, default=8)
    ap.add_argument("--feature", type=int, default=602)
    ap.add_argument("--refresh", type=int, default=3)
    ap.add_argument("--budget-mib", type=int, default=256)
    ap.add_argument(
        "--mesh", default="",
        help="Pv,Pf — also price the 2D (vertex x feature) mesh layout "
        "(strategy 'ring2d' in the --json payload; predict_mesh)",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="machine-readable mode: print the predict_all() per-strategy "
        "prediction (exchange rows, peak resident rows, bytes/epoch) as "
        "one JSON line and skip the DepCache ladder / auto-policy audit",
    )
    args = ap.parse_args(argv)

    if args.cora:
        from neutronstarlite_tpu.graph.storage import (
            build_graph, load_edges,
        )

        fix = os.path.join(REPO, "tests", "fixtures", "cora")
        src, dst = load_edges(os.path.join(fix, "cora.2708.edge.self"))
        g = build_graph(src, dst, 2708, weight="gcn_norm")
        name = "cora"
    else:
        from bench import build_and_cache_graph, load_cached_graph

        d, v_num, e_num, _ = build_and_cache_graph(args.scale)
        g, _, _ = load_cached_graph(d)
        name = f"reddit_synth_x{args.scale:g}"

    mesh = None
    if args.mesh:
        from neutronstarlite_tpu.parallel.partitioner import MeshSpec

        spec = MeshSpec.parse(args.mesh)
        mesh = (spec.pv, spec.pf)

    if args.json:
        out = predict_all(g, args.partitions, args.feature, mesh=mesh)
        out["graph"] = name
        print(json.dumps(out))
        return 0

    out = accounting(
        g, args.partitions, args.feature, args.refresh,
        args.budget_mib << 20,
    )
    out["graph"] = name
    print(
        "\n".join(
            [f"wire accounting: {name} P={out['P']} f={out['f']} "
             f"vp={out['vp']} mb={out['mb']}"]
            + [f"  {k:14s} {v:>12d} rows/dev/layer "
               f"({out['bytes_per_layer'][k] / 2**20:.1f} MiB wire, "
               f"{out['peak_resident_rows'][k]:>8d} rows "
               f"{out['peak_resident_bytes'][k] / 2**20:.1f} MiB resident)"
               for k, v in out["layers"].items()]
            + [f"  depcache t={e['threshold']:>6d}: mc={e['mc']:>6d} "
               f"mf={e['mf']:>6d} hot={e['hot_fraction']:.3f} "
               f"amortized={e['amortized_rows']:>10.0f} rows/dev/layer"
               for e in out["depcache"]]
            + [f"  comm auto -> {out['comm_auto']['choice']} "
               f"(wire argmin {out['comm_auto']['wire_argmin']}, "
               f"optimal={out['comm_auto']['wire_optimal']})",
               f"  rep auto -> t={out['rep_auto']['threshold']} "
               f"fits={out['rep_auto']['fits']} "
               f"minimal={out['rep_auto']['wire_minimal_under_budget']}"]
        ),
        file=sys.stderr,
    )
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
