"""Preflight checkpoint validator: manifest schema + per-array digests.

Runs exactly the verification ``restore_checkpoint`` applies before
trusting a step (utils/checkpoint.verify_step_dir) as a standalone CLI,
so CI or an operator can validate a checkpoint directory *before*
scheduling a resume on expensive accelerator time:

  python -m neutronstarlite_tpu.tools.verify_checkpoint <ckpt-dir> [...]
      [--quiet]

For every ``step-<n>/`` dir under each given checkpoint root (plus a
legacy flat-layout checkpoint, if present) it prints per-array status —
sha256 digest, shape, and dtype checked against the manifest — and a
verdict line. Quarantined ``*.corrupt`` dirs are listed as evidence but
do not fail the check (restore already routes around them).

Exit codes: 0 = every verifiable checkpoint is intact; 1 = corruption or
an unreadable input; 2 = no checkpoint found at all.

Beyond the CLI, :func:`preflight_checkpoint` is the ROLLOUT preflight
(serve/crosshost.py): a rolling model rollout refuses a candidate
checkpoint root whose NEWEST retained step fails manifest/digest
verification — restore would silently route around it to an older step,
and "promote checkpoint X" must never quietly serve checkpoint X-1.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from neutronstarlite_tpu.utils.checkpoint import (  # noqa: E402
    ARRAYS,
    CORRUPT_SUFFIX,
    MANIFEST,
    CheckpointCorruptError,
    list_steps,
    verify_step_dir,
)


class PreflightError(RuntimeError):
    """A checkpoint root failed rollout preflight; carries the digest/
    manifest problems (empty for "no checkpoint at all")."""

    def __init__(self, msg: str, problems: List[str] = ()):  # type: ignore[assignment]
        super().__init__(msg)
        self.problems = list(problems)


def preflight_checkpoint(root: str) -> Tuple[str, int]:
    """Verify the checkpoint a restore from ``root`` would actually
    trust: the newest retained ``step-<n>/`` (or a legacy flat layout).
    Returns ``(step_dir, step)`` when it verifies; raises
    :class:`PreflightError` when the root holds no checkpoint or the
    newest step fails manifest schema / sha256 digest verification.

    Strictness is deliberate: ``restore_checkpoint`` quarantines a
    corrupt newest step and falls back to an older one — right for crash
    recovery, wrong for a rollout, where the operator named a SPECIFIC
    model and a silent fallback would canary (and promote) a different
    one."""
    if not os.path.isdir(root):
        raise PreflightError(f"{root}: not a directory")
    steps = list_steps(root)
    if steps:
        step_dir = steps[-1][1]  # list_steps sorts ascending by step
    elif os.path.exists(os.path.join(root, MANIFEST)):
        step_dir = root  # legacy flat layout / direct step dir
    else:
        raise PreflightError(
            f"{root}: no checkpoint found (no step-*/ dirs, no {MANIFEST})"
        )
    try:
        manifest, _status, _arrays = verify_step_dir(step_dir)
    except CheckpointCorruptError as e:
        raise PreflightError(
            f"{step_dir}: failed digest/manifest verification",
            problems=e.problems,
        ) from e
    except OSError as e:
        raise PreflightError(f"{step_dir}: unreadable ({e})") from e
    return step_dir, int(manifest.get("step", 0))


def _verify_one(step_dir: str, quiet: bool) -> bool:
    """Print per-array status for one step dir; True when intact."""
    label = os.path.relpath(step_dir)
    try:
        manifest, status, _arrays = verify_step_dir(step_dir)
    except CheckpointCorruptError as e:
        print(f"{label}: CORRUPT")
        for problem in e.problems:
            print(f"  !! {problem}")
        return False
    if not quiet:
        for name in sorted(status):
            meta = manifest.get("arrays", {}).get(name, {})
            print(
                f"  {name:<24s} {status[name]:<4s} "
                f"shape={tuple(meta.get('shape', ()))} "
                f"dtype={meta.get('dtype')} "
                f"sha256={meta.get('sha256', '')[:12]}"
            )
    n = len(status)
    legacy_note = "" if manifest.get("format", 1) >= 2 else " (no digests: legacy format)"
    print(f"{label}: OK step={manifest.get('step')} arrays={n}{legacy_note}")
    return True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate checkpoint manifest schema + sha256 digests"
    )
    ap.add_argument("paths", nargs="+", help="checkpoint dir(s) "
                    "(CHECKPOINT_DIR roots or individual step-N dirs)")
    ap.add_argument("--quiet", action="store_true",
                    help="verdict lines only, no per-array detail")
    args = ap.parse_args(argv)

    found = 0
    corrupt = 0
    for root in args.paths:
        if not os.path.isdir(root):
            print(f"{root}: not a directory", file=sys.stderr)
            corrupt += 1
            continue
        targets: List[str] = [d for _s, d in list_steps(root)]
        if os.path.exists(os.path.join(root, MANIFEST)):
            targets.append(root)  # legacy flat layout / direct step dir
        for name in sorted(os.listdir(root)):
            if CORRUPT_SUFFIX in name:
                print(f"{os.path.join(os.path.relpath(root), name)}: "
                      "quarantined (skipped)")
        if not targets:
            print(f"{root}: no checkpoint found "
                  f"(no step-*/ dirs, no {MANIFEST})", file=sys.stderr)
            continue
        for step_dir in targets:
            found += 1
            if not _verify_one(step_dir, args.quiet):
                corrupt += 1
    if corrupt:
        return 1
    if not found:
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
