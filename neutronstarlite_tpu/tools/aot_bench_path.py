"""AOT-compile one bench (order, path) full-scale program — compiler only.

Round-3 postmortem follow-up: both full-scale pallas sweep legs timed out
on-chip, and the leading explanation is aggregate Mosaic compile time.
The chip lease and the remote COMPILER are separate services — during the
2026-07-31 lease wedge the compiler kept answering (a Cora AOT compile
took 16.5 s while every ``jax.devices()`` init hung). This tool exploits
that: it builds the EXACT program bench.py's worker would run (same
trainer factory, same synthetic Reddit graph cache, same tables) and
compiles it against a TPU topology with no chip claimed, so

1. the compile-time question ("does the merged-level pallas program
   compile, and in how long?") is answered without burning a measurement
   window, and
2. the persistent executable cache (shared dir with the workers) may be
   seeded, turning the worker's own compile into a cache hit.

``NTS_PALLAS_FORCE_COMPILED=1`` is set so the pallas executor emits real
Mosaic calls while tracing on the CPU host (interpret mode would compile
the wrong program).

Usage: python -m neutronstarlite_tpu.tools.aot_bench_path
         [--order eager] [--path pallas] [--scale 1.0]
         [--topology v5e:2x2] [--precision bfloat16]
Prints ONE JSON line: {order, path, ok, build_s, compile_s, *_gib | error}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--order", default="eager", choices=["standard", "eager"])
    ap.add_argument(
        "--path", default="pallas",
        choices=["scatter", "ell", "blocked", "pallas", "bsp"],
    )
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--topology", default="v5e:2x2")
    ap.add_argument("--precision", default="bfloat16")
    ap.add_argument("--kernel-tile", type=int, default=8192)
    args = ap.parse_args(argv)

    # contract: no accelerator is ever claimed — host build on CPU, the
    # compile goes to the topology compiler
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["NTS_PALLAS_FORCE_COMPILED"] = "1"
    from neutronstarlite_tpu.utils.platform import honor_platform_env

    honor_platform_env()

    import jax
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS
    import numpy as np

    # same cache dir as the bench workers: a successful compile here can
    # make the worker's first run a cache hit
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/nts_jit_cache"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    except Exception as e:  # pragma: no cover
        print(f"compile cache unavailable: {e}", file=sys.stderr, flush=True)

    from bench import (
        LAYERS,
        N_LABELS,
        _make_trainer,
        build_and_cache_graph,
        build_host_tables,
        load_cached_graph,
    )
    from neutronstarlite_tpu.graph.dataset import GNNDatum

    out = {"order": args.order, "path": args.path, "scale": args.scale,
           "topology": args.topology}
    t0 = time.time()
    try:
        d, v_num, _, _ = build_and_cache_graph(args.scale)
        host_graph, src, dst = load_cached_graph(d)
        sizes = [int(s) for s in LAYERS.split("-")]
        datum = GNNDatum.random_generate(v_num, sizes[0], N_LABELS, seed=7)
        host_ell = build_host_tables(args.path, host_graph, args.kernel_tile)
        trainer = _make_trainer(
            args.order, args.path, args.precision, src, dst, datum, v_num,
            epochs=1, warmup=0, host_graph=host_graph, host_ell=host_ell,
            kernel_tile=args.kernel_tile,
        )
        topo = topologies.get_topology_desc(
            platform="tpu", topology_name=args.topology
        )
        mesh1 = Mesh(np.array(list(topo.devices)[:1]), ("one",))
        rep = NamedSharding(mesh1, PS())

        def spec(a):
            if hasattr(a, "shape") and hasattr(a, "dtype"):
                return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=rep)
            return a

        shapes = jax.tree.map(spec, trainer.aot_args())
        out["build_s"] = round(time.time() - t0, 1)
        t0 = time.time()
        compiled = trainer._train_step.lower(*shapes).compile()
        mem = compiled.memory_analysis()
        out.update(
            ok=True,
            compile_s=round(time.time() - t0, 1),
            argument_gib=round(mem.argument_size_in_bytes / 2**30, 3),
            temp_gib=round(mem.temp_size_in_bytes / 2**30, 3),
            output_gib=round(mem.output_size_in_bytes / 2**30, 3),
        )
    except Exception as e:  # noqa: BLE001 — report, don't trace-dump
        out.update(
            ok=False, error=f"{type(e).__name__}: {str(e)[:500]}",
            elapsed_s=round(time.time() - t0, 1),
        )
    print(json.dumps(out))
    return 0 if out.get("ok") else 1


if __name__ == "__main__":
    raise SystemExit(main())
