"""Single-chip kernel micro-benchmarks (docs/PERF.md §1, reproducible).

Round 2's §1 table came from ad-hoc scripts; this tool makes the method
durable and extends it to the round-3 kernels. Shapes follow §1: the
5%-Reddit edge set (E=5.73M) over the half-Reddit vertex table
([116k, f]), bf16 compute. Timing defeats the remote execution path's
identical-dispatch caching by feeding a fresh scalar into every
iteration (naive repeat-timing reports impossible numbers — §1's note);
reported time is the median of ``--iters`` post-compile runs.

Ops: dense matmul / HBM stream (method validation against hardware
peaks), random row gather, XLA ELL aggregate, sorted scatter-add, fused
Pallas ELL (VMEM-resident), fused Pallas ELL at 602 wide (the round-3
feature-column-chunked regime), and the streamed block-sparse kernel
(ops/bsp_ell.py). Failures (e.g. a Mosaic lowering gap) are recorded
per-op, never fatal.

Usage: python -m neutronstarlite_tpu.tools.micro_bench [--iters 10]
Prints ONE JSON line; the recovery plan step ``micro_kernels`` archives
it under docs/perf_runs/round3/.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

V = 116482  # half Reddit (the §1 table shapes)
E = 5730794  # 5% Reddit edges
F = 128
F_WIDE = 602


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument(
        "--scale", type=float, default=1.0,
        help="shrink V/E (CPU smoke tests; 1.0 = the §1 table shapes)",
    )
    ap.add_argument(
        "--ops", default="",
        help="comma-separated op-name substrings to run (default: all). "
        "A hung Mosaic compile stalls this process in C++ where no Python "
        "timeout can interrupt it — run suspect ops as separate invocations "
        "(the recovery plan's per-step subprocess timeout is the kill)",
    )
    args = ap.parse_args(argv)
    op_filter = [s for s in args.ops.split(",") if s]
    global V, E
    V = max(int(V * args.scale), 64)
    E = max(int(E * args.scale), 512)

    from neutronstarlite_tpu.utils.platform import honor_platform_env

    honor_platform_env()
    import jax
    import jax.numpy as jnp

    from neutronstarlite_tpu.graph.storage import build_graph
    from neutronstarlite_tpu.graph.synthetic import synthetic_power_law_graph
    from neutronstarlite_tpu.ops.bsp_ell import BspEllPair, bsp_gather_dst_from_src
    from neutronstarlite_tpu.ops.device_graph import DeviceGraph
    from neutronstarlite_tpu.ops.aggregate import gather_dst_from_src
    from neutronstarlite_tpu.ops.edge import (
        aggregate_edge_to_dst_weighted,
        edge_softmax,
    )
    from neutronstarlite_tpu.ops.ell import EllPair, ell_gather_dst_from_src
    from neutronstarlite_tpu.ops.fused_edge import (
        FusedEdgePair,
        fused_edge_attention_aggregate,
    )
    from neutronstarlite_tpu.ops.pallas_kernels import (
        PALLAS_MIN_K,
        gather_dst_from_src_pallas,
        merge_low_k_levels,
    )

    def key_rng(key: str) -> np.random.Generator:
        # one independent stream per builder key: array contents must not
        # depend on demand order (an --ops-filtered triage run and a full
        # run build resources in different orders; a shared stream would
        # make them measure different random data)
        import zlib

        return np.random.default_rng([args.seed, zlib.crc32(key.encode())])

    out = {"platform": jax.default_backend(), "device": str(jax.devices()[0]),
           "V": V, "E": E, "ops": {}}

    def selected(name: str) -> bool:
        return not op_filter or any(s in name for s in op_filter)

    # Every input — graph tables AND dense arrays — is built lazily through
    # this cache, so a filtered triage run pays only for what its ops touch
    # (the bsp packing and the 233k x 602 wide table are minutes/hundreds
    # of MB at --scale 2.0 on the 1-core rig). Ops declare their resources
    # by key in OPS below; there is exactly one place op names live.
    built = {}

    def need(key):
        if key not in built:
            print(f"building {key} (host)...", file=sys.stderr, flush=True)
            built[key] = builders[key]()
        return built[key]

    builders = {
        "g": lambda: build_graph(
            *synthetic_power_law_graph(V, E, seed=args.seed), V,
            weight="gcn_norm",
        ),
        "dg": lambda: DeviceGraph.from_host(need("g")),
        "ell": lambda: EllPair.from_host(need("g")),
        # the production pallas path merges low-K levels at build time
        # (PallasEllPair.from_pair) — measure what production runs
        "ell_merged": lambda: merge_low_k_levels(
            need("ell").fwd, PALLAS_MIN_K
        ),
        "bsp": lambda: BspEllPair.from_host(need("g"), dt=512, vt=8192),
        "x": lambda: jnp.asarray(
            key_rng("x").standard_normal((V, F)).astype(np.float32),
            jnp.bfloat16,
        ),
        "xw": lambda: jnp.asarray(
            key_rng("xw").standard_normal((V, F_WIDE)).astype(np.float32),
            jnp.bfloat16,
        ),
        "w_mm": lambda: jnp.asarray(
            key_rng("w_mm").standard_normal((F_WIDE, F)).astype(np.float32),
            jnp.bfloat16,
        ),
        "idx": lambda: jnp.asarray(
            key_rng("idx").integers(0, V, size=E), jnp.int32
        ),
        "big": lambda: jnp.asarray(
            key_rng("big").standard_normal(8 << 20).astype(np.float32)  # 32 MB
        ),
        # ---- edge family (GAT/GGCN attention chains): unit-weight graph,
        # the eager DeviceGraph chain vs the fused blocked kernel
        "g1": lambda: build_graph(
            *synthetic_power_law_graph(V, E, seed=args.seed), V,
            weight="ones",
        ),
        "dg1": lambda: DeviceGraph.from_host(need("g1")),
        "fused": lambda: FusedEdgePair.from_host(need("g1")),
        "al": lambda: jnp.asarray(
            key_rng("al").standard_normal((V, 1)).astype(np.float32)
        ),
        "ar": lambda: jnp.asarray(
            key_rng("ar").standard_normal((V, 1)).astype(np.float32)
        ),
        "hs": lambda: jnp.asarray(
            key_rng("hs").standard_normal((V, F)).astype(np.float32),
            jnp.bfloat16,
        ),
        "hd": lambda: jnp.asarray(
            key_rng("hd").standard_normal((V, F)).astype(np.float32),
            jnp.bfloat16,
        ),
    }

    def eager_edge_chain(dg, h, a_src, a_dst, slope):
        """The decoupled score -> per-dst softmax -> weighted-aggregate
        chain over the [Ep]-shaped edge space (models/gat.py / ggcn.py)."""
        score = jax.nn.leaky_relu(
            a_src[dg.csc_src] + a_dst[dg.csc_dst], negative_slope=slope
        )
        s = edge_softmax(dg, score)
        return aggregate_edge_to_dst_weighted(dg, s, h)


    def timed(name, make_fn, traffic_bytes=None, flops=None):
        """make_fn() -> fn(scalar) -> array; records median ms (+ rate)."""
        try:
            fn = make_fn()
            jfn = jax.jit(fn)
            jax.block_until_ready(jfn(jnp.float32(1.0)))  # compile
            ts = []
            for i in range(args.iters):
                s = jnp.float32(1.0 + 1e-6 * (i + 1))  # fresh dispatch
                t0 = time.perf_counter()
                jax.block_until_ready(jfn(s))
                ts.append(time.perf_counter() - t0)
            med = float(np.median(ts))
            rec = {"ms": round(med * 1e3, 4)}
            if traffic_bytes:
                rec["apparent_gbs"] = round(traffic_bytes / med / 1e9, 1)
            if flops:
                rec["tflops"] = round(flops / med / 1e12, 1)
            out["ops"][name] = rec
            print(f"{name}: {rec}", file=sys.stderr, flush=True)
        except Exception as e:  # noqa: BLE001 — record, keep going
            out["ops"][name] = {"error": f"{type(e).__name__}: {str(e)[:200]}"}
            print(f"{name} FAILED: {out['ops'][name]}", file=sys.stderr, flush=True)

    # the single source of op names: (name, needs, fn_factory, kwargs).
    # Resources are resolved EAGERLY (outside any jit trace — building a
    # table mid-trace caches leaked tracers) and only for selected ops, so
    # the filter decides what gets built and a rename cannot drift out of
    # sync with a gate
    OPS = [
        ("matmul_bf16_602x128", ("xw", "w_mm"),
         lambda xw, w_mm: lambda s: (xw * s) @ w_mm,
         dict(flops=2.0 * V * F_WIDE * F)),
        ("hbm_stream_f32_64MB", ("big",),
         lambda big: lambda s: big * s,
         dict(traffic_bytes=2 * (8 << 20) * 4)),
        ("row_gather_bf16", ("x", "idx"),
         lambda x, idx: lambda s: (x * s)[idx],
         dict(traffic_bytes=E * F * 2)),
        ("ell_aggregate_xla_bf16", ("ell", "x"),
         lambda ell, x: lambda s: ell_gather_dst_from_src(ell, x * s),
         dict(traffic_bytes=E * F * 2)),
        ("sorted_scatter_bf16", ("dg", "x"),
         lambda dg, x: lambda s: gather_dst_from_src(dg, x * s),
         dict(traffic_bytes=E * F * 2)),
        ("bsp_streamed_bf16", ("bsp", "x"),
         lambda bsp, x: lambda s: bsp_gather_dst_from_src(bsp, x * s),
         dict(traffic_bytes=E * F * 2)),
        # edge family: eager chain vs the fused blocked kernel, fwd+bwd
        # (the fused backward is three streamed passes; forward-only
        # timing would hide most of its cost). The `_eager` / `_fused`
        # suffix pair is what metrics_report --diff canonicalizes when a
        # micro_bench JSON is used as a diff side (scripts/ci_tier1.sh).
        ("edge_gat_eager", ("dg1", "x", "al", "ar"),
         lambda dg, x, al, ar: lambda s: jax.grad(
             lambda h: (eager_edge_chain(dg, h, al, ar, 0.01) ** 2).sum()
         )(x * s),
         dict(traffic_bytes=3 * E * F * 2)),
        ("edge_gat_fused", ("fused", "x", "al", "ar"),
         lambda fe, x, al, ar: lambda s: jax.grad(
             lambda h: (
                 fused_edge_attention_aggregate(fe, h, al, ar, 0.01) ** 2
             ).sum()
         )(x * s),
         dict(traffic_bytes=3 * E * F * 2)),
        ("edge_ggcn_eager", ("dg1", "x", "hs", "hd"),
         lambda dg, x, hs, hd: lambda s: jax.grad(
             lambda h: (eager_edge_chain(dg, h, hs, hd, 0.2) ** 2).sum()
         )(x * s),
         dict(traffic_bytes=3 * E * F * 2)),
        ("edge_ggcn_fused", ("fused", "x", "hs", "hd"),
         lambda fe, x, hs, hd: lambda s: jax.grad(
             lambda h: (
                 fused_edge_attention_aggregate(fe, h, hs, hd, 0.2) ** 2
             ).sum()
         )(x * s),
         dict(traffic_bytes=3 * E * F * 2)),
        # the two resident-kernel ops are LAST: they cannot lower to
        # Mosaic (ops/pallas_kernels.py) and the remote compile service is
        # known to HANG on lowering errors rather than surface them — if
        # that happens here it must cost the step's tail, not the
        # measurable ops above
        ("pallas_ell_resident_bf16", ("ell_merged", "x"),
         lambda ell, x: lambda s: gather_dst_from_src_pallas(ell, x * s),
         dict(traffic_bytes=E * F * 2)),
        ("pallas_ell_fchunked_602_bf16", ("ell_merged", "xw"),
         lambda ell, xw: lambda s: gather_dst_from_src_pallas(ell, xw * s),
         dict(traffic_bytes=E * F_WIDE * 2)),
    ]

    run = [op for op in OPS if selected(op[0])]
    if not run:
        # a filter matching nothing must fail LOUDLY: a vacuous {} with
        # rc 0 would let the supervisor mark a triage step collected
        print(
            f"FATAL: --ops {args.ops!r} matches none of "
            f"{[op[0] for op in OPS]}",
            file=sys.stderr, flush=True,
        )
        return 2
    for name, needs, fn_factory, kwargs in run:
        timed(name,
              lambda ff=fn_factory, nd=needs: ff(*[need(k) for k in nd]),
              **kwargs)

    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
