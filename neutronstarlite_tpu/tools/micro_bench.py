"""Single-chip kernel micro-benchmarks (docs/PERF.md §1, reproducible).

Round 2's §1 table came from ad-hoc scripts; this tool makes the method
durable and extends it to the round-3 kernels. Shapes follow §1: the
5%-Reddit edge set (E=5.73M) over the half-Reddit vertex table
([116k, f]), bf16 compute. Timing defeats the remote execution path's
identical-dispatch caching by feeding a fresh scalar into every
iteration (naive repeat-timing reports impossible numbers — §1's note);
reported time is the median of ``--iters`` post-compile runs.

Ops: dense matmul / HBM stream (method validation against hardware
peaks), random row gather, XLA ELL aggregate, sorted scatter-add, fused
Pallas ELL (VMEM-resident), fused Pallas ELL at 602 wide (the round-3
feature-column-chunked regime), and the streamed block-sparse kernel
(ops/bsp_ell.py). Failures (e.g. a Mosaic lowering gap) are recorded
per-op, never fatal.

Usage: python -m neutronstarlite_tpu.tools.micro_bench [--iters 10]
Prints ONE JSON line; the recovery plan step ``micro_kernels`` archives
it under docs/perf_runs/round3/.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

V = 116482  # half Reddit (the §1 table shapes)
E = 5730794  # 5% Reddit edges
F = 128
F_WIDE = 602


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument(
        "--scale", type=float, default=1.0,
        help="shrink V/E (CPU smoke tests; 1.0 = the §1 table shapes)",
    )
    args = ap.parse_args(argv)
    global V, E
    V = max(int(V * args.scale), 64)
    E = max(int(E * args.scale), 512)

    from neutronstarlite_tpu.utils.platform import honor_platform_env

    honor_platform_env()
    import jax
    import jax.numpy as jnp

    from neutronstarlite_tpu.graph.storage import build_graph
    from neutronstarlite_tpu.graph.synthetic import synthetic_power_law_graph
    from neutronstarlite_tpu.ops.bsp_ell import BspEllPair, bsp_gather_dst_from_src
    from neutronstarlite_tpu.ops.device_graph import DeviceGraph
    from neutronstarlite_tpu.ops.aggregate import gather_dst_from_src
    from neutronstarlite_tpu.ops.ell import EllPair, ell_gather_dst_from_src
    from neutronstarlite_tpu.ops.pallas_kernels import gather_dst_from_src_pallas

    rng = np.random.default_rng(args.seed)
    out = {"platform": jax.default_backend(), "device": str(jax.devices()[0]),
           "V": V, "E": E, "ops": {}}

    print("building graph + tables (host)...", file=sys.stderr, flush=True)
    src, dst = synthetic_power_law_graph(V, E, seed=args.seed)
    g = build_graph(src, dst, V, weight="gcn_norm")
    dg = DeviceGraph.from_host(g)
    ell = EllPair.from_host(g)
    bsp = BspEllPair.from_host(g, dt=512, vt=8192)

    x = jnp.asarray(rng.standard_normal((V, F)).astype(np.float32), jnp.bfloat16)
    xw = jnp.asarray(
        rng.standard_normal((V, F_WIDE)).astype(np.float32), jnp.bfloat16
    )
    w_mm = jnp.asarray(
        rng.standard_normal((F_WIDE, F)).astype(np.float32), jnp.bfloat16
    )
    idx = jnp.asarray(rng.integers(0, V, size=E), jnp.int32)
    big = jnp.asarray(rng.standard_normal(8 << 20).astype(np.float32))  # 32 MB

    def timed(name, fn, traffic_bytes=None, flops=None):
        """fn(scalar) -> array; records median ms (+ derived rate)."""
        try:
            jfn = jax.jit(fn)
            jax.block_until_ready(jfn(jnp.float32(1.0)))  # compile
            ts = []
            for i in range(args.iters):
                s = jnp.float32(1.0 + 1e-6 * (i + 1))  # fresh dispatch
                t0 = time.perf_counter()
                jax.block_until_ready(jfn(s))
                ts.append(time.perf_counter() - t0)
            med = float(np.median(ts))
            rec = {"ms": round(med * 1e3, 4)}
            if traffic_bytes:
                rec["apparent_gbs"] = round(traffic_bytes / med / 1e9, 1)
            if flops:
                rec["tflops"] = round(flops / med / 1e12, 1)
            out["ops"][name] = rec
            print(f"{name}: {rec}", file=sys.stderr, flush=True)
        except Exception as e:  # noqa: BLE001 — record, keep going
            out["ops"][name] = {"error": f"{type(e).__name__}: {str(e)[:200]}"}
            print(f"{name} FAILED: {out['ops'][name]}", file=sys.stderr, flush=True)

    timed("matmul_bf16_602x128", lambda s: (xw * s) @ w_mm,
          flops=2.0 * V * F_WIDE * F)
    timed("hbm_stream_f32_64MB", lambda s: big * s,
          traffic_bytes=2 * big.size * 4)
    timed("row_gather_bf16", lambda s: (x * s)[idx],
          traffic_bytes=E * F * 2)
    timed("ell_aggregate_xla_bf16",
          lambda s: ell_gather_dst_from_src(ell, x * s),
          traffic_bytes=E * F * 2)
    timed("sorted_scatter_bf16",
          lambda s: gather_dst_from_src(dg, x * s),
          traffic_bytes=E * F * 2)
    timed("pallas_ell_resident_bf16",
          lambda s: gather_dst_from_src_pallas(ell, x * s),
          traffic_bytes=E * F * 2)
    timed("pallas_ell_fchunked_602_bf16",
          lambda s: gather_dst_from_src_pallas(ell, xw * s),
          traffic_bytes=E * F_WIDE * 2)
    timed("bsp_streamed_bf16",
          lambda s: bsp_gather_dst_from_src(bsp, x * s),
          traffic_bytes=E * F * 2)

    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
