"""Mini-batch sampled GCN benchmark at Reddit scale.

The full-batch north star (bench.py) covers one of the reference's two
headline training modes; this tool covers the other — fan-out-sampled
mini-batch training (GCN_CPU_SAMPLE, toolkits/GCN_CPU_SAMPLE.hpp; the
BASELINE.json config list names "GCN_CPU_SAMPLE mini-batch neighbor-sampling
on ogbn-products"). Neither products nor Reddit ships in the reference
checkout (download scripts only, zero egress here), so the graph is the same
Reddit-scale synthetic power-law graph bench.py builds — shared through its
on-disk cache — with GraphSAGE-convention sampling hyperparameters
(batch 512, fanout 25-10) over the reference's Reddit layer widths.

Metrics: median per-batch step time (sample + pad + device step, the
pipeline's steady state) and sampled-edges/sec; epoch time extrapolated to
the full train split. Batches replay ONE compiled program (padded static
shapes) — the number to watch is the steady-state batch rate, which is why
the tool reports it directly instead of only a whole-epoch wall time.

Usage: python -m neutronstarlite_tpu.tools.bench_sample [--scale S]
         [--batch-size 512] [--fanout 25-10] [--batches N]
Prints ONE JSON line: {"metric": "gcn_reddit_sampled_batch_time", ...}
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--batch-size", type=int, default=512)
    ap.add_argument("--fanout", default="25-10")
    ap.add_argument(
        "--batches", type=int, default=60,
        help="timed batches after warmup (one compiled program replays; "
        "steady state needs tens, not an epoch's hundreds)",
    )
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--precision", default="float32",
                    choices=["float32", "bfloat16"])
    ap.add_argument(
        "--deadline", type=float,
        default=float(os.environ.get("NTS_SAMPLE_DEADLINE_S", 1500)),
        help="hard wall bound: dump stacks and exit 3 (fires before an "
        "external supervisor's kill so diagnostics survive)",
    )
    args = ap.parse_args(argv)

    import bench  # graph cache + LAYERS/N_LABELS (one source of the workload)

    bench.start_watchdog(args.deadline)

    from neutronstarlite_tpu.utils.platform import honor_platform_env

    honor_platform_env()

    cache_dir, v_num, e_num, gen_s = bench.build_and_cache_graph(args.scale)
    host_graph, src, dst = bench.load_cached_graph(cache_dir)

    import jax

    from neutronstarlite_tpu.graph.dataset import GNNDatum
    from neutronstarlite_tpu.models.gcn_sample import GCNSampleTrainer, _batch_arrays
    from neutronstarlite_tpu.utils.config import InputInfo

    sizes = [int(s) for s in bench.LAYERS.split("-")]
    datum = GNNDatum.random_generate(v_num, sizes[0], bench.N_LABELS, seed=7)

    cfg = InputInfo()
    cfg.algorithm = "GCNSAMPLESINGLE"
    cfg.vertices = v_num
    cfg.layer_string = bench.LAYERS
    cfg.batch_size = args.batch_size
    cfg.fanout_string = args.fanout
    cfg.epochs = 1
    cfg.learn_rate = 0.01
    cfg.weight_decay = 0.0001
    cfg.decay_epoch = -1
    cfg.drop_rate = 0.5
    cfg.precision = args.precision

    t0 = time.time()
    tr = GCNSampleTrainer.from_arrays(
        cfg, src, dst, datum, host_graph=host_graph
    )
    build_s = time.time() - t0

    sampler = tr.samplers[0]
    n_train = len(sampler.seed_nids)
    batches_per_epoch = -(-n_train // args.batch_size)

    # steady-state batch loop: the trainer's own run() loops a full epoch;
    # here we time a bounded number of batches through the SAME compiled
    # train step (tr._train_batch) to get the rate without an epoch's wall
    key = jax.random.PRNGKey(9)
    gen = sampler.sample_epoch()
    times = []
    sample_times = []
    total = args.warmup + args.batches
    loss = None
    for bi in range(total):
        # the whole pipeline is timed — host sampling included (the trainer
        # overlaps sampling with device compute via async dispatch, so the
        # serial sum here is an UPPER bound on real epoch time; the split
        # is reported so the overlap headroom is visible)
        t0 = time.time()
        try:
            b = next(gen)
        except StopIteration:
            gen = sampler.sample_epoch()
            b = next(gen)
        t_sampled = time.time()
        nodes, hops, seed_mask, seeds = _batch_arrays(b)
        bkey = jax.random.fold_in(key, bi)
        tr.params, tr.opt_state, loss = tr._train_batch(
            tr.params, tr.opt_state, tr.feature, tr.label,
            nodes, hops, seed_mask, seeds, bkey,
        )
        jax.block_until_ready(loss)
        times.append(time.time() - t0)
        sample_times.append(t_sampled - t0)

    batch_s = float(np.median(times[args.warmup:]))
    sample_s = float(np.median(sample_times[args.warmup:]))
    # sampled work per batch: padded slot capacities bound it; real edges
    # vary per batch — report capacity (the shape the device executes)
    hop_caps = [int(h.src_local.shape[0]) for h in b.hops]
    slots_per_batch = int(sum(hop_caps))
    out = {
        "metric": "gcn_reddit_sampled_batch_time",
        "value": round(batch_s, 5),
        "unit": "s",
        "vs_baseline": None,  # reference publishes no sampled numbers
        "extra": {
            "scale": args.scale,
            "v_num": v_num,
            "e_num": e_num,
            "layers": bench.LAYERS,
            "batch_size": args.batch_size,
            "fanout": args.fanout,
            "precision": args.precision,
            "batches_timed": args.batches,
            "sample_s_median": round(sample_s, 5),
            "device_pad_s_median": round(batch_s - sample_s, 5),
            "edge_slots_per_batch": slots_per_batch,
            "edge_slots_per_sec": round(slots_per_batch / batch_s, 0),
            "train_seeds": int(n_train),
            "batches_per_epoch": int(batches_per_epoch),
            "epoch_s_extrapolated": round(batch_s * batches_per_epoch, 3),
            "final_loss": float(loss),
            "build_s": round(build_s, 1),
            "graph_cache_build_s": round(gen_s, 1),
            "device": str(jax.devices()[0]),
        },
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
