"""Run the cross-host telemetry hub (obs/hub) as a process.

Polls each target's ``/telemetry`` endpoint, merges the fleet view via
the exact histogram merge law, writes ONE schema-valid merged stream
under ``NTS_METRICS_DIR`` (rendered natively by tools/metrics_report and
tools/dashboard), optionally appends ``kind=fleet`` perf-ledger rows,
and re-exports the merged view on its own /metrics + /healthz (+
/telemetry — hubs compose: a region hub's endpoint is a valid target
for a global hub).

Usage:
  python -m neutronstarlite_tpu.tools.telemetry_hub
      --targets host1:9100,host2:9100[,...]   (or NTS_HUB_TARGETS)
      [--poll S]        poll interval (NTS_HUB_POLL_S, default 2.0)
      [--miss-k K]      polls missed before target_loss
                        (NTS_HUB_MISS_K, default 3)
      [--polls N]       stop after N cycles (default: forever; the CI
                        smoke uses a bounded run)
      [--port P]        arm the merged-view exporter on port P
                        (0 = ephemeral; omit to not serve)
      [--ledger DIR]    append kind=fleet rows (default NTS_LEDGER_DIR)
      [--ledger-every N] one row per N polls (default 1)

Exit 0 on a completed bounded run or a clean ^C; exit 1 on setup errors
(no targets). A DEAD TARGET IS NOT AN ERROR: it becomes a typed
``target_loss`` record and /healthz reports degraded-but-ok while any
target still answers — the hub outliving its fleet is the point.
"""

from __future__ import annotations

import argparse
import json
import sys

from neutronstarlite_tpu.obs import exporter as exp
from neutronstarlite_tpu.obs import hub as hub_mod
from neutronstarlite_tpu.obs import ledger


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="cross-host telemetry aggregation hub: poll "
        "/telemetry targets, merge the fleet view (exact histogram "
        "merge), re-export + re-stream it"
    )
    ap.add_argument("--targets", default=None,
                    help="comma-separated /telemetry endpoints "
                    "(default NTS_HUB_TARGETS)")
    ap.add_argument("--poll", type=float, default=None,
                    help="poll interval seconds (NTS_HUB_POLL_S)")
    ap.add_argument("--miss-k", type=int, default=None,
                    help="consecutive missed polls before target_loss "
                    "(NTS_HUB_MISS_K)")
    ap.add_argument("--polls", type=int, default=None,
                    help="stop after N poll cycles (default: forever)")
    ap.add_argument("--port", type=int, default=None,
                    help="serve the merged view on this port "
                    "(0 = ephemeral; omit to not serve)")
    ap.add_argument("--ledger", default=None,
                    help="fleet-row ledger directory "
                    "(default NTS_LEDGER_DIR)")
    ap.add_argument("--ledger-every", type=int, default=1)
    ap.add_argument("--json", action="store_true",
                    help="print one JSON line per poll cycle")
    args = ap.parse_args(argv)

    targets = ([t.strip() for t in args.targets.split(",") if t.strip()]
               if args.targets else hub_mod.hub_targets())
    if not targets:
        print("telemetry_hub: no targets (--targets or NTS_HUB_TARGETS)",
              file=sys.stderr)
        return 1

    hub = hub_mod.TelemetryHub(
        targets, poll_s=args.poll, miss_k=args.miss_k,
        ledger_dir=args.ledger or ledger.ledger_dir(),
        ledger_every=args.ledger_every,
    )
    server = None
    if args.port is not None:
        server = exp.MetricsExporter(hub.registry, port=args.port)
        print(f"telemetry_hub: merged view on port {server.port} "
              "(/metrics /healthz /telemetry)", file=sys.stderr)

    def on_poll(cycle):
        if args.json:
            print(json.dumps(cycle), flush=True)
        else:
            print(
                f"telemetry_hub: poll {cycle['poll']}: "
                f"{cycle['targets_ok']}/{cycle['targets']} target(s) ok"
                + (f", {cycle['targets_lost']} LOST"
                   if cycle["targets_lost"] else ""),
                file=sys.stderr, flush=True,
            )

    try:
        hub.run(polls=args.polls, on_poll=on_poll)
    finally:
        hub.close()
        if server is not None:
            server.close()
        if hub.stream_path():
            print(f"telemetry_hub: merged stream -> {hub.stream_path()}",
                  file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
