"""Render the fleet telemetry fabric as a self-contained dashboard.

Input is either a hub's merged stream (``--stream`` file or
NTS_METRICS_DIR directory — any mix of hub + trainer + serve streams
renders) or a LIVE hub endpoint (``--url http://host:port`` — one
/telemetry snapshot is fetched and rendered); output is ONE static HTML
file with zero external assets (inline CSS, inline SVG sparklines — it
opens from a file:// path on an air-gapped rig) or, with ``--watch``,
a terminal ticker.

Panels:

- **fleet topology** — every polled target with its liveness verdict
  (ok / LOST with the miss count and last-ok age) from the hub's
  ``telemetry`` / ``target_loss`` / ``recovery`` records;
- **fleet health + SLO burn** — targets_ok/targets_lost over the run,
  the SLO rollup (worst state, breaching count) per poll;
- **latency quantiles** — the merged histograms' p50/p95/p99 (exact
  under the merge law) with per-poll sparklines from the perf ledger's
  ``kind=fleet`` rows (``--ledger`` / NTS_LEDGER_DIR) when available;
- **straggler heat strip** — per-partition epoch seconds from
  ``heartbeat.seconds`` shaded against the fleet median, with typed
  ``straggler`` records called out (obs/skew);
- **per-request waterfall** — when the input carries distributed-trace
  spans (router + replica streams, NTS_TRACE on), the slowest complete
  request chains render as stacked stage bars (queue/sample/execute/…
  plus the router-overhead remainder), with the complete-chain
  fraction, router-overhead quantiles, and the freshness lineage
  (graph_seq/model_seq) summarized above them
  (tools/trace_timeline.request_tracing_report).

Usage:
  python -m neutronstarlite_tpu.tools.dashboard --stream DIR_OR_FILE
      [--out fleet_dashboard.html] [--ledger DIR]
  python -m neutronstarlite_tpu.tools.dashboard --url http://host:port
      [--out ...]
  python -m neutronstarlite_tpu.tools.dashboard --stream DIR --watch
      [--interval S] [--polls N]

Exit 0 on a rendered dashboard (even an empty one — "no data yet" is a
valid fleet state); exit 1 on unreadable inputs.
"""

from __future__ import annotations

import argparse
import html
import json
import os
import sys
import time
import urllib.request
from typing import Any, Dict, List, Optional

from neutronstarlite_tpu.obs import ledger
from neutronstarlite_tpu.obs.hist import latest_hists
from neutronstarlite_tpu.obs.schema import validate_event
from neutronstarlite_tpu.obs.skew import partition_epoch_seconds

FETCH_TIMEOUT_S = 5.0


# ---- data model -------------------------------------------------------------


def load_stream_events(paths: List[str]) -> List[Dict[str, Any]]:
    from neutronstarlite_tpu.tools.metrics_report import (
        expand_paths, load_events,
    )

    events: List[Dict[str, Any]] = []
    for p in expand_paths(paths):
        events.extend(load_events(p))
    return events


def fetch_url_events(url: str) -> List[Dict[str, Any]]:
    """One /telemetry snapshot from a live hub (or any exporter)."""
    u = url if "://" in url else f"http://{url}"
    if not u.rstrip("/").endswith("/telemetry"):
        u = u.rstrip("/") + "/telemetry"
    with urllib.request.urlopen(u, timeout=FETCH_TIMEOUT_S) as resp:
        body = resp.read().decode("utf-8")
    events: List[Dict[str, Any]] = []
    for raw in body.splitlines():
        raw = raw.strip()
        if not raw:
            continue
        rec = json.loads(raw)
        validate_event(rec)
        events.append(rec)
    return events


def fabric_model(events: List[Dict[str, Any]],
                 fleet_rows: Optional[List[Dict[str, Any]]] = None,
                 ) -> Dict[str, Any]:
    """Everything the panels render, from one pass over the records."""
    telemetry = [e for e in events if e.get("event") == "telemetry"]
    hub_polls = [e for e in telemetry if e.get("source") == "hub"]
    losses = [e for e in events if e.get("event") == "target_loss"]
    rejoins = [e for e in events if e.get("event") == "recovery"
               and e.get("action") == "target_rejoin"]
    stragglers = [e for e in events if e.get("event") == "straggler"]

    # per-target final liveness: lost unless a later rejoin
    targets: Dict[str, Dict[str, Any]] = {}
    for e in losses:
        targets[str(e.get("target"))] = {
            "state": "LOST", "missed_polls": e.get("missed_polls"),
            "last_ok_ts": e.get("last_ok_ts"), "ts": e.get("ts"),
        }
    for e in rejoins:
        t = str(e.get("target"))
        prior = targets.get(t)
        if prior is None or (e.get("ts") or 0) >= (prior.get("ts") or 0):
            targets[t] = {"state": "ok", "rejoined": True,
                          "ts": e.get("ts")}

    hists = latest_hists(events)
    quantiles = {
        name: {"count": h.count, **h.quantiles()}
        for name, h in sorted(hists.items())
    }

    # distributed request tracing (lazy import: trace_timeline pulls in
    # metrics_report at module load)
    from neutronstarlite_tpu.tools.trace_timeline import (
        request_tracing_report,
    )

    tracing = request_tracing_report(events)

    last_poll = hub_polls[-1] if hub_polls else None
    heat = partition_epoch_seconds(events)
    return {
        "tracing": tracing,
        "polls": len(hub_polls),
        "last": last_poll,
        "poll_series": [
            {
                "ts": e.get("ts"),
                "targets": e.get("targets"),
                "targets_ok": e.get("targets_ok"),
                "targets_lost": e.get("targets_lost"),
                "slo": e.get("slo") or {},
            }
            for e in hub_polls
        ],
        "targets": targets,
        "losses": losses,
        "stragglers": stragglers,
        "quantiles": quantiles,
        "heat": heat,
        "fleet_rows": fleet_rows or [],
        "exporters": [e for e in telemetry if e.get("source") != "hub"],
    }


# ---- SVG / HTML helpers -----------------------------------------------------


def sparkline(values: List[Optional[float]], width: int = 180,
              height: int = 36, color: str = "#2a7de1") -> str:
    """Inline SVG polyline over ``values`` (Nones skipped); empty input
    renders an empty frame rather than nothing — a panel with no history
    yet still shows WHERE the history will appear."""
    pts = [(i, v) for i, v in enumerate(values) if v is not None]
    if not pts:
        return (f'<svg class="spark" width="{width}" height="{height}">'
                f'</svg>')
    lo = min(v for _, v in pts)
    hi = max(v for _, v in pts)
    span = (hi - lo) or 1.0
    n = max(len(values) - 1, 1)
    coords = " ".join(
        f"{(i / n) * (width - 4) + 2:.1f},"
        f"{height - 4 - ((v - lo) / span) * (height - 8):.1f}"
        for i, v in pts
    )
    return (
        f'<svg class="spark" width="{width}" height="{height}">'
        f'<polyline fill="none" stroke="{color}" stroke-width="1.5" '
        f'points="{coords}"/></svg>'
    )


def _fmt(v: Any, nd: int = 2) -> str:
    if isinstance(v, bool) or v is None:
        return html.escape(str(v))
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return html.escape(str(v))


_CSS = """
body { font-family: -apple-system, 'Segoe UI', Roboto, sans-serif;
       background: #14171c; color: #d8dee6; margin: 1.5rem; }
h1 { font-size: 1.3rem; } h2 { font-size: 1.0rem; margin-top: 1.6rem;
     border-bottom: 1px solid #2a313b; padding-bottom: .3rem; }
table { border-collapse: collapse; margin-top: .5rem; }
td, th { padding: .25rem .7rem; border: 1px solid #2a313b;
         font-size: .85rem; text-align: left; }
th { background: #1b2027; }
.ok { color: #4caf50; } .lost { color: #ef5350; font-weight: 600; }
.warn { color: #ffb300; } .dim { color: #7b8694; }
.spark { vertical-align: middle; background: #1b2027;
         border: 1px solid #2a313b; }
.heat td.cell { width: 1.4rem; height: 1.1rem; padding: 0;
                border: 1px solid #14171c; }
.badge { display: inline-block; padding: .1rem .5rem; border-radius: 3px;
         font-size: .8rem; margin-right: .4rem; }
.badge.ok { background: #1e3a24; } .badge.bad { background: #3a1e1e; }
.wf { display: inline-block; width: 420px; height: 1.1rem;
      background: #1b2027; border: 1px solid #2a313b;
      font-size: 0; white-space: nowrap; overflow: hidden; }
.wf span { display: inline-block; height: 100%; }
.legend span { display: inline-block; width: .8rem; height: .8rem;
               margin: 0 .25rem 0 .8rem; vertical-align: middle; }
"""

# waterfall stage palette: the serve stages in causal order, then the
# router-overhead remainder
_WF_COLORS = (
    ("queue", "#7b8694"), ("cache_lookup", "#2a7de1"),
    ("sample", "#8e5ad1"), ("h2d_copy", "#1faf9b"),
    ("handoff", "#b0a030"), ("execute", "#4caf50"),
    ("reply", "#caa26a"), ("router overhead", "#ef5350"),
)


def _heat_color(ratio: Optional[float]) -> str:
    """Shade a partition-epoch cell by its time relative to the epoch
    median: 1.0 = neutral, hotter = redder."""
    if ratio is None:
        return "#20262e"
    x = max(min((ratio - 1.0) / 1.0, 1.0), 0.0)  # 1.0..2.0x -> 0..1
    r = int(0x2a + x * (0xef - 0x2a))
    g = int(0x7d - x * (0x7d - 0x35))
    b = int(0x52 - x * (0x52 - 0x30))
    return f"#{r:02x}{g:02x}{b:02x}"


def render_html(model: Dict[str, Any], title: str = "fleet telemetry",
                ) -> str:
    out: List[str] = [
        "<!doctype html><html><head><meta charset='utf-8'>",
        f"<title>{html.escape(title)}</title>",
        f"<style>{_CSS}</style></head><body>",
        f"<h1>{html.escape(title)}</h1>",
    ]
    last = model["last"]
    if last is not None:
        lost = last.get("targets_lost") or 0
        badge = ("<span class='badge bad'>DEGRADED</span>" if lost
                 else "<span class='badge ok'>HEALTHY</span>")
        out.append(
            f"<p>{badge} {_fmt(last.get('targets_ok'))}/"
            f"{_fmt(last.get('targets'))} targets ok over "
            f"{model['polls']} poll(s); SLO: "
            f"{_fmt((last.get('slo') or {}).get('worst'))} "
            f"({_fmt((last.get('slo') or {}).get('breaching'))} "
            f"breaching)</p>"
        )
    else:
        out.append("<p class='dim'>no hub poll records in this input "
                   "(exporter-only snapshot or trainer stream)</p>")

    # fleet topology -------------------------------------------------------
    out.append("<h2>fleet topology</h2>")
    if model["targets"] or model["exporters"]:
        out.append("<table><tr><th>target</th><th>state</th>"
                   "<th>detail</th></tr>")
        for t, info in sorted(model["targets"].items()):
            cls = "lost" if info["state"] == "LOST" else "ok"
            detail = (
                f"missed {info.get('missed_polls')} poll(s)"
                if info["state"] == "LOST"
                else ("rejoined after a loss" if info.get("rejoined")
                      else "")
            )
            out.append(f"<tr><td>{html.escape(t)}</td>"
                       f"<td class='{cls}'>{info['state']}</td>"
                       f"<td class='dim'>{html.escape(detail)}</td></tr>")
        for e in model["exporters"]:
            who = e.get("replica") or e.get("algorithm") or e.get("run_id")
            hp = e.get("health") or {}
            ok = hp.get("ok")
            cls = "ok" if ok else ("dim" if ok is None else "lost")
            out.append(
                f"<tr><td>{html.escape(str(who))}</td>"
                f"<td class='{cls}'>"
                f"{'ok' if ok else 'unknown' if ok is None else 'BAD'}"
                f"</td><td class='dim'>exporter surface, uptime "
                f"{_fmt(e.get('uptime_s'))}s</td></tr>"
            )
        out.append("</table>")
    else:
        out.append("<p class='dim'>no targets seen</p>")

    # fleet health over time ----------------------------------------------
    series = model["poll_series"]
    if series:
        out.append("<h2>fleet health (per poll)</h2><table>")
        ok_vals = [s.get("targets_ok") for s in series]
        lost_vals = [s.get("targets_lost") for s in series]
        breach = [(s.get("slo") or {}).get("breaching") for s in series]
        out.append(f"<tr><th>targets ok</th>"
                   f"<td>{sparkline(ok_vals, color='#4caf50')}</td>"
                   f"<td>{_fmt(ok_vals[-1])}</td></tr>")
        out.append(f"<tr><th>targets lost</th>"
                   f"<td>{sparkline(lost_vals, color='#ef5350')}</td>"
                   f"<td>{_fmt(lost_vals[-1])}</td></tr>")
        out.append(f"<tr><th>SLO breaching</th>"
                   f"<td>{sparkline(breach, color='#ffb300')}</td>"
                   f"<td>{_fmt(breach[-1])}</td></tr>")
        out.append("</table>")

    # latency quantiles ----------------------------------------------------
    out.append("<h2>latency quantiles (exact merge)</h2>")
    if model["quantiles"]:
        rows_by_name: Dict[str, List[Dict[str, Any]]] = {}
        for row in model["fleet_rows"]:
            for name, q in (row.get("hist_quantiles") or {}).items():
                rows_by_name.setdefault(name, []).append(q)
        out.append("<table><tr><th>histogram</th><th>count</th>"
                   "<th>p50</th><th>p95</th><th>p99</th>"
                   "<th>p99 history (ledger)</th></tr>")
        for name, q in model["quantiles"].items():
            hist_q = rows_by_name.get(name, [])
            spark = sparkline([r.get("p99") for r in hist_q])
            out.append(
                f"<tr><td>{html.escape(name)}</td>"
                f"<td>{_fmt(q.get('count'))}</td>"
                f"<td>{_fmt(q.get('p50'))}</td>"
                f"<td>{_fmt(q.get('p95'))}</td>"
                f"<td>{_fmt(q.get('p99'))}</td>"
                f"<td>{spark}</td></tr>"
            )
        out.append("</table>")
        out.append("<p class='dim'>quantiles reconstructed from native "
                   "1.02-growth buckets via the histogram merge law — "
                   "~1% relative error, NOT the /metrics ladder's</p>")
    else:
        out.append("<p class='dim'>no histograms in this input</p>")

    # per-request waterfall (distributed traces) ---------------------------
    tracing = model.get("tracing")
    if tracing is not None:
        out.append("<h2>per-request waterfall (distributed traces)</h2>")
        ov = {
            q: tracing.get(f"router_overhead_{q}_ms")
            for q in ("p50", "p95", "p99")
        }
        gs = tracing.get("graph_seqs") or []
        ms_ = tracing.get("model_seqs") or []
        out.append(
            f"<p>{_fmt(tracing['n_complete'])}/{_fmt(tracing['n_ok'])} "
            f"complete chains "
            f"(frac {_fmt(tracing['complete_frac'], 3)}); "
            f"router overhead ms p50/p95/p99 = "
            f"{_fmt(ov['p50'])}/{_fmt(ov['p95'])}/{_fmt(ov['p99'])}; "
            f"lineage graph_seq {_fmt(gs[0]) + '..' + _fmt(gs[-1]) if gs else 'n/a'}, "
            f"model_seq {html.escape(','.join(str(m) for m in ms_)) if ms_ else 'n/a'}"
            f"</p>"
        )
        complete = [c for c in tracing["chains"] if c["complete"]]
        complete.sort(key=lambda c: c["total_ms"], reverse=True)
        if complete:
            out.append("<p class='legend dim'>" + "".join(
                f"<span style='background:{col}'></span>{html.escape(nm)}"
                for nm, col in _WF_COLORS
            ) + "</p>")
            out.append("<table><tr><th>req</th><th>total</th>"
                       "<th>stages</th><th>fabric</th></tr>")
            for c in complete[:12]:
                total = c["total_ms"] or 1e-9
                segs = []
                for nm, col in _WF_COLORS[:-1]:
                    d = (c.get("stages_ms") or {}).get(nm)
                    if not d:
                        continue
                    segs.append(
                        f"<span style='width:{d / total * 100:.2f}%;"
                        f"background:{col}' "
                        f"title='{html.escape(nm)}: {d:.3f}ms'></span>"
                    )
                overhead = c.get("router_overhead_ms")
                if overhead and overhead > 0:
                    segs.append(
                        f"<span style='width:"
                        f"{overhead / total * 100:.2f}%;"
                        f"background:{_WF_COLORS[-1][1]}' "
                        f"title='router overhead: {overhead:.3f}ms'>"
                        f"</span>"
                    )
                fabric = ", ".join(
                    f"{c[k]} {lbl}" for k, lbl in (
                        ("n_retries", "retry"), ("n_reroutes", "re-route"),
                        ("n_suspects", "suspect"), ("n_sheds", "shed"),
                    ) if c.get(k)
                ) or "—"
                out.append(
                    f"<tr><td>{html.escape(str(c.get('req_id')))}</td>"
                    f"<td>{_fmt(c['total_ms'])}ms</td>"
                    f"<td><div class='wf'>{''.join(segs)}</div></td>"
                    f"<td class='dim'>{html.escape(fabric)}</td></tr>"
                )
            out.append("</table>")
        incomplete = [
            c for c in tracing["chains"]
            if not c["complete"] and c["status"] == "ok"
        ]
        if incomplete:
            out.append(
                f"<p class='warn'>{len(incomplete)} answered request(s) "
                f"with an incomplete trace chain (replica leg missing — "
                f"torn stream or NTS_TRACE off on a replica)</p>"
            )

    # straggler heat strip -------------------------------------------------
    out.append("<h2>straggler heat strip</h2>")
    heat = model["heat"]
    if heat:
        epochs = sorted({ep for per in heat.values() for ep in per})
        out.append("<table class='heat'><tr><th>partition</th>")
        out.extend(f"<th class='dim'>{ep}</th>" for ep in epochs)
        out.append("</tr>")
        import statistics as _st

        med_by_epoch = {
            ep: _st.median([per[ep] for per in heat.values() if ep in per])
            for ep in epochs
        }
        flagged = {e.get("partition") for e in model["stragglers"]}
        for p in sorted(heat):
            mark = " ⚠" if p in flagged else ""
            out.append(f"<tr><th>p{p}{mark}</th>")
            for ep in epochs:
                s = heat[p].get(ep)
                med = med_by_epoch.get(ep) or 0.0
                ratio = (s / med) if (s and med > 0) else None
                out.append(
                    f"<td class='cell' title='p{p} e{ep}: {_fmt(s, 3)}s' "
                    f"style='background:{_heat_color(ratio)}'></td>"
                )
            out.append("</tr>")
        out.append("</table>")
        for e in model["stragglers"]:
            out.append(
                f"<p class='warn'>straggler: partition "
                f"{_fmt(e.get('partition'))} at epoch "
                f"{_fmt(e.get('epoch'))} — {_fmt(e.get('seconds'), 3)}s "
                f"vs median {_fmt(e.get('median_s'), 3)}s "
                f"({_fmt((e.get('excess') or 0) * 100, 0)}% over, "
                f"{_fmt(e.get('consecutive'))} consecutive) — "
                f"slow-but-alive, advisory</p>"
            )
    else:
        out.append("<p class='dim'>no per-partition timings "
                   "(heartbeat.seconds) in this input</p>")

    out.append(f"<p class='dim'>generated "
               f"{time.strftime('%Y-%m-%d %H:%M:%S')}</p>")
    out.append("</body></html>")
    return "".join(out)


# ---- terminal watch mode ----------------------------------------------------


def watch_line(model: Dict[str, Any]) -> str:
    last = model["last"]
    if last is None:
        return "dashboard: no hub polls yet"
    q = model["quantiles"]
    lat = next(
        (f"{name} p99={v.get('p99'):.1f}" for name, v in q.items()
         if v.get("p99") is not None), "no hists",
    )
    lost = last.get("targets_lost") or 0
    return (
        f"dashboard: poll {model['polls']}: "
        f"{last.get('targets_ok')}/{last.get('targets')} ok"
        + (f" ({lost} LOST)" if lost else "")
        + f" | slo={_fmt((last.get('slo') or {}).get('worst'))}"
        + f" | {lat}"
        + (f" | stragglers={len(model['stragglers'])}"
           if model["stragglers"] else "")
    )


# ---- CLI --------------------------------------------------------------------


def _load(args) -> Dict[str, Any]:
    if args.url:
        events = fetch_url_events(args.url)
    else:
        events = load_stream_events(args.stream)
    fleet_rows = []
    ldir = args.ledger or ledger.ledger_dir()
    if ldir:
        fleet_rows = [r for r in ledger.read_rows(directory=ldir)
                      if r.get("kind") == "fleet"]
    return fabric_model(events, fleet_rows)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render the fleet telemetry fabric (hub stream or "
        "live hub URL) as one self-contained HTML dashboard"
    )
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--stream", nargs="+",
                     help="merged-stream file(s) or a metrics directory")
    src.add_argument("--url",
                     help="live hub (or exporter) base URL — one "
                     "/telemetry snapshot is fetched")
    ap.add_argument("--out", default="fleet_dashboard.html")
    ap.add_argument("--ledger", default=None,
                    help="perf-ledger dir for quantile history "
                    "sparklines (default NTS_LEDGER_DIR)")
    ap.add_argument("--title", default="fleet telemetry")
    ap.add_argument("--watch", action="store_true",
                    help="terminal ticker instead of HTML")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--polls", type=int, default=None,
                    help="watch mode: stop after N refreshes "
                    "(default: forever)")
    args = ap.parse_args(argv)

    try:
        model = _load(args)
    except Exception as e:
        print(f"dashboard: cannot load input: {e}", file=sys.stderr)
        return 1

    if args.watch:
        n = 0
        try:
            while True:
                print(watch_line(model), flush=True)
                n += 1
                if args.polls is not None and n >= args.polls:
                    break
                time.sleep(args.interval)
                model = _load(args)
        except KeyboardInterrupt:
            pass
        return 0

    doc = render_html(model, title=args.title)
    try:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(doc)
    except OSError as e:
        print(f"dashboard: cannot write {args.out}: {e}", file=sys.stderr)
        return 1
    print(f"dashboard: wrote {args.out} ({len(doc)} bytes; "
          f"{model['polls']} hub poll(s), "
          f"{len(model['quantiles'])} histogram(s), "
          f"{len(model['stragglers'])} straggler record(s))",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
