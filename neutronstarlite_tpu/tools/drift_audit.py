"""Prediction-drift auditor: the analytic models get audited, not trusted.

The system leans on analytic predictions in several load-bearing places:
the live wire counters are PRICED by ``wire_accounting``'s formulas (the
``wire.bytes_per_epoch_fwd`` gauge), and the tuner's prior
(``predict_all``/``predict_mesh`` byte scores) prunes the candidate
space and decides outright on a cached-mode miss. Nothing ever checked
those predictions against what actually ran — a mispriced model would
keep winning tuner decisions forever.

This module closes the loop. After a run (the in-process
``audit_registry`` hook in ``ToolkitBase.finalize_metrics``) or offline
over any obs stream (the CLI), it compares:

- **wire_accounting**: the predicted per-epoch forward wire bytes
  (gauge) vs the live per-epoch counter. They are priced by one shared
  formula today, so drift here means a code path desynchronized them —
  exactly the regression the shared-formula design exists to prevent;
- **tune_prior**: within each tuning episode's MEASURED trials, whether
  the prior's ranking held — the prior's byte-argmin candidate vs the
  measured-seconds argmin. Drift = how much slower the prior's pick
  actually ran than the measured best. This is the one that matters on
  a cached-mode miss, where the prior decides alone;
- **wire_quant** (the numerics leg, obs/numerics): the MEASURED relative
  RMS error of the narrowed (bf16) ring payload — the
  ``wire.quant_rel_err`` gauge / ``tensor_stats`` records — against
  ``NTS_QUANT_TOL``. A ``WIRE_DTYPE:bf16`` tuner decision whose measured
  error exceeds the tolerance gets its tune-cache entry flagged for
  re-trial exactly like a mispriced prior: the decision traded accuracy
  for bytes on a payload where the trade measurably does not hold;
- **staleness** (the streaming leg, stream/): how far the served model's
  last fine-tuned sequence point (``finetune_round.seq_hi`` /
  ``stream.model_seq``) lags the graph head the fleet is actually
  serving (``delta_commit.seq`` / ``stream.head_seq``). The implicit
  "prediction" here is the freshness contract — the model was trained
  on the graph it serves — and a lag beyond ``NTS_STALENESS_TOL``
  sequence points is that contract measurably broken: the fine-tune
  worker is not keeping up with the delta rate.

Drift beyond ``--threshold`` (``NTS_DRIFT_TOL``, default 0.1) emits one
typed ``model_drift`` record per disagreement (rendered by
metrics_report as a "prediction drift:" block), and — when the drift
implicates a tuner decision — FLAGS the matching tune-cache entries for
re-trial (``tune/cache.flag_for_retrial``): the next ``NTS_TUNE=measure``
run treats a flagged entry as a loud miss and re-runs real trials
instead of replaying a decision whose cost model was wrong.

Usage:
  python -m neutronstarlite_tpu.tools.drift_audit <metrics-dir-or-file>
      [--threshold 0.1] [--tune-dir DIR] [--no-flag] [--emit] [--json]

Exit 0 = no drift, 3 = drift found (distinct from --diff's 2: drift is
a model-quality signal, not a per-run perf regression), 1 = no usable
input.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from neutronstarlite_tpu.obs.ledger import as_number as _num  # noqa: E402
from neutronstarlite_tpu.utils.logging import get_logger  # noqa: E402

log = get_logger("tools")

DEFAULT_THRESHOLD = 0.1


def drift_threshold() -> float:
    """``NTS_DRIFT_TOL``: the relative disagreement above which a typed
    ``model_drift`` record is emitted (default 0.1 = 10%)."""
    raw = os.environ.get("NTS_DRIFT_TOL", "")
    if not raw:
        return DEFAULT_THRESHOLD
    try:
        return float(raw)
    except ValueError:
        log.warning("bad NTS_DRIFT_TOL=%r; using %g", raw,
                    DEFAULT_THRESHOLD)
        return DEFAULT_THRESHOLD


def wire_drift(counters: Dict[str, Any], gauges: Dict[str, Any],
               epochs: int, threshold: float) -> List[Dict[str, Any]]:
    """Predicted (gauge) vs observed (live counter / epochs) wire bytes.
    Empty when the run carries no wire telemetry or the two agree within
    the threshold."""
    pred = _num(gauges.get("wire.bytes_per_epoch_fwd"))
    total = _num(counters.get("wire.bytes_fwd"))
    if pred is None or total is None or not epochs:
        return []
    obs_v = total / epochs
    if pred > 0:
        drift = obs_v / pred - 1.0
    else:
        drift = 1.0 if obs_v > 0 else 0.0
    if abs(drift) <= threshold:
        return []
    return [{
        "metric": "wire_bytes_fwd_per_epoch",
        "source": "wire_accounting",
        "predicted": pred,
        "observed": obs_v,
        "drift": drift,
        "threshold": threshold,
    }]


def tune_prior_drift(events: List[Dict[str, Any]],
                     threshold: float) -> List[Dict[str, Any]]:
    """Per tuning episode — a (run_id, family, partitions) group of
    ``tune_trial`` records: did the prior's byte ranking pick the
    measured winner? Drift = measured seconds of the prior's pick /
    measured best - 1. run_id is part of the group key because the CLI
    merges every stream in a dir: without it, two runs' trials of the
    SAME candidate would land in one ranking and the rig's ~20%
    run-to-run swing would read as prior drift."""
    groups: Dict[tuple, List[Dict[str, Any]]] = {}
    for e in events:
        if e.get("event") != "tune_trial":
            continue
        groups.setdefault(
            (e.get("run_id"), e.get("family"), e.get("partitions")), []
        ).append(e)
    out: List[Dict[str, Any]] = []
    for (run_id, family, partitions), trials in sorted(
        groups.items(), key=lambda kv: tuple(str(x) for x in kv[0])
    ):
        measured = [
            t for t in trials
            if _num(t.get("seconds")) is not None
            and _num(t.get("predicted_bytes")) is not None
        ]
        if len(measured) < 2:
            continue  # a ranking needs two measured points
        prior_pick = min(measured, key=lambda t: t["predicted_bytes"])
        best = min(measured, key=lambda t: t["seconds"])
        best_s, pick_s = float(best["seconds"]), float(prior_pick["seconds"])
        drift = (pick_s / best_s - 1.0) if best_s > 0 else 0.0
        if drift <= threshold:
            continue
        out.append({
            "metric": "tune_prior_ranking",
            "source": "tune_prior",
            "predicted": best_s,
            "observed": pick_s,
            "drift": drift,
            "threshold": threshold,
            "family": family,
            "partitions": partitions,
            "candidate": prior_pick.get("candidate"),
            "measured_best": best.get("candidate"),
            # the episode's full cache-key facts ride along when the
            # trial records carry them (select._decide stamps them), so
            # flagging can hit exactly the implicated entry instead of
            # every (family, P) entry across graphs and rigs
            "graph_digest": prior_pick.get("graph_digest"),
            "backend": prior_pick.get("backend"),
            "layers": prior_pick.get("layers"),
            "episode_run_id": run_id,
        })
    return out


_NARROW_WIRE = ("bf16", "bfloat16")


def _run_quant_errors(events: List[Dict[str, Any]]) -> Dict[str, float]:
    """{run_id: max measured wire quant error} — from ``tensor_stats``
    records carrying ``quant_rel_err`` (the NTS_QUANT_PROBE / NTS_NUMERICS
    wire groups), with the run_summary ``wire.quant_rel_err`` gauge as
    the records-rotated-away fallback."""
    out: Dict[str, float] = {}
    for e in events:
        err = None
        if e.get("event") == "tensor_stats":
            err = _num(e.get("quant_rel_err"))
        elif e.get("event") == "run_summary":
            err = _num((e.get("gauges") or {}).get("wire.quant_rel_err"))
        if err is None:
            continue
        rid = e.get("run_id")
        out[rid] = max(out.get(rid, 0.0), err)
    return out


def wire_quant_drift(events: List[Dict[str, Any]],
                     quant_threshold: Optional[float] = None
                     ) -> List[Dict[str, Any]]:
    """The numerics leg: per run, the MEASURED relative RMS error of the
    narrowed ring payload (obs/numerics — wire.quant_rel_err) against
    ``NTS_QUANT_TOL``. A breach emits one drift entry; when the stream
    shows the bf16 wire came from a TUNER decision, the entry carries
    the decision's full cache-key facts so ``flag_tune_cache`` marks
    exactly the implicated entry for re-trial — the same loud-miss
    contract mispriced priors get. An explicitly-pinned WIRE_DTYPE:bf16
    run still gets the record (the user deserves the audit), just with
    nothing to flag."""
    if quant_threshold is None:
        from neutronstarlite_tpu.obs.numerics import quant_tol

        quant_threshold = quant_tol()
    errors = _run_quant_errors(events)
    if not errors:
        return []
    decisions: Dict[str, Dict[str, Any]] = {}
    for e in events:
        if e.get("event") != "tune_decision":
            continue
        wd = str((e.get("decision") or {}).get("wire_dtype", "")).lower()
        if wd in _NARROW_WIRE or "bf16" in (e.get("candidate") or ""):
            decisions[e.get("run_id")] = e
    out: List[Dict[str, Any]] = []
    for rid, err in sorted(errors.items(), key=lambda kv: str(kv[0])):
        if err <= quant_threshold:
            continue
        entry: Dict[str, Any] = {
            "metric": "wire_quant_rel_err",
            "source": "wire_quant",
            "predicted": quant_threshold,
            "observed": err,
            # NTS_QUANT_TOL=0 is the legitimate "flag ANY measured
            # error" setting — the drift is then the raw error, not a
            # ratio against zero
            "drift": (err / quant_threshold - 1.0) if quant_threshold > 0
            else err,
            "threshold": quant_threshold,
            "episode_run_id": rid,
        }
        d = decisions.get(rid)
        if d is not None:
            entry.update({
                "family": d.get("family"),
                "partitions": d.get("partitions"),
                "candidate": d.get("candidate"),
                "graph_digest": d.get("graph_digest"),
                "backend": d.get("backend"),
                "layers": d.get("layers"),
            })
        out.append(entry)
    return out


def staleness_drift(events: List[Dict[str, Any]],
                    tol: Optional[int] = None) -> List[Dict[str, Any]]:
    """The streaming-freshness leg: per run, the graph-head sequence the
    fleet serves (max ``delta_commit.seq``, with the run_summary
    ``stream.head_seq`` gauge as the records-rotated-away fallback) vs
    the last sequence point the published model was fine-tuned through
    (max ``finetune_round.seq_hi`` / ``stream.model_seq``). A lag beyond
    ``NTS_STALENESS_TOL`` emits one ``source="staleness"`` entry — drift
    and threshold are expressed as fractions of the head (the report's
    rendering contract); the raw ``lag``/``tol`` counts ride along."""
    if tol is None:
        from neutronstarlite_tpu.stream.finetune import (
            staleness_tol_from_env,
        )

        tol = staleness_tol_from_env()
    heads: Dict[str, int] = {}
    models: Dict[str, int] = {}
    for e in events:
        rid = e.get("run_id")
        kind = e.get("event")
        if kind == "delta_commit":
            s = _num(e.get("seq"))
            if s is not None:
                heads[rid] = max(heads.get(rid, 0), int(s))
        elif kind == "finetune_round":
            s = _num(e.get("seq_hi"))
            if s is not None:
                models[rid] = max(models.get(rid, 0), int(s))
        elif kind == "run_summary":
            g = e.get("gauges") or {}
            h, m = _num(g.get("stream.head_seq")), _num(
                g.get("stream.model_seq"))
            if h is not None:
                heads[rid] = max(heads.get(rid, 0), int(h))
            if m is not None:
                models[rid] = max(models.get(rid, 0), int(m))
    out: List[Dict[str, Any]] = []
    for rid, head in sorted(heads.items(), key=lambda kv: str(kv[0])):
        model = models.get(rid, 0)
        lag = head - model
        if lag <= tol or head <= 0:
            continue
        out.append({
            "metric": "model_staleness_seq",
            "source": "staleness",
            "predicted": float(head),
            "observed": float(model),
            "drift": float(model) / head - 1.0,
            "threshold": float(tol) / head,
            "head_seq": head,
            "model_seq": model,
            "lag": lag,
            "tol": int(tol),
            "episode_run_id": rid,
        })
    return out


def audit_events(events: List[Dict[str, Any]],
                 threshold: Optional[float] = None,
                 quant_threshold: Optional[float] = None
                 ) -> List[Dict[str, Any]]:
    """Every drift entry one stream's records support (run_summary wire
    pairs + tune episodes + measured wire quant errors). Pure: no
    records emitted, nothing flagged."""
    threshold = threshold if threshold is not None else drift_threshold()
    out: List[Dict[str, Any]] = []
    for e in events:
        if e.get("event") == "run_summary":
            out.extend(wire_drift(
                e.get("counters") or {}, e.get("gauges") or {},
                int(e.get("epochs") or 0), threshold,
            ))
    out.extend(tune_prior_drift(events, threshold))
    out.extend(wire_quant_drift(events, quant_threshold))
    out.extend(staleness_drift(events))
    return out


def flag_tune_cache(drifts: List[Dict[str, Any]],
                    tune_directory: Optional[str] = None) -> List[str]:
    """Flag the tune-cache entries a tuner-prior drift implicates for
    re-trial; returns the flagged entry paths. Matching uses EVERY
    cache-key fact the drift carries (family, partitions, graph digest,
    backend, layers — None facts match anything, tolerating streams
    whose trials predate the key stamping), so one graph's drift on one
    rig never wipes another rig's measured decisions. Each drift dict
    gains ``flagged_entries`` (all basenames) and ``flagged_entry``
    (the first — the compact report cross-link)."""
    from neutronstarlite_tpu.tune import cache

    directory = tune_directory or cache.tune_dir()
    if not directory:
        return []
    flagged: List[str] = []
    for d in drifts:
        if d.get("source") not in ("tune_prior", "wire_quant"):
            continue
        if d.get("source") == "wire_quant" and d.get("family") is None:
            # a pinned-cfg bf16 run: measured error, but no tuner
            # decision to flag (and a fact-free find_entries would
            # match EVERY entry in the cache)
            continue
        for path in cache.find_entries(
            directory, family=d.get("family"),
            partitions=d.get("partitions"),
            graph_digest=d.get("graph_digest"),
            backend=d.get("backend"),
            layers=d.get("layers"),
        ):
            if d.get("source") == "wire_quant":
                reason = (
                    f"measured wire quant error {d['observed']:.3g} > "
                    f"NTS_QUANT_TOL {d['threshold']:g} "
                    f"(decision {d.get('candidate')})"
                )
            else:
                reason = (
                    f"prior ranking drift {d['drift'] * 100:+.1f}% "
                    f"(prior pick {d.get('candidate')} vs measured best "
                    f"{d.get('measured_best')})"
                )
            if cache.flag_for_retrial(path, reason):
                flagged.append(path)
                names = d.setdefault("flagged_entries", [])
                names.append(os.path.basename(path))
                d["flagged_entry"] = names[0]
    return flagged


def audit_registry(metrics, epochs: int,
                   threshold: Optional[float] = None) -> List[Dict[str, Any]]:
    """The in-process post-run hook (ToolkitBase.finalize_metrics): audit
    the live registry's wire pair and emit ``model_drift`` records for
    any breach. ``NTS_DRIFT_AUDIT=0`` disables. Never raises."""
    if metrics is None or os.environ.get("NTS_DRIFT_AUDIT", "1") == "0":
        return []
    try:
        threshold = threshold if threshold is not None else drift_threshold()
        snap = metrics.snapshot(include_hists=False)
        drifts = wire_drift(
            snap["counters"], snap["gauges"], epochs, threshold
        )
        # the numerics leg, in-process: a measured wire quant error over
        # NTS_QUANT_TOL leaves its model_drift record in the stream
        # (flagging stays with the offline CLI, which has the tune facts)
        from neutronstarlite_tpu.obs.numerics import quant_tol

        qtol = quant_tol()
        qerr = _num(snap["gauges"].get("wire.quant_rel_err"))
        if qerr is not None and qerr > qtol:
            drifts.append({
                "metric": "wire_quant_rel_err",
                "source": "wire_quant",
                "predicted": qtol,
                "observed": qerr,
                "drift": (qerr / qtol - 1.0) if qtol > 0 else qerr,
                "threshold": qtol,
            })
        for d in drifts:
            metrics.event("model_drift", **d)
        return drifts
    except Exception as e:  # telemetry must never fail a run
        log.warning("drift audit failed: %s", e)
        return []


# ---- CLI --------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="audit analytic predictions (wire pricing, tuner "
        "priors) against measured telemetry; exit 3 on drift beyond "
        "--threshold"
    )
    ap.add_argument("paths", nargs="+",
                    help="obs JSONL file(s) or NTS_METRICS_DIR-style dirs")
    ap.add_argument("--threshold", type=float, default=None,
                    help="relative drift threshold (default NTS_DRIFT_TOL "
                    "or 0.1)")
    ap.add_argument("--tune-dir", default=None,
                    help="tune cache to flag on tuner-prior drift "
                    "(default NTS_TUNE_DIR)")
    ap.add_argument("--no-flag", action="store_true",
                    help="report only; never touch the tune cache")
    ap.add_argument("--emit", action="store_true",
                    help="write the model_drift records as a new "
                    "drift-audit stream next to the audited one (dirs "
                    "only), so metrics_report renders them")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    from neutronstarlite_tpu.tools.metrics_report import (
        expand_paths, load_events,
    )

    paths = expand_paths(args.paths)
    if not paths:
        print("no .jsonl inputs found", file=sys.stderr)
        return 1
    events: List[Dict[str, Any]] = []
    for p in paths:
        try:
            events.extend(load_events(p))
        except OSError as e:
            print(f"{p}: {e}", file=sys.stderr)
    if not events:
        print("no parseable records in the inputs", file=sys.stderr)
        return 1

    threshold = (
        args.threshold if args.threshold is not None else drift_threshold()
    )
    drifts = audit_events(events, threshold)
    flagged: List[str] = []
    if drifts and not args.no_flag:
        flagged = flag_tune_cache(drifts, args.tune_dir)

    if drifts and args.emit:
        emit_dir = next((p for p in args.paths if os.path.isdir(p)), None)
        if emit_dir is None:
            print("--emit needs a directory input; skipping emission",
                  file=sys.stderr)
        else:
            from neutronstarlite_tpu.obs import registry as obs_registry
            import time as _time

            reg = obs_registry.MetricsRegistry(
                f"driftaudit-{os.getpid()}", algorithm="DRIFTAUDIT",
                path=os.path.join(
                    emit_dir,
                    f"{_time.strftime('%Y%m%d-%H%M%S')}-driftaudit-"
                    f"{os.getpid()}.jsonl",
                ),
            )
            for d in drifts:
                reg.event("model_drift", **d)
            reg.close()

    if args.json:
        print(json.dumps({
            "threshold": threshold,
            "drift": drifts,
            "flagged": [os.path.basename(p) for p in flagged],
        }))
    else:
        if not drifts:
            print(f"drift audit: no prediction drifted beyond "
                  f"{threshold:.0%}")
        for d in drifts:
            if d.get("source") == "staleness":
                print(
                    f"model drift: {d['metric']} model at seq "
                    f"{d['observed']:g} vs graph head {d['predicted']:g} "
                    f"(lag {d['lag']} > NTS_STALENESS_TOL {d['tol']}, "
                    f"source=staleness)"
                )
                continue
            extra = ""
            if d.get("candidate"):
                extra = (
                    f" prior_pick={d['candidate']} "
                    f"measured_best={d.get('measured_best')}"
                )
            if d.get("flagged_entry"):
                extra += f" flagged={d['flagged_entry']}"
            print(
                f"model drift: {d['metric']} predicted={d['predicted']:g} "
                f"observed={d['observed']:g} "
                f"({d['drift'] * 100:+.1f}% > {threshold:.0%}, "
                f"source={d['source']}){extra}"
            )
    return 3 if drifts else 0


if __name__ == "__main__":
    raise SystemExit(main())
