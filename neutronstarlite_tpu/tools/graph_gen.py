"""Deterministic synthetic graph + delta-trace generator (stream rigs).

Every streaming test and gate in this repo needs the same two inputs: a
graph that LOOKS like real graph data (skewed degrees, not a uniform
Erdős–Rényi blob — the sampled trainer and the device neighbor table
behave differently under skew) and a reproducible stream of edits to
feed the delta log. This module generates both from a seed alone, so a
trace referenced in a test or a gate is a (generator-version, seed,
knobs) tuple, never a checked-in binary.

Two graph families:

- ``rmat``: the classic recursive-matrix generator (Chakrabarti et al.)
  — each edge picks a quadrant per bit level with probabilities
  (a, b, c, d), yielding the power-law in/out skew real web/social
  graphs show. Self-loops and duplicate pairs are kept (build_graph
  handles multigraphs; removal semantics drop every occurrence).
- ``powerlaw``: preferential-attachment flavored — destination picked
  ~ (current in-degree + 1), source uniform. Cheaper to reason about in
  closed form; the heavy-tail knob is ``gamma``.

The delta trace is generated in COMMIT ROUNDS: each round stages one
delta per writer (writer ids sorted — matching the log's canonical
(writer_id, writer_seq) merge order exactly, so generated removals are
always valid at their application point) and then commits. Edits track
a running pair-count table so a removal always names a live edge, and
every ``vertex_every``-th round appends a vertex (with deterministic
feature row and attachment edges) — the margin/overflow paths get
exercised, not just edge churn.

Usage (library): :func:`synth_edges`, :func:`synth_data`,
:func:`delta_trace`, :func:`write_trace_log`.

Usage (CLI)::

  python -m neutronstarlite_tpu.tools.graph_gen OUT_DIR \
      [--kind rmat|powerlaw] [--vertices 512] [--edges 2048] \
      [--feat-dim 16] [--classes 4] [--seed 0] \
      [--rounds 6] [--writers 2] [--adds 4] [--removes 1] \
      [--vertex-every 3] [--json]

writes ``OUT_DIR/base.npz`` (src, dst, feature, label, mask) plus a
populated delta log at ``OUT_DIR/log/`` and prints the head digest —
two invocations with the same knobs produce byte-identical logs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from neutronstarlite_tpu.utils.logging import get_logger  # noqa: E402

log = get_logger("tools")

GENERATOR_VERSION = 1  # bump on any distribution-visible change


def rmat_edges(v_num: int, e_num: int, *, a: float = 0.57, b: float = 0.19,
               c: float = 0.19, seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """R-MAT edge list over the smallest power-of-two grid covering
    ``v_num`` (out-of-range picks are redrawn by modulo — cheap and
    deterministic). Returns (src, dst) uint32 arrays of length e_num."""
    if v_num <= 0 or e_num <= 0:
        raise ValueError("rmat_edges needs v_num > 0 and e_num > 0")
    d = 1.0 - a - b - c
    if d < 0:
        raise ValueError("rmat quadrant probabilities exceed 1")
    scale = max(int(np.ceil(np.log2(max(v_num, 2)))), 1)
    rng = np.random.default_rng(seed)
    src = np.zeros(e_num, dtype=np.int64)
    dst = np.zeros(e_num, dtype=np.int64)
    for _level in range(scale):
        r = rng.random(e_num)
        # quadrant: 0 = (0,0) w.p. a, 1 = (0,1) w.p. b, 2 = (1,0) w.p.
        # c, 3 = (1,1) w.p. d — one random draw, three thresholds
        right = (r >= a) & (r < a + b) | (r >= a + b + c)
        down = r >= a + b
        src = (src << 1) | down.astype(np.int64)
        dst = (dst << 1) | right.astype(np.int64)
    return (src % v_num).astype(np.uint32), (dst % v_num).astype(np.uint32)


def powerlaw_edges(v_num: int, e_num: int, *, gamma: float = 0.8,
                   seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Preferential-attachment flavored edge list: dst sampled
    proportional to ``(in_degree + 1) ** gamma`` (built incrementally in
    chunks so the tail actually forms), src uniform."""
    if v_num <= 0 or e_num <= 0:
        raise ValueError("powerlaw_edges needs v_num > 0 and e_num > 0")
    rng = np.random.default_rng(seed)
    indeg = np.zeros(v_num, dtype=np.float64)
    srcs: List[np.ndarray] = []
    dsts: List[np.ndarray] = []
    done = 0
    while done < e_num:
        n = min(max(v_num // 4, 64), e_num - done)
        w = (indeg + 1.0) ** float(gamma)
        p = w / w.sum()
        dst = rng.choice(v_num, size=n, p=p)
        src = rng.integers(0, v_num, size=n)
        np.add.at(indeg, dst, 1.0)
        srcs.append(src)
        dsts.append(dst)
        done += n
    return (np.concatenate(srcs).astype(np.uint32),
            np.concatenate(dsts).astype(np.uint32))


def synth_edges(kind: str, v_num: int, e_num: int,
                seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    if kind == "rmat":
        return rmat_edges(v_num, e_num, seed=seed)
    if kind == "powerlaw":
        return powerlaw_edges(v_num, e_num, seed=seed)
    raise ValueError(f"unknown graph kind {kind!r} (rmat | powerlaw)")


def synth_data(kind: str, v_num: int, e_num: int, feat_dim: int,
               classes: int, seed: int = 0):
    """(src, dst, GNNDatum) — labels are planted from a random linear
    readout of each vertex's SYMMETRIC 1-hop neighborhood mean (self +
    in + out neighbors), i.e. inside a GCN's receptive field — so the
    model can actually LEARN the labels through aggregation and the
    fine-tune accuracy oracle has signal (a readout of raw per-vertex
    features is near-unlearnable once neighbors are averaged in)."""
    from neutronstarlite_tpu.graph.dataset import GNNDatum

    src, dst = synth_edges(kind, v_num, e_num, seed=seed)
    rng = np.random.default_rng(seed + 1)
    feature = rng.standard_normal((v_num, feat_dim)).astype(np.float32) * 0.5
    readout = rng.standard_normal((feat_dim, classes)).astype(np.float32)
    hood = feature.astype(np.float64).copy()
    deg = np.ones(v_num)
    np.add.at(hood, dst, feature[src])
    np.add.at(deg, dst, 1)
    np.add.at(hood, src, feature[dst])
    np.add.at(deg, src, 1)
    hood /= deg[:, None]
    label = np.argmax(hood @ readout, axis=1).astype(np.int32)
    mask = (np.arange(v_num) % 3).astype(np.int32)
    return src, dst, GNNDatum(feature=feature, label=label, mask=mask)


def _feature_row(feat_dim: int, seed: int, index: int) -> np.ndarray:
    rng = np.random.default_rng(seed + 104729 + index)
    return (rng.standard_normal((1, feat_dim)) * 0.5).astype(np.float32)


def delta_trace(src: np.ndarray, dst: np.ndarray, v_num: int,
                feat_dim: int, *, rounds: int = 6, writers: int = 2,
                adds_per_delta: int = 4, removes_per_delta: int = 1,
                vertex_every: int = 3, seed: int = 0
                ) -> List[List[Tuple[str, "object"]]]:
    """A reproducible delta trace: ``rounds`` commit rounds, each a list
    of (writer_id, GraphDelta) in the log's canonical writer order.
    Removals always name an edge live at their canonical application
    point (a running pair-count table mirrors the log's own apply
    order); every ``vertex_every``-th round, the FIRST writer's delta
    appends one vertex wired into the existing graph."""
    from neutronstarlite_tpu.serve.delta import GraphDelta

    rng = np.random.default_rng(seed + 2)
    live: Dict[Tuple[int, int], int] = {}
    for s, t in zip(src.tolist(), dst.tolist()):
        live[(s, t)] = live.get((s, t), 0) + 1
    v = int(v_num)
    appended = 0
    trace: List[List[Tuple[str, object]]] = []
    wids = [f"w{i}" for i in range(int(writers))]
    for rnd in range(int(rounds)):
        batch: List[Tuple[str, object]] = []
        for wi, wid in enumerate(sorted(wids)):
            add_vertices = 0
            add_features = None
            add: List[Tuple[int, int]] = []
            if vertex_every and wi == 0 and rnd % vertex_every == (
                    vertex_every - 1):
                add_vertices = 1
                add_features = _feature_row(feat_dim, seed, appended)
                appended += 1
                # wire the newcomer both ways so it can serve AND
                # influence its neighborhood
                peer = int(rng.integers(0, v))
                add.extend([(peer, v), (v, peer)])
                v += 1
            for _ in range(int(adds_per_delta)):
                add.append((int(rng.integers(0, v)), int(rng.integers(0, v))))
            remove: List[Tuple[int, int]] = []
            pool = list(live.keys())
            for _ in range(min(int(removes_per_delta), max(len(pool) - 1, 0))):
                pair = pool[int(rng.integers(0, len(pool)))]
                if pair in live and pair not in remove:
                    remove.append(pair)
            # mirror the canonical apply: removals drop EVERY occurrence
            for pair in remove:
                live.pop(pair, None)
            for pair in add:
                live[pair] = live.get(pair, 0) + 1
            batch.append((wid, GraphDelta.edges(
                add=add, remove=remove, add_vertices=add_vertices,
                add_features=add_features,
            )))
        trace.append(batch)
    return trace


def write_trace_log(log_root: str, graph, trace) -> "object":
    """Stage + commit a :func:`delta_trace` into a DeltaLog at
    ``log_root`` (one commit per round — the round structure IS the
    commit structure, keeping generated removals valid). Returns the
    populated log."""
    from neutronstarlite_tpu.stream.log import DeltaLog

    dlog = DeltaLog(log_root, graph)
    if dlog.head_seq:
        raise ValueError(
            f"{log_root} already holds {dlog.head_seq} committed entries; "
            "refusing to regenerate over a live log"
        )
    for batch in trace:
        for wid, delta in batch:
            dlog.writer(wid).stage(delta)
        dlog.commit()
    return dlog


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="deterministic synthetic graph + delta-trace "
        "generator: base.npz + a populated stream log from a seed alone"
    )
    ap.add_argument("out_dir")
    ap.add_argument("--kind", choices=("rmat", "powerlaw"), default="rmat")
    ap.add_argument("--vertices", type=int, default=512)
    ap.add_argument("--edges", type=int, default=2048)
    ap.add_argument("--feat-dim", type=int, default=16)
    ap.add_argument("--classes", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--rounds", type=int, default=6)
    ap.add_argument("--writers", type=int, default=2)
    ap.add_argument("--adds", type=int, default=4)
    ap.add_argument("--removes", type=int, default=1)
    ap.add_argument("--vertex-every", type=int, default=3,
                    help="append one vertex every Nth round (0 disables)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    from neutronstarlite_tpu.graph.storage import build_graph

    src, dst, datum = synth_data(
        args.kind, args.vertices, args.edges, args.feat_dim, args.classes,
        seed=args.seed,
    )
    os.makedirs(args.out_dir, exist_ok=True)
    np.savez(
        os.path.join(args.out_dir, "base.npz"), src=src, dst=dst,
        feature=datum.feature, label=datum.label, mask=datum.mask,
    )
    graph = build_graph(src, dst, args.vertices, use_native=False)
    trace = delta_trace(
        src, dst, args.vertices, args.feat_dim, rounds=args.rounds,
        writers=args.writers, adds_per_delta=args.adds,
        removes_per_delta=args.removes, vertex_every=args.vertex_every,
        seed=args.seed,
    )
    dlog = write_trace_log(os.path.join(args.out_dir, "log"), graph, trace)
    summary = {
        "generator_version": GENERATOR_VERSION,
        "kind": args.kind,
        "seed": args.seed,
        "vertices": args.vertices,
        "edges": int(len(src)),
        "head_seq": dlog.head_seq,
        "head_v_num": int(dlog.head_graph.v_num),
        "base_digest": dlog.base_digest,
        "head_digest": dlog.head_digest,
    }
    if args.json:
        print(json.dumps(summary))
    else:
        print(
            f"{args.out_dir}: {args.kind} graph V={args.vertices} "
            f"E={len(src)}, {dlog.head_seq} committed deltas "
            f"(head V={int(dlog.head_graph.v_num)}), head digest "
            f"{dlog.head_digest[:12]}"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
