"""Render obs JSONL metric streams into the reference-shaped report.

The reference prints its epoch attribution as ``#key=value(ms)`` lines from
DEBUGINFO() (toolkits/GCN.hpp:308-353). This CLI reads one or more JSONL
files written under ``NTS_METRICS_DIR`` (or directories of them), validates
each record against the obs schema, and renders:

- per run: the ``#key=value(ms)`` block — epoch timing attribution
  (first/warm/compile-overhead), the PhaseTimers buckets, then non-time
  counters (wire bytes, batches) and memory as ``#key=value`` lines;
- per run: the recovery timeline — every ``fault`` / ``recovery`` (and
  elastic ``rank_loss`` / ``replan``) record (resilience/) with its
  offset from the stream's first event, so a run's
  failure-and-recovery history reads at a glance;
- per run: the elastic-timeline block (``NTS_ELASTIC=1`` runs) —
  heartbeat volume, rank-loss detections, survivor replans with their
  time-to-recover, and the final ``dist.active_partitions``;
- per run: the span timeline block (tools/trace_timeline derived
  metrics) — span inventory, measured ring overlap efficiency, serve
  critical-path breakdown, retry cost — when the stream carries ``span``
  records;
- per run: the latency-histogram block (merged ``hist`` records with
  their bounded-error quantiles), the slo timeline (``slo_status`` +
  ``shed`` records interleaved), and the backend-probe block
  (``backend_probe`` records; probe-only streams — a bench whose backend
  never answered — render as their own small block);
- per run: the program-cost block (``program_cost`` records, obs/cost —
  XLA's own FLOPs/bytes/memory per labeled executable) and the
  prediction-drift block (``model_drift`` records, tools/drift_audit —
  analytic models caught disagreeing with measured telemetry);
- across runs: a comparison table keyed by run_id/algorithm/fingerprint.

A metrics dir whose only contents are ``flight/`` dumps renders the
dumps with a loud note instead of an empty report; a dir carrying both
streams and dumps renders only the streams (dump records duplicate
stream records — including both would double-count) and says the dumps
exist.

Serving percentiles are read from the stream's merged ``hist`` records
(cumulative snapshots that survive NTS_METRICS_MAX_MB rotation) with the
raw ``serve_request`` full-sort as the pre-histogram fallback; ``--diff``
treats the histogram quantile error bound as an implicit tolerance floor
for serve_p99_ms. Flight-recorder dumps (``flight_*.jsonl``, obs/flight)
are ordinary record streams and render natively.

A file with epoch events but no run_summary (killed run) still renders:
the summary is synthesized from the epoch events, marked ``(synthesized)``.

``--diff A B`` compares two runs' summaries metric by metric (warm epoch
time, wire bytes, shed rate, serve p99) with a per-metric % delta and
exits 2 when any metric regressed beyond ``--tol`` — the BENCH trajectory
check as a gate instead of an eyeball.

Usage:
  python -m neutronstarlite_tpu.tools.metrics_report <file-or-dir> [...]
      [--json]
  python -m neutronstarlite_tpu.tools.metrics_report --diff A B
      [--tol 0.05]
Exit code 0 when every input yielded a report; 1 when nothing usable was
found (or any input was unreadable); 2 when --diff found a regression.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from neutronstarlite_tpu.obs import schema  # noqa: E402
from neutronstarlite_tpu.obs.collectors import steady_state_stats  # noqa: E402
from neutronstarlite_tpu.obs.hist import latest_hists  # noqa: E402


def expand_paths(args: List[str]) -> List[str]:
    out: List[str] = []
    for a in args:
        if os.path.isdir(a):
            out.extend(sorted(glob.glob(os.path.join(a, "*.jsonl"))))
        else:
            out.append(a)
    return out


def expand_report_paths(args: List[str]) -> List[str]:
    """expand_paths with the flight-recorder subdirectory handled
    explicitly: a metrics dir whose only contents are ``flight/`` dumps
    (the run crashed before its stream opened, or only the recorder
    fired) renders the DUMPS with a loud note instead of an empty
    report; a dir carrying both keeps rendering only the streams — dump
    records duplicate stream records, so including both would
    double-count — and says the dumps exist."""
    out: List[str] = []
    for a in args:
        if not os.path.isdir(a):
            out.append(a)
            continue
        top = sorted(glob.glob(os.path.join(a, "*.jsonl")))
        dumps = sorted(glob.glob(os.path.join(a, "flight", "*.jsonl")))
        if top:
            out.extend(top)
            if dumps:
                print(
                    f"{a}: note: {len(dumps)} flight-recorder dump(s) "
                    f"under {os.path.join(a, 'flight')} are NOT included "
                    "(dump records duplicate the stream; pass the "
                    "flight/ directory explicitly to render them)",
                    file=sys.stderr,
                )
        elif dumps:
            print(
                f"{a}: no metrics streams, but {len(dumps)} "
                f"flight-recorder dump(s) under "
                f"{os.path.join(a, 'flight')} — rendering the dumps "
                "(each is the last-records ring a trigger snapshotted, "
                "not a full run)",
                file=sys.stderr,
            )
            out.extend(dumps)
    return out


def load_events(path: str) -> List[Dict[str, Any]]:
    """Parse + validate one JSONL stream; bad lines are reported to stderr
    and skipped (a crashed writer may leave a torn final line). A rotated
    ``<path>.1`` chunk (NTS_METRICS_MAX_MB) holds the stream's OLDEST
    records — it is read first, so run_start/run_summary survive a
    rotation that fired right after they were written."""
    rotated = path + ".1"
    chunks = [rotated, path] if os.path.exists(rotated) else [path]
    events: List[Dict[str, Any]] = []
    for chunk in chunks:
        with open(chunk, "r", encoding="utf-8") as fh:
            for ln, raw in enumerate(fh, 1):
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    obj = json.loads(raw)
                    schema.validate_event(obj)
                except (json.JSONDecodeError, ValueError) as e:
                    print(f"{chunk}:{ln}: skipping bad record: {e}",
                          file=sys.stderr)
                    continue
                events.append(obj)
    return events


def summarize(path: str, events: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The run_summary record for one stream (last one wins), synthesized
    from epoch events when the run died before finalizing."""
    summaries = [e for e in events if e["event"] == "run_summary"]
    if summaries:
        rec = dict(summaries[-1])
        rec["synthesized"] = False
        return rec
    epochs = [e for e in events if e["event"] == "epoch"]
    if not epochs:
        return None
    start = next((e for e in events if e["event"] == "run_start"), {})
    times = [e["seconds"] for e in epochs]
    losses = [e["loss"] for e in epochs if e.get("loss") is not None]
    # same definition as ToolkitBase.avg_epoch_time: exclude the compile
    # (first) epoch when more than one ran, so a synthesized summary is
    # comparable to a finalized one under the same report key
    warm = times[1:] if len(times) > 1 else times
    return {
        "event": "run_summary",
        "run_id": epochs[-1]["run_id"],
        "algorithm": start.get("algorithm", ""),
        "fingerprint": start.get("fingerprint", ""),
        "epochs": len(epochs),
        "epoch_time": steady_state_stats(times),
        "avg_epoch_s": sum(warm) / len(warm),
        "epoch_times_s": times,
        "loss_history": losses,
        "phases": {},
        "counters": {},
        "gauges": {},
        "timings": {},
        "memory": {"available": False, "bytes_in_use": None,
                   "peak_bytes_in_use": None, "devices": []},
        "synthesized": True,
    }


def _ms(v: Optional[float]) -> str:
    return f"{v * 1000:.3f}" if v is not None else "n/a"


# ---- serving streams (serve/) ----------------------------------------------


def summarize_serve(events: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The serve_summary record for a stream that served requests (last one
    wins), synthesized from serve_request records when the server died
    before close() — mirroring summarize()'s contract for training runs."""
    serves = [e for e in events if e["event"] == "serve_summary"]
    if serves:
        rec = dict(serves[-1])
        rec["synthesized"] = False
        return rec
    reqs = [e for e in events if e["event"] == "serve_request"]
    if not reqs:
        return None
    served = [e for e in reqs if e["status"] != "shed"]
    # quantiles come from the merged `hist` records when the stream
    # carries any (cumulative snapshots survive NTS_METRICS_MAX_MB
    # rotation — the raw serve_request sort below loses every rotated-away
    # request, which used to lose p99 entirely); raw full-sort is the
    # fallback for pre-histogram streams only
    hist = latest_hists(events).get("serve.latency_ms")
    if hist is not None and hist.count:
        latency = hist.quantiles()
        source = "hist"
    else:
        from neutronstarlite_tpu.serve.batcher import latency_percentiles

        lat = [
            e["total_ms"] for e in served if e.get("total_ms") is not None
        ]
        latency = latency_percentiles(lat)
        source = "raw"
    ts = [e["ts"] for e in served]
    span = (max(ts) - min(ts)) if len(ts) > 1 else 0.0
    # after rotation the surviving raw records undercount; the histogram's
    # cumulative count covers every answered request — use the larger
    # (sheds and throughput stay raw-derived: the hist holds no timestamps
    # and sheds record no latency)
    n_answered = len(served)
    if source == "hist":
        n_answered = max(n_answered, hist.count)
    return {
        "event": "serve_summary",
        "run_id": reqs[-1]["run_id"],
        # "requests" counts ANSWERED requests, matching the live record
        # (InferenceServer.request_count only counts flushed requests;
        # sheds are separate there too)
        "requests": n_answered,
        "shed": sum(1 for e in reqs if e["status"] == "shed"),
        "latency_ms": latency,
        "latency_source": source,
        "throughput_rps": (len(ts) / span) if span > 0 else None,
        "counters": {},
        "synthesized": True,
    }


def _lat_ms(v: Optional[float]) -> str:
    return f"{v:.3f}" if v is not None else "n/a"


def render_serve(path: str, rec: Dict[str, Any],
                 events: List[Dict[str, Any]]) -> str:
    """The #key=value(ms) block for one serving stream."""
    lat = rec.get("latency_ms") or {}
    rps = rec.get("throughput_rps")
    lines = [
        f"== serve {rec.get('run_id', '?')}"
        f"{' (synthesized)' if rec.get('synthesized') else ''} — {path}",
        "--------------------finish serving !",
        f"#requests={rec.get('requests', 0)}",
        f"#shed={rec.get('shed', 0)}",
        f"#p50_latency={_lat_ms(lat.get('p50'))}(ms)",
        f"#p95_latency={_lat_ms(lat.get('p95'))}(ms)",
        f"#p99_latency={_lat_ms(lat.get('p99'))}(ms)",
        f"#throughput={f'{rps:.2f}' if rps is not None else 'n/a'}(req/s)",
    ]
    flushes = [e for e in events if e["event"] == "batch_flush"]
    if flushes:
        reasons: Dict[str, int] = {}
        for e in flushes:
            reasons[e["reason"]] = reasons.get(e["reason"], 0) + 1
        lines.append(
            f"#batches={len(flushes)} ("
            + " ".join(f"{k}={v}" for k, v in sorted(reasons.items())) + ")"
        )
    for name, v in sorted((rec.get("counters") or {}).items()):
        v = int(v) if float(v).is_integer() else v
        lines.append(f"#{name}={v}")
    cache = rec.get("cache")
    if isinstance(cache, dict):
        lines.append(
            "#cache_hits={hits} misses={misses} entries={entries} "
            "expired={expired}".format(**cache)
        )
    lines.extend(render_sample(rec))
    lines.extend(rec.get("_scan") or [])
    lines.extend(rec.get("_deltas") or [])
    lines.extend(rec.get("_stream") or [])
    lines.extend(rec.get("_cost") or [])
    lines.extend(rec.get("_drift") or [])
    lines.extend(rec.get("_numerics") or [])
    lines.extend(rec.get("_fleet") or [])
    lines.extend(rec.get("_hists") or [])
    lines.extend(rec.get("_slo") or [])
    lines.extend(rec.get("_trace") or [])
    return "\n".join(lines)


def render_ring(events: List[Dict[str, Any]],
                rec: Dict[str, Any]) -> List[str]:
    """The ring-pipelined exchange block: overlap/memory facts from the
    per-hop ``ring_step`` records (parallel/dist_ring_blocked.py) plus the
    residency gauges the trainer pins. Empty when the stream has none."""
    hops = [e for e in events if e["event"] == "ring_step"]
    if not hops:
        return []
    gauges = rec.get("gauges") or {}
    total = sum(e["bytes"] for e in hops)
    by_step: Dict[int, int] = {}
    for e in hops:
        by_step[e["step"]] = by_step.get(e["step"], 0) + e["bytes"]
    epochs = len({e.get("epoch") for e in hops})
    # skip count from the trainer's trace-time gauge when present — a
    # trimmed SUFFIX ships no hops at all, so its skipped steps never
    # appear in the per-hop records; fall back to the records otherwise
    skipped = gauges.get("ring.skipped_steps")
    if skipped is None:
        skipped = sum(1 for e in hops if e.get("skipped")) // max(epochs, 1)
    lines = [
        "ring-pipelined exchange:",
        f"#ring_hops_per_epoch={len(by_step)} "
        f"(skipped_compute_steps={int(skipped)})",
        f"#ring_wire_bytes={total} ({total / 2**20:.2f} MiB over "
        f"{epochs} epoch(s))",
    ]
    peak = gauges.get("wire.peak_resident_rows")
    if peak is not None:
        lines.append(
            f"#ring_peak_resident_rows={int(peak)} (double buffer: "
            "resident shard + one in flight; the all_gather family holds "
            "P*vp)"
        )
    timed = [e["seconds"] for e in hops if e.get("seconds") is not None]
    if timed:
        lines.append(
            f"#ring_hop_time_total={sum(timed) * 1000:.3f}(ms) over "
            f"{len(timed)} measured hops"
        )
    # 2D (vertex x feature) mesh gauges (parallel/partitioner.py): the
    # resolved shape and the feature-slab width each hop carried
    shape = gauges.get("mesh.shape")
    if shape is not None:
        lines.append(
            f"#mesh_shape={shape} (Pv={gauges.get('mesh.pv')}, "
            f"Pf={gauges.get('mesh.pf')}, slab_cols="
            f"{gauges.get('mesh.slab_cols')})"
        )
        feat_bytes = gauges.get("wire.peak_resident_feature_bytes")
        if feat_bytes is not None:
            lines.append(
                f"#mesh_peak_resident_feature_bytes={int(feat_bytes)} "
                "(O(vp*f/Pf): the slab-resident double buffer)"
            )
    return lines


def render_sample(rec: Dict[str, Any]) -> List[str]:
    """The async-sampling-pipeline block (sample/pipeline.py gauges +
    counters the sampled trainer / serve stack pin). Empty for runs that
    never pipelined sampling."""
    gauges = rec.get("gauges") or {}
    counters = rec.get("counters") or {}
    if (
        "sample.queue_depth" not in gauges
        and "sample.stall_ms" not in counters
        and "sample.h2d_bytes" not in counters
    ):
        return []
    lines = ["sampling pipeline:"]
    depth = gauges.get("sample.queue_depth")
    if depth is not None:
        lines.append(
            f"#sample_queue_depth_peak={int(depth)} (bounded prefetch; "
            "NTS_SAMPLE_PREFETCH)"
        )
    stall = counters.get("sample.stall_ms")
    produced = counters.get("sample.produced")
    if stall is not None:
        per = ""
        if produced:
            per = f" ({stall / produced:.3f} ms/batch over {int(produced)})"
        lines.append(f"#sample_stall={stall:.3f}(ms){per}")
    h2d = counters.get("sample.h2d_ms")
    if h2d is not None:
        lines.append(f"#sample_h2d={h2d:.3f}(ms)")
    hb = counters.get("sample.h2d_bytes")
    if hb is not None:
        # the per-batch H2D payload total (sample/pipeline.py producers
        # measure it; the sync path prices the wire_accounting formula;
        # SAMPLE_PIPELINE:fused pins it to exactly 0)
        lines.append(f"#sample_h2d_bytes={int(hb)}")
    return lines


def render_epoch_scan(events: List[Dict[str, Any]]) -> List[str]:
    """The fused one-dispatch epoch block (``epoch_scan`` records,
    SAMPLE_PIPELINE:fused): per-epoch scan receipts aggregated per
    bucket — batches, dispatches (pinned to 1/epoch by the trainer), and
    the H2D byte count (pinned to 0). Empty for non-fused runs."""
    recs = [e for e in events if e["event"] == "epoch_scan"]
    if not recs:
        return []
    by_bucket: Dict[int, Dict[str, Any]] = {}
    for e in recs:
        agg = by_bucket.setdefault(
            int(e["bucket"]),
            {"epochs": 0, "batches": 0, "dispatches": 0, "h2d_bytes": 0,
             "seconds": 0.0},
        )
        agg["epochs"] += 1
        agg["batches"] += int(e["batches"])
        agg["dispatches"] += int(e["dispatches"])
        agg["h2d_bytes"] += int(e["h2d_bytes"])
        if isinstance(e.get("seconds"), (int, float)):
            agg["seconds"] += float(e["seconds"])
    lines = ["fused epoch scan:"]
    for bucket, agg in sorted(by_bucket.items()):
        lines.append(
            f"#epoch_scan=bucket {bucket} epochs={agg['epochs']} "
            f"batches={agg['batches']} dispatches={agg['dispatches']} "
            f"h2d_bytes={agg['h2d_bytes']} "
            f"total={_ms(agg['seconds'])}(ms)"
        )
    return lines


def render_tuning(events: List[Dict[str, Any]],
                  rec: Dict[str, Any]) -> List[str]:
    """The autotuner block (tune/; DIST_PATH:auto / KERNEL:auto /
    WIRE_DTYPE:auto under NTS_TUNE): every decision with its source
    (measured | cached | prior) and score, plus the trial inventory.
    Empty for runs that never consulted the tuner."""
    trials = [e for e in events if e["event"] == "tune_trial"]
    decisions = [e for e in events if e["event"] == "tune_decision"]
    gauges = rec.get("gauges") or {}
    if not (trials or decisions):
        # records may have rotated away (NTS_METRICS_MAX_MB); the gauge
        # snapshot in run_summary still pins the decision
        if "tune.decision" not in gauges:
            return []
        return [
            "tuning:",
            f"#tune_decision={gauges['tune.decision']} "
            f"(source={gauges.get('tune.decision_source')}, "
            f"P={gauges.get('tune.partitions')})",
        ]
    lines = ["tuning:"]
    for d in decisions:
        secs = d.get("seconds")
        pred = d.get("predicted_bytes")
        lines.append(
            f"#tune_decision={d['candidate']} (source={d['source']}, "
            f"P={d.get('partitions')}"
            + (f", score={secs * 1000:.3f}ms" if secs is not None else "")
            + (f", predicted={pred}B" if pred is not None else "")
            + ")"
        )
    if trials:
        by_source: Dict[str, int] = {}
        for t in trials:
            by_source[t["source"]] = by_source.get(t["source"], 0) + 1
        lines.append(
            f"#tune_trials={len(trials)} ("
            + " ".join(f"{k}={v}" for k, v in sorted(by_source.items()))
            + ")"
        )
    return lines


def render_hists(events: List[Dict[str, Any]]) -> List[str]:
    """The latency-histogram block: every merged ``hist`` record with its
    count and bounded-error quantiles. Empty for pre-histogram streams."""
    hists = latest_hists(events)
    if not hists:
        return []

    def _q(v):
        return f"{v:.3f}" if v is not None else "n/a"

    lines = ["latency histograms:"]
    for name, h in sorted(hists.items()):
        q = h.quantiles()
        lines.append(
            f"#hist_{name}=count={h.count} p50={_q(q['p50'])} "
            f"p95={_q(q['p95'])} p99={_q(q['p99'])} "
            f"max={_q(h.max)} (quantile err <= {h.rel_error * 100:.1f}%)"
        )
    return lines


_MAX_SHED_LINES = 40


def slo_timeline(events: List[Dict[str, Any]]) -> List[str]:
    """``slo_status`` verdicts and ``shed`` rejections as ONE
    offset-stamped timeline — burn-rate breaches next to the sheds they
    caused. Empty when the stream carries no slo_status records (plain
    queue-bound sheds stay in the serve block's #shed counter)."""
    slos = [e for e in events if e["event"] == "slo_status"]
    if not slos:
        return []
    sheds = [e for e in events if e["event"] == "shed"]
    t0 = events[0]["ts"] if events else 0.0
    lines = ["slo timeline:"]
    shown_sheds = 0
    for e in sorted(slos + sheds, key=lambda e: (e["ts"], e["seq"])):
        off = e["ts"] - t0
        if e["event"] == "slo_status":
            burn = e.get("burn_rate")
            val = e.get("value")
            lines.append(
                f"  +{off:8.2f}s slo      {e['metric']} state={e['state']}"
                f" burn={f'{burn:.2f}' if burn is not None else 'n/a'}"
                f" value={f'{val:.3f}' if val is not None else 'n/a'}"
                f" (objective {e['objective']})"
            )
        else:
            shown_sheds += 1
            if shown_sheds > _MAX_SHED_LINES:
                continue
            lines.append(
                f"  +{off:8.2f}s shed     reason={e.get('reason')}"
                + (f" depth={e['queue_depth']}"
                   if e.get("queue_depth") is not None else "")
            )
    if shown_sheds > _MAX_SHED_LINES:
        lines.append(
            f"  ... and {shown_sheds - _MAX_SHED_LINES} more shed(s) "
            "(full detail in the stream)"
        )
    return lines


def render_program_costs(events: List[Dict[str, Any]],
                         rec: Optional[Dict[str, Any]] = None) -> List[str]:
    """The compiled-program cost block (obs/cost): XLA's own FLOPs /
    bytes / memory per labeled executable — from the run_summary's
    consolidated list when present, the raw ``program_cost`` records
    otherwise (latest per label wins). Empty for uninstrumented runs."""
    costs = list((rec or {}).get("program_costs") or [])
    if not costs:
        costs = [e for e in events if e["event"] == "program_cost"]
    if not costs:
        return []
    by_label: Dict[str, Dict[str, Any]] = {}
    for c in costs:
        if c.get("label"):
            by_label[c["label"]] = c

    def _n(v):
        return f"{v:g}" if v is not None else "n/a"

    lines = ["program costs:"]
    for label, c in sorted(by_label.items()):
        if not c.get("available"):
            lines.append(
                f"#program_cost={label} unavailable "
                f"({c.get('error') or 'backend exposes no analysis'})"
            )
            continue
        mem = c.get("memory") or {}
        tail = ""
        if mem.get("peak_bytes") is not None:
            tail = (
                f" peak={mem['peak_bytes']}B (args={_n(mem.get('argument_bytes'))}"
                f" out={_n(mem.get('output_bytes'))}"
                f" temp={_n(mem.get('temp_bytes'))})"
            )
        lines.append(
            f"#program_cost={label} flops={_n(c.get('flops'))} "
            f"bytes_accessed={_n(c.get('bytes_accessed'))}"
            f"{tail} (source={c.get('source')})"
        )
    return lines


def render_drift(events: List[Dict[str, Any]]) -> List[str]:
    """The prediction-drift block (tools/drift_audit): every
    ``model_drift`` record — an analytic model (wire pricing, tuner
    prior) caught disagreeing with what actually ran. Empty for
    drift-free streams."""
    drifts = [e for e in events if e["event"] == "model_drift"]
    if not drifts:
        return []

    def _n(v):
        return f"{v:g}" if v is not None else "n/a"

    lines = ["prediction drift:"]
    for d in drifts:
        extra = ""
        if d.get("candidate"):
            extra += (
                f" prior_pick={d['candidate']}"
                + (f" measured_best={d['measured_best']}"
                   if d.get("measured_best") else "")
            )
        if d.get("flagged_entry"):
            extra += f" flagged={d['flagged_entry']}"
            more = len(d.get("flagged_entries") or []) - 1
            if more > 0:
                extra += f" (+{more} more)"
        lines.append(
            f"#model_drift={d['metric']} predicted={_n(d.get('predicted'))} "
            f"observed={_n(d.get('observed'))} "
            f"({d['drift'] * 100:+.1f}% > {d['threshold'] * 100:.0f}%, "
            f"source={d.get('source')}){extra}"
        )
    return lines


_MAX_DELTA_LINES = 20


def render_deltas(events: List[Dict[str, Any]]) -> List[str]:
    """The live graph-delta block (serve/delta.py): every ``graph_delta``
    application with its incremental-invalidation receipt and the digest
    the tuner/ledger keying now sees. Empty for frozen-graph streams."""
    deltas = [e for e in events if e["event"] == "graph_delta"]
    if not deltas:
        return []
    lines = ["graph deltas:"]
    for i, d in enumerate(deltas):
        if i >= _MAX_DELTA_LINES:
            lines.append(
                f"  ... and {len(deltas) - _MAX_DELTA_LINES} more "
                "delta(s) (full detail in the stream)"
            )
            break
        secs = d.get("seconds")
        lines.append(
            f"#graph_delta=+{d['added_edges']}e -{d['removed_edges']}e "
            f"+{d['added_vertices']}v "
            f"invalidated={d.get('cache_invalidated', 0)} "
            f"rows_patched={d.get('rows_patched', 0)} "
            f"dirty={d.get('dirty_predictions', 0)} "
            f"digest={str(d['graph_digest'])[:12]}"
            + (f" ({secs * 1000:.1f} ms)" if secs is not None else "")
            + (f" [{d['replica']}]" if d.get("replica") else "")
        )
    return lines


_MAX_STREAM_LINES = 20


def render_stream(events: List[Dict[str, Any]]) -> List[str]:
    """The streaming-graph block (stream/): every ``delta_commit``
    receipt (the multi-writer log's total-order facts per sequence
    point) and every ``finetune_round`` drain, with the closing
    head-vs-model staleness summary. Empty for non-streaming runs."""
    commits = [e for e in events if e["event"] == "delta_commit"]
    rounds = [e for e in events if e["event"] == "finetune_round"]
    if not (commits or rounds):
        return []
    lines = ["stream:"]
    for i, e in enumerate(commits):
        if i >= _MAX_STREAM_LINES:
            lines.append(
                f"  ... and {len(commits) - _MAX_STREAM_LINES} more "
                "commit(s) (full detail in the stream)"
            )
            break
        secs = e.get("seconds")
        fp = e.get("fp_rate")
        lines.append(
            f"#delta_commit=seq {e['seq']} [{e['writer']}#"
            f"{e['writer_seq']}] +{e['added_edges']}e "
            f"-{e['removed_edges']}e +{e['added_vertices']}v "
            f"dirty={e.get('dirty', 0)} "
            f"({e.get('dirty_mode', 'exact')}"
            + (f", fp={fp:.3f}" if fp is not None else "")
            + f") digest={str(e['graph_digest'])[:12]}"
            + (f" ({secs * 1000:.1f} ms)" if secs is not None else "")
        )
    for e in rounds:
        secs = e.get("seconds")
        loss = e.get("loss")
        lines.append(
            f"#finetune_round={e['round']} seq {e['seq_lo']}.."
            f"{e['seq_hi']} dirty={e['dirty']} "
            f"epochs={e['epochs']} batches={e['batches']} "
            + (f"loss={loss:.4f} " if loss is not None else "loss=n/a ")
            + f"ckpt_step={e['ckpt_step']}"
            + (f" rollout={e['verdict']}" if e.get("verdict") else "")
            + (f" ({secs:.2f}s)" if secs is not None else "")
        )
    if commits and rounds:
        head = commits[-1]["seq"]
        model = rounds[-1]["seq_hi"]
        lines.append(
            f"#stream_staleness=model at seq {model} vs graph head "
            f"{head} (lag {max(head - model, 0)})"
        )
    return lines


def render_numerics(events: List[Dict[str, Any]],
                    rec: Dict[str, Any]) -> List[str]:
    """The numerics-health block (obs/numerics, NTS_NUMERICS=1): the
    LAST ``tensor_stats`` snapshot per tensor group (within a stream the
    latest per name supersedes), the global grad norm / wire quant-error
    gauges, and every ``nonfinite_provenance`` verdict. Empty for
    uninstrumented streams."""
    stats: Dict[str, Dict[str, Any]] = {}
    for e in events:
        if e["event"] == "tensor_stats":
            stats[e["name"]] = e
    provs = [e for e in events if e["event"] == "nonfinite_provenance"]
    gauges = rec.get("gauges") or {}
    if not (stats or provs):
        return []

    def _n(v):
        return f"{v:.4g}" if v is not None else "n/a"

    lines = ["numerics:"]
    for name, e in sorted(stats.items()):
        tail = ""
        if e.get("quant_rel_err") is not None:
            tail = f" quant_rel_err={e['quant_rel_err']:.3g}"
        lines.append(
            f"#numerics_{name}=finite={e['finite_fraction']:.4f} "
            f"absmax={_n(e.get('absmax'))} rms={_n(e.get('rms'))} "
            f"zero={e['zero_fraction']:.4f}{tail}"
            + (f" (epoch {e['epoch']})" if e.get("epoch") is not None
               else "")
        )
    gn = gauges.get("numerics.grad_global_norm")
    if gn is not None:
        lines.append(f"#grad_global_norm={gn:g}")
    qe = gauges.get("wire.quant_rel_err")
    if qe is not None:
        lines.append(f"#wire_quant_rel_err={qe:g}")
    for e in provs:
        lines.append(
            f"#nonfinite_provenance="
            f"layer {e['layer'] if e.get('layer') is not None else '?'} "
            f"op={e.get('op') or '?'} name={e.get('name') or '?'} "
            f"({e['fault_kind']} at epoch {e.get('epoch')}, "
            f"{e.get('checked', 0)} taps checked"
            + (", injected)" if e.get("injected") else ")")
        )
    return lines


def render_probes(events: List[Dict[str, Any]]) -> List[str]:
    """The ``backend_probe`` block (bench.py's subprocess PJRT check) —
    the stale-anchor cause, visible at last. Empty without probes."""
    probes = [e for e in events if e["event"] == "backend_probe"]
    if not probes:
        return []
    lines = ["backend probes:"]
    for e in probes:
        lines.append(
            f"#backend_probe=attempt {e['attempt']} "
            f"outcome={e['outcome']} "
            f"platform={e.get('platform') or '?'} "
            f"{e['seconds']:.1f}s"
            + (f" (timeout_s={e['timeout_s']:g})"
               if e.get("timeout_s") is not None else "")
        )
        err = e.get("error")
        if err:
            tail = str(err).strip().splitlines()[-1][:160]
            lines.append(f"    error: {tail}")
    return lines


_TIMELINE_SKIP = ("event", "run_id", "schema", "ts", "seq", "error")


def recovery_timeline(events: List[Dict[str, Any]]) -> List[str]:
    """``fault``/``recovery`` records as offset-stamped one-liners; the
    elastic ``rank_loss``/``replan`` records and ``stream_rotated``
    markers (the NTS_METRICS_MAX_MB guard) ride the same timeline — a
    truncated history must say so in the report."""
    t0 = events[0]["ts"] if events else 0.0
    lines: List[str] = []
    for e in events:
        if e["event"] not in ("fault", "recovery", "rank_loss", "replan",
                              "stream_rotated", "nonfinite_provenance",
                              "target_loss", "straggler", "rollout"):
            continue
        detail = " ".join(
            f"{k}={e[k]}" for k in sorted(e)
            if k not in _TIMELINE_SKIP and e[k] is not None
        )
        lines.append(f"  +{e['ts'] - t0:8.2f}s {e['event']:<8s} {detail}")
    return lines


def render_elastic(events: List[Dict[str, Any]],
                   rec: Dict[str, Any]) -> List[str]:
    """The elastic-timeline block (resilience/elastic, NTS_ELASTIC=1):
    heartbeat volume, every rank-loss detection, every survivor replan
    with its time-to-recover (rank_loss -> first post-replan epoch_end),
    and the final dist.active_partitions gauge. Empty for runs that
    never ran elastic."""
    beats = [e for e in events if e["event"] == "heartbeat"]
    losses = [e for e in events if e["event"] == "rank_loss"]
    replans = [e for e in events if e["event"] == "replan"]
    if not (beats or losses or replans):
        return []
    lines = ["elastic timeline:"]
    if beats:
        parts = {e["partition"] for e in beats}
        lines.append(
            f"#heartbeats={len(beats)} over {len(parts)} partition(s)"
        )
    for e in losses:
        part = e.get("partition")
        missed = e.get("missed_beats")
        lines.append(
            f"#rank_loss=partition "
            f"{part if part is not None else '?'} at epoch "
            f"{e.get('epoch')} ({e.get('reason', '?')}"
            + (f", {missed} missed beats)" if missed is not None else ")")
        )
    # the rank_loss -> first-post-replan-epoch pairing has ONE
    # implementation (trace_timeline.elastic_report, run_id-guarded for
    # merged dirs); this block and the span-timeline verdict must never
    # disagree on the same stream
    from neutronstarlite_tpu.tools.trace_timeline import elastic_report

    episodes = (elastic_report(events) or {}).get("episodes") or []
    for e, ep in zip(replans, episodes):
        secs = e.get("seconds")
        moved = e.get("moved_vertices")
        lines.append(
            f"#replan={e['from_partitions']}->{e['to_partitions']} "
            f"partitions (lost partition {e.get('lost')}"
            + (f", {moved} vertices re-owned" if moved is not None else "")
            + (f", rebuilt in {secs * 1000:.1f} ms)" if secs is not None
               else ")")
        )
        if ep["recover_s"] is not None:
            lines.append(
                f"#time_to_recover={ep['recover_s']:.2f}s "
                "(rank_loss -> first post-replan epoch_end)"
            )
    active = (rec.get("gauges") or {}).get("dist.active_partitions")
    if active is not None:
        lines.append(f"#active_partitions={int(active)}")
    return lines


def render_fleet(events: List[Dict[str, Any]]) -> List[str]:
    """The telemetry-fabric block (obs/hub + obs/skew + serve/crosshost):
    hub/exporter ``telemetry`` snapshots, every ``target_loss`` (the
    cross-host analog of rank_loss), every advisory ``straggler``
    verdict, and every ``rollout`` attempt with its canary evidence.
    Empty for streams the fabric never touched."""
    telemetry = [e for e in events if e["event"] == "telemetry"]
    losses = [e for e in events if e["event"] == "target_loss"]
    stragglers = [e for e in events if e["event"] == "straggler"]
    rollouts = [e for e in events if e["event"] == "rollout"]
    if not (telemetry or losses or stragglers or rollouts):
        return []
    lines = ["fleet telemetry:"]
    if telemetry:
        hub = [e for e in telemetry if e.get("source") == "hub"]
        lines.append(
            f"#telemetry={len(telemetry)} snapshot(s)"
            + (f" ({len(hub)} hub poll(s))" if hub else "")
        )
        last = (hub or telemetry)[-1]
        if last.get("targets") is not None:
            lines.append(
                f"#fleet_targets={last.get('targets_ok')}/"
                f"{last.get('targets')} ok, "
                f"{last.get('targets_lost')} lost"
            )
        slo = last.get("slo")
        if isinstance(slo, dict) and slo.get("objectives"):
            lines.append(
                f"#fleet_slo={slo.get('worst')} "
                f"({slo.get('breaching')}/{slo.get('objectives')} "
                "breaching)"
            )
    for e in losses:
        lines.append(
            f"#target_loss={e.get('target')} after "
            f"{e.get('missed_polls')} missed poll(s) "
            f"({e.get('reason', '?')}) — merged view continues on the "
            "survivors"
        )
    for e in stragglers:
        exc = e.get("excess")
        lines.append(
            f"#straggler=partition {e.get('partition')} at epoch "
            f"{e.get('epoch')}"
            + (f" (+{exc * 100:.0f}% over the fleet median"
               if isinstance(exc, (int, float)) else " (")
            + f", {e.get('consecutive')} consecutive) — "
            "slow-but-alive, advisory (NOT a rank_loss)"
        )
    for e in rollouts:
        canary = e.get("canary") or {}
        dis = canary.get("disagreement")
        detail = ""
        if dis is not None:
            tol = canary.get("tolerance")
            detail = (
                f" canary disagreement={dis:g}"
                + (f" (tol {tol:g})" if isinstance(tol, (int, float))
                   else "")
            )
        err = e.get("error")
        lines.append(
            f"#rollout={e.get('verdict')} ckpt={e.get('ckpt_dir')}"
            f"{detail} restarted={e.get('restarted', 0)}/"
            f"{e.get('replicas', '?')}"
            + (f" rolled_back={e['rolled_back']}"
               if e.get("rolled_back") else "")
            + (f" — {err}" if err else "")
        )
    return lines


def render_run(path: str, rec: Dict[str, Any]) -> str:
    """The reference-shaped #key=value(ms) block for one run."""
    et = rec.get("epoch_time", {})
    lines = [
        f"== run {rec.get('run_id', '?')} "
        f"[{rec.get('algorithm') or '?'} fp={rec.get('fingerprint') or '?'}]"
        f"{' (synthesized)' if rec.get('synthesized') else ''} — {path}",
        "--------------------finish algorithm !",
        f"#epochs={rec.get('epochs', 0)}",
        f"#avg_epoch_time={_ms(rec.get('avg_epoch_s'))}(ms)",
        f"#first_epoch_time={_ms(et.get('first_s'))}(ms)",
        f"#warm_median_epoch_time={_ms(et.get('warm_median_s'))}(ms)",
        f"#compile_overhead={_ms(et.get('compile_overhead_s'))}(ms)",
    ]
    for name, ph in sorted((rec.get("phases") or {}).items()):
        lines.append(
            f"#{name}_time={_ms(ph.get('total_s'))}(ms) "
            f"count={ph.get('count', 0)}"
        )
    for name, t in sorted((rec.get("timings") or {}).items()):
        if name == "epoch":  # already attributed above
            continue
        lines.append(
            f"#{name}_time={_ms(t.get('total_s'))}(ms) "
            f"count={t.get('count', 0)} avg={_ms(t.get('avg_s'))}(ms)"
        )
    for name, v in sorted((rec.get("counters") or {}).items()):
        v = int(v) if float(v).is_integer() else v
        lines.append(f"#{name}={v}")
    mem = rec.get("memory") or {}
    if mem.get("available"):
        lines.append(f"#peak_hbm_bytes={mem.get('peak_bytes_in_use')}")
        lines.append(f"#hbm_bytes_in_use={mem.get('bytes_in_use')}")
    else:
        lines.append("#peak_hbm_bytes=null (backend exposes no memory_stats)")
    loss = (rec.get("result") or {}).get("loss")
    if loss is not None:
        lines.append(f"#final_loss={loss}")
    lines.extend(rec.get("_ring") or [])
    lines.extend(rec.get("_tune") or [])
    lines.extend(rec.get("_deltas") or [])
    lines.extend(rec.get("_stream") or [])
    lines.extend(rec.get("_cost") or [])
    lines.extend(rec.get("_drift") or [])
    lines.extend(rec.get("_numerics") or [])
    lines.extend(rec.get("_elastic") or [])
    lines.extend(rec.get("_fleet") or [])
    lines.extend(render_sample(rec))
    lines.extend(rec.get("_scan") or [])
    lines.extend(rec.get("_hists") or [])
    lines.extend(rec.get("_slo") or [])
    lines.extend(rec.get("_probe") or [])
    lines.extend(rec.get("_trace") or [])
    timeline = rec.get("_timeline") or []
    if timeline:
        lines.append("recovery timeline:")
        lines.extend(timeline)
    return "\n".join(lines)


def render_table(rows: List[Dict[str, Any]]) -> str:
    """Cross-run comparison keyed by run_id."""
    header = ("run_id", "algo", "fp", "epochs", "warm_ms", "first_ms",
              "wire_MiB", "peak_hbm_MiB")
    table = [header]
    for rec in rows:
        et = rec.get("epoch_time", {})
        counters = rec.get("counters") or {}
        # None-checks, not truthiness: a legitimate 0 (P=1 dist run) must
        # render as 0.00, distinguishable from "not instrumented"
        wire = counters.get("wire.bytes_fwd")
        if wire is None:
            wire = counters.get("wire.feature_gather_bytes")
        mem = rec.get("memory") or {}
        peak = mem.get("peak_bytes_in_use")
        table.append((
            str(rec.get("run_id", "?"))[:40],
            str(rec.get("algorithm") or "?"),
            str(rec.get("fingerprint") or "?")[:12],
            str(rec.get("epochs", 0)),
            _ms(et.get("warm_median_s")),
            _ms(et.get("first_s")),
            f"{wire / 2**20:.2f}" if wire is not None else "n/a",
            f"{peak / 2**20:.1f}" if peak is not None else "n/a",
        ))
    widths = [max(len(r[i]) for r in table) for i in range(len(header))]
    return "\n".join(
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
        for row in table
    )


# ---- --diff: two-run regression gate ---------------------------------------


def _load_side(path: str):
    """(train summary, serve summary) for one --diff side: the first
    stream under ``path`` carrying each (a side is one run's
    NTS_METRICS_DIR, or a single file)."""
    rec = srec = None
    for p in expand_paths([path]):
        try:
            events = load_events(p)
        except OSError as e:
            print(f"{p}: {e}", file=sys.stderr)
            continue
        if rec is None:
            rec = summarize(p, events)
        if srec is None:
            srec = summarize_serve(events)
    return rec, srec


def _diff_metrics(rec, srec):
    """{metric: value} — every entry is lower-is-better so the regression
    rule is uniform; None/absent entries are skipped in the comparison."""
    out = {}
    if rec is not None:
        et = rec.get("epoch_time") or {}
        out["warm_median_epoch_s"] = et.get("warm_median_s")
        out["avg_epoch_s"] = rec.get("avg_epoch_s")
        counters = rec.get("counters") or {}
        # wire.bytes_fwd is a run-total counter; normalize per epoch so a
        # longer run doesn't read as a wire regression (every other diff
        # metric is already per-epoch or a rate)
        wire = counters.get("wire.bytes_fwd")
        n_epochs = rec.get("epochs") or 0
        out["wire_bytes_fwd_per_epoch"] = (
            wire / n_epochs if wire is not None and n_epochs > 0 else None
        )
        # the fused-edge structural gate (scripts/ci_tier1.sh): the
        # attention/edge trainers pin their [Ep, f] edge-tensor HBM
        # traffic estimate here — exactly 0 on the fused path, so any
        # future regression that silently reroutes KERNEL:fused_edge back
        # to the eager chain trips the zero-baseline absolute floor
        gauges = rec.get("gauges") or {}
        out["edge_hbm_bytes_per_epoch"] = gauges.get(
            "kernel.edge_hbm_bytes_per_epoch"
        )
        # the async sampling pipeline's residual stall (sample/pipeline.py)
        # — per epoch, like every other diff metric; absent on sync runs
        # (the shared-metric filter skips it there)
        stall = counters.get("sample.stall_ms")
        out["sample_stall_ms_per_epoch"] = (
            stall / n_epochs if stall is not None and n_epochs > 0 else None
        )
        # the per-batch H2D payload (sample/fused.py's structural gate:
        # exactly 0 when fused, so any regression that reintroduces a
        # host transfer trips the zero-baseline absolute floor)
        h2d = counters.get("sample.h2d_bytes")
        out["sample_h2d_bytes_per_epoch"] = (
            h2d / n_epochs if h2d is not None and n_epochs > 0 else None
        )
        # numerics plane (obs/numerics, NTS_NUMERICS=1 / NTS_QUANT_PROBE):
        # the final grad-norm trajectory point and the measured wire
        # quantization error — both carry tolerance floors (_TOL_FLOORS):
        # grad norms swing with seeds/shuffling well beyond timing noise,
        # and the quant error of one payload jitters only at float
        # granularity, so a tight floor still catches a dtype regression
        out["grad_global_norm"] = gauges.get("numerics.grad_global_norm")
        out["wire_quant_rel_err"] = gauges.get("wire.quant_rel_err")
    if srec is not None:
        answered = srec.get("requests", 0)
        shed = srec.get("shed", 0)
        out["shed_rate"] = (
            shed / (answered + shed) if (answered + shed) > 0 else None
        )
        out["serve_p99_ms"] = (srec.get("latency_ms") or {}).get("p99")
    return out


def _micro_metrics(obj) -> Dict[str, Any]:
    """A tools/micro_bench JSON as a --diff side: per-op median ms, with
    the ``_eager`` / ``_fused`` suffix canonicalized away so a
    fused-vs-eager comparison shares keys across its two sides (each side
    should be produced with an --ops filter selecting one family — the
    ci_tier1 edge-family leg does)."""
    out: Dict[str, Any] = {}
    for name, rec in (obj.get("ops") or {}).items():
        ms = rec.get("ms")
        if ms is None:
            continue
        for suf in ("_eager", "_fused", "_1d", "_2d"):
            if name.endswith(suf):
                name = name[: -len(suf)]
                break
        key = f"micro.{name}_ms"
        if key in out:
            # both variants of one op in a single JSON (micro_bench run
            # without an --ops family filter) would silently compare a
            # mix; keep the first and say so loudly instead
            print(
                f"diff: duplicate canonical metric {key} in micro_bench "
                "side (both variants — _eager/_fused or _1d/_2d — "
                "present?) — keeping the first; produce each side with "
                "an --ops family filter (or comm_bench --side)",
                file=sys.stderr,
            )
            continue
        out[key] = ms
    return out


def _side_metrics(path: str) -> Dict[str, Any]:
    """One --diff side -> {metric: value}: an obs stream dir/file, or a
    micro_bench JSON file (detected by its {"platform", "ops"} shape)."""
    if os.path.isfile(path):
        try:
            with open(path, "r", encoding="utf-8") as fh:
                lines = fh.read().splitlines()
        except OSError:
            lines = []
        for raw in lines:  # log lines may precede the one JSON line
            raw = raw.strip()
            if not raw.startswith("{"):
                continue
            try:
                obj = json.loads(raw)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict) and "ops" in obj and "platform" in obj:
                return _micro_metrics(obj)
            break  # a JSON object of another shape: treat as obs stream
    return _diff_metrics(*_load_side(path))


# per-metric tolerance floors: serve percentiles are histogram-derived
# (obs/hist, bounded relative quantile error ~1% per side), so two
# identical distributions can legitimately differ by up to ~2% between
# sides — a --tol below that would flag quantization noise as regression.
# grad_global_norm varies run to run with seeds/dropout far beyond
# timing noise (25% floor: catch a blow-up, not a reshuffle — the
# one-sided growth check here is deliberate and complements
# perf_sentinel's two-sided ADVISORY trajectory leg, which also
# catches the collapse-toward-zero direction);
# wire_quant_rel_err on one payload is near-deterministic (5% floor:
# a dtype/rounding regression doubles it, float jitter does not).
# The floor is implicit: the effective tolerance is max(--tol, floor).
_TOL_FLOORS = {
    "serve_p99_ms": 0.0202,
    "grad_global_norm": 0.25,
    "wire_quant_rel_err": 0.05,
}


def run_diff(a_path: str, b_path: str, tol: float,
             as_json: bool = False) -> int:
    """Compare run B against baseline A; exit 2 when any shared metric
    regressed (grew) by more than ``tol`` (fractional, e.g. 0.05 = 5%;
    against a 0.0 baseline ``tol`` is the absolute threshold instead).
    Histogram-derived metrics carry their quantile error bound as an
    implicit tolerance floor (_TOL_FLOORS). ``as_json`` emits one
    machine-readable object instead of the table. A side may also be a
    micro_bench JSON file (see _side_metrics)."""
    a = _side_metrics(a_path)
    b = _side_metrics(b_path)
    shared = [
        k for k in a
        if a.get(k) is not None and b.get(k) is not None
    ]
    if not shared:
        print("diff: no comparable metrics between the two sides",
              file=sys.stderr)
        return 1
    header = ("metric", "A", "B", "delta")
    table = [header]
    regressions = []
    detail: Dict[str, Dict[str, Any]] = {}
    for k in shared:
        va, vb = float(a[k]), float(b[k])
        eff_tol = max(tol, _TOL_FLOORS.get(k, 0.0))
        if va > 0:
            delta = (vb - va) / va
            dstr = f"{delta * 100:+.1f}%"
        else:
            delta = 1.0 if vb > 0 else 0.0
            dstr = "n/a" if vb == va else f"+{vb:g} (A was 0)"
        # zero baseline: no relative delta exists, so --tol acts as an
        # absolute floor (shed_rate 0 -> 0.0001 passes at --tol 0.05
        # instead of failing on ANY nonzero value)
        regressed = vb > va * (1.0 + eff_tol) if va > 0 else vb > tol
        if regressed:
            regressions.append(f"{k}: {va:g} -> {vb:g} ({dstr})")
        detail[k] = {"a": va, "b": vb, "delta": delta,
                     "regressed": regressed}
        table.append(
            (k, f"{va:g}", f"{vb:g}", dstr + (" REGRESSED" if regressed else ""))
        )
    if as_json:
        print(json.dumps({
            "tol": tol,
            "metrics": detail,
            "regressed": sorted(k for k in detail
                                if detail[k]["regressed"]),
        }))
        return 2 if regressions else 0
    widths = [max(len(r[i]) for r in table) for i in range(len(header))]
    for row in table:
        print("  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip())
    if regressions:
        print(
            f"REGRESSION beyond --tol {tol:g}: " + "; ".join(regressions),
            file=sys.stderr,
        )
        return 2
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render obs JSONL metric streams into the "
        "reference-shaped #key=value(ms) report"
    )
    ap.add_argument("paths", nargs="*",
                    help="JSONL file(s) or NTS_METRICS_DIR-style directories")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON line (the summaries) instead of text")
    ap.add_argument("--diff", nargs=2, metavar=("A", "B"), default=None,
                    help="compare run B against baseline A (each a file or "
                    "metrics dir); exit 2 on regression beyond --tol")
    ap.add_argument("--tol", type=float, default=0.05,
                    help="--diff regression tolerance as a fraction "
                    "(default 0.05 = 5%%); absolute threshold when the "
                    "baseline value is 0")
    args = ap.parse_args(argv)

    if args.diff is not None:
        return run_diff(args.diff[0], args.diff[1], args.tol,
                        as_json=args.json)
    if not args.paths:
        ap.error("paths required (or use --diff A B)")

    paths = expand_report_paths(args.paths)
    if not paths:
        print("no .jsonl inputs found (a dir holding only a flight/ "
              "subdirectory would have said so above)", file=sys.stderr)
        return 1
    rows: List[Dict[str, Any]] = []
    failed = False
    # every loaded stream also feeds the CROSS-stream request-tracing
    # block: per-request chains span the router's and each replica's
    # files, so the join only makes sense over the merged view
    all_events: List[Dict[str, Any]] = []
    for p in paths:
        try:
            events = load_events(p)
        except OSError as e:
            print(f"{p}: {e}", file=sys.stderr)
            failed = True
            continue
        all_events.extend(events)
        rec = summarize(p, events)
        srec = summarize_serve(events)
        probe_lines = render_probes(events)
        fleet_lines = render_fleet(events)
        if rec is None and srec is None:
            if fleet_lines:
                # a hub's merged stream (obs/hub): no run behind it —
                # the fabric block + merged hists + SLO timeline render
                # it natively instead of "skipping"
                rows.append({
                    "event": "fleet_report",
                    "run_id": events[-1]["run_id"] if events else "?",
                    "telemetry_records": sum(
                        1 for e in events if e["event"] == "telemetry"
                    ),
                    "target_losses": sum(
                        1 for e in events if e["event"] == "target_loss"
                    ),
                    "stragglers": sum(
                        1 for e in events if e["event"] == "straggler"
                    ),
                    "rollouts": sum(
                        1 for e in events if e["event"] == "rollout"
                    ),
                    "_path": p,
                    "_fleet_only": True,
                    "_fleet": fleet_lines,
                    "_hists": render_hists(events),
                    "_slo": slo_timeline(events),
                    "_timeline": recovery_timeline(events),
                })
                continue
            if probe_lines:
                # a probe-only stream (bench.py's backend check with no
                # run behind it — every timed-out round since r05 looks
                # like this) renders its own small block
                probes = [
                    e for e in events if e["event"] == "backend_probe"
                ]
                rows.append({
                    "event": "backend_probe_report",
                    "run_id": probes[-1]["run_id"],
                    "attempts": len(probes),
                    "outcomes": [e["outcome"] for e in probes],
                    "_path": p,
                    "_probe_only": True,
                    "_probe": probe_lines,
                })
                continue
            only_stream = render_stream(events)
            if only_stream:
                # a stream-only file (a tailing replica's delta_commit /
                # finetune_round receipts with no run behind them, e.g. a
                # rotated-away or ingest-sidecar stream) renders the
                # streaming block natively
                rows.append({
                    "event": "stream_report",
                    "run_id": events[-1]["run_id"] if events else "?",
                    "delta_commits": sum(
                        1 for e in events if e["event"] == "delta_commit"
                    ),
                    "finetune_rounds": sum(
                        1 for e in events if e["event"] == "finetune_round"
                    ),
                    "_path": p,
                    "_stream_only": True,
                    "_stream": only_stream,
                    "_hists": render_hists(events),
                })
                continue
            # a run_start-only stream (trainer constructed/crashed before
            # its first epoch) is skippable noise, not a render failure —
            # but a directory yielding NOTHING still exits 1 below
            print(f"{p}: no run_summary, epoch, or serving events; skipping",
                  file=sys.stderr)
            continue
        # the span-timeline block (derived metrics) rides whichever record
        # renders this stream — the training one when present, else the
        # serving one — so it prints exactly once per stream
        from neutronstarlite_tpu.tools.trace_timeline import timeline_block

        trace_lines = timeline_block(events)
        hist_lines = render_hists(events)
        slo_lines = slo_timeline(events)
        drift_lines = render_drift(events)
        delta_lines = render_deltas(events)
        stream_lines = render_stream(events)
        numerics_lines = render_numerics(events, rec or {})
        scan_lines = render_epoch_scan(events)
        if rec is not None:
            rec["_path"] = p
            rec["_timeline"] = recovery_timeline(events)
            rec["_ring"] = render_ring(events, rec)
            rec["_tune"] = render_tuning(events, rec)
            rec["_deltas"] = delta_lines
            rec["_stream"] = stream_lines
            rec["_cost"] = render_program_costs(events, rec)
            rec["_drift"] = drift_lines
            rec["_numerics"] = numerics_lines
            rec["_scan"] = scan_lines
            rec["_elastic"] = render_elastic(events, rec)
            rec["_fleet"] = fleet_lines
            rec["_hists"] = hist_lines
            rec["_slo"] = slo_lines
            rec["_probe"] = probe_lines
            rec["_trace"] = trace_lines
        if srec is not None:
            srec["_path"] = p
            srec["_events"] = events
            srec["_serve"] = True
            srec["_deltas"] = delta_lines if rec is None else []
            srec["_stream"] = stream_lines if rec is None else []
            srec["_cost"] = (
                render_program_costs(events, srec) if rec is None else []
            )
            srec["_drift"] = drift_lines if rec is None else []
            srec["_numerics"] = numerics_lines if rec is None else []
            srec["_scan"] = scan_lines if rec is None else []
            srec["_fleet"] = fleet_lines if rec is None else []
            srec["_hists"] = hist_lines if rec is None else []
            srec["_slo"] = slo_lines if rec is None else []
            srec["_trace"] = trace_lines if rec is None else []
        rows.extend(r for r in (rec, srec) if r is not None)
    if not rows:
        return 1
    if args.json:
        print(json.dumps(
            [{k: v for k, v in r.items() if not k.startswith("_")}
             for r in rows]
        ))
    else:
        for rec in rows:
            if rec.get("_probe_only"):
                print(f"== backend probe — {rec['_path']}")
                print("\n".join(rec["_probe"]))
            elif rec.get("_fleet_only"):
                lines = [f"== fleet {rec.get('run_id', '?')} — "
                         f"{rec['_path']}"]
                lines.extend(rec["_fleet"])
                lines.extend(rec.get("_hists") or [])
                lines.extend(rec.get("_slo") or [])
                timeline = rec.get("_timeline") or []
                if timeline:
                    lines.append("recovery timeline:")
                    lines.extend(timeline)
                print("\n".join(lines))
            elif rec.get("_stream_only"):
                lines = [f"== stream {rec.get('run_id', '?')} — "
                         f"{rec['_path']}"]
                lines.extend(rec["_stream"])
                lines.extend(rec.get("_hists") or [])
                print("\n".join(lines))
            elif rec.get("_serve"):
                print(render_serve(rec["_path"], rec, rec["_events"]))
            else:
                print(render_run(rec["_path"], rec))
            print()
        # fleet-merged distributed tracing: the per-request chain block
        # joins spans ACROSS the loaded streams (router + replicas), so
        # it renders once over the merged view, after the per-stream
        # blocks (lazy import: trace_timeline imports from this module)
        from neutronstarlite_tpu.tools.trace_timeline import (
            request_tracing_block,
        )

        tracing_lines = request_tracing_block(all_events)
        if tracing_lines:
            print("\n".join(tracing_lines))
            print()
        train_rows = [r for r in rows if not r.get("_serve")
                      and not r.get("_probe_only")
                      and not r.get("_fleet_only")
                      and not r.get("_stream_only")]
        if len(train_rows) > 1:
            print(render_table(train_rows))
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
