"""HBM-bandwidth roofline model for the full-batch GCN epoch.

VERDICT round-2 item 2: state the bandwidth bound for the full-scale ELL
epoch, measure the gap, close or explain it. This tool owns the BOUND
side: a per-path byte model of one training epoch (forward + backward +
Adam) over the Reddit-scale workload, evaluated against the v5e's ~819
GB/s HBM, and — when `docs/perf_runs/round4/*.json` holds measured epoch
times — the achieved fraction per measured config.

Byte model (per layer application; b = itemsize of the compute dtype):

- ELL / Pallas-resident regime: the gathered table sits in VMEM (or is
  column-chunked to fit), so HBM pays the TABLE STREAM, not the gathers:
  nbr+wgt slots (pad-inflated) * (4+4) B per direction, the input rows
  once (V*f*b), the output rows once (V*f*b). The Pallas f-chunked
  variant re-reads the tables once per 128-wide column chunk.
- scatter path: the sorted-scatter update stream is HBM-visible:
  E*(4+4+4) B of edge arrays + E*f*b gathered rows + E*f*4 scatter
  updates per direction (the model that explains why ELL wins).
- matmuls: V*(f_in + f_out)*b activations + weights (negligible) each
  way; Adam: 4 reads + 2 writes of every parameter (f32).

The numbers are a BOUND, not a prediction: XLA fusion can beat the
scatter model's middle terms and padding waste can exceed the slot
inflation measured host-side. Usage:

    python -m neutronstarlite_tpu.tools.roofline [--scale 1.0]
        [--runs-dir docs/perf_runs/round4] [--markdown]
"""

from __future__ import annotations

import argparse
import glob
import json
import os

REDDIT_V, REDDIT_E = 232965, 114615892
LAYERS = (602, 128, 41)
HBM_GBS = 819.0  # v5e
MXU_TFLOPS_BF16 = 197.0  # v5e peak
ELL_PAD = 1.33  # measured fwd slot inflation at full scale (PERF.md 3b)
# Mosaic bsp kernel (the PALLAS:1 path): measured full-scale EXECUTED
# block counts per direction (build logs; round 4 — the SMEM ceiling is
# gone via grid segmentation, and the model prices the padded grid the
# kernel actually runs: vt=2048 segments into 2 balanced calls of
# 143,360, docs/perf_runs/round4/r4warm_eager_bsp_2048_balanced.log)
BSP_BLOCKS = {8192: 140896, 4096: 174445, 2048: 286720}
BSP_R = 128  # rows per block (one-hot matmul height)


def epoch_bytes(
    order: str, path: str, v: int, e: int, b: int = 2, vt: int = 0
) -> float:
    """Approximate HBM bytes of one epoch (fwd+bwd, all layers, + Adam)."""
    widths = list(LAYERS)
    total = 0.0
    slots = e * ELL_PAD
    for i in range(len(widths) - 1):
        f_in, f_out = widths[i], widths[i + 1]
        f_agg = f_in if order == "standard" else f_out
        # aggregation, forward + backward (transpose tables, same volume)
        vmem_budget = 96 << 20
        if path in ("pallas", "bsp"):
            # Mosaic bsp kernel (PALLAS:1): the bound is MXU time, not
            # HBM — each block pays one [R, vt] @ [vt, f_agg] bf16 dot
            # (the weights-folded one-hot gather); slab streams and table
            # reads are an order smaller. Convert the FLOP bound into
            # equivalent "bytes" at the HBM rate so one epoch model serves
            # (bound_s divides by HBM_GBS).
            vt = vt or (8192 if path == "bsp" else 4096)
            blocks = BSP_BLOCKS.get(vt, BSP_BLOCKS[4096]) * (v / REDDIT_V)
            mxu_flops = 2.0 * blocks * BSP_R * vt * f_agg
            agg = 2 * mxu_flops / (MXU_TFLOPS_BF16 * 1e12) * (HBM_GBS * 1e9)
        elif path in ("ell", "blocked"):
            agg = 2 * (slots * 8.0 + 2 * v * f_agg * b)
            if path == "ell" and v * f_agg * b > vmem_budget:
                # XLA gather table beyond VMEM: every gathered row is an
                # HBM transaction (the regime the pallas f-chunking and
                # the blocked layouts exist to avoid)
                agg += 2 * slots * f_agg * b
        else:  # scatter
            agg = 2 * (e * 12.0 + e * f_agg * b + e * f_agg * 4.0)
        # the layer matmul fwd+bwd activation traffic
        mm = 2 * v * (f_in + f_out) * b
        total += agg + mm
    params = sum(
        widths[i] * widths[i + 1] for i in range(len(widths) - 1)
    )
    total += 6 * 4 * params  # Adam reads/writes, f32
    return total


def bound_s(order: str, path: str, v: int, e: int, vt: int = 0) -> float:
    return epoch_bytes(order, path, v, e, vt=vt) / (HBM_GBS * 1e9)


def collect_measured(runs_dir: str):
    """(name, epoch_s, order, path, vt) from the plan's salvaged step
    JSONs. Files are parsed from their LAST JSON line (raw stdout dumps
    carry log-line prefixes); records without a measured value — AOT
    warm/capacity artifacts — are skipped."""
    out = []
    for p in sorted(glob.glob(os.path.join(runs_dir, "*.json"))):
        try:
            with open(p) as fh:
                lines = [
                    ln for ln in fh.read().strip().splitlines()
                    if ln.startswith("{")
                ]
            rec = json.loads(lines[-1]) if lines else {}
        except (OSError, json.JSONDecodeError, IndexError):
            continue
        extra = rec.get("extra") or {}
        if rec.get("value") is None or extra.get("stale"):
            continue
        if "order" in extra and "path" in extra:
            out.append((
                os.path.basename(p)[:-5], float(rec["value"]),
                extra["order"], extra["path"],
                int(extra.get("kernel_tile") or 0),
            ))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument(
        "--runs-dir",
        default=os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
            "docs", "perf_runs", "round4",
        ),
    )
    ap.add_argument("--markdown", action="store_true")
    args = ap.parse_args(argv)

    v = max(int(REDDIT_V * args.scale), 64)
    e = max(int(REDDIT_E * args.scale), 512)

    rows = []
    for order in ("standard", "eager"):
        for path in ("scatter", "ell", "pallas"):
            rows.append((order, path, 0, bound_s(order, path, v, e)))
        # the bsp src-tile sweep (plan steps eager_bsp / bsp_vt_*): one
        # bound row per measured block count so every leg has ITS bound
        for vt in sorted(BSP_BLOCKS, reverse=True):
            rows.append((order, "bsp", vt, bound_s(order, "bsp", v, e, vt=vt)))

    measured = collect_measured(args.runs_dir)
    meas_by = {}
    for n, t, o, p, vt in measured:
        # bsp legs are vt-keyed (bench's default src tile is 8192 when
        # the record predates the kernel_tile extra); other paths ignore
        # the knob for row matching
        key_vt = (vt or 8192) if p == "bsp" else 0
        meas_by[(o, p, key_vt)] = (n, t)

    if args.markdown:
        print(f"| order | path | HBM bound (s) | measured (s) | % of roofline |")
        print("|---|---|---|---|---|")
    else:
        print(f"roofline @ scale {args.scale:g} (V={v} E={e}, {HBM_GBS:.0f} GB/s)")
    for order, path, vt, t_bound in rows:
        m = meas_by.get((order, path, vt))
        path = f"{path}@vt{vt}" if vt else path
        if args.markdown:
            if m:
                print(f"| {order} | {path} | {t_bound:.3f} | {m[1]:.3f} "
                      f"| {100 * t_bound / m[1]:.0f}% ({m[0]}) |")
            else:
                print(f"| {order} | {path} | {t_bound:.3f} | — | — |")
        else:
            tail = (
                f"  measured {m[1]:.3f}s = {100 * t_bound / m[1]:.0f}% of bound"
                f" ({m[0]})" if m else ""
            )
            print(f"{order:9s} {path:8s} bound {t_bound:.3f}s{tail}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
