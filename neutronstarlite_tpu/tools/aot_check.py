"""AOT compile-check / capacity planning against a TPU topology — no chips.

``python -m neutronstarlite_tpu.tools.aot_check <file.cfg>
[--topology v5e:2x4]``

Compiles the cfg's FULL jitted train step for the named accelerator
topology via ``jax.experimental.topologies`` (PJRT topology descriptions +
the plugin's compiler — remote or local) and prints one JSON line with the
compile result and the compiled module's memory needs (argument/temp/output
bytes vs HBM). Host-side graph/table construction runs on the CPU backend;
no accelerator is claimed at any point, so this works on a dev box with
zero TPU access — offline capacity planning the reference's
compile-and-run-or-OOM workflow cannot do (its CUDA kernels only fail at
launch time, toolkits/main.cpp:34-199 has no dry-run mode).

Single-mesh models lower with every argument replicated on one topology
device. ``ALGORITHM:GCNDIST`` lowers the real distributed step — the
ppermute ring / all_gather+ELL / mirror all_to_all exchange over a mesh of
all topology devices — by building the sharded program spec directly
(mirroring DistGCNTrainer.build_model, which cannot be reused verbatim
because it device_puts onto the runtime mesh).
"""

from __future__ import annotations

import argparse
import json
import os
import time


def _spec_map(args, rep):
    """Replace every array leaf with a ShapeDtypeStruct on ``rep`` (shared
    by the single-device and sampled AOT cases)."""
    import jax

    def spec(a):
        if hasattr(a, "shape") and hasattr(a, "dtype"):
            return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=rep)
        return a

    return jax.tree.map(spec, args)


def _single_device_case(cfg, base_dir, rep):
    """Build the trainer host-side (CPU backend) and return (jitted, args)
    with every leaf replaced by a replicated ShapeDtypeStruct."""
    from neutronstarlite_tpu.models import get_algorithm

    cls = get_algorithm(cfg.algorithm)
    toolkit = cls(cfg, base_dir=base_dir)
    toolkit.init_graph()
    toolkit.init_nn()
    if not hasattr(toolkit, "aot_args"):
        raise SystemExit(
            f"ALGORITHM {cfg.algorithm}: trainer exposes no aot_args() hook"
        )

    return toolkit._train_step, _spec_map(toolkit.aot_args(), rep)


def _synthetic_edges(cfg, scale: float):
    """Reddit-scale synthetic edge list via bench.py's on-disk graph cache
    (numpy only — the cache is shared with the benchmark, so a prior bench
    run makes this instant). Overrides the cfg's EDGE_FILE/VERTICES."""
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if repo not in _sys.path:
        _sys.path.insert(0, repo)
    from bench import build_and_cache_graph, load_cached_graph

    d, v_num, _, _ = build_and_cache_graph(scale)
    _, src, dst = load_cached_graph(d)
    cfg.vertices = v_num
    return src, dst


def _dist_gcn_case(cfg, base_dir, mesh, edges=None):
    """The distributed GCN train step as ShapeDtypeStructs over ``mesh``
    (mirrors DistGCNTrainer.build_model; kept in sync by
    tests/test_aot_check.py's parity check)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as PS

    from neutronstarlite_tpu.graph.storage import build_graph, load_edges
    from neutronstarlite_tpu.models.gcn import init_gcn_params
    from neutronstarlite_tpu.models.gcn_dist import (
        DistGCNTrainer,
        dist_gcn_forward,
    )
    from neutronstarlite_tpu.nn.param import AdamConfig, adam_init, adam_update
    from neutronstarlite_tpu.parallel.mesh import PARTITION_AXIS

    P = mesh.devices.size
    if edges is None:
        edge_path = cfg.resolve_path(cfg.edge_file, base_dir)
        src, dst = load_edges(edge_path)
    else:
        src, dst = edges
    host_graph = build_graph(src, dst, cfg.vertices, weight="gcn_norm")
    sizes = cfg.layer_sizes()

    # same DIST_PATH resolution as DistGCNTrainer.build_model — the tool
    # must compile the exchange the trainer ships, not a different one
    dist_path = getattr(cfg, "dist_path", "")
    wire_dtype = None
    if dist_path in ("ring_blocked", "ring_blocked_sim"):
        layer_kind = "ring_blocked"
    elif dist_path == "all_gather":
        layer_kind = "ell"
    else:
        layer_kind = DistGCNTrainer.resolve_comm_layer(cfg, host_graph, P)
    if layer_kind == "ring_blocked":
        from neutronstarlite_tpu.parallel.dist_graph import DistGraph
        from neutronstarlite_tpu.parallel.dist_ring_blocked import (
            RingBlockedPair,
            default_ring_vt,
        )
        from neutronstarlite_tpu.parallel.ring_schedule import (
            resolve_wire_dtype,
        )

        dist = DistGraph.build(host_graph, P, edge_chunk=cfg.edge_chunk or None)
        host_blocks = RingBlockedPair.build(
            dist, vt=default_ring_vt(dist.vp, cfg.kernel_tile)
        )
        wire_dtype = resolve_wire_dtype(getattr(cfg, "wire_dtype", ""))
    elif layer_kind == "mirror":
        # the GCN fused path ships the SPLIT layout since round 5
        from neutronstarlite_tpu.parallel.mirror import SplitMirror

        dist = SplitMirror.build(host_graph, P)
        host_blocks = (
            dist.need_ids, dist.r_src_slot, dist.r_dst, dist.r_weight,
            dist.r_mask, dist.l_src, dist.l_dst, dist.l_weight,
            dist.l_mask,
        )
    else:
        from neutronstarlite_tpu.parallel.dist_graph import DistGraph

        dist = DistGraph.build(host_graph, P, edge_chunk=cfg.edge_chunk or None)
        if (
            layer_kind == "ell"
            and getattr(cfg, "pallas_kernel", False)
            and os.environ.get("NTS_PALLAS_RESIDENT", "0") != "1"
        ):
            # PALLAS:1 -> the per-shard rectangular Mosaic bsp kernel
            # (same gate as DistGCNTrainer.build_model; main() forces
            # compiled-Mosaic lowering at tool entry)
            from neutronstarlite_tpu.ops.bsp_ell import DEFAULT_VT
            from neutronstarlite_tpu.parallel.dist_bsp import DistBspPair

            host_blocks = DistBspPair.build(
                dist, vt=cfg.kernel_tile or DEFAULT_VT
            )
        elif layer_kind == "ell" and cfg.kernel_tile > 0:
            from neutronstarlite_tpu.parallel.dist_blocked import (
                DistBlockedEllPair,
            )

            host_blocks = DistBlockedEllPair.build(dist, vt=cfg.kernel_tile)
        elif layer_kind == "ell":
            from neutronstarlite_tpu.parallel.dist_ell import DistEllPair

            host_blocks = DistEllPair.build(dist)
        else:
            # step-major ring layout (DistGraph.step_blocks) — what the
            # trainer ships since round 3
            host_blocks = dist.step_blocks()

    vsh = NamedSharding(mesh, PS(PARTITION_AXIS, None))
    vsh1 = NamedSharding(mesh, PS(PARTITION_AXIS))
    rsh = NamedSharding(mesh, PS())

    def bspec(a):
        # block arrays shard over their leading (dst-partition/device) axis
        nd = len(a.shape)
        sh = NamedSharding(mesh, PS(PARTITION_AXIS, *([None] * (nd - 1))))
        return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh)

    blocks = jax.tree.map(bspec, host_blocks)
    vp_total = dist.vp * P
    params = init_gcn_params(
        jax.random.PRNGKey(0), sizes, with_bn=DistGCNTrainer.with_bn
    )
    adam_cfg = AdamConfig(
        alpha=cfg.learn_rate,
        weight_decay=cfg.weight_decay,
        decay_rate=cfg.decay_rate,
        decay_epoch=cfg.decay_epoch,
    )
    masked_nll = DistGCNTrainer.masked_nll_loss
    drop_rate = cfg.drop_rate
    # same precision binding as DistGCNTrainer.build_model
    compute_dtype = jnp.bfloat16 if cfg.precision == "bfloat16" else None

    def train_step(params, opt_state, blocks, feature, label, train01, valid, key):
        def loss_fn(p):
            logits = dist_gcn_forward(
                mesh, dist, blocks, p, feature, valid, key, drop_rate, True,
                compute_dtype=compute_dtype, wire_dtype=wire_dtype,
            )
            return masked_nll(logits, label, train01), logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        params, opt_state = adam_update(params, grads, opt_state, adam_cfg)
        return params, opt_state, loss, logits

    def rspec(a):
        return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=rsh)

    args = (
        jax.tree.map(rspec, params),
        jax.tree.map(rspec, adam_init(params)),
        blocks,
        jax.ShapeDtypeStruct((vp_total, sizes[0]), jnp.float32, sharding=vsh),
        jax.ShapeDtypeStruct((vp_total,), jnp.int32, sharding=vsh1),
        jax.ShapeDtypeStruct((vp_total,), jnp.float32, sharding=vsh1),
        jax.ShapeDtypeStruct((vp_total,), jnp.float32, sharding=vsh1),
        jax.ShapeDtypeStruct((2,), jnp.uint32, sharding=rsh),
    )
    return jax.jit(train_step), args, layer_kind


def _sampled_synthetic_case(cfg, scale: float, rep):
    """The sampled trainer's per-batch train step at full graph scale
    (feature/label tables at [V, f] ride the jit boundary; batch shapes are
    static from FANOUT x BATCH_SIZE). VERDICT r4 item 4: the sampled path
    (reference: core/ntsSampler.hpp:113, toolkits/GCN_CPU_SAMPLE.hpp) had
    no full-scale AOT check."""
    from neutronstarlite_tpu.graph.dataset import GNNDatum
    from neutronstarlite_tpu.models import get_algorithm

    src, dst = _synthetic_edges(cfg, scale)
    sizes = cfg.layer_sizes()
    datum = GNNDatum.random_generate(cfg.vertices, sizes[0], sizes[-1], seed=0)
    cls = get_algorithm(cfg.algorithm)
    toolkit = cls.from_arrays(cfg, src, dst, datum)

    return toolkit._train_step, _spec_map(toolkit.aot_args(), rep)


def _dist_edge_case(cfg, base_dir, mesh, edges=None):
    """The distributed GAT/GGCN train step (the EDGE-SPACE chain: [P, El]
    mirror-CSR tables materialized per layer — the capacity risk VERDICT r4
    item 3 flags; reference chain /root/reference/toolkits/
    GAT_CPU_DIST.hpp:185-211) as ShapeDtypeStructs over ``mesh``. Mirrors
    DistGATTrainer.build_model; kept honest by tests/test_aot_check.py."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as PS

    from neutronstarlite_tpu.graph.storage import build_graph, load_edges
    from neutronstarlite_tpu.models.gat_dist import DistGATTrainer
    from neutronstarlite_tpu.models.gat import init_gat_params
    from neutronstarlite_tpu.models.ggcn import init_ggcn_params
    from neutronstarlite_tpu.models.ggcn_dist import DistGGCNTrainer
    from neutronstarlite_tpu.nn.param import AdamConfig, adam_init, adam_update
    from neutronstarlite_tpu.parallel.mesh import PARTITION_AXIS
    from neutronstarlite_tpu.parallel.mirror import MirrorGraph

    is_ggcn = cfg.algorithm.upper().startswith(("GGCN", "GGNN"))
    cls = DistGGCNTrainer if is_ggcn else DistGATTrainer
    P = mesh.devices.size
    if edges is None:
        src, dst = load_edges(cfg.resolve_path(cfg.edge_file, base_dir))
    else:
        src, dst = edges
    host_graph = build_graph(src, dst, cfg.vertices, weight=cls.weight_mode)
    mg = MirrorGraph.build(host_graph, P)
    sizes = cfg.layer_sizes()

    def tspec(a):
        sh = NamedSharding(mesh, PS(PARTITION_AXIS, *([None] * (a.ndim - 1))))
        return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh)

    # BOTH edge-chain models compile their chunked + remat'd form (what
    # the trainer builds; un-chunked the chains AOT-measured 76.9 GiB
    # (GGCN) / 14.8 GiB (GAT) at full Reddit —
    # docs/perf_runs/round5/aot_fullscale.log). Only need_ids + chunk
    # tables ship (the trainer's 7-tuple).
    import os as _os

    import numpy as _np

    from neutronstarlite_tpu.parallel.mirror import chunk_edge_list

    ec = int(_os.environ.get("NTS_EDGE_CHUNK", 1_000_000))
    ch = chunk_edge_list(mg, ec)
    probe = _np.zeros((P, ch.dp), _np.int32)
    tables = (tspec(mg.need_ids),) + tuple(
        tspec(t) for t in (ch.slot, ch.dstl, ch.dstr, ch.mask, ch.base)
    ) + (tspec(probe),)
    geo_extra = {"n_chunks": int(ch.slot.shape[1]),
                 "ec": int(ch.slot.shape[2]), "dp": int(ch.dp)}
    params = (
        init_ggcn_params(jax.random.PRNGKey(0), sizes)
        if is_ggcn else init_gat_params(jax.random.PRNGKey(0), sizes)
    )
    adam_cfg = AdamConfig(
        alpha=cfg.learn_rate, weight_decay=cfg.weight_decay,
        decay_rate=cfg.decay_rate, decay_epoch=cfg.decay_epoch,
    )
    # the cfg's precision policy comes pre-bound by the trainer's own
    # classmethod — the tool cannot drift from the shipped program
    forward = cls.bind_forward(cfg)
    masked_nll = cls.masked_nll_loss
    drop_rate = cfg.drop_rate

    def train_step(params, opt_state, tables, feature, label, train01, key):
        def loss_fn(p):
            logits = forward(mesh, mg, tables, p, feature, key, drop_rate, True)
            return masked_nll(logits, label, train01), logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        params, opt_state = adam_update(params, grads, opt_state, adam_cfg)
        return params, opt_state, loss, logits

    vsh = NamedSharding(mesh, PS(PARTITION_AXIS, None))
    vsh1 = NamedSharding(mesh, PS(PARTITION_AXIS))
    rsh = NamedSharding(mesh, PS())

    def rspec(a):
        return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=rsh)

    pv = mg.vp * P
    args = (
        jax.tree.map(rspec, params),
        jax.tree.map(rspec, adam_init(params)),
        tables,
        jax.ShapeDtypeStruct((pv, sizes[0]), jnp.float32, sharding=vsh),
        jax.ShapeDtypeStruct((pv,), jnp.int32, sharding=vsh1),
        jax.ShapeDtypeStruct((pv,), jnp.float32, sharding=vsh1),
        jax.ShapeDtypeStruct((2,), jnp.uint32, sharding=rsh),
    )
    geo = {"Mb": mg.mb, "El": mg.el, "vp": mg.vp}
    geo.update(geo_extra)
    return jax.jit(train_step), args, geo


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("cfg")
    ap.add_argument(
        "--topology", default="v5e:2x4",
        help="PJRT topology name (e.g. v5e:2x4, v5e:4x4, v4:2x2x2)",
    )
    ap.add_argument(
        "--platform", default="tpu",
        help="PJRT platform for get_topology_desc",
    )
    ap.add_argument(
        "--synthetic-scale", type=float, default=None,
        help="ignore EDGE_FILE and use bench.py's cached Reddit-scale "
        "synthetic graph at this scale (1.0 = full) — full-scale capacity "
        "checks without the dataset on disk (dist algorithms only)",
    )
    args = ap.parse_args(argv)

    # host work runs on the CPU backend UNCONDITIONALLY (even when the
    # environment selects an accelerator platform): this tool's contract is
    # that no accelerator is ever claimed — the topology compile below goes
    # to the compiler, not to chips
    os.environ["JAX_PLATFORMS"] = "cpu"
    # Pallas must emit real Mosaic while tracing on this CPU host — the
    # interpret default would compile the emulation (set at TOOL entry,
    # not inside the reusable _dist_gcn_case: a hidden env mutation there
    # would flip every later pallas call in a shared process)
    os.environ["NTS_PALLAS_FORCE_COMPILED"] = "1"
    from neutronstarlite_tpu.utils.platform import honor_platform_env

    honor_platform_env()

    import numpy as np
    import jax
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

    from neutronstarlite_tpu.parallel.mesh import PARTITION_AXIS
    from neutronstarlite_tpu.utils.config import InputInfo

    cfg = InputInfo.read_from_cfg_file(args.cfg)
    base_dir = os.path.dirname(os.path.abspath(args.cfg))
    topo = topologies.get_topology_desc(
        platform=args.platform, topology_name=args.topology
    )
    devices = list(topo.devices)

    out = {
        "cfg": os.path.basename(args.cfg),
        "algorithm": cfg.algorithm,
        "topology": args.topology,
        "devices": len(devices),
    }
    alg = cfg.algorithm.upper()
    EDGE_DIST = (
        "GATCPUDIST", "GATGPUDIST", "GATDIST", "GATCPUDISTOPTM",
        "GGCNDIST", "GGCNCPUDIST", "GGNNDIST",
    )
    t0 = time.time()
    try:
        if alg in ("GCNDIST", "GCNTPUDIST") or alg in EDGE_DIST:
            n = cfg.partitions or len(devices)
            if n > len(devices):
                # ValueError (not SystemExit) so the JSON error contract holds
                raise ValueError(
                    f"PARTITIONS:{n} exceeds the {len(devices)}-device "
                    f"topology {args.topology}"
                )
            mesh = Mesh(np.array(devices[:n]), (PARTITION_AXIS,))
            edges = (
                _synthetic_edges(cfg, args.synthetic_scale)
                if args.synthetic_scale is not None
                else None
            )
            out["vertices"] = cfg.vertices
            if alg in EDGE_DIST:
                jitted, shapes, geo = _dist_edge_case(
                    cfg, base_dir, mesh, edges=edges
                )
                out["comm_layer"] = "mirror-edge"
                out.update(geo)
            else:
                jitted, shapes, layer_kind = _dist_gcn_case(
                    cfg, base_dir, mesh, edges=edges
                )
                out["comm_layer"] = layer_kind
            out["partitions"] = n
        elif alg in ("GCNSAMPLESINGLE", "GCNSAMPLE", "GCNCPUSAMPLE") and (
            args.synthetic_scale is not None
        ):
            # full-scale sampled-trainer capacity: build the trainer over
            # the cached synthetic graph + random datum (shapes are all
            # that reach the compiler)
            mesh1 = Mesh(np.array(devices[:1]), ("one",))
            rep = NamedSharding(mesh1, PS())
            jitted, shapes = _sampled_synthetic_case(
                cfg, args.synthetic_scale, rep
            )
            out["vertices"] = cfg.vertices
        else:
            if args.synthetic_scale is not None:
                raise ValueError(
                    "--synthetic-scale supports dist algorithms and "
                    "GCNSAMPLESINGLE only"
                )
            mesh1 = Mesh(np.array(devices[:1]), ("one",))
            rep = NamedSharding(mesh1, PS())
            jitted, shapes = _single_device_case(cfg, base_dir, rep)
        build_s = time.time() - t0
        t0 = time.time()
        compiled = jitted.lower(*shapes).compile()
        mem = compiled.memory_analysis()
        out.update(
            ok=True,
            build_s=round(build_s, 1),
            compile_s=round(time.time() - t0, 1),
            argument_gib=round(mem.argument_size_in_bytes / 2**30, 3),
            temp_gib=round(mem.temp_size_in_bytes / 2**30, 3),
            output_gib=round(mem.output_size_in_bytes / 2**30, 3),
        )
    except Exception as e:  # noqa: BLE001 — report, don't trace-dump
        out.update(ok=False, error=f"{type(e).__name__}: {str(e)[:500]}")
    print(json.dumps(out))
    return 0 if out.get("ok") else 1


if __name__ == "__main__":
    raise SystemExit(main())
