"""Serving load generator: closed/open-loop SLO measurement.

``python -m neutronstarlite_tpu.tools.serve_bench <cfg> [<ckpt_dir>]
[--train] [--mode closed|open] [--clients C | --rps R] [--requests N]``

Drives the in-process serving stack (serve/server.py) and reports tail
latency + throughput **from the obs records**: the serving run writes its
typed JSONL stream (serve_request / batch_flush / shed / serve_summary)
under NTS_METRICS_DIR (a temp dir when unset), and the percentiles printed
here are computed by re-reading that stream — the measurement artifact is
the same one tools/metrics_report renders, not a private side channel.

Two load models:
- **closed** (default): C concurrent clients, each submits its next
  request only after the previous completes — measures capacity at a
  fixed concurrency (the classic closed-loop knee).
- **open**: requests arrive at a fixed rate R regardless of completions —
  measures behavior under offered load, including the shedding path once
  R exceeds capacity.

``--train`` first runs the cfg's training loop (with CHECKPOINT_DIR set
to the serving checkpoint dir) when no checkpoint exists yet — the
zero-to-serving path for smoke configs.

Prints ONE BENCH_*-compatible JSON line:
  {"metric": "serve_p99_latency_ms", "value": ..., "unit": "ms",
   "vs_baseline": null, "extra": {p50/p95/p99, throughput, sheds, ...}}
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time
from typing import Any, Dict

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from neutronstarlite_tpu.utils.logging import get_logger  # noqa: E402

log = get_logger("serve_bench")


def ensure_checkpoint(cfg, base_dir: str, ckpt_dir: str, train: bool) -> None:
    """Train the cfg's toolkit into ``ckpt_dir`` when empty and --train."""
    from neutronstarlite_tpu.utils.checkpoint import have_checkpoint

    if have_checkpoint(ckpt_dir, getattr(cfg, "ckpt_backend", "")):
        return
    if not train:
        raise SystemExit(
            f"no checkpoint under {ckpt_dir!r}; pass --train to train one "
            "from the cfg first"
        )
    from neutronstarlite_tpu.models import get_algorithm

    log.info("no checkpoint under %s; training %d epochs first",
             ckpt_dir, cfg.epochs)
    prev = os.environ.get("NTS_SAMPLE_WORKERS")
    os.environ.setdefault("NTS_SAMPLE_WORKERS", "0")
    try:
        toolkit = get_algorithm(cfg.algorithm)(cfg, base_dir=base_dir)
        toolkit.init_graph()
        toolkit.init_nn()
        toolkit.run()
    finally:
        if prev is None:
            os.environ.pop("NTS_SAMPLE_WORKERS", None)


def run_closed_loop(server, v_num: int, n_requests: int, clients: int,
                    seeds_per_request: int, seed: int) -> int:
    """C clients, each with one request outstanding; returns error count."""
    counter = {"next": 0, "errors": 0}
    lock = threading.Lock()

    def client(idx: int) -> None:
        rng = np.random.default_rng(seed + 1000 + idx)
        while True:
            with lock:
                if counter["next"] >= n_requests:
                    return
                counter["next"] += 1
            req = server.submit(rng.integers(0, v_num, seeds_per_request))
            try:
                req.result(timeout=120.0)
            except Exception:
                with lock:
                    counter["errors"] += 1

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(max(clients, 1))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return counter["errors"]


def run_open_loop(server, v_num: int, n_requests: int, rps: float,
                  seeds_per_request: int, seed: int) -> int:
    """Fixed arrival rate; sheds count as completed-with-error."""
    rng = np.random.default_rng(seed + 2000)
    interval = 1.0 / max(rps, 1e-6)
    pending = []
    t_next = time.perf_counter()
    for _ in range(n_requests):
        now = time.perf_counter()
        if now < t_next:
            time.sleep(t_next - now)
        t_next += interval
        pending.append(
            server.submit(rng.integers(0, v_num, seeds_per_request))
        )
    errors = 0
    for req in pending:
        try:
            req.result(timeout=120.0)
        except Exception:
            errors += 1
    return errors


def percentiles_from_stream(path: str) -> Dict[str, Any]:
    """Recompute the SLO numbers from the serving obs JSONL records.

    Quantiles come from the stream's merged ``hist`` records (obs/hist:
    cumulative snapshots, fixed memory, survive NTS_METRICS_MAX_MB
    rotation); the raw full-sort of every serve_request line — O(N) memory
    and blind to rotated-away requests — is only the fallback for
    pre-histogram streams. A rotated ``<path>.1`` chunk is read first so
    counts cover the whole run where it survived."""
    from neutronstarlite_tpu.obs import schema
    from neutronstarlite_tpu.obs.hist import latest_hists

    events = []
    rotated = path + ".1"
    for chunk in ([rotated, path] if os.path.exists(rotated) else [path]):
        with open(chunk, "r", encoding="utf-8") as fh:
            for raw in fh:
                raw = raw.strip()
                if not raw:
                    continue
                obj = json.loads(raw)
                schema.validate_event(obj)
                events.append(obj)
    reqs = [e for e in events if e["event"] == "serve_request"]
    served = [
        e for e in reqs
        if e["status"] != "shed" and e.get("total_ms") is not None
    ]
    ts = [e["ts"] for e in served]
    summary = None
    for e in events:
        if e["event"] == "serve_summary":
            summary = e
    out: Dict[str, Any] = {
        "served": len(served),
        "shed": sum(1 for e in reqs if e["status"] == "shed"),
        "batches": sum(1 for e in events if e["event"] == "batch_flush"),
        "summary": summary,
    }
    h = latest_hists(events).get("serve.latency_ms")
    if h is not None and h.count:
        out["latency_ms"] = h.quantiles()
        out["latency_source"] = "hist"
        out["served"] = max(out["served"], h.count)
    elif served:
        lat = [e["total_ms"] for e in served]
        p50, p95, p99 = np.percentile(np.asarray(lat), [50, 95, 99])
        out["latency_ms"] = {
            "p50": float(p50), "p95": float(p95), "p99": float(p99),
        }
        out["latency_source"] = "raw"
    else:
        out["latency_ms"] = {"p50": None, "p95": None, "p99": None}
        out["latency_source"] = None
    span = (max(ts) - min(ts)) if len(ts) > 1 else 0.0
    out["throughput_rps"] = len(ts) / span if span > 0 else None
    return out


def main(argv=None) -> int:
    from neutronstarlite_tpu.utils.platform import honor_platform_env

    honor_platform_env()
    ap = argparse.ArgumentParser(
        description="closed/open-loop serving benchmark over the serve/ "
        "stack; prints one BENCH-compatible JSON line"
    )
    ap.add_argument("cfg")
    ap.add_argument("ckpt", nargs="?", default="",
                    help="checkpoint dir (default: cfg CHECKPOINT_DIR, "
                    "or a temp dir with --train)")
    ap.add_argument("--train", action="store_true",
                    help="train the cfg first when no checkpoint exists")
    ap.add_argument("--mode", choices=("closed", "open"), default="closed")
    ap.add_argument("--clients", type=int, default=4,
                    help="closed-loop concurrency")
    ap.add_argument("--rps", type=float, default=200.0,
                    help="open-loop arrival rate")
    ap.add_argument("--requests", type=int, default=200)
    ap.add_argument("--seeds-per-request", type=int, default=1)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    from neutronstarlite_tpu.utils.config import InputInfo

    cfg = InputInfo.read_from_cfg_file(args.cfg)
    base_dir = os.path.dirname(os.path.abspath(args.cfg))
    scratch = None
    ckpt_dir = args.ckpt or cfg.checkpoint_dir
    if not ckpt_dir:
        if not args.train:
            raise SystemExit(
                "no checkpoint dir: pass one, set CHECKPOINT_DIR in the "
                "cfg, or use --train"
            )
        scratch = tempfile.mkdtemp(prefix="nts_serve_bench_")
        ckpt_dir = os.path.join(scratch, "ckpt")
    cfg.checkpoint_dir = ckpt_dir
    if not os.environ.get("NTS_METRICS_DIR"):
        # the SLO numbers below are read back from this stream
        os.environ["NTS_METRICS_DIR"] = (
            scratch or tempfile.mkdtemp(prefix="nts_serve_bench_")
        )

    ensure_checkpoint(cfg, base_dir, ckpt_dir, args.train)

    from neutronstarlite_tpu.serve.engine import (
        InferenceEngine,
        ServeSetupError,
    )
    from neutronstarlite_tpu.serve.server import InferenceServer

    try:
        engine = InferenceEngine.from_config(
            cfg, base_dir=base_dir, ckpt_dir=ckpt_dir,
            rng=np.random.default_rng(args.seed),
        )
    except ServeSetupError as e:
        raise SystemExit(f"serve_bench: {e}")
    t0 = time.perf_counter()
    engine.warmup()
    warmup_s = time.perf_counter() - t0
    server = InferenceServer(engine)
    v_num = engine.toolkit.host_graph.v_num

    t0 = time.perf_counter()
    if args.mode == "closed":
        errors = run_closed_loop(
            server, v_num, args.requests, args.clients,
            args.seeds_per_request, args.seed,
        )
    else:
        errors = run_open_loop(
            server, v_num, args.requests, args.rps,
            args.seeds_per_request, args.seed,
        )
    wall_s = time.perf_counter() - t0
    stats = server.close()

    stream_path = engine.metrics.path
    if stream_path and os.path.exists(stream_path):
        obs_view = percentiles_from_stream(stream_path)
    else:  # metrics dir unusable: fall back to the in-memory view
        obs_view = {
            "served": stats["requests"], "shed": stats["shed"],
            "batches": None, "latency_ms": stats["latency_ms"],
            "throughput_rps": stats["throughput_rps"], "summary": None,
        }
    lat = obs_view["latency_ms"]
    # the serving-side sampling-pipeline telemetry (SAMPLE_PIPELINE:
    # pipelined/device): queue depth + residual stall ride the
    # serve_summary record's registry snapshot, so the open-loop p99
    # report carries the overlap verdict next to the latency it buys
    summary = obs_view.get("summary") or {}
    s_counters = summary.get("counters") or {}
    s_gauges = summary.get("gauges") or {}
    result = {
        "metric": "serve_p99_latency_ms",
        "value": lat["p99"],
        "unit": "ms",
        "vs_baseline": None,
        "extra": {
            "mode": args.mode,
            "clients": args.clients if args.mode == "closed" else None,
            "rps_offered": args.rps if args.mode == "open" else None,
            "requests": args.requests,
            "seeds_per_request": args.seeds_per_request,
            "p50_ms": lat["p50"],
            "p95_ms": lat["p95"],
            "p99_ms": lat["p99"],
            "throughput_rps": obs_view["throughput_rps"],
            "latency_source": obs_view.get("latency_source"),
            "served": obs_view["served"],
            "shed": obs_view["shed"],
            "errors": errors,
            "batches": obs_view["batches"],
            "warmup_compile_s": warmup_s,
            "compile_counts": {
                str(k): v for k, v in stats["compile_counts"].items()
            },
            "cache": stats["cache"],
            "sample_pipeline": engine.opts.sample_pipeline,
            "sample_queue_depth": s_gauges.get("sample.queue_depth"),
            "sample_stall_ms": s_counters.get("sample.stall_ms"),
            "wall_s": wall_s,
            "metrics_stream": stream_path,
        },
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
