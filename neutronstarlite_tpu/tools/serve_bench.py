"""Serving load generator: closed/open-loop SLO measurement.

``python -m neutronstarlite_tpu.tools.serve_bench <cfg> [<ckpt_dir>]
[--train] [--mode closed|open] [--clients C | --rps R] [--requests N]
[--replicas N] [--cb 0|1] [--delta-rate R]``

Drives the in-process serving stack (serve/server.py — or the
multi-replica fleet, serve/fleet.py, with ``--replicas N``) and reports
tail latency + throughput **from the obs records**: the serving run
writes its typed JSONL stream(s) (serve_request / batch_flush / shed /
serve_summary; one stream per replica in fleet mode, merged here through
the mergeable ``hist`` records) under NTS_METRICS_DIR (a temp dir when
unset), and the percentiles printed here are computed by re-reading
those streams — the measurement artifact is the same one
tools/metrics_report renders, not a private side channel.

Two load models:
- **closed** (default): C concurrent clients, each submits its next
  request only after the previous completes — measures capacity at a
  fixed concurrency (the classic closed-loop knee).
- **open**: requests arrive at a fixed rate R regardless of completions —
  measures behavior under offered load, including the shedding path once
  R exceeds capacity.

Fleet/live-graph legs:
- ``--replicas N`` serves through a ReplicaSet (SLO-routed, supervised);
- ``--cb 0|1`` pins continuous batching (SERVE_CB) for the run;
- ``--delta-rate R`` applies R live graph-delta batches per second
  (``--delta-edges`` random edge inserts each, the previous batch
  removed) DURING the load — the open-loop "predictions track a live
  graph" leg.
- ``--targets host:port,...`` drives already-running replica PROCESSES
  through the cross-host router (serve/crosshost) instead of building an
  in-process server; latency comes from the router's merged fleet
  histograms (``--v-num`` supplies the seed-id space).
- ``--trace`` (targets mode): after the load, merge the router's and
  the replicas' span streams (the tools/trace_timeline ``--fleet``
  cross-process join) and report the complete-chain fraction plus
  router-overhead p50/p95/p99 — client latency minus the replica's
  summed stage time, per traced request. The scalars ride the
  kind=serve ledger row so perf_sentinel can gate router_overhead_p99.
  ``--trace-dirs`` overrides which streams are merged (default:
  NTS_METRICS_DIR).

``--train`` first runs the cfg's training loop (with CHECKPOINT_DIR set
to the serving checkpoint dir) when no checkpoint exists yet — the
zero-to-serving path for smoke configs.

Prints ONE BENCH_*-compatible JSON line:
  {"metric": "serve_p99_latency_ms", "value": ..., "unit": "ms",
   "vs_baseline": null, "extra": {p50/p95/p99, throughput, sheds, ...}}

When ``NTS_LEDGER_DIR`` is set, one ``kind=serve`` row (p50/p95/p99,
shed rate, replica count, delta rate — keyed by cfg fingerprint + load
shape + graph digest) is appended to the cross-run perf ledger, so
``tools/perf_sentinel check --kind serve`` trend-gates serve latency the
way it already gates epoch time.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time
from typing import Any, Dict

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from neutronstarlite_tpu.utils.logging import get_logger  # noqa: E402

log = get_logger("serve_bench")


def ensure_checkpoint(cfg, base_dir: str, ckpt_dir: str, train: bool) -> None:
    """Train the cfg's toolkit into ``ckpt_dir`` when empty and --train."""
    from neutronstarlite_tpu.utils.checkpoint import have_checkpoint

    if have_checkpoint(ckpt_dir, getattr(cfg, "ckpt_backend", "")):
        return
    if not train:
        raise SystemExit(
            f"no checkpoint under {ckpt_dir!r}; pass --train to train one "
            "from the cfg first"
        )
    from neutronstarlite_tpu.models import get_algorithm

    log.info("no checkpoint under %s; training %d epochs first",
             ckpt_dir, cfg.epochs)
    prev = os.environ.get("NTS_SAMPLE_WORKERS")
    os.environ.setdefault("NTS_SAMPLE_WORKERS", "0")
    try:
        toolkit = get_algorithm(cfg.algorithm)(cfg, base_dir=base_dir)
        toolkit.init_graph()
        toolkit.init_nn()
        toolkit.run()
    finally:
        if prev is None:
            os.environ.pop("NTS_SAMPLE_WORKERS", None)


def run_closed_loop(server, v_num: int, n_requests: int, clients: int,
                    seeds_per_request: int, seed: int) -> int:
    """C clients, each with one request outstanding; returns error count."""
    counter = {"next": 0, "errors": 0}
    lock = threading.Lock()

    def client(idx: int) -> None:
        rng = np.random.default_rng(seed + 1000 + idx)
        while True:
            with lock:
                if counter["next"] >= n_requests:
                    return
                counter["next"] += 1
            req = server.submit(rng.integers(0, v_num, seeds_per_request))
            try:
                req.result(timeout=120.0)
            except Exception:
                with lock:
                    counter["errors"] += 1

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(max(clients, 1))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return counter["errors"]


def run_open_loop(server, v_num: int, n_requests: int, rps: float,
                  seeds_per_request: int, seed: int) -> int:
    """Fixed arrival rate; sheds count as completed-with-error."""
    rng = np.random.default_rng(seed + 2000)
    interval = 1.0 / max(rps, 1e-6)
    pending = []
    t_next = time.perf_counter()
    for _ in range(n_requests):
        now = time.perf_counter()
        if now < t_next:
            time.sleep(t_next - now)
        t_next += interval
        pending.append(
            server.submit(rng.integers(0, v_num, seeds_per_request))
        )
    errors = 0
    for req in pending:
        try:
            req.result(timeout=120.0)
        except Exception:
            errors += 1
    return errors


def percentiles_from_streams(paths) -> Dict[str, Any]:
    """Recompute the SLO numbers from one or many serving obs streams
    (fleet mode: one stream per replica + the front door).

    Quantiles come from the streams' merged ``hist`` records (obs/hist:
    cumulative snapshots, fixed memory, survive NTS_METRICS_MAX_MB
    rotation, and MERGE across replicas — the fleet p99 is exact); the
    raw full-sort of every serve_request line — O(N) memory and blind to
    rotated-away requests — is only the fallback for pre-histogram
    streams. A rotated ``<path>.1`` chunk is read first so counts cover
    the whole run where it survived."""
    from neutronstarlite_tpu.obs import schema
    from neutronstarlite_tpu.obs.hist import latest_hists

    events = []
    for path in paths:
        rotated = path + ".1"
        chunks = [rotated, path] if os.path.exists(rotated) else [path]
        for chunk in chunks:
            with open(chunk, "r", encoding="utf-8") as fh:
                for raw in fh:
                    raw = raw.strip()
                    if not raw:
                        continue
                    obj = json.loads(raw)
                    schema.validate_event(obj)
                    events.append(obj)
    reqs = [e for e in events if e["event"] == "serve_request"]
    served = [
        e for e in reqs
        if e["status"] != "shed" and e.get("total_ms") is not None
    ]
    ts = [e["ts"] for e in served]
    summary = None
    for e in events:
        if e["event"] == "serve_summary":
            summary = e
    out: Dict[str, Any] = {
        "served": len(served),
        "shed": sum(1 for e in reqs if e["status"] == "shed"),
        "batches": sum(1 for e in events if e["event"] == "batch_flush"),
        "summary": summary,
    }
    h = latest_hists(events).get("serve.latency_ms")
    if h is not None and h.count:
        out["latency_ms"] = h.quantiles()
        out["latency_source"] = "hist"
        out["served"] = max(out["served"], h.count)
    elif served:
        lat = [e["total_ms"] for e in served]
        p50, p95, p99 = np.percentile(np.asarray(lat), [50, 95, 99])
        out["latency_ms"] = {
            "p50": float(p50), "p95": float(p95), "p99": float(p99),
        }
        out["latency_source"] = "raw"
    else:
        out["latency_ms"] = {"p50": None, "p95": None, "p99": None}
        out["latency_source"] = None
    span = (max(ts) - min(ts)) if len(ts) > 1 else 0.0
    out["throughput_rps"] = len(ts) / span if span > 0 else None
    return out


def percentiles_from_stream(path: str) -> Dict[str, Any]:
    """Single-stream wrapper (the pre-fleet entry point)."""
    return percentiles_from_streams([path])


def run_delta_loop(target, rate: float, edges_per_delta: int, seed: int,
                   stop: threading.Event, counts: Dict[str, int]) -> None:
    """Apply live graph-delta batches at ``rate``/s while the load runs:
    each batch inserts ``edges_per_delta`` random NOVEL edges and removes
    the previous batch's — the graph keeps changing, its size stays
    bounded, and the base graph is never damaged. Novelty matters:
    removal drops EVERY occurrence of a listed pair, so a random insert
    that collided with a pre-existing edge would take the original down
    with it on the next round — candidates are filtered against the
    current edge set (one O(E) key build per batch; bench scale).
    ``target`` is an InferenceServer or ReplicaSet (both expose
    apply_delta)."""
    from neutronstarlite_tpu.serve.delta import GraphDelta, _edge_keys

    rng = np.random.default_rng(seed + 31337)
    interval = 1.0 / max(rate, 1e-6)
    last: list = []
    while not stop.wait(interval):
        g = target.engine.sampler.graph
        v = g.v_num
        existing = set(_edge_keys(
            g.row_indices.astype(np.int64), g.dst_of_edge.astype(np.int64)
        ).tolist())
        add: list = []
        chosen = set()
        for _ in range(20 * max(edges_per_delta, 1)):  # bounded tries
            if len(add) >= max(edges_per_delta, 1):
                break
            u, w = int(rng.integers(0, v)), int(rng.integers(0, v))
            key = (u << 32) | w
            if key in existing or key in chosen:
                continue
            chosen.add(key)
            add.append((u, w))
        if not add:
            continue
        try:
            target.apply_delta(GraphDelta.edges(add=add, remove=last))
        except Exception as e:  # the load must finish; deltas are the leg
            log.warning("delta application failed (%s); stopping deltas", e)
            return
        last = add
        counts["applied"] += 1


def _run_targets_mode(args) -> int:
    """Drive already-running replica processes through the cross-host
    router (serve/crosshost): same load loops, same front-door contract
    (``submit()`` -> future), latency from the router's merged fleet
    histograms (the exact bucket-addition view) instead of local
    streams."""
    from neutronstarlite_tpu.serve.crosshost import CrossHostFleet

    targets = [t.strip() for t in args.targets.split(",") if t.strip()]
    if args.trace and not os.environ.get("NTS_METRICS_DIR"):
        # the router's span stream must land somewhere readable: the
        # post-run merge joins it with the replicas' streams by trace_id
        os.environ["NTS_METRICS_DIR"] = tempfile.mkdtemp(
            prefix="nts_serve_bench_trace_"
        )
    fleet = CrossHostFleet.from_targets(targets)
    t0 = time.perf_counter()
    try:
        if args.mode == "closed":
            errors = run_closed_loop(
                fleet, args.v_num, args.requests, args.clients,
                args.seeds_per_request, args.seed,
            )
        else:
            errors = run_open_loop(
                fleet, args.v_num, args.requests, args.rps,
                args.seeds_per_request, args.seed,
            )
        wall_s = time.perf_counter() - t0
    finally:
        stats = fleet.close()
    trace_view: Dict[str, Any] = {}
    if args.trace:
        # merge the router's + replicas' span streams (all processes
        # share NTS_METRICS_DIR, or pass --trace-dirs) and derive the
        # per-request chain verdict the same way trace_timeline --fleet
        # does — the measurement artifact is the shared obs streams
        from neutronstarlite_tpu.tools.metrics_report import (
            expand_paths,
            load_events,
        )
        from neutronstarlite_tpu.tools.trace_timeline import (
            request_tracing_report,
        )

        dirs = (args.trace_dirs or
                [os.environ.get("NTS_METRICS_DIR", "")])
        merged = []
        for p in expand_paths([d for d in dirs if d]):
            try:
                merged.extend(load_events(p))
            except OSError as e:
                log.warning("serve_bench --trace: cannot read %s (%s)",
                            p, e)
        rep = request_tracing_report(merged)
        if rep is None:
            log.warning("serve_bench --trace: no request traces found "
                        "(is NTS_TRACE on in the replicas?)")
        else:
            trace_view = {
                "trace_complete_frac": rep["complete_frac"],
                "trace_chains": rep["n_traces"],
                "router_overhead_p50_ms": rep["router_overhead_p50_ms"],
                "router_overhead_p95_ms": rep["router_overhead_p95_ms"],
                "router_overhead_p99_ms": rep["router_overhead_p99_ms"],
            }
    lat = stats["latency_ms"]
    result = {
        "metric": "serve_p99_latency_ms",
        "value": lat.get("p99"),
        "unit": "ms",
        "vs_baseline": None,
        "extra": {
            "mode": args.mode,
            "clients": args.clients if args.mode == "closed" else None,
            "rps_offered": args.rps if args.mode == "open" else None,
            "requests": args.requests,
            "seeds_per_request": args.seeds_per_request,
            "p50_ms": lat.get("p50"),
            "p95_ms": lat.get("p95"),
            "p99_ms": lat.get("p99"),
            "latency_source": "fleet_hist",
            "served": stats["requests"],
            "shed": stats["shed"],
            "errors": errors,
            "restarts": stats["restarts"],
            "targets": targets,
            "replicas": stats["replicas"],
            "targets_lost": stats["targets_lost"],
            "wall_s": wall_s,
            **trace_view,
        },
    }
    # one kind=serve row (NTS_LEDGER_DIR): targets-mode runs share the
    # serve trajectory keyed by the target count; with --trace the row
    # carries router_overhead_* + trace_complete_frac, which
    # perf_sentinel gates like any serve scalar
    from neutronstarlite_tpu.obs import ledger

    if ledger.ledger_dir():
        served = stats["requests"]
        shed = stats["shed"]
        total = served + shed
        ledger.append_row(ledger.serve_row(
            latency_ms=lat,
            shed_rate=(shed / total) if total > 0 else None,
            throughput_rps=stats.get("throughput_rps"),
            requests=args.requests,
            cfg_fingerprint=f"targets{len(targets)}",
            graph_digest=None,
            mode=args.mode,
            replicas=stats["replicas"],
            continuous_batching=False,
            extra={
                "clients": (
                    args.clients if args.mode == "closed" else None
                ),
                "rps_offered": (
                    args.rps if args.mode == "open" else None
                ),
                **trace_view,
            },
        ))
    print(json.dumps(result))
    return 0


def main(argv=None) -> int:
    from neutronstarlite_tpu.utils.platform import honor_platform_env

    honor_platform_env()
    ap = argparse.ArgumentParser(
        description="closed/open-loop serving benchmark over the serve/ "
        "stack; prints one BENCH-compatible JSON line"
    )
    ap.add_argument("cfg", nargs="?", default="",
                    help="cfg file (unused in --targets mode)")
    ap.add_argument("ckpt", nargs="?", default="",
                    help="checkpoint dir (default: cfg CHECKPOINT_DIR, "
                    "or a temp dir with --train)")
    ap.add_argument("--train", action="store_true",
                    help="train the cfg first when no checkpoint exists")
    ap.add_argument("--mode", choices=("closed", "open"), default="closed")
    ap.add_argument("--clients", type=int, default=4,
                    help="closed-loop concurrency")
    ap.add_argument("--rps", type=float, default=200.0,
                    help="open-loop arrival rate")
    ap.add_argument("--requests", type=int, default=200)
    ap.add_argument("--seeds-per-request", type=int, default=1)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--replicas", type=int, default=None,
                    help="serve through an N-replica ReplicaSet "
                    "(default: cfg SERVE_REPLICAS / NTS_SERVE_REPLICAS)")
    ap.add_argument("--route", choices=("least_burn", "round_robin"),
                    default=None, help="fleet routing policy override")
    ap.add_argument("--cb", choices=("0", "1"), default=None,
                    help="pin continuous batching (SERVE_CB) for the run")
    ap.add_argument("--delta-rate", type=float, default=0.0,
                    help="apply this many live graph-delta batches per "
                    "second during the load (0 = frozen graph)")
    ap.add_argument("--delta-edges", type=int, default=4,
                    help="edge inserts per delta batch (the previous "
                    "batch is removed)")
    ap.add_argument("--targets", default=None,
                    help="drive a cross-host fleet (serve/crosshost) at "
                    "these replica addresses instead of an in-process "
                    "server; cfg/ckpt are ignored")
    ap.add_argument("--v-num", type=int, default=2708,
                    help="seed-id space for --targets mode (the remote "
                    "graph is not introspectable)")
    ap.add_argument("--trace", action="store_true",
                    help="targets mode: distributed request tracing — "
                    "after the load, merge the router's + replicas' span "
                    "streams (trace_timeline --fleet join) and report "
                    "complete-chain fraction + router-overhead "
                    "p50/p95/p99 (requires NTS_TRACE on in the replicas)")
    ap.add_argument("--trace-dirs", nargs="+", default=None,
                    help="metrics dirs/files holding the fleet's span "
                    "streams (default: NTS_METRICS_DIR)")
    args = ap.parse_args(argv)
    if args.targets:
        return _run_targets_mode(args)
    if not args.cfg:
        ap.error("cfg is required without --targets")
    if args.cb is not None:
        os.environ["NTS_SERVE_CB"] = args.cb
    if args.route is not None:
        os.environ["NTS_SERVE_ROUTE"] = args.route

    from neutronstarlite_tpu.utils.config import InputInfo

    cfg = InputInfo.read_from_cfg_file(args.cfg)
    base_dir = os.path.dirname(os.path.abspath(args.cfg))
    scratch = None
    ckpt_dir = args.ckpt or cfg.checkpoint_dir
    if not ckpt_dir:
        if not args.train:
            raise SystemExit(
                "no checkpoint dir: pass one, set CHECKPOINT_DIR in the "
                "cfg, or use --train"
            )
        scratch = tempfile.mkdtemp(prefix="nts_serve_bench_")
        ckpt_dir = os.path.join(scratch, "ckpt")
    cfg.checkpoint_dir = ckpt_dir
    if not os.environ.get("NTS_METRICS_DIR"):
        # the SLO numbers below are read back from this stream
        os.environ["NTS_METRICS_DIR"] = (
            scratch or tempfile.mkdtemp(prefix="nts_serve_bench_")
        )

    ensure_checkpoint(cfg, base_dir, ckpt_dir, args.train)

    from neutronstarlite_tpu.serve.engine import (
        InferenceEngine,
        ServeSetupError,
    )
    from neutronstarlite_tpu.serve.server import InferenceServer

    try:
        engine = InferenceEngine.from_config(
            cfg, base_dir=base_dir, ckpt_dir=ckpt_dir,
            rng=np.random.default_rng(args.seed),
        )
    except ServeSetupError as e:
        raise SystemExit(f"serve_bench: {e}")
    from neutronstarlite_tpu.serve.fleet import FleetOptions, ReplicaSet

    t0 = time.perf_counter()
    engine.warmup()
    warmup_s = time.perf_counter() - t0
    replicas = (
        args.replicas if args.replicas is not None
        else FleetOptions.from_cfg(cfg).replicas
    )
    if replicas > 1:
        server = ReplicaSet.from_engine(
            engine, replicas, seed=args.seed
        )
        stream_paths = server.stream_paths()
    else:
        server = InferenceServer(engine)
        stream_paths = [engine.metrics.path] if engine.metrics.path else []
    v_num = engine.toolkit.host_graph.v_num
    # the PRE-delta digest is the run's workload identity: the ledger row
    # must key on it, or two --delta-rate runs (whose applied-delta count
    # depends on wall-clock timing) would never share a trajectory and
    # the serve sentinel would silently never gate them
    initial_digest = engine.graph_digest()

    delta_stop = threading.Event()
    delta_counts = {"applied": 0}
    delta_thread = None
    if args.delta_rate > 0:
        delta_thread = threading.Thread(
            target=run_delta_loop,
            args=(server, args.delta_rate, args.delta_edges, args.seed,
                  delta_stop, delta_counts),
            daemon=True,
        )
        delta_thread.start()

    t0 = time.perf_counter()
    if args.mode == "closed":
        errors = run_closed_loop(
            server, v_num, args.requests, args.clients,
            args.seeds_per_request, args.seed,
        )
    else:
        errors = run_open_loop(
            server, v_num, args.requests, args.rps,
            args.seeds_per_request, args.seed,
        )
    wall_s = time.perf_counter() - t0
    delta_stop.set()
    if delta_thread is not None:
        delta_thread.join(timeout=30.0)
    # the graph digest the run ENDED on (deltas bump it) — the ledger key
    graph_digest = engine.graph_digest()
    stats = server.close()
    if replicas > 1:
        # normalize the fleet stats onto the single-server report shape:
        # the AOT ladder is SHARED across replicas (clone warm start), so
        # r0's compile counts are the fleet's; cache stats sum
        per = stats.get("per_replica") or {}
        first = per.get("r0") or {}
        stats["compile_counts"] = first.get("compile_counts", {})
        agg: Dict[str, int] = {}
        for s in per.values():
            for k, v in (s.get("cache") or {}).items():
                agg[k] = agg.get(k, 0) + int(v)
        stats["cache"] = agg

    stream_paths = [p for p in stream_paths if p and os.path.exists(p)]
    if stream_paths:
        obs_view = percentiles_from_streams(stream_paths)
    else:  # metrics dir unusable: fall back to the in-memory view
        obs_view = {
            "served": stats["requests"], "shed": stats["shed"],
            "batches": None, "latency_ms": stats["latency_ms"],
            "throughput_rps": stats["throughput_rps"], "summary": None,
        }
    stream_path = stream_paths[0] if stream_paths else None
    lat = obs_view["latency_ms"]
    # the serving-side sampling-pipeline telemetry (SAMPLE_PIPELINE:
    # pipelined/device): queue depth + residual stall ride the
    # serve_summary record's registry snapshot, so the open-loop p99
    # report carries the overlap verdict next to the latency it buys
    summary = obs_view.get("summary") or {}
    s_counters = summary.get("counters") or {}
    s_gauges = summary.get("gauges") or {}
    result = {
        "metric": "serve_p99_latency_ms",
        "value": lat["p99"],
        "unit": "ms",
        "vs_baseline": None,
        "extra": {
            "mode": args.mode,
            "clients": args.clients if args.mode == "closed" else None,
            "rps_offered": args.rps if args.mode == "open" else None,
            "requests": args.requests,
            "seeds_per_request": args.seeds_per_request,
            "p50_ms": lat["p50"],
            "p95_ms": lat["p95"],
            "p99_ms": lat["p99"],
            "throughput_rps": obs_view["throughput_rps"],
            "latency_source": obs_view.get("latency_source"),
            "served": obs_view["served"],
            "shed": obs_view["shed"],
            "errors": errors,
            "batches": obs_view["batches"],
            "warmup_compile_s": warmup_s,
            "compile_counts": {
                str(k): v for k, v in stats["compile_counts"].items()
            },
            "cache": stats["cache"],
            "sample_pipeline": engine.opts.sample_pipeline,
            "sample_queue_depth": s_gauges.get("sample.queue_depth"),
            "sample_stall_ms": s_counters.get("sample.stall_ms"),
            "continuous_batching": engine.opts.continuous_batching,
            "replicas": replicas,
            "fleet_shed": stats.get("fleet_shed"),
            "restarts": stats.get("restarts"),
            "delta_rate": args.delta_rate,
            "deltas_applied": delta_counts["applied"],
            "graph_digest": graph_digest,
            "wall_s": wall_s,
            "metrics_stream": stream_path,
        },
    }
    # one kind=serve row into the cross-run perf ledger (NTS_LEDGER_DIR):
    # perf_sentinel check --kind serve trend-gates these the way it
    # gates epoch time (key embeds mode/replicas/CB — no mixed shapes)
    from neutronstarlite_tpu.obs import config_fingerprint, ledger

    if ledger.ledger_dir():
        served = obs_view["served"]
        shed = obs_view["shed"]
        total = served + shed
        ledger.append_row(ledger.serve_row(
            latency_ms=lat,
            shed_rate=(shed / total) if total > 0 else None,
            throughput_rps=obs_view["throughput_rps"],
            requests=args.requests,
            cfg_fingerprint=config_fingerprint(cfg),
            graph_digest=initial_digest,
            mode=args.mode,
            replicas=replicas,
            continuous_batching=engine.opts.continuous_batching,
            delta_rate=args.delta_rate,
            deltas_applied=delta_counts["applied"],
            extra={
                "clients": args.clients if args.mode == "closed" else None,
                "rps_offered": args.rps if args.mode == "open" else None,
            },
        ))
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
