"""Summarize a jax.profiler trace into a top-ops attribution table.

VERDICT r3 item 2 asks for the measured-residual attribution of the
full-scale epoch ("gather effective bandwidth? dispatch? Adam/NN
fraction?"). The `profile_trace` plan step captures the xplane trace;
this tool turns it into numbers ON THIS RIG — the installed
tensorboard_plugin_profile's converter is broken against this
tensorflow build (pywrap mismatch), so the xplane proto is parsed
directly via tensorflow's bundled schema.

Aggregates per-op TOTAL duration over the busiest device plane (TPU
planes preferred; an explicit --plane tpu/cpu request FAILS rather than
silently summarizing the other kind), grouping repeated XLA program
instances by stripping trailing `.N` suffixes. Semantics note: device
op lines don't nest, so their totals partition busy time; HOST thread
lines can nest/overlap (block_until_ready wrapping executor spans), so
busy_ms on a cpu plane can exceed wall_ms. Prints a top-N table plus
ONE JSON line for the plan's artifact collector.

Usage: python -m neutronstarlite_tpu.tools.trace_summary <trace_dir>
         [--top 25] [--plane tpu|cpu|auto]
`trace_dir` is NTS_PROFILE_DIR or any parent of plugins/profile/*/.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from collections import defaultdict

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def find_xplanes(root: str) -> "list[str]":
    hits = sorted(
        glob.glob(os.path.join(root, "**", "*.xplane.pb"), recursive=True),
        key=os.path.getmtime,
    )
    return hits


def load_xspace(path: str):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    xs = xplane_pb2.XSpace()
    with open(path, "rb") as fh:
        xs.ParseFromString(fh.read())
    return xs


def pick_plane(xs, prefer: str):
    """TPU device plane when present (auto), else the busiest
    non-metadata plane. An EXPLICIT tpu/cpu request with no matching
    plane returns None — summarizing host threads as device attribution
    (or vice versa) would be silently wrong."""
    scored = []
    for p in xs.planes:
        n_events = sum(len(li.events) for li in p.lines)
        if not n_events:
            continue
        is_tpu = "TPU" in p.name.upper()
        scored.append((is_tpu, n_events, p))
    if not scored:
        return None
    if prefer == "tpu":
        scored = [s for s in scored if s[0]]
    elif prefer == "cpu":
        scored = [s for s in scored if not s[0]]
    else:  # auto: any TPU plane outranks event count
        scored.sort(key=lambda s: (s[0], s[1]))
        return scored[-1][2] if scored else None
    if not scored:
        return None
    scored.sort(key=lambda s: s[1])
    return scored[-1][2]


_SUFFIX = None  # compiled lazily


def _group_name(name: str) -> str:
    """fusion.123 / dot_general.7 -> fusion / dot_general (repeated XLA
    program instances roll up into one attribution row)."""
    global _SUFFIX
    if _SUFFIX is None:
        import re

        _SUFFIX = re.compile(r"\.\d+$")
    return _SUFFIX.sub("", name)


def summarize(plane, top: int) -> dict:
    md = {m_id: m.name for m_id, m in plane.event_metadata.items()}
    tot = defaultdict(int)
    cnt = defaultdict(int)
    span_lo, span_hi = None, 0
    for line in plane.lines:
        # offsets are line-relative: anchor on each line's timestamp
        base_ps = int(line.timestamp_ns) * 1000
        for ev in line.events:
            name = _group_name(md.get(ev.metadata_id, f"id{ev.metadata_id}"))
            tot[name] += ev.duration_ps
            cnt[name] += 1
            lo = base_ps + ev.offset_ps
            hi = lo + ev.duration_ps
            span_lo = lo if span_lo is None else min(span_lo, lo)
            span_hi = max(span_hi, hi)
    wall_ps = (span_hi - (span_lo or 0)) or 1
    busy_ps = sum(tot.values()) or 1
    rows = sorted(tot.items(), key=lambda kv: -kv[1])[:top]
    return {
        "plane": plane.name,
        "wall_ms": round(wall_ps / 1e9, 3),
        "busy_ms": round(busy_ps / 1e9, 3),
        "ops": [
            {
                "name": name[:120],
                "total_ms": round(ps / 1e9, 3),
                "count": cnt[name],
                "pct_of_busy": round(100.0 * ps / busy_ps, 1),
            }
            for name, ps in rows
        ],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_dir")
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--plane", default="auto", choices=["auto", "tpu", "cpu"])
    args = ap.parse_args(argv)

    paths = find_xplanes(args.trace_dir)
    if not paths:
        print(json.dumps({"ok": False,
                          "error": f"no *.xplane.pb under {args.trace_dir}"}))
        return 1
    xs = load_xspace(paths[-1])  # newest capture
    plane = pick_plane(xs, args.plane)
    if plane is None:
        what = (
            f"no {args.plane} plane with events"
            if args.plane != "auto" else "no events in any plane"
        )
        print(json.dumps({"ok": False, "error": what}))
        return 1
    out = summarize(plane, args.top)
    out.update(ok=True, xplane=paths[-1])
    for op in out["ops"]:
        print(
            f"{op['total_ms']:>10.3f} ms {op['pct_of_busy']:>5.1f}% "
            f"x{op['count']:<6d} {op['name']}",
            file=sys.stderr,
        )
    print(
        f"plane {out['plane']}: wall {out['wall_ms']} ms, "
        f"busy {out['busy_ms']} ms ({paths[-1]})",
        file=sys.stderr,
    )
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
