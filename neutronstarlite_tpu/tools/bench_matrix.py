"""Workload-matrix benchmark: epoch time for every cfg in configs/.

BASELINE.md's measurement plan is a matrix of per-workload epoch times
(GCN Cora/Citeseer/Pubmed/Reddit, GAT, GIN, CommNet, sampled GCN — the
reference's root *.cfg files). ``bench.py`` owns the north-star
Reddit-scale number; this tool measures the REST of the matrix in one
pass and prints a table plus one JSON line, so every registered model
family has a measured epoch time on the current backend — the analog of
running the reference's run_nts.sh over its cfg set.

Each workload runs in-process (they share one backend init), overriding
EPOCHS to warmup+epochs; the metric is the median post-warmup epoch time
from the trainer's own epoch_times (the reference's per-epoch timers).
Workloads failing to build/run are reported, not fatal.

Usage: python -m neutronstarlite_tpu.tools.bench_matrix [--configs DIR]
       [--epochs N] [--warmup N] [--skip reddit_full]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

import numpy as np


def measure_cfg(cfg_path: str, epochs: int, warmup: int):
    from neutronstarlite_tpu.models import get_algorithm
    from neutronstarlite_tpu.utils.config import InputInfo

    cfg = InputInfo.read_from_cfg_file(cfg_path)
    cfg.epochs = warmup + epochs
    cls = get_algorithm(cfg.algorithm)
    toolkit = cls(cfg, base_dir=os.path.dirname(os.path.abspath(cfg_path)))
    t0 = time.time()
    toolkit.init_graph()
    toolkit.init_nn()
    build_s = time.time() - t0
    result = toolkit.run()
    times = toolkit.epoch_times[warmup:]
    med = float(np.median(times)) if times else None
    return {
        "algorithm": cfg.algorithm,
        "vertices": cfg.vertices,
        "layers": cfg.layer_string,
        "epoch_s": round(med, 5) if med is not None else None,
        "first_epoch_s": round(toolkit.epoch_times[0], 3)
        if toolkit.epoch_times else None,
        "build_s": round(build_s, 2),
        "loss": result.get("loss"),
        "acc_train": (result.get("acc") or {}).get("train"),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--configs", default=os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), "configs",
        ),
    )
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument(
        "--skip", default="reddit",
        help="comma-separated substrings of cfg names to skip (default: the "
        "reddit workloads — bench.py owns Reddit scale)",
    )
    args = ap.parse_args(argv)

    from neutronstarlite_tpu.utils.platform import honor_platform_env

    honor_platform_env()
    import jax

    # persistent compile cache (same as bench.py's workers): the driver
    # re-runs the matrix every round and the remote compile service is the
    # flakiest link — serialized executables turn repeats into cache hits
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/nts_jit_cache"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    except Exception as e:  # pragma: no cover
        print(f"compile cache unavailable: {e}", file=sys.stderr, flush=True)

    skips = [s for s in args.skip.split(",") if s]
    rows = []
    for cfg_path in sorted(glob.glob(os.path.join(args.configs, "*.cfg"))):
        name = os.path.basename(cfg_path)[: -len(".cfg")]
        if any(s in name for s in skips):
            continue
        print(f"== {name}", file=sys.stderr, flush=True)
        try:
            try:
                row = {
                    "workload": name,
                    **measure_cfg(cfg_path, args.epochs, args.warmup),
                }
            except FileNotFoundError:
                # synthesizable dataset not materialized yet: run the prep
                # tool (graph/prep.py, the generate_nts_dataset analog) once
                from neutronstarlite_tpu.graph import prep

                dataset = next(
                    (d for d in prep.DATASETS if d in name), None
                )
                if dataset is None:
                    raise
                base = os.path.dirname(os.path.abspath(cfg_path))
                prep.main(["--dataset", dataset,
                           "--out", os.path.join(base, "..", "data")])
                row = {
                    "workload": name,
                    **measure_cfg(cfg_path, args.epochs, args.warmup),
                }
        except Exception as e:  # a broken workload must not sink the matrix
            row = {"workload": name, "error": f"{type(e).__name__}: {e}"[:200]}
        rows.append(row)
        print(f"   {row}", file=sys.stderr, flush=True)

    dev = str(jax.devices()[0])
    print(f"\nworkload matrix on {dev} (median of {args.epochs} epochs "
          f"after {args.warmup} warmup):", file=sys.stderr)
    for r in rows:
        if r.get("epoch_s") is not None:
            print(f"  {r['workload']:<22} {r['algorithm']:<18} "
                  f"{r['epoch_s']*1000:9.2f} ms/epoch",
                  file=sys.stderr)
        else:
            print(f"  {r['workload']:<22} FAILED: {r.get('error')}",
                  file=sys.stderr)
    print(json.dumps({"device": dev, "rows": rows}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
