"""Sampling-pipeline benchmark: steady-state batches/sec, sync vs pipelined.

tools/bench_sample.py measures the sampled path's per-batch cost with the
sample+step chain run SERIALLY — the upper bound the async pipeline
(sample/pipeline.py) is built to beat. This leg runs the actual trainer
epoch loop in two (or three) SAMPLE_PIPELINE modes over ONE shared host
graph (one native build — tie-edge order is nondeterministic across
builds, and a shared graph keeps sync/pipelined bitwise-comparable) and
reports steady-state batches/sec per mode plus the telemetry that explains
the difference: the synchronous path's serial sample time vs the pipelined
path's residual ``sample.stall_ms`` vs the fused path's dispatch count
(one ``lax.scan`` per epoch, ``sample.h2d_bytes`` exactly 0 —
sample/fused.py).

With ``NTS_LEDGER_DIR`` set, each measured mode also lands one kind=run
row in the cross-run perf ledger (cfg key ``sample_bench/<mode>`` so the
modes never share a trajectory): perf_sentinel trend-gates the
steady-state ``warm_median_epoch_s`` per mode, and the batches/s +
dispatch counts ride along as context.

Usage: python -m neutronstarlite_tpu.tools.sample_bench [--scale S]
         [--batch-size 512] [--fanout 25-10] [--epochs 3]
         [--modes sync,pipelined,fused]
Prints ONE BENCH-style JSON line:
  {"metric": "sample_pipeline_batches_per_sec", "value": <pipelined bps>,
   "extra": {per-mode epoch times, stall/sample ms, loss parity}}
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def measure_mode(mode, cfg_proto, src, dst, datum, host_graph):
    import jax

    from neutronstarlite_tpu.models.gcn_sample import GCNSampleTrainer

    import dataclasses

    cfg = dataclasses.replace(cfg_proto, sample_pipeline=(
        "" if mode == "sync" else mode
    ))
    t0 = time.time()
    tr = GCNSampleTrainer.from_arrays(
        cfg, src, dst, datum, host_graph=host_graph
    )
    result = tr.run()
    wall_s = time.time() - t0
    snap = tr.metrics.snapshot()
    counters = snap["counters"]
    epochs = tr.epoch_times
    warm = epochs[1:] if len(epochs) > 1 else epochs
    batches = int(counters.get("sample.batches", 0)) / max(len(epochs), 1)
    warm_epoch_s = float(np.median(warm)) if warm else 0.0
    # distributions off the registry histograms (obs/hist) instead of
    # scalar peaks/full-sorts: the depth histogram separates a queue that
    # sat empty (producer-bound) from one that sat full (consumer-bound) —
    # one high-water number cannot
    from neutronstarlite_tpu.obs.hist import LogHistogram

    hists = snap.get("hists") or {}

    def _hq(name):
        d = hists.get(name)
        if not d or not d.get("count"):
            return None
        h = LogHistogram.from_dict(d)
        q = h.quantiles()
        q["max"] = h.max
        q["count"] = h.count
        return q

    jax.clear_caches()
    return {
        "mode": mode,
        "warm_epoch_s": round(warm_epoch_s, 5),
        "batches_per_epoch": int(batches),
        "batches_per_sec": (
            round(batches / warm_epoch_s, 2) if warm_epoch_s > 0 else None
        ),
        "sample_stall_ms_total": counters.get("sample.stall_ms"),
        "sample_stall_ms_dist": _hq("sample.stall_ms"),
        "sample_h2d_ms_total": counters.get("sample.h2d_ms"),
        # fused pins this to exactly 0; sync prices the wire_accounting
        # formula; pipelined/device measure it per staged batch
        "sample_h2d_bytes_total": counters.get("sample.h2d_bytes"),
        # fused: ONE scan dispatch per epoch (sample/fused.py counts
        # them), plus the per-bucket compile count — steady state must
        # show dispatches == epochs and exactly one compile
        "dispatches": counters.get("sample.dispatches"),
        "epoch_compiles": {
            k: int(v) for k, v in counters.items()
            if k.startswith("sample.epoch_compiles.")
        } or None,
        "queue_depth_peak": snap["gauges"].get("sample.queue_depth"),
        "queue_depth_dist": _hq("sample.queue_depth"),
        # full precision: the sync==pipelined parity flag below is a
        # BITWISE claim — rounding would hide exactly the sub-1e-6
        # divergence a pipeline-determinism regression produces
        "loss_history": [float(v) for v in tr.loss_history],
        "final_loss": result["loss"],
        "wall_s": round(wall_s, 2),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=0.02,
                    help="fraction of the Reddit-scale synthetic graph")
    ap.add_argument("--batch-size", type=int, default=512)
    ap.add_argument("--fanout", default="25-10")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--modes", default="sync,pipelined",
                    help="comma list of SAMPLE_PIPELINE modes to sweep "
                    "(sync, pipelined, device, fused)")
    ap.add_argument("--precision", default="float32",
                    choices=["float32", "bfloat16"])
    args = ap.parse_args(argv)

    modes = [m.strip() for m in args.modes.split(",") if m.strip()]
    for m in modes:
        if m not in ("sync", "pipelined", "device", "fused"):
            raise SystemExit(f"unknown mode {m!r} in --modes")
    # the env override outranks cfg.sample_pipeline in
    # resolve_sample_pipeline — left set, every leg of this sweep would
    # silently run the SAME mode and the verdict would be meaningless
    if os.environ.pop("NTS_SAMPLE_PIPELINE", None) is not None:
        print(
            "sample_bench: ignoring NTS_SAMPLE_PIPELINE — each --modes "
            "leg selects its own mode", file=sys.stderr,
        )

    import bench  # graph cache + LAYERS/N_LABELS (one source of the workload)

    from neutronstarlite_tpu.utils.platform import honor_platform_env

    honor_platform_env()

    cache_dir, v_num, e_num, gen_s = bench.build_and_cache_graph(args.scale)
    host_graph, src, dst = bench.load_cached_graph(cache_dir)

    from neutronstarlite_tpu.graph.dataset import GNNDatum
    from neutronstarlite_tpu.utils.config import InputInfo

    sizes = [int(s) for s in bench.LAYERS.split("-")]
    datum = GNNDatum.random_generate(v_num, sizes[0], bench.N_LABELS, seed=7)

    cfg = InputInfo()
    cfg.algorithm = "GCNSAMPLESINGLE"
    cfg.vertices = v_num
    cfg.layer_string = bench.LAYERS
    cfg.batch_size = args.batch_size
    cfg.fanout_string = args.fanout
    cfg.epochs = args.epochs
    cfg.learn_rate = 0.01
    cfg.weight_decay = 0.0001
    cfg.decay_epoch = -1
    cfg.drop_rate = 0.5
    cfg.precision = args.precision

    os.environ.setdefault("NTS_FINAL_EVAL", "0")
    rows = {
        m: measure_mode(m, cfg, src, dst, datum, host_graph) for m in modes
    }

    head = rows.get("fused") or rows.get("pipelined") or rows[modes[0]]
    sync = rows.get("sync")
    parity = None
    if sync is not None and "pipelined" in rows:
        parity = sync["loss_history"] == rows["pipelined"]["loss_history"]
    # fused draws the SAME distribution with a different (on-device)
    # stream, so its oracle is proximity, not bitwise equality — report
    # the max per-epoch divergence for the caller to judge
    fused_vs_sync = None
    if sync is not None and "fused" in rows:
        fl = rows["fused"]["loss_history"]
        sl = sync["loss_history"]
        if fl and sl and len(fl) == len(sl):
            fused_vs_sync = round(
                max(abs(a - b) for a, b in zip(fl, sl)), 6
            )
    out = {
        "metric": "sample_pipeline_batches_per_sec",
        "value": head["batches_per_sec"],
        "unit": "batches/s",
        "vs_baseline": (
            round(head["batches_per_sec"] / sync["batches_per_sec"], 3)
            if sync and sync["batches_per_sec"] and head["batches_per_sec"]
            else None
        ),
        "extra": {
            "scale": args.scale,
            "v_num": v_num,
            "e_num": e_num,
            "batch_size": args.batch_size,
            "fanout": args.fanout,
            "epochs": args.epochs,
            "modes": rows,
            "sync_pipelined_loss_parity": parity,
            "fused_sync_loss_maxdiff": fused_vs_sync,
            "graph_cache_build_s": round(gen_s, 1),
        },
    }
    # one kind=run row PER MODE into the cross-run perf ledger
    # (NTS_LEDGER_DIR; disabled = no-op): the cfg key embeds the mode so
    # sync/pipelined/device/fused never share a trajectory —
    # perf_sentinel trend-gates warm_median_epoch_s per mode and the
    # batches/s + dispatch counts ride as context
    from neutronstarlite_tpu.obs import ledger

    if ledger.ledger_dir():
        for m, r in rows.items():
            ledger.append_row({
                "kind": "run",
                "ts": time.time(),
                "run_id": f"sample_bench-{m}",
                "algorithm": "GCNSAMPLESINGLE",
                "cfg": f"sample_bench/{m}/B{args.batch_size}/"
                       f"{args.fanout}/s{args.scale}",
                "graph_digest": None,
                "backend": ledger.backend_fingerprint(),
                "epochs": args.epochs,
                "warm_median_epoch_s": r["warm_epoch_s"],
                "avg_epoch_s": r["warm_epoch_s"],
                "sample_stall_ms_per_epoch": (
                    r["sample_stall_ms_total"] / max(args.epochs, 1)
                    if r["sample_stall_ms_total"] is not None else None
                ),
                "sample_h2d_bytes_per_epoch": (
                    r["sample_h2d_bytes_total"] / max(args.epochs, 1)
                    if r["sample_h2d_bytes_total"] is not None else None
                ),
                "batches_per_sec": r["batches_per_sec"],
                "dispatches": r["dispatches"],
                "final_loss": r["final_loss"],
            })
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
