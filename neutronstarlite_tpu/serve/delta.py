"""Live graph-delta ingestion: node/edge updates applied between flushes.

A serving fleet over a FROZEN graph answers yesterday's structure; this
module lets served predictions track a live graph. A :class:`GraphDelta`
(edge inserts/removes, vertex appends with their feature rows) is turned
into a :class:`DeltaPlan` — the post-delta host graph plus the exact
incremental damage — and applied to one or many engines/servers between
flushes:

- **Host graph rebuild, deterministically.** The edge list is extracted
  from the live CSC (which preserves within-destination order), edited,
  and rebuilt through the NumPy ``build_graph`` path. Because a stable
  dst-sort of an already-sorted list is the identity, the rebuilt CSC is
  BITWISE what a fresh build over the same edited edge list produces —
  the before/after oracle (tests/test_delta.py) compares served
  predictions against a genuinely fresh engine and demands equality.
  Removing an edge that does not exist raises (the loudness contract);
  removal drops EVERY occurrence of a listed (src, dst) pair.

- **Incremental invalidation, not a flush-the-world.** The plan computes
  two dirty sets. ``dirty_rows`` — vertices whose in-neighbor SET
  changed — are the only device neighbor-table rows patched in place
  (sample/device_sampler.py ``apply_delta``). ``dirty`` — vertices whose
  served logits can differ post-delta — is the out-edge closure (over
  the union of the old and new graphs, L−1 hops) of every vertex whose
  aggregation input changed: destinations of touched edges (their
  in-edge weights renormalize with the in-degree) plus out-neighbors of
  touched sources (their edge weights renormalize with the source
  out-degree). Only those embedding-cache entries are invalidated; every
  other cached row keeps hitting (the hit-rate assertion in the tests).

- **Digest bump.** The plan carries the post-delta canonical
  ``graph_digest`` (graph/digest.py); applying it updates the toolkit's
  cached digest, so the tune-cache key and the perf-ledger row key both
  see a DIFFERENT graph — a stale pre-delta tune decision becomes a miss
  on the next measure run instead of silently replaying.

Staleness contract: a delta takes effect for every flush PRODUCED after
``apply`` returns (the per-replica graph gate serializes against the
produce stage); flushes already prepared/in flight serve the pre-delta
view, and their results are not re-inserted into the embedding cache
(the server's graph-version check). Vertex-appending deltas additionally
invalidate the AOT bucket ladder — the feature operand's shape changed —
so the next flush per bucket pays one recompile (logged loudly);
edge-only deltas never recompile anything.

Every application emits one typed ``graph_delta`` obs record per server
(counts, dirty sizes, the new digest) rendered by tools/metrics_report.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

from neutronstarlite_tpu.graph.digest import graph_digest
from neutronstarlite_tpu.graph.storage import CSCGraph, build_graph
from neutronstarlite_tpu.utils.logging import get_logger

log = get_logger("serve")


def _ids(v) -> np.ndarray:
    return np.asarray(v, dtype=np.int64).reshape(-1)


@dataclasses.dataclass
class GraphDelta:
    """One batch of live-graph updates (all fields optional/empty)."""

    add_src: np.ndarray = dataclasses.field(
        default_factory=lambda: np.empty(0, np.int64))
    add_dst: np.ndarray = dataclasses.field(
        default_factory=lambda: np.empty(0, np.int64))
    remove_src: np.ndarray = dataclasses.field(
        default_factory=lambda: np.empty(0, np.int64))
    remove_dst: np.ndarray = dataclasses.field(
        default_factory=lambda: np.empty(0, np.int64))
    add_vertices: int = 0
    # feature rows for the appended vertices ([add_vertices, f]); required
    # whenever add_vertices > 0 — a vertex without features cannot serve
    add_features: Optional[np.ndarray] = None

    def __post_init__(self):
        self.add_src = _ids(self.add_src)
        self.add_dst = _ids(self.add_dst)
        self.remove_src = _ids(self.remove_src)
        self.remove_dst = _ids(self.remove_dst)
        if len(self.add_src) != len(self.add_dst):
            raise ValueError("add_src/add_dst length mismatch")
        if len(self.remove_src) != len(self.remove_dst):
            raise ValueError("remove_src/remove_dst length mismatch")
        if self.add_vertices < 0:
            raise ValueError("add_vertices must be >= 0")
        if self.add_vertices and self.add_features is None:
            raise ValueError(
                "add_vertices > 0 needs add_features rows — an appended "
                "vertex without features cannot be served"
            )

    @classmethod
    def edges(cls, add: Iterable[Tuple[int, int]] = (),
              remove: Iterable[Tuple[int, int]] = (),
              add_vertices: int = 0,
              add_features: Optional[np.ndarray] = None) -> "GraphDelta":
        """Convenience constructor from (src, dst) pair lists."""
        add = list(add)
        remove = list(remove)
        return cls(
            add_src=np.array([e[0] for e in add], np.int64),
            add_dst=np.array([e[1] for e in add], np.int64),
            remove_src=np.array([e[0] for e in remove], np.int64),
            remove_dst=np.array([e[1] for e in remove], np.int64),
            add_vertices=add_vertices,
            add_features=add_features,
        )

    @property
    def empty(self) -> bool:
        return (len(self.add_src) == 0 and len(self.remove_src) == 0
                and self.add_vertices == 0)


@dataclasses.dataclass
class DeltaPlan:
    """The post-delta graph plus the exact incremental damage."""

    src: np.ndarray  # the edited edge list (CSC order — dst-sorted)
    dst: np.ndarray
    v_num: int
    graph: CSCGraph  # rebuilt via the deterministic NumPy path
    digest: str  # canonical post-delta graph digest
    dirty_rows: np.ndarray  # in-neighbor SET changed -> device-table rows
    dirty: np.ndarray  # predictions possibly changed -> cache invalidation
    added_edges: int
    removed_edges: int
    added_vertices: int
    add_features: Optional[np.ndarray]
    hops: int
    rows_patched: int = 0  # filled by apply_to_engines


def _edge_keys(src: np.ndarray, dst: np.ndarray) -> np.ndarray:
    # vertex ids are < 2**32 (uint32 storage), so one int64 packs a pair
    return (src.astype(np.int64) << 32) | dst.astype(np.int64)


def _out_neighbors(g: CSCGraph, vs: np.ndarray) -> np.ndarray:
    """Unique destinations of the out-edges of ``vs`` (CSR walk); ids
    beyond the graph (appended vertices walked on the OLD graph) are
    skipped."""
    vs = np.unique(vs)
    vs = vs[(vs >= 0) & (vs < g.v_num)]
    if len(vs) == 0:
        return np.empty(0, np.int64)
    deg = g.out_degree[vs].astype(np.int64)
    total = int(deg.sum())
    if total == 0:
        return np.empty(0, np.int64)
    starts = g.row_offset[vs].astype(np.int64)
    within = np.arange(total) - np.repeat(np.cumsum(deg) - deg, deg)
    idx = np.repeat(starts, deg) + within
    return np.unique(g.column_indices[idx].astype(np.int64))


def plan_delta(graph: CSCGraph, delta: GraphDelta, hops: int,
               dirty_closure=None) -> DeltaPlan:
    """Turn a delta into the post-delta graph + dirty sets (pure).

    ``dirty_closure`` swaps the exact out-closure for an approximate one
    (stream/ingest.py's bitset tracker): a callable
    ``(old_graph, new_graph, changed_src, changed_dst, hops) -> dirty``
    whose result must be a SUPERSET of the exact closure — invalidating
    extra cache rows costs recompute, missing one serves stale logits.
    """
    old_src = graph.row_indices.astype(np.int64)
    old_dst = graph.dst_of_edge.astype(np.int64)
    new_v = graph.v_num + int(delta.add_vertices)

    for name, arr in (("add_src", delta.add_src), ("add_dst", delta.add_dst),
                      ("remove_src", delta.remove_src),
                      ("remove_dst", delta.remove_dst)):
        if len(arr) and (int(arr.min()) < 0 or int(arr.max()) >= new_v):
            raise ValueError(
                f"graph delta {name} references vertex "
                f"{int(arr.max() if arr.max() >= new_v else arr.min())} "
                f"outside 0..{new_v - 1}"
            )

    mask = np.ones(len(old_src), dtype=bool)
    removed = 0
    if len(delta.remove_src):
        keys = _edge_keys(old_src, old_dst)
        rm_keys = np.unique(_edge_keys(delta.remove_src, delta.remove_dst))
        present = np.isin(rm_keys, keys)
        if not present.all():
            missing = rm_keys[~present][:5]
            pairs = [(int(k >> 32), int(k & 0xFFFFFFFF)) for k in missing]
            raise ValueError(
                f"graph delta removes edge(s) that do not exist: {pairs}"
                + (" ..." if (~present).sum() > 5 else "")
            )
        mask = ~np.isin(keys, rm_keys)
        removed = int((~mask).sum())

    src = np.concatenate([old_src[mask], delta.add_src])
    dst = np.concatenate([old_dst[mask], delta.add_dst])
    # the NumPy path: a stable dst-sort of this (already mostly sorted)
    # list — deterministic, so a fresh build over the same edited list is
    # bitwise identical (the oracle's ground)
    g2 = build_graph(
        src.astype(np.uint32), dst.astype(np.uint32), new_v,
        weight="gcn_norm", use_native=False,
    )

    changed_dst = np.unique(np.concatenate([delta.remove_dst, delta.add_dst]))
    changed_src = np.unique(np.concatenate([delta.remove_src, delta.add_src]))
    if dirty_closure is not None:
        dirty = np.unique(np.asarray(
            dirty_closure(graph, g2, changed_src, changed_dst, int(hops)),
            dtype=np.int64,
        ))
    else:
        # aggregation inputs that changed: touched destinations (in-degree
        # renormalizes every in-edge weight) + out-neighbors of touched
        # sources (out-degree renormalizes every out-edge weight) — walked
        # on BOTH graphs so removed reach still counts
        seed = np.unique(np.concatenate([
            changed_dst,
            _out_neighbors(graph, changed_src),
            _out_neighbors(g2, changed_src),
        ])).astype(np.int64)
        dirty = seed
        frontier = seed
        for _ in range(max(int(hops) - 1, 0)):
            nxt = np.union1d(
                _out_neighbors(graph, frontier), _out_neighbors(g2, frontier)
            )
            fresh = np.setdiff1d(nxt, dirty, assume_unique=False)
            if len(fresh) == 0:
                break
            dirty = np.union1d(dirty, fresh)
            frontier = fresh

    return DeltaPlan(
        src=src, dst=dst, v_num=new_v, graph=g2, digest=graph_digest(g2),
        dirty_rows=changed_dst.astype(np.int64), dirty=dirty,
        added_edges=int(len(delta.add_src)), removed_edges=removed,
        added_vertices=int(delta.add_vertices),
        add_features=delta.add_features, hops=int(hops),
    )


# ---------------------------------------------------------------------------
# application
# ---------------------------------------------------------------------------


def apply_to_engines(engines: Sequence, delta: GraphDelta,
                     plan: Optional[DeltaPlan] = None) -> DeltaPlan:
    """Swap the post-delta graph into every engine (no server state).

    Engines cloned from one template share the toolkit, the device hop
    sampler and the AOT ladder — the shared pieces are patched exactly
    once; per-engine samplers each get the new graph reference. Returns
    the plan (``plan.rows_patched`` set)."""
    import jax.numpy as jnp

    base = engines[0]
    if plan is None:
        plan = plan_delta(base.sampler.graph, delta,
                          hops=len(base.fanouts))
    g = plan.graph

    rows_patched = 0
    hop_samplers = set()
    hop = getattr(base.sampler, "hop_sampler", None)
    if hop is not None:
        rows_patched = hop.apply_delta(g, plan.dirty_rows)
        hop_samplers.add(id(hop))
    new_feature = None
    in_margin = False
    if plan.added_vertices:
        feat = base.feature
        rows = np.asarray(plan.add_features)
        if rows.ndim != 2 or rows.shape[0] != plan.added_vertices \
                or rows.shape[1] != feat.shape[1]:
            raise ValueError(
                f"add_features must be [{plan.added_vertices}, "
                f"{feat.shape[1]}], got {rows.shape}"
            )
        v0 = plan.v_num - plan.added_vertices
        if int(feat.shape[0]) >= plan.v_num:
            # recompile-free path (stream/ingest.reserve_feature_margin):
            # the slab was pre-sized with capacity slack, so the appended
            # rows PATCH into reserved space — the shape (and therefore
            # the AOT ladder's feature aval) never changes; zero bucket
            # recompiles, compile_counts pinned by tests
            in_margin = True
            new_feature = feat.at[v0:plan.v_num].set(
                jnp.asarray(rows, dtype=feat.dtype)
            )
            log.info(
                "graph delta appended %d vertices within the capacity "
                "margin (%d slack rows remain): feature rows patched in "
                "place, AOT bucket ladder untouched",
                plan.added_vertices, int(feat.shape[0]) - plan.v_num,
            )
        else:
            new_feature = jnp.concatenate(
                [feat, jnp.asarray(rows, dtype=feat.dtype)], axis=0
            )
            if int(feat.shape[0]) > v0 or getattr(base, "margin_armed",
                                                  False):
                # margin was armed but this append outgrew it (possibly
                # with zero slack left): degrade LOUDLY to the full
                # AOT-invalidation path (the PR 14 behavior) — re-arm
                # via stream/ingest to restore the recompile-free
                # contract
                log.warning(
                    "graph delta appended %d vertices, OVERFLOWING the "
                    "capacity margin (%d slack rows available): falling "
                    "back to the full AOT-invalidation path",
                    plan.added_vertices, int(feat.shape[0]) - v0,
                )

    toolkits = set()
    ladders = set()
    for eng in engines:
        h = getattr(eng.sampler, "hop_sampler", None)
        if h is not None and id(h) not in hop_samplers:
            rows_patched += h.apply_delta(g, plan.dirty_rows)
            hop_samplers.add(id(h))
        eng.sampler.set_graph(g)
        tk = eng.toolkit
        if id(tk) not in toolkits:
            tk.host_graph = g
            # the tuner/ledger keying follows the live graph: the old
            # cached digest would keep keying decisions to a graph that
            # no longer exists
            tk._tune_graph_digest = plan.digest
            toolkits.add(id(tk))
        if new_feature is not None:
            eng.feature = new_feature
            if not in_margin and id(eng._compiled) not in ladders:
                ladders.add(id(eng._compiled))
                if eng._compiled:
                    log.warning(
                        "graph delta appended %d vertices: the feature "
                        "operand changed shape, invalidating %d AOT bucket "
                        "executable(s) — the next flush per bucket "
                        "recompiles once", plan.added_vertices,
                        len(eng._compiled),
                    )
                eng._compiled.clear()
    if new_feature is not None:
        # the fine-tune worker trains over the SAME slab the engines
        # serve from — keep the shared toolkit's reference current
        for tk_id, tk in {id(e.toolkit): e.toolkit for e in engines}.items():
            tk.feature = new_feature
    plan.rows_patched = rows_patched
    return plan


def apply_to_servers(servers: Sequence, delta: GraphDelta,
                     extra_engines: Sequence = (),
                     plan: Optional[DeltaPlan] = None,
                     dirty_closure=None) -> DeltaPlan:
    """The full between-flushes application over one or many servers
    (the fleet path): compute the plan once, take every server's graph
    gate (no flush is mid-produce while the graph swaps), swap engines,
    invalidate only the dirty embedding-cache entries, refresh hot
    masks, bump graph versions, and emit one ``graph_delta`` record per
    server stream. ``plan``/``dirty_closure`` are the stream ingestor's
    hooks (precomputed plan; approximate dirty closure)."""
    if not servers:
        raise ValueError("apply_to_servers needs at least one server")
    t0 = time.perf_counter()
    base = servers[0].engine
    if plan is None:
        plan = plan_delta(base.sampler.graph, delta, hops=len(base.fanouts),
                          dirty_closure=dirty_closure)
    engines: List = []
    seen = set()
    for eng in [s.engine for s in servers] + list(extra_engines):
        if id(eng) not in seen:
            seen.add(id(eng))
            engines.append(eng)
    with contextlib.ExitStack() as stack:
        for s in servers:
            stack.enter_context(s._graph_gate)
        apply_to_engines(engines, delta, plan=plan)
        rows_patched = plan.rows_patched
        seconds = time.perf_counter() - t0
        for s in servers:
            n_inv = s.cache.invalidate(plan.dirty)
            if s.opts.hot_threshold > 0:
                from neutronstarlite_tpu.parallel.feature_cache import (
                    hot_vertex_mask,
                )

                s.cache.hot_mask = hot_vertex_mask(
                    plan.graph, s.opts.hot_threshold
                )
            s._graph_version += 1
            if s.metrics is not None:
                s.metrics.counter_add("serve.graph_deltas")
                s.metrics.gauge_set("graph.digest", plan.digest)
                fields = dict(
                    added_edges=plan.added_edges,
                    removed_edges=plan.removed_edges,
                    added_vertices=plan.added_vertices,
                    graph_digest=plan.digest,
                    cache_invalidated=int(n_inv),
                    rows_patched=int(rows_patched),
                    dirty_predictions=int(len(plan.dirty)),
                    seconds=float(seconds),
                )
                if getattr(s, "replica", None):
                    fields["replica"] = s.replica
                s.metrics.event("graph_delta", **fields)
    log.info(
        "graph delta applied: +%de -%de +%dv, %d dirty prediction(s), "
        "%d device row(s) patched, digest %s (%.1f ms)",
        plan.added_edges, plan.removed_edges, plan.added_vertices,
        len(plan.dirty), rows_patched, plan.digest[:12],
        (time.perf_counter() - t0) * 1000.0,
    )
    return plan
