"""Micro-batching request queue with deadline/size coalescing + shedding.

The serving analog of the training-side work queue (``Sampler``'s batch
walk, ntsSampler.hpp:125-137): individual per-node prediction requests are
coalesced into padded micro-batches so the device executes the same
fixed-shape AOT executables steady-state training uses. A flush fires when
``max_batch`` seeds have accumulated OR ``max_wait_ms`` has elapsed since
the oldest pending request — whichever comes first — so a lone request
never waits longer than the deadline and a burst fills whole buckets.

Overload policy is explicit: the queue depth is bounded (``max_queue``
pending requests) and a request arriving beyond it is REJECTED with a
reason (a ``shed`` obs record + ``RequestShedError`` on its future) instead
of being enqueued into unbounded latency collapse — the load generator
(tools/serve_bench.py) measures exactly this knee.

All knobs live on :class:`ServeOptions`; each has a cfg key (SERVE_*) and an
``NTS_SERVE_*`` env override (launcher parity with NTS_PARTITIONS_OVERRIDE).
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import threading
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from neutronstarlite_tpu.utils.logging import get_logger

log = get_logger("serve")


def latency_percentiles(samples_ms) -> dict:
    """{p50, p95, p99} (ms, linear-interpolated np.percentile) over RAW
    samples — since the live telemetry plane (obs/hist) this is only the
    FALLBACK definition for pre-histogram streams: the live serve
    surfaces (stats(), serve_summary, serve_bench, metrics_report's
    synthesized summary) all report quantiles from the mergeable
    LogHistogram, which survives rotation and bounds memory. Lives here
    (not server.py) so the report CLI can import it without pulling
    jax."""
    if not samples_ms:
        return {"p50": None, "p95": None, "p99": None}
    arr = np.asarray(list(samples_ms), dtype=np.float64)
    p50, p95, p99 = np.percentile(arr, [50, 95, 99])
    return {"p50": float(p50), "p95": float(p95), "p99": float(p99)}


class RequestShedError(RuntimeError):
    """The server rejected this request under overload (reason attached)."""

    def __init__(self, reason: str):
        super().__init__(f"request shed: {reason}")
        self.reason = reason


def _env_override(name: str, cast, current):
    raw = os.environ.get(name, "")
    if not raw:
        return current
    try:
        return cast(raw)
    except ValueError:
        log.warning("%s=%r is not a valid %s; keeping %r",
                    name, raw, cast.__name__, current)
        return current


@dataclasses.dataclass
class ServeOptions:
    """Every serving knob in one place (docs/SERVING.md has the semantics)."""

    max_batch: int = 16  # flush size == largest AOT shape bucket
    max_wait_ms: float = 5.0  # deadline coalescing window
    max_queue: int = 256  # pending-request bound; beyond it: shed
    buckets: Tuple[int, ...] = ()  # explicit AOT ladder; () = geometric x4
    cache_cap: int = 0  # inference embedding cache entries (0 = disabled)
    cache_max_age_s: float = 60.0  # staleness bound for cached embeddings
    hot_threshold: int = 0  # out-degree >= threshold => cacheable vertex
    sample_pipeline: str = "sync"  # SAMPLE_PIPELINE / NTS_SAMPLE_PIPELINE:
    # sync (sample inside the flush, the parity oracle), pipelined (the
    # flusher samples + stages H2D while a separate executor thread runs
    # the previous flush on the device — serve/server.py two-stage flush),
    # device (pipelined + the on-device uniform hop sampler), fused (a
    # cache miss's sample+execute is ONE dispatch per bucket through the
    # engine's fused ladder — serve/engine.py _fused_forward_fn)
    continuous_batching: bool = False  # SERVE_CB / NTS_SERVE_CB: run the
    # two-stage flush even with sync sampling — the batcher admits and
    # PRODUCES the next bucket (cache pass + sample + H2D staging) while
    # the executor runs the current one, so sustained open-loop load never
    # serializes on flush-wait (p99 under load is what this buys; the
    # sample draws and results are identical to sync — same thread order)

    @classmethod
    def from_cfg(cls, cfg: Any = None) -> "ServeOptions":
        """cfg SERVE_* fields, then NTS_SERVE_* env overrides on top."""
        o = cls()
        if cfg is not None:
            o.max_batch = int(getattr(cfg, "serve_max_batch", o.max_batch))
            o.max_wait_ms = float(
                getattr(cfg, "serve_max_wait_ms", o.max_wait_ms)
            )
            o.max_queue = int(getattr(cfg, "serve_max_queue", o.max_queue))
            if getattr(cfg, "serve_buckets", ""):
                o.buckets = tuple(cfg.serve_bucket_list())
            o.cache_cap = int(getattr(cfg, "serve_cache_cap", o.cache_cap))
            o.cache_max_age_s = float(
                getattr(cfg, "serve_cache_max_age_s", o.cache_max_age_s)
            )
            o.hot_threshold = int(
                getattr(cfg, "serve_hot_threshold", o.hot_threshold)
            )
            o.continuous_batching = bool(
                int(getattr(cfg, "serve_cb", o.continuous_batching))
            )
        o.max_batch = _env_override("NTS_SERVE_MAX_BATCH", int, o.max_batch)
        o.max_wait_ms = _env_override(
            "NTS_SERVE_MAX_WAIT_MS", float, o.max_wait_ms
        )
        o.max_queue = _env_override("NTS_SERVE_MAX_QUEUE", int, o.max_queue)
        raw = os.environ.get("NTS_SERVE_BUCKETS", "")
        if raw:
            try:
                o.buckets = tuple(
                    int(tok) for tok in raw.split("-") if tok
                )
            except ValueError:
                log.warning("NTS_SERVE_BUCKETS=%r unparseable; ignoring", raw)
        o.cache_cap = _env_override("NTS_SERVE_CACHE_CAP", int, o.cache_cap)
        o.cache_max_age_s = _env_override(
            "NTS_SERVE_CACHE_MAX_AGE_S", float, o.cache_max_age_s
        )
        o.hot_threshold = _env_override(
            "NTS_SERVE_HOT_THRESHOLD", int, o.hot_threshold
        )
        raw_cb = os.environ.get("NTS_SERVE_CB", "")
        if raw_cb:
            if raw_cb not in ("0", "1"):
                log.warning("NTS_SERVE_CB=%r is not 0|1; keeping %r",
                            raw_cb, o.continuous_batching)
            else:
                o.continuous_batching = raw_cb == "1"
        # ONE grammar for the selector (env-wins, alias map, validation):
        # sample.pipeline.resolve_sample_pipeline — imported lazily so
        # this module stays importable without jax (metrics_report pulls
        # latency_percentiles at module level)
        from neutronstarlite_tpu.sample.pipeline import (
            resolve_sample_pipeline,
        )

        o.sample_pipeline = resolve_sample_pipeline(cfg)
        if o.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {o.max_batch}")
        if o.max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {o.max_queue}")
        return o

    def ladder(self) -> List[int]:
        """The AOT shape-bucket ladder, ascending, always topped by
        ``max_batch``. Default: geometric x4 (1, 4, 16, ...) — a small
        number of executables covering every flush size with <= 4x padding
        waste, the compile-once discipline of Accel-GCN-style fixed-shape
        execution."""
        if self.buckets:
            out = sorted({int(b) for b in self.buckets if int(b) >= 1})
            if not out:
                raise ValueError(f"no usable buckets in {self.buckets!r}")
            if out[-1] < self.max_batch:
                out.append(self.max_batch)
            return [b for b in out if b <= self.max_batch] or [self.max_batch]
        out = []
        b = 1
        while b < self.max_batch:
            out.append(b)
            b *= 4
        out.append(self.max_batch)
        return out


# process-wide request id sequence: the join key between a request's
# ``serve_request`` record and its lifecycle spans (obs/trace) — unique
# within one stream (ids are per-process, streams are per-process files)
_REQ_IDS = itertools.count()


class ServeRequest:
    """One in-flight request: seed ids + a completion future.

    ``ctx`` (obs/trace.TraceContext or None) is the remote caller's trace
    hop when the request arrived over the wire — the server's lifecycle
    spans parent into it so the replica-side timeline joins the router's
    trace."""

    __slots__ = ("node_ids", "req_id", "t_submit", "t_flush", "t_done",
                 "status", "logits", "error", "ctx", "_done")

    def __init__(self, node_ids: np.ndarray, ctx: Any = None):
        self.node_ids = node_ids
        self.req_id = f"q{next(_REQ_IDS):x}"
        self.t_submit = time.perf_counter()
        self.t_flush: Optional[float] = None
        self.t_done: Optional[float] = None
        self.status = "pending"
        self.logits: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.ctx = ctx
        self._done = threading.Event()

    # -- completion (batcher/server side) ---------------------------------
    def _complete(self, logits: Optional[np.ndarray], status: str,
                  error: Optional[BaseException] = None) -> None:
        self.logits = logits
        self.status = status
        self.error = error
        self.t_done = time.perf_counter()
        self._done.set()

    # -- consumption (client side) ----------------------------------------
    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until served; raises the per-request error (e.g.
        :class:`RequestShedError`) instead of returning garbage."""
        if not self._done.wait(timeout):
            raise TimeoutError("request not served within timeout")
        if self.error is not None:
            raise self.error
        return self.logits

    def done(self) -> bool:
        return self._done.is_set()

    @property
    def total_ms(self) -> Optional[float]:
        if self.t_done is None:
            return None
        return (self.t_done - self.t_submit) * 1000.0

    @property
    def queue_ms(self) -> Optional[float]:
        if self.t_flush is None:
            return None
        return (self.t_flush - self.t_submit) * 1000.0


class MicroBatcher:
    """Bounded request queue + background flusher thread.

    ``flush_fn(requests, reason)`` runs on the flusher thread and must
    complete every request it is handed (the server's `_flush`); an
    exception from it fails that batch's requests, never the thread.
    """

    def __init__(
        self,
        flush_fn: Callable[[List[ServeRequest], str], None],
        options: ServeOptions,
        metrics: Any = None,
        slo: Any = None,
    ):
        self.flush_fn = flush_fn
        self.opts = options
        self.metrics = metrics
        # the SLO burn-rate engine (obs/slo.SloEngine, NTS_SLO_SPEC):
        # when armed, burn-rate shedding is the FIRST admission gate —
        # under sustained overload it fires long before the static
        # max_queue bound below does (the start of SLO-driven routing)
        self.slo = slo
        self._pending: List[ServeRequest] = []
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False
        self._aborted = False
        self.shed_count = 0
        self._thread = threading.Thread(
            target=self._loop, name="serve-batcher", daemon=True
        )
        self._thread.start()

    # ---- client side -----------------------------------------------------
    def submit(self, node_ids: Sequence[int],
               ctx: Any = None) -> ServeRequest:
        """Enqueue one request; never blocks. Overload and malformed input
        reject-with-reason on the returned future. ``ctx`` carries the
        remote caller's TraceContext through to the lifecycle spans."""
        ids = np.asarray(node_ids, dtype=np.int64).reshape(-1)
        req = ServeRequest(ids, ctx=ctx)
        reason = None
        if len(ids) == 0:
            reason = "empty_request"
        elif len(ids) > self.opts.max_batch:
            reason = (
                f"request_too_large ({len(ids)} seeds > max_batch "
                f"{self.opts.max_batch})"
            )
        if reason is None and self.slo is not None:
            # burn-rate gate before the hard bound: while a latency
            # objective is breaching, the effective queue bound shrinks
            # to max_queue / burn (the depth read is advisory — shedding
            # is a heuristic, the hard bound below stays exact)
            reason = self.slo.shed_advice(
                len(self._pending), self.opts.max_queue
            )
        if reason is None:
            with self._cond:
                if self._closed or self._aborted:
                    reason = "server_closed"
                elif len(self._pending) >= self.opts.max_queue:
                    reason = f"queue_full (depth {len(self._pending)})"
                else:
                    self._pending.append(req)
                    self._cond.notify()
        if reason is not None:
            self._shed(req, reason)
        return req

    def _shed(self, req: ServeRequest, reason: str) -> None:
        with self._lock:  # sheds arrive from arbitrary client threads
            self.shed_count += 1
        req._complete(None, "shed", RequestShedError(reason))
        if self.metrics is not None:
            self.metrics.counter_add("serve.shed")
            self.metrics.event(
                "shed", reason=reason, queue_depth=len(self._pending),
                req_id=req.req_id,
            )
            self.metrics.event(
                "serve_request", n_seeds=max(len(req.node_ids), 1),
                status="shed", total_ms=req.total_ms, req_id=req.req_id,
            )

    # ---- fleet-side surface (serve/fleet.py) -----------------------------
    @property
    def depth(self) -> int:
        """Current pending-request count (advisory read — the router's
        queue-depth signal)."""
        return len(self._pending)

    def alive(self) -> bool:
        """Is the flusher thread still running? False after close() drains
        or after an injected death (``abort``)."""
        return self._thread.is_alive()

    def requeue(self, req: ServeRequest) -> None:
        """Re-enqueue a request stolen from a dead replica (fleet
        re-route): size validation already passed at the original submit,
        so only the bound and liveness gates apply; ``t_submit`` is kept,
        so the recorded latency honestly includes the dead time."""
        with self._cond:
            if self._closed or self._aborted:
                reason = "server_closed"
            elif len(self._pending) >= self.opts.max_queue:
                reason = f"queue_full (depth {len(self._pending)}, requeue)"
            else:
                self._pending.append(req)
                self._cond.notify()
                return
        self._shed(req, reason)

    def steal_pending(self) -> List[ServeRequest]:
        """Take every pending request (the fleet re-routes them after a
        replica death — in-flight work is re-routed, never dropped)."""
        with self._cond:
            out = self._pending
            self._pending = []
        return out

    def abort(self) -> None:
        """Chaos hook: kill the flusher thread WITHOUT draining — the
        simulated dead replica. Pending requests stay queued for
        ``steal_pending``; new submits shed with server_closed."""
        with self._cond:
            self._aborted = True
            self._cond.notify_all()

    # ---- flusher thread --------------------------------------------------
    def _take_batch(self) -> Tuple[List[ServeRequest], str]:
        """Block until a flush condition holds; pop one batch under lock."""
        with self._cond:
            while True:
                if self._aborted:
                    return [], "abort"
                if self._pending:
                    n_seeds = sum(len(r.node_ids) for r in self._pending)
                    deadline = (
                        self._pending[0].t_submit
                        + self.opts.max_wait_ms / 1000.0
                    )
                    now = time.perf_counter()
                    if n_seeds >= self.opts.max_batch:
                        return self._pop_upto(), "size"
                    if self._closed:
                        return self._pop_upto(), "drain"
                    if now >= deadline:
                        return self._pop_upto(), "deadline"
                    self._cond.wait(timeout=deadline - now)
                elif self._closed:
                    return [], "stop"
                else:
                    self._cond.wait()

    def _pop_upto(self) -> List[ServeRequest]:
        """Pop requests FIFO until the next one would overflow max_batch
        seeds (each request fits alone — submit() rejected larger ones)."""
        out: List[ServeRequest] = []
        seeds = 0
        while self._pending:
            n = len(self._pending[0].node_ids)
            if out and seeds + n > self.opts.max_batch:
                break
            req = self._pending.pop(0)
            seeds += n
            out.append(req)
        return out

    def _loop(self) -> None:
        while True:
            batch, reason = self._take_batch()
            if not batch:
                return  # "stop": closed and drained
            t_flush = time.perf_counter()
            for r in batch:
                r.t_flush = t_flush
            try:
                self.flush_fn(batch, reason)
            except BaseException as e:  # a bad batch must not kill serving
                log.warning("flush failed (%s): %s", type(e).__name__, e)
                for r in batch:
                    if not r.done():
                        r._complete(None, "error", e)

    def close(self, timeout: float = 30.0) -> None:
        """Drain pending requests (flushed with reason "drain") and stop."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout)
