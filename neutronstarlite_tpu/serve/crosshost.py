"""Cross-host serve fabric: process-level replicas behind an HTTP router.

PR 14's :class:`~neutronstarlite_tpu.serve.fleet.ReplicaSet` is N threads
in one process sharing one device — "millions of users" needs replicas as
separate PROCESSES (each owning its own device or mesh slice, each
killable by a real OOM/preemption) and a router that treats a machine
dying as routine. This module supplies both halves:

**The replica child** (``python -m neutronstarlite_tpu.serve.crosshost
--child ...``) is a long-running serve process: one
:class:`~neutronstarlite_tpu.serve.engine.InferenceEngine` restored from
a digest-verified checkpoint, AOT bucket ladder warmed from persisted
state (tune cache + SERVE_BUCKETS — the compile-warm handoff that
replaces PR 14's in-process clone), fronted by an
:class:`~neutronstarlite_tpu.serve.server.InferenceServer` whose
exporter port carries BOTH planes: the PR 11/16 scrape surfaces
(/metrics /healthz /slo /telemetry) and a ``POST /predict`` data plane
(obs/exporter.bind_predict). One host:port per replica — the
``NTS_FLEET_TARGETS`` grammar stays a single address. The child writes
``{"port", "pid", "replica"}`` atomically to its ``--port-file`` once
serving, and exits cleanly on SIGTERM (drain + ``serve_summary``).

**The router** (:class:`CrossHostFleet`, CLI: tools/serve_router)
discovers replicas from ``NTS_FLEET_TARGETS`` (routing/telemetry only)
or spawns N children itself (full supervision — it records each child's
:class:`LaunchRecipe`). It generalizes PR 14's routing UNCHANGED —
``choose_replica``/``classify_states`` are imported from serve/fleet —
over state scraped instead of shared: one ``/telemetry`` fetch per
replica per poll (through obs/httpc, the shared retrying client)
supplies liveness (the embedded health payload), queue depth (gauges),
drain/burn (``slo_status`` records, sheddable-metric math mirroring
obs/slo.route_state) and the fleet p99 (native-bucket ``hist`` records
merged by the exact bucket-addition law — the PR 16 hub IS the poll
engine here, so miss-K ``target_loss`` latching, frozen histograms,
``recovery action=target_rejoin`` and ``kind=fleet`` ledger rows come
with it). Fleet-level shed (``fleet_breach``) happens only when ALL
live replicas breach; a dead replica's owed requests re-route — a
refused/timed-out POST retries against the next live replica, never
drops.

**Supervised process restart**: a replica that misses
``miss_k`` consecutive polls is a typed ``target_loss`` (the PR 16
contract) ESCALATED — the router respawns it from its recorded launch
recipe (cfg + checkpoint + inherited tune-cache/SERVE_BUCKETS env, so
the new process comes up compile-warm from persisted state), re-points
the telemetry target at the new port, and emits the existing
``recovery action=restart`` record. Targets-mode fleets (no recipe)
keep the loss as a target_loss and serve on the survivors.

**Rolling model rollout**: ``rollout(ckpt_dir)``
1. PREFLIGHTS the candidate (tools/verify_checkpoint.preflight_checkpoint
   — manifest schema + sha256 digests of the newest step; a corrupt
   candidate is refused with zero replicas restarted),
2. CANARY-GATES it: the router builds the candidate and the serving
   model side by side (same rng seed, same call order — the engine's
   rng-neutral replay idiom, so identical neighborhoods are sampled)
   and shadow-evals mirrored traffic; the relative-RMS disagreement
   must stay inside ``NTS_CANARY_TOL`` (a ``model_drift`` record with
   ``source="canary"`` carries the evidence — the PR 13 auditor as
   promotion gate),
3. then drains and restarts replicas ONE AT A TIME (the fleet never
   stops answering): mark expected-down (the router's fetch serves the
   frozen last-good snapshot to the hub, so an INTENTIONAL restart
   never burns misses or tears the merged-p99 trajectory), wait out
   in-flight requests, SIGTERM, respawn from the recipe with the new
   checkpoint, wait for the port file, resume routing.
A failed canary refuses before any restart; a mid-rollout replica
death or ``close()`` ABORTS and rolls already-updated replicas back to
the old checkpoint. Exactly one typed ``rollout`` record per call
carries the verdict (promoted | preflight_reject | canary_reject |
aborted | refused) and the canary evidence.

Knobs: ``NTS_FLEET_TARGETS`` (comma-separated host:port or URLs),
``NTS_CANARY_TOL`` (relative-RMS gate, default 0.05),
``NTS_CANARY_SEEDS`` (mirror batches to shadow-eval, default 8),
``NTS_ROUTER_WORKERS`` (dispatch threads, default 8),
``NTS_HTTPC_*`` (the shared client), plus the hub's ``NTS_HUB_MISS_K``
and serve/fleet's ``NTS_SERVE_ROUTE*`` family. docs/SERVING.md has the
full table; docs/RESILIENCE.md pins the rollout-abort contract.
"""

from __future__ import annotations

import argparse
import collections
import dataclasses
import json
import os
import queue as queue_mod
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from neutronstarlite_tpu.obs import httpc, registry as obs_registry
from neutronstarlite_tpu.obs.hub import TelemetryHub
from neutronstarlite_tpu.obs.trace import TraceContext, Tracer
from neutronstarlite_tpu.serve.batcher import RequestShedError, ServeRequest
from neutronstarlite_tpu.serve.fleet import (
    FleetOptions,
    choose_replica,
)
from neutronstarlite_tpu.utils.logging import get_logger

log = get_logger("serve")

DEFAULT_CANARY_TOL = 0.05
DEFAULT_CANARY_SEEDS = 8
DEFAULT_POLL_S = 0.5
DEFAULT_PREDICT_TIMEOUT_S = 60.0
DEFAULT_SPAWN_TIMEOUT_S = 180.0
DEFAULT_DRAIN_TIMEOUT_S = 30.0


# ---- knobs ------------------------------------------------------------------


def fleet_targets() -> List[str]:
    """``NTS_FLEET_TARGETS``: comma-separated replica addresses, each
    ``host:port`` or a full base URL (ONE port per replica — it carries
    /predict and every scrape surface)."""
    raw = os.environ.get("NTS_FLEET_TARGETS", "")
    return [t.strip() for t in raw.split(",") if t.strip()]


def canary_tol() -> float:
    """``NTS_CANARY_TOL``: max relative-RMS logit disagreement between
    the candidate and the serving model on mirrored traffic (the
    drift_threshold pattern)."""
    raw = os.environ.get("NTS_CANARY_TOL", "")
    if not raw:
        return DEFAULT_CANARY_TOL
    try:
        return max(float(raw), 0.0)
    except ValueError:
        log.warning("bad NTS_CANARY_TOL=%r; using %g", raw,
                    DEFAULT_CANARY_TOL)
        return DEFAULT_CANARY_TOL


def _env_int(name: str, default: int, lo: int = 1) -> int:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return max(int(raw), lo)
    except ValueError:
        log.warning("bad %s=%r; using %d", name, raw, default)
        return default


def normalize_base(target: str) -> str:
    """``host:port`` / URL -> base URL with no trailing slash or path."""
    t = target.strip().rstrip("/")
    if not t.startswith("http://") and not t.startswith("https://"):
        t = f"http://{t}"
    return t


def _metric_sheddable(metric: str) -> bool:
    """Whether an ``slo_status.metric`` name is a sheddable objective —
    the same serve/queue-latency-quantile rule obs/slo applies when it
    parses NTS_SLO_SPEC, here applied to the scraped verdict."""
    from neutronstarlite_tpu.obs import slo as slo_mod

    m = slo_mod._QUANTILE_RE.fullmatch(metric)
    if not m:
        return False
    entry = slo_mod._QUANTILE_METRICS.get(m.group("base"))
    return bool(entry and entry[1])


# ---------------------------------------------------------------------------
# the replica child process
# ---------------------------------------------------------------------------


def _write_port_file(path: str, payload: Dict[str, Any]) -> None:
    """Atomic publish (tmp + rename): a reader never sees a torn file."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(payload, fh)
    os.replace(tmp, path)


def child_main(argv=None) -> int:
    """The long-running replica process: serve until SIGTERM/SIGINT."""
    from neutronstarlite_tpu.utils.config import InputInfo
    from neutronstarlite_tpu.utils.platform import honor_platform_env

    honor_platform_env()
    ap = argparse.ArgumentParser(
        description="cross-host serve replica: load a checkpoint, serve "
        "POST /predict + scrape surfaces on one exporter port until "
        "SIGTERM"
    )
    ap.add_argument("cfg")
    ap.add_argument("ckpt", nargs="?", default="")
    ap.add_argument("--replica", default="r0")
    ap.add_argument("--port-file", default="",
                    help="write {port,pid,replica} JSON here once serving")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--predict-timeout-s", type=float,
                    default=DEFAULT_PREDICT_TIMEOUT_S)
    args = ap.parse_args(argv)

    if not os.environ.get("NTS_METRICS_PORT", ""):
        # the exporter IS this process's front door; without it there is
        # nothing to serve on (0 = ephemeral, published via --port-file)
        os.environ["NTS_METRICS_PORT"] = "0"

    from neutronstarlite_tpu.serve.engine import InferenceEngine, \
        ServeSetupError
    from neutronstarlite_tpu.serve.server import InferenceServer

    cfg = InputInfo.read_from_cfg_file(args.cfg)
    base_dir = os.path.dirname(os.path.abspath(args.cfg))
    try:
        engine = InferenceEngine.from_config(
            cfg, base_dir=base_dir, ckpt_dir=args.ckpt,
            rng=np.random.default_rng(args.seed),
        )
    except ServeSetupError as e:
        print(f"serve replica {args.replica}: {e}", file=sys.stderr)
        return 2
    # NTS_STREAM_LOG: follow a shared DeltaLog — the margin must be
    # reserved BEFORE warmup so in-margin appends never touch the ladder
    stream_root = os.environ.get("NTS_STREAM_LOG", "")
    ingestor = None
    if stream_root:
        from neutronstarlite_tpu.stream.ingest import StreamIngestor

        ingestor = StreamIngestor([engine], log_root=stream_root)
        ingestor.arm()
    engine.warmup()
    server = InferenceServer(engine, replica=args.replica)
    reg = server.metrics
    if reg is not None:
        # the router derives depth/capacity from the scraped gauges —
        # publish the static bound once, the live depth per request
        reg.gauge_set("serve.max_queue", server.opts.max_queue)
        reg.gauge_set("serve.queue_depth", server.batcher.depth)
    exporter = server.exporter
    if exporter is None:
        print(f"serve replica {args.replica}: no exporter "
              "(NTS_METRICS_PORT unset/unbindable) — nothing to serve on",
              file=sys.stderr)
        server.close()
        return 2

    predict_timeout = max(float(args.predict_timeout_s), 1.0)
    # freshness lineage for the request spans: which delta-log seq (the
    # stream ingestor's applied head; 0 for a static graph) answered
    if ingestor is not None:
        server.graph_seq_source = lambda: ingestor.applied_seq
    else:
        server.graph_seq_source = lambda: 0

    def _predict(payload: Dict[str, Any],
                 ctx=None) -> Tuple[int, Dict[str, Any]]:
        ids = payload.get("node_ids")
        if not isinstance(ids, list) or not ids or not all(
            isinstance(i, int) and not isinstance(i, bool) for i in ids
        ):
            return 400, {"error": "node_ids must be a non-empty list of "
                                  "ints"}
        node_ids = np.asarray(ids, dtype=np.int64)
        replay = payload.get("replay_seed")
        if replay is not None:
            # deterministic replay probe (the bitwise oracle / canary
            # leg): pin the sampler rng for exactly this prediction, then
            # restore — rng-neutral like the engine's own compile draw,
            # serialized against flush sampling by the graph gate
            with server._graph_gate:
                gen = engine.sampler.rng
                saved = gen.bit_generator.state
                gen.bit_generator.state = np.random.default_rng(
                    int(replay)
                ).bit_generator.state
                try:
                    vals = engine.predict(node_ids)
                finally:
                    gen.bit_generator.state = saved
            return 200, {"status": "ok", "values": vals.tolist(),
                         "dtype": str(vals.dtype), "replay": True,
                         "ckpt_step": engine.ckpt_step,
                         "replica": args.replica}
        req = server.submit(node_ids, ctx=ctx)
        if reg is not None:
            reg.gauge_set("serve.queue_depth", server.batcher.depth)
        try:
            vals = req.result(timeout=predict_timeout)
        except RequestShedError as e:
            return 503, {"status": "shed", "error": str(e),
                         "replica": args.replica}
        except TimeoutError as e:
            return 504, {"status": "timeout", "error": str(e),
                         "replica": args.replica}
        except Exception as e:
            return 500, {"status": "error", "error": str(e),
                         "replica": args.replica}
        finally:
            if reg is not None:
                reg.gauge_set("serve.queue_depth", server.batcher.depth)
        return 200, {"status": "ok", "values": vals.tolist(),
                     "dtype": str(vals.dtype), "req_id": req.req_id,
                     "ckpt_step": engine.ckpt_step,
                     "replica": args.replica}

    exporter.bind_predict(_predict)

    stop = threading.Event()

    def _on_signal(_sig, _frm):
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    if ingestor is not None:
        # tail the shared log: every replica applies the same committed
        # total order, so the whole fleet converges on the same per-seq
        # digests without any cross-replica coordination
        ingestor.servers = [server]
        poll_s = max(
            float(os.environ.get("NTS_STREAM_POLL_S", "0.5") or 0.5), 0.01
        )

        def _tail():
            while not stop.is_set():
                try:
                    applied = ingestor.consume()
                except Exception:
                    # divergence / corruption is permanent for this
                    # replica: stop applying (stale but consistent
                    # serving beats silently-wrong graphs), keep serving
                    log.exception(
                        "replica %s: stream tail failed at seq %d; "
                        "serving freezes on the last applied graph",
                        args.replica, ingestor.applied_seq,
                    )
                    return
                if applied:
                    log.info(
                        "replica %s: applied %d stream entries, head "
                        "seq %d", args.replica, len(applied),
                        ingestor.applied_seq,
                    )
                stop.wait(poll_s)

        threading.Thread(
            target=_tail, name="stream-tail", daemon=True
        ).start()

    if args.port_file:
        _write_port_file(args.port_file, {
            "port": exporter.port, "pid": os.getpid(),
            "replica": args.replica, "ckpt_step": engine.ckpt_step,
        })
    log.info("replica %s serving ckpt step %d on port %d (pid %d)",
             args.replica, engine.ckpt_step, exporter.port, os.getpid())
    stop.wait()
    exporter.bind_predict(None)
    server.close()
    return 0


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------


# Env the fabric's observability depends on, pinned INTO each recipe at
# spawn: ``LaunchRecipe.env()`` re-reads ``os.environ`` at every respawn,
# so a router whose environment mutated between spawn and a supervised
# restart (or a rollout respawn) would silently hand the new child a
# different tracing config — a restarted replica must keep emitting spans
# into its own stream (the restart-then-trace test pins this).
_TRACE_ENV_KEYS = ("NTS_TRACE", "NTS_METRICS_DIR", "NTS_TRACE_STEP")


def _pin_trace_env(extra_env: Dict[str, str]) -> Dict[str, str]:
    """Snapshot the spawn-time tracing env into ``extra_env`` (explicit
    caller-provided values win)."""
    for key in _TRACE_ENV_KEYS:
        if key not in extra_env and key in os.environ:
            extra_env[key] = os.environ[key]
    return extra_env


@dataclasses.dataclass
class LaunchRecipe:
    """Everything needed to (re)spawn one replica child compile-warm:
    the cfg, the checkpoint, the replica identity, and the env the child
    inherits (tune-cache dir, SERVE_BUCKETS, NTS_METRICS_DIR, SLO spec —
    persisted state, not in-process handles)."""

    cfg_path: str
    ckpt_dir: str
    replica: str
    seed: int
    port_file: str
    extra_env: Dict[str, str] = dataclasses.field(default_factory=dict)

    def argv(self) -> List[str]:
        return [
            sys.executable, "-m", "neutronstarlite_tpu.serve.crosshost",
            self.cfg_path, self.ckpt_dir,
            "--replica", self.replica,
            "--port-file", self.port_file,
            "--seed", str(self.seed),
        ]

    def env(self) -> Dict[str, str]:
        e = dict(os.environ)
        e.update(self.extra_env)
        e["NTS_METRICS_PORT"] = "0"  # ephemeral; published via port_file
        return e


class _RouterReplica:
    """One routed endpoint: address + (spawn mode) process and recipe."""

    def __init__(self, idx: int, base_url: str = "",
                 recipe: Optional[LaunchRecipe] = None,
                 proc: Optional[subprocess.Popen] = None):
        self.idx = idx
        self.rid = f"r{idx}"
        self.base_url = base_url
        self.recipe = recipe
        self.proc = proc
        self.ckpt_dir = recipe.ckpt_dir if recipe is not None else None
        self.restarts = 0
        self.respawn_failures = 0
        self.expected_down = False  # rollout maintenance window
        self.cached_body: Optional[str] = None  # last good /telemetry
        self.suspect_until = 0.0  # routing cooldown after a failed POST
        self.in_flight = 0

    @property
    def telemetry_url(self) -> str:
        return f"{self.base_url}/telemetry"

    @property
    def predict_url(self) -> str:
        return f"{self.base_url}/predict"


class CrossHostFleet:
    """N replica processes behind one ``submit()``, routed over HTTP."""

    def __init__(self, replicas: List[_RouterReplica], *,
                 options: Optional[FleetOptions] = None,
                 registry=None,
                 ledger_dir: Optional[str] = None,
                 ledger_every: int = 1,
                 poll_s: float = DEFAULT_POLL_S,
                 miss_k: Optional[int] = None,
                 predict_timeout_s: float = DEFAULT_PREDICT_TIMEOUT_S,
                 spawn_timeout_s: float = DEFAULT_SPAWN_TIMEOUT_S,
                 drain_timeout_s: float = DEFAULT_DRAIN_TIMEOUT_S,
                 fetch: Optional[Callable[[str], str]] = None,
                 start_polling: bool = True):
        if not replicas:
            raise ValueError("CrossHostFleet needs at least one replica "
                             "(NTS_FLEET_TARGETS or spawn())")
        self.replicas = replicas
        self.options = options or FleetOptions()
        self.registry = registry or obs_registry.open_run("router")
        self._owns_registry = registry is None
        self.tracer = Tracer(self.registry)
        self.predict_timeout_s = float(predict_timeout_s)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self._fetch_impl = fetch  # None -> the shared retrying client
        self._closed = False
        self._lock = threading.Lock()  # replica bookkeeping + sticky
        self._proc_lock = threading.Lock()  # spawn/kill serialization
        self._sticky: Optional[int] = None
        self._rollout_lock = threading.Lock()
        self._rollout_active = False
        # the mirror buffer: recent request seed-id batches, the canary's
        # shadow traffic (deterministic fallback when traffic was thin)
        self._mirror: "collections.deque[List[int]]" = collections.deque(
            maxlen=32
        )
        self.hub = TelemetryHub(
            [r.telemetry_url for r in replicas],
            poll_s=poll_s, miss_k=miss_k, registry=self.registry,
            ledger_dir=ledger_dir, ledger_every=ledger_every,
            fetch=self._fetch,
        )
        self._url_to_idx: Dict[str, int] = {
            t.url: i for i, t in enumerate(self.hub.targets)
        }
        self.registry.gauge_set("fleet.replicas", len(replicas))
        # dispatcher pool: workers re-route owed requests across replicas
        self._dispatch_q: "queue_mod.Queue[Optional[ServeRequest]]" = \
            queue_mod.Queue()
        self._workers = [
            threading.Thread(target=self._worker_loop,
                             name=f"router-dispatch-{i}", daemon=True)
            for i in range(_env_int("NTS_ROUTER_WORKERS", 8))
        ]
        for w in self._workers:
            w.start()
        self._poll_stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None
        try:
            self.hub.poll_once()  # routing state before the first request
        except Exception as e:  # pragma: no cover - poll never raises
            log.warning("router: initial poll failed (%s)", e)
        if start_polling:
            self._poll_thread = threading.Thread(
                target=self._poll_loop, name="router-poll", daemon=True
            )
            self._poll_thread.start()

    # ---- construction ----------------------------------------------------

    @classmethod
    def from_targets(cls, targets: Optional[List[str]] = None,
                     **kw) -> "CrossHostFleet":
        """Discovery mode: route over already-running replicas
        (``NTS_FLEET_TARGETS`` when ``targets`` is None). No launch
        recipes — a dead replica stays a ``target_loss`` and the fleet
        serves on the survivors; rollout() is refused."""
        targets = fleet_targets() if targets is None else targets
        if not targets:
            raise ValueError(
                "no replica targets: set NTS_FLEET_TARGETS "
                "(host:port,host:port,...) or use spawn()"
            )
        reps = [_RouterReplica(i, normalize_base(t))
                for i, t in enumerate(targets)]
        return cls(reps, **kw)

    @classmethod
    def spawn(cls, cfg_path: str, ckpt_dir: str, replicas: int = 3, *,
              spawn_dir: Optional[str] = None, seed: int = 0,
              extra_env: Optional[Dict[str, str]] = None,
              spawn_timeout_s: float = DEFAULT_SPAWN_TIMEOUT_S,
              **kw) -> "CrossHostFleet":
        """Supervision mode: fork N replica children (concurrently —
        they warm their AOT ladders in parallel), record each child's
        :class:`LaunchRecipe`, and wait for every port file. Children
        that fail to come up are killed and the error raised — spawn
        never leaks a process."""
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        cfg_path = os.path.abspath(cfg_path)
        ckpt_dir = os.path.abspath(ckpt_dir)
        spawn_dir = spawn_dir or tempfile.mkdtemp(prefix="nts-crosshost-")
        os.makedirs(spawn_dir, exist_ok=True)
        reps: List[_RouterReplica] = []
        try:
            for i in range(replicas):
                recipe = LaunchRecipe(
                    cfg_path=cfg_path, ckpt_dir=ckpt_dir, replica=f"r{i}",
                    seed=seed + i,
                    port_file=os.path.join(spawn_dir, f"r{i}.port.json"),
                    # pin the SPAWN-TIME tracing env into the recipe so a
                    # supervised restart / rollout respawn (which re-reads
                    # os.environ) keeps the child's trace config stable
                    extra_env=_pin_trace_env(dict(extra_env or {})),
                )
                r = _RouterReplica(i, recipe=recipe)
                r.proc = _spawn_child(recipe)
                reps.append(r)
            deadline = time.monotonic() + spawn_timeout_s
            for r in reps:
                info = _wait_port_file(r.recipe.port_file, r.proc, deadline)
                r.base_url = f"http://127.0.0.1:{info['port']}"
        except Exception:
            for r in reps:
                _reap(r.proc)
            raise
        return cls(reps, spawn_timeout_s=spawn_timeout_s, **kw)

    # ---- telemetry fetch (the hub's injected fetch) ----------------------

    def _fetch(self, url: str) -> str:
        with self._lock:
            idx = self._url_to_idx.get(url)
        if idx is None:  # a stale URL raced a restart: miss, self-heals
            raise httpc.HttpRefused(f"router: unknown target {url}")
        r = self.replicas[idx]
        if r.expected_down and r.cached_body is not None:
            # an INTENTIONAL (rollout) down: the hub keeps seeing the
            # frozen last-good snapshot — no misses, no target_loss, an
            # unbroken merged-histogram trajectory across the restart
            return r.cached_body
        if self._fetch_impl is not None:
            body = self._fetch_impl(url)
        else:
            body = httpc.fetch(url, target=idx,
                               deadline_s=httpc.http_timeout_s() * 2,
                               tracer=self.tracer,
                               span_name="telemetry_poll")
        r.cached_body = body
        return body

    # ---- routing state from scraped records ------------------------------

    def _derive_state(self, r: _RouterReplica, t) -> Dict[str, Any]:
        beating = False
        depth = 0
        max_queue = 64
        draining = False
        burn = 0.0
        tel = None
        records = t.records
        for rec in records:
            if rec.get("event") == "telemetry":
                tel = rec
        if tel is not None:
            health = tel.get("health") or {}
            beating = bool(health.get("ok"))
            serve = health.get("serve") or {}
            if serve.get("beating") is False:
                beating = False
            gauges = tel.get("gauges") or {}
            try:
                depth = int(gauges.get("serve.queue_depth") or 0)
                max_queue = int(gauges.get("serve.max_queue") or max_queue)
            except (TypeError, ValueError):
                pass
        latest: Dict[tuple, Dict[str, Any]] = {}
        for rec in records:
            if rec.get("event") == "slo_status":
                latest[(rec.get("run_id"), rec.get("objective"))] = rec
        for rec in latest.values():
            if not _metric_sheddable(str(rec.get("metric") or "")):
                continue
            try:
                burn = max(burn, float(rec.get("burn_rate") or 0.0))
            except (TypeError, ValueError):
                pass
            if rec.get("state") == "breach":
                draining = True
        if t.lost or r.expected_down or time.monotonic() < r.suspect_until:
            beating = False
        return {"idx": r.idx, "beating": beating, "draining": draining,
                "burn": burn, "depth": depth, "max_queue": max_queue}

    def route_states(self) -> List[Dict[str, Any]]:
        return [self._derive_state(r, t)
                for r, t in zip(self.replicas, self.hub.targets)]

    def _route(self, states) -> Tuple[Optional[int], Optional[str]]:
        with self._lock:
            idx, reason = choose_replica(
                states, self._sticky, self.options.hysteresis
            )
            self._sticky = idx
            return idx, reason

    # ---- the front door --------------------------------------------------

    def submit(self, node_ids) -> ServeRequest:
        """Enqueue one request; the dispatcher routes (and re-routes) it
        over HTTP. Overload/closure rejects with RequestShedError on the
        future — owed requests are otherwise never dropped."""
        req = ServeRequest(np.asarray(node_ids, dtype=np.int64).reshape(-1))
        if self._closed:
            self._shed(req, "fleet_closed")
            return req
        self._dispatch_q.put(req)
        return req

    def predict(self, node_ids,
                timeout: Optional[float] = None) -> np.ndarray:
        return self.submit(node_ids).result(
            timeout if timeout is not None else self.predict_timeout_s + 5.0
        )

    def _shed(self, req: ServeRequest, reason: str, ctx=None) -> None:
        self.registry.counter_add("fleet.sheds", 1.0)
        try:
            if ctx is not None:
                self.tracer.complete("shed", dur_s=0.0, cat="router",
                                     ctx=ctx, req_id=req.req_id,
                                     reason=reason)
            self.registry.event("shed", reason=reason, req_id=req.req_id)
            self.registry.event(
                "serve_request", n_seeds=max(len(req.node_ids), 1),
                status="shed", total_ms=None, req_id=req.req_id,
            )
        except Exception as e:  # telemetry must not break the reply
            log.warning("router: shed record failed (%s)", e)
        req._complete(None, "shed", RequestShedError(reason))

    def _worker_loop(self) -> None:
        while True:
            req = self._dispatch_q.get()
            if req is None:
                return
            try:
                self._dispatch(req)
            except Exception as e:  # a reply must always land
                if not req.done():
                    req._complete(None, "error", e)

    def _dispatch(self, req: ServeRequest) -> None:
        deadline = time.monotonic() + self.predict_timeout_s
        tried: set = set()
        shed_seen = False
        # per-request trace: trace_id = run_id:req_id so every span this
        # request produces — router-side, httpc's predict_post, and the
        # replica's handler/request/queue spans across the wire — joins
        # on one id in the fleet-merged timeline
        tracing = self.tracer.enabled
        root_id = None
        root_ctx = None
        if tracing:
            trace_id = f"{self.registry.run_id}:{req.req_id}"
            root_id = self.tracer.next_id()
            root_ctx = TraceContext(trace_id, root_id)

        def _root_done(status: str, **extra) -> None:
            if not tracing:
                return
            self.tracer.complete(
                "fleet_request",
                dur_s=time.perf_counter() - req.t_submit,
                t0=req.t_submit, cat="router", span_id=root_id,
                ctx=TraceContext(root_ctx.trace_id, None),
                req_id=req.req_id, status=status,
                n_seeds=len(req.node_ids), **extra,
            )

        while True:
            if self._closed:
                self._shed(req, "fleet_closed", ctx=root_ctx)
                _root_done("shed", reason="fleet_closed")
                return
            states = self.route_states()
            fresh = [s for s in states if s["idx"] not in tried]
            is_reroute = bool(tried)
            t_route = time.perf_counter()
            idx, reason = self._route(fresh if fresh else states)
            if tracing:
                self.tracer.complete(
                    "re_route" if is_reroute else "route_decision",
                    dur_s=time.perf_counter() - t_route, t0=t_route,
                    cat="router", ctx=root_ctx, req_id=req.req_id,
                    target=idx, reason=reason,
                )
            if idx is not None and idx in tried:
                # every replica has already failed this request once;
                # this is a fresh pass (bounded by the deadline)
                tried.clear()
            if idx is None:
                if tried:
                    # the untried subset looks unroutable, but a replica
                    # we already tried may have recovered — re-evaluate
                    # over the whole fleet before any shed verdict
                    tried.clear()
                    continue
                if reason and reason.startswith("fleet_breach"):
                    # the SLO contract: all live replicas breaching is
                    # the ONLY load-based fleet-level shed
                    self._shed(req, reason, ctx=root_ctx)
                    _root_done("shed", reason=reason)
                    return
                if time.monotonic() >= deadline:
                    shed_reason = (
                        "replica_queues_full (every live replica shed)"
                        if shed_seen else (reason or "fleet_down")
                    )
                    self._shed(req, shed_reason, ctx=root_ctx)
                    _root_done("shed", reason=shed_reason)
                    return
                nap = min(self.hub.poll_s, 0.2) or 0.05
                time.sleep(nap)
                if tracing:
                    self.tracer.complete(
                        "backoff", dur_s=nap, cat="router", ctx=root_ctx,
                        req_id=req.req_id, reason=reason,
                    )
                tried.clear()
                continue
            r = self.replicas[idx]
            budget = deadline - time.monotonic()
            if budget <= 0:
                self._shed(req, "dispatch_deadline", ctx=root_ctx)
                _root_done("shed", reason="dispatch_deadline")
                return
            with self._lock:
                r.in_flight += 1
            try:
                body = httpc.fetch(
                    r.predict_url,
                    data=json.dumps({
                        "node_ids": [int(i) for i in req.node_ids],
                        "req_id": req.req_id,
                    }).encode("utf-8"),
                    retries=0,  # a POST is not idempotent on a live
                    # replica: re-dispatch is OURS, across replicas
                    timeout_s=min(self.predict_timeout_s, budget),
                    target=idx,
                    tracer=self.tracer, ctx=root_ctx,
                    span_name="predict_post",
                )
            except httpc.HttpStatusError as e:
                with self._lock:
                    r.in_flight -= 1
                if e.status in (503, 429):
                    shed_seen = True  # replica-level shed: route around
                else:
                    log.warning("router: replica %s POST failed (%s)",
                                r.rid, e)
                tried.add(idx)
                continue
            except httpc.HttpError as e:
                with self._lock:
                    r.in_flight -= 1
                # refused/timeout: the replica may be dead — cool it down
                # for a poll and RE-ROUTE the owed request
                cooldown = max(self.hub.poll_s, 0.2)
                r.suspect_until = time.monotonic() + cooldown
                if tracing:
                    self.tracer.complete(
                        "suspect", dur_s=0.0, cat="router", ctx=root_ctx,
                        req_id=req.req_id, target=idx,
                        error=httpc.error_class(e), cooldown_s=cooldown,
                    )
                log.warning("router: replica %s unreachable (%s); "
                            "re-routing %s", r.rid, e, req.req_id)
                tried.add(idx)
                continue
            with self._lock:
                r.in_flight -= 1
            r.suspect_until = 0.0
            try:
                out = json.loads(body)
                vals = np.asarray(out["values"],
                                  dtype=np.dtype(out.get("dtype",
                                                         "float32")))
            except (ValueError, KeyError, TypeError) as e:
                log.warning("router: replica %s returned a bad predict "
                            "payload (%s)", r.rid, e)
                tried.add(idx)
                continue
            self.registry.counter_add("fleet.requests", 1.0)
            self._mirror.append([int(i) for i in req.node_ids])
            req._complete(vals, "ok")
            _root_done("ok", target=idx,
                       replica_req_id=str(out.get("req_id") or ""))
            return

    # ---- polling + supervision -------------------------------------------

    def _poll_loop(self) -> None:
        while not self._poll_stop.wait(self.hub.poll_s):
            if self._closed:
                return
            try:
                self.hub.poll_once()
            except Exception as e:  # pragma: no cover - poll never raises
                log.warning("router: poll failed (%s)", e)
            try:
                self._supervise()
            except Exception as e:
                log.warning("router: supervision pass failed (%s)", e)

    def _supervise(self) -> None:
        """Escalate the hub's miss-K verdicts: a LOST spawned replica is
        respawned from its recipe (recovery action=restart); targets-mode
        losses stay target_loss-only."""
        if self._rollout_active or self._closed:
            return
        for r, t in zip(self.replicas, self.hub.targets):
            if not t.lost or r.expected_down or r.recipe is None:
                continue
            if r.respawn_failures >= 3:
                continue  # gave up on this one; the record trail says so
            self._restart_replica(r, reason="target_loss")

    def _restart_replica(self, r: _RouterReplica, reason: str) -> bool:
        """Supervised process restart from the recorded launch recipe."""
        t_restart = time.perf_counter()
        old_url = r.base_url
        with self._lock:
            owed = r.in_flight
        recipe = dataclasses.replace(
            r.recipe, ckpt_dir=r.ckpt_dir or r.recipe.ckpt_dir
        )
        try:
            with self._proc_lock:
                if self._closed:
                    return False
                _reap(r.proc)
                r.proc = None
                if os.path.exists(recipe.port_file):
                    os.remove(recipe.port_file)
                r.proc = _spawn_child(recipe)
            info = _wait_port_file(
                recipe.port_file, r.proc,
                time.monotonic() + self.spawn_timeout_s,
            )
        except Exception as e:
            r.respawn_failures += 1
            log.warning("router: respawn of %s failed (%s) — attempt %d",
                        r.rid, e, r.respawn_failures)
            with self._proc_lock:
                _reap(r.proc)
                r.proc = None
            if self.tracer.enabled:
                self.tracer.complete(
                    "restart_replica",
                    dur_s=time.perf_counter() - t_restart, t0=t_restart,
                    cat="fleet", replica=r.rid, reason=reason,
                    error=str(e)[:200],
                )
            return False
        r.respawn_failures = 0
        r.restarts += 1
        r.recipe = recipe
        self._repoint(r, f"http://127.0.0.1:{info['port']}")
        if self.tracer.enabled:
            self.tracer.complete(
                "restart_replica",
                dur_s=time.perf_counter() - t_restart, t0=t_restart,
                cat="fleet", replica=r.rid, reason=reason,
                restarts=r.restarts,
            )
        self.registry.counter_add("fleet.restarts", 1.0)
        self.registry.event(
            "recovery", action="restart", replica=r.rid,
            target=old_url or None, reason=reason,
            attempt=r.restarts, owed_requests=int(owed),
            pid=int(info.get("pid") or 0),
        )
        log.warning(
            "router: replica %s restarted supervised (%s; %d owed "
            "request(s) re-routing)", r.rid, reason, owed,
        )
        return True

    def _repoint(self, r: _RouterReplica, base_url: str) -> None:
        """Re-point the replica (and its hub target) at a new port."""
        t = self.hub.targets[r.idx]
        with self._lock:
            self._url_to_idx.pop(t.url, None)
            r.base_url = base_url
            r.suspect_until = 0.0
            t.url = r.telemetry_url
            self._url_to_idx[t.url] = r.idx

    # ---- rolling model rollout -------------------------------------------

    def rollout(self, ckpt_dir: str) -> Dict[str, Any]:
        """Preflight -> canary gate -> one-at-a-time drain/restart.
        Returns (and emits, exactly once) the typed ``rollout`` record's
        fields; never raises — every failure mode is a verdict."""
        t0 = time.monotonic()
        ckpt_dir = os.path.abspath(ckpt_dir)
        with self._rollout_lock:
            if self._rollout_active:
                return self._emit_rollout(
                    ckpt_dir, "refused", t0=t0,
                    error="rollout already in progress",
                )
            self._rollout_active = True
        try:
            # the traced rollout chain: preflight / canary / roll_one
            # spans emitted inside auto-parent under this root (same
            # thread — the tracer's thread-local span stack)
            with self.tracer.span("rollout", cat="rollout",
                                  ckpt_dir=ckpt_dir):
                return self._rollout_impl(ckpt_dir, t0)
        finally:
            self._rollout_active = False

    def _rollout_impl(self, ckpt_dir: str, t0: float) -> Dict[str, Any]:
        from neutronstarlite_tpu.tools.verify_checkpoint import (
            PreflightError,
            preflight_checkpoint,
        )

        if self._closed:
            return self._emit_rollout(ckpt_dir, "refused", t0=t0,
                                      error="fleet closed")
        if any(r.recipe is None for r in self.replicas):
            return self._emit_rollout(
                ckpt_dir, "refused", t0=t0,
                error="no launch recipe (targets-mode fleet: the router "
                      "cannot restart replicas it did not spawn)",
            )
        # 1. preflight: the digest-verified gate — a corrupt candidate is
        # refused before any replica is touched
        t_pf = time.perf_counter()
        try:
            _step_dir, step = preflight_checkpoint(ckpt_dir)
        except PreflightError as e:
            detail = "; ".join(e.problems[:3])
            self.tracer.complete(
                "rollout_preflight", dur_s=time.perf_counter() - t_pf,
                t0=t_pf, cat="rollout", ok=False,
            )
            return self._emit_rollout(
                ckpt_dir, "preflight_reject", t0=t0,
                error=f"{e}" + (f" [{detail}]" if detail else ""),
            )
        self.tracer.complete(
            "rollout_preflight", dur_s=time.perf_counter() - t_pf,
            t0=t_pf, cat="rollout", ok=True, ckpt_step=step,
        )
        # 2. canary gate: shadow-eval mirrored traffic, promote only
        # inside NTS_CANARY_TOL
        t_cn = time.perf_counter()
        try:
            canary = self._canary(ckpt_dir)
        except Exception as e:
            self.tracer.complete(
                "rollout_canary", dur_s=time.perf_counter() - t_cn,
                t0=t_cn, cat="rollout", ok=False,
            )
            return self._emit_rollout(
                ckpt_dir, "canary_reject", t0=t0, ckpt_step=step,
                error=f"canary evaluation failed: {e}",
            )
        self.tracer.complete(
            "rollout_canary", dur_s=time.perf_counter() - t_cn, t0=t_cn,
            cat="rollout", ok=bool(canary.get("passed")),
        )
        if not canary.get("passed"):
            return self._emit_rollout(
                ckpt_dir, "canary_reject", t0=t0, ckpt_step=step,
                canary=canary,
                error=(f"canary disagreement {canary['disagreement']:g} "
                       f"exceeds NTS_CANARY_TOL={canary['tolerance']:g}"),
            )
        # 3. sequential drain/restart — the fleet keeps answering
        prev_ckpt = {r.idx: (r.ckpt_dir or r.recipe.ckpt_dir)
                     for r in self.replicas}
        updated: List[_RouterReplica] = []
        for r in self.replicas:
            abort = self._abort_reason(r)
            if abort is None and not self._roll_one(r, ckpt_dir):
                abort = f"replica {r.rid} failed to come back on the " \
                        f"candidate checkpoint"
            if abort is not None:
                rolled_back = self._rollback(updated, prev_ckpt)
                return self._emit_rollout(
                    ckpt_dir, "aborted", t0=t0, ckpt_step=step,
                    canary=canary, error=abort,
                    restarted=len(updated) - rolled_back,
                    rolled_back=rolled_back,
                )
            updated.append(r)
        return self._emit_rollout(
            ckpt_dir, "promoted", t0=t0, ckpt_step=step, canary=canary,
            restarted=len(updated),
        )

    def _abort_reason(self, current: _RouterReplica) -> Optional[str]:
        if self._closed:
            return "fleet closed mid-rollout"
        for other, t in zip(self.replicas, self.hub.targets):
            if other is current or other.expected_down:
                continue
            if t.lost:
                return (f"replica {other.rid} died mid-rollout "
                        "(target_loss)")
        return None

    def _roll_one(self, r: _RouterReplica, ckpt_dir: str) -> bool:
        """Drain one replica, restart it on the candidate checkpoint."""
        with self.tracer.span("roll_one", cat="rollout",
                              replica=r.rid) as h:
            ok = self._roll_one_impl(r, ckpt_dir)
            h.attrs["ok"] = ok
            return ok

    def _roll_one_impl(self, r: _RouterReplica, ckpt_dir: str) -> bool:
        r.expected_down = True  # no NEW routing; hub sees the frozen
        # last-good snapshot (continuous merged view, zero misses)
        drain_deadline = time.monotonic() + self.drain_timeout_s
        while time.monotonic() < drain_deadline:
            with self._lock:
                if r.in_flight == 0:
                    break
            time.sleep(0.02)
        recipe = dataclasses.replace(r.recipe, ckpt_dir=ckpt_dir)
        try:
            with self._proc_lock:
                if self._closed:
                    r.expected_down = False
                    return False
                _terminate(r.proc)
                r.proc = None
                if os.path.exists(recipe.port_file):
                    os.remove(recipe.port_file)
                r.proc = _spawn_child(recipe)
            info = _wait_port_file(
                recipe.port_file, r.proc,
                time.monotonic() + self.spawn_timeout_s,
            )
        except Exception as e:
            log.warning("router: rollout respawn of %s failed (%s)",
                        r.rid, e)
            with self._proc_lock:
                _reap(r.proc)
                r.proc = None
            r.expected_down = False
            return False
        r.recipe = recipe
        r.ckpt_dir = ckpt_dir
        self._repoint(r, f"http://127.0.0.1:{info['port']}")
        self.registry.counter_add("fleet.rollout_restarts", 1.0)
        t = self.hub.targets[r.idx]
        t.missed = 0  # a maintenance window is not a liveness miss
        r.expected_down = False
        return True

    def _rollback(self, updated: List[_RouterReplica],
                  prev_ckpt: Dict[int, str]) -> int:
        """Return already-updated replicas to their pre-rollout
        checkpoint; counts successes. Skipped when the fleet is closing
        (close() reaps everything anyway)."""
        if self._closed:
            return 0
        rolled = 0
        for r in reversed(updated):
            old_ckpt = prev_ckpt.get(r.idx)
            if old_ckpt and self._roll_one(r, old_ckpt):
                rolled += 1
        return rolled

    def _canary(self, ckpt_dir: str) -> Dict[str, Any]:
        """Shadow-eval the candidate against the serving model on
        mirrored traffic. Both engines are built with the SAME rng seed
        and consume it in the SAME call order, so they sample identical
        neighborhoods — disagreement is model disagreement, not sampling
        noise (relative Frobenius RMS; exactly 0.0 for identical
        params)."""
        from neutronstarlite_tpu.resilience import events
        from neutronstarlite_tpu.serve.engine import InferenceEngine
        from neutronstarlite_tpu.utils.config import InputInfo

        recipe = self.replicas[0].recipe
        current = self.replicas[0].ckpt_dir or recipe.ckpt_dir
        cfg = InputInfo.read_from_cfg_file(recipe.cfg_path)
        base_dir = os.path.dirname(os.path.abspath(recipe.cfg_path))
        tol = canary_tol()
        n_batches = _env_int("NTS_CANARY_SEEDS", DEFAULT_CANARY_SEEDS)
        prev_sink = events.get_sink()  # engine construction installs its
        # registry as the process fault sink; the router's must survive
        try:
            eng_old = InferenceEngine.from_config(
                cfg, base_dir=base_dir, ckpt_dir=current,
                rng=np.random.default_rng(0xCA9A),
            )
            eng_new = InferenceEngine.from_config(
                cfg, base_dir=base_dir, ckpt_dir=ckpt_dir,
                rng=np.random.default_rng(0xCA9A),
            )
        finally:
            events.set_sink(prev_sink)
        try:
            batches = [list(b) for b in self._mirror][-n_batches:]
            if len(batches) < n_batches:
                v_num = eng_old.toolkit.host_graph.v_num
                rng = np.random.default_rng(0xCA9A)
                batches += [
                    rng.integers(0, v_num, size=4).tolist()
                    for _ in range(n_batches - len(batches))
                ]
            worst = 0.0
            for ids in batches:
                a = eng_old.predict(np.asarray(ids, dtype=np.int64))
                b = eng_new.predict(np.asarray(ids, dtype=np.int64))
                denom = float(np.linalg.norm(a)) or 1.0
                worst = max(worst, float(np.linalg.norm(
                    b.astype(np.float64) - a.astype(np.float64)
                )) / denom)
        finally:
            events.set_sink(prev_sink)
        canary = {
            "disagreement": worst,
            "tolerance": tol,
            "seeds": sum(len(b) for b in batches),
            "batches": len(batches),
            "mirrored": len([b for b in self._mirror]) > 0,
            "passed": worst <= tol,
        }
        # the drift auditor as promotion gate: same record kind, a canary
        # source — dashboards and the report render it natively
        try:
            self.registry.event(
                "model_drift", metric="canary_logit_rms",
                source="canary", predicted=0.0, observed=worst,
                drift=worst, threshold=tol,
                candidate=ckpt_dir, family="serve/rollout",
            )
        except Exception as e:
            log.warning("router: canary model_drift record failed (%s)", e)
        return canary

    def _emit_rollout(self, ckpt_dir: str, verdict: str, *,
                      t0: float, ckpt_step: Optional[int] = None,
                      canary: Optional[Dict[str, Any]] = None,
                      restarted: int = 0, rolled_back: int = 0,
                      error: Optional[str] = None) -> Dict[str, Any]:
        fields = {
            "ckpt_dir": ckpt_dir,
            "verdict": verdict,
            "ckpt_step": ckpt_step,
            "replicas": len(self.replicas),
            "restarted": int(max(restarted, 0)),
            "rolled_back": int(max(rolled_back, 0)),
            "canary": canary,
            "seconds": round(time.monotonic() - t0, 3),
            "error": error,
        }
        self.registry.counter_add("fleet.rollouts", 1.0)
        self.registry.gauge_set(
            "fleet.rollout_promoted", 1.0 if verdict == "promoted" else 0.0
        )
        try:
            self.registry.event("rollout", **fields)
        except Exception as e:
            log.warning("router: rollout record failed (%s)", e)
        (log.info if verdict == "promoted" else log.warning)(
            "rollout %s: %s (restarted %d/%d%s)", verdict, ckpt_dir,
            fields["restarted"], len(self.replicas),
            f"; {error}" if error else "",
        )
        return fields

    # ---- stats + lifecycle -----------------------------------------------

    def stats(self) -> Dict[str, Any]:
        snap = self.registry.snapshot(include_hists=False)
        merged = self.hub.merged_hists()
        lat = merged.get("serve.latency_ms")
        return {
            "replicas": len(self.replicas),
            "requests": int(snap["counters"].get("fleet.requests", 0)),
            "shed": int(snap["counters"].get("fleet.sheds", 0)),
            "restarts": int(snap["counters"].get("fleet.restarts", 0)),
            "rollouts": int(snap["counters"].get("fleet.rollouts", 0)),
            "latency_ms": (lat.quantiles() if lat is not None and lat.count
                           else {"p50": None, "p95": None, "p99": None}),
            "targets_lost": sum(1 for t in self.hub.targets if t.lost),
        }

    def close(self) -> Dict[str, Any]:
        """Stop dispatch, reap every child, close the merged stream —
        idempotent, never leaks a process, never drops an owed request
        silently (undispatched requests complete as fleet_closed
        sheds)."""
        with self._lock:
            if self._closed:
                return self.stats()
            self._closed = True
        self._poll_stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=10.0)
        for _ in self._workers:
            self._dispatch_q.put(None)
        for w in self._workers:
            w.join(timeout=10.0)
        while True:  # requests still queued behind the sentinels
            try:
                req = self._dispatch_q.get_nowait()
            except queue_mod.Empty:
                break
            if req is not None and not req.done():
                self._shed(req, "fleet_closed")
        with self._proc_lock:
            for r in self.replicas:
                _terminate(r.proc)
                r.proc = None
        s = self.stats()
        try:
            merged = self.hub.merged_hists()
            lat = s["latency_ms"]
            self.registry.emit_hists()
            snap = self.registry.snapshot(include_hists=False)
            self.registry.event(
                "serve_summary", requests=s["requests"], shed=s["shed"],
                latency_ms={"p50": lat.get("p50"), "p95": lat.get("p95"),
                            "p99": lat.get("p99")},
                throughput_rps=None, counters=snap["counters"],
                gauges=snap["gauges"], fleet=True, crosshost=True,
                hist_counts={n: h.count for n, h in merged.items()},
            )
        except Exception as e:
            log.warning("router: close-time serve_summary failed (%s)", e)
        if self._owns_registry:
            self.registry.close()
        return s


# ---- child process plumbing -------------------------------------------------


def _spawn_child(recipe: LaunchRecipe) -> subprocess.Popen:
    log.info("spawning replica %s (ckpt %s)", recipe.replica,
             recipe.ckpt_dir)
    return subprocess.Popen(recipe.argv(), env=recipe.env())


def _wait_port_file(path: str, proc: subprocess.Popen,
                    deadline: float) -> Dict[str, Any]:
    """Poll for the child's atomic port-file publish; raises on child
    death or timeout (the caller reaps)."""
    while time.monotonic() < deadline:
        if os.path.exists(path):
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    info = json.load(fh)
                if isinstance(info, dict) and info.get("port"):
                    return info
            except (OSError, ValueError):
                pass  # racing the atomic rename; retry
        rc = proc.poll()
        if rc is not None:
            raise RuntimeError(
                f"replica child exited rc={rc} before publishing "
                f"{path}"
            )
        time.sleep(0.05)
    raise TimeoutError(f"replica child did not publish {path} in time")


def _terminate(proc: Optional[subprocess.Popen],
               grace_s: float = 15.0) -> None:
    """SIGTERM with a grace window, then SIGKILL; always reaps."""
    if proc is None or proc.poll() is not None:
        if proc is not None:
            proc.wait()
        return
    try:
        proc.terminate()
    except OSError:
        pass
    try:
        proc.wait(timeout=grace_s)
    except subprocess.TimeoutExpired:
        _reap(proc)


def _reap(proc: Optional[subprocess.Popen]) -> None:
    """SIGKILL + wait; safe on dead/None procs."""
    if proc is None:
        return
    if proc.poll() is None:
        try:
            proc.kill()
        except OSError:
            pass
    try:
        proc.wait(timeout=10.0)
    except subprocess.TimeoutExpired:  # pragma: no cover
        log.warning("router: child pid %s did not reap", proc.pid)


if __name__ == "__main__":
    raise SystemExit(child_main())
