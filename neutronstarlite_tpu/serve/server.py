"""In-process inference server + CLI entrypoint.

``InferenceServer`` composes the three serving pieces: requests enter the
micro-batching queue (serve/batcher.py), flushes look up the inference
embedding cache then sample + execute the remainder on the smallest
covering AOT bucket (serve/sampling.py + serve/engine.py), and every event
lands in the obs stream as a typed record (serve_request / batch_flush /
shed / serve_summary) — serving runs produce the same JSONL + report
artifacts as training runs (tools/metrics_report renders them).

The request API is deliberately transport-free: ``submit()`` returns a
future, ``predict()`` blocks — an HTTP/RPC front end is a thin loop over
it, and the load generator (tools/serve_bench.py) drives it directly.

CLI: ``python -m neutronstarlite_tpu.serve.server <cfg> [<ckpt_dir>]
[--requests N]`` loads the checkpoint, warms the bucket ladder, serves a
batch of random requests, and prints the latency summary (docs/SERVING.md).
"""

from __future__ import annotations

import argparse
import itertools
import os
import queue as queue_mod
import sys
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from neutronstarlite_tpu.serve.batcher import (  # noqa: E402
    MicroBatcher,
    ServeOptions,
    ServeRequest,
)
from neutronstarlite_tpu.obs.trace import TraceContext  # noqa: E402
from neutronstarlite_tpu.serve.engine import InferenceEngine  # noqa: E402
from neutronstarlite_tpu.serve.sampling import EmbeddingCache  # noqa: E402
from neutronstarlite_tpu.utils.logging import get_logger  # noqa: E402

log = get_logger("serve")

# process-wide like batcher._REQ_IDS: two servers (or a restarted one)
# sharing one registry stream must not collide flush ids — trace_timeline
# joins stage spans to serve_request records by (run_id, flush_id)
_FLUSH_IDS = itertools.count()


class InferenceServer:
    """Micro-batched, cache-fronted serving over one InferenceEngine."""

    def __init__(self, engine: InferenceEngine,
                 options: Optional[ServeOptions] = None,
                 replica: Optional[str] = None):
        self.engine = engine
        self.opts = options or engine.opts
        self.metrics = engine.metrics
        # fleet identity (serve/fleet.py): stamps the exporter surface
        # label, the flight-dump filename prefix, and the graph_delta
        # records; None for a standalone server
        self.replica = replica
        if self.metrics is not None and replica:
            self.metrics.gauge_set("serve.replica", replica)
            if self.metrics.flight is not None:
                self.metrics.flight.tag = replica
        self.cache = EmbeddingCache.for_graph(
            engine.toolkit.host_graph,
            self.opts.cache_cap,
            self.opts.cache_max_age_s,
            self.opts.hot_threshold,
        )
        # span tracing over the same obs stream: each flush becomes one
        # batch_flush span with cache/sample/execute/reply stage children,
        # each request one request/queue span pair — joined to the typed
        # serve_request records by req_id (tools/trace_timeline computes
        # the per-request critical-path breakdown from exactly this)
        from neutronstarlite_tpu.obs.trace import Tracer

        self.tracer = Tracer(self.metrics)
        # the live telemetry plane (obs/): latency distributions become
        # mergeable histograms on the registry, the SLO burn-rate engine
        # (NTS_SLO_SPEC) evaluates them and drives burn-rate shedding in
        # the batcher below, and the HTTP exporter (NTS_METRICS_PORT)
        # serves /metrics, /healthz and /slo off the same registry
        from neutronstarlite_tpu.obs import exporter as obs_exporter
        from neutronstarlite_tpu.obs.slo import SloEngine

        self.slo = (
            SloEngine.from_env(self.metrics, scope="serve")
            if self.metrics is not None else None
        )
        self.exporter = obs_exporter.maybe_start(
            self.metrics, slo=self.slo, replica=replica
        )
        # SAMPLE_PIPELINE:pipelined/device — two-stage flush: the batcher's
        # flusher thread becomes the PRODUCER (cache pass + per-request
        # fan-out sampling + async H2D staging) and a dedicated executor
        # thread runs the AOT executable + replies, so sampling flush i+1
        # overlaps device execution of flush i and the `sample` span leaves
        # the batch_flush critical path. The queue is bounded: a stalled
        # executor backpressures the producer, which backs up the batcher,
        # which sheds — overload policy unchanged.
        # continuous batching (SERVE_CB / NTS_SERVE_CB) rides the same
        # two-stage machinery with synchronous sampling: the produce
        # stage of bucket i+1 overlaps the execute of bucket i.
        # SAMPLE_PIPELINE:fused deliberately does NOT force the two-stage
        # path: its flush has no host sampling to overlap (sample+execute
        # is one dispatch), so fused alone uses the simple sync flush and
        # only rides the producer/executor split when CB asks for it
        self.pipelined = (
            self.opts.continuous_batching
            or self.opts.sample_pipeline in ("pipelined", "device")
        )
        # serializes the flush PRODUCE stage against live graph-delta
        # application (serve/delta.py): a delta lands between flushes,
        # never inside one; the version guards cache re-insertion of
        # pre-delta logits by in-flight prepared flushes
        self._graph_gate = threading.RLock()
        self._graph_version = 0
        self._prep_q: Optional[queue_mod.Queue] = None
        self._exec_thread: Optional[threading.Thread] = None
        self._producing = False
        self._prep_peak = 0
        if self.pipelined:
            self._prep_q = queue_mod.Queue(maxsize=2)
            self._exec_thread = threading.Thread(
                target=self._exec_loop, name="serve-executor", daemon=True
            )
            self._exec_thread.start()
        self.batcher = MicroBatcher(
            self._flush, self.opts, self.metrics, slo=self.slo
        )
        # the registry histogram is cumulative across every server bound
        # to it (a restarted server shares the run's registry); this
        # server's quantiles subtract the at-construction snapshot so
        # stats()/serve_summary describe THIS server's requests only
        self._lat_baseline = (
            self.metrics.hists().get("serve.latency_ms")
            if self.metrics is not None else None
        )
        self._stats_lock = threading.Lock()
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None
        self.request_count = 0
        self._closed = False
        # freshness lineage: every request span is stamped with the graph
        # version (delta-log seq) and model version (checkpoint step) that
        # answered it. model_seq comes from the engine; graph_seq from
        # whoever owns the delta stream (the crosshost child wires its
        # StreamIngestor's applied_seq here; standalone servers report the
        # engine's static graph as seq 0)
        self.graph_seq_source = None  # () -> int | None

    # ---- request API -----------------------------------------------------
    def submit(self, node_ids, ctx=None) -> ServeRequest:
        """Enqueue one request (any 1..max_batch vertex ids); returns the
        future. Overload rejects with RequestShedError on the future.
        ``ctx`` (obs/trace.TraceContext) parents this request's lifecycle
        spans into a remote caller's trace."""
        return self.batcher.submit(node_ids, ctx=ctx)

    def predict(self, node_ids, timeout: Optional[float] = 60.0) -> np.ndarray:
        """Blocking convenience wrapper: logits [n, n_classes]."""
        return self.submit(node_ids).result(timeout)

    # ---- live graph deltas (serve/delta.py) ------------------------------
    def apply_delta(self, delta):
        """Apply a GraphDelta between flushes: post-delta graph swapped
        in under the graph gate, only the touched embedding-cache
        entries invalidated, device neighbor-table rows patched, digest
        bumped, one typed ``graph_delta`` record emitted. Returns the
        DeltaPlan."""
        from neutronstarlite_tpu.serve import delta as delta_mod

        return delta_mod.apply_to_servers([self], delta)

    # ---- fleet-side surface (serve/fleet.py) -----------------------------
    def beating(self) -> bool:
        """Replica liveness: the flusher (and, pipelined, the executor)
        thread still running and the server not closed — what the fleet
        heartbeat monitor consumes each tick."""
        if self._closed:
            return False
        alive = self.batcher.alive()
        if self._exec_thread is not None:
            alive = alive and self._exec_thread.is_alive()
        return alive

    def inject_death(self) -> None:
        """Chaos hook: kill the flusher thread without draining — the
        fleet's heartbeat monitor must detect the silence, restart the
        replica supervised, and re-route the stolen pending requests."""
        self.batcher.abort()

    def steal_inflight(self) -> List[ServeRequest]:
        """Every request this (dead) server still owes an answer:
        batcher-pending plus any prepared-but-unexecuted flushes. The
        fleet re-routes them — in-flight requests are re-routed, never
        dropped."""
        out = self.batcher.steal_pending()
        if self._prep_q is not None:
            while True:
                try:
                    item = self._prep_q.get_nowait()
                except queue_mod.Empty:
                    break
                if item is None:
                    continue
                out.extend(item[0])
        return [r for r in out if not r.done()]

    # ---- the flush path (batcher thread) ---------------------------------
    def _flush(self, requests: List[ServeRequest], reason: str) -> None:
        if self.pipelined:
            self._flush_pipelined(requests, reason)
            return
        t0 = time.perf_counter()
        flush_id = next(_FLUSH_IDS)
        batch_span = self.tracer.begin(
            "batch_flush", cat="serve", flush_id=flush_id, reason=reason,
            n_requests=len(requests),
        )
        try:
            bucket, n_seeds, exec_ms = self._flush_body(
                requests, t0, flush_id, batch_span
            )
        except BaseException as e:
            # the batcher deliberately survives a bad flush (_loop catches
            # everything); the span must still land — and pop off the
            # flusher thread's stack — or every later flush parents under
            # a handle that never reaches the stream
            self.tracer.end(batch_span, error=type(e).__name__)
            raise
        self.tracer.end(batch_span, bucket=bucket, n_seeds=n_seeds)
        self._record(requests, reason, bucket, n_seeds, exec_ms, flush_id)

    def _flush_body(self, requests: List[ServeRequest], t0: float,
                    flush_id: int, batch_span):
        with self._graph_gate:  # a graph delta lands between flushes
            return self._flush_body_locked(requests, t0, flush_id,
                                           batch_span)

    def _flush_body_locked(self, requests: List[ServeRequest], t0: float,
                           flush_id: int, batch_span):
        # cache pass: per requested id, a fresh cached row or a compute slot
        all_ids, cached_rows = self._cache_pass(requests)
        t_cache = time.perf_counter()
        bucket = None
        rows: Dict[int, np.ndarray] = dict(cached_rows)
        t_sample = t_cache
        if all_ids:
            uniq = np.asarray(all_ids, dtype=np.int64)
            bucket = self.engine.sampler.bucket_for(len(uniq))
            if getattr(self.engine, "fused", False):
                # SAMPLE_PIPELINE:fused — the miss set's fan-out draw,
                # remap, gather and forward are ONE dispatch inside the
                # engine's fused bucket executable; there is no host
                # sampling stage (its span is structurally zero)
                t_sample = time.perf_counter()
                logits = self.engine.fused_predict_rows(uniq, bucket)
            else:
                batch = self.engine.sampler.sample(bucket, uniq)
                t_sample = time.perf_counter()
                logits = self.engine.forward_batch(batch, bucket)
            for i, vid in enumerate(uniq.tolist()):
                rows[vid] = logits[i]
            self.cache.insert(uniq, logits[: len(uniq)])
        t_exec = time.perf_counter()
        exec_ms = (t_exec - t0) * 1000.0

        for r in requests:
            out = np.stack([rows[v] for v in r.node_ids.tolist()])
            status = "cached" if all(
                v in cached_rows for v in r.node_ids.tolist()
            ) else "ok"
            r._complete(out, status)
        t_reply = time.perf_counter()
        # stage children, back-to-back over the flush body — the sum of a
        # request's queue span + these four IS its end-to-end latency (the
        # critical-path contract tests pin within tolerance)
        for name, a, b in (
            ("cache_lookup", t0, t_cache),
            ("sample", t_cache, t_sample),
            ("execute", t_sample, t_exec),
            ("reply", t_exec, t_reply),
        ):
            self.tracer.complete(
                name, dur_s=b - a, t0=a, cat="serve", parent=batch_span,
                flush_id=flush_id,
            )
        return bucket, len(all_ids), exec_ms

    # ---- the two-stage pipelined flush path ------------------------------
    def _cache_pass(self, requests: List[ServeRequest]):
        """Per requested id: a fresh cached row or a compute slot (shared
        by both flush paths)."""
        all_ids: List[int] = []
        seen = set()
        cached_rows: Dict[int, np.ndarray] = {}
        for r in requests:
            for vid in r.node_ids.tolist():
                if vid in seen:
                    continue
                seen.add(vid)
                row = self.cache.lookup(vid)
                if row is not None:
                    cached_rows[vid] = row
                else:
                    all_ids.append(vid)
        return all_ids, cached_rows

    def _flush_pipelined(self, requests: List[ServeRequest],
                         reason: str) -> None:
        """Producer stage (batcher thread): cache pass + fan-out sampling +
        H2D staging, then hand off to the executor. All spans here are
        retroactive completes keyed by flush_id (the critical-path join
        key) — the batch_flush span itself is emitted by the executor once
        the flush really finishes, so no cross-thread span stack is held
        open across the queue."""
        t0 = time.perf_counter()
        flush_id = next(_FLUSH_IDS)
        self._producing = True
        try:
            with self._graph_gate:  # delta lands between produce stages
                version = self._graph_version
                all_ids, cached_rows = self._cache_pass(requests)
                t_cache = time.perf_counter()
                bucket = None
                prepared = None
                uniq = None
                t_sample = t_cache
                t_h2d = t_cache
                exec_ctx = None
                if all_ids:
                    uniq = np.asarray(all_ids, dtype=np.int64)
                    bucket = self.engine.sampler.bucket_for(len(uniq))
                    if getattr(self.engine, "fused", False):
                        # fused produce stage: no host sampling, no
                        # subgraph H2D — only the padded seed vector +
                        # draw key stage to device; sample+execute run
                        # as ONE dispatch in the executor
                        t_sample = time.perf_counter()
                        prepared = self.engine.prepare_fused(uniq, bucket)
                        exec_ctx = (
                            self.engine._ensure_fused(bucket),
                            self.engine.params,
                            self.engine.feature,
                            self.engine._fused_exec_tables(),
                        )
                    else:
                        batch = self.engine.sampler.sample(bucket, uniq)
                        t_sample = time.perf_counter()
                        prepared = self.engine.prepare_batch(batch)
                        # snapshot the executable + operands UNDER the
                        # gate: a vertex-appending delta swaps
                        # engine.feature and clears the AOT ladder, and
                        # an in-flight prepared flush must answer with
                        # the PRE-delta view — not crash on a
                        # shape-mismatched operand (the staleness
                        # contract). Compiling here (cold bucket) also
                        # keeps compile out of the executor's
                        # steady-state path.
                        exec_ctx = (
                            self.engine._ensure_compiled(bucket),
                            self.engine.params,
                            self.engine.feature,
                        )
                    t_h2d = time.perf_counter()
            for name, a, b in (
                ("cache_lookup", t0, t_cache),
                ("sample", t_cache, t_sample),
                ("h2d_copy", t_sample, t_h2d),
            ):
                self.tracer.complete(
                    name, dur_s=b - a, t0=a, cat="serve",
                    flush_id=flush_id,
                )
        except BaseException:
            self._producing = False
            raise
        self._producing = False
        # bounded handoff: blocks when the executor is behind (backpressure
        # flows to the batcher queue, whose bound sheds — policy unchanged)
        self._prep_q.put(
            (requests, reason, flush_id, t0, t_h2d, bucket, uniq,
             cached_rows, prepared, version, exec_ctx)
        )
        depth = self._prep_q.qsize()
        if self.metrics is not None:
            # depth as a distribution, not just a peak: stall diagnosis
            # needs to see whether the queue sat empty (producer-bound)
            # or full (executor-bound), not one high-water number
            self.metrics.hist_observe("sample.queue_depth", depth, unit="")
        if depth > self._prep_peak:
            self._prep_peak = depth
            if self.metrics is not None:
                self.metrics.gauge_set("sample.queue_depth", depth)

    def _exec_loop(self) -> None:
        while True:
            t_idle = time.perf_counter()
            producing = self._producing
            item = self._prep_q.get()
            if item is None:
                return
            wait = time.perf_counter() - t_idle
            if producing and self.metrics is not None:
                # the executor was waiting ON the producer (a flush was
                # mid-production when we went idle) — the residual,
                # un-overlapped sampling time
                self.metrics.counter_add("sample.stall_ms", wait * 1000.0)
                self.tracer.complete(
                    "sample_wait", dur_s=wait, t0=t_idle, cat="sample",
                )
            (requests, reason, flush_id, t0, t_h2d, bucket, uniq,
             cached_rows, prepared, version, exec_ctx) = item
            try:
                self._execute_prepared(
                    requests, reason, flush_id, t0, t_h2d, bucket, uniq,
                    cached_rows, prepared, version, exec_ctx,
                )
            except BaseException as e:  # mirror MicroBatcher._loop
                log.warning(
                    "pipelined flush failed (%s): %s", type(e).__name__, e
                )
                self.tracer.complete(
                    "batch_flush", dur_s=time.perf_counter() - t0, t0=t0,
                    cat="serve", flush_id=flush_id, reason=reason,
                    n_requests=len(requests), error=type(e).__name__,
                )
                for r in requests:
                    if not r.done():
                        r._complete(None, "error", e)

    def _execute_prepared(self, requests, reason, flush_id, t0, t_h2d,
                          bucket, uniq, cached_rows, prepared,
                          version: int = 0, exec_ctx=None) -> None:
        t_exec0 = time.perf_counter()
        # the producer->executor queue wait: without this stage the serve
        # critical path's stage sum would silently undershoot the recorded
        # latency by exactly the handoff time in pipelined mode
        self.tracer.complete(
            "handoff", dur_s=t_exec0 - t_h2d, t0=t_h2d, cat="serve",
            flush_id=flush_id,
        )
        rows: Dict[int, np.ndarray] = dict(cached_rows)
        if prepared is not None:
            if getattr(self.engine, "fused", False):
                # one dispatch: sample+execute inside the fused bucket
                # executable (exec_ctx carries the produce-time snapshot
                # incl. the table operands, same staleness contract)
                logits = self.engine.execute_fused_prepared(
                    prepared, bucket, exec_ctx=exec_ctx
                )
            elif exec_ctx is not None:
                # the produce-time snapshot: executable + params + feature
                # captured under the graph gate, so a delta that swapped
                # engine.feature / cleared the AOT ladder mid-flight
                # cannot hand this flush a shape-mismatched operand
                nodes, hops = prepared
                executable, params, feature = exec_ctx
                logits = np.asarray(executable(params, feature, nodes, hops))
            else:
                nodes, hops = prepared
                logits = self.engine.execute_prepared(nodes, hops, bucket)
            for i, vid in enumerate(uniq.tolist()):
                rows[vid] = logits[i]
            # the version check + insert run UNDER the gate: a delta
            # between an unlocked check and the insert would let
            # pre-delta logits re-poison the freshly invalidated cache
            with self._graph_gate:
                if version == self._graph_version:
                    self.cache.insert(uniq, logits[: len(uniq)])
        t_exec = time.perf_counter()
        exec_ms = (t_exec - t0) * 1000.0
        for r in requests:
            out = np.stack([rows[v] for v in r.node_ids.tolist()])
            status = "cached" if all(
                v in cached_rows for v in r.node_ids.tolist()
            ) else "ok"
            r._complete(out, status)
        t_reply = time.perf_counter()
        for name, a, b in (
            ("execute", t_exec0, t_exec),
            ("reply", t_exec, t_reply),
        ):
            self.tracer.complete(
                name, dur_s=b - a, t0=a, cat="serve", flush_id=flush_id,
            )
        n_seeds = len(uniq) if uniq is not None else 0
        self.tracer.complete(
            "batch_flush", dur_s=t_reply - t0, t0=t0, cat="serve",
            flush_id=flush_id, reason=reason, n_requests=len(requests),
            bucket=bucket, n_seeds=n_seeds,
        )
        self._record(requests, reason, bucket, n_seeds, exec_ms, flush_id)

    def _lineage(self):
        """(graph_seq, model_seq) for the freshness-lineage span fields:
        which delta-log seq and which checkpoint step answered. Never
        raises — lineage is best-effort telemetry."""
        graph_seq = None
        if self.graph_seq_source is not None:
            try:
                v = self.graph_seq_source()
                graph_seq = int(v) if v is not None else None
            except Exception:
                graph_seq = None
        model_seq = getattr(self.engine, "ckpt_step", None)
        if model_seq is not None:
            try:
                model_seq = int(model_seq)
            except (TypeError, ValueError):
                model_seq = None
        return graph_seq, model_seq

    def _record(self, requests: List[ServeRequest], reason: str,
                bucket: Optional[int], n_seeds: int, exec_ms: float,
                flush_id: Optional[int] = None) -> None:
        now = time.perf_counter()
        with self._stats_lock:
            if self._t_first is None:
                self._t_first = requests[0].t_submit
            self._t_last = now
            self.request_count += len(requests)
        if self.metrics is None:
            return
        self.metrics.counter_add("serve.batches")
        self.metrics.counter_add("serve.requests", len(requests))
        if bucket is not None:
            self.metrics.counter_add("serve.computed_seeds", n_seeds)
            self.metrics.counter_add(
                "serve.padded_seeds", max(bucket - n_seeds, 0)
            )
        self.metrics.observe("serve.exec", exec_ms / 1000.0)
        # flush-stage + per-bucket latency distributions (obs/hist): the
        # registry histograms are what stats()/serve_summary report, what
        # the SLO engine windows over, and what the stream's `hist`
        # records persist — no raw-record full-sorts anywhere downstream
        self.metrics.hist_observe("serve.exec_ms", exec_ms)
        if bucket is not None:
            self.metrics.hist_observe(
                f"serve.exec_ms.bucket_{bucket}", exec_ms
            )
        self.metrics.event(
            "batch_flush", n_requests=len(requests), n_seeds=n_seeds,
            reason=reason, bucket=bucket, exec_ms=exec_ms,
            flush_id=flush_id,
        )
        graph_seq, model_seq = self._lineage()
        for r in requests:
            if r.status == "cached":
                self.metrics.counter_add("serve.cached_requests")
            if r.total_ms is not None:
                self.metrics.hist_observe("serve.latency_ms", r.total_ms)
            if r.queue_ms is not None:
                self.metrics.hist_observe("serve.queue_ms", r.queue_ms)
            self.metrics.event(
                "serve_request", n_seeds=len(r.node_ids), status=r.status,
                total_ms=r.total_ms, queue_ms=r.queue_ms,
                req_id=r.req_id, flush_id=flush_id,
            )
            if r.t_done is None or r.t_flush is None:
                continue
            # request lifecycle spans, retroactive from the recorded
            # perf_counter marks (same clock domain as the tracer). When
            # the request arrived over the wire (r.ctx), the span joins
            # the caller's trace — parented under the exporter's handler
            # span, carrying the (send_ts, recv_ts) clock pair and the
            # graph_seq/model_seq freshness lineage.
            span = self.tracer.complete(
                "request", dur_s=r.t_done - r.t_submit, t0=r.t_submit,
                cat="serve", ctx=r.ctx, req_id=r.req_id, status=r.status,
                n_seeds=len(r.node_ids), flush_id=flush_id,
                graph_seq=graph_seq, model_seq=model_seq,
            )
            queue_ctx = (
                TraceContext(r.ctx.trace_id, span.span_id)
                if r.ctx is not None else None
            )
            self.tracer.complete(
                "queue", dur_s=r.t_flush - r.t_submit, t0=r.t_submit,
                cat="serve", parent=span, ctx=queue_ctx, req_id=r.req_id,
            )
        if self.slo is not None:
            # completions are the SLO engine's observation stream; a tick
            # here keeps burn rates fresh even when no new arrivals are
            # calling the batcher's admission gate
            self.slo.tick()

    # ---- SLO telemetry ---------------------------------------------------
    def _latency_quantiles(self) -> Dict[str, Optional[float]]:
        """{p50, p95, p99} off the live latency histogram — fixed memory
        no matter how many requests were served (the raw-list full-sort
        this replaces grew without bound). hists() copies under the
        registry lock (stats() is called from monitoring threads while
        the flusher mutates the live buckets), and the at-construction
        baseline is subtracted so the numbers are THIS server's."""
        h = (
            self.metrics.hists().get("serve.latency_ms")
            if self.metrics is not None else None
        )
        if h is not None:
            h = h.delta(self._lat_baseline)
        if h is None or h.count == 0:
            return {"p50": None, "p95": None, "p99": None}
        return h.quantiles()

    def stats(self) -> Dict[str, Any]:
        with self._stats_lock:
            span = (
                self._t_last - self._t_first
                if self._t_first is not None and self._t_last is not None
                else None
            )
            served = self.request_count
        lat = self._latency_quantiles()
        rps = served / span if span and span > 0 else None
        return {
            "requests": served,
            "shed": self.batcher.shed_count,
            "latency_ms": lat,
            "throughput_rps": rps,
            "cache": self.cache.stats(),
            "compile_counts": dict(self.engine.compile_counts),
        }

    def close(self) -> Dict[str, Any]:
        """Drain the queue, emit the consolidated serve_summary record, and
        return the stats dict (idempotent)."""
        if self._closed:
            return self.stats()
        self._closed = True
        self.batcher.close()
        if self._exec_thread is not None:
            # the batcher has drained: everything is enqueued; the sentinel
            # lands behind the last prepared flush (FIFO), so the executor
            # finishes real work first
            self._prep_q.put(None)
            self._exec_thread.join(timeout=60.0)
        if self.slo is not None:
            self.slo.close()  # final forced evaluation -> last slo_status
        s = self.stats()
        if self.metrics is not None:
            # final cumulative hist snapshots BEFORE the summary: the
            # stream's quantiles survive rotation, and downstream
            # consumers (serve_bench, metrics_report) read these instead
            # of full-sorting raw serve_request records
            self.metrics.emit_hists()
            snap = self.metrics.snapshot()
            self.metrics.event(
                "serve_summary",
                requests=s["requests"],
                shed=s["shed"],
                latency_ms=s["latency_ms"],
                throughput_rps=s["throughput_rps"],
                counters=snap["counters"],
                gauges=snap["gauges"],
                hists=snap["hists"],
                cache=s["cache"],
                compile_counts={
                    str(k): v for k, v in s["compile_counts"].items()
                },
                ckpt_step=self.engine.ckpt_step,
            )
            self.metrics.close()
        return s


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    from neutronstarlite_tpu.utils.config import InputInfo
    from neutronstarlite_tpu.utils.platform import honor_platform_env

    honor_platform_env()
    ap = argparse.ArgumentParser(
        description="serve a trained checkpoint: load, AOT-warm the bucket "
        "ladder, answer --requests random per-node predictions, print SLOs"
    )
    ap.add_argument("cfg", help="training .cfg (LAYERS/FANOUT/paths)")
    ap.add_argument("ckpt", nargs="?", default="",
                    help="checkpoint dir (default: the cfg's CHECKPOINT_DIR)")
    ap.add_argument("--requests", type=int, default=50)
    ap.add_argument("--seeds-per-request", type=int, default=1)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    cfg = InputInfo.read_from_cfg_file(args.cfg)
    base_dir = os.path.dirname(os.path.abspath(args.cfg))
    from neutronstarlite_tpu.serve.engine import ServeSetupError

    try:
        engine = InferenceEngine.from_config(
            cfg, base_dir=base_dir, ckpt_dir=args.ckpt,
            rng=np.random.default_rng(args.seed),
        )
    except ServeSetupError as e:
        print(f"serve: {e}", file=sys.stderr)
        return 2
    engine.warmup()
    server = InferenceServer(engine)
    rng = np.random.default_rng(args.seed + 1)
    v_num = engine.toolkit.host_graph.v_num
    pending = [
        server.submit(rng.integers(0, v_num, size=args.seeds_per_request))
        for _ in range(args.requests)
    ]
    errors = 0
    for req in pending:
        try:
            req.result(timeout=120.0)
        except Exception:
            errors += 1
    s = server.close()
    lat = s["latency_ms"]

    def _fmt(v):
        return f"{v:.2f}ms" if v is not None else "n/a"

    print(
        f"served {s['requests']} requests (shed {s['shed']}, errors {errors})"
        f" | p50 {_fmt(lat['p50'])} p95 {_fmt(lat['p95'])} "
        f"p99 {_fmt(lat['p99'])}"
        + (f" | {s['throughput_rps']:.1f} req/s"
           if s["throughput_rps"] else "")
    )
    if engine.metrics is not None and engine.metrics.path:
        print(f"metrics stream: {engine.metrics.path} (render with "
              f"python -m neutronstarlite_tpu.tools.metrics_report "
              f"{engine.metrics.path})")
    return 0 if errors == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
