"""Serve fleet: SLO-routed replicas behind one submit() front door.

One ``InferenceServer`` is a single engine over a frozen graph; the gap
to "heavy traffic from millions of users" is fleet shape. A
:class:`ReplicaSet` runs N replicas in one process — each an
``InferenceServer`` over an ``InferenceEngine.clone()`` that shares the
checkpoint-restored params, the feature slab, the device hop-sampler
table, and the AOT bucket ladder, so replica N+1 starts COMPILE-WARM and
(because the shared toolkit carries its tune-cache-resolved knobs and
graph digest) never re-measures anything — behind a single ``submit()``.

**Routing** consumes each replica's live telemetry IN PROCESS — the same
state the PR 11 exporter serves over HTTP (`/slo` burn verdicts,
`/healthz` liveness, queue depth), read without a scrape because the
router is co-located:

- ``least_burn`` (default): score = worst sheddable SLO burn +
  queue-depth fraction; the lowest-scored healthy replica wins, with
  HYSTERESIS — the previous choice is kept until a rival beats it by
  more than ``NTS_SERVE_ROUTE_HYST`` — so equal replicas don't flap the
  route every request.
- **Drain-on-breach**: a replica whose sheddable SLO objective is in
  breach receives no NEW requests (it drains and recovers) as long as
  any healthy replica remains.
- **Fleet-level shed only when ALL replicas breach**: the front door
  rejects (``fleet_breach`` shed record + RequestShedError) only when no
  replica can reasonably take the request — one breaching replica never
  costs a request, it just routes around (the FLEET_GATE pin).
- ``round_robin``: the policy-free baseline (still skips dead/draining).

**Supervision** reuses the heartbeat pattern of resilience/elastic.py
verbatim: a monitor thread feeds one ``LivenessMonitor`` beat per
replica per tick (typed ``heartbeat`` records, replica index =
partition); a replica whose flusher/executor thread died misses beats,
trips a typed ``rank_loss`` record at ``NTS_HEARTBEAT_MISS_K``, and is
restarted SUPERVISED: a fresh ``InferenceServer`` over the same warm
engine clone (zero recompiles), a typed ``recovery action=restart``
record, and every request the dead replica still owed — batcher-pending
and prepared-but-unexecuted — is re-routed to a live replica, not
dropped (latency honestly keeps the original ``t_submit``).

**Live graph deltas** (serve/delta.py) apply fleet-wide under every
replica's graph gate: one plan, every engine swapped, only the touched
cache entries invalidated per replica, one ``graph_delta`` record per
replica stream.

Telemetry: each replica owns its own MetricsRegistry (stream file,
histograms, SLO engine) labeled ``r0..rN-1``; the exporter merges them
under one port with ``replica="rK"`` labels (obs/exporter.py), and the
fleet itself owns a registry for the front-door records (heartbeats,
rank_loss, restarts, fleet sheds) plus the consolidated close-time
``serve_summary`` whose latency quantiles MERGE the replicas' histograms
(obs/hist merge law — the fleet p99 is exact, not an average of
averages).

Knobs: SERVE_REPLICAS/NTS_SERVE_REPLICAS, SERVE_ROUTE/NTS_SERVE_ROUTE
(least_burn | round_robin), NTS_SERVE_ROUTE_HYST, NTS_SERVE_HEARTBEAT_S,
NTS_HEARTBEAT_MISS_K (shared with elastic), SERVE_CB/NTS_SERVE_CB
(continuous batching, serve/batcher.py). docs/SERVING.md has the table.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from neutronstarlite_tpu.serve.batcher import (
    RequestShedError,
    ServeOptions,
    ServeRequest,
)
from neutronstarlite_tpu.serve.engine import InferenceEngine
from neutronstarlite_tpu.serve.server import InferenceServer
from neutronstarlite_tpu.utils.logging import get_logger

log = get_logger("serve")

_ROUTES = ("least_burn", "round_robin")


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        log.warning("%s=%r is not a number; using %g", name, raw, default)
        return default


@dataclasses.dataclass
class FleetOptions:
    """Fleet-shape knobs (the ServeOptions pattern: cfg key + env)."""

    replicas: int = 1  # SERVE_REPLICAS / NTS_SERVE_REPLICAS
    route: str = "least_burn"  # SERVE_ROUTE / NTS_SERVE_ROUTE
    hysteresis: float = 0.25  # NTS_SERVE_ROUTE_HYST: score margin a rival
    # must beat the sticky choice by before the route moves
    heartbeat_s: float = 0.5  # NTS_SERVE_HEARTBEAT_S: monitor tick (0=off)

    @classmethod
    def from_cfg(cls, cfg: Any = None) -> "FleetOptions":
        o = cls()
        if cfg is not None:
            o.replicas = int(getattr(cfg, "serve_replicas", o.replicas))
            o.route = str(getattr(cfg, "serve_route", "") or o.route)
        raw = os.environ.get("NTS_SERVE_REPLICAS", "")
        if raw:
            try:
                o.replicas = int(raw)
            except ValueError:
                log.warning("NTS_SERVE_REPLICAS=%r is not an int; keeping %d",
                            raw, o.replicas)
        o.route = os.environ.get("NTS_SERVE_ROUTE", "") or o.route
        o.hysteresis = _env_float("NTS_SERVE_ROUTE_HYST", o.hysteresis)
        o.heartbeat_s = _env_float("NTS_SERVE_HEARTBEAT_S", o.heartbeat_s)
        if o.replicas < 1:
            raise ValueError(f"SERVE_REPLICAS must be >= 1, got {o.replicas}")
        if o.route not in _ROUTES:
            raise ValueError(
                f"SERVE_ROUTE must be one of {'|'.join(_ROUTES)}, "
                f"got {o.route!r}"
            )
        if o.hysteresis < 0:
            o.hysteresis = 0.0
        return o


def classify_states(
    states: Sequence[Dict[str, Any]]
) -> Tuple[List[Dict[str, Any]], Optional[str]]:
    """(healthy states, shed reason): the live/healthy split BOTH
    routing policies share — dead replicas never route, draining
    (SLO-breaching) ones drain, and the fleet-level shed reason exists
    ONLY when no healthy replica remains."""
    live = [s for s in states if s.get("beating")]
    if not live:
        return [], "fleet_down (no live replica)"
    healthy = [s for s in live if not s.get("draining")]
    if not healthy:
        # fleet-level shed ONLY here: every live replica is breaching
        return [], (
            f"fleet_breach (all {len(live)} live replica(s) breaching "
            "their SLO)"
        )
    return healthy, None


def choose_replica(states: Sequence[Dict[str, Any]],
                   sticky: Optional[int] = None,
                   hysteresis: float = 0.25) -> Tuple[Optional[int],
                                                      Optional[str]]:
    """The least-burn routing decision, pure (unit-testable).

    ``states``: per replica {idx, beating, draining, burn, depth,
    max_queue}. Returns (replica index, None) or (None, shed reason).
    Score = burn + depth/max_queue (both lower-is-better, burn dominates
    once an SLO is in trouble); the sticky previous choice is kept
    unless a rival's score beats it by more than ``hysteresis`` — equal
    replicas therefore do not flap the route."""
    healthy, reason = classify_states(states)
    if not healthy:
        return None, reason

    def score(s: Dict[str, Any]) -> float:
        return (s.get("burn") or 0.0) + (
            s.get("depth", 0) / max(s.get("max_queue", 1), 1)
        )

    best = min(healthy, key=score)
    if sticky is not None:
        st = next((s for s in healthy if s["idx"] == sticky), None)
        if st is not None and score(st) <= score(best) + hysteresis:
            return st["idx"], None
    return best["idx"], None


class Replica:
    """One fleet member: server + its labeled registry + identity."""

    def __init__(self, rid: str, idx: int, engine: InferenceEngine,
                 server: InferenceServer):
        self.rid = rid
        self.idx = idx
        self.engine = engine
        self.server = server
        self.restarts = 0
        # served/shed counts carried across supervised restarts: a fresh
        # InferenceServer starts at zero, but the replica's history —
        # and the fleet serve_summary, whose merged histogram spans the
        # whole registry — must not forget the dead incarnation's work
        self.carried_requests = 0
        self.carried_shed = 0

    def requests_total(self) -> int:
        return self.carried_requests + self.server.request_count

    def shed_total(self) -> int:
        return self.carried_shed + self.server.batcher.shed_count

    @property
    def registry(self):
        return self.server.metrics

    def beating(self) -> bool:
        return self.server.beating()

    def route_state(self) -> Dict[str, Any]:
        """The router's per-replica view — the same facts the exporter
        serves at /slo + /healthz, consumed in-process."""
        draining, burn = False, 0.0
        slo = self.server.slo
        if slo is not None:
            slo.tick()  # rate-limited internally
            draining, burn = slo.route_state()
        return {
            "idx": self.idx,
            "beating": self.beating(),
            "draining": draining,
            "burn": burn,
            "depth": self.server.batcher.depth,
            "max_queue": self.server.opts.max_queue,
        }


class ReplicaSet:
    """N replicas + router + heartbeat supervisor behind one submit()."""

    def __init__(self, engine: InferenceEngine,
                 options: Optional[ServeOptions] = None,
                 fleet: Optional[FleetOptions] = None,
                 cfg: Any = None, seed: int = 0):
        from neutronstarlite_tpu import obs
        from neutronstarlite_tpu.resilience import elastic, events

        self.engine = engine  # the warm template (never serves directly)
        self.opts = options or engine.opts
        self.fleet_opts = fleet or FleetOptions.from_cfg(
            cfg if cfg is not None else engine.cfg
        )
        self.cfg = cfg if cfg is not None else engine.cfg
        self._seed = seed
        # the fleet's own stream: front-door sheds, heartbeats,
        # rank_loss, restart recoveries, and the consolidated summary
        self.registry = obs.open_run("serve-fleet", cfg=self.cfg, seed=seed)
        self.registry.gauge_set("fleet.replicas", self.fleet_opts.replicas)
        self.registry.gauge_set("fleet.route", self.fleet_opts.route)
        # the fleet is the process's active run: LivenessMonitor beats and
        # restart recovery records flow through the resilience event sink
        events.set_sink(self.registry)
        self._events = events
        self.replicas: List[Replica] = [
            self._build_replica(i) for i in range(self.fleet_opts.replicas)
        ]
        self.shed_count = 0
        self._lock = threading.Lock()
        self._sticky: Optional[int] = None
        self._rr = 0
        self._closed = False
        self._monitor = elastic.LivenessMonitor(
            partitions=self.fleet_opts.replicas
        )
        self._tick = 0
        self._monitor_thread: Optional[threading.Thread] = None
        if self.fleet_opts.heartbeat_s > 0:
            self._monitor_thread = threading.Thread(
                target=self._monitor_loop, name="serve-fleet-monitor",
                daemon=True,
            )
            self._monitor_thread.start()
        log.info(
            "serve fleet up: %d replica(s), route=%s (hysteresis %.2f), "
            "heartbeat %.2fs x miss_k %d",
            self.fleet_opts.replicas, self.fleet_opts.route,
            self.fleet_opts.hysteresis, self.fleet_opts.heartbeat_s,
            self._monitor.miss_k,
        )

    # ---- construction ----------------------------------------------------
    @classmethod
    def from_engine(cls, engine: InferenceEngine, replicas: int,
                    options: Optional[ServeOptions] = None,
                    **kw) -> "ReplicaSet":
        fleet = FleetOptions.from_cfg(engine.cfg)
        fleet.replicas = int(replicas)
        return cls(engine, options=options, fleet=fleet, **kw)

    def _build_replica(self, idx: int) -> Replica:
        from neutronstarlite_tpu import obs

        rid = f"r{idx}"
        reg = obs.open_run(f"serve-{rid}", cfg=self.cfg, seed=self._seed)
        eng = self.engine.clone(
            metrics=reg,
            rng=np.random.default_rng(self._seed + 1000 * (idx + 1)),
        )
        server = InferenceServer(eng, options=self.opts, replica=rid)
        return Replica(rid, idx, eng, server)

    # ---- routing ---------------------------------------------------------
    def _route(self) -> Tuple[Optional[Replica], Optional[str]]:
        states = [r.route_state() for r in self.replicas]
        with self._lock:
            if self.fleet_opts.route == "round_robin":
                healthy, reason = classify_states(states)
                if not healthy:
                    return None, reason
                idx = healthy[self._rr % len(healthy)]["idx"]
                self._rr += 1
                return self.replicas[idx], None
            idx, reason = choose_replica(
                states, sticky=self._sticky,
                hysteresis=self.fleet_opts.hysteresis,
            )
            if idx is None:
                return None, reason
            self._sticky = idx
            return self.replicas[idx], None

    def submit(self, node_ids) -> ServeRequest:
        """The fleet front door: route to the least-burn healthy replica;
        fleet-level shed only when NO replica can take the request."""
        replica, reason = self._route()
        if replica is not None:
            return replica.server.submit(node_ids)
        ids = np.asarray(node_ids, dtype=np.int64).reshape(-1)
        req = ServeRequest(ids)
        with self._lock:
            self.shed_count += 1
        self.registry.counter_add("fleet.shed")
        self.registry.event(
            "shed", reason=reason, req_id=req.req_id,
        )
        self.registry.event(
            "serve_request", n_seeds=max(len(ids), 1), status="shed",
            total_ms=None, req_id=req.req_id,
        )
        req._complete(None, "shed", RequestShedError(reason))
        return req

    def predict(self, node_ids, timeout: Optional[float] = 60.0):
        return self.submit(node_ids).result(timeout)

    # ---- supervision (the elastic heartbeat pattern) ---------------------
    def _monitor_loop(self) -> None:
        from neutronstarlite_tpu.resilience.elastic import RankLossError

        while not self._closed:
            time.sleep(self.fleet_opts.heartbeat_s)
            if self._closed:
                return
            self._tick += 1
            alive = [
                i for i, r in enumerate(self.replicas) if r.beating()
            ]
            for i, r in enumerate(self.replicas):
                reg = r.registry
                if reg is not None:
                    reg.gauge_set("serve.beating", i in alive)
            try:
                self._monitor.epoch_end(self._tick, alive=alive)
            except RankLossError:
                pass  # detection below reads the miss counters directly
            for i in range(len(self.replicas)):
                if i in alive:
                    continue
                if self._monitor.missed(i) >= self._monitor.miss_k:
                    try:
                        self._restart(i)
                    except Exception as e:  # supervision must survive
                        log.warning("replica r%d restart failed (%s); "
                                    "retrying next tick", i, e)

    def _restart(self, idx: int) -> None:
        """Supervised replica restart: steal the dead replica's in-flight
        requests, bring up a fresh server over the same warm engine
        clone (zero recompiles — the shared AOT ladder), re-route the
        stolen work, and clear the liveness latch so a SECOND death
        re-detects."""
        if self._closed:
            return
        dead = self.replicas[idx]
        stolen = dead.server.steal_inflight()
        dead.server.inject_death()  # ensure the flusher really is gone
        if dead.server._prep_q is not None:
            dead.server._prep_q.put(None)  # release the old executor
        self._events.emit_recovery(
            "restart", replica=dead.rid, stolen_requests=len(stolen),
        )
        self.registry.counter_add("fleet.restarts")
        server = InferenceServer(
            dead.engine, options=self.opts, replica=dead.rid
        )
        fresh = Replica(dead.rid, idx, dead.engine, server)
        fresh.restarts = dead.restarts + 1
        fresh.carried_requests = dead.requests_total()
        fresh.carried_shed = dead.shed_total()
        with self._lock:
            if self._closed:
                # close() won the race while we were building: the fresh
                # server must not outlive the fleet (leaked threads + a
                # stream that never gets its serve_summary)
                server.close()
                return
            self.replicas[idx] = fresh
            if self._sticky == idx:
                self._sticky = None
        self._monitor.clear(idx)
        rerouted = 0
        for req in stolen:
            target, _reason = self._route()
            if target is None:
                target = fresh
            target.server.batcher.requeue(req)
            rerouted += 1
        log.warning(
            "replica %s restarted supervised (restart #%d); %d in-flight "
            "request(s) re-routed, none dropped",
            dead.rid, fresh.restarts, rerouted,
        )

    def inject_replica_death(self, idx: int) -> None:
        """Chaos hook (tests / FLEET_GATE): silence one replica the way a
        real thread death would — the heartbeat monitor must notice."""
        self.replicas[idx].server.inject_death()

    # ---- live graph deltas ----------------------------------------------
    def apply_delta(self, delta):
        """Fleet-wide delta: one plan, every replica's engine swapped
        under its graph gate, per-replica cache invalidation + records
        (serve/delta.py)."""
        from neutronstarlite_tpu.serve import delta as delta_mod

        plan = delta_mod.apply_to_servers(
            [r.server for r in self.replicas], delta,
            extra_engines=[self.engine],
        )
        self.registry.counter_add("fleet.graph_deltas")
        self.registry.gauge_set("graph.digest", plan.digest)
        return plan

    # ---- stats / close ---------------------------------------------------
    def _merged_latency(self):
        from neutronstarlite_tpu.obs.hist import LogHistogram

        merged: Optional[LogHistogram] = None
        for r in self.replicas:
            reg = r.registry
            if reg is None:
                continue
            h = reg.hists().get("serve.latency_ms")
            if h is None:
                continue
            merged = h if merged is None else merged.merge(h)
        return merged

    def stats(self) -> Dict[str, Any]:
        per = {}
        for r in self.replicas:
            s = r.server.stats()
            # across-restart totals: the fresh server's counters alone
            # would forget the dead incarnation's served/shed work
            s["requests"] = r.requests_total()
            s["shed"] = r.shed_total()
            per[r.rid] = s
        h = self._merged_latency()
        requests = sum(s["requests"] for s in per.values())
        shed = self.shed_count + sum(s["shed"] for s in per.values())
        spans = [
            (r.server._t_first, r.server._t_last) for r in self.replicas
            if r.server._t_first is not None and r.server._t_last is not None
        ]
        span = (
            max(b for _a, b in spans) - min(a for a, _b in spans)
            if spans else None
        )
        return {
            "replicas": len(self.replicas),
            "requests": requests,
            "shed": shed,
            "fleet_shed": self.shed_count,
            "restarts": sum(r.restarts for r in self.replicas),
            "latency_ms": (
                h.quantiles() if h is not None and h.count
                else {"p50": None, "p95": None, "p99": None}
            ),
            "throughput_rps": (
                requests / span if span and span > 0 else None
            ),
            "per_replica": per,
        }

    def telemetry_targets(self) -> List[str]:
        """Per-replica ``/telemetry?replica=rK`` URLs off the shared
        exporter — the fleet's poll targets for a cross-host hub
        (obs/hub): each URL serves ONE replica's full-resolution
        snapshot, so a hub pointed at them reconstructs the same merged
        p99 this fleet computes in-process. Empty when no exporter is
        armed (``NTS_METRICS_PORT`` unset)."""
        exps = [r.server.exporter for r in self.replicas
                if r.server is not None
                and getattr(r.server, "exporter", None) is not None]
        if not exps:
            return []
        exp = exps[0]  # maybe_start: one singleton port per process
        host = os.environ.get("NTS_METRICS_HOST", "127.0.0.1")
        return [
            f"http://{host}:{exp.port}/telemetry?replica={r.rid}"
            for r in self.replicas
        ]

    def stream_paths(self) -> List[str]:
        """Every JSONL stream this fleet writes (replicas + front door) —
        what serve_bench merges its percentiles from."""
        out = []
        for r in self.replicas:
            if r.registry is not None and r.registry.path:
                out.append(r.registry.path)
        if self.registry.path:
            out.append(self.registry.path)
        return out

    def close(self) -> Dict[str, Any]:
        """Drain every replica, emit the fleet serve_summary (merged
        latency histogram — the fleet p99 is exact), release the event
        sink."""
        if self._closed:
            return self.stats()
        self._closed = True
        if self._monitor_thread is not None:
            self._monitor_thread.join(
                timeout=self.fleet_opts.heartbeat_s * 4 + 1.0
            )
        for r in self.replicas:
            r.server.close()
        s = self.stats()
        snap = self.registry.snapshot()
        self.registry.event(
            "serve_summary",
            requests=s["requests"],
            shed=s["shed"],
            latency_ms=s["latency_ms"],
            throughput_rps=s["throughput_rps"],
            counters=snap["counters"],
            gauges=snap["gauges"],
            replicas=s["replicas"],
            restarts=s["restarts"],
            fleet=True,
        )
        self.registry.close()
        if self._events.get_sink() is self.registry:
            self._events.set_sink(None)
        return s
