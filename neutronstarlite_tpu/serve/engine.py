"""Inference engine: digest-verified checkpoint -> AOT bucket executables.

Turns a trained sampled-GCN toolkit into an online scorer:

1. **Checkpoint load.** The model is reconstructed through the trainer's
   own lifecycle (``get_algorithm`` -> ``init_graph``/``init_nn``) and the
   weights restored via utils/checkpoint.py — the same digest-verified,
   quarantine-on-corruption restore path training resume uses, so a
   bit-flipped checkpoint can never silently serve garbage.

2. **Eval-mode forward.** The per-bucket forward is the exact eval-mode
   computation of the sampled trainer (models/gcn_sample.py
   ``batch_forward`` with ``train=False``): feature gather ->
   per-hop ``minibatch_gather`` + matmul (+ relu between layers), dropout
   compiled out entirely. Served logits are therefore bit-identical to the
   toolkit's own eval forward on the same sampled batch (the parity oracle
   in tests/test_serve.py).

3. **AOT shape buckets.** Request batches vary in size, but XLA recompiles
   per shape — fatal for tail latency. So a small ladder of batch-size
   buckets (ServeOptions.ladder) is compiled ahead of time via
   ``jax.jit(...).lower(...).compile()``; every flush pads to the smallest
   covering bucket and replays that executable. ``compile_counts`` proves
   the discipline: exactly one compilation per bucket, ever — the
   fixed-shape compile-once design the sampler's padded capacities were
   built for (SURVEY.md "pad to fanout capacity ... to avoid
   recompilation"; Accel-GCN's fixed-shape execution argument).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from neutronstarlite_tpu.ops.minibatch import get_feature, minibatch_gather
from neutronstarlite_tpu.sample.sampler import SampledBatch
from neutronstarlite_tpu.serve.batcher import ServeOptions
from neutronstarlite_tpu.serve.sampling import ServeSampler
from neutronstarlite_tpu.utils.config import InputInfo
from neutronstarlite_tpu.utils.logging import get_logger

log = get_logger("serve")


class ServeSetupError(RuntimeError):
    """Unservable configuration (no checkpoint, unsupported model, ...)."""


def _eval_forward_fn(caps: List[int], compute_dtype):
    """The bucket's eval-mode forward — textually the ``train=False`` path
    of GCNSampleTrainer.build_model's batch_forward (dropout never traced),
    closed over this bucket's node capacities."""

    def cast(a):
        return a.astype(compute_dtype) if compute_dtype is not None else a

    def forward(params, feature, nodes, hops):
        x = cast(get_feature(feature, nodes[0]))
        for i, (p, (src_l, dst_l, w)) in enumerate(zip(params, hops)):
            agg = minibatch_gather(src_l, dst_l, w, x, caps[i + 1])
            h = cast(agg) @ cast(p["W"])
            if i < len(params) - 1:
                h = jax.nn.relu(h)
            x = h
        return x.astype(jnp.float32)  # [bucket, n_classes]

    return forward


def _fused_forward_fn(caps: List[int], fanouts: List[int], compute_dtype):
    """SAMPLE_PIPELINE:fused — the request's WHOLE cache-miss path as one
    program: on-device fan-out draw + dedup/remap (sample/fused.py) feeding
    the same eval-mode forward, so a served bucket is sample+execute in ONE
    dispatch. Operands are the resident tables plus a padded seed vector,
    the live count and a draw key — no per-request subgraph H2D."""
    forward = _eval_forward_fn(caps, compute_dtype)
    from neutronstarlite_tpu.sample.fused import fused_sample_subgraph

    caps_t, fans_t = tuple(int(c) for c in caps), tuple(int(f) for f in fanouts)

    def fused_forward(params, feature, nbr, eff_deg, out_deg, in_deg,
                      seeds_pad, n_real, key):
        nodes, hops = fused_sample_subgraph(
            nbr, eff_deg, out_deg, in_deg, seeds_pad, n_real, key,
            caps_t, fans_t,
        )
        return forward(params, feature, nodes, hops)

    return fused_forward


def batch_device_args(batch: SampledBatch):
    """SampledBatch -> the (nodes, hops) device pytree, one conversion for
    both the AOT lowering and every steady-state call (shapes and dtypes
    must match the compiled executable's avals exactly)."""
    nodes = [jnp.asarray(n) for n in batch.nodes]
    hops = [
        (jnp.asarray(h.src_local), jnp.asarray(h.dst_local),
         jnp.asarray(h.weight))
        for h in batch.hops
    ]
    return nodes, hops


class InferenceEngine:
    """Checkpoint-backed scorer with a ladder of AOT bucket executables."""

    def __init__(
        self,
        toolkit: Any,
        ckpt_dir: str,
        options: Optional[ServeOptions] = None,
        metrics: Any = None,
        rng: Optional[np.random.Generator] = None,
    ):
        self.toolkit = toolkit
        self.cfg = toolkit.cfg
        self.opts = options or ServeOptions.from_cfg(self.cfg)
        self.metrics = metrics if metrics is not None else toolkit.metrics
        # structural check FIRST: an unservable parameter family must fail
        # with this message, not an opaque tree-mismatch inside restore
        self._check_servable(toolkit.params)
        self._restore(ckpt_dir)
        self.params = toolkit.params
        self.feature = toolkit.feature
        fanouts = getattr(toolkit, "fanouts", None)
        if not fanouts:
            sizes = self.cfg.layer_sizes()
            fanouts = self.cfg.fanouts()[-(len(sizes) - 1):]
        if not fanouts:
            raise ServeSetupError(
                "serving samples per-request fan-outs; the cfg needs FANOUT"
            )
        self.fanouts = list(fanouts)
        self.compute_dtype = (
            jnp.bfloat16 if self.cfg.precision == "bfloat16" else None
        )
        hop_sampler = None
        if self.opts.sample_pipeline in ("device", "fused"):
            # SAMPLE_PIPELINE:device — per-request fan-outs draw on-device
            # (sample/device_sampler.py); distribution-equivalent to the
            # host sampler, see docs/SAMPLING.md. fused goes further: the
            # same table feeds the one-dispatch sample+execute program
            # (_fused_forward_fn). The sampled trainer this engine
            # restored through already built the neighbor table for
            # the same mode — reuse it rather than uploading a second copy.
            hop_sampler = getattr(
                getattr(toolkit, "par_sampler", None), "hop_sampler", None
            )
            if hop_sampler is None:
                from neutronstarlite_tpu.sample.device_sampler import (
                    DeviceUniformSampler,
                )

                hop_sampler = DeviceUniformSampler.from_host(
                    toolkit.host_graph
                )
        self.sampler = ServeSampler(
            toolkit.host_graph, self.fanouts, self.opts.ladder(), rng=rng,
            hop_sampler=hop_sampler,
        )
        self.buckets = self.sampler.buckets
        self._compiled: Dict[int, Any] = {}
        # fused ladder: bucket -> (table_shapes, executable). Keyed off the
        # live table shapes so a delta that REBUILT the neighbor table
        # (new V or width) recompiles instead of feeding the executable
        # shape-mismatched operands; in-place row patches keep the program.
        self._fused_compiled: Dict[int, Any] = {}
        # degree vectors shared across clones, re-derived when a delta
        # swaps the host graph (mutated in place so clones see the swap)
        self._fused_shared: Dict[str, Any] = {"graph": None, "degrees": None}
        self.compile_counts: Dict[int, int] = {}
        # shared across clones (serve/fleet.py): two replica executors
        # racing a cold bucket must still compile it exactly once
        self._compile_lock = threading.Lock()

    def clone(self, metrics: Any = None,
              rng: Optional[np.random.Generator] = None) -> "InferenceEngine":
        """A warm replica engine over the SAME toolkit/params/graph.

        The serve fleet's replica N+1 startup path: the clone shares the
        checkpoint-restored params, the feature slab, the device hop
        sampler table, and — crucially — the AOT bucket ladder
        (``_compiled``/``compile_counts`` are the same dicts), so a new
        replica serves its first request with ZERO recompiles; and since
        the toolkit (with its tune-resolved knobs and cached graph
        digest) is shared, nothing is ever re-measured (the PR 9
        decision cache did that work once). Only the ServeSampler is
        fresh: numpy Generators are not thread-safe, so each replica
        draws from its own."""
        new = object.__new__(InferenceEngine)
        new.toolkit = self.toolkit
        new.cfg = self.cfg
        new.opts = self.opts
        new.metrics = metrics if metrics is not None else self.metrics
        new.params = self.params
        new.feature = self.feature
        new.fanouts = list(self.fanouts)
        new.compute_dtype = self.compute_dtype
        new.ckpt_step = self.ckpt_step
        new.sampler = ServeSampler(
            self.sampler.graph, self.fanouts, self.opts.ladder(), rng=rng,
            hop_sampler=self.sampler.hop_sampler,
        )
        new.buckets = new.sampler.buckets
        new._compiled = self._compiled
        new._fused_compiled = self._fused_compiled
        new._fused_shared = self._fused_shared
        new.compile_counts = self.compile_counts
        new._compile_lock = self._compile_lock
        return new

    @property
    def fused(self) -> bool:
        """SAMPLE_PIPELINE:fused — serve cache misses through the
        one-dispatch sample+execute ladder instead of host sample +
        bucket forward."""
        return self.opts.sample_pipeline == "fused"

    def graph_digest(self) -> str:
        """The canonical digest of the graph this engine serves — the
        tune-cache/perf-ledger keying fact a graph delta bumps
        (serve/delta.py updates the toolkit's cached copy)."""
        digest = getattr(self.toolkit, "_tune_graph_digest", None)
        if digest is None:
            from neutronstarlite_tpu.graph.digest import graph_digest

            digest = graph_digest(self.sampler.graph)
            self.toolkit._tune_graph_digest = digest
        return digest

    def apply_delta(self, delta) -> Any:
        """Engine-level delta application (no cache/batcher state — the
        server/fleet paths add those; serve/delta.py has the
        semantics)."""
        from neutronstarlite_tpu.serve import delta as delta_mod

        return delta_mod.apply_to_engines([self], delta)

    # ---- construction ----------------------------------------------------
    @classmethod
    def from_config(
        cls,
        cfg: InputInfo,
        base_dir: Optional[str] = None,
        ckpt_dir: str = "",
        options: Optional[ServeOptions] = None,
        rng: Optional[np.random.Generator] = None,
    ) -> "InferenceEngine":
        """Full lifecycle from a cfg file's contents: load graph + datum,
        build the model, restore the checkpoint."""
        from neutronstarlite_tpu.models import get_algorithm

        ckpt = ckpt_dir or cfg.checkpoint_dir
        if not ckpt:
            raise ServeSetupError(
                "no checkpoint directory: pass one explicitly or set "
                "CHECKPOINT_DIR in the cfg"
            )
        # serving never consumes the training batch stream — suppress the
        # sampled trainer's forked worker pool for this construction
        prev = os.environ.get("NTS_SAMPLE_WORKERS")
        os.environ["NTS_SAMPLE_WORKERS"] = "0"
        try:
            toolkit = get_algorithm(cfg.algorithm)(cfg, base_dir=base_dir)
            toolkit.init_graph()
            toolkit.init_nn()
        finally:
            if prev is None:
                os.environ.pop("NTS_SAMPLE_WORKERS", None)
            else:
                os.environ["NTS_SAMPLE_WORKERS"] = prev
        return cls(toolkit, ckpt, options=options, rng=rng)

    def _restore(self, ckpt_dir: str) -> None:
        from neutronstarlite_tpu.utils.checkpoint import have_checkpoint

        if not ckpt_dir or not have_checkpoint(
            ckpt_dir, getattr(self.cfg, "ckpt_backend", "")
        ):
            raise ServeSetupError(
                f"no checkpoint under {ckpt_dir!r} — train first "
                "(CHECKPOINT_DIR + a run), or point serving at an "
                "existing one"
            )
        step = self.toolkit.restore(ckpt_dir)  # digest-verified restore
        if step == 0 and not have_checkpoint(
            ckpt_dir, getattr(self.cfg, "ckpt_backend", "")
        ):
            # every retained step failed verification and was quarantined
            raise ServeSetupError(
                f"every checkpoint under {ckpt_dir!r} failed integrity "
                "verification (quarantined *.corrupt)"
            )
        self.ckpt_step = step
        log.info("serving checkpoint step %d from %s", step, ckpt_dir)

    # the one parameter family the AOT bucket forward can rebuild today;
    # grows as the engine learns more model forwards
    SERVABLE_FAMILIES = (
        "sampled-GCN (params = [{'W': ...}, ...]; ALGORITHM:GCNSAMPLESINGLE)",
    )

    @staticmethod
    def _param_family(p) -> str:
        """Best-effort name for a parameter tree's model family, so the
        refusal names what the checkpoint IS, not just what it isn't."""
        if not isinstance(p, (list, tuple)) or not p:
            return f"non-layer-list params ({type(p).__name__})"
        keys = set()
        for layer in p:
            if not isinstance(layer, dict):
                return f"layer list with non-dict entries ({type(layer).__name__})"
            keys |= set(layer)
        if "a" in keys:
            return "GAT family (attention vector 'a' present)"
        if "Ws" in keys or "Wd" in keys:
            return "GGCN family (gated edge-NN weights Ws/Wd)"
        if "W1" in keys or "W2" in keys:
            return "GIN family (two-layer MLP W1/W2)"
        if "C" in keys or "H" in keys:
            return "CommNet family (C/H projections)"
        if "bn" in keys:
            return "full-batch GCN family (batch-norm stats present)"
        return f"unrecognized family (layer keys: {sorted(keys)})"

    def _check_servable(self, p) -> None:
        """The engine serves the sampled-GCN parameter family: a list of
        layers each holding exactly one dense ``W``. Anything else (bn
        stats, attention params) would silently skip math — refuse,
        naming the DETECTED family and the supported list."""
        ok = isinstance(p, (list, tuple)) and len(p) > 0 and all(
            isinstance(layer, dict) and set(layer) == {"W"} for layer in p
        )
        if not ok:
            supported = "; ".join(self.SERVABLE_FAMILIES)
            raise ServeSetupError(
                f"ALGORITHM {self.cfg.algorithm!r} checkpoints are not "
                f"servable: detected {self._param_family(p)}; the engine "
                f"supports: {supported}"
            )

    # ---- AOT bucket executables ------------------------------------------
    def warmup(self, buckets: Optional[List[int]] = None) -> None:
        """Compile the executable ladder ahead of traffic (the ladder the
        configured pipeline actually serves through)."""
        for b in buckets if buckets is not None else self.buckets:
            if self.fused:
                self._ensure_fused(int(b))
            else:
                self._ensure_compiled(int(b))

    def _ensure_compiled(self, bucket: int):
        compiled = self._compiled.get(bucket)
        if compiled is not None:
            return compiled
        with self._compile_lock:
            return self._compile_bucket(bucket)

    def _compile_bucket(self, bucket: int):
        compiled = self._compiled.get(bucket)  # a racing clone got here first
        if compiled is not None:
            return compiled
        caps = self.sampler.node_caps(bucket)
        forward = _eval_forward_fn(caps, self.compute_dtype)
        # one host-side sample supplies shape-representative args: padded
        # capacities are static per bucket, so any seed set works. The
        # draw must be RNG-NEUTRAL (state saved + restored): otherwise a
        # warm engine (cloned AOT ladder, zero compiles) and a cold one
        # consume different rng streams and the "one seed replays the
        # serving trace bit-identically" contract breaks between them —
        # the delta oracle compares exactly such a warm/cold pair
        rng_state = self.sampler.rng.bit_generator.state
        try:
            rep = self.sampler.sample(
                bucket, np.zeros(1, np.int64)
            )
        finally:
            self.sampler.rng.bit_generator.state = rng_state
        nodes, hops = batch_device_args(rep)
        t0 = time.perf_counter()
        compiled = jax.jit(forward).lower(
            self.params, self.feature, nodes, hops
        ).compile()
        dt = time.perf_counter() - t0
        self._compiled[bucket] = compiled
        self.compile_counts[bucket] = self.compile_counts.get(bucket, 0) + 1
        if self.metrics is not None:
            self.metrics.counter_add(f"serve.compiles.bucket_{bucket}")
            self.metrics.observe("serve.compile", dt)
            # compiled-program cost attribution (obs/cost): the bucket
            # executable already exists, so cost AND memory analysis are
            # free reads — the real per-bucket HBM envelope next to the
            # ladder's shape math
            from neutronstarlite_tpu.obs.cost import capture_program_cost

            capture_program_cost(
                self.metrics, f"serve.bucket_{bucket}", compiled=compiled,
                bucket=bucket, compile_s=round(dt, 4),
            )
        log.info("AOT-compiled bucket %d (caps %s) in %.3fs", bucket, caps, dt)
        return compiled

    # ---- fused one-dispatch ladder (SAMPLE_PIPELINE:fused) ----------------
    def _fused_exec_tables(self):
        """The live device operand tables of the fused program — read at
        call time, never snapshotted at construction: a graph delta
        patches/rebuilds ``hop_sampler.nbr``/``eff_deg`` in place
        (serve/delta.py) and swaps the host graph, and the next request
        must draw from the post-delta structure."""
        hs = self.sampler.hop_sampler
        shared = self._fused_shared
        g = self.sampler.graph
        if shared["graph"] is not g:
            from neutronstarlite_tpu.sample.fused import degree_tables

            shared["degrees"] = degree_tables(g)
            shared["graph"] = g
        out_deg, in_deg = shared["degrees"]
        return hs.nbr, hs.eff_deg, out_deg, in_deg

    def _ensure_fused(self, bucket: int):
        tables = self._fused_exec_tables()
        shapes = tuple(a.shape for a in tables)
        entry = self._fused_compiled.get(bucket)
        if entry is not None and entry[0] == shapes:
            return entry[1]
        with self._compile_lock:
            entry = self._fused_compiled.get(bucket)
            if entry is not None and entry[0] == shapes:
                return entry[1]
            return self._compile_fused_bucket(bucket, tables, shapes)

    def _compile_fused_bucket(self, bucket: int, tables, shapes):
        caps = self.sampler.node_caps(bucket)
        fn = _fused_forward_fn(caps, self.fanouts, self.compute_dtype)
        seeds = jnp.zeros((bucket,), jnp.int32)
        t0 = time.perf_counter()
        compiled = jax.jit(fn).lower(
            self.params, self.feature, *tables, seeds, np.int32(1),
            jax.random.PRNGKey(0),
        ).compile()
        dt = time.perf_counter() - t0
        self._fused_compiled[bucket] = (shapes, compiled)
        self.compile_counts[bucket] = self.compile_counts.get(bucket, 0) + 1
        if self.metrics is not None:
            self.metrics.counter_add(f"serve.compiles.bucket_{bucket}")
            self.metrics.observe("serve.compile", dt)
            from neutronstarlite_tpu.obs.cost import capture_program_cost

            capture_program_cost(
                self.metrics, f"serve.fused_bucket_{bucket}",
                compiled=compiled, bucket=bucket, compile_s=round(dt, 4),
            )
        log.info(
            "AOT-compiled fused bucket %d (caps %s, sample+execute one "
            "dispatch) in %.3fs", bucket, caps, dt,
        )
        return compiled

    def prepare_fused(self, ids: np.ndarray, bucket: int):
        """The fused flush's produce stage: pad the miss set to the bucket
        and stage (seeds, live count, draw key) — the ONLY per-request
        operands; the subgraph itself never exists host-side. The draw key
        consumes the sampler's shared Generator so a serving trace stays
        replayable end-to-end from one seed (the device-mode contract)."""
        ids = np.asarray(ids, dtype=np.int64).reshape(-1)
        seeds = np.zeros((int(bucket),), dtype=np.int32)
        seeds[: len(ids)] = ids
        key = jax.random.PRNGKey(
            int(self.sampler.rng.integers(0, 2 ** 31 - 1))
        )
        seeds_dev, key_dev = jax.device_put((seeds, key))
        return seeds_dev, np.int32(len(ids)), key_dev

    def execute_fused_prepared(self, prepared, bucket: int,
                               exec_ctx=None) -> np.ndarray:
        """ONE dispatch: on-device draw + remap + gather + forward for a
        prepared fused flush. ``exec_ctx`` is the pipelined server's
        produce-time (executable, params, feature, tables) snapshot."""
        b = int(bucket)
        if exec_ctx is not None:
            compiled, params, feature, tables = exec_ctx
        else:
            compiled = self._ensure_fused(b)
            params, feature = self.params, self.feature
            tables = self._fused_exec_tables()
        seeds, n_real, key = prepared
        out = np.asarray(
            compiled(params, feature, *tables, seeds, n_real, key)
        )
        if self.metrics is not None:
            self.metrics.counter_add(f"serve.fused_dispatches.bucket_{b}")
            from neutronstarlite_tpu.obs import numerics

            if numerics.numerics_enabled():
                numerics.observe_serve_batch(self.metrics, out, b)
        return out

    def fused_predict_rows(self, ids: np.ndarray,
                           bucket: Optional[int] = None) -> np.ndarray:
        """Fresh fused logits [n, n_classes] for arbitrary vertex ids —
        prepare + the one dispatch."""
        ids = np.asarray(ids, dtype=np.int64).reshape(-1)
        b = int(bucket) if bucket is not None \
            else self.sampler.bucket_for(len(ids))
        logits = self.execute_fused_prepared(self.prepare_fused(ids, b), b)
        return logits[: len(ids)]

    # ---- scoring ---------------------------------------------------------
    def prepare_batch(self, batch: SampledBatch):
        """SampledBatch -> device-resident (nodes, hops), the H2D stage of
        the two-stage serve pipeline: issued through ONE ``jax.device_put``
        so the copy is in flight while the previous flush executes."""
        return jax.device_put((
            [np.asarray(n) for n in batch.nodes],
            [(h.src_local, h.dst_local, h.weight) for h in batch.hops],
        ))

    def execute_prepared(self, nodes, hops, bucket: int) -> np.ndarray:
        """Run the bucket's AOT executable over already-device-resident
        batch arrays (the executor stage)."""
        compiled = self._ensure_compiled(int(bucket))
        out = np.asarray(compiled(self.params, self.feature, nodes, hops))
        if self.metrics is not None:
            # numerics plane (NTS_NUMERICS=1): engine stats on every
            # executed request batch — host numpy over the logits the
            # reply already fetched (no extra device sync); a non-finite
            # batch leaves a LOUD tensor_stats record, the gauges track
            # the last batch either way
            from neutronstarlite_tpu.obs import numerics

            if numerics.numerics_enabled():
                numerics.observe_serve_batch(self.metrics, out, bucket)
        return out

    def forward_batch(self, batch: SampledBatch,
                      bucket: Optional[int] = None) -> np.ndarray:
        """Logits [bucket, n_classes] for a prepared SampledBatch (rows
        beyond the real seed count are padding)."""
        b = int(bucket) if bucket is not None else len(batch.seeds)
        nodes, hops = batch_device_args(batch)
        return self.execute_prepared(nodes, hops, b)

    def predict(self, node_ids: np.ndarray) -> np.ndarray:
        """Fresh-sampled logits [n, n_classes] for arbitrary vertex ids."""
        ids = np.asarray(node_ids, dtype=np.int64).reshape(-1)
        bucket = self.sampler.bucket_for(len(ids))
        if self.fused:
            return self.fused_predict_rows(ids, bucket)
        batch = self.sampler.sample(bucket, ids)
        logits = self.forward_batch(batch, bucket)
        return logits[: len(ids)]
