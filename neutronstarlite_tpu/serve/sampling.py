"""Serving-side sampling: per-request fan-out + inference embedding cache.

Fresh-node fan-out reuses the training sampler verbatim (sample/sampler.py
— the host-side ``Sampler::reservoir_sample`` reproduction): one Sampler
per AOT shape bucket, all sharing ONE injectable ``numpy.random.Generator``
so a serving run is reproducible end-to-end from a single seed (and tests
can replay exact fan-outs without monkeypatching).

The embedding cache is the serving instance of the hybrid dependency
management idea (parallel/feature_cache.py): a vertex's logits can be
(1) recomputed fresh every request — exact, pays sample+forward; or
(2) served from a bounded LRU cache — zero compute, bounded staleness.
Which vertices are worth caching follows the same hot/cold split rule as
the training-side DepCache (``hot_vertex_mask``: out-degree >= threshold;
a row referenced by many consumers amortizes its cache slot). Staleness is
bounded by ``cache_max_age_s`` — entries older than that are recomputed,
the serving analog of the training cache's ``cache_refresh`` epochs.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from neutronstarlite_tpu.graph.storage import CSCGraph
from neutronstarlite_tpu.parallel.feature_cache import hot_vertex_mask
from neutronstarlite_tpu.sample.sampler import SampledBatch, Sampler


class ServeSampler:
    """One training-equivalent Sampler per shape bucket, shared RNG."""

    def __init__(
        self,
        graph: CSCGraph,
        fanouts: Sequence[int],
        buckets: Sequence[int],
        seed: int = 0,
        rng: Optional[np.random.Generator] = None,
        hop_sampler=None,
    ):
        self.graph = graph
        self.fanouts = list(fanouts)
        self.hop_sampler = hop_sampler
        self.rng = np.random.default_rng(seed) if rng is None else rng
        # buckets share the injected Generator: draws interleave in request
        # order, so a serving trace replays bit-identically from one seed.
        # hop_sampler (SAMPLE_PIPELINE:device): the on-device uniform draw
        # (sample/device_sampler.py), shared across buckets too.
        self._samplers: Dict[int, Sampler] = {
            int(b): Sampler(
                graph, np.empty(0, np.int64), int(b), self.fanouts,
                rng=self.rng, hop_sampler=hop_sampler,
            )
            for b in buckets
        }
        self.buckets = sorted(self._samplers)

    def bucket_for(self, n_seeds: int) -> int:
        """Smallest bucket holding ``n_seeds`` (callers cap at max_batch ==
        the top bucket, so this always resolves)."""
        for b in self.buckets:
            if n_seeds <= b:
                return b
        raise ValueError(
            f"{n_seeds} seeds exceed the largest bucket {self.buckets[-1]}"
        )

    def node_caps(self, bucket: int) -> List[int]:
        return self._samplers[int(bucket)].node_caps

    def sample(self, bucket: int, seed_ids: np.ndarray) -> SampledBatch:
        return self._samplers[int(bucket)].sample_batch(seed_ids)

    def set_graph(self, graph: CSCGraph) -> None:
        """Swap in a post-delta host graph (serve/delta.py): every bucket
        Sampler re-points at the new structure; capacities/fanouts/rng
        are graph-independent and keep their state."""
        self.graph = graph
        for s in self._samplers.values():
            s.graph = graph


class EmbeddingCache:
    """Bounded LRU of per-vertex inference outputs with a staleness TTL.

    Thread-safe (the batcher flushes from its own thread while stats are
    read from clients). ``capacity <= 0`` disables everything — gets miss,
    puts drop — so callers never branch on "is there a cache".
    """

    def __init__(
        self,
        capacity: int,
        max_age_s: float = 60.0,
        hot_mask: Optional[np.ndarray] = None,
        clock=time.monotonic,
    ):
        self.capacity = int(capacity)
        self.max_age_s = float(max_age_s)
        # hot/cold split: only vertices flagged hot are cacheable; None =
        # every vertex (threshold 0 in hot_vertex_mask terms)
        self.hot_mask = hot_mask
        self.clock = clock
        self._lock = threading.Lock()
        self._rows: "OrderedDict[int, Tuple[float, np.ndarray]]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.expired = 0
        self.invalidated = 0

    @classmethod
    def for_graph(cls, graph: CSCGraph, capacity: int, max_age_s: float,
                  hot_threshold: int) -> "EmbeddingCache":
        mask = (
            hot_vertex_mask(graph, hot_threshold) if hot_threshold > 0
            else None
        )
        return cls(capacity, max_age_s, hot_mask=mask)

    def lookup(self, vid: int) -> Optional[np.ndarray]:
        """Fresh cached row for ``vid`` or None (stale entries evict)."""
        if self.capacity <= 0:
            return None
        with self._lock:
            got = self._rows.get(int(vid))
            if got is None:
                self.misses += 1
                return None
            t, row = got
            if self.clock() - t > self.max_age_s:
                del self._rows[int(vid)]
                self.expired += 1
                self.misses += 1
                return None
            self._rows.move_to_end(int(vid))
            self.hits += 1
            return row

    def insert(self, vids: np.ndarray, rows: np.ndarray) -> int:
        """Cache freshly computed rows for the cache-eligible (hot) ids;
        returns how many were inserted. LRU-evicts beyond capacity."""
        if self.capacity <= 0:
            return 0
        now = self.clock()
        inserted = 0
        with self._lock:
            for vid, row in zip(np.asarray(vids).tolist(), rows):
                if self.hot_mask is not None and not self.hot_mask[vid]:
                    continue
                self._rows[int(vid)] = (now, np.asarray(row))
                self._rows.move_to_end(int(vid))
                inserted += 1
            while len(self._rows) > self.capacity:
                self._rows.popitem(last=False)
        return inserted

    def invalidate(self, vids) -> int:
        """Drop the cached rows for exactly ``vids`` (the graph-delta
        dirty set, serve/delta.py) — entries for untouched vertices keep
        hitting; returns how many entries were actually dropped."""
        if self.capacity <= 0:
            return 0
        n = 0
        with self._lock:
            for vid in np.asarray(vids, dtype=np.int64).tolist():
                if self._rows.pop(int(vid), None) is not None:
                    n += 1
            self.invalidated += n
        return n

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "entries": len(self._rows),
                "hits": self.hits,
                "misses": self.misses,
                "expired": self.expired,
                "invalidated": self.invalidated,
            }
