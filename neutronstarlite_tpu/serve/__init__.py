"""serve/ — online GNN inference serving (docs/SERVING.md).

The training-only reproduction turned into a service: a digest-verified
checkpoint is reconstructed in eval mode, a ladder of shape-bucketed
forward executables is AOT-compiled once (engine.py), per-node requests
coalesce in a deadline/size micro-batching queue with explicit overload
shedding (batcher.py), fresh-node fan-outs reuse the training sampler with
an LRU inference embedding cache on top (sampling.py), and every serving
event is a typed obs/ record (server.py) rendered by tools/metrics_report.

Fleet scale: fleet.py runs SERVE_REPLICAS SLO-routed replicas (least-burn
with hysteresis, drain-on-breach, fleet-shed only on all-breach,
heartbeat-supervised restart) behind one submit(); SERVE_CB adds
continuous batching; delta.py applies live graph deltas between flushes
with incremental invalidation and a graph-digest bump.

Entry points:
  python -m neutronstarlite_tpu.serve.server <cfg> [<ckpt_dir>]
  python -m neutronstarlite_tpu.tools.serve_bench <cfg> [--train]
      [--replicas N] [--cb 0|1] [--delta-rate R] ...
"""

import importlib

# lazy re-exports: importing the package (or its light modules — batcher,
# sampling — e.g. from the jax-free report CLI) must not pull jax via
# engine/server
_EXPORTS = {
    "MicroBatcher": "batcher",
    "RequestShedError": "batcher",
    "ServeOptions": "batcher",
    "ServeRequest": "batcher",
    "latency_percentiles": "batcher",
    "InferenceEngine": "engine",
    "ServeSetupError": "engine",
    "EmbeddingCache": "sampling",
    "ServeSampler": "sampling",
    "InferenceServer": "server",
    "FleetOptions": "fleet",
    "Replica": "fleet",
    "ReplicaSet": "fleet",
    "choose_replica": "fleet",
    "DeltaPlan": "delta",
    "GraphDelta": "delta",
    "plan_delta": "delta",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    return getattr(
        importlib.import_module(f"neutronstarlite_tpu.serve.{mod}"), name
    )
