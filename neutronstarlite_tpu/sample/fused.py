"""Fused on-device sampling: draw -> remap -> gather -> train, ONE program.

``SAMPLE_PIPELINE:device`` (sample/device_sampler.py) moved only the
per-hop draw on-device — dedup/remap/weights still round-trip through the
host, so every mini-batch pays an H2D staging copy and a fresh dispatch.
This module takes the hardware-sampling direction (PAPERS.md,
arXiv:2209.02916) to its limit, ``SAMPLE_PIPELINE:fused``: the WHOLE
batch — seed shuffle, hop draws, dedup/remap, feature gather,
forward/backward, optimizer — is one jitted program over the resident
neighbor table (device_sampler's fixed-width [V, D] layout), the degree
vectors and the (margin-padded, stream-compatible) feature slab; whole
epochs then wrap in ``lax.scan`` over per-(epoch, index) fold-in keys so
a training epoch is ONE dispatch with ZERO per-batch host->device
transfer (``sample.h2d_bytes`` reads exactly 0 — scalar dispatch
operands like the epoch index are not batch payload and are not
counted).

The fixed-shape trick everywhere: every hop works at the sampler's
static capacities (``node_caps``/``fanouts``, sample/sampler.py), so one
program per batch-count bucket compiles once and replays — the serve
AOT ladder's discipline (serve/engine.py), now applied to training.

On-device dedup+remap (:func:`device_dedup_remap`) reproduces the host
``np.unique + np.searchsorted`` sorted-unique semantics exactly with a
stable sort + new-run cumsum + fixed-width scatter; capacity overflow is
impossible in-pipeline because a hop's candidate count equals its unique
capacity (``ecap = node_caps[h+1] * fanout == node_caps[h]``) by the
sampler's capacity construction.

Determinism contract (docs/SAMPLING.md): fused draws consume
``jax.random`` fold-in streams keyed on (epoch, batch index, hop), so
fused mode is DISTRIBUTION-equivalent to the host sampler (same
top-k-of-uniform-priorities construction; the statistical oracles in
tests pin it) and BITWISE deterministic across reruns of the same seed —
the same contract device mode carries, now for the whole batch.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from neutronstarlite_tpu.utils.logging import get_logger

log = get_logger("fused_sample")

# fold-in tags separating the fused key streams: the per-batch key feeds
# dropout exactly like the sync path's bkey; the draw stream must not
# alias it or sampling would correlate with dropout masks
_DRAW_TAG = 0x5eed
_SHUFFLE_TAG = 0x5f0e


@functools.partial(jax.jit, static_argnums=(2,))
def device_dedup_remap(src, valid, ncap: int):
    """On-device ``np.unique`` + ``np.searchsorted`` at fixed width.

    ``src [E]`` candidate global ids, ``valid [E]`` which entries are
    real draws; returns ``(uniq [ncap], src_local [E], n_uniq)`` where
    ``uniq`` holds the sorted distinct valid ids (zero-padded past
    ``n_uniq``) and ``src_local[i]`` is the batch-local index of
    ``src[i]`` in ``uniq`` (0 for invalid entries — the host padder's
    fill). Matches the host dedup bit-for-bit: sorted-unique order,
    searchsorted indices.

    Construction: invalid slots are priced to the dtype's max sentinel,
    a STABLE argsort groups equal ids into runs, the run-head flags
    cumsum into dense ranks, and a ``mode='drop'`` scatter places each
    run head at its rank (ranks past ``ncap`` fall off the edge instead
    of corrupting memory — in-pipeline they cannot occur, since the
    sampler's ``ecap == ncap`` capacity identity bounds uniques by
    construction). Real ids must stay below the sentinel (graph vertex
    ids always do: ``v_num < iinfo(int32).max``).
    """
    E = src.shape[0]
    sent = jnp.iinfo(src.dtype).max
    keyv = jnp.where(valid, src, sent)
    order = jnp.argsort(keyv)  # stable: equal ids keep input order
    sv = keyv[order]
    prev = jnp.concatenate([jnp.full((1,), -1, dtype=sv.dtype), sv[:-1]])
    new_run = (sv != prev) & (sv != sent)
    rank = jnp.cumsum(new_run) - 1  # dense rank of each sorted slot's id
    n_uniq = new_run.sum().astype(jnp.int32)
    uniq = jnp.zeros((ncap,), dtype=src.dtype).at[
        jnp.where(new_run, rank, ncap)
    ].set(sv, mode="drop")
    sorted_local = jnp.where(sv != sent, rank, 0).astype(jnp.int32)
    src_local = jnp.zeros((E,), dtype=jnp.int32).at[order].set(sorted_local)
    return uniq, src_local, n_uniq


def _draw_hop(nbr, eff_deg, key, dsts_pad, n_dst, fanout: int):
    """One fused uniform without-replacement draw over a PADDED dst set:
    the device_sampler._hop construction (k smallest per-slot priorities,
    padding slots priced out at 2) plus the row mask ``row < n_dst`` —
    padded dst rows index row 0, which has a REAL effective degree, so
    without the mask they would contribute phantom draws."""
    rows = nbr[dsts_pad]  # [dcap, D]
    eff = eff_deg[dsts_pad]
    slot = jnp.arange(rows.shape[1])[None, :]
    prio = jax.random.uniform(key, rows.shape)
    prio = jnp.where(slot < eff[:, None], prio, 2.0)
    k = min(int(fanout), int(rows.shape[1]))
    neg, idx = jax.lax.top_k(-prio, k)
    src = jnp.take_along_axis(rows, idx, axis=1)  # [dcap, k]
    valid = -neg < 1.5
    valid = valid & (jnp.arange(rows.shape[0])[:, None] < n_dst)
    if k < fanout:  # table narrower than the fanout: pad draw columns
        pad = int(fanout) - k
        src = jnp.pad(src, ((0, 0), (0, pad)))
        valid = jnp.pad(valid, ((0, 0), (0, pad)))
    return src, valid


def fused_sample_subgraph(
    nbr, eff_deg, out_deg, in_deg, seeds_pad, n_real, key,
    node_caps: Tuple[int, ...], fanouts: Tuple[int, ...],
):
    """The whole padded multi-hop subgraph of one seed batch, on-device.

    The traced twin of ``Sampler._make_batch`` (sample/sampler.py): walks
    hops outermost-in at the sampler's static capacities and returns
    ``(nodes, hops)`` in exactly the batch-array structure the trainers'
    ``batch_forward`` consumes — ``nodes[l] [node_caps[l]]`` padded
    global ids, ``hops[h] = (src_local, dst_local, weight)`` each at
    ``ecap_h = node_caps[h+1] * fanouts[h]``. ``n_real`` is the traced
    live-seed count (padded seed rows never draw); weights are the
    GCN-norm ``1/sqrt(out_deg * in_deg)``, 0 on padding, like the host.
    """
    n_hops = len(fanouts)
    nodes = [None] * (n_hops + 1)
    hops = [None] * n_hops
    nodes[-1] = seeds_pad
    cur, cur_n = seeds_pad, n_real
    for h in range(n_hops - 1, -1, -1):
        fanout = int(fanouts[h])
        dcap = int(node_caps[h + 1])
        ncap = int(node_caps[h])
        hkey = jax.random.fold_in(key, h)
        src2d, valid2d = _draw_hop(nbr, eff_deg, hkey, cur, cur_n, fanout)
        src = src2d.reshape(-1)  # [ecap], row-major: slot r*fanout+j
        valid = valid2d.reshape(-1)
        dst_idx = jnp.repeat(
            jnp.arange(dcap, dtype=jnp.int32), fanout
        )
        uniq, src_local, n_uniq = device_dedup_remap(src, valid, ncap)
        d_out = jnp.maximum(out_deg[src], 1).astype(jnp.float32)
        d_in = jnp.maximum(in_deg[cur[dst_idx]], 1).astype(jnp.float32)
        w = jnp.where(valid, 1.0 / jnp.sqrt(d_out * d_in), 0.0)
        hops[h] = (
            src_local,
            jnp.where(valid, dst_idx, 0),
            w.astype(jnp.float32),
        )
        nodes[h] = uniq
        cur, cur_n = uniq, n_uniq
    return nodes, hops


def degree_tables(graph):
    """Device-resident int32 degree vectors for the fused weight math —
    uploaded once next to the neighbor table, read by every fused batch."""
    out_deg = jax.device_put(jnp.asarray(graph.out_degree, jnp.int32))
    in_deg = jax.device_put(jnp.asarray(graph.in_degree, jnp.int32))
    return out_deg, in_deg


class FusedEpochRunner:
    """One AOT-compiled ``lax.scan`` program per batch-count bucket.

    ``step_fn(params, opt_state, feature, label, nodes, hops, seed_mask,
    seeds, key)`` is the trainer's UNJITTED per-batch update (loss +
    grad + optimizer; with ``has_stats`` it returns a 4th numerics-stats
    pytree). The runner wraps seed shuffle + per-batch fused sampling +
    ``step_fn`` in one scanned program, compiles it AHEAD OF TIME via
    ``jax.jit(...).lower(...).compile()`` (the serve ladder's explicit
    compile-count discipline — ``compile_counts`` proves one compile per
    bucket, ever) and replays it once per epoch: one dispatch, zero
    per-batch H2D.

    Epoch boundaries are the scan boundaries: checkpoint hooks, numerics
    emission and loss-history/guard reads all happen between dispatches
    on materialized host values — a mid-epoch rollback lands on the
    previous scan's output exactly like the sync path's epoch end.
    """

    def __init__(
        self,
        step_fn,
        node_caps: Sequence[int],
        fanouts: Sequence[int],
        batch_size: int,
        tables,
        train_nids,
        metrics: Any = None,
        has_stats: bool = False,
    ):
        self.step_fn = step_fn
        self.node_caps = tuple(int(c) for c in node_caps)
        self.fanouts = tuple(int(f) for f in fanouts)
        self.batch_size = int(batch_size)
        self.nbr, self.eff_deg, self.out_deg, self.in_deg = tables
        nids = np.asarray(train_nids, dtype=np.int32)
        self.n_seeds = int(len(nids))
        if self.n_seeds == 0:
            raise ValueError("fused sampling needs at least one seed")
        self.train_nids = jax.device_put(jnp.asarray(nids))
        self.n_batches = -(-self.n_seeds // self.batch_size)  # ceil
        self.metrics = metrics
        self.has_stats = bool(has_stats)
        self._fns: Dict[int, Any] = {}
        self._compiled: Dict[int, Any] = {}
        self.compile_counts: Dict[int, int] = {}
        self._lock = threading.Lock()

    # ---- program construction -------------------------------------------
    def build_epoch_fn(self, n_batches: int):
        """The pure epoch function for a batch-count bucket (cached; also
        the structural-pin surface — tests ``jax.make_jaxpr`` this and
        assert one ``scan`` and no host callbacks in the epoch body)."""
        fn = self._fns.get(n_batches)
        if fn is not None:
            return fn
        B = self.batch_size
        caps, fanouts = self.node_caps, self.fanouts
        n_seeds, step, has_stats = self.n_seeds, self.step_fn, self.has_stats

        def epoch_fn(params, opt_state, feature, label, nbr, eff_deg,
                     out_deg, in_deg, train_nids, epoch, key):
            ekey = jax.random.fold_in(key, epoch)
            # on-device epoch shuffle: the host sampler's per-epoch
            # reshuffle, from the scan program's own fold-in stream
            perm = jax.random.permutation(
                jax.random.fold_in(ekey, _SHUFFLE_TAG), n_seeds
            )
            shuffled = train_nids[perm]
            total = n_batches * B
            seeds_flat = jnp.zeros(
                (total,), dtype=shuffled.dtype
            ).at[:n_seeds].set(shuffled)
            mask_flat = (jnp.arange(total) < n_seeds).astype(jnp.float32)
            seeds_mat = seeds_flat.reshape(n_batches, B)
            mask_mat = mask_flat.reshape(n_batches, B)
            counts = mask_mat.sum(axis=1).astype(jnp.int32)

            def body(carry, xs):
                params, opt_state = carry
                seeds, smask, n_live, bi = xs
                # the sync loop's per-batch key schedule, so dropout
                # streams line up with the host path's epoch*100003+bi
                bkey = jax.random.fold_in(key, epoch * 100003 + bi)
                skey = jax.random.fold_in(bkey, _DRAW_TAG)
                nodes, hops = fused_sample_subgraph(
                    nbr, eff_deg, out_deg, in_deg, seeds, n_live, skey,
                    caps, fanouts,
                )
                out = step(params, opt_state, feature, label, nodes,
                           hops, smask, seeds, bkey)
                if has_stats:
                    params, opt_state, loss, stats = out
                    return (params, opt_state), (loss, stats)
                params, opt_state, loss = out
                return (params, opt_state), loss

            xs = (seeds_mat, mask_mat, counts,
                  jnp.arange(n_batches, dtype=jnp.int32))
            (params, opt_state), ys = jax.lax.scan(
                body, (params, opt_state), xs
            )
            if has_stats:
                losses, stats = ys
                # the sync loop keeps the LAST batch's stats per epoch
                stats_last = jax.tree_util.tree_map(lambda a: a[-1], stats)
                return params, opt_state, losses, stats_last
            return params, opt_state, ys

        self._fns[n_batches] = epoch_fn
        return epoch_fn

    def _epoch_args(self, params, opt_state, feature, label, epoch, key):
        return (params, opt_state, feature, label, self.nbr, self.eff_deg,
                self.out_deg, self.in_deg, self.train_nids,
                np.int32(epoch), key)

    def _ensure_compiled(self, n_batches: int, args):
        compiled = self._compiled.get(n_batches)
        if compiled is not None:
            return compiled
        with self._lock:
            compiled = self._compiled.get(n_batches)
            if compiled is not None:
                return compiled
            fn = self.build_epoch_fn(n_batches)
            t0 = time.perf_counter()
            compiled = jax.jit(fn).lower(*args).compile()
            dt = time.perf_counter() - t0
            self._compiled[n_batches] = compiled
            self.compile_counts[n_batches] = (
                self.compile_counts.get(n_batches, 0) + 1
            )
            if self.metrics is not None:
                self.metrics.counter_add(
                    f"sample.epoch_compiles.b{n_batches}"
                )
                from neutronstarlite_tpu.obs.cost import (
                    capture_program_cost,
                )

                capture_program_cost(
                    self.metrics, f"sample.epoch_scan_b{n_batches}",
                    compiled=compiled, bucket=n_batches,
                    compile_s=round(dt, 4),
                )
            log.info(
                "AOT-compiled fused epoch scan (%d batches x %d seeds, "
                "caps %s) in %.3fs",
                n_batches, self.batch_size, list(self.node_caps), dt,
            )
            return compiled

    # ---- the one dispatch ------------------------------------------------
    def run_epoch(self, params, opt_state, feature, label, epoch: int, key):
        """One epoch, one dispatch. Returns ``(params, opt_state,
        losses[n_batches], stats_or_None)`` — all device values; the
        caller's ``block_until_ready`` is the epoch sync point."""
        args = self._epoch_args(params, opt_state, feature, label, epoch,
                                key)
        compiled = self._ensure_compiled(self.n_batches, args)
        out = compiled(*args)
        if self.metrics is not None:
            self.metrics.counter_add("sample.dispatches")
        if self.has_stats:
            params, opt_state, losses, stats = out
            return params, opt_state, losses, stats
        params, opt_state, losses = out
        return params, opt_state, losses, None
