"""Multi-worker sampling pipeline for the mini-batch trainers.

The reference overlaps ONE host sampler thread with device compute
(toolkits/GCN_CPU_SAMPLE.hpp + core/ntsSampler.hpp:113-172 work queue);
after round 2's native sampler work our epoch went host-bound at ~24
ms/batch on a single core (docs/PERF.md §3b) — the chip idles behind the
sampler. This module shards the epoch's BATCHES over worker processes
(seed-sharding; VERDICT round-2 item 9):

- determinism by construction: batch i of epoch e is sampled with an RNG
  seeded by SeedSequence((base_seed, e, i)) regardless of which worker
  (or the main process) produces it — worker count is a pure throughput
  knob, never a semantics knob, and the inline workers=0 path yields
  bit-identical batches;
- a PERSISTENT pool of ``fork``ed workers shares the replicated host CSC
  (the FullyRepGraph analog) copy-on-write — no graph pickling, no extra
  RSS, no per-epoch spawn cost. The pool forks at CONSTRUCTION time, and
  trainers construct their sampler before the first JAX backend touch:
  forking after PJRT's runtime threads exist risks a child deadlocked on
  a lock the forked thread held;
- results stream back through a queue with a bounded reorder buffer
  (batches must arrive to the trainer in epoch order for checkpoint /
  logging reproducibility); the buffer bound also acts as the prefetch
  depth, so even one worker overlaps sampling with device compute across
  the epoch boundary the async-dispatch trick cannot cover.

Worker count: NTS_SAMPLE_WORKERS env wins; default min(4, cpu_count - 1)
(0 on a single-core host = the inline path).
"""

from __future__ import annotations

import os
from typing import List, Sequence

import numpy as np

from neutronstarlite_tpu.graph.storage import CSCGraph
from neutronstarlite_tpu.sample.sampler import SampledBatch, Sampler
from neutronstarlite_tpu.utils.logging import get_logger

log = get_logger("sample_parallel")


class _WorkerError:
    """Pickled across the result queue when a worker's sampling raises."""

    def __init__(self, msg: str):
        self.msg = msg


def _worker_main(state, in_q, out_q):
    """Spawn-context worker entry (must be module-level picklable): the
    child reconstructs an inline sampler from the pickled state and
    serves the queue protocol. Fork-context workers use the in-process
    closure instead (graph shared copy-on-write, nothing pickled)."""
    graph, batch_size, fanouts, base_seed = state
    s = ParallelEpochSampler(
        graph, np.zeros(0, np.int64), batch_size, fanouts,
        seed=base_seed, workers=0,
    )
    _serve(s._make_one, in_q, out_q)


def _serve(make_one, in_q, out_q):
    while True:
        item = in_q.get()
        if item is None:
            return
        epoch, i, seeds = item
        try:
            out_q.put((epoch, i, make_one(seeds, epoch, i)))
        except Exception as e:  # surface instead of silent death
            import traceback

            out_q.put((epoch, i, _WorkerError(
                f"{e}\n{traceback.format_exc(limit=5)}"
            )))


def _jax_backend_live() -> bool:
    """True when a JAX backend has already been initialized in this
    process (fork-safety gate; checked WITHOUT triggering an init)."""
    try:
        import sys

        xb = sys.modules.get("jax._src.xla_bridge")
        return bool(xb is not None and getattr(xb, "_backends", None))
    except Exception:  # pragma: no cover - conservative default
        return True


def default_workers() -> int:
    env = os.environ.get("NTS_SAMPLE_WORKERS")
    if env is not None:
        return max(int(env), 0)
    return max(min(4, (os.cpu_count() or 1) - 1), 0)


def _batch_seed(
    base_seed: int, epoch: int, idx: int, kind: int = 0
) -> np.random.SeedSequence:
    # kind 0 = batch sampling, 1 = the epoch shuffle (SeedSequence entries
    # must be non-negative, so the stream split is its own field)
    return np.random.SeedSequence(
        [int(base_seed), int(epoch), int(kind), int(idx)]
    )


class ParallelEpochSampler:
    """Epoch-order batch stream with optional multiprocess seed-sharding.

    Construction mirrors sample.Sampler (the reference builds one per
    mask split, GCN_CPU_SAMPLE.hpp:251-265); ``sample_epoch(epoch)``
    yields SampledBatch in deterministic order.
    """

    def __init__(
        self,
        graph: CSCGraph,
        seed_nids: np.ndarray,
        batch_size: int,
        fanouts: Sequence[int],
        seed: int = 0,
        workers: int | None = None,
        ctx_method: str | None = None,
        hop_sampler=None,
    ):
        self.graph = graph
        self.seed_nids = np.asarray(seed_nids, dtype=np.int64)
        self.batch_size = int(batch_size)
        self.fanouts = list(fanouts)
        self.base_seed = int(seed)
        self.workers = default_workers() if workers is None else max(workers, 0)
        # on-device hop sampler (SAMPLE_PIPELINE:device): its tables are
        # device buffers — unpicklable for spawn, and a forked child must
        # not touch the live JAX runtime — so sampling goes inline (the
        # draw itself is the part the device accelerates)
        self.hop_sampler = hop_sampler
        if hop_sampler is not None and self.workers > 0:
            log.info(
                "device hop sampler active: sampling runs inline "
                "(%d workers disabled — device buffers cannot cross the "
                "worker-process boundary)", self.workers,
            )
            self.workers = 0
        # fork (default): workers share the CSC copy-on-write — zero pickling,
        # but only safe BEFORE the first JAX backend touch. spawn: workers
        # pickle the graph once at pool start — costs RSS + startup at Reddit
        # scale, but is safe with a live multithreaded JAX runtime (the
        # fork-after-threads hazard, and CPython's os.fork RuntimeWarning,
        # don't apply). NTS_SAMPLE_CTX env overrides.
        self.ctx_method = (
            ctx_method or os.environ.get("NTS_SAMPLE_CTX") or "fork"
        )
        self._procs: list = []
        self._in_q = self._out_q = None
        # the invariant is simple: fork pools only BEFORE backend init,
        # spawn pools otherwise (NTS_SAMPLE_CTX=spawn is the safe opt-in)
        if (
            self.workers > 1
            and self.ctx_method == "fork"
            and _jax_backend_live()
        ):
            # the invariant "fork before the first JAX backend touch" only
            # holds for the first trainer in a pristine process; forking
            # with live PJRT runtime threads risks a child deadlocked on a
            # lock a forked-away thread held. Degrade to inline sampling
            # loudly rather than gamble (NTS_SAMPLE_CTX=spawn opts into the
            # pickling pool instead, which tolerates a live backend).
            log.warning(
                "JAX backend already initialized in this process; "
                "disabling %d sampling workers (fork-after-threads is "
                "deadlock-prone) — sampling runs inline "
                "(NTS_SAMPLE_CTX=spawn keeps workers at a pickling cost)",
                self.workers,
            )
            self.workers = 0
        if self.workers > 1:
            # start the persistent pool NOW — for fork, before any JAX
            # backend touch (trainers construct their sampler first)
            self._start_pool()

    def _start_pool(self):
        import multiprocessing as mp

        ctx = mp.get_context(self.ctx_method)
        self._in_q = ctx.Queue()
        self._out_q = ctx.Queue(maxsize=2 * self.workers)
        in_q, out_q = self._in_q, self._out_q
        if self.ctx_method == "fork":
            make_one = self._make_one  # graph shared copy-on-write

            def worker():
                _serve(make_one, in_q, out_q)

            targets = [dict(target=worker) for _ in range(self.workers)]
        else:  # spawn: module-level entry, graph pickled once per worker
            state = (self.graph, self.batch_size, self.fanouts, self.base_seed)
            targets = [
                dict(target=_worker_main, args=(state, in_q, out_q))
                for _ in range(self.workers)
            ]
        self._procs = [ctx.Process(daemon=True, **t) for t in targets]
        for p in self._procs:
            p.start()

    def close(self):
        """Stop the persistent pool (daemon workers also die with the
        parent; this is the orderly path)."""
        if self._in_q is not None:
            for _ in self._procs:
                self._in_q.put(None)
            for p in self._procs:
                p.join(timeout=5)
                if p.is_alive():  # pragma: no cover - cleanup path
                    p.terminate()
            self._procs = []
            self._in_q = self._out_q = None
            self.workers = 0

    # -- deterministic per-batch sampling ---------------------------------
    def _epoch_batches(self, epoch: int, shuffle: bool) -> List[np.ndarray]:
        nids = self.seed_nids.copy()
        if shuffle:
            np.random.default_rng(
                _batch_seed(self.base_seed, epoch, 0, kind=1)
            ).shuffle(nids)
        return [
            nids[lo: lo + self.batch_size]
            for lo in range(0, len(nids), self.batch_size)
        ]

    def _make_one(self, seeds: np.ndarray, epoch: int, idx: int) -> SampledBatch:
        ss = _batch_seed(self.base_seed, epoch, idx)
        s = Sampler(
            self.graph, seeds, self.batch_size, self.fanouts,
            seed=int(ss.generate_state(1)[0]),
            hop_sampler=self.hop_sampler,
        )
        return s._make_batch(seeds)

    # -- epoch streams ----------------------------------------------------
    def sample_epoch(self, epoch: int = 0, shuffle: bool = True):
        batches = self._epoch_batches(epoch, shuffle)
        if self._in_q is None or len(batches) <= 1:
            for i, seeds in enumerate(batches):
                yield self._make_one(seeds, epoch, i)
            return
        yield from self._sample_epoch_mp(batches, epoch)

    def _sample_epoch_mp(self, batches: List[np.ndarray], epoch: int):
        import queue as queue_mod

        n = len(batches)
        for i, seeds in enumerate(batches):
            self._in_q.put((epoch, i, seeds))
        buf = {}
        nxt = 0
        while nxt < n:
            while nxt not in buf:
                try:
                    e, i, b = self._out_q.get(timeout=30.0)
                except queue_mod.Empty:
                    # a batch takes ~ms; 30 s of silence means dead workers
                    # (e.g. OOM-killed) — fail loudly, never hang the epoch
                    dead = [p.pid for p in self._procs if not p.is_alive()]
                    raise RuntimeError(
                        f"sampling workers stalled (dead pids: {dead}); "
                        f"epoch {epoch} batch {nxt} never arrived"
                    )
                if isinstance(b, _WorkerError):
                    raise RuntimeError(f"sampling worker failed: {b.msg}")
                if e != epoch:
                    # stale result from an abandoned earlier epoch
                    # (consumer dropped the generator mid-stream): discard
                    continue
                buf[i] = b
            yield buf.pop(nxt)
            nxt += 1
