"""Fan-out neighbor sampler producing padded, static-shape batch subgraphs.

Reference: ``Sampler::reservoir_sample`` (core/ntsSampler.hpp:113-172) walks a
work queue of seed vertices in batches; per layer it reservoir-samples up to
``fanout[l]`` in-neighbors per destination over the replicated whole-graph CSC
(``FullyRepGraph``), then ``sampCSC::postprocessing`` dedups and remaps source
ids to batch-local indices via std::map (core/coocsc.hpp:62-89).

TPU re-design: sampling is host-side vectorized NumPy (per-dst top-fanout by
random priority == uniform without replacement, the reservoir's distribution),
and every batch is padded to fixed capacities derived from batch_size x
fanout products, so the device step compiles ONCE and replays for every batch
(XLA static shapes; SURVEY.md "hard parts": "pad to fanout capacity ... to
avoid recompilation"). Padding edges carry weight 0; padding vertices index
row 0 and are masked out of the loss.

Layer ordering: ``hops[0]`` is the innermost (input) hop; seeds are the
destinations of the last hop. nodes[0] are the input vertices whose features
feed the network (``get_feature``'s gather, ntsMiniBatchGraphOp.hpp:36-60).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np

from neutronstarlite_tpu.graph.storage import CSCGraph


@dataclasses.dataclass
class SampledHop:
    """One hop's batch-local CSC (the sampCSC analog, coocsc.hpp:26)."""

    src_local: np.ndarray  # [Ecap] index into previous layer's node list
    dst_local: np.ndarray  # [Ecap] index into this layer's node list
    weight: np.ndarray  # [Ecap] float32, 0 on padding
    n_dst: int  # real destination count (<= dst capacity)


@dataclasses.dataclass
class SampledBatch:
    """Padded multi-hop subgraph for one seed batch."""

    nodes: List[np.ndarray]  # per layer: padded global vertex ids
    hops: List[SampledHop]  # len == n_layers; hops[l]: nodes[l] -> nodes[l+1]
    seed_mask: np.ndarray  # [B] 1.0 on real seeds, 0.0 on padding
    seeds: np.ndarray  # [B] padded global seed ids


class Sampler:
    """Per-epoch batch sampler over a set of seed vertices.

    The reference builds three of these (train/val/test from mask nids,
    GCN_CPU_SAMPLE.hpp:251-265); do the same here.
    """

    def __init__(
        self,
        graph: CSCGraph,
        seed_nids: np.ndarray,
        batch_size: int,
        fanouts: Sequence[int],
        seed: int = 0,
        use_native: Optional[bool] = None,
        rng: Optional[np.random.Generator] = None,
        hop_sampler=None,
    ):
        self.graph = graph
        # optional on-device uniform hop sampler (sample/device_sampler.py,
        # SAMPLE_PIPELINE:device): replaces the per-hop draw only; dedup/
        # remap/weights stay host-side. It draws via jax.random seeded from
        # this sampler's Generator, so it excludes the native path (which
        # seeds its own PRNG) the same way an injected rng does.
        self.hop_sampler = hop_sampler
        if hop_sampler is not None:
            if use_native:
                raise ValueError(
                    "use_native=True cannot combine with a device "
                    "hop_sampler; pass one or the other"
                )
            use_native = False
        self.seed_nids = np.asarray(seed_nids, dtype=np.int64)
        self.batch_size = batch_size
        if use_native and rng is not None:
            # the native sampler seeds its own PRNG from ``seed`` and would
            # silently ignore the injected Generator — contradictory args
            raise ValueError(
                "use_native=True cannot honor an injected rng; pass one or "
                "the other"
            )
        if use_native is None:
            # an injected Generator must actually drive the draws (see
            # above), so default to the NumPy path when one is supplied
            from neutronstarlite_tpu import native

            use_native = native.available() if rng is None else False
        self.use_native = bool(use_native)
        self._native_seed = seed
        # fanouts listed outermost-first in the cfg (FANOUT:5-10-10); hop h
        # (input -> output) uses fanouts[h] reversed so the seed-adjacent hop
        # gets the last entry, matching init_gnnctx_fanout's layer indexing.
        self.fanouts = list(fanouts)
        self.rng = np.random.default_rng(seed) if rng is None else rng
        # per-layer node capacities, seeds outward
        n_hops = len(self.fanouts)
        caps = [batch_size]
        for f in reversed(self.fanouts):
            caps.append(caps[-1] * f)
        self.node_caps = list(reversed(caps))  # node_caps[-1] == batch_size

    # -- vectorized per-dst uniform sampling without replacement ----------
    def _sample_neighbors(self, dsts: np.ndarray, fanout: int, cap=None):
        """Return (src, dst_idx) pairs: for each dst, up to ``fanout``
        distinct in-neighbors chosen uniformly (reservoir distribution).
        ``cap`` is the hop's static dst capacity — only the device hop
        sampler needs it (fixed shapes compile once per hop level)."""
        g = self.graph
        if self.hop_sampler is not None:
            return self.hop_sampler.sample_neighbors(
                np.asarray(dsts, np.int64), fanout, self.rng, cap=cap
            )
        if self.use_native:
            from neutronstarlite_tpu import native

            self._native_seed += 1
            return native.sample_hop(
                g.column_offset, g.row_indices, np.asarray(dsts, np.int64),
                fanout, self._native_seed,
            )
        deg = g.in_degree[dsts].astype(np.int64)
        starts = g.column_offset[dsts]
        total = int(deg.sum())
        if total == 0:
            return np.empty(0, np.int64), np.empty(0, np.int64)
        # candidate edge list: all in-edges of all dsts
        dst_idx = np.repeat(np.arange(len(dsts)), deg)
        within = np.arange(total) - np.repeat(np.cumsum(deg) - deg, deg)
        cand_src = g.row_indices[(np.repeat(starts, deg) + within).astype(np.int64)]
        # random priority per candidate; take top-fanout within each segment
        prio = self.rng.random(total)
        order = np.lexsort((prio, dst_idx))
        seg_start = np.repeat(np.cumsum(deg) - deg, deg)
        rank = np.arange(total) - seg_start  # position within segment, post-sort
        keep = order[rank < fanout]
        return cand_src[keep].astype(np.int64), dst_idx[keep]

    def _make_batch(self, seeds: np.ndarray) -> SampledBatch:
        B = self.batch_size
        n_real = len(seeds)
        seeds_pad = np.zeros(B, dtype=np.int64)
        seeds_pad[:n_real] = seeds
        seed_mask = np.zeros(B, dtype=np.float32)
        seed_mask[:n_real] = 1.0

        g = self.graph
        nodes: List[np.ndarray] = [None] * (len(self.fanouts) + 1)
        hops: List[Optional[SampledHop]] = [None] * len(self.fanouts)
        nodes[-1] = seeds_pad
        cur_nodes = seeds  # real (unpadded) dst set, outermost layer
        cur_count = n_real
        for h in range(len(self.fanouts) - 1, -1, -1):
            fanout = self.fanouts[h]
            src, dst_idx = self._sample_neighbors(
                cur_nodes, fanout, cap=self.node_caps[h + 1]
            )
            # dedup + batch-local remap (sampCSC::postprocessing's role;
            # native hash passes, or np.unique + searchsorted fallback —
            # identical sorted-unique semantics either way)
            if self.use_native:
                from neutronstarlite_tpu import native

                uniq, src_local = native.dedup_remap(src)
            else:
                uniq = np.unique(src)
                src_local = np.searchsorted(uniq, src)
            # per-edge weight: full-graph GCN norm (nts_norm_degree over the
            # original degrees, ntsBaseOp.hpp:194)
            d_out = np.maximum(g.out_degree[src], 1).astype(np.float64)
            d_in = np.maximum(g.in_degree[cur_nodes[dst_idx]], 1).astype(np.float64)
            w = (1.0 / np.sqrt(d_out * d_in)).astype(np.float32)

            ecap = self.node_caps[h + 1] * fanout
            hop = SampledHop(
                src_local=_pad(src_local, ecap),
                dst_local=_pad(dst_idx, ecap),
                weight=_pad(w, ecap),
                n_dst=cur_count,
            )
            hops[h] = hop
            ncap = self.node_caps[h]
            if len(uniq) > ncap:
                raise AssertionError(
                    f"hop {h}: {len(uniq)} unique sources exceed capacity {ncap}"
                )
            nodes[h] = _pad(uniq, ncap)
            cur_nodes = uniq
            cur_count = len(uniq)
        return SampledBatch(
            nodes=list(nodes), hops=list(hops), seed_mask=seed_mask, seeds=seeds_pad
        )

    def sample_batch(self, seeds) -> SampledBatch:
        """One padded batch for an arbitrary seed set (<= batch_size) —
        the online-serving entry point (serve/sampling.py): a request's
        fresh-node fan-out, same capacities and distribution as the
        training epoch walk."""
        seeds = np.asarray(seeds, dtype=np.int64)
        if seeds.ndim != 1 or len(seeds) == 0:
            raise ValueError("sample_batch needs a non-empty 1-D seed array")
        if len(seeds) > self.batch_size:
            raise ValueError(
                f"{len(seeds)} seeds exceed this sampler's batch capacity "
                f"{self.batch_size}"
            )
        return self._make_batch(seeds)

    def sample_epoch(self, shuffle: bool = True):
        """Yield SampledBatch for every seed batch (the work-queue walk,
        ntsSampler.hpp:125-137)."""
        nids = self.seed_nids.copy()
        if shuffle:
            self.rng.shuffle(nids)
        for lo in range(0, len(nids), self.batch_size):
            yield self._make_batch(nids[lo : lo + self.batch_size])


def dirty_biased_seeds(
    seed_nids: np.ndarray,
    dirty: np.ndarray,
    n: int,
    dirty_frac: float,
    rng: np.random.Generator,
) -> np.ndarray:
    """Draw ``n`` training seeds biased toward the dirty region.

    The continuous fine-tune worker's seed policy (stream/finetune.py):
    roughly ``dirty_frac`` of the draw comes from ``seed_nids ∩ dirty``
    (the vertices whose aggregation inputs a delta changed — where the
    model is most stale), the rest uniformly from the remaining seeds so
    the update never forgets the clean region. Without replacement
    within each pool; short pools spill into the other so the draw
    always returns ``min(n, len(seed_nids))`` distinct seeds.
    """
    seed_nids = np.asarray(seed_nids, dtype=np.int64)
    n = int(min(n, len(seed_nids)))
    if n <= 0:
        return np.empty(0, np.int64)
    dirty = np.asarray(dirty, dtype=np.int64)
    is_dirty = np.isin(seed_nids, dirty)
    pool_d = seed_nids[is_dirty]
    pool_c = seed_nids[~is_dirty]
    want_d = int(min(round(n * float(dirty_frac)), len(pool_d)))
    want_c = min(n - want_d, len(pool_c))
    # spill: a short clean pool refills from dirty (and vice versa above)
    want_d = min(n - want_c, len(pool_d))
    take_d = rng.choice(pool_d, size=want_d, replace=False) \
        if want_d else np.empty(0, np.int64)
    take_c = rng.choice(pool_c, size=want_c, replace=False) \
        if want_c else np.empty(0, np.int64)
    out = np.concatenate([take_d, take_c]).astype(np.int64)
    rng.shuffle(out)
    return out


def _pad(arr: np.ndarray, n: int, fill=0) -> np.ndarray:
    out = np.full((n,) + arr.shape[1:], fill, dtype=arr.dtype)
    out[: min(len(arr), n)] = arr[:n] if len(arr) > n else arr
    return out
