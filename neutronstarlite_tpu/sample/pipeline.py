"""Async device-resident sampling pipeline: K-deep prefetch + H2D overlap.

The sampled path (training via models/gcn_sample.py, serving via
serve/sampling.py) draws neighbors in host numpy *synchronously inside the
step loop* — the classic sample-and-aggregate bottleneck the hardware-
sampling paper targets (PAPERS.md: "Hardware Acceleration of Sampling
Algorithms in Sample and Aggregate GNNs", arXiv:2209.02916). JAX's async
dispatch hides some of it by accident (the step returns before the device
finishes), but nothing overlaps the host->device transfer of the padded
CSR batch, and one slow sample stalls the whole chain.

This module makes sampling a real pipeline stage:

- ONE persistent producer thread walks the scheduled epochs through the
  deterministic batch source (sample/parallel.ParallelEpochSampler — its
  per-(epoch, index) SeedSequence seeding means the pipeline changes
  *when* a batch is produced, never *what* is produced, so pipelined and
  synchronous execution are bitwise-identical);
- every produced SampledBatch is pushed through ``jax.device_put`` ON THE
  PRODUCER THREAD, so the H2D copy of batch i+1 is in flight while the
  device computes batch i (double buffering falls out of the queue depth);
- the queue is BOUNDED (``NTS_SAMPLE_PREFETCH``, default 3): a stalled
  consumer backpressures the producer instead of ballooning host memory
  with padded batches;
- the producer runs ahead ACROSS epoch boundaries (the whole epoch range
  is scheduled up front), covering the epoch-edge bubble async dispatch
  cannot;
- worker failure propagates: an exception in the producer surfaces at the
  consumer as :class:`SampleWorkerError` (a resilience HealthError, so a
  supervised run rolls back and retries through the normal
  rollback/restart machinery) — never a silent hang;
- ``close()`` drains and joins — breaking out of an epoch mid-stream (an
  early stop, a guard trip) leaves no running thread behind;
- the producer plants a ``sample_produce`` fault point per batch
  (resilience/faults: ``exc@point=sample_produce`` / ``stall@point=...``)
  so chaos tests can kill the worker mid-epoch.

Telemetry (obs/): per-batch ``sample_produce`` / ``h2d_copy`` spans on the
producer and ``sample_wait`` spans on the consumer (all cat="sample"),
plus ``sample.stall_ms`` (counter: consumer time blocked on the queue),
``sample.produced`` / ``sample.h2d_ms`` counters and the
``sample.queue_depth`` gauge (high-water mark). tools/trace_timeline
derives the overlap verdict from exactly these spans.

Selection: the ``SAMPLE_PIPELINE:`` cfg key / ``NTS_SAMPLE_PIPELINE`` env
(resolved by :func:`resolve_sample_pipeline`): ``sync`` (default — the
parity oracle), ``pipelined`` (this module over the host sampler), or
``device`` (pipelined + the jitted on-device uniform hop sampler,
sample/device_sampler.py). docs/SAMPLING.md has the full contract.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Any, Iterable, Optional

import numpy as np

from neutronstarlite_tpu.resilience.faults import fault_point
from neutronstarlite_tpu.resilience.guards import HealthError
from neutronstarlite_tpu.sample.sampler import SampledBatch
from neutronstarlite_tpu.utils.logging import get_logger
from neutronstarlite_tpu.utils.timing import get_time

log = get_logger("sample_pipeline")

SAMPLE_PIPELINE_MODES = ("sync", "pipelined", "device", "fused")


class SampleWorkerError(HealthError):
    """The pipeline's producer died; a supervised run treats it like any
    other health fault (rollback to the last good checkpoint + retry)."""

    code = "sample_worker"


def resolve_sample_pipeline(cfg: Any = None) -> str:
    """The active sampling mode: ``NTS_SAMPLE_PIPELINE`` env wins (launcher
    parity with NTS_KERNEL_OVERRIDE — set-but-empty is NOT an override),
    then the cfg's ``SAMPLE_PIPELINE:`` key, then ``sync``."""
    raw = os.environ.get("NTS_SAMPLE_PIPELINE", "")
    if not raw.strip():
        raw = getattr(cfg, "sample_pipeline", "") if cfg is not None else ""
    v = (raw or "").strip().lower()
    if v in ("", "sync", "off", "0"):
        return "sync"
    if v in ("pipelined", "on", "1"):
        return "pipelined"
    if v == "device":
        return "device"
    if v == "fused":
        return "fused"
    if v == "auto":
        # the tuner (tune/select.py) resolves SAMPLE_PIPELINE:auto into a
        # concrete mode BEFORE the trainer funnel reads it — reaching here
        # means a non-tuned entry point got a raw auto
        raise ValueError(
            "SAMPLE_PIPELINE:auto is resolved by the tuner "
            "(models/base._resolve_tune_autos); this entry point received "
            "it unresolved — set an explicit mode (sync, pipelined, "
            "device or fused)"
        )
    raise ValueError(
        f"SAMPLE_PIPELINE/NTS_SAMPLE_PIPELINE must be sync, pipelined, "
        f"device or fused, got {raw!r}"
    )


def default_depth() -> int:
    """Prefetch depth (``NTS_SAMPLE_PREFETCH``, >= 1). 3 gives double
    buffering plus one slot of slack for sampling-time jitter."""
    raw = os.environ.get("NTS_SAMPLE_PREFETCH", "")
    if raw:
        try:
            return max(int(raw), 1)
        except ValueError:
            log.warning("NTS_SAMPLE_PREFETCH=%r is not an int; using 3", raw)
    return 3


def batch_to_device(b: SampledBatch):
    """SampledBatch -> the (nodes, hops, seed_mask, seeds) device pytree —
    the exact structure models/gcn_sample._batch_arrays builds, but issued
    through ONE ``jax.device_put`` so the transfer is dispatched (and on
    accelerators, in flight) before the consumer ever touches the batch.
    device_put canonicalizes dtypes identically to jnp.asarray, so the
    compiled train step sees the same avals either way."""
    import jax

    return jax.device_put((
        [np.asarray(n) for n in b.nodes],
        [(h.src_local, h.dst_local, h.weight) for h in b.hops],
        b.seed_mask,
        b.seeds,
    ))


def payload_nbytes(b) -> int:
    """Host bytes of one padded SampledBatch's device payload — the
    measured twin of ``wire_accounting.sample_batch_payload_bytes`` (the
    two must agree: padded capacities are static, so measured == priced).
    Non-batch payloads (tests inject arbitrary objects) count 0."""
    try:
        arrs = list(b.nodes) + [b.seed_mask, b.seeds]
        for h in b.hops:
            arrs += [h.src_local, h.dst_local, h.weight]
        return int(sum(np.asarray(a).nbytes for a in arrs))
    except (AttributeError, TypeError):
        return 0


class _EpochDone:
    __slots__ = ("epoch",)

    def __init__(self, epoch: int):
        self.epoch = epoch


class _WorkerFailed:
    __slots__ = ("msg",)

    def __init__(self, msg: str):
        self.msg = msg


class SamplePipeline:
    """Bounded prefetch queue between a deterministic batch source and the
    training step loop.

    ``source`` must expose ``sample_epoch(epoch)`` yielding SampledBatch
    in deterministic order (sample/parallel.ParallelEpochSampler);
    ``epochs`` is the ordered schedule the producer walks — the consumer
    MUST call :meth:`epoch_stream` for exactly those epochs in that order.
    ``transfer`` maps a SampledBatch to the payload the consumer receives
    (default: :func:`batch_to_device`; tests inject identity).
    """

    def __init__(
        self,
        source: Any,
        epochs: Iterable[int],
        depth: Optional[int] = None,
        metrics: Any = None,
        tracer: Any = None,
        transfer=batch_to_device,
        stall_timeout_s: float = 120.0,
    ):
        self.source = source
        self.epochs = list(epochs)
        self.depth = default_depth() if depth is None else max(int(depth), 1)
        self.metrics = metrics
        self.tracer = tracer
        self.transfer = transfer
        self.stall_timeout_s = float(stall_timeout_s)
        self._q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._peak_depth = 0
        self.produced = 0
        self.stall_s = 0.0  # total consumer wait, all epochs
        self.last_epoch_stall_s = 0.0  # consumer wait within the last epoch
        self._thread = threading.Thread(
            target=self._produce, name="sample-pipeline", daemon=True
        )
        self._thread.start()

    # ---- producer thread -------------------------------------------------
    def _span(self, name: str, dur_s: float, t0: float, **attrs) -> None:
        if self.tracer is not None:
            self.tracer.complete(
                name, dur_s=dur_s, t0=t0, cat="sample", **attrs
            )

    def _put(self, item) -> bool:
        """Bounded put that stays responsive to close(); False = stopping."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self) -> None:
        try:
            for epoch in self.epochs:
                if self._stop.is_set():
                    return
                it = iter(self.source.sample_epoch(epoch))
                idx = 0
                while not self._stop.is_set():
                    t0 = get_time()
                    try:
                        b = next(it)
                    except StopIteration:
                        break
                    # chaos hook: exc/stall/crash specs with
                    # point=sample_produce fire here, inside the worker
                    fault_point("sample_produce", epoch=epoch)
                    t1 = get_time()
                    payload = self.transfer(b)
                    t2 = get_time()
                    self._span("sample_produce", t1 - t0, t0,
                               epoch=int(epoch), index=idx)
                    self._span("h2d_copy", t2 - t1, t1,
                               epoch=int(epoch), index=idx)
                    if not self._put((epoch, idx, payload)):
                        return
                    self.produced += 1
                    depth = self._q.qsize()
                    if self.metrics is not None:
                        self.metrics.counter_add("sample.produced")
                        self.metrics.counter_add(
                            "sample.h2d_ms", (t2 - t1) * 1000.0
                        )
                        # the staged payload's size next to its time:
                        # zero-H2D (SAMPLE_PIPELINE:fused) is a measured
                        # number, not just a structural claim
                        self.metrics.counter_add(
                            "sample.h2d_bytes", payload_nbytes(b)
                        )
                        # depth as a distribution (obs/hist), not just a
                        # peak: stall diagnosis sees whether the queue sat
                        # empty (consumer-starved) or full (backpressured)
                        self.metrics.hist_observe(
                            "sample.queue_depth", depth, unit=""
                        )
                        if depth > self._peak_depth:
                            self._peak_depth = depth
                            self.metrics.gauge_set(
                                "sample.queue_depth", depth
                            )
                    elif depth > self._peak_depth:
                        self._peak_depth = depth
                    idx += 1
                if not self._put(_EpochDone(epoch)):
                    return
        except BaseException as e:  # surface at the consumer, never hang
            import traceback

            msg = f"{type(e).__name__}: {e}\n" + traceback.format_exc(limit=6)
            log.warning("sampling pipeline worker failed: %s", e)
            # bypass the bounded put's stop gate last: even a closing
            # pipeline should record the failure if there is room
            if not self._put(_WorkerFailed(msg)):
                try:
                    self._q.put_nowait(_WorkerFailed(msg))
                except queue.Full:
                    pass

    # ---- consumer side ---------------------------------------------------
    def _get(self):
        waited = 0.0
        while True:
            try:
                return self._q.get(timeout=0.25)
            except queue.Empty:
                waited += 0.25
                if not self._thread.is_alive():
                    raise SampleWorkerError(
                        "sampling pipeline worker died without delivering "
                        "its epoch (see the log for its traceback)"
                    )
                if waited >= self.stall_timeout_s:
                    # a batch takes ~ms; this much silence means a wedged
                    # worker (e.g. deadlocked child pool) — fail loudly,
                    # never hang the epoch
                    raise SampleWorkerError(
                        f"sampling pipeline stalled for "
                        f"{self.stall_timeout_s:g}s with a live worker"
                    )

    def epoch_stream(self, epoch: int):
        """Yield this epoch's device-resident payloads in order. Epochs
        must be consumed in the constructor's scheduled order."""
        self.last_epoch_stall_s = 0.0
        while True:
            t0 = get_time()
            item = self._get()
            wait = get_time() - t0
            self.stall_s += wait
            self.last_epoch_stall_s += wait
            if self.metrics is not None:
                self.metrics.counter_add("sample.stall_ms", wait * 1000.0)
                self.metrics.hist_observe("sample.stall_ms", wait * 1000.0)
            self._span("sample_wait", wait, t0, epoch=int(epoch))
            if isinstance(item, _WorkerFailed):
                raise SampleWorkerError(
                    f"sampling pipeline worker failed: {item.msg}"
                )
            if isinstance(item, _EpochDone):
                if item.epoch != epoch:
                    raise SampleWorkerError(
                        f"sampling pipeline out of order: consumer asked "
                        f"for epoch {epoch}, producer finished "
                        f"{item.epoch} (epochs must be consumed in the "
                        "scheduled order)"
                    )
                return
            e, idx, payload = item
            if e != epoch:
                raise SampleWorkerError(
                    f"sampling pipeline out of order: got batch {idx} of "
                    f"epoch {e} while consuming epoch {epoch}"
                )
            yield payload

    @property
    def peak_depth(self) -> int:
        return self._peak_depth

    def close(self) -> None:
        """Drain and join the producer (idempotent). Safe mid-epoch: an
        early-stopped consumer calls this and no thread survives it."""
        self._stop.set()
        # unblock a producer stuck in put() by draining whatever is queued;
        # bounded — a producer wedged inside the source itself cannot be
        # interrupted, only diagnosed
        deadline = get_time() + 5.0
        while self._thread.is_alive() and get_time() < deadline:
            try:
                self._q.get(timeout=0.05)
            except queue.Empty:
                pass
            self._thread.join(timeout=0.05)
        if self._thread.is_alive():  # pragma: no cover - diagnostics only
            log.warning(
                "sampling pipeline worker did not exit within 5s of "
                "close() (daemon thread; it dies with the process)"
            )
