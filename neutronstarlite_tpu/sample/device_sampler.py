"""Jitted on-device uniform-neighbor sampling over a device-resident CSR.

The host sampler (sample/sampler.py) draws per-destination uniform
without-replacement neighbor sets by ranking random priorities over the
candidate edge list — exact, but serial host work on the step loop's
critical path. This module is the opt-in fast path for the uniform case
(``SAMPLE_PIPELINE:device``): the neighbor structure lives on the device
as a fixed-width table and the draw is one jitted program (gather +
``jax.random`` + ``top_k``), the design the hardware-sampling paper's
fixed-size neighbor buffers argue for (PAPERS.md, arXiv:2209.02916).

Layout: a padded neighbor table ``nbr [V, D]`` (D = min(max in-degree,
``NTS_SAMPLE_DEVICE_MAX_DEG``, default 512)) plus the effective degree
``eff_deg [V]``. Vertices with more than D in-neighbors are pre-thinned
to D uniformly at table build (seeded, host-side, once) — the fixed-width
buffer's capacity rule; within the table every draw is exact uniform
without replacement:

    prio ~ U[0,1) per slot; padding slots get prio=2
    chosen = top_k(-prio, fanout)         # k smallest priorities
    valid  = chosen prio < 2              # slot was real

which is precisely the host sampler's priority-ranking construction, so
the two distributions match (tests/test_sample_pipeline.py pins this
statistically, and exactly for deg <= fanout).

Determinism: each draw consumes one 31-bit seed from the caller's numpy
Generator (the Sampler's per-batch seeded rng), so device sampling is
reproducible per (epoch, batch index) like the host path — but the draws
themselves differ from the host sampler's (a different PRNG), so
``device`` mode is distribution-equivalent, not bitwise-equal, to
``sync``/``pipelined`` (docs/SAMPLING.md spells out the contract).
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from neutronstarlite_tpu.graph.storage import CSCGraph
from neutronstarlite_tpu.utils.logging import get_logger

log = get_logger("device_sampler")


@functools.partial(jax.jit, static_argnums=(4,))
def _hop(nbr, eff_deg, key, dsts, fanout: int):
    """One uniform without-replacement draw for every dst row: k smallest
    of per-slot random priorities, padding slots priced out at prio=2.
    The table rides as an ARGUMENT (never a closure constant — a Reddit-
    scale table inlined into the HLO would be a gigabyte-sized program)."""
    rows = nbr[dsts]  # [B, D]
    eff = eff_deg[dsts]  # [B]
    slot = jnp.arange(rows.shape[1])[None, :]
    prio = jax.random.uniform(key, rows.shape)
    prio = jnp.where(slot < eff[:, None], prio, 2.0)
    neg, idx = jax.lax.top_k(-prio, fanout)  # k smallest priorities
    src = jnp.take_along_axis(rows, idx, axis=1)  # [B, fanout]
    valid = -neg < 1.5  # padding slots carry prio 2
    return src, valid


def default_max_width() -> int:
    raw = os.environ.get("NTS_SAMPLE_DEVICE_MAX_DEG", "")
    if raw:
        try:
            return max(int(raw), 1)
        except ValueError:
            log.warning(
                "NTS_SAMPLE_DEVICE_MAX_DEG=%r is not an int; using 512", raw
            )
    return 512


class DeviceUniformSampler:
    """Fixed-width device neighbor table + the jitted per-hop draw."""

    def __init__(self, nbr, eff_deg, width: int, thinned: int):
        self.width = int(width)
        self.thinned = int(thinned)  # vertices whose neighbor set was capped
        self.nbr = jax.device_put(nbr)  # [V, D] int32
        self.eff_deg = jax.device_put(eff_deg)  # [V] int32
        # row-capacity margin (stream/ingest): slack rows beyond V with
        # eff_deg 0 — never drawn from until a vertex append claims them
        self.margin = 0

    def reserve_capacity(self, extra_rows: int) -> None:
        """Pre-size the table with ``extra_rows`` slack rows so vertex
        appends within the margin PATCH rows in place instead of forcing
        a full rebuild + re-upload (the stream ingestion contract —
        docs/STREAMING.md). Slack rows carry eff_deg 0, so no draw ever
        reads them until a delta's dirty_rows patch claims them."""
        extra = int(extra_rows)
        if extra <= 0:
            return
        self.margin = max(self.margin, extra)
        pad_nbr = jnp.zeros((extra, int(self.nbr.shape[1])), dtype=jnp.int32)
        pad_deg = jnp.zeros((extra,), dtype=jnp.int32)
        self.nbr = jnp.concatenate([self.nbr, pad_nbr], axis=0)
        self.eff_deg = jnp.concatenate([self.eff_deg, pad_deg], axis=0)

    @classmethod
    def from_host(
        cls,
        graph: CSCGraph,
        max_width: Optional[int] = None,
        seed: int = 0,
    ) -> "DeviceUniformSampler":
        cap = default_max_width() if max_width is None else max(int(max_width), 1)
        deg = graph.in_degree.astype(np.int64)
        v_num = graph.v_num
        D = int(min(max(deg.max() if len(deg) else 1, 1), cap))
        total = int(deg.sum())
        # slot index of every edge within its destination's run; edge
        # positions go through column_offset (the host sampler's gather),
        # never an assumed-contiguous row_indices layout
        within = np.arange(total) - np.repeat(np.cumsum(deg) - deg, deg)
        starts = graph.column_offset[:-1].astype(np.int64)
        pos = np.repeat(starts, deg) + within
        src = graph.row_indices[pos].astype(np.int64)
        dst = np.repeat(np.arange(v_num), deg)
        thinned = int((deg > D).sum())
        if thinned:
            # pre-thin over-capacity vertices uniformly (the same random-
            # priority ranking the host sampler uses, seeded once)
            prio = np.random.default_rng(seed).random(total)
            order = np.lexsort((prio, dst))
            rank = np.arange(total) - np.repeat(np.cumsum(deg) - deg, deg)
            keep = order[rank < D]
            src, dst = src[keep], dst[keep]
            eff = np.minimum(deg, D)
            within = (
                np.arange(len(src))
                - np.repeat(np.cumsum(eff) - eff, eff)
            )
            log.warning(
                "device sampler: %d vertices exceed the %d-wide neighbor "
                "table; their neighbor sets are pre-thinned uniformly at "
                "build (NTS_SAMPLE_DEVICE_MAX_DEG raises the cap)",
                thinned, D,
            )
        else:
            eff = deg
        nbr = np.zeros((v_num, D), dtype=np.int32)
        nbr[dst, within] = src.astype(np.int32)
        return cls(nbr, eff.astype(np.int32), D, thinned)

    def apply_delta(self, graph: CSCGraph, rows, seed: int = 0) -> int:
        """Patch ONLY the neighbor-table rows a graph delta touched
        (serve/delta.py ``dirty_rows`` — vertices whose in-neighbor SET
        changed): each dirty row is regathered from the post-delta host
        CSC and scattered into the resident table in place, so an
        edge-level delta never re-uploads the [V, D] table. Falls back to
        a full rebuild (logged) only when the table's SHAPE must change —
        appended vertices (new V), a dirty vertex outgrowing the current
        width while the width sits below the NTS_SAMPLE_DEVICE_MAX_DEG
        cap, or a dirty row that needs PRE-THINNING (deg > width: the
        build-time thin draws from one global priority stream, and a
        per-row re-draw would diverge from what a fresh table holds —
        the bitwise fresh-engine oracle demands the rebuilt form).
        Returns the number of rows written (V on a rebuild)."""
        rows = np.unique(np.asarray(rows, dtype=np.int64))
        cap = default_max_width()
        max_deg = int(graph.in_degree.max()) if graph.v_num else 1
        needed = int(min(max(max_deg, 1), cap))
        rows_over = (
            len(rows) > 0
            and int(graph.in_degree[rows].max()) > self.width
        )
        # a table holding ANY pre-thinned rows rebuilds too: their kept
        # neighbor subsets came from the PRE-delta global priority stream
        # (positions shift with the edge layout), so an in-place patch of
        # other rows would leave them diverged from what a fresh build
        # over the post-delta graph holds — only full shapes patch. With
        # a reserved capacity margin (reserve_capacity), appended
        # vertices whose rows still fit the table patch like any dirty
        # row; only OUTGROWING the physical rows forces the rebuild
        if (graph.v_num > int(self.nbr.shape[0]) or needed > self.width
                or rows_over or self.thinned > 0):
            log.warning(
                "device sampler: delta changed the table shape or "
                "touched a pre-thinned row (V %d -> %d, width %d -> %d); "
                "rebuilding the full neighbor table",
                int(self.nbr.shape[0]), graph.v_num, self.width, needed,
            )
            fresh = DeviceUniformSampler.from_host(graph, seed=seed)
            self.nbr, self.eff_deg = fresh.nbr, fresh.eff_deg
            self.width, self.thinned = fresh.width, fresh.thinned
            if self.margin:
                self.reserve_capacity(self.margin)  # keep the slack armed
            return graph.v_num
        if len(rows) == 0:
            return 0
        D = self.width
        patch = np.zeros((len(rows), D), dtype=np.int32)
        eff = graph.in_degree[rows].astype(np.int32)  # all <= D here
        for j, v in enumerate(rows.tolist()):
            start = int(graph.column_offset[v])
            d = int(graph.in_degree[v])
            patch[j, :d] = graph.row_indices[start:start + d]
        idx = jnp.asarray(rows, dtype=jnp.int32)
        self.nbr = self.nbr.at[idx].set(jnp.asarray(patch))
        self.eff_deg = self.eff_deg.at[idx].set(jnp.asarray(eff))
        return int(len(rows))

    def sample_neighbors(
        self,
        dsts: np.ndarray,
        fanout: int,
        rng: np.random.Generator,
        cap: Optional[int] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Host-facing drop-in for Sampler._sample_neighbors: (src global
        ids, dst batch-local indices) for up to ``fanout`` distinct uniform
        in-neighbors per dst. ``cap`` pads the dst set to a static shape so
        the jitted draw compiles once per hop level (one compiled program
        per (cap, fanout) pair — both come from the sampler's static
        node_caps/fanouts, so the cache is tiny)."""
        n_real = len(dsts)
        B = int(cap) if cap is not None else n_real
        if n_real > B:
            raise ValueError(f"{n_real} dsts exceed the static cap {B}")
        fanout = int(min(fanout, self.width))
        dsts_pad = np.zeros(B, dtype=np.int64)
        dsts_pad[:n_real] = dsts
        key = jax.random.PRNGKey(int(rng.integers(0, 2**31 - 1)))
        src, valid = _hop(self.nbr, self.eff_deg, key, dsts_pad, fanout)
        src = np.asarray(src)
        valid = np.array(valid)  # writable copy (device buffers are not)
        valid[n_real:] = False  # padded dst rows are not real draws
        dst_idx = np.broadcast_to(
            np.arange(B, dtype=np.int64)[:, None], src.shape
        )
        keep = valid.ravel()
        return (
            src.ravel().astype(np.int64)[keep],
            dst_idx.ravel()[keep],
        )
