from neutronstarlite_tpu.sample.sampler import Sampler, SampledBatch

__all__ = ["Sampler", "SampledBatch"]
