from neutronstarlite_tpu.sample.sampler import Sampler, SampledBatch

__all__ = ["Sampler", "SampledBatch"]

# sample.pipeline (SamplePipeline / resolve_sample_pipeline) and
# sample.device_sampler (DeviceUniformSampler) are imported lazily by
# their consumers — both pull jax, which this package root must not.
