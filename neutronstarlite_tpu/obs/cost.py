"""Compiled-program cost attribution: XLA's own numbers per executable.

Every measurement surface so far is OUTSIDE the executable: host epoch
timers, analytically priced wire counters, structural jaxpr pins ("no
[Ep, f] aval"). XLA itself knows more — ``Compiled.cost_analysis()``
(FLOPs, bytes accessed) and ``Compiled.memory_analysis()`` (argument /
output / temp / generated-code buffer allocation) — and it knows it ONCE,
at compile time, for exactly the program that will run. This module
captures that as one typed ``program_cost`` record per executable, keyed
by a stable program label, so the perf ledger (obs/ledger.py) and the
drift auditor (tools/drift_audit.py) get real per-executable numbers next
to the structural pins.

Two capture paths, cheapest that fits:

- ``compiled=``: an already-compiled ``jax.stages.Compiled`` (the serve
  engine's AOT bucket ladder, comm_bench legs) — cost AND memory
  analysis are free reads off the existing executable
  (``source="compiled"``).
- ``jitted=`` + ``args=``: a ``jax.jit`` function the caller will invoke
  through the normal cached-call path (train steps). Lowering is one
  extra trace but NO extra compile (``Lowered.cost_analysis()`` runs
  XLA's HLO cost pass on the unoptimized module), so the default capture
  never doubles a trainer's compile time (``source="lowered"``, memory
  null). ``NTS_COST_MEMORY=1`` opts into compiling the lowering too for
  the full memory analysis — the persistent compile cache makes that a
  near-free second hit where it is configured.

Degradation is graceful and LOUD-in-band: a backend that exposes neither
analysis (or a lowering that fails) still leaves a record —
``available=false`` with the error — never a crash and never silence
(the "probe that times out leaves no trace" postmortem, applied to cost
capture). ``NTS_PROGRAM_COST`` is three-state: ``0`` never, ``1``
always, unset = capture only when telemetry persists (a JSONL sink or an
armed ledger) — see :func:`cost_enabled` for why the auto gate exists.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from neutronstarlite_tpu.utils.logging import get_logger

log = get_logger("obs")

# memory_analysis attribute -> record field (plain ints; the host_* split
# is dropped — host staging buffers are not the HBM envelope this record
# exists to pin)
_MEM_FIELDS = {
    "argument_size_in_bytes": "argument_bytes",
    "output_size_in_bytes": "output_bytes",
    "temp_size_in_bytes": "temp_bytes",
    "alias_size_in_bytes": "alias_bytes",
    "generated_code_size_in_bytes": "generated_code_bytes",
}


def cost_enabled(metrics=None) -> bool:
    """Three-state ``NTS_PROGRAM_COST``: ``0`` = never, ``1`` = always,
    unset = AUTO — capture only when the telemetry is actually being
    persisted (the registry has a JSONL sink, or ``NTS_LEDGER_DIR`` is
    armed). The auto gate matters: ``Lowered.cost_analysis()`` runs an
    XLA pass over the traced module, which costs seconds per dist
    trainer build — fine inside an instrumented run, unacceptable as a
    tax on every bare construction in the test suite."""
    raw = os.environ.get("NTS_PROGRAM_COST", "")
    if raw == "0":
        return False
    if raw == "1":
        return True
    if metrics is not None and getattr(metrics, "path", None):
        return True
    return bool(os.environ.get("NTS_LEDGER_DIR"))


def memory_capture_enabled() -> bool:
    """``NTS_COST_MEMORY=1``: compile the capture lowering too, so
    jit-path programs (train steps) get the full memory analysis. Off by
    default — it doubles compile work where no persistent compile cache
    backs the run."""
    return os.environ.get("NTS_COST_MEMORY", "0") == "1"


def _first_module(analysis) -> Optional[Dict[str, Any]]:
    """cost_analysis() returns a dict on current jax, a one-per-module
    list on older releases; either way the program's numbers are the
    first module's."""
    if isinstance(analysis, dict):
        return analysis
    if isinstance(analysis, (list, tuple)) and analysis:
        first = analysis[0]
        return first if isinstance(first, dict) else None
    return None


def cost_from_analysis(analysis) -> Dict[str, Optional[float]]:
    """{flops, bytes_accessed, transcendentals} from one cost_analysis()
    result (nulls where the backend omits a key)."""
    d = _first_module(analysis) or {}

    def num(key):
        v = d.get(key)
        return float(v) if isinstance(v, (int, float)) else None

    return {
        "flops": num("flops"),
        "bytes_accessed": num("bytes accessed"),
        "transcendentals": num("transcendentals"),
    }


def memory_from_compiled(compiled) -> Optional[Dict[str, Optional[int]]]:
    """The memory_analysis() buffer-allocation numbers as a plain dict,
    or None when the backend exposes none. ``peak_bytes`` is the derived
    live-at-once envelope: arguments + outputs + temporaries (XLA's
    buffer assignment holds all three live across the program body)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out: Dict[str, Optional[int]] = {}
    for attr, field in _MEM_FIELDS.items():
        v = getattr(ma, attr, None)
        out[field] = int(v) if isinstance(v, int) else None
    sized = [out.get(k) for k in ("argument_bytes", "output_bytes",
                                  "temp_bytes")]
    out["peak_bytes"] = (
        sum(v for v in sized if v is not None)
        if any(v is not None for v in sized) else None
    )
    if all(v is None for v in out.values()):
        return None
    return out


def capture_program_cost(
    metrics,
    label: str,
    compiled=None,
    jitted=None,
    args: tuple = (),
    **extra: Any,
) -> Optional[Dict[str, Any]]:
    """Capture one program's cost as a typed ``program_cost`` record.

    ``metrics``: the run's MetricsRegistry (record lands in its stream
    AND in its run_summary ``program_costs`` list — bench.py's
    ``extra.metrics`` therefore carries it for free). Returns the record,
    or None when capture is disabled or the registry is absent. Never
    raises: a failed analysis emits ``available=false`` with the error.
    """
    if metrics is None or not cost_enabled(metrics):
        return None
    fields: Dict[str, Any] = {
        "label": str(label),
        "available": False,
        "source": "error",
        "flops": None,
        "bytes_accessed": None,
        "transcendentals": None,
        "memory": None,
    }
    try:
        import jax

        fields["platform"] = jax.default_backend()
    except Exception:
        fields["platform"] = None
    try:
        if compiled is None and jitted is not None:
            lowered = jitted.lower(*args)
            if memory_capture_enabled():
                compiled = lowered.compile()
            else:
                fields.update(cost_from_analysis(lowered.cost_analysis()))
                fields["source"] = "lowered"
                fields["available"] = (
                    fields["flops"] is not None
                    or fields["bytes_accessed"] is not None
                )
        if compiled is not None:
            try:
                fields.update(cost_from_analysis(compiled.cost_analysis()))
            except Exception as e:
                fields["error"] = f"cost_analysis: {e}"
            fields["memory"] = memory_from_compiled(compiled)
            fields["source"] = "compiled"
            fields["available"] = (
                fields["flops"] is not None
                or fields["bytes_accessed"] is not None
                or fields["memory"] is not None
            )
        if not fields["available"] and "error" not in fields:
            # neither analysis yielded a number: a degraded backend —
            # the record still lands (queryable absence, not silence)
            fields.setdefault(
                "error", "backend exposed no cost or memory analysis"
            )
    except Exception as e:  # telemetry must never fail the program build
        fields["error"] = str(e)[:300]
        log.warning("program_cost capture failed for %s: %s", label, e)
    rec = metrics.event("program_cost", **dict(fields, **extra))
    record_list = getattr(metrics, "program_costs", None)
    if record_list is not None:
        record_list.append(
            {k: v for k, v in rec.items()
             if k not in ("event", "run_id", "schema", "seq")}
        )
    return rec
