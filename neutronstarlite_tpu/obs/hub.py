"""Cross-host telemetry aggregation: the pull-based fleet hub.

Every observability surface before this was process-local: each rank
(or serve replica) streams its own JSONL and serves its own /metrics,
and the only cross-stream math lived in offline tools (metrics_report
over many files) or in-process (serve/fleet's merged latency view). A
real fleet is N *hosts* — there is no shared filesystem and no shared
process — so this module adds the missing tier: a hub that POLLS each
target's ``/telemetry`` endpoint (obs/exporter — the full-resolution
NDJSON snapshot: native 1.02-growth histogram buckets, gauges/counters,
SLO verdicts, health) and rebuilds the fleet view centrally.

Why /telemetry and not /metrics: the Prometheus ladder is LOSSY (a
fixed ~18-edge histogram; obs/hist.PROM_EDGES_MS) — quantiles
reconstructed from it carry unbounded error on distributions that land
between edges. The /telemetry payload ships the native buckets, so the
hub reconstructs each :class:`~neutronstarlite_tpu.obs.hist.LogHistogram`
and merges via the exact bucket-addition merge law — the SAME math
``latest_hists`` applies to multi-rank streams and serve/fleet applies
in-process. Fleet p50/p95/p99 from the hub are therefore exact up to
the histogram's own documented ~1% relative bucket error, never the
ladder's.

The hub is itself an ordinary observability citizen:

- its merged histograms are installed into its own
  :class:`~neutronstarlite_tpu.obs.registry.MetricsRegistry` (via
  ``hist_set``), so the stock exporter renders the FLEET view on the
  hub's own /metrics and /healthz (``health_payload`` understands the
  ``hub.*`` gauges: lost targets = degraded-but-ok while any target
  still answers);
- every poll appends typed records to ONE schema-valid merged stream
  under ``NTS_METRICS_DIR`` (a ``telemetry`` record with
  ``source="hub"``, cumulative ``hist`` snapshots, ``target_loss`` /
  ``recovery`` on liveness edges), rendered natively by
  tools/metrics_report and tools/dashboard;
- each poll cycle can append a ``kind=fleet`` row to the perf ledger
  (obs/ledger.fleet_row), putting fleet tail latency and
  ``targets_lost`` on a perf_sentinel-gated trajectory.

Per-target liveness reuses the miss-K pattern from
resilience/elastic.LivenessMonitor: a target that fails
``NTS_HUB_MISS_K`` consecutive polls becomes ONE typed ``target_loss``
record (the cross-host analog of ``rank_loss``) — never an exception;
the hub keeps polling and keeps serving the survivors' merged view with
the lost target's histograms FROZEN at their last-seen snapshot (a
cumulative histogram of real observations remains true after its source
dies; dropping it would deflate fleet counts). A target that answers
again emits a ``recovery`` record (``action="target_rejoin"``) and
resumes live updates.

Knobs: ``NTS_HUB_TARGETS`` (comma-separated target URLs or host:port),
``NTS_HUB_POLL_S`` (default 2.0), ``NTS_HUB_MISS_K`` (default 3).
CLI: tools/telemetry_hub.py; rendering: tools/dashboard.py.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

from neutronstarlite_tpu.obs import httpc, ledger, registry as obs_registry
from neutronstarlite_tpu.obs.hist import LogHistogram, latest_hists
from neutronstarlite_tpu.obs.schema import validate_event
from neutronstarlite_tpu.utils.logging import get_logger

log = get_logger("obs")

DEFAULT_POLL_S = 2.0
DEFAULT_MISS_K = 3
FETCH_TIMEOUT_S = 5.0


# ---- knobs ------------------------------------------------------------------


def hub_targets() -> List[str]:
    """``NTS_HUB_TARGETS``: comma-separated /telemetry endpoints."""
    raw = os.environ.get("NTS_HUB_TARGETS", "")
    return [t.strip() for t in raw.split(",") if t.strip()]


def hub_poll_s() -> float:
    raw = os.environ.get("NTS_HUB_POLL_S", "")
    if not raw:
        return DEFAULT_POLL_S
    try:
        return max(float(raw), 0.0)
    except ValueError:
        log.warning("bad NTS_HUB_POLL_S=%r; using %g", raw, DEFAULT_POLL_S)
        return DEFAULT_POLL_S


def hub_miss_k() -> int:
    raw = os.environ.get("NTS_HUB_MISS_K", "")
    if not raw:
        return DEFAULT_MISS_K
    try:
        return max(int(raw), 1)
    except ValueError:
        log.warning("bad NTS_HUB_MISS_K=%r; using %d", raw, DEFAULT_MISS_K)
        return DEFAULT_MISS_K


def normalize_target(target: str) -> str:
    """``host:port`` / bare URLs normalize to a full /telemetry URL (a
    URL already naming a path — e.g. ``...?replica=r1`` — passes
    through untouched)."""
    t = target.strip()
    if not t.startswith("http://") and not t.startswith("https://"):
        t = f"http://{t}"
    scheme, _, rest = t.partition("://")
    if "/" not in rest:
        t = f"{scheme}://{rest}/telemetry"
    return t


def _default_fetch(url: str) -> str:
    """One poll's fetch: the shared retrying client (obs/httpc), so a
    single dropped connection is retried within the poll before it costs
    the target one of its ``miss_k`` misses — retry-then-miss, not
    miss-on-first-blip. The whole retry budget is bounded by the poll
    timeout so a hung target cannot stall the cycle."""
    return httpc.fetch(url, timeout_s=FETCH_TIMEOUT_S,
                       deadline_s=FETCH_TIMEOUT_S * 2)


class _Target:
    """One polled endpoint's liveness + last-known snapshot."""

    def __init__(self, url: str):
        self.url = url
        self.missed = 0
        self.lost = False  # latched at miss_k (one record per loss)
        self.ever_ok = False
        self.last_ok_ts: Optional[float] = None
        self.records: List[Dict[str, Any]] = []  # last VALID snapshot


class TelemetryHub:
    """Poll N ``/telemetry`` targets; merge into one fleet view.

    ``fetch`` is injectable (tests drive the hub without sockets); the
    default is the shared retrying client (obs/httpc: bounded jittered
    backoff under a per-poll deadline). The hub NEVER
    raises out of a poll: a dead target is a liveness fact (miss-K ->
    ``target_loss``), a malformed payload is a warning + a miss (a
    half-written response must not poison the merged view), and ledger
    /stream failures degrade to warnings like every obs writer."""

    def __init__(self, targets: List[str], *,
                 poll_s: Optional[float] = None,
                 miss_k: Optional[int] = None,
                 registry: Optional[obs_registry.MetricsRegistry] = None,
                 ledger_dir: Optional[str] = None,
                 ledger_every: int = 1,
                 fetch: Optional[Callable[[str], str]] = None):
        if not targets:
            raise ValueError("TelemetryHub needs at least one target "
                             "(NTS_HUB_TARGETS or --targets)")
        self.targets = [_Target(normalize_target(t)) for t in targets]
        self.poll_s = hub_poll_s() if poll_s is None else max(
            float(poll_s), 0.0
        )
        self.miss_k = hub_miss_k() if miss_k is None else max(int(miss_k), 1)
        self.registry = registry or obs_registry.open_run("hub")
        self._owns_registry = registry is None
        self.ledger_dir = ledger_dir
        self.ledger_every = max(int(ledger_every), 1)
        self.fetch = fetch or _default_fetch
        self.polls = 0
        self.started_at = time.time()

    # ---- one poll cycle --------------------------------------------------

    def _poll_target(self, t: _Target) -> bool:
        """Fetch + validate one target; True on a good snapshot."""
        try:
            body = self.fetch(t.url)
        except Exception as e:
            log.warning("hub: target %s unreachable (%s)", t.url, e)
            return False
        records: List[Dict[str, Any]] = []
        try:
            for ln, raw in enumerate(body.splitlines(), 1):
                raw = raw.strip()
                if not raw:
                    continue
                rec = json.loads(raw)
                validate_event(rec)
                records.append(rec)
        except ValueError as e:
            # schema-invalid or torn mid-line: treat as a failed poll —
            # a half-written payload must not replace a good snapshot
            log.warning("hub: target %s returned a bad payload at line "
                        "%d (%s)", t.url, ln, e)
            return False
        if not records:
            log.warning("hub: target %s returned an empty payload", t.url)
            return False
        t.records = records
        return True

    def merged_hists(self) -> Dict[str, LogHistogram]:
        """The fleet histograms: every target's last-known cumulative
        ``hist`` records merged by the exact bucket-addition law
        (distinct run_ids merge, latest per run supersedes —
        obs/hist.latest_hists). Lost targets contribute their FROZEN
        last snapshot."""
        pool: List[Dict[str, Any]] = []
        for t in self.targets:
            pool.extend(t.records)
        return latest_hists(pool)

    def slo_rollup(self) -> Dict[str, Any]:
        """The fleet SLO posture: verdict counts over every target's
        last-seen ``slo_status`` records (latest per (run, objective))."""
        latest: Dict[tuple, str] = {}
        for t in self.targets:
            for rec in t.records:
                if rec.get("event") != "slo_status":
                    continue
                key = (rec.get("run_id"), rec.get("objective"))
                latest[key] = str(rec.get("state"))
        states = list(latest.values())
        return {
            "objectives": len(states),
            "breaching": sum(1 for s in states if s == "breach"),
            "worst": ("breach" if any(s == "breach" for s in states)
                      else "ok" if states else "none"),
        }

    def poll_once(self) -> Dict[str, Any]:
        """One poll cycle over every target: liveness accounting, the
        exact histogram merge, the merged-view refresh (own registry
        gauges + cumulative hist records + one ``telemetry`` record),
        and optionally one ``kind=fleet`` ledger row."""
        self.polls += 1
        now = time.time()
        ok = 0
        for t in self.targets:
            if self._poll_target(t):
                ok += 1
                t.missed = 0
                t.last_ok_ts = now
                t.ever_ok = True
                if t.lost:
                    t.lost = False
                    # the cross-host rejoin: same record the elastic
                    # plane uses for every healed state
                    self.registry.event(
                        "recovery", action="target_rejoin", target=t.url,
                    )
                    log.warning("hub: target %s rejoined", t.url)
            else:
                t.missed += 1
                if t.missed >= self.miss_k and not t.lost:
                    t.lost = True
                    self.registry.event(
                        "target_loss", target=t.url,
                        reason=("poll_miss" if t.ever_ok
                                else "never_answered"),
                        missed_polls=int(t.missed),
                        miss_k=int(self.miss_k),
                        last_ok_ts=t.last_ok_ts,
                    )
                    log.warning(
                        "hub: target %s LOST (%d consecutive missed "
                        "poll(s), NTS_HUB_MISS_K=%d) — merged view "
                        "continues on the survivors with its last "
                        "snapshot frozen", t.url, t.missed, self.miss_k,
                    )
        lost = sum(1 for t in self.targets if t.lost)
        merged = self.merged_hists()
        for name, h in sorted(merged.items()):
            self.registry.hist_set(name, h)
        self.registry.gauge_set("hub.targets", len(self.targets))
        self.registry.gauge_set("hub.targets_ok", ok)
        self.registry.gauge_set("hub.targets_lost", lost)
        self.registry.counter_add("hub.polls", 1.0)
        self.registry.emit_hists()
        slo = self.slo_rollup()
        self.registry.event(
            "telemetry", source="hub",
            counters=self.registry.snapshot(include_hists=False)["counters"],
            gauges={
                "hub.targets": len(self.targets),
                "hub.targets_ok": ok,
                "hub.targets_lost": lost,
            },
            slo=slo,
            targets=len(self.targets), targets_ok=ok, targets_lost=lost,
            uptime_s=round(now - self.started_at, 3),
        )
        if self.ledger_dir and self.polls % self.ledger_every == 0:
            hq = {
                name: {"count": h.count, **h.quantiles()}
                for name, h in sorted(merged.items())
            }
            ledger.append_row(
                ledger.fleet_row(
                    len(self.targets), ok, lost, self.polls, hq,
                ),
                directory=self.ledger_dir,
            )
        return {
            "poll": self.polls,
            "targets": len(self.targets),
            "targets_ok": ok,
            "targets_lost": lost,
            "hists": {n: h.count for n, h in merged.items()},
            "slo": slo,
        }

    # ---- lifecycle -------------------------------------------------------

    def run(self, polls: Optional[int] = None,
            on_poll: Optional[Callable[[Dict[str, Any]], None]] = None,
    ) -> Dict[str, Any]:
        """Poll forever (or ``polls`` times); returns the last cycle's
        summary. KeyboardInterrupt exits cleanly (the CLI's ^C)."""
        last: Dict[str, Any] = {}
        n = 0
        try:
            while polls is None or n < polls:
                cycle_t0 = time.time()
                last = self.poll_once()
                if on_poll is not None:
                    on_poll(last)
                n += 1
                if polls is not None and n >= polls:
                    break
                sleep_s = self.poll_s - (time.time() - cycle_t0)
                if sleep_s > 0:
                    time.sleep(sleep_s)
        except KeyboardInterrupt:
            log.warning("hub: interrupted; closing the merged stream")
        return last

    def close(self) -> None:
        """Flush the final merged snapshot and close the hub's stream
        (only a registry the hub itself opened)."""
        if self._owns_registry:
            try:
                self.registry.emit_hists()
            finally:
                self.registry.close()

    def stream_path(self) -> Optional[str]:
        return self.registry.path
