"""Shared retrying HTTP client for every cross-host surface.

Two subsystems talk HTTP to the fleet: the telemetry hub polls
``/telemetry`` (obs/hub.py) and the cross-host serve router scrapes the
same endpoint plus POSTs ``/predict`` (serve/crosshost.py). Before this
module each caller rolled its own single-shot urllib fetch, so one
dropped SYN — a replica mid-restart, a transient listen-queue overflow —
counted as a full missed poll. This client gives them ONE retry policy:

- a per-request socket timeout (``NTS_HTTPC_TIMEOUT_S``, default 5.0 —
  the hub's historical FETCH_TIMEOUT_S);
- bounded retries (``NTS_HTTPC_RETRIES``, default 2 retries after the
  first attempt) with the supervisor's jittered exponential backoff
  math reused verbatim (resilience/supervisor.backoff_jitter_frac):
  ``delay = base * 2**(attempt-1) * (1 + jitter)``, base
  ``NTS_HTTPC_BACKOFF_S`` (default 0.05 s — scrapes, not restarts);
- an overall per-call deadline (``deadline_s``) that bounds BOTH the
  in-flight request and any backoff sleep — a caller with a poll budget
  never overshoots it because a retry was in progress;
- a typed error taxonomy so callers can route on failure mode instead
  of string-matching urllib internals: :class:`HttpTimeout` (the socket
  timed out / the deadline expired mid-flight), :class:`HttpRefused`
  (connection refused / reset — the "process is dead" signal the router
  escalates), :class:`HttpStatusError` (an answer arrived but not 200 —
  carries ``.status``). All subclass :class:`HttpError` (an ``OSError``,
  so legacy ``except OSError`` call sites keep working).

Effect on the hub: ``_default_fetch`` now delegates here, turning
miss-on-first-blip into retry-then-miss — a target only burns one of
its ``NTS_HUB_MISS_K`` misses after the client's whole retry budget is
exhausted.

Chaos: every attempt passes through ``fault_point("http_fetch",
target=...)`` (resilience/faults), so ``net_drop@target=k`` (raises
refused) and ``slow_net@target=k,ms=`` (injects latency) exercise the
retry path, the miss-K escalation, and the router's re-route logic
end-to-end without touching a real socket fault.
"""

from __future__ import annotations

import errno as _errno
import os
import socket
import time
import urllib.error
import urllib.request
from typing import Optional

from neutronstarlite_tpu.utils.logging import get_logger

log = get_logger("obs")

DEFAULT_TIMEOUT_S = 5.0
DEFAULT_RETRIES = 2
DEFAULT_BACKOFF_S = 0.05


class HttpError(OSError):
    """Base of the typed taxonomy (an OSError: legacy handlers match)."""


class HttpTimeout(HttpError):
    """The request (or the caller's deadline) timed out in flight."""


class HttpRefused(HttpError):
    """Connection refused/reset — nothing is listening at the target."""


class HttpStatusError(HttpError):
    """An HTTP answer arrived but with a non-200 status."""

    def __init__(self, status: int, url: str, detail: str = ""):
        super().__init__(f"HTTP {status} from {url}"
                         + (f": {detail}" if detail else ""))
        self.status = int(status)


def _env_pos_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return max(float(raw), 0.0)
    except ValueError:
        log.warning("bad %s=%r; using %g", name, raw, default)
        return default


def _env_pos_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return max(int(raw), 0)
    except ValueError:
        log.warning("bad %s=%r; using %d", name, raw, default)
        return default


def http_timeout_s() -> float:
    return _env_pos_float("NTS_HTTPC_TIMEOUT_S", DEFAULT_TIMEOUT_S)


def http_retries() -> int:
    return _env_pos_int("NTS_HTTPC_RETRIES", DEFAULT_RETRIES)


def http_backoff_s() -> float:
    return _env_pos_float("NTS_HTTPC_BACKOFF_S", DEFAULT_BACKOFF_S)


# connection-level "nobody home" errnos (refused, reset, aborted, no
# route): all mean the target process is not answering, which is the
# distinction the router's death-escalation cares about
_REFUSED_ERRNOS = frozenset({
    _errno.ECONNREFUSED, _errno.ECONNRESET, _errno.ECONNABORTED,
    _errno.EHOSTUNREACH, _errno.ENETUNREACH, _errno.EPIPE,
})


def _classify(exc: BaseException, url: str) -> HttpError:
    """Map the urllib/socket zoo onto the typed taxonomy."""
    if isinstance(exc, urllib.error.HTTPError):
        return HttpStatusError(exc.code, url, getattr(exc, "reason", ""))
    if isinstance(exc, urllib.error.URLError):
        exc = exc.reason if isinstance(exc.reason, BaseException) else exc
    if isinstance(exc, (socket.timeout, TimeoutError)):
        return HttpTimeout(f"timed out fetching {url}: {exc}")
    if isinstance(exc, ConnectionError):
        return HttpRefused(f"connection failed to {url}: {exc}")
    if isinstance(exc, OSError) and exc.errno in _REFUSED_ERRNOS:
        return HttpRefused(f"connection failed to {url}: {exc}")
    return HttpError(f"fetch failed for {url}: {exc}")


def error_class(exc: BaseException) -> str:
    """The span-attribute error taxonomy: timeout / refused / status /
    error — the retry-attribution tag on httpc's child spans and the
    router's suspect spans."""
    if isinstance(exc, HttpTimeout):
        return "timeout"
    if isinstance(exc, HttpRefused):
        return "refused"
    if isinstance(exc, HttpStatusError):
        return "status"
    return "error"


def fetch(url: str, *,
          timeout_s: Optional[float] = None,
          retries: Optional[int] = None,
          backoff_s: Optional[float] = None,
          deadline_s: Optional[float] = None,
          target: Optional[int] = None,
          data: Optional[bytes] = None,
          content_type: str = "application/json",
          tracer=None,
          ctx=None,
          span_name: str = "http_fetch") -> str:
    """GET (or POST, when ``data`` is given) ``url`` with retries.

    ``deadline_s`` bounds the WHOLE call (requests + backoff sleeps);
    ``target`` is the caller's integer index for this endpoint, matched
    by ``net_drop@target=k`` / ``slow_net@target=k`` fault specs. POSTs
    are retried like GETs — callers whose POST is not idempotent (the
    router's /predict) should pass ``retries=0`` and own re-dispatch.

    Distributed tracing (both optional, zero cost when absent):

    - ``tracer`` (obs/trace.Tracer) — the call emits one ``span_name``
      span covering the whole call (child of ``ctx`` / the caller's open
      span), plus one ``http_retry`` child span per FAILED attempt
      tagged with the :func:`error_class` (timeout/refused/status) and
      the backoff it cost;
    - ``ctx`` (obs/trace.TraceContext) — the trace crosses the wire:
      ``X-NTS-Trace-Id`` / ``X-NTS-Parent-Span`` / ``X-NTS-Send-Ts``
      headers are injected on every attempt (send_ts re-stamped per
      retry), parenting the server-side handler spans under this call's
      span. With a disabled tracer (``NTS_TRACE=0``) no context exists,
      no headers are added and no spans allocate — the hot path is
      byte-identical to the pre-tracing client.

    Raises the typed :class:`HttpError` subclass of the LAST attempt
    once the retry budget (or the deadline) is exhausted.
    """
    # lazy imports: obs.httpc is imported by obs/hub at module load, and
    # resilience/{faults,supervisor} import obs modules — a top-level
    # import here would be a cycle
    from neutronstarlite_tpu.resilience import faults
    from neutronstarlite_tpu.resilience.supervisor import backoff_jitter_frac

    timeout_s = http_timeout_s() if timeout_s is None else float(timeout_s)
    retries = http_retries() if retries is None else max(int(retries), 0)
    backoff_s = (http_backoff_s() if backoff_s is None
                 else max(float(backoff_s), 0.0))
    t0 = time.monotonic()

    trace_on = tracer is not None and getattr(tracer, "enabled", False)
    sid = None          # this call's span id (remote spans parent to it)
    hdr_ctx = None      # context serialized into the request headers
    emit_ctx = None     # context our own child retry spans emit under
    send_ts: Optional[float] = None
    if trace_on:
        from neutronstarlite_tpu.obs.trace import TraceContext

        sid = tracer.next_id()
        trace_id = ctx.trace_id if ctx is not None else tracer.trace_id
        hdr_ctx = TraceContext(trace_id, sid)
        emit_ctx = TraceContext(trace_id, sid)
    elif ctx is not None:
        hdr_ctx = ctx

    def remaining() -> Optional[float]:
        if deadline_s is None:
            return None
        return deadline_s - (time.monotonic() - t0)

    def finish(outcome: str, status: Optional[int], attempts: int) -> None:
        if not trace_on:
            return
        attrs = {"url": url, "outcome": outcome, "attempts": attempts}
        if target is not None:
            attrs["target"] = target
        if status is not None:
            attrs["status"] = status
        if send_ts is not None:
            attrs["send_ts"] = send_ts
        tracer.complete(
            span_name, dur_s=time.monotonic() - t0, cat="http",
            span_id=sid, ctx=ctx, **attrs,
        )

    last: Optional[HttpError] = None
    attempt = 0
    for attempt in range(1, retries + 2):
        budget = remaining()
        if budget is not None and budget <= 0:
            err = last or HttpTimeout(
                f"deadline {deadline_s:g}s expired before fetching {url}"
            )
            finish(error_class(err), getattr(err, "status", None),
                   attempt - 1)
            raise err
        t_attempt = time.monotonic()
        try:
            # the chaos seam: net_drop raises refused here, slow_net
            # sleeps here — BEFORE the socket, so injected faults spend
            # the same retry/deadline budget a real one would
            faults.fault_point("http_fetch", target=target)
            req = urllib.request.Request(url, data=data)
            if data is not None:
                req.add_header("Content-Type", content_type)
            if hdr_ctx is not None:
                send_ts = time.time()  # re-stamped per attempt
                for k, v in hdr_ctx.to_headers(send_ts=send_ts).items():
                    req.add_header(k, v)
            t = timeout_s if budget is None else max(min(timeout_s, budget),
                                                     1e-3)
            with urllib.request.urlopen(req, timeout=t) as resp:
                if resp.status != 200:
                    raise HttpStatusError(resp.status, url)
                body = resp.read().decode("utf-8")
                finish("ok", 200, attempt)
                return body
        except HttpError as e:
            last = e
        except Exception as e:
            last = _classify(e, url)
        delay = 0.0
        will_retry = attempt <= retries
        if will_retry:
            delay = backoff_s * (2.0 ** (attempt - 1))
            delay *= 1.0 + backoff_jitter_frac(attempt)
            budget = remaining()
            if budget is not None:
                if budget <= 0:
                    will_retry = False
                    delay = 0.0
                else:
                    delay = min(delay, budget)
        if trace_on:
            # retry attribution: one child span per failed attempt, the
            # error class + the backoff it cost readable off the trace
            retry_attrs = {
                "attempt": attempt, "error": error_class(last),
                "backoff_s": delay if will_retry else 0.0,
                "will_retry": will_retry,
            }
            if isinstance(last, HttpStatusError):
                retry_attrs["status"] = last.status
            tracer.complete(
                "http_retry", dur_s=time.monotonic() - t_attempt,
                cat="http", ctx=emit_ctx, **retry_attrs,
            )
        if not will_retry:
            break
        if delay > 0:
            time.sleep(delay)
    assert last is not None
    finish(error_class(last), getattr(last, "status", None), attempt)
    raise last
