"""Unified run-metrics subsystem (the observability spine).

The reference attributes every epoch to compute/copy/wait/comm buckets via
its ``DEBUGINFO()`` report (toolkits/GCN.hpp:308-353). This package gives the
TPU port one machine-readable telemetry surface over the signals that were
previously scattered across utils/timing (host phase timers),
models/debuginfo (bucket decomposition), tools/wire_accounting (exchange
volume) and ad-hoc bench prints:

- :class:`MetricsRegistry` — counters, gauges, timing summaries, plus a
  structured per-epoch JSONL event stream written under ``NTS_METRICS_DIR``;
- :mod:`collectors` — device memory, compile-vs-steady-state step
  attribution, phase-timer snapshots;
- :mod:`schema` — the JSONL event schema and its validator (tests and
  tools/metrics_report consume it);
- :mod:`trace` — hierarchical span tracing (trace_id / span_id /
  parent_id) over the same JSONL stream; tools/trace_timeline merges the
  per-rank span files into one causal timeline and a Chrome trace;
- :mod:`hist` — log-bucketed mergeable latency histograms (bounded
  relative quantile error, fixed memory) serialized as typed ``hist``
  records so tail quantiles survive rotation and multi-rank runs;
- :mod:`slo` — declarative objectives (``NTS_SLO_SPEC``) evaluated as
  rolling multi-window burn rates; the serve admission/shed signal;
- :mod:`exporter` — the opt-in HTTP pull endpoint (``NTS_METRICS_PORT``):
  /metrics (Prometheus text), /healthz, /slo;
- :mod:`flight` — the always-on bounded flight recorder: the last N
  records at full resolution, dumped on fault/breach/SIGUSR2;
- :mod:`cost` — compiled-program cost attribution: per-executable XLA
  ``cost_analysis()``/``memory_analysis()`` captured once at build time
  as typed ``program_cost`` records;
- :mod:`ledger` — the cross-run perf ledger (``NTS_LEDGER_DIR``): one
  atomically-appended row per run/suite/probe, keyed by graph digest +
  cfg fingerprint + backend; ``tools/perf_sentinel`` gates new rows
  against the MAD-scaled trend of their own history;
- :mod:`numerics` — the numerics health plane (``NTS_NUMERICS``):
  stats-fused step outputs as typed ``tensor_stats`` records, the
  one-shot non-finite provenance replay (``nonfinite_provenance``),
  the batched whole-tree finiteness check the guards use, and the
  measured wire quantization error (``NTS_QUANT_PROBE`` /
  ``NTS_QUANT_TOL``, audited by tools/drift_audit).

Every trainer run emits one ``run_summary`` record; ``tools/metrics_report``
renders one or more streams into the reference-shaped ``#key=value(ms)``
report and a cross-run comparison table. See docs/OBSERVABILITY.md.
"""

from neutronstarlite_tpu.obs.cost import capture_program_cost
from neutronstarlite_tpu.obs.hist import LogHistogram
from neutronstarlite_tpu.obs.registry import (
    MetricsRegistry,
    config_fingerprint,
    metrics_dir,
    open_run,
)
from neutronstarlite_tpu.obs.schema import SCHEMA_VERSION, validate_event
from neutronstarlite_tpu.obs.trace import Tracer

__all__ = [
    "LogHistogram",
    "MetricsRegistry",
    "SCHEMA_VERSION",
    "Tracer",
    "capture_program_cost",
    "config_fingerprint",
    "metrics_dir",
    "open_run",
    "validate_event",
]
