"""Opt-in pull-based HTTP telemetry endpoint (``NTS_METRICS_PORT``).

Serves three paths from a lock-light snapshot of the live registry —
scrapes copy the metric dicts under the registry lock (microseconds) and
format OUTSIDE it, so a scrape can never block a serve flush or a ring
step:

- ``/metrics`` — Prometheus text exposition: counters, numeric gauges,
  timing summaries (``_count``/``_sum``), and every LogHistogram as a
  cumulative-bucket histogram over the fixed ``le`` ladder
  (obs/hist.PROM_EDGES_MS) plus ``_sum``/``_count``;
- ``/healthz`` — JSON liveness: run identity, uptime, fault/restart
  counters, the supervisor state gauge, elastic partition count;
- ``/slo`` — the SLO engine's current objective verdicts as JSON (404
  when no engine is armed).

``NTS_METRICS_PORT=0`` binds an ephemeral port (``exporter.port`` reports
it — tests and in-process drivers use this); the listener binds
``NTS_METRICS_HOST`` (default 127.0.0.1 — expose deliberately, not by
default). One exporter per process: :func:`maybe_start` is a singleton
that REBINDS to the newest registry (train-then-serve runs hand off the
same stream; the latest-wins convention of resilience/events.set_sink).
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from neutronstarlite_tpu.obs.hist import PROM_EDGES_MS
from neutronstarlite_tpu.utils.logging import get_logger

log = get_logger("obs")


def _prom_name(name: str) -> str:
    out = "".join(c if c.isalnum() or c == "_" else "_" for c in name)
    return f"nts_{out}"


def prometheus_text(registry, slo=None) -> str:
    """Render one Prometheus text-format snapshot of the registry.

    A name can exist as BOTH a scalar and a histogram (sample.stall_ms
    is a cumulative counter and a distribution; sample.queue_depth a
    high-water gauge and a distribution) — Prometheus rejects a second
    TYPE declaration for one family, so the colliding scalar renders
    under a suffixed name (`_total` for counters, `_peak` for gauges)
    and the histogram keeps the bare family."""
    snap = registry.snapshot(include_hists=False)
    hists = registry.hists()
    lines: List[str] = []
    for name, v in sorted(snap["counters"].items()):
        pn = _prom_name(name + "_total" if name in hists else name)
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {float(v):g}")
    for name, v in sorted(snap["gauges"].items()):
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue  # non-numeric gauges (strings) have no Prom encoding
        pn = _prom_name(name + "_peak" if name in hists else name)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {float(v):g}")
    for name, t in sorted(snap["timings"].items()):
        pn = _prom_name(name + "_seconds")
        lines.append(f"# TYPE {pn} summary")
        lines.append(f"{pn}_count {int(t['count'])}")
        lines.append(f"{pn}_sum {float(t['total_s']):g}")
    for name, h in sorted(hists.items()):
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} histogram")
        cumulative = 0
        for edge in PROM_EDGES_MS:
            cumulative = h.count_le(edge)
            lines.append(f'{pn}_bucket{{le="{edge:g}"}} {cumulative}')
        lines.append(f'{pn}_bucket{{le="+Inf"}} {h.count}')
        lines.append(f"{pn}_sum {h.sum:g}")
        lines.append(f"{pn}_count {h.count}")
    if slo is not None:
        for v in slo.verdicts():
            pn = _prom_name("slo_burn_rate")
            lines.append(
                f'{pn}{{objective="{v["objective"]}"}} '
                f'{v["burn_rate"] if v["burn_rate"] is not None else "NaN"}'
            )
            lines.append(
                f'nts_slo_breached{{objective="{v["objective"]}"}} '
                f'{1 if v["state"] == "breach" else 0}'
            )
    return "\n".join(lines) + "\n"


def health_payload(registry, started_at: float) -> Dict[str, Any]:
    snap = registry.snapshot(include_hists=False)
    counters = snap["counters"]
    gauges = snap["gauges"]
    gave_up = bool(gauges.get("resilience.gave_up"))
    return {
        "ok": not gave_up,
        "run_id": registry.run_id,
        "algorithm": registry.algorithm,
        "uptime_s": round(time.time() - started_at, 3),
        "supervisor": {
            "state": gauges.get("resilience.state"),
            "attempt": gauges.get("resilience.attempt"),
            "faults": counters.get("resilience.faults", 0),
            "restarts": counters.get("resilience.restarts", 0),
            "replans": counters.get("resilience.replans", 0),
        },
        "liveness": {
            "active_partitions": gauges.get("dist.active_partitions"),
            "last_event_ts": registry.last_event_ts,
        },
    }


class MetricsExporter:
    """The HTTP listener; ``registry``/``slo`` are rebindable live."""

    def __init__(self, registry, port: int, host: str = "127.0.0.1",
                 slo=None):
        self.registry = registry
        self.slo = slo
        self.started_at = time.time()
        exporter = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *_a):  # scrapes must not spam the log
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (http.server API)
                try:
                    path = self.path.split("?", 1)[0]
                    if path == "/metrics":
                        body = prometheus_text(
                            exporter.registry, exporter.slo
                        ).encode()
                        self._send(
                            200, body,
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                    elif path == "/healthz":
                        body = json.dumps(health_payload(
                            exporter.registry, exporter.started_at
                        )).encode()
                        self._send(200, body, "application/json")
                    elif path == "/slo":
                        if exporter.slo is None:
                            self._send(
                                404,
                                b'{"error": "no SLO engine armed '
                                b'(NTS_SLO_SPEC unset)"}',
                                "application/json",
                            )
                        else:
                            exporter.slo.tick()
                            body = json.dumps(
                                exporter.slo.verdicts()
                            ).encode()
                            self._send(200, body, "application/json")
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except Exception as e:  # a bad scrape must not kill serving
                    try:
                        self._send(
                            500, f"scrape failed: {e}\n".encode(),
                            "text/plain",
                        )
                    except Exception:
                        pass

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="obs-exporter",
            daemon=True,
        )
        self._thread.start()
        log.info("metrics exporter listening on http://%s:%d "
                 "(/metrics /healthz /slo)", host, self.port)

    def rebind(self, registry, slo=None) -> None:
        """Latest surface wins for BOTH fields: keeping a previous run's
        SLO engine (bound to its closed registry) would serve stale /slo
        verdicts next to the new registry's /metrics."""
        self.registry = registry
        self.slo = slo

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
        self._thread.join(timeout=5.0)


_singleton: Optional[MetricsExporter] = None
_singleton_lock = threading.Lock()


def maybe_start(registry, slo=None) -> Optional[MetricsExporter]:
    """Start (or rebind) the process's exporter when ``NTS_METRICS_PORT``
    is set; None otherwise. Never raises — a taken port degrades to a
    warning, not a dead trainer."""
    global _singleton
    raw = os.environ.get("NTS_METRICS_PORT", "")
    if not raw:
        return None
    with _singleton_lock:
        if _singleton is not None:
            _singleton.rebind(registry, slo)
            return _singleton
        try:
            port = int(raw)
        except ValueError:
            log.warning("NTS_METRICS_PORT=%r is not an int; exporter off",
                        raw)
            return None
        host = os.environ.get("NTS_METRICS_HOST", "127.0.0.1")
        try:
            _singleton = MetricsExporter(registry, port, host=host, slo=slo)
        except OSError as e:
            log.warning("metrics exporter could not bind %s:%s (%s); "
                        "exporter off", host, port, e)
            return None
        return _singleton
